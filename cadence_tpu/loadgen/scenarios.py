"""End-to-end load scenarios against a real wire cluster.

The scenario everything else exists for — `overload_scenario` — is the
admission-control proof from Cadence's operational playbook: drive one
domain (the AGGRESSOR) at a multiple of its per-domain quota while a
second domain (the VICTIM) runs normal mixed traffic on the same
cluster, optionally under seeded wire chaos in every host process.
The system passes when overload degrades by SHEDDING, not by latency
collapse:

- ≥ 90% of the aggressor's overflow (traffic beyond its quota capacity)
  is rejected as a typed ServiceBusy — visible both client-side (the
  generator's shed counts) and server-side (`quotas/*` on /metrics);
- the victim domain's p99 (measured from intended send time — open
  loop, no coordinated omission) stays within its SLO;
- every workflow the traffic produced verifies oracle↔device with zero
  checksum divergence — overload and shedding never corrupt state.

The quota is enforced PER HOST (each host's token buckets are local),
so the scenario splits the cluster-wide budget across hosts through the
`env_per_role` seam of `rpc/cluster.launch` — exactly how a production
deployment divides a domain's global RPS across frontends.
"""
from __future__ import annotations

import os
import time
from typing import Dict, List, Optional

from .generator import DecisionCompleters, LoadGenerator
from .mixes import (
    START_ONLY_MIX,
    STANDARD_MIX,
    DomainPlan,
    build_schedule,
)
from .slo import SLO, evaluate_slos

VICTIM_DOMAIN = "lg-victim"
AGGRESSOR_DOMAIN = "lg-aggressor"

#: the chaos spec the scenario uses when chaos is requested without an
#: explicit spec (mirrors tests/test_chaos_soak.py rates)
DEFAULT_CHAOS_SPEC = "drop=0.04,sever=0.02,delay=0.1,delay_ms=8,seed=17"

#: seeded store-fault spec for overload-with-store-chaos runs: writes in
#: the store-server process raise TransientStoreError BEFORE they apply
#: (engine/faults.FaultInjector), so the retry tier heals them without
#: double-applying — the same nothing-was-applied contract the wire
#: chaos keeps (tests/test_chaos_soak.py rates)
DEFAULT_STORE_FAULT_SPEC = "rate=0.04,seed=13"


def _collect_quota_metrics(cluster) -> Dict[str, object]:
    """Per-host quotas/* counters over the admin wire op + one raw
    /metrics body (the operator surface the shed counters live on)."""
    import urllib.request

    from ..rpc.wire import call as wire_call

    per_host: Dict[str, Dict[str, float]] = {}
    for name, port in cluster.hosts.items():
        snap = wire_call(("127.0.0.1", port), ("admin_metrics",),
                         timeout=10)["snapshot"]
        per_host[name] = dict(snap.get("quotas", {}))
    scrape_port = sorted(cluster.http_ports.values())[0]
    body = urllib.request.urlopen(
        f"http://127.0.0.1:{scrape_port}/metrics", timeout=10
    ).read().decode("utf-8")
    shed_total = sum(float(h.get("shed", 0)) for h in per_host.values())
    admitted_total = sum(float(h.get("admitted", 0))
                         for h in per_host.values())
    return {"per_host": per_host, "shed_total": shed_total,
            "admitted_total": admitted_total,
            "prometheus_has_shed": "cadence_shed_total" in body,
            "prometheus_sample": [line for line in body.splitlines()
                                  if line.startswith("cadence_shed")
                                  or line.startswith("cadence_admitted")]}


def _verify_cluster_state(cluster) -> Dict[str, object]:
    """Oracle↔device checksum verification over the REMOTE store: the
    whole point of running it from here is that RemoteStores duck-types
    Stores, so TPUReplayEngine replays every persisted history on device
    and compares against the authoritative mutable states across the
    wire — the zero-divergence contract applied to loadgen traffic."""
    from ..core.checksum import DEFAULT_LAYOUT
    from ..engine.tpu_engine import TPUReplayEngine
    from ..rpc.client import RemoteStores
    from ..utils import compile_cache

    compile_cache.enable()
    stores = RemoteStores(("127.0.0.1", cluster.store_port))
    engine = TPUReplayEngine(stores, DEFAULT_LAYOUT)
    result = engine.verify_all()
    # the cluster is LIVE under the verify (real-clock hosts still pump
    # timers — a decision timeout can commit between a key's history
    # read and its execution-row read, a torn comparison that is not a
    # divergence): re-verify only the flagged keys until they read
    # stable — a REAL divergence survives every re-read, a mid-commit
    # phantom clears on the next one
    divergent = list(result.divergent)
    first_pass = len(divergent)
    for _ in range(3):
        if not divergent:
            break
        time.sleep(1.0)
        divergent = list(engine.verify_all(divergent).divergent)
    closed = 0
    for info in stores.domain.list_domains():
        closed += len(stores.visibility.list_closed(info.domain_id))
    return {"total": result.total,
            "verified_on_device": result.verified_on_device,
            "escalated": len(result.escalated),
            "fallback": len(result.fallback),
            "divergent": len(divergent),
            "divergent_first_pass": first_pass,
            "completed_workflows": closed,
            "ok": not divergent}


def _run_harness(plans, schedule, duration_s: float, num_hosts: int,
                 num_shards: int, workers: int, chaos_spec: str,
                 verify: bool, env_per_role=None):
    """The shared wire-cluster lifecycle every scenario runs: launch →
    prepare/seed → completer fleet → (chaos window) open-loop run →
    drain → quota scrape → oracle↔device verify → teardown. Client-side
    wire chaos joins for the measured window only (setup and post-run
    verification read cleanly, like the chaos soak's discipline);
    host-side chaos from the env stays on for the whole cluster life.
    Returns (load, quota_metrics, verify_doc)."""
    from ..rpc import chaos as chaos_mod
    from ..rpc.cluster import launch

    env_extra = ({"CADENCE_TPU_CHAOS": chaos_spec} if chaos_spec else {})
    cluster = launch(num_hosts=num_hosts, num_shards=num_shards,
                     env_extra=env_extra, env_per_role=env_per_role)
    try:
        clients = [cluster.frontend(i) for i in range(num_hosts)]
        gen = LoadGenerator(clients, schedule, plans, workers=workers)
        gen.prepare()
        # admission counters can move during prepare too (a pool seed on
        # the quota-limited domain sheds server-side and the generator
        # retries it): baseline AFTER prepare, so `*_run` deltas cover
        # exactly the measured window and compare one-for-one with the
        # generator's client-side counts
        pre = _collect_quota_metrics(cluster)
        counter = {"n": 0}

        def completer_client():
            counter["n"] += 1
            return cluster.frontend(counter["n"] % num_hosts)

        completers = DecisionCompleters(
            completer_client, [p.domain for p in plans])
        completers.start()
        if chaos_spec:
            chaos_mod.install(chaos_mod.parse_spec(chaos_spec))
        try:
            load = gen.run()
        finally:
            chaos_mod.uninstall()
        # drain: let the completers finish the admitted churn backlog
        drain_deadline = time.monotonic() + max(5.0, duration_s)
        last = -1
        while time.monotonic() < drain_deadline:
            time.sleep(0.5)
            if completers.completed == last:
                break
            last = completers.completed
        completers.stop()
        load.completed_churn = completers.completed

        quota_metrics = _collect_quota_metrics(cluster)
        quota_metrics["shed_total_run"] = (
            quota_metrics["shed_total"] - pre["shed_total"])
        quota_metrics["admitted_total_run"] = (
            quota_metrics["admitted_total"] - pre["admitted_total"])
        verify_doc = _verify_cluster_state(cluster) if verify else None
    finally:
        chaos_mod.uninstall()
        cluster.stop()
    return load, quota_metrics, verify_doc


def overload_scenario(duration_s: float = 8.0, num_hosts: int = 2,
                      victim_rps: float = 4.0,
                      aggressor_quota_rps: float = 4.0,
                      overdrive: float = 2.0,
                      chaos_spec: str = "",
                      store_fault_spec: str = "",
                      seed: int = 20260803,
                      victim_p99_slo_ms: float = 2500.0,
                      workers: int = 32,
                      verify: bool = True,
                      pool_size: int = 6,
                      num_shards: int = 8) -> dict:
    """Run the two-domain overload scenario; returns the trajectory doc
    (see module docstring for the contract it gates).

    Default rates are sized for the test deployment (every role is a
    GIL-bound Python process sharing one store server, ~20-40 admitted
    ops/s cluster-wide): the aggressor's 2x overdrive must overflow its
    QUOTA, not the cluster's raw capacity, and the dispatch pool must
    never become the bottleneck — an open-loop harness whose own workers
    backlog is re-introducing the coordinated omission it exists to
    prevent. Production deployments scale the same knobs up."""
    per_host_quota = aggressor_quota_rps / num_hosts
    if per_host_quota < 1.0:
        # the burst=0→rps alias caps each host's bucket at per_host_quota
        # tokens: below 1.0, try_consume(1) can NEVER succeed and every
        # aggressor request (including prepare's pool seed) sheds forever
        raise ValueError(
            f"aggressor_quota_rps={aggressor_quota_rps} split over "
            f"{num_hosts} hosts gives each a {per_host_quota} rps bucket "
            "(burst aliases to rps): capacity below one token can never "
            "admit a request — raise the quota or lower num_hosts")
    env_per_role = {"host": {
        "CADENCE_TPU_QUOTAS": f"domain.{AGGRESSOR_DOMAIN}={per_host_quota}"}}
    if store_fault_spec:
        # store chaos rides the per-role seam like the per-host quotas:
        # only the STORE server process injects (engine/faults pre-apply
        # TransientStoreError), so the shed/SLO gate is proven to hold
        # with the persistence tier flapping under overload too
        env_per_role["store"] = {
            "CADENCE_TPU_STORE_FAULTS": store_fault_spec}

    plans = [
        DomainPlan(VICTIM_DOMAIN, victim_rps, mix=STANDARD_MIX,
                   pool_size=pool_size),
        DomainPlan(AGGRESSOR_DOMAIN, aggressor_quota_rps * overdrive,
                   mix=START_ONLY_MIX, pool_size=1),
    ]
    schedule = build_schedule(plans, duration_s, seed)
    load, quota_metrics, verify_doc = _run_harness(
        plans, schedule, duration_s, num_hosts, num_shards, workers,
        chaos_spec, verify, env_per_role=env_per_role)

    # -- admission accounting ---------------------------------------------
    agg = load.totals(AGGRESSOR_DOMAIN)
    vic = load.totals(VICTIM_DOMAIN)
    # bucket capacity over the ACTUAL wall window (token refill does not
    # stop when the run overshoots its intended duration): rate * window
    # + burst, where burst defaults to one second's tokens per host (the
    # documented burst=0 alias), summed across hosts
    window = max(duration_s, load.duration_s)
    capacity = aggressor_quota_rps * window + per_host_quota * num_hosts
    overflow = max(0.0, agg.sent - capacity)
    # both shed origins count as rejected overflow (a breaker shed under
    # chaos still rejected the request with a typed ServiceBusy), but
    # only quota sheds (`shed`) have matching server-side counters
    shed_ratio = (((agg.shed + agg.shed_busy) / overflow)
                  if overflow > 0 else 1.0)

    slos = [SLO(domain=VICTIM_DOMAIN, p99_ms=victim_p99_slo_ms,
                max_error_rate=0.2)]
    slo_report = evaluate_slos(load, slos)

    doc = {
        "scenario": "overload",
        "run": {
            "duration_s": duration_s, "num_hosts": num_hosts,
            "num_shards": num_shards, "seed": seed,
            "victim_rps": victim_rps,
            "aggressor_quota_rps": aggressor_quota_rps,
            "aggressor_quota_rps_per_host": per_host_quota,
            "overdrive": overdrive, "chaos": chaos_spec,
            "store_faults": store_fault_spec,
            "workers": workers,
        },
        "traffic": load.as_dict(),
        "admission": {
            "aggressor": {
                "sent": agg.sent, "ok": agg.ok, "shed": agg.shed,
                "shed_busy": agg.shed_busy, "errors": agg.errors,
                "capacity_estimate": round(capacity, 1),
                "overflow_estimate": round(overflow, 1),
                "shed_ratio_of_overflow": round(min(shed_ratio, 1.0), 4),
            },
            "victim": {
                "sent": vic.sent, "ok": vic.ok, "shed": vic.shed,
                "shed_busy": vic.shed_busy, "errors": vic.errors,
            },
            "max_retry_after_s": load.max_retry_after_s,
            "scrape": quota_metrics,
        },
        "slo": slo_report.as_dict(),
        "verify": verify_doc,
    }
    doc["ok"] = bool(
        slo_report.ok
        and shed_ratio >= 0.9
        and quota_metrics["shed_total_run"] > 0
        and (verify_doc is None or verify_doc["divergent"] == 0))
    return doc


def serving_scenario(duration_s: float = 4.0, rps: float = 160.0,
                     workers: int = 16, pool_size: int = 12,
                     seed: int = 20260803, num_shards: int = 4,
                     serving_batch: int = 8,
                     serving_wait_us: int = 80000) -> dict:
    """The device-serving tier comparison (ISSUE 10's acceptance run):
    the SAME seeded open-loop schedule of decision transactions (signals
    against a long-lived pool — each one is a full history-engine
    transaction: load → apply → persist) driven twice against a fresh
    in-process cluster, tier OFF then tier ON, recording per-mode
    decision-transaction p50/p99, and for the ON mode the scheduler's
    launches/sec, coalescing factor and parity counters.

    The tier's contract, gated in `doc["ok"]`:
    - coalescing: concurrent committed transactions fold into shared
      device launches (factor > 1.5 — one launch serves several
      transactions' appends, the micro-batching claim);
    - latency: the handoff is post-commit and fire-and-forget, so the
      decision-transaction p99 with the tier ON must be no worse than
      with it OFF (the device twin costs the request path nothing);
    - parity: every served transaction's device payload checksum equals
      the oracle's committed row — divergence counter 0, and the
      post-run full verify stays green with the resident pool the tier
      maintained.

    Runs in-process (Onebox) on purpose: the comparison isolates the
    engine transaction loop from wire/chaos noise; the wire-cluster
    tier rides the same CADENCE_TPU_SERVING knob in production."""
    from ..engine.onebox import Onebox
    from ..utils import compile_cache
    from ..utils import metrics as m
    from .mixes import OP_SIGNAL, TrafficMix, trace_digest

    compile_cache.enable()
    domain = "lg-serving"
    mix = TrafficMix("serving-signal", {OP_SIGNAL: 1.0})
    plans = [DomainPlan(domain, rps, mix=mix, pool_size=pool_size)]
    schedule = build_schedule(plans, duration_s, seed)

    modes: Dict[str, dict] = {}
    for mode in ("off", "on"):
        box = Onebox(num_hosts=1, num_shards=num_shards)
        if mode == "on":
            scheduler = box.enable_serving()
            # fixed flush width (pow2 bucket of 8) and every suffix
            # event-bucket pre-compiled, so the measured window never
            # pays a mid-run XLA compile (a mid-window compile stalls
            # the drain, folds deepen, and the NEXT bucket compiles too
            # — the snowball scheduler.warm exists to prevent); window
            # wide enough that concurrent transactions genuinely
            # coalesce
            scheduler.max_batch = serving_batch
            scheduler.max_wait_us = serving_wait_us
            scheduler.warm()
        gen = LoadGenerator([box.frontend], schedule, plans,
                            workers=workers, pump=box.pump_once)
        gen.prepare(setup_deadline_s=120.0)
        # warmup (both modes, identical populations): two signal rounds
        # per pool workflow compile the from-state suffix shapes BEFORE
        # the measured window — XLA compiles are deployment warmup, not
        # steady-state decision latency (same discipline as the reset
        # warmup in LoadGenerator._warm_reset_path)
        from .mixes import pool_workflow_ids
        for rnd in range(2):
            for wf in pool_workflow_ids(plans[0]):
                box.frontend.signal_workflow_execution(
                    domain, wf, "lg-warmup",
                    request_id=f"lg-warm-{rnd}-{wf}")
            if mode == "on":
                box.serving.drain(timeout=120.0)
        pre_txns = box.metrics.counter(m.SCOPE_TPU_SERVING,
                                       m.M_SERVING_TXNS)
        pre_launches = box.metrics.counter(m.SCOPE_TPU_SERVING,
                                           m.M_SERVING_LAUNCHES)
        load = gen.run()
        if mode == "on":
            # settle: the tier is async by design — drain the coalescing
            # queue (and any in-flight flush) before reading counters
            box.serving.drain(timeout=60.0)
        pct = load.percentiles(OP_SIGNAL)
        t = load.totals(domain)
        doc_mode = {
            "sent": t.sent, "ok": t.ok, "errors": t.errors,
            "duration_s": round(load.duration_s, 3),
            "decision_p50_ms": round(pct["p50"] * 1000, 3),
            "decision_p99_ms": round(pct["p99"] * 1000, 3),
        }
        if mode == "on":
            txns = box.metrics.counter(m.SCOPE_TPU_SERVING,
                                       m.M_SERVING_TXNS) - pre_txns
            launches = box.metrics.counter(
                m.SCOPE_TPU_SERVING, m.M_SERVING_LAUNCHES) - pre_launches
            stats = box.serving.stats()
            doc_mode.update({
                "serving": stats,
                "window_transactions": txns,
                "window_launches": launches,
                "launches_per_sec": round(launches / load.duration_s, 2),
                "coalescing_factor": round(txns / launches, 3)
                if launches else 0.0,
            })
        verify = box.tpu.verify_all()
        doc_mode["verify"] = {"total": verify.total,
                              "divergent": len(verify.divergent),
                              "resident_served": len(verify.resident),
                              "ok": bool(verify.ok)}
        if mode == "on":
            box.serving.stop()
        modes[mode] = doc_mode

    on, off = modes["on"], modes["off"]
    doc = {
        "scenario": "serving",
        "run": {"duration_s": duration_s, "rps": rps, "workers": workers,
                "pool_size": pool_size, "seed": seed,
                "num_shards": num_shards, "serving_batch": serving_batch,
                "serving_wait_us": serving_wait_us,
                "trace_digest": trace_digest(schedule)},
        "off": off,
        "on": on,
        "comparison": {
            "coalescing_factor": on.get("coalescing_factor", 0.0),
            "p99_on_ms": on["decision_p99_ms"],
            "p99_off_ms": off["decision_p99_ms"],
            "p99_on_le_off": bool(on["decision_p99_ms"]
                                  <= off["decision_p99_ms"]),
            "parity_divergence": on["serving"]["parity_divergence"],
        },
    }
    doc["ok"] = bool(
        on.get("coalescing_factor", 0.0) > 1.5
        and doc["comparison"]["p99_on_le_off"]
        and on["serving"]["parity_divergence"] == 0
        and on["verify"]["divergent"] == 0
        and off["verify"]["divergent"] == 0)
    return doc


def _host_metrics(cluster, names=None) -> Dict[str, dict]:
    """One admin_metrics snapshot per (live) host: {host: {scope: {...}}}."""
    from ..rpc.wire import call as wire_call

    out: Dict[str, dict] = {}
    for name in sorted(names if names is not None else cluster.hosts):
        if cluster.procs[name].poll() is not None:
            continue
        try:
            out[name] = wire_call(("127.0.0.1", cluster.hosts[name]),
                                  ("admin_metrics",),
                                  timeout=15)["snapshot"]
        except Exception:
            continue
    return out


def _counter_delta(current: Dict[str, dict], baseline: Dict[str, dict],
                   scope: str, metric: str, hosts=None) -> float:
    """Summed per-host counter movement between two scrape snapshots."""
    total = 0.0
    for name, snap in current.items():
        if hosts is not None and name not in hosts:
            continue
        now = float(snap.get(scope, {}).get(metric, 0.0))
        base = float(baseline.get(name, {}).get(scope, {})
                     .get(metric, 0.0))
        total += max(0.0, now - base)
    return total


def cluster_serving_scenario(duration_s: float = 12.0, num_hosts: int = 3,
                             rps: float = 16.0, pool_size: int = 16,
                             kill_at_frac: float = 0.5,
                             seed: int = 20260804,
                             p99_slo_ms: float = 8000.0,
                             workers: int = 24, num_shards: int = 8,
                             hb_interval: float = 0.15, ttl: float = 1.5,
                             hydration_floor: float = 0.8,
                             verify: bool = True) -> dict:
    """Multi-host device serving under host death (ISSUE 13's acceptance
    run): a wire cluster with the serving tier ON in every host process
    (each host its own serving mesh / resident pool / ServingScheduler
    over its ring slice, snapshot policy aggressive so the shared store
    stays fresh), driven by a seeded signal-dominant open-loop schedule
    against the SURVIVING hosts' frontends — and mid-window one host is
    SIGKILLed. The TTL drops it from the ring, the survivors steal its
    shards, and the migration tier (engine/migration.py) warm-starts the
    stolen state from persisted snapshots + batch-range reads.

    The subsystem's contract, gated in `doc["ok"]`:
    - the victim domain's p99 (clocked from intended send time — the
      kill window's failover stalls are IN the number) holds its SLO
      and the error rate stays bounded;
    - zero parity divergence everywhere: the serving tier's gated
      per-transaction counter, the migration tier's hydration parity,
      and the post-run oracle↔device verify over the store;
    - the survivors' post-kill admits for the stolen shards are
      ≥ `hydration_floor` snapshot-hydrated (migrated-in vs cold/stale
      steals) — warm failover, not a replay storm;
    - `events_per_sec_cluster` is recorded next to the per-pod number
      (the first events/s/CLUSTER north star: summed device-replayed
      events across every host over the measured window)."""
    import threading

    from ..rpc.cluster import launch
    from ..utils import metrics as cm
    from .mixes import OP_QUERY, OP_SIGNAL, OP_START, TrafficMix

    env_extra = {
        "CADENCE_TPU_SERVING": "1",
        # every parity-clean append refreshes the shared snapshot store:
        # host death can land anywhere and the survivors still hydrate
        "CADENCE_TPU_SNAPSHOT_MIN_EVENTS": "1",
        "CADENCE_TPU_SNAPSHOT_EVERY_EVENTS": "1",
        # a narrow flush width + trimmed warm shapes keep the hosts'
        # boot warm-up (rpc/server: serving_warmed) fast on small boxes;
        # the drive below never folds past these buckets
        "CADENCE_TPU_SERVING_BATCH": "8",
        "CADENCE_TPU_SERVING_WARM_EVENTS": "16,32,64",
    }
    domain = VICTIM_DOMAIN
    # signal-dominant: signals are full history-engine transactions on
    # the long-lived pool — the hot resident state whose migration the
    # scenario gates; the start tail keeps churn (and its completers)
    # exercising cold admits without letting sub-second-old workflows
    # dominate the steal-time population
    mix = TrafficMix("cluster-serving",
                     {OP_SIGNAL: 0.7, OP_START: 0.15, OP_QUERY: 0.15})
    plans = [DomainPlan(domain, rps, mix=mix, pool_size=pool_size)]
    schedule = build_schedule(plans, duration_s, seed)

    cluster = launch(num_hosts=num_hosts, num_shards=num_shards,
                     hb_interval=hb_interval, ttl=ttl,
                     env_extra=env_extra)
    victim_host = sorted(cluster.hosts)[-1]
    survivors = [n for n in sorted(cluster.hosts) if n != victim_host]
    kill_scrape: Dict[str, dict] = {}
    owned_before = {}
    try:
        # the LB view: traffic only ever targets hosts that stay alive —
        # the kill exercises the HISTORY-tier failover (shard steal +
        # state migration), which is where the resident state lives
        # hold traffic until every host's serving tier is WARM (the boot
        # warm-up compiles the flush kernels in the background): a
        # mid-window compile would stall the victim's drain long enough
        # that its pre-kill snapshots never land — deployment warmup,
        # the same discipline every serving scenario keeps
        deadline = time.monotonic() + 600
        while time.monotonic() < deadline:
            docs = {}
            for n in sorted(cluster.hosts):
                try:
                    docs[n] = cluster.admin(n, "admin_cluster")
                except Exception:
                    pass
            if len(docs) == len(cluster.hosts) and all(
                    d.get("serving_warmed") for d in docs.values()):
                break
            time.sleep(0.5)
        else:
            raise TimeoutError("serving tier never warmed on all hosts")
        clients = [cluster.frontend(n) for n in survivors]
        gen = LoadGenerator(clients, schedule, plans, workers=workers)
        gen.prepare(setup_deadline_s=120.0)
        counter = {"n": 0}

        def completer_client():
            counter["n"] += 1
            return cluster.frontend(survivors[counter["n"]
                                              % len(survivors)])

        completers = DecisionCompleters(completer_client, [domain])
        completers.start()
        start_scrape = _host_metrics(cluster)
        owned_before.update(cluster.owned_shards())

        def killer():
            time.sleep(max(0.1, duration_s * kill_at_frac))
            # baseline right before the kill: the hydration gate is on
            # POST-KILL deltas, and the victim's contribution to the
            # cluster events number ends here
            kill_scrape.update(_host_metrics(cluster))
            cluster.kill_host(victim_host)

        kill_thread = threading.Thread(target=killer, daemon=True)
        kill_thread.start()
        load = gen.run()
        kill_thread.join(timeout=30)
        # settle: let the survivors finish stealing/hydrating and the
        # completers drain the churn backlog
        deadline = time.monotonic() + max(5.0, ttl * 4)
        last = -1
        while time.monotonic() < deadline:
            time.sleep(0.5)
            if completers.completed == last:
                break
            last = completers.completed
        completers.stop()
        load.completed_churn = completers.completed

        end_scrape = _host_metrics(cluster, names=survivors)
        cluster_docs = {n: cluster.admin(n, "admin_cluster")
                        for n in survivors}
        owned_after = cluster.owned_shards()
        verify_doc = _verify_cluster_state(cluster) if verify else None
    finally:
        cluster.stop()

    # -- the warm-failover accounting ---------------------------------------
    mig_in = _counter_delta(end_scrape, kill_scrape,
                            cm.SCOPE_TPU_MIGRATION, cm.M_MIG_IN)
    mig_cold = _counter_delta(end_scrape, kill_scrape,
                              cm.SCOPE_TPU_MIGRATION, cm.M_MIG_COLD)
    mig_stale = _counter_delta(end_scrape, kill_scrape,
                               cm.SCOPE_TPU_MIGRATION, cm.M_MIG_STALE)
    # young steals (record-less sub-floor histories — a start committed
    # moments before the kill) are reported but NOT charged against the
    # warm-failover ratio: the snapshot policy's own min_events floor
    # deems them not worth a record, and their "cold replay" is a few
    # events, not a storm
    mig_young = _counter_delta(end_scrape, kill_scrape,
                               cm.SCOPE_TPU_MIGRATION, cm.M_MIG_YOUNG)
    steals = mig_in + mig_cold + mig_stale
    hydration_ratio = (mig_in / steals) if steals > 0 else 0.0
    # divergence is summed over the SURVIVORS' whole life (end_scrape)
    # PLUS the victim's pre-kill window (kill_scrape still includes it)
    # — a divergence the victim recorded before dying counts too
    victim_pre_kill = {k: v for k, v in kill_scrape.items()
                       if k == victim_host}
    serving_divergence = _counter_delta(
        end_scrape, {}, cm.SCOPE_TPU_SERVING, cm.M_SERVING_DIVERGENCE) \
        + _counter_delta(victim_pre_kill, {}, cm.SCOPE_TPU_SERVING,
                         cm.M_SERVING_DIVERGENCE)
    migration_divergence = _counter_delta(
        end_scrape, {}, cm.SCOPE_TPU_MIGRATION, cm.M_MIG_DIVERGENCE) \
        + _counter_delta(victim_pre_kill, {}, cm.SCOPE_TPU_MIGRATION,
                         cm.M_MIG_DIVERGENCE)

    # -- events/s/cluster: device-replayed events summed over every host
    # (survivors over the whole window + the victim up to its death)
    def events_of(scrapes, base, hosts):
        return (_counter_delta(scrapes, base, cm.SCOPE_TPU_RESIDENT,
                               cm.M_RESIDENT_EVENTS_APPENDED, hosts=hosts)
                + _counter_delta(scrapes, base, cm.SCOPE_TPU_REPLAY,
                                 cm.M_EVENTS_REPLAYED, hosts=hosts))

    window = max(duration_s, load.duration_s)
    events_cluster = events_of(end_scrape, start_scrape, set(survivors)) \
        + events_of(kill_scrape, start_scrape, {victim_host})
    per_host_events = {
        n: events_of(end_scrape, start_scrape, {n}) for n in survivors}
    per_host_events[victim_host] = events_of(kill_scrape, start_scrape,
                                             {victim_host})
    events_per_sec_pod = max(
        (e / window for e in per_host_events.values()), default=0.0)

    pct = load.percentiles(OP_SIGNAL)
    # error bound matches overload_scenario's victim convention (0.2):
    # requests IN FLIGHT to the victim at the SIGKILL instant surface as
    # honest connection errors (the retry tier only re-sends faults that
    # provably applied nothing), so a kill window always costs a few
    slos = [SLO(domain=domain, p99_ms=p99_slo_ms, max_error_rate=0.2)]
    slo_report = evaluate_slos(load, slos)
    victim_shards_taken = set(owned_before.get(victim_host, [])) <= set(
        s for n in survivors for s in owned_after.get(n, []))

    doc = {
        "scenario": "cluster-serving",
        "run": {"duration_s": duration_s, "num_hosts": num_hosts,
                "num_shards": num_shards, "rps": rps,
                "pool_size": pool_size, "seed": seed,
                "kill_at_frac": kill_at_frac, "ttl": ttl,
                "victim_host": victim_host, "survivors": survivors,
                "workers": workers, "hydration_floor": hydration_floor},
        "traffic": load.as_dict(),
        "latency": {"signal_p50_ms": round(pct["p50"] * 1000, 3),
                    "signal_p99_ms": round(pct["p99"] * 1000, 3)},
        "slo": slo_report.as_dict(),
        "failover": {
            "owned_before": {n: sorted(v)
                             for n, v in owned_before.items()},
            "owned_after": {n: sorted(v) for n, v in owned_after.items()},
            "victim_shards_taken": bool(victim_shards_taken),
            "migrated_in": mig_in, "cold_steals": mig_cold,
            "young_steals": mig_young, "stale_snapshots": mig_stale,
            "hydration_ratio": round(hydration_ratio, 4),
            "suffix_events": _counter_delta(
                end_scrape, kill_scrape, cm.SCOPE_TPU_MIGRATION,
                cm.M_MIG_SUFFIX_EVENTS),
        },
        "parity": {
            "serving_divergence": serving_divergence,
            "migration_divergence": migration_divergence,
        },
        "cluster": {n: {"owned_shards": d["owned_shards"],
                        "migration": d["migration"],
                        "resident_entries":
                            (d["resident"] or {}).get("entries", 0)}
                    for n, d in cluster_docs.items()},
        "north_star": {
            "events_per_sec_cluster": round(events_cluster / window, 1),
            "events_per_sec_pod": round(events_per_sec_pod, 1),
            "events_replayed_cluster": events_cluster,
            "window_s": round(window, 3),
        },
        "verify": verify_doc,
    }
    doc["ok"] = bool(
        slo_report.ok
        and victim_shards_taken
        and steals > 0
        and hydration_ratio >= hydration_floor
        and serving_divergence == 0
        and migration_divergence == 0
        and (verify_doc is None or verify_doc["divergent"] == 0))
    return doc


def region_failover_scenario(duration_s: float = 10.0, num_hosts: int = 2,
                             rps: float = 10.0, pool_size: int = 12,
                             kill_at_frac: float = 0.6,
                             seed: int = 20260806,
                             p99_slo_ms: float = 8000.0,
                             workers: int = 16, num_shards: int = 8,
                             hb_interval: float = 0.15, ttl: float = 1.5,
                             hydration_floor: float = 0.8,
                             max_repl_lag: int = 64,
                             verify: bool = True) -> dict:
    """Active-active multi-region failover under region kill (ISSUE 17's
    acceptance run): TWO wire regions — each its own WAL-backed store
    server + N service hosts with the serving tier ON — continuously
    replicating (history, domain metadata, and shipped snapshot records
    all ride the replication stream; the standby leader's device applier
    keeps its HBM state hot at the bulk-ingest rate). Standard-mix
    traffic drives the active region; mid-window EVERY active-region
    process is SIGKILLed. The standby then promotes WARM: pre-flip
    snapshot hydration of its serving tier, domain flip with a failover
    version bump, task regeneration — and a second traffic phase runs
    against the promoted region.

    The contract, gated in `doc["ok"]`:
    - replication lag is bounded at the kill instant (the data-loss
      window an unplanned region failover can ever cost);
    - the promoted region's signal p99 (decision-transaction latency,
      clocked from intended send time) holds its SLO;
    - the stolen executions are ≥ `hydration_floor` warm at promotion:
      snapshot-hydrated or already device-resident via the standby's
      device-speed apply — not a cold replay storm;
    - zero parity divergence everywhere: both regions' serving tiers,
      the migration/hydration parity gates, and the replication device
      applier's own per-apply parity counter;
    - post-run oracle↔device verify is green on BOTH regions — the
      promoted one live, the killed one after relaunching its store
      server from the WAL it crashed with (fsck-clean recovery);
    - `events_per_sec_fleet` (device-replayed events summed over every
      host of every region) is recorded next to the per-region
      `events_per_sec_cluster` north star."""
    import shutil
    import subprocess
    import sys
    import tempfile
    import threading
    import types

    import cadence_tpu

    from ..engine.failovermanager import FailoverManager
    from ..engine.multicluster import _refresh_domain_tasks
    from ..engine.replication import REPLICATION_QUEUE
    from ..rpc.cluster import _wait_listening, free_port, launch_group
    from ..utils import metrics as cm
    from .mixes import (
        OP_QUERY,
        OP_SIGNAL,
        OP_SIGNAL_WITH_START,
        OP_START,
        ScheduledOp,
        TrafficMix,
    )

    env_extra = {
        "CADENCE_TPU_SERVING": "1",
        # aggressive snapshot policy: every parity-clean append refreshes
        # the local store AND ships the record to the peer region, so the
        # kill can land anywhere and the standby still hydrates warm
        "CADENCE_TPU_SNAPSHOT_MIN_EVENTS": "1",
        "CADENCE_TPU_SNAPSHOT_EVERY_EVENTS": "1",
        "CADENCE_TPU_SERVING_BATCH": "8",
        "CADENCE_TPU_SERVING_WARM_EVENTS": "16,32,64",
    }
    domain = "lg-region"
    plans = [DomainPlan(domain, rps, mix=STANDARD_MIX,
                        pool_size=pool_size)]
    schedule = build_schedule(plans, duration_s, seed)
    # promoted-phase traffic against the STOLEN pool: signal-dominant
    # (decision transactions on the hydrated rows), a start tail for
    # post-failover admits — no resets (their compile warm-up belongs to
    # prepare, which phase 2 deliberately skips: the pool it drives is
    # the replicated one, not a freshly seeded one)
    mix2 = TrafficMix("region-promoted", {OP_SIGNAL: 0.5, OP_START: 0.2,
                                          OP_QUERY: 0.2,
                                          OP_SIGNAL_WITH_START: 0.1})
    plans2 = [DomainPlan(domain, rps, mix=mix2, pool_size=pool_size)]
    schedule2 = [
        # churn start ids restart phase-1's replicated churn ids unless
        # salted; pool/sws/query ids must NOT be salted (the stolen pool
        # is the point)
        ScheduledOp(index=op.index, at_s=op.at_s, kind=op.kind,
                    domain=op.domain,
                    workflow_id=(f"p2-{op.workflow_id}"
                                 if op.kind == OP_START
                                 else op.workflow_id), arg=op.arg)
        for op in build_schedule(plans2, duration_s, seed + 1)]

    wal_dir = tempfile.mkdtemp(prefix="cadence-region-")
    group = launch_group(("primary", "standby"), num_hosts=num_hosts,
                         num_shards=num_shards, hb_interval=hb_interval,
                         ttl=ttl, env_extra=env_extra, wal_dir=wal_dir)
    pcluster = group.clusters["primary"]
    scluster = group.clusters["standby"]
    primary_hosts = sorted(pcluster.hosts)
    standby_hosts = sorted(scluster.hosts)
    kill_scrape_primary: Dict[str, dict] = {}
    lag_doc = {"lag": -1, "tail": 0}
    recover_proc = None
    try:
        # hold traffic until every host in BOTH regions is serving-warm
        deadline = time.monotonic() + 600
        while time.monotonic() < deadline:
            docs = []
            for cl in (pcluster, scluster):
                for n in sorted(cl.hosts):
                    try:
                        docs.append(cl.admin(n, "admin_cluster"))
                    except Exception:
                        pass
            if (len(docs) == len(pcluster.hosts) + len(scluster.hosts)
                    and all(d.get("serving_warmed") for d in docs)):
                break
            time.sleep(0.5)
        else:
            raise TimeoutError("serving tier never warmed in both regions")
        group.register_global_domain(domain)

        clients = [pcluster.frontend(n) for n in primary_hosts]
        gen = LoadGenerator(clients, schedule, plans, workers=workers)
        gen.prepare(setup_deadline_s=120.0)
        # let the seeded pool replicate before the measured window so the
        # kill-time lag number reflects steady-state streaming, not the
        # prepare burst
        group.replicate()
        counter = {"n": 0}

        def completer_client():
            counter["n"] += 1
            return pcluster.frontend(
                primary_hosts[counter["n"] % len(primary_hosts)])

        completers = DecisionCompleters(completer_client, [domain])
        completers.start()
        start_scrape_primary = _host_metrics(pcluster)
        start_scrape_standby = _host_metrics(scluster)
        t_fleet0 = time.monotonic()

        def killer():
            time.sleep(max(0.1, duration_s * kill_at_frac))
            # the pre-kill lag gate: bounded wait for the stream to be
            # caught up (traffic still flowing), then record the honest
            # number — this is the data-loss window the kill can cost
            lag_deadline = time.monotonic() + 15.0
            while True:
                try:
                    tail = group.active.stores.queue.size(REPLICATION_QUEUE)
                    ack = group.standby.stores.queue.get_ack(
                        "repl-from:primary", "standby")
                    lag_doc["lag"], lag_doc["tail"] = max(0, tail - ack), tail
                except Exception:
                    pass
                if (0 <= lag_doc["lag"] <= max_repl_lag
                        or time.monotonic() > lag_deadline):
                    break
                time.sleep(0.2)
            kill_scrape_primary.update(_host_metrics(pcluster))
            # kill -9 EVERY active-region process: serving plane first,
            # then the region's store itself
            for name in primary_hosts:
                try:
                    pcluster.kill_host(name)
                except Exception:
                    pass
            try:
                pcluster.store_proc.kill()
                pcluster.store_proc.wait(timeout=10)
            except Exception:
                pass
            # the remaining phase-1 schedule has no region to land on:
            # abort so the workers stop burning retry backoff against a
            # dead region (their in-flight errors are already recorded)
            gen.abort()

        kill_thread = threading.Thread(target=killer, daemon=True)
        kill_thread.start()
        load1 = gen.run()
        kill_thread.join(timeout=60)
        completers.stop()
        load1.completed_churn = completers.completed

        # -- warm promotion: pre-flip hydration, then flip + regenerate --
        t_promote0 = time.monotonic()
        fm = FailoverManager(group)
        prehydration = fm._prehydrate(group.standby) or {}
        group.standby.frontend.update_domain(domain,
                                             active_cluster="standby")
        _refresh_domain_tasks(group.standby, domain)
        promote_s = time.monotonic() - t_promote0

        clients2 = [scluster.frontend(n) for n in standby_hosts]
        gen2 = LoadGenerator(clients2, schedule2, plans2, workers=workers,
                             request_salt="p2-")
        counter2 = {"n": 0}

        def completer_client2():
            counter2["n"] += 1
            return scluster.frontend(
                standby_hosts[counter2["n"] % len(standby_hosts)])

        completers2 = DecisionCompleters(completer_client2, [domain])
        completers2.start()
        load2 = gen2.run()
        drain_deadline = time.monotonic() + max(5.0, ttl * 4)
        last = -1
        while time.monotonic() < drain_deadline:
            time.sleep(0.5)
            if completers2.completed == last:
                break
            last = completers2.completed
        completers2.stop()
        load2.completed_churn = completers2.completed
        window = max(2 * duration_s, time.monotonic() - t_fleet0)

        end_scrape_standby = _host_metrics(scluster)
        verify_standby = (_verify_cluster_state(scluster)
                          if verify else None)

        # -- the killed region comes back: relaunch its store from the
        # WAL it crashed with (recover_stores fsck runs inside the store
        # server) and verify oracle↔device over the recovered state
        verify_primary = None
        if verify:
            rport = free_port()
            renv = dict(os.environ)
            renv.setdefault("JAX_PLATFORMS", "cpu")
            repo = os.path.dirname(os.path.dirname(
                os.path.abspath(cadence_tpu.__file__)))
            renv["PYTHONPATH"] = repo + os.pathsep + renv.get(
                "PYTHONPATH", "")
            recover_proc = subprocess.Popen(
                [sys.executable, "-m", "cadence_tpu.rpc.storeserver",
                 "--port", str(rport), "--wal", pcluster.wal], env=renv)
            _wait_listening(rport, recover_proc)
            verify_primary = _verify_cluster_state(
                types.SimpleNamespace(store_port=rport))
    finally:
        if recover_proc is not None and recover_proc.poll() is None:
            recover_proc.kill()
            recover_proc.wait(timeout=10)
        group.stop()
        shutil.rmtree(wal_dir, ignore_errors=True)

    # -- warm-promotion accounting: a stolen execution is warm when its
    # HBM state was snapshot-hydrated at the flip OR already resident via
    # the standby's device-speed apply; young (sub-snapshot-floor)
    # histories are reported, not charged (same convention as
    # cluster_serving_scenario)
    warm = (prehydration.get("hydrated", 0)
            + prehydration.get("already_resident", 0))
    cold = prehydration.get("cold", 0) + prehydration.get("stale", 0)
    steals = warm + cold
    hydration_ratio = (warm / steals) if steals > 0 else 0.0

    def _life_sum(scope, metric):
        """Whole-life counter: standby over its life + primary pre-kill."""
        return (_counter_delta(end_scrape_standby, {}, scope, metric)
                + _counter_delta(kill_scrape_primary, {}, scope, metric))

    serving_divergence = _life_sum(cm.SCOPE_TPU_SERVING,
                                   cm.M_SERVING_DIVERGENCE)
    migration_divergence = _life_sum(cm.SCOPE_TPU_MIGRATION,
                                     cm.M_MIG_DIVERGENCE)
    repl_device_divergence = _life_sum(cm.SCOPE_REPLICATION,
                                       cm.M_REPL_DEVICE_DIVERGENCE)
    snapshots_installed = _counter_delta(end_scrape_standby, {},
                                         cm.SCOPE_REPLICATION,
                                         cm.M_REPL_SNAP_INSTALLED)
    device_applied = _counter_delta(end_scrape_standby, {},
                                    cm.SCOPE_REPLICATION,
                                    cm.M_REPL_DEVICE_APPLIED)

    def events_of(scrapes, base, hosts):
        return (_counter_delta(scrapes, base, cm.SCOPE_TPU_RESIDENT,
                               cm.M_RESIDENT_EVENTS_APPENDED, hosts=hosts)
                + _counter_delta(scrapes, base, cm.SCOPE_TPU_REPLAY,
                                 cm.M_EVENTS_REPLAYED, hosts=hosts))

    events_primary = events_of(kill_scrape_primary, start_scrape_primary,
                               set(primary_hosts))
    events_standby = events_of(end_scrape_standby, start_scrape_standby,
                               set(standby_hosts))
    events_fleet = events_primary + events_standby

    pct2 = load2.percentiles(OP_SIGNAL)
    slos = [SLO(domain=domain, p99_ms=p99_slo_ms, max_error_rate=0.2)]
    slo_report = evaluate_slos(load2, slos)
    lag_bounded = 0 <= lag_doc["lag"] <= max_repl_lag

    doc = {
        "scenario": "region-failover",
        "run": {"duration_s": duration_s, "num_hosts": num_hosts,
                "num_shards": num_shards, "rps": rps,
                "pool_size": pool_size, "seed": seed,
                "kill_at_frac": kill_at_frac, "ttl": ttl,
                "workers": workers, "hydration_floor": hydration_floor,
                "max_repl_lag": max_repl_lag,
                "regions": {"primary": primary_hosts,
                            "standby": standby_hosts}},
        "traffic": {"active_phase": load1.as_dict(),
                    "promoted_phase": load2.as_dict()},
        "latency": {"promoted_signal_p50_ms": round(pct2["p50"] * 1000, 3),
                    "promoted_signal_p99_ms": round(pct2["p99"] * 1000, 3)},
        "slo": slo_report.as_dict(),
        "replication": {
            "lag_at_kill": lag_doc["lag"],
            "queue_tail_at_kill": lag_doc["tail"],
            "lag_bounded": lag_bounded,
            "snapshots_installed": snapshots_installed,
            "device_applied": device_applied,
        },
        "failover": {
            "promote_s": round(promote_s, 3),
            "prehydration": prehydration,
            "warm_steals": warm, "cold_steals": cold,
            "young_steals": prehydration.get("young", 0),
            "hydration_ratio": round(hydration_ratio, 4),
        },
        "parity": {
            "serving_divergence": serving_divergence,
            "migration_divergence": migration_divergence,
            "replication_device_divergence": repl_device_divergence,
        },
        "north_star": {
            "events_per_sec_fleet": round(events_fleet / window, 1),
            "events_per_sec_cluster": round(events_standby / window, 1),
            "events_per_sec_cluster_killed_region": round(
                events_primary / window, 1),
            "events_replayed_fleet": events_fleet,
            "window_s": round(window, 3),
        },
        "verify": {"promoted_region": verify_standby,
                   "killed_region_recovered": verify_primary},
    }
    doc["ok"] = bool(
        slo_report.ok
        and lag_bounded
        and steals > 0
        and hydration_ratio >= hydration_floor
        and snapshots_installed > 0
        and serving_divergence == 0
        and migration_divergence == 0
        and repl_device_divergence == 0
        and (verify_standby is None or verify_standby["divergent"] == 0)
        and (verify_primary is None or verify_primary["divergent"] == 0))
    return doc


def mixed_scenario(duration_s: float = 8.0, num_hosts: int = 2,
                   domains: Optional[List[str]] = None,
                   rps_per_domain: float = 3.0,
                   chaos_spec: str = "", seed: int = 20260803,
                   p99_slo_ms: float = 2500.0,
                   workers: int = 16, verify: bool = True,
                   pool_size: int = 6, num_shards: int = 8,
                   mix_name: str = "standard") -> dict:
    """Plain mixed-traffic run (no quotas): the `load run` CLI verb —
    the baseline latency-trajectory recorder. `mix_name` selects the
    traffic blend (mixes.MIXES — `query-heavy` drives the visibility
    read surface; set CADENCE_TPU_VISIBILITY=1 in the environment and
    the launched store server inherits it, serving those reads from the
    columnar device tier); visibility ops get their own per-op SLO rows
    so the read path is gated alongside the write path."""
    from .mixes import MIXES, VIS_OPS

    domains = list(domains or ["lg-a", "lg-b"])
    mix = MIXES.get(mix_name, STANDARD_MIX)
    plans = [DomainPlan(d, rps_per_domain, mix=mix,
                        pool_size=pool_size) for d in domains]
    schedule = build_schedule(plans, duration_s, seed)
    load, quota_metrics, verify_doc = _run_harness(
        plans, schedule, duration_s, num_hosts, num_shards, workers,
        chaos_spec, verify)

    slos = [SLO(p99_ms=p99_slo_ms, max_error_rate=0.2)]
    if any(mix.weights.get(op, 0) > 0 for op in VIS_OPS):
        slos += [SLO(op=op, p99_ms=p99_slo_ms, max_error_rate=0.0)
                 for op in VIS_OPS]
    slo_report = evaluate_slos(load, slos)
    doc = {
        "scenario": "mixed",
        "run": {"duration_s": duration_s, "num_hosts": num_hosts,
                "num_shards": num_shards, "seed": seed,
                "domains": domains, "rps_per_domain": rps_per_domain,
                "chaos": chaos_spec, "workers": workers,
                "mix": mix.name},
        "traffic": load.as_dict(),
        "admission": {"scrape": quota_metrics},
        "slo": slo_report.as_dict(),
        "verify": verify_doc,
    }
    doc["ok"] = bool(slo_report.ok
                     and (verify_doc is None
                          or verify_doc["divergent"] == 0))
    return doc


def visibility_scenario(duration_s: float = 4.0, rps: float = 60.0,
                        workers: int = 16, pool_size: int = 8,
                        seed: int = 20260804, num_shards: int = 4,
                        staleness_bound: int = 64) -> dict:
    """The device-visibility tier comparison (ISSUE 12's acceptance
    run): the SAME seeded query-heavy open-loop schedule driven twice
    against a fresh in-process cluster — device tier OFF (host dict/set
    indexes) then ON (columnar mask kernels, per-query parity gate) —
    recording per-op List/Scan/Count p50/p99, the device/fallback path
    mix, the recorded-staleness gauge, and the parity counters.

    The tier's contract, gated in `doc["ok"]`:
    - parity: every device-served query's result ids equal the host
      store's answer under the same lock (divergence counter 0;
      host fallbacks are COUNTED, never failures);
    - staleness: the observed appender backlog at query time stays
      under the configured bound (the flush keeps reads
      read-your-writes consistent);
    - the post-run oracle↔device verify stays green (visibility reads
      never perturb execution state)."""
    import os

    from ..engine.onebox import Onebox
    from ..utils import compile_cache
    from ..utils import metrics as cm
    from .mixes import QUERY_HEAVY_MIX, VIS_OPS, trace_digest

    compile_cache.enable()
    domain = "lg-vis"
    plans = [DomainPlan(domain, rps, mix=QUERY_HEAVY_MIX,
                        pool_size=pool_size)]
    schedule = build_schedule(plans, duration_s, seed)
    vis_ops_scheduled = sum(1 for op in schedule if op.kind in VIS_OPS)

    saved = {k: os.environ.get(k) for k in
             ("CADENCE_TPU_VISIBILITY", "CADENCE_TPU_VISIBILITY_PARITY",
              "CADENCE_TPU_VISIBILITY_STALENESS")}
    modes: Dict[str, dict] = {}
    try:
        for mode in ("off", "on"):
            os.environ["CADENCE_TPU_VISIBILITY"] = \
                "1" if mode == "on" else "0"
            os.environ["CADENCE_TPU_VISIBILITY_PARITY"] = "1"
            # the bound under test IS the view's configured bound:
            # queries inside it may serve the lagging view (parity
            # skipped there by design), past it they flush inline
            os.environ["CADENCE_TPU_VISIBILITY_STALENESS"] = \
                str(staleness_bound)
            box = Onebox(num_hosts=1, num_shards=num_shards)
            gen = LoadGenerator([box.frontend], schedule, plans,
                                workers=workers, pump=box.pump_once)
            gen.prepare(setup_deadline_s=120.0)
            if mode == "on":
                # warm the kernel variants OUTSIDE the measured window:
                # one pass over the seeded query pool compiles every
                # mask shape the schedule will replay, and a write →
                # drain → query cycle compiles the delta-scatter apply
                # kernel (deployment warmup, same discipline as the
                # serving scenario — a mid-window XLA compile would
                # stall the flush and smear the measured p99)
                from .generator import CHURN_TYPE, churn_task_list
                from .mixes import VIS_QUERIES
                info = box.stores.domain.by_name(domain)
                for q in VIS_QUERIES:
                    box.stores.visibility.query(info.domain_id, q)
                    box.stores.visibility.count(info.domain_id, q)
                box.frontend.start_workflow_execution(
                    domain, "lg-vis-warm", CHURN_TYPE,
                    churn_task_list(domain))
                box.pump_once()
                for q in VIS_QUERIES[:2]:
                    box.stores.visibility.query(info.domain_id, q)
            load = gen.run()
            pct_list = load.percentiles("list")
            pct_count = load.percentiles("count")
            t = load.totals(domain)
            reg = box.metrics
            sc = cm.SCOPE_TPU_VISIBILITY
            doc_mode = {
                "sent": t.sent, "ok": t.ok, "errors": t.errors,
                "duration_s": round(load.duration_s, 3),
                "list_p50_ms": round(pct_list["p50"] * 1000, 3),
                "list_p99_ms": round(pct_list["p99"] * 1000, 3),
                "count_p50_ms": round(pct_count["p50"] * 1000, 3),
                "count_p99_ms": round(pct_count["p99"] * 1000, 3),
            }
            if mode == "on":
                view = box.stores.visibility._device
                staleness = reg.histogram(sc, cm.M_VIS_STALENESS)
                doc_mode.update({
                    "visibility": view.stats() if view is not None
                    else {},
                    "staleness_observed_max": (view.staleness_max
                                               if view is not None else 0),
                    "staleness_served_max": (view.served_staleness_max
                                             if view is not None else 0),
                    "staleness_p99": round(staleness.percentile(0.99), 3),
                    "device_served": reg.counter(sc,
                                                 cm.M_VIS_DEVICE_SERVED),
                    "host_fallbacks": reg.counter(
                        sc, cm.M_VIS_HOST_FALLBACKS),
                    "parity_checks": reg.counter(sc,
                                                 cm.M_VIS_PARITY_CHECKS),
                    "parity_divergence": reg.counter(sc,
                                                     cm.M_VIS_DIVERGENCE),
                })
                if view is not None:
                    view.stop()
            verify = box.tpu.verify_all()
            doc_mode["verify"] = {"total": verify.total,
                                  "divergent": len(verify.divergent),
                                  "ok": bool(verify.ok)}
            modes[mode] = doc_mode
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v

    on, off = modes["on"], modes["off"]
    # the gate is on SERVED staleness: a query may observe a deeper
    # backlog, but it must flush before serving past the bound
    staleness_ok = on.get("staleness_served_max", 0) <= staleness_bound
    doc = {
        "scenario": "visibility",
        "run": {"duration_s": duration_s, "rps": rps, "workers": workers,
                "pool_size": pool_size, "seed": seed,
                "num_shards": num_shards,
                "staleness_bound": staleness_bound,
                "vis_ops_scheduled": vis_ops_scheduled,
                "trace_digest": trace_digest(schedule)},
        "off": off,
        "on": on,
        "comparison": {
            "list_p99_on_ms": on["list_p99_ms"],
            "list_p99_off_ms": off["list_p99_ms"],
            "device_served": on.get("device_served", 0),
            "host_fallbacks": on.get("host_fallbacks", 0),
            "parity_divergence": on.get("parity_divergence", 0),
            "staleness_p99": on.get("staleness_p99", 0.0),
            "staleness_observed_max": on.get("staleness_observed_max", 0),
            "staleness_served_max": on.get("staleness_served_max", 0),
            "staleness_ok": bool(staleness_ok),
        },
    }
    doc["ok"] = bool(
        on.get("parity_divergence", 0) == 0
        and on.get("device_served", 0) > 0
        and on.get("parity_checks", 0) > 0
        and staleness_ok
        and on["verify"]["divergent"] == 0
        and off["verify"]["divergent"] == 0)
    return doc
