"""Interleaving fuzzer: seeded live-transaction schedules under chaos.

The history fuzzer (gen/fuzz.py) proves the replay kernel over
arbitrary *persisted* histories; this module fuzzes what produces them
— the serving tier's live transaction stream. A seeded schedule of
frontend operations (start / signal with request-id dedup races /
signal-with-start start-vs-signal races / cancel / terminate / reset /
query / decision & activity completions / manual-clock advances) drives
a durable-WAL Onebox with the device-serving tier enabled, while three
seeded fault families fire:

- op chaos (the wire-chaos spec, rpc/chaos.py syntax): dispatches are
  dropped or delayed BEFORE anything is applied — the transport-retry
  shape, healed by the driver's retry loop exactly like `rpc/client`'s;
- store faults (engine/faults.FaultInjector): writes raise
  TransientStoreError before they apply, across frontend ops AND queue
  pumps (the at-least-once redelivery path);
- crashpoints (engine/crashpoints.py, `raise` mode): the process "dies"
  at an exact WAL/store commit offset; the driver discards the live
  box, runs the recovery fsck (gated CLEAN at every kill), recovers
  from the WAL prefix, rebuilds the cluster on the SAME manual clock,
  refreshes tasks, and replays the op — the in-process analog of the
  kill-anywhere crash matrix, mid-traffic.

The acceptance bar mirrors the chaos soak's, extended to the serving
tier: the chaotic run's final per-workflow mutable-state checksums must
be BYTE-IDENTICAL to a fault-free run of the same schedule,
`tpu.serving/parity-divergence` must be 0 while the tier actually took
transactions, every kill's recovery fsck must be clean, and a closing
`verify_all` (device bulk replay vs live states) must hold zero
divergence.

Determinism contract: ops execute in schedule order on one thread; all
decision/activity content is seeded by `(seed, workflow, schedule_id)`
— state-derived, so crash-replayed ops regenerate identical decisions;
time comes from one ManualTimeSource that survives recovery. Run ids
minted by reset/continue-as-new are uuid4 (engine-owned), which is why
the comparison is the canonical payload checksum — run-id strings are
not part of it, exactly as in the chaos soak.
"""
from __future__ import annotations

import os
import random
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ..core.checksum import crc32_of_row, payload_row
from ..core.codec import serialize_history
from ..core.enums import EMPTY_EVENT_ID, DecisionType, EventType, WorkflowState
from ..core.events import HistoryBatch, HistoryEvent, RetryPolicy
from ..engine import crashpoints, walcheck
from ..engine.crashpoints import CrashPoint, SimulatedCrash
from ..engine.faults import FaultInjector, TransientStoreError, inject_faults
from ..engine.domain import DomainNotActiveError
from ..engine.durability import open_durable_stores, recover_stores
from ..engine.history_engine import Decision, InvalidRequestError, TaskToken
from ..engine.multicluster import ReplicatedClusters
from ..engine.onebox import Onebox
from ..engine.persistence import (
    EntityNotExistsError,
    WorkflowAlreadyStartedError,
)
from ..engine.replication import ReplicationTask, _DeviceApplier
from ..models.deciders import SignalDecider
from ..rpc import chaos as chaos_mod
from ..rpc.chaos import ChaosError
from ..utils import metrics as m
from ..utils.clock import ManualTimeSource

DOMAIN = "ilv-domain"

#: schedule_id past which the seeded decision script closes the run
_CLOSE_SCHED = 34
#: crashpoint sites the kill op rotates through — all fire on the
#: DRIVER thread (type=h filters WAL sites to history records written
#: inside the commit; the store sites live at the compound commits)
KILL_SITES = (
    (crashpoints.SITE_BEFORE_WRITE, "h"),
    (crashpoints.SITE_MID_RECORD, "h"),
    (crashpoints.SITE_AFTER_WRITE, "h"),
    (crashpoints.SITE_AFTER_FSYNC, "h"),
    ("store.execution.create_workflow", ""),
    ("store.execution.update_workflow", ""),
    ("store.history.append_batch", ""),
)


def _tl(wf: str) -> str:
    return f"tl-{wf}"


@dataclass
class _ActResp:
    """Poll-shaped carrier for a reconstructed activity token (the
    worker-held-token analog, see _direct_activity)."""

    token: object
    activity_id: str


@dataclass
class _DecisionResp:
    """Poll-shaped carrier for a reconstructed decision token (see
    _direct_decision)."""

    token: object
    history: list
    queries: tuple = ()
    query_only: bool = False


# ---------------------------------------------------------------------------
# Schedule generation
# ---------------------------------------------------------------------------


def build_schedule(seed: int, num_workflows: int = 4,
                   length: int = 120, kills: int = 2) -> List[dict]:
    """A seeded op schedule. `kills` crashpoint arms are woven in at
    seeded positions (the fault-free run skips them); every workflow is
    started early and the tail of the schedule drives all of them
    closed."""
    rng = random.Random(f"ilv-schedule:{seed}")
    wfs = [f"ilv-wf-{i}" for i in range(num_workflows)]
    ops: List[dict] = []
    # starts first: half by StartWorkflowExecution, half by the
    # signal-with-start race (the start arm)
    for i, wf in enumerate(wfs):
        if i % 2 == 0:
            ops.append({"op": "start", "wf": wf,
                        "retry": rng.random() < 0.3})
        else:
            ops.append({"op": "sws", "wf": wf, "name": "sws-first",
                        "request_id": f"sws-rid-{wf}"})
        ops.append({"op": "decide", "wf": wf})
    sig_seq = 0
    for _ in range(length):
        wf = rng.choice(wfs)
        r = rng.random()
        if r < 0.30:
            sig_seq += 1
            ops.append({"op": "signal", "wf": wf,
                        "name": f"sig-{sig_seq}",
                        "request_id": f"rid-{sig_seq}"})
            if rng.random() < 0.25:
                # the dedup race: the same request id again — must be a
                # no-op however the interleaving lands
                ops.append({"op": "signal", "wf": wf,
                            "name": f"sig-{sig_seq}",
                            "request_id": f"rid-{sig_seq}"})
        elif r < 0.40:
            # signal-with-start against a RUNNING workflow: the signal
            # arm of the race (request id dedups the crash-retry)
            sig_seq += 1
            ops.append({"op": "sws", "wf": wf,
                        "name": f"sws-{sig_seq}",
                        "request_id": f"sws-rid-{sig_seq}"})
        elif r < 0.62:
            ops.append({"op": "decide", "wf": wf})
        elif r < 0.74:
            ops.append({"op": "act", "wf": wf})
        elif r < 0.80:
            ops.append({"op": "query", "wf": wf})
            ops.append({"op": "decide", "wf": wf})
        elif r < 0.84:
            ops.append({"op": "advance",
                        "seconds": rng.choice([1, 2, 5, 11])})
        elif r < 0.88 and rng.random() < 0.5:
            ops.append({"op": "reset", "wf": wf})
            ops.append({"op": "decide", "wf": wf})
        elif r < 0.92:
            ops.append({"op": "cancel", "wf": wf})
            ops.append({"op": "decide", "wf": wf})
        else:
            ops.append({"op": "pump"})
    # weave the kill arms in at seeded interior positions
    lo = 2 * num_workflows + 1
    for k in range(kills):
        pos = rng.randrange(lo, max(lo + 1, len(ops) - 5))
        site, rtype = KILL_SITES[rng.randrange(len(KILL_SITES))]
        ops.insert(pos, {"op": "kill", "site": site, "type": rtype,
                         "hit": rng.randrange(1, 4)})
    return ops


# ---------------------------------------------------------------------------
# The driver
# ---------------------------------------------------------------------------


@dataclass
class RunResult:
    checksums: Dict[str, Tuple[int, int]] = field(default_factory=dict)
    kills: int = 0
    fsck_clean: int = 0
    fsck_findings: List[str] = field(default_factory=list)
    retries: int = 0
    chaos_drops: int = 0
    chaos_delays: int = 0
    store_faults: int = 0
    serving_transactions: int = 0
    parity_divergence: int = -1
    verify_total: int = 0
    verify_divergent: int = 0
    ops_executed: int = 0

    @property
    def ok(self) -> bool:
        return (not self.fsck_findings
                and self.parity_divergence == 0
                and self.verify_divergent == 0)


class _OpGate:
    """The in-process stand-in for wire chaos: the same seeded spec
    grammar (rpc/chaos.parse_spec), applied at the op-dispatch boundary
    — a drop/sever fires BEFORE anything executes (nothing applied, so
    a retry is always safe), a delay sleeps. The driver's retry loop is
    the `rpc/client._Pool` seat."""

    def __init__(self, spec: str, seed: int) -> None:
        self.chaos = chaos_mod.parse_spec(spec) if spec else None
        self._rng = random.Random(f"ilv-gate:{seed}")
        self.drops = 0
        self.delays = 0

    def __call__(self) -> None:
        c = self.chaos
        if c is None:
            return
        r_delay, r_jitter, r_drop = (self._rng.random(), self._rng.random(),
                                     self._rng.random())
        if c.delay > 0 and r_delay < c.delay:
            self.delays += 1
            time.sleep(r_jitter * c.delay_ms / 1000.0)
        if r_drop < c.drop + c.sever:
            self.drops += 1
            raise ChaosError("ilv gate: op dropped before dispatch")


class InterleaveDriver:
    """Executes one schedule against a durable serving-enabled Onebox."""

    _BENIGN = (WorkflowAlreadyStartedError, InvalidRequestError,
               EntityNotExistsError)

    def __init__(self, wal_path: str, seed: int, serving: bool = True,
                 chaos_spec: str = "", store_fault_rate: float = 0.0,
                 max_attempts: int = 60) -> None:
        self.wal_path = wal_path
        self.seed = seed
        self.serving = serving
        self.max_attempts = max_attempts
        self.clock = ManualTimeSource()
        self.gate = _OpGate(chaos_spec, seed)
        self.injector = (FaultInjector(rate=store_fault_rate,
                                       seed=seed ^ 0x5a5a)
                         if store_fault_rate > 0 else None)
        self.result = RunResult()
        self.original_run: Dict[str, str] = {}
        self.box = None
        self._open_box(fresh=True)

    # -- box lifecycle -------------------------------------------------------

    def _open_box(self, fresh: bool) -> None:
        if fresh and not os.path.exists(self.wal_path):
            stores = open_durable_stores(self.wal_path)
        else:
            stores, _report = recover_stores(self.wal_path,
                                             verify_on_device=False,
                                             rebuild_on_device=False)
        if self.injector is not None:
            inject_faults(stores, self.injector)
        box = Onebox(num_hosts=1, num_shards=4, stores=stores,
                     time_source=self.clock)
        if self.serving:
            box.enable_serving()
        self.box = box
        if not fresh:
            # the task queues and matching backlog are not durable;
            # rebuilt state is (durability.recover_stores contract).
            # NO pump here: the refreshed tasks drain at the current
            # op's end like everyone else's — a mid-op recovery pump
            # would process cascades at a decision-in-flight state the
            # fault-free run never pumps in (child-started events would
            # BUFFER instead of recording, shifting history bytes).
            # Polls don't need it either: _direct_decision /
            # _direct_activity dispatch from the STORE when matching
            # comes up empty.
            self._retrying(lambda b: b.refresh_all_tasks(), allow_kill=False)

    def _recover_from_crash(self) -> None:
        """The armed crashpoint fired: the 'process' died mid-commit.
        fsck the surviving WAL (gated clean), recover, rebuild."""
        crashpoints.uninstall()
        self.result.kills += 1
        box, self.box = self.box, None
        try:
            if box.serving is not None:
                box.serving.stop()
            box.stores.wal.close()
        except Exception:
            pass
        report = walcheck.fsck(self.wal_path)
        if report.ok:
            self.result.fsck_clean += 1
        else:
            self.result.fsck_findings.extend(
                f"kill {self.result.kills}: {f.code} [{f.subject}] "
                f"{f.detail}" for f in report.findings)
        self._open_box(fresh=False)

    # -- dispatch ------------------------------------------------------------

    def _retrying(self, op, allow_kill: bool = True):
        """Run `op(box)` to convergence through the three fault
        families. `op` must be self-contained (re-resolves all state
        from the box), the retry-safety contract every arm of the real
        retry tier demands."""
        last: Optional[BaseException] = None
        for attempt in range(self.max_attempts):
            try:
                if attempt:
                    self.result.retries += 1
                self.gate()
                return op(self.box)
            except ChaosError as exc:
                last = exc
            except TransientStoreError as exc:
                self.result.store_faults += 1
                last = exc
            except self._BENIGN:
                return None
            except SimulatedCrash as exc:
                if not allow_kill:
                    raise
                last = exc
                self._recover_from_crash()
        raise RuntimeError(
            f"op did not converge after {self.max_attempts} attempts "
            f"(last: {type(last).__name__}: {last})")

    # -- seeded worker behavior ----------------------------------------------

    def _decisions_for(self, wf: str, resp) -> List[Decision]:
        """The worker script: seeded by (seed, TOKEN workflow,
        schedule_id) so a crash-replayed decision regenerates the same
        commands — keyed to the workflow the task BELONGS to (a shared
        task list serves children too), never to which op polled it."""
        sched_id = resp.token.schedule_id
        tk_wf = resp.token.workflow_id
        rng = random.Random(f"ilv-decide:{self.seed}:{tk_wf}:{sched_id}")
        cancel_requested = any(
            e.event_type == EventType.WorkflowExecutionCancelRequested
            for e in resp.history)
        if cancel_requested:
            return [Decision(DecisionType.CancelWorkflowExecution, {})]
        if sched_id >= _CLOSE_SCHED:
            if rng.random() < 0.75:
                return [Decision(DecisionType.CompleteWorkflowExecution,
                                 {"result": b"done"})]
            return [Decision(DecisionType.FailWorkflowExecution,
                             {"reason": "ilv-fail"})]
        is_original = (resp.token.run_id == self.original_run.get(wf))
        if is_original and sched_id >= _CLOSE_SCHED // 2 \
                and rng.random() < 0.3:
            return [Decision(DecisionType.ContinueAsNewWorkflowExecution,
                             {"task_list": _tl(wf)})]
        menu = []
        for k in range(rng.randrange(0, 3)):
            r = rng.random()
            if r < 0.35:
                menu.append(Decision(DecisionType.ScheduleActivityTask, dict(
                    activity_id=f"a-{sched_id}-{k}", task_list=_tl(tk_wf),
                    schedule_to_start_timeout_seconds=60,
                    schedule_to_close_timeout_seconds=120,
                    start_to_close_timeout_seconds=60,
                    heartbeat_timeout_seconds=0)))
            elif r < 0.55:
                menu.append(Decision(DecisionType.StartTimer, dict(
                    timer_id=f"t-{sched_id}-{k}",
                    start_to_fire_timeout_seconds=rng.choice([1, 2, 5]))))
            elif r < 0.75:
                menu.append(Decision(DecisionType.RecordMarker,
                                     dict(marker_name="ilv-marker")))
            elif r < 0.9:
                menu.append(Decision(
                    DecisionType.UpsertWorkflowSearchAttributes,
                    dict(search_attributes={"CustomKeywordField": b"ilv"})))
            else:
                # the initiator's OWN name prefixes the child id: a
                # child's child nests ("X-child-25-child-2") instead of
                # colliding with the parent's other children or itself
                # — a collision's start outcome would hinge on the
                # squatter's open/closed state at task-processing time,
                # exactly the timing the checksum gate must not see
                child_id = f"{tk_wf}-child-{sched_id}"
                menu.append(Decision(
                    DecisionType.StartChildWorkflowExecution, dict(
                        workflow_id=child_id,
                        workflow_type="ilv-child",
                        task_list=_tl(child_id),
                        execution_start_to_close_timeout_seconds=300,
                        task_start_to_close_timeout_seconds=10)))
        return menu

    def _family(self, wf: str) -> List[str]:
        """`wf` plus its (grand)children, sorted — the driver's fixed
        service order over the per-workflow task lists. Derived from
        STATE, so both runs compute the same family at the same op."""
        def op(box):
            names = {k[1] for k in box.stores.execution.list_executions()}
            return [wf] + sorted(n for n in names
                                 if n.startswith(f"{wf}-child"))
        return self._retrying(op, allow_kill=False) or [wf]

    def _decide_once(self, wf: str) -> bool:
        """Serve ONE decision from `wf`'s family, parent first then
        children in name order — each workflow owns its task list, so
        which decision an op completes never depends on matching's
        interleaving of a shared queue. Poll and respond are SEPARATELY
        retried phases — the real worker's shape: a fault after the
        poll consumed the task must retry the RESPOND with the held
        token, never lose the completion by re-polling an empty list
        (the respond's "decision no longer current" benign arm covers
        the already-applied crash-retry). True when a decision task was
        actually completed."""
        for member in self._family(wf):
            resp = None
            for _ in range(8):
                resp = self._retrying(
                    lambda b: b.frontend.poll_for_decision_task(
                        DOMAIN, _tl(member)))
                if resp is None or not resp.query_only:
                    break
                # query-only tasks are stateless and NOT durable (a
                # crash drops them): answer and poll again, so whether
                # one existed never changes which decision this op
                # completes
                qo = resp

                def answer(box):
                    for qid, _qtype, _args in qo.queries:
                        box.frontend.respond_query_task_completed(
                            qo.execution, qid, b"ilv-answer")
                self._retrying(answer)
                resp = None
            if resp is None:
                resp = self._direct_decision(member)
            if resp is None:
                continue
            qr = {qid: b"ilv-answer" for qid, _t, _a in resp.queries}
            self._retrying(
                lambda b: b.frontend.respond_decision_task_completed(
                    resp.token, self._decisions_for(member, resp),
                    query_results=qr))
            return True
        return False

    def _act_once(self, wf: str) -> bool:
        for member in self._family(wf):
            resp = self._retrying(
                lambda b: b.frontend.poll_for_activity_task(
                    DOMAIN, _tl(member)))
            if resp is None:
                resp = self._direct_activity(member)
            if resp is None:
                continue
            rng = random.Random(
                f"ilv-act:{self.seed}:{member}:{resp.activity_id}")
            # one draw per COMPLETION, not per retry attempt
            roll = rng.random()

            def op(box):
                if roll < 0.8:
                    box.frontend.respond_activity_task_completed(resp.token)
                else:
                    box.frontend.respond_activity_task_failed(
                        resp.token, reason="ilv-act-fail")

            self._retrying(op)
            return True
        return False

    def _direct_decision(self, wf: str):
        """The state-driven dispatch seat: matching's in-memory queues
        are deliberately lossy (kills drop them; stale tasks from closed
        or reset runs eat poll slots benignly), so a None poll does NOT
        mean no decision is dispatchable. The STORE is the truth: an
        in-flight decision reconstructs its token (the worker held it
        across the server death), a scheduled one starts directly
        through the engine (exactly what the frontend's poll does after
        the matching pop, with a deterministic request id). This keeps
        the op's history effect a function of replicated STATE, never of
        matching-queue noise — the convergence invariant the
        fault-free-vs-chaos checksum gate rests on."""

        def op(box):
            domain_id = box.stores.domain.by_name(DOMAIN).domain_id
            run = box.stores.execution.get_current_run_id(domain_id, wf)
            ms = box.stores.execution.get_workflow(domain_id, wf, run)
            info = ms.execution_info
            if (info.state == WorkflowState.Completed
                    or info.decision_schedule_id == EMPTY_EVENT_ID):
                return None
            engine = box.route(wf)
            if info.decision_started_id > 0:
                token = TaskToken(domain_id=domain_id, workflow_id=wf,
                                  run_id=run,
                                  schedule_id=info.decision_schedule_id,
                                  started_id=info.decision_started_id,
                                  attempt=info.decision_attempt)
            else:
                token = engine.record_decision_task_started(
                    domain_id, wf, run, info.decision_schedule_id,
                    request_id=f"ilv-direct-{info.decision_schedule_id}")
            history = box.stores.history.read_events(domain_id, wf, run)
            queries = engine.queries.attach((domain_id, wf, run))
            return _DecisionResp(token=token, history=history,
                                 queries=tuple(queries))

        return self._retrying(op)

    def _direct_activity(self, wf: str):
        """The activity twin of _direct_decision: a started-uncompleted
        activity reconstructs its token; a pending unstarted one (its
        matching task lost or shadowed by stale entries) starts directly
        through the engine, lowest schedule id first — the FIFO order
        matching itself would have used."""

        def op(box):
            domain_id = box.stores.domain.by_name(DOMAIN).domain_id
            run = box.stores.execution.get_current_run_id(domain_id, wf)
            ms = box.stores.execution.get_workflow(domain_id, wf, run)
            if ms.execution_info.state == WorkflowState.Completed:
                return None
            pending = sorted(ms.pending_activity_info_ids.values(),
                             key=lambda ai: ai.schedule_id)
            for ai in pending:
                if ai.started_id > 0:
                    return _ActResp(
                        token=TaskToken(
                            domain_id=domain_id, workflow_id=wf,
                            run_id=run, schedule_id=ai.schedule_id,
                            started_id=ai.started_id, attempt=ai.attempt),
                        activity_id=ai.activity_id)
            for ai in pending:
                token = box.route(wf).record_activity_task_started(
                    domain_id, wf, run, ai.schedule_id,
                    request_id=f"ilv-direct-act-{ai.schedule_id}")
                return _ActResp(token=token, activity_id=ai.activity_id)
            return None

        return self._retrying(op)

    # -- op execution --------------------------------------------------------

    def _execute(self, item: dict) -> None:
        op = item["op"]
        wf = item.get("wf", "")
        if op == "start":
            retry = (RetryPolicy(initial_interval_seconds=1,
                                 backoff_coefficient=2.0,
                                 maximum_interval_seconds=8,
                                 maximum_attempts=3)
                     if item.get("retry") else None)
            self._retrying(lambda b: b.frontend.start_workflow_execution(
                DOMAIN, wf, "ilv-type", _tl(wf), retry_policy=retry))
            self._note_original(wf)
        elif op == "sws":
            self._retrying(
                lambda b: b.frontend.signal_with_start_workflow_execution(
                    DOMAIN, wf, item["name"], "ilv-type", _tl(wf),
                    request_id=item.get("request_id")))
            self._note_original(wf)
        elif op == "signal":
            self._retrying(lambda b: b.frontend.signal_workflow_execution(
                DOMAIN, wf, item["name"], request_id=item["request_id"]))
        elif op == "cancel":
            self._retrying(
                lambda b: b.frontend.request_cancel_workflow_execution(
                    DOMAIN, wf))
        elif op == "terminate":
            self._retrying(
                lambda b: b.frontend.terminate_workflow_execution(
                    DOMAIN, wf, reason="ilv-terminate"))
        elif op == "query":
            self._retrying(lambda b: b.frontend.query_workflow(
                DOMAIN, wf, "ilv-query"))
        elif op == "reset":
            self._reset(wf)
        elif op == "decide":
            self._decide_once(wf)
        elif op == "act":
            self._act_once(wf)
        elif op == "advance":
            self.clock.advance(int(item["seconds"] * 1_000_000_000))
            self._pump()
        elif op == "pump":
            self._pump()
        elif op == "kill":
            crashpoints.install(CrashPoint(
                site=item["site"], hit=item["hit"], mode="raise",
                record_type=item.get("type", "")))
        else:
            raise ValueError(f"unknown schedule op {op!r}")
        self._pump()

    def _note_original(self, wf: str) -> None:
        """Record the first run id AFTER the start op converged — never
        from the start call's return value, which a crash-retry can
        swallow (the baseline and chaos runs must agree on which run is
        eligible for the continue-as-new arm)."""
        if wf in self.original_run:
            return
        domain_id = self._retrying(
            lambda b: b.stores.domain.by_name(DOMAIN).domain_id,
            allow_kill=False)
        run = self._retrying(
            lambda b: b.stores.execution.get_current_run_id(domain_id, wf))
        if run is not None:
            self.original_run[wf] = run

    def _reset(self, wf: str) -> None:
        """Reset to the SECOND decision boundary, when the history has
        one. Retry-safe: a crash-retry must not reset twice, so the op
        re-checks the precondition (current run changed ⇒ applied)."""
        domain_id = self._retrying(
            lambda b: b.stores.domain.by_name(DOMAIN).domain_id,
            allow_kill=False)
        before = self._retrying(
            lambda b: b.stores.execution.get_current_run_id(domain_id, wf))
        if before is None:
            return

        issued = [False]

        def op(box):
            current = box.stores.execution.get_current_run_id(domain_id, wf)
            if current != before:
                return None  # an earlier attempt applied fully
            ms = box.stores.execution.get_workflow(domain_id, wf, current)
            if (ms.execution_info.state == WorkflowState.Completed
                    and not issued[0]):
                return None
            # issued[0] and Completed: OUR half-applied reset terminated
            # the base but died before the new run's commit point —
            # re-issuing the reset on the terminated base resumes it
            # (terminate is a no-op on a closed run), so a fault between
            # the two commits never strands a terminated-but-unreset run
            events = box.stores.history.read_events(domain_id, wf, current)
            starts = [e for e in events
                      if e.event_type == EventType.DecisionTaskStarted]
            if len(starts) < 2:
                return None
            finish_id = starts[1].id + 1
            if not any(e.id == finish_id and e.event_type
                       == EventType.DecisionTaskCompleted for e in events):
                return None  # boundary not a completed decision
            issued[0] = True
            return box.frontend.reset_workflow_execution(
                DOMAIN, wf, decision_finish_event_id=finish_id,
                reason="ilv-reset")

        self._retrying(op)

    def _pump(self, rounds: int = 20) -> None:
        """Drain the queue cascade to QUIESCENCE (bounded): child starts
        generate decision tasks generate child-started records — a fixed
        round count leaves the tail's timing hostage to how fault
        retries interleaved with task generation, which is exactly the
        noise the checksum gate must not see. Quiescent-at-every-op
        makes the transfer cascade's depth irrelevant."""
        for _ in range(rounds):
            if self._retrying(lambda b: b.pump_once()) == 0:
                break

    # -- run -----------------------------------------------------------------

    def run(self, schedule: List[dict], with_kills: bool = True) -> RunResult:
        wfs = sorted({item["wf"] for item in schedule if "wf" in item})
        self._retrying(lambda b: b.frontend.register_domain(DOMAIN))
        for item in schedule:
            if item["op"] == "kill" and not with_kills:
                continue
            self._execute(item)
            self.result.ops_executed += 1
        # an unfired arm must not leak into the close phase bookkeeping
        crashpoints.uninstall()
        self._finish(wfs)
        return self.result

    def _finish(self, wfs: List[str]) -> None:
        """Drive every workflow closed, quiesce, and collect the gates."""
        domain_id = self._retrying(
            lambda b: b.stores.domain.by_name(DOMAIN).domain_id,
            allow_kill=False)

        def is_open(wf: str) -> bool:
            def op(box):
                run = box.stores.execution.get_current_run_id(domain_id, wf)
                ms = box.stores.execution.get_workflow(domain_id, wf, run)
                return ms.execution_info.state != WorkflowState.Completed
            out = self._retrying(op)
            return bool(out)

        for wf in wfs:
            for _ in range(80):
                if not is_open(wf):
                    break
                progressed = self._decide_once(wf)
                progressed = self._act_once(wf) or progressed
                self._pump()
                if not progressed:
                    self.clock.advance(2_000_000_000)
                    self._pump()
            if is_open(wf):
                # cron chains / starved runs: the operator hammer
                self._execute({"op": "terminate", "wf": wf})
        # bounded quiesce (not pump_until_quiet: tasks parked for closed
        # runs may legitimately linger in the matching backlog)
        for _ in range(50):
            if self._retrying(lambda b: b.pump_once()) == 0:
                break
        box = self.box
        if box.serving is not None:
            box.serving.drain(timeout=30)
            self.result.serving_transactions = int(box.metrics.counter(
                m.SCOPE_TPU_SERVING, m.M_SERVING_TXNS))
            self.result.parity_divergence = int(box.metrics.counter(
                m.SCOPE_TPU_SERVING, m.M_SERVING_DIVERGENCE))
        else:
            self.result.parity_divergence = 0
        for wf in wfs:
            def op(b, wf=wf):
                run = b.stores.execution.get_current_run_id(domain_id, wf)
                ms = b.stores.execution.get_workflow(domain_id, wf, run)
                return (int(crc32_of_row(payload_row(ms))),
                        int(ms.execution_info.close_status))
            self.result.checksums[wf] = self._retrying(op)
        self.gate.chaos = None  # verify below runs fault-free
        if self.injector is not None:
            self.injector.rate = 0.0
        verify = box.tpu.verify_all()
        self.result.verify_total = verify.total
        self.result.verify_divergent = len(verify.divergent)
        self.result.chaos_drops = self.gate.drops
        self.result.chaos_delays = self.gate.delays
        if box.serving is not None:
            box.serving.stop()
        box.stores.wal.close()


# ---------------------------------------------------------------------------
# The scenario: chaotic run vs fault-free oracle run
# ---------------------------------------------------------------------------


def interleave_scenario(seed: int = 20260804, num_workflows: int = 4,
                        length: int = 90, kills: int = 2,
                        chaos_spec: str = "drop=0.05,delay=0.1,delay_ms=4,"
                                          "seed=11",
                        store_fault_rate: float = 0.04,
                        workdir: str = "/tmp",
                        serving: bool = True) -> dict:
    """Run one seeded schedule twice — fault-free, then under the full
    chaos matrix — and gate the serving tier's zero-divergence story.
    Returns a JSON-able doc with `ok`."""
    schedule = build_schedule(seed, num_workflows=num_workflows,
                              length=length, kills=kills)
    paths = {name: os.path.join(workdir, f"ilv-{seed}-{name}.wal.jsonl")
             for name in ("baseline", "chaos")}
    for p in paths.values():
        if os.path.exists(p):
            os.remove(p)
    try:
        baseline = InterleaveDriver(
            paths["baseline"], seed, serving=serving).run(
                schedule, with_kills=False)
        chaotic = InterleaveDriver(
            paths["chaos"], seed, serving=serving, chaos_spec=chaos_spec,
            store_fault_rate=store_fault_rate).run(schedule)
    finally:
        crashpoints.uninstall()
        for p in paths.values():
            if os.path.exists(p):
                os.remove(p)
    identical = chaotic.checksums == baseline.checksums
    doc = {
        "scenario": "interleave",
        "seed": seed, "workflows": num_workflows,
        "schedule_ops": len(schedule), "kills_armed": kills,
        "chaos_spec": chaos_spec, "store_fault_rate": store_fault_rate,
        "serving": serving,
        "baseline": {
            "checksums": baseline.checksums,
            "serving_transactions": baseline.serving_transactions,
            "verify_total": baseline.verify_total,
        },
        "chaos": {
            "checksums": chaotic.checksums,
            "kills_fired": chaotic.kills,
            "fsck_clean": chaotic.fsck_clean,
            "fsck_findings": chaotic.fsck_findings,
            "retries": chaotic.retries,
            "op_drops": chaotic.chaos_drops,
            "op_delays": chaotic.chaos_delays,
            "store_faults": chaotic.store_faults,
            "serving_transactions": chaotic.serving_transactions,
            "parity_divergence": chaotic.parity_divergence,
            "verify_total": chaotic.verify_total,
            "verify_divergent": chaotic.verify_divergent,
        },
        "checksums_identical": identical,
        "ok": bool(identical and baseline.ok and chaotic.ok
                   and chaotic.kills == chaotic.fsck_clean
                   and (not serving
                        or chaotic.serving_transactions > 0)),
    }
    return doc


# ---------------------------------------------------------------------------
# Replication-seam fuzz profile: the apply pump vs live standby traffic
# ---------------------------------------------------------------------------

DOMAIN_R = "rilv-domain"
TL_R = "rilv-tasklist"


def build_replication_schedule(seed: int, num_workflows: int = 4,
                               length: int = 48,
                               poisons: int = 2) -> List[dict]:
    """A seeded schedule over the REPLICATION seam. Phase 1 drives live
    traffic on the active cluster with incremental apply-pump drains
    woven between ops (each drain is one queue page — so applies land at
    arbitrary history offsets, not at quiet barriers); `poisons`
    semantically-invalid ReplicationTasks are injected at seeded
    positions. A single mid-schedule `promote` is the split-brain NDC
    version bump; phase 2 interleaves standby-side live signals/resets
    with DIVERGENT active-side writes and bidirectional drains. The
    closing `heal` converges both sides."""
    rng = random.Random(f"rilv-schedule:{seed}")
    wfs = [f"rilv-wf-{i}" for i in range(num_workflows)]
    ops: List[dict] = [{"op": "start", "wf": wf} for wf in wfs]
    ops.append({"op": "drain"})
    sig = 0
    for _ in range(length):
        wf = rng.choice(wfs)
        r = rng.random()
        if r < 0.55:
            sig += 1
            ops.append({"op": "signal", "wf": wf, "name": f"ra-{sig}"})
        elif r < 0.9:
            ops.append({"op": "drain"})
        else:
            sig += 1
            # the dedup race across the wire: the same signal twice
            ops.append({"op": "signal", "wf": wf, "name": f"ra-{sig}",
                        "request_id": f"rrid-{sig}"})
            ops.append({"op": "signal", "wf": wf, "name": f"ra-{sig}",
                        "request_id": f"rrid-{sig}"})
    # poison tasks: seeded interior positions, phase 1 only (version 1)
    lo = num_workflows + 2
    for _ in range(poisons):
        pos = rng.randrange(lo, len(ops))
        ops.insert(pos, {"op": "poison", "wf": rng.choice(wfs)})
    ops.append({"op": "promote"})
    for _ in range(length // 2):
        wf = rng.choice(wfs)
        r = rng.random()
        if r < 0.40:
            sig += 1
            ops.append({"op": "s_signal", "wf": wf, "name": f"rs-{sig}"})
        elif r < 0.55:
            ops.append({"op": "s_reset", "wf": wf})
        elif r < 0.70:
            sig += 1
            # divergent active-side write: the old active keeps going at
            # its version — the loser branch NDC must fork and retire
            ops.append({"op": "signal", "wf": wf, "name": f"rz-{sig}"})
        else:
            ops.append({"op": "drain_both"})
    ops.append({"op": "heal"})
    return ops


class _ReplicationDriver:
    """Executes one replication-seam schedule against an in-process
    two-cluster group (`ReplicatedClusters`): the active cluster's live
    engine, the standby's apply pump (host replicator + device twin),
    and — after the promote — the standby's OWN live engine writing at
    the bumped failover version."""

    def __init__(self, seed: int, num_workflows: int = 4) -> None:
        self.seed = seed
        self.clusters = ReplicatedClusters(num_hosts=1, num_shards=4)
        # the serving tier feeds the seam under test: its post-flush
        # snapshot policy is what SHIPS records down the stream (the
        # wired Snapshotter.shipper), seeding the standby's device twin
        self.clusters.active.enable_serving()
        self.clusters.standby.enable_serving()
        self.clusters.register_global_domain(DOMAIN_R)
        self.wfs = [f"rilv-wf-{i}" for i in range(num_workflows)]
        # stays open through the whole run (signals land well short of
        # the close threshold) so every drain applies a LIVE history
        self.deciders = {wf: SignalDecider(expected_signals=999)
                         for wf in self.wfs}
        self.domain_id = self.clusters.active.stores.domain.by_name(
            DOMAIN_R).domain_id
        self.poisons_sent = 0
        self.drains = 0
        self.promoted = False

    # -- worker loop ---------------------------------------------------------

    def _drive(self, box, rounds: int = 200) -> None:
        """Bounded poll/decide/pump loop on one box (the taskpoller
        shape, in-package)."""
        for _ in range(rounds):
            progressed = box.pump_once() > 0
            while True:
                resp = box.frontend.poll_for_decision_task(DOMAIN_R, TL_R)
                if resp is None:
                    break
                progressed = True
                if resp.query_only:
                    for qid, _t, _a in resp.queries:
                        box.frontend.respond_query_task_completed(
                            resp.execution, qid, b"rilv")
                    continue
                decider = self.deciders[resp.token.workflow_id]
                try:
                    box.frontend.respond_decision_task_completed(
                        resp.token, decider.decide(resp.history))
                except InvalidRequestError:
                    pass  # stale token from a reset base run
                except DomainNotActiveError:
                    pass  # peer promotion landed on this workflow first
            if not progressed and box.matching.backlog() == 0:
                return

    # -- ops -----------------------------------------------------------------

    def _signal(self, box, wf: str, name: str, request_id=None) -> None:
        try:
            box.frontend.signal_workflow_execution(
                DOMAIN_R, wf, name, request_id=request_id)
        except (EntityNotExistsError, InvalidRequestError):
            return  # closed by an earlier close — benign
        except DomainNotActiveError:
            # the split-brain loser already saw the winner's higher
            # failover version on this workflow (reverse replication
            # raced ahead of its domain record): the write is rejected
            # typed, pre-apply — exactly the arbitration contract
            return
        self._drive(box)

    def _poison(self, wf: str) -> None:
        """Inject one semantically-invalid ReplicationTask: contiguity
        holds (first_event_id == the standby's expected next) but the
        batch completes an activity that was never scheduled — the host
        replicator must raise ReplayError and quarantine to the DLQ,
        never half-apply. Crafted after a full drain so the poison is at
        the head of the gap, not deduped behind real traffic."""
        self.clusters.replicate()
        run_id = self.clusters.standby.stores.execution.get_current_run_id(
            self.domain_id, wf)
        ms = self.clusters.standby.stores.execution.get_workflow(
            self.domain_id, wf, run_id)
        if ms.execution_info.state == WorkflowState.Completed:
            return
        next_id = ms.execution_info.next_event_id
        bad = HistoryBatch(
            domain_id=self.domain_id, workflow_id=wf, run_id=run_id,
            events=[HistoryEvent(
                id=next_id, event_type=EventType.ActivityTaskCompleted,
                version=1, timestamp=1,
                attrs=dict(scheduled_event_id=99990 + self.poisons_sent,
                           started_event_id=99991))])
        self.clusters.publisher.stores.queue.enqueue(
            "replication",
            ReplicationTask(domain_id=self.domain_id, workflow_id=wf,
                            run_id=run_id, first_event_id=next_id,
                            next_event_id=next_id + 1, version=1,
                            events_blob=serialize_history([bad])))
        self.poisons_sent += 1

    def _reset_standby(self, wf: str) -> None:
        """Live reset on the promoted standby: rewind to the second
        decision boundary when the history has one (the NDC fork + new
        run id that must replicate back and win)."""
        box = self.clusters.standby
        run_id = box.stores.execution.get_current_run_id(self.domain_id, wf)
        if run_id is None:
            return
        events = box.stores.history.read_events(self.domain_id, wf, run_id)
        starts = [e for e in events
                  if e.event_type == EventType.DecisionTaskStarted]
        if len(starts) < 2:
            return
        finish_id = starts[1].id + 1
        if not any(e.id == finish_id
                   and e.event_type == EventType.DecisionTaskCompleted
                   for e in events):
            return
        try:
            box.frontend.reset_workflow_execution(
                DOMAIN_R, wf, decision_finish_event_id=finish_id,
                reason="rilv-reset")
        except (EntityNotExistsError, InvalidRequestError):
            return
        self._drive(box)

    def _execute(self, item: dict) -> None:
        op, wf = item["op"], item.get("wf", "")
        c = self.clusters
        if op == "start":
            c.active.frontend.start_workflow_execution(
                DOMAIN_R, wf, "rilv-type", TL_R)
            self._drive(c.active)
        elif op == "signal":
            self._signal(c.active, wf, item["name"],
                         request_id=item.get("request_id"))
        elif op == "s_signal":
            self._signal(c.standby, wf, item["name"])
        elif op == "s_reset":
            self._reset_standby(wf)
        elif op == "drain":
            self.drains += 1
            c.active.serving.drain(timeout=30)  # flushes ship snapshots
            c.domain_processor.process_once()
            c.processor.process_once()
        elif op == "drain_both":
            self.drains += 1
            c.active.serving.drain(timeout=30)
            c.standby.serving.drain(timeout=30)
            c.processor.process_once()
            c.reverse_processor.process_once()
        elif op == "poison":
            self._poison(wf)
        elif op == "promote":
            c.replicate()  # standby forks from a replicated prefix
            c.split_brain_promote(DOMAIN_R)
            self.promoted = True
            self._drive(c.standby)
        elif op == "heal":
            c.heal(DOMAIN_R, "standby")
            self._drive(c.standby)
            self._drive(c.active)
            c.active.serving.drain(timeout=30)
            c.standby.serving.drain(timeout=30)
            c.replicate()
            c.replicate_reverse()
        else:
            raise ValueError(f"unknown replication schedule op {op!r}")

    def run(self, schedule: List[dict]) -> None:
        for item in schedule:
            self._execute(item)

    # -- gates ---------------------------------------------------------------

    def checksums(self, box) -> Dict[str, Tuple[str, int, int]]:
        """(current run id, canonical payload crc, close status) per
        workflow — the cross-region byte-identity gate."""
        out: Dict[str, Tuple[str, int, int]] = {}
        for wf in self.wfs:
            run_id = box.stores.execution.get_current_run_id(
                self.domain_id, wf)
            ms = box.stores.execution.get_workflow(self.domain_id, wf, run_id)
            out[wf] = (run_id, int(crc32_of_row(payload_row(ms))),
                       int(ms.execution_info.close_status))
        return out


def replication_interleave_scenario(seed: int = 20260806,
                                    num_workflows: int = 4,
                                    length: int = 48,
                                    poisons: int = 2) -> dict:
    """Fuzz the replication seam (ISSUE 17 satellite): one seeded
    schedule interleaves the standby's apply pump — host replicator +
    device twin, one queue page at a time — with live active-side
    traffic, a mid-schedule split-brain promotion (NDC failover-version
    bump), live signals/resets on the promoted standby racing divergent
    old-active writes, and seeded poison ReplicationTasks. Gates:

    - after heal, every workflow's (run id, canonical payload checksum,
      close status) is BYTE-IDENTICAL across both clusters;
    - the DLQ holds exactly the injected poisons — quarantine is
      DLQ-only (nothing else quarantined, nothing half-applied) and the
      reverse direction's DLQ is empty;
    - the device twin took real bulk applies with zero parity
      divergence on both registries;
    - closing verify_all (device bulk replay vs live state) is green on
      both clusters."""
    schedule = build_replication_schedule(
        seed, num_workflows=num_workflows, length=length, poisons=poisons)
    # fuzz histories are SHORT: tighten the snapshot policy so the
    # shipping seam actually carries records at this scale (the policy
    # is read at Snapshotter construction, inside the driver)
    knobs = {"CADENCE_TPU_SNAPSHOT_MIN_EVENTS": "1",
             "CADENCE_TPU_SNAPSHOT_EVERY_EVENTS": "4"}
    saved_env = {k: os.environ.get(k) for k in knobs}
    os.environ.update(knobs)
    try:
        driver = _ReplicationDriver(seed, num_workflows=num_workflows)
    finally:
        for k, v in saved_env.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    try:
        driver.run(schedule)
        c = driver.clusters
        c.active.serving.drain(timeout=30)
        c.standby.serving.drain(timeout=30)
    finally:
        for box in (driver.clusters.active, driver.clusters.standby):
            if box.serving is not None:
                box.serving.stop()

    active_sums = driver.checksums(c.active)
    standby_sums = driver.checksums(c.standby)
    dlq = c.processor.read_dlq()
    reverse_dlq = c.reverse_processor.read_dlq()

    def _counter(box, scope, name):
        return int(box.metrics.counter(scope, name))

    device_applied = _counter(c.standby, m.SCOPE_REPLICATION,
                              m.M_REPL_DEVICE_APPLIED)
    device_divergence = (
        _counter(c.standby, m.SCOPE_REPLICATION, m.M_REPL_DEVICE_DIVERGENCE)
        + _counter(c.active, m.SCOPE_REPLICATION, m.M_REPL_DEVICE_DIVERGENCE))
    serving_divergence = (
        _counter(c.standby, m.SCOPE_TPU_SERVING, m.M_SERVING_DIVERGENCE)
        + _counter(c.active, m.SCOPE_TPU_SERVING, m.M_SERVING_DIVERGENCE))
    verify_active = c.active.tpu.verify_all()
    verify_standby = c.standby.tpu.verify_all()

    device_expected = _DeviceApplier(c.standby.tpu,
                                     c.standby.metrics).enabled()
    identical = active_sums == standby_sums
    dlq_exact = (len(dlq) == driver.poisons_sent
                 and len(reverse_dlq) == 0
                 and all("missing activity" in e.error for e in dlq))
    doc = {
        "scenario": "replication-interleave",
        "seed": seed, "workflows": num_workflows,
        "schedule_ops": len(schedule),
        "drains": driver.drains,
        "promoted": driver.promoted,
        "poisons_injected": driver.poisons_sent,
        "dlq_depth": len(dlq),
        "reverse_dlq_depth": len(reverse_dlq),
        "dlq_exact": dlq_exact,
        "active_checksums": active_sums,
        "standby_checksums": standby_sums,
        "checksums_identical": identical,
        "replication": {
            "applied": c.processor.applied,
            "deduped": c.processor.deduped,
            "resends": c.processor.resends,
            "snapshots_installed": c.processor.snapshots_installed,
            "device_enabled": device_expected,
            "device_applied": device_applied,
            "device_divergence": device_divergence,
        },
        "serving_divergence": serving_divergence,
        "verify": {
            "active": {"total": verify_active.total,
                       "divergent": len(verify_active.divergent)},
            "standby": {"total": verify_standby.total,
                        "divergent": len(verify_standby.divergent)},
        },
        "ok": bool(identical and dlq_exact and driver.promoted
                   and driver.poisons_sent == poisons
                   and device_divergence == 0
                   and serving_divergence == 0
                   and (not device_expected
                        or (device_applied > 0
                            and c.processor.snapshots_installed > 0))
                   and verify_active.ok and verify_standby.ok),
    }
    return doc
