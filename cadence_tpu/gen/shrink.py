"""Minimizing shrinker: reduce a parity-failing history to a minimal
failing batch sequence before reporting.

A fuzzed divergence on a 200-event history is unactionable; the same
divergence on 3 batches names the broken transition. The shrinker is
classic ddmin (Zeller's delta debugging) over the BATCH axis — batches
are the transaction-boundary unit both replayers consume
(`apply_batch` / one encoded segment), so any subset is still a
replayable input even when it is no longer a *legal* workflow history:
the failure predicate decides what counts, and the default parity
predicates treat "either side errors" as NOT the failure being chased
(a shrink must preserve the original defect, not trade it for a
different crash).

Reproducibility: a `ShrinkReport` carries the generator coordinates
`(seed, workflow_index, profile, target_events)` plus the KEPT batch
indices and the minimal slice's digest — `reproduce()` regenerates the
exact minimal input from the seed alone, which is what the tests pin.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, List, Optional, Sequence

import numpy as np

from ..core.checksum import DEFAULT_LAYOUT, PayloadLayout
from ..core.events import HistoryBatch
from .fuzz import generate_fuzz_history, history_digest, oracle_final_row

Predicate = Callable[[List[HistoryBatch]], bool]


@dataclass
class ShrinkReport:
    """One shrink outcome, reproducible from the generator coordinates."""

    seed: int
    workflow_index: int
    profile: str
    target_events: int
    kept_indices: List[int] = field(default_factory=list)
    original_batches: int = 0
    original_events: int = 0
    shrunk_batches: int = 0
    shrunk_events: int = 0
    predicate_calls: int = 0
    digest: str = ""
    event_types: List[str] = field(default_factory=list)

    def reproduce(self) -> List[HistoryBatch]:
        """Regenerate the minimal failing slice from the seed alone."""
        full = generate_fuzz_history(self.seed, self.workflow_index,
                                     self.target_events, self.profile)
        return [full[i] for i in self.kept_indices]

    def summary(self) -> dict:
        return {
            "seed": self.seed, "workflow_index": self.workflow_index,
            "profile": self.profile, "target_events": self.target_events,
            "kept_indices": self.kept_indices,
            "batches": f"{self.original_batches} -> {self.shrunk_batches}",
            "events": f"{self.original_events} -> {self.shrunk_events}",
            "predicate_calls": self.predicate_calls,
            "digest": self.digest, "event_types": self.event_types,
        }


def _events_of(batches: Sequence[HistoryBatch]) -> int:
    return sum(len(b.events) + len(b.new_run_events or ())
               for b in batches)


def shrink_batches(batches: List[HistoryBatch], failing: Predicate,
                   max_calls: int = 2000) -> tuple:
    """ddmin over the batch list: returns (minimal_indices, calls).

    Invariant: `failing([batches[i] for i in minimal_indices])` is True,
    and removing ANY single remaining batch makes it False (1-minimal)."""
    calls = 0

    def check(indices: List[int]) -> bool:
        nonlocal calls
        calls += 1
        if calls > max_calls:
            raise RuntimeError(f"shrinker exceeded {max_calls} "
                               "predicate calls")
        return failing([batches[i] for i in indices])

    if not check(list(range(len(batches)))):
        raise ValueError("shrink_batches called with a non-failing input")
    indices = list(range(len(batches)))
    n = 2
    while len(indices) >= 2:
        chunk = max(1, len(indices) // n)
        subsets = [indices[i:i + chunk]
                   for i in range(0, len(indices), chunk)]
        reduced = False
        # try each subset alone, then each complement
        for sub in subsets:
            if len(sub) < len(indices) and check(sub):
                indices, n, reduced = sub, 2, True
                break
        if not reduced:
            for sub in subsets:
                comp = [i for i in indices if i not in sub]
                if comp and len(comp) < len(indices) and check(comp):
                    indices, n, reduced = comp, max(2, n - 1), True
                    break
        if not reduced:
            if n >= len(indices):
                break
            n = min(len(indices), n * 2)
    # 1-minimality sweep: ddmin at full granularity can still keep a
    # batch whose removal alone preserves the failure
    changed = True
    while changed and len(indices) > 1:
        changed = False
        for i in list(indices):
            trial = [j for j in indices if j != i]
            if check(trial):
                indices = trial
                changed = True
                break
    return indices, calls


def shrink_history(seed: int, workflow_index: int, failing: Predicate,
                   target_events: int = 100, profile: str = "mixed",
                   max_calls: int = 2000) -> ShrinkReport:
    """Shrink one generated history against `failing`; the report's
    coordinates alone reproduce the minimal slice."""
    batches = generate_fuzz_history(seed, workflow_index, target_events,
                                    profile)
    kept, calls = shrink_batches(batches, failing, max_calls=max_calls)
    minimal = [batches[i] for i in kept]
    from ..core.enums import EventType
    return ShrinkReport(
        seed=seed, workflow_index=workflow_index, profile=profile,
        target_events=target_events, kept_indices=kept,
        original_batches=len(batches), original_events=_events_of(batches),
        shrunk_batches=len(minimal), shrunk_events=_events_of(minimal),
        predicate_calls=calls, digest=history_digest(minimal),
        event_types=sorted({EventType(e.event_type).name
                            for b in minimal for e in b.events}))


# ---------------------------------------------------------------------------
# Parity predicates
# ---------------------------------------------------------------------------


def _device_row(batches: List[HistoryBatch],
                layout: PayloadLayout) -> Optional[np.ndarray]:
    """One history's device payload row, or None when the kernel flags
    an error (capacity overflow, corrupt shape — not the divergence
    being chased)."""
    from ..ops.replay import replay_corpus

    rows, _crcs, errors = replay_corpus([batches], layout)
    if int(errors[0]) != 0:
        return None
    return rows[0]


def parity_predicate(layout: PayloadLayout = DEFAULT_LAYOUT) -> Predicate:
    """True iff oracle and device BOTH replay the slice cleanly and
    their payload rows differ — the real divergence-chasing predicate
    (`fuzz shrink` uses it on reported failures)."""

    def failing(batches: List[HistoryBatch]) -> bool:
        if not batches:
            return False
        try:
            expected = oracle_final_row(batches, layout)
        except Exception:
            return False  # oracle rejects the slice: different failure
        got = _device_row(batches, layout)
        return got is not None and not (got == expected).all()

    return failing


def poisoned_parity_predicate(poison_signal: str,
                              layout: PayloadLayout = DEFAULT_LAYOUT
                              ) -> Predicate:
    """The injected-divergence harness: behaves exactly like
    `parity_predicate`, except the device row is bit-flipped whenever
    the slice still contains a signal named `poison_signal` — a
    deterministic stand-in for "the kernel mishandles this one event",
    letting shrinker tests run the REAL reduction loop against a known
    minimal witness (the batch carrying the poisoned signal)."""
    base_layout = layout

    def failing(batches: List[HistoryBatch]) -> bool:
        if not batches:
            return False
        poisoned = any(
            e.get("signal_name") == poison_signal
            for b in batches
            for group in (b.events, b.new_run_events or ())
            for e in group)
        if not poisoned:
            return False
        try:
            expected = oracle_final_row(batches, base_layout)
        except Exception:
            return False
        got = _device_row(batches, base_layout)
        if got is None:
            return False
        got = got.copy()
        got[0] ^= 1  # the injected device-side defect
        return not (got == expected).all()

    return failing


def inject_poison_signal(seed: int, workflow_index: int,
                         target_events: int = 100,
                         profile: str = "mixed") -> Optional[str]:
    """Pick the LAST generated signal name of a history as the poison
    (deterministic per seed); None when the walk emitted no signals."""
    batches = generate_fuzz_history(seed, workflow_index, target_events,
                                    profile)
    from ..core.enums import EventType
    names = [e.get("signal_name")
             for b in batches for e in b.events
             if e.event_type == EventType.WorkflowExecutionSignaled
             and e.get("signal_name")]
    return names[-1] if names else None
