"""Fleet chaos campaigns: seeded fault schedules against a REAL cluster.

`gen/interleave.py` proves the single-process serving tier converges to
byte-identical state under crashpoint kills and injected faults. This
module is the same discipline one deployment tier up: a seeded CAMPAIGN
drives a live workload schedule against a multi-host wire cluster
(`rpc/cluster.launch` — real OS processes, real sockets) while a
campaign planner fires FLEET-level faults between workload ops:

- real SIGKILL of service-host processes mid-traffic (survivors steal
  the dead host's shards after the heartbeat TTL);
- real SIGKILL of the store-server process, its WAL fsck'd clean and
  the store relaunched on the same port (boot recovery replays the WAL
  under the hosts' feet — `rpc/storeserver.serve`);
- ASYMMETRIC network partitions (rpc/chaos.PartitionTable through the
  `admin_partition` wire op): host A → store severed while store → A
  and B → store keep flowing, healed on schedule. A host partitioned
  from the store stops heartbeating, so the partition doubles as a
  membership drop — and the heal as a rejoin + shard steal-back;
- membership FLAPS (SIGSTOP until the TTL evicts the host from every
  survivor's ring, then SIGCONT): the restored host re-acquires its
  stolen shards through the range fence, witnessed by the
  `controller/fenced-evictions` counter.

The acceptance oracle is the chaos-soak bar applied fleet-wide: final
per-workflow payload checksums byte-identical to a fault-free run of
the SAME seed, `wal fsck` clean on every killed store's recovered WAL,
zero divergence on every `tpu.serving`/`tpu.migration`/replication
parity counter across all hosts, and a closing `verify_all` over the
remote store (both regions when `regions=2`). What makes byte-identity
achievable under real kills: every workload op is retried to
CONVERGENCE with deterministic request ids (signal dedup, benign
already-started), and decisions dispatch from STORE truth
(`_complete_once`, the `gen/interleave._direct_decision` seat) rather
than from matching's lossy in-memory queues — so an op's history effect
is a function of replicated state, never of which process died when.
Storm profiles (`profile="storm"`: reset/cron/retry churn) gate on
self-consistency only (fsck + parity + verify_all): their terminal
state is legitimately timing-dependent.

On failure, `gen/shrink.py`'s ddmin generalizes to campaign schedules:
`shrink_campaign` reduces the combined workload+fault op list to a
1-minimal reproducer replayable from `(seed, kept_indices)` alone
(`CampaignShrinkReport.reproduce`), and the scenario dumps every live
process's flight-recorder ring beside the failing doc.
"""
from __future__ import annotations

import json
import os
import random
import tempfile
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence

from ..core.checksum import DEFAULT_LAYOUT, crc32_of_row, payload_row
from ..core.enums import (
    EMPTY_EVENT_ID,
    DecisionType,
    EventType,
    WorkflowState,
)
from ..core.events import RetryPolicy
from ..engine import walcheck
from ..engine.controller import ShardNotOwnedError
from ..engine.faults import TransientStoreError
from ..engine.history_engine import Decision, InvalidRequestError, TaskToken
from ..engine.persistence import (
    EntityNotExistsError,
    ShardOwnershipLostError,
    WorkflowAlreadyStartedError,
)
from ..engine.tpu_engine import TPUReplayEngine
from ..rpc.chaos import ChaosError
from ..rpc.client import RemoteCluster, RemoteStores
from ..rpc.cluster import Cluster, launch, launch_group
from ..rpc.wire import call as wire_call
from ..utils import compile_cache
from ..utils.circuitbreaker import CircuitOpenError, ServiceBusy

DOMAIN = "fleet-chaos"
TL = "fleet-tl"
WF_PREFIX = "fcwf"

#: workload verbs a campaign schedule may carry
WORKLOAD_KINDS = ("start", "signal", "complete", "sws", "reset",
                  "terminate")
#: fleet-fault verbs the planner interleaves into the schedule
FAULT_KINDS = ("kill_host", "kill_store", "partition", "heal_partition",
               "flap_begin", "flap_end")

PROFILES = ("steady", "storm")

TRAJECTORY_SCHEMA = "cadence-tpu/fleetchaos-trajectory/v1"
_TRAJ_PATTERN = "CHAOS_r{:02d}.json"


@dataclass(frozen=True)
class CampaignOp:
    """One schedule slot: a workload verb or a fleet fault. Host targets
    are INDICES into the sorted host-name list (index 0 — the driver's
    stable frontend — is never a fault victim), so the same campaign
    replays against any naming scheme (plain and region-prefixed)."""

    kind: str
    wf: int = -1        # workload target (WF_PREFIX-<wf>)
    seq: int = -1       # per-workflow sequence (signal/sws naming)
    host: int = -1      # fault victim index (1-based into sorted hosts)
    peer: str = ""      # partition far end: "store" or "host:<i>"
    flag: str = ""      # start/complete modifier: "cron"/"retry"/"fail"

    def as_dict(self) -> dict:
        out = {"kind": self.kind}
        for key in ("wf", "seq", "host"):
            if getattr(self, key) >= 0:
                out[key] = getattr(self, key)
        for key in ("peer", "flag"):
            if getattr(self, key):
                out[key] = getattr(self, key)
        return out


def build_campaign(seed: int, num_workflows: int = 6,
                   signals_per_wf: int = 2, num_hosts: int = 3,
                   kills: int = 1, store_kills: int = 0,
                   partitions: int = 1, flaps: int = 0,
                   profile: str = "steady") -> List[CampaignOp]:
    """The seeded campaign grammar: per-workflow op chains (start →
    signals → store-truth complete, plus reset/cron/retry churn in the
    storm profile) randomly merged into one schedule, then fleet faults
    inserted at seeded positions — flaps in the first half, partitions
    cut in the middle third and healed before the kill band, store
    kills mid-schedule, host kills in the final third (so every fault
    fires MID-traffic and a partitioned host is healed before it can be
    killed). Deterministic: same arguments ⇒ same op list, which is
    what lets a `CampaignShrinkReport` replay from coordinates alone."""
    if profile not in PROFILES:
        raise ValueError(f"unknown campaign profile {profile!r}")
    if num_hosts < 2 and (kills or partitions or flaps):
        raise ValueError("fleet faults need at least 2 hosts "
                         "(host index 0 is the protected coordinator)")
    kills = min(kills, num_hosts - 1)
    rng = random.Random(f"fleet:{seed}:{profile}:{num_workflows}:"
                        f"{signals_per_wf}:{num_hosts}")

    chains: List[List[CampaignOp]] = []
    for w in range(num_workflows):
        flag = ""
        if profile == "storm":
            flag = rng.choice(("", "", "", "cron", "retry"))
        chain = [CampaignOp("start", wf=w, flag=flag)]
        chain += [CampaignOp("signal", wf=w, seq=s)
                  for s in range(signals_per_wf)]
        chain.append(CampaignOp(
            "complete", wf=w, flag=("fail" if flag == "retry" else flag)))
        if profile == "storm" and flag == "":
            extra = rng.choice(("reset", "terminate", "sws", ""))
            if extra == "sws":
                chain.append(CampaignOp("sws", wf=w, seq=signals_per_wf))
            elif extra:
                chain.append(CampaignOp(extra, wf=w))
        chains.append(chain)

    ops: List[CampaignOp] = []
    live = [c for c in chains if c]
    while live:
        chain = rng.choice(live)
        ops.append(chain.pop(0))
        live = [c for c in chains if c]

    n = len(ops)
    victims = list(range(1, num_hosts))
    kill_victims = victims[-kills:] if kills else []
    flap_victims = [v for v in victims if v not in kill_victims]
    if flaps and not flap_victims:
        raise ValueError("flaps need a non-coordinator host that "
                         "survives every kill")

    inserts = []  # (workload index, tiebreak, fault op)
    for f in range(flaps):
        victim = flap_victims[f % len(flap_victims)]
        begin = rng.randrange(max(1, n // 6), max(2, n // 3))
        end = rng.randrange(max(begin + 1, n // 3), max(begin + 2, n // 2))
        inserts.append((begin, 0, CampaignOp("flap_begin", host=victim)))
        inserts.append((end, 1, CampaignOp("flap_end", host=victim)))
    for p in range(partitions):
        src = victims[p % len(victims)]
        peers = ["store"] + [f"host:{i}" for i in range(num_hosts)
                             if i != src]
        peer = rng.choice(peers)
        cut = rng.randrange(max(1, n // 3), max(2, n // 2))
        heal = rng.randrange(max(cut + 1, n // 2),
                             max(cut + 2, 2 * n // 3))
        inserts.append((cut, 2, CampaignOp("partition", host=src,
                                           peer=peer)))
        inserts.append((heal, 3, CampaignOp("heal_partition", host=src,
                                            peer=peer)))
    for _ in range(store_kills):
        inserts.append((rng.randrange(max(1, n // 2), max(2, 2 * n // 3)),
                        4, CampaignOp("kill_store")))
    for victim in kill_victims:
        inserts.append((rng.randrange(max(1, 2 * n // 3), max(2, n)),
                        5, CampaignOp("kill_host", host=victim)))

    inserts.sort(key=lambda t: (t[0], t[1]))
    out: List[CampaignOp] = []
    cursor = 0
    for idx, op in enumerate(ops):
        while cursor < len(inserts) and inserts[cursor][0] <= idx:
            out.append(inserts[cursor][2])
            cursor += 1
        out.append(op)
    out.extend(item[2] for item in inserts[cursor:])
    return out


class CampaignDriver:
    """Executes one campaign against a live wire cluster. Workload ops
    retry to convergence through the full fault surface (partitions are
    ChaosError, kills are connection errors, steals are ownership
    errors) with deterministic request ids; fault ops drive the fleet
    (kill/relaunch/sever/heal/flap) and record their witnesses."""

    BENIGN = (WorkflowAlreadyStartedError, InvalidRequestError,
              EntityNotExistsError)
    RETRYABLE = (ChaosError, ConnectionError, OSError, TimeoutError,
                 ServiceBusy, CircuitOpenError, TransientStoreError,
                 ShardOwnershipLostError, ShardNotOwnedError)

    def __init__(self, cluster: Cluster, seed: int, faults: bool = True,
                 max_attempts: int = 80, converge_s: float = 90.0) -> None:
        self.cluster = cluster
        self.seed = seed
        self.faults = faults
        self.max_attempts = max_attempts
        self.converge_s = converge_s
        self.stores = RemoteStores(("127.0.0.1", cluster.store_port))
        self.remote = RemoteCluster(("127.0.0.1", cluster.store_port))
        self.started: List[str] = []
        self.retries = 0
        self.kills = 0
        self.store_kills = 0
        self.partitions_cut = 0
        self.partitions_healed = 0
        self.flaps = 0
        self.skipped: List[str] = []
        self.fsck_reports: List[dict] = []
        self._paused: set = set()
        self._responded: set = set()
        self._failed: set = set()
        self._domain_id: Optional[str] = None

    # -- plumbing ----------------------------------------------------------

    def _host_name(self, index: int) -> str:
        return sorted(self.cluster.hosts)[index]

    def _live_hosts(self) -> List[str]:
        return [name for name in sorted(self.cluster.hosts)
                if self.cluster.procs[name].poll() is None
                and name not in self._paused]

    def _frontend(self):
        live = self._live_hosts()
        if not live:
            raise RuntimeError("campaign has no live host left")
        return self.cluster.frontend(live[0])

    def _domain(self) -> str:
        if self._domain_id is None:
            self._domain_id = self._retrying(
                lambda: self.stores.domain.by_name(DOMAIN).domain_id)
        return self._domain_id

    def _retrying(self, op):
        """Run `op()` to convergence. `op` must be self-contained
        (re-resolves all state per attempt) — the retry-safety contract
        that keeps an op's history effect deterministic under kills."""
        last: Optional[BaseException] = None
        for attempt in range(self.max_attempts):
            if attempt:
                self.retries += 1
                time.sleep(min(1.0, 0.1 * attempt))
            try:
                return op()
            except self.BENIGN:
                return None
            except self.RETRYABLE as exc:
                last = exc
        raise RuntimeError(
            f"campaign op did not converge after {self.max_attempts} "
            f"attempts (last: {type(last).__name__}: {last})")

    def register(self) -> None:
        self._retrying(lambda: self._frontend().register_domain(DOMAIN))

    # -- workload ----------------------------------------------------------

    @staticmethod
    def wf_name(index: int) -> str:
        return f"{WF_PREFIX}-{index}"

    def execute(self, op: CampaignOp) -> None:
        if op.kind in FAULT_KINDS:
            self._exec_fault(op)
        elif op.kind == "start":
            self._start(op)
        elif op.kind == "signal":
            self._signal(op)
        elif op.kind == "complete":
            self._complete(op)
        elif op.kind == "sws":
            self._sws(op)
        elif op.kind == "reset":
            self._reset(op)
        elif op.kind == "terminate":
            self._terminate(op)
        else:
            raise ValueError(f"unknown campaign op {op.kind!r}")

    def _start(self, op: CampaignOp) -> None:
        wf = self.wf_name(op.wf)
        retry = (RetryPolicy(initial_interval_seconds=1,
                             backoff_coefficient=2.0,
                             maximum_interval_seconds=4,
                             maximum_attempts=2)
                 if op.flag == "retry" else None)
        cron = "* * * * *" if op.flag == "cron" else ""
        # 3600s timeouts: no decision/execution timer may fire
        # asynchronously mid-campaign — a timeout event appended by a
        # host's timer pump (not by a driver op) would shift history
        # bytes between the fault-free and chaotic runs
        self._retrying(lambda: self._frontend().start_workflow_execution(
            DOMAIN, wf, "fleet-type", TL, execution_timeout=3600,
            decision_timeout=3600, cron_schedule=cron, retry_policy=retry))
        if wf not in self.started:
            self.started.append(wf)

    def _signal(self, op: CampaignOp) -> None:
        wf = self.wf_name(op.wf)
        self._retrying(
            lambda: self._frontend().signal_workflow_execution(
                DOMAIN, wf, f"sig-{op.seq}",
                request_id=f"fc:{self.seed}:{wf}:{op.seq}"))

    def _sws(self, op: CampaignOp) -> None:
        wf = self.wf_name(op.wf)
        self._retrying(
            lambda: self._frontend().signal_with_start_workflow_execution(
                DOMAIN, wf, f"sws-{op.seq}", "fleet-type", TL,
                execution_timeout=3600, decision_timeout=3600,
                request_id=f"fc-sws:{self.seed}:{wf}:{op.seq}"))
        if wf not in self.started:
            self.started.append(wf)

    def _terminate(self, op: CampaignOp) -> None:
        wf = self.wf_name(op.wf)
        self._retrying(
            lambda: self._frontend().terminate_workflow_execution(
                DOMAIN, wf, reason="fleet-terminate-storm"))

    def _complete(self, op: CampaignOp) -> None:
        """Drive the workflow's current run to completion from STORE
        truth, retried until the close is observable — the convergence
        loop that absorbs the started-but-reply-lost ambiguity a real
        SIGKILL creates (the decision re-dispatches from state)."""
        wf = self.wf_name(op.wf)
        deadline = time.monotonic() + self.converge_s
        while True:
            if self._retrying(lambda: self._complete_once(wf, op.flag)):
                return
            if time.monotonic() > deadline:
                raise RuntimeError(f"{wf} never completed in "
                                   f"{self.converge_s:.0f}s")
            time.sleep(0.2)

    def _complete_once(self, wf: str, flag: str) -> bool:
        domain_id = self._domain()
        if flag == "cron" and wf in self._responded:
            return True  # the cron respawn stays open by design
        try:
            run = self.stores.execution.get_current_run_id(domain_id, wf)
            ms = self.stores.execution.get_workflow(domain_id, wf, run)
        except EntityNotExistsError:
            return True  # shrunk slice without the start op: nothing to do
        info = ms.execution_info
        if info.state == WorkflowState.Completed:
            return True
        if info.decision_schedule_id == EMPTY_EVENT_ID:
            return False  # retry-backoff timer not fired yet
        engine = self.remote.engine(wf)
        if info.decision_started_id > 0:
            token = TaskToken(domain_id=domain_id, workflow_id=wf,
                              run_id=run,
                              schedule_id=info.decision_schedule_id,
                              started_id=info.decision_started_id,
                              attempt=info.decision_attempt)
        else:
            token = engine.record_decision_task_started(
                domain_id, wf, run, info.decision_schedule_id,
                request_id=f"fc-dts:{wf}:{run}:"
                           f"{info.decision_schedule_id}")
        decisions = [Decision(DecisionType.CompleteWorkflowExecution,
                              {"result": b"fleet-done"})]
        if flag == "fail" and wf not in self._failed:
            # the retry-storm arm: fail the FIRST attempt so the
            # workflow retry policy spawns a backoff run
            self._failed.add(wf)
            decisions = [Decision(DecisionType.FailWorkflowExecution,
                                  {"reason": "fleet-retry-storm"})]
        self._frontend().respond_decision_task_completed(token, decisions)
        self._responded.add(wf)
        return False  # loop re-reads state (retry/cron runs continue)

    def _reset(self, op: CampaignOp) -> None:
        """Storm reset: rewind a (typically completed) run to its only
        decision boundary — the new run stays open with a fresh pending
        decision, which the self-consistency gates must absorb."""
        wf = self.wf_name(op.wf)

        def body():
            domain_id = self._domain()
            run = self.stores.execution.get_current_run_id(domain_id, wf)
            events = self.stores.history.read_events(domain_id, wf, run)
            finish = next((e.id for e in events
                           if e.event_type == EventType.DecisionTaskCompleted),
                          None)
            if finish is None:
                return None
            self._frontend().reset_workflow_execution(
                DOMAIN, wf, decision_finish_event_id=finish,
                reason="fleet-reset-storm")

        self._retrying(body)

    # -- fleet faults ------------------------------------------------------

    def _peer_name(self, peer: str) -> str:
        if peer == "store":
            return "store"
        return self._host_name(int(peer.split(":", 1)[1]))

    def _exec_fault(self, op: CampaignOp) -> None:
        if not self.faults:
            return
        if op.kind == "kill_host":
            name = self._host_name(op.host)
            if self.cluster.procs[name].poll() is not None:
                self.skipped.append(f"kill_host:{name}:already-dead")
                return
            self.cluster.kill_host(name)
            self.kills += 1
        elif op.kind == "kill_store":
            self.cluster.kill_store()
            report = walcheck.fsck(self.cluster.wal)
            self.fsck_reports.append({
                "at": f"store-kill-{self.store_kills + 1}",
                "ok": report.ok,
                "findings": [f.as_dict() for f in report.findings]})
            self.cluster.relaunch_store()
            self.store_kills += 1
        elif op.kind == "partition":
            name = self._host_name(op.host)
            if self.cluster.procs[name].poll() is not None:
                self.skipped.append(f"partition:{name}:dead")
                return
            self.cluster.sever(name, self._peer_name(op.peer))
            self.partitions_cut += 1
        elif op.kind == "heal_partition":
            name = self._host_name(op.host)
            if self.cluster.procs[name].poll() is not None:
                self.skipped.append(f"heal:{name}:dead")
                return
            self.cluster.heal(name, self._peer_name(op.peer))
            self.partitions_healed += 1
        elif op.kind == "flap_begin":
            name = self._host_name(op.host)
            if self.cluster.procs[name].poll() is not None:
                self.skipped.append(f"flap:{name}:dead")
                return
            self.cluster.pause_host(name)
            self._paused.add(name)
            self._await_ring(lambda members: name not in members,
                             f"{name} never dropped from the ring")
            self.flaps += 1
        elif op.kind == "flap_end":
            name = self._host_name(op.host)
            if name not in self._paused:
                self.skipped.append(f"flap_end:{name}:not-paused")
                return
            self.cluster.resume_host(name)
            self._paused.discard(name)
            self._await_ring(lambda members: name in members,
                             f"{name} never rejoined the ring")

    def _await_ring(self, pred, what: str, timeout: float = 30.0) -> None:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            members = self._ring_view()
            if members is not None and pred(members):
                return
            time.sleep(0.1)
        raise TimeoutError(f"ring: {what}")

    def _ring_view(self) -> Optional[set]:
        for name in self._live_hosts():
            try:
                return set(self.cluster.ping(name)[3])
            except Exception:
                continue
        return None

    def summary(self) -> dict:
        return {"kills": self.kills, "store_kills": self.store_kills,
                "partitions_cut": self.partitions_cut,
                "partitions_healed": self.partitions_healed,
                "flaps": self.flaps, "retries": self.retries,
                "skipped": list(self.skipped),
                "workflows_started": list(self.started)}


# ---------------------------------------------------------------------------
# Fleet gates
# ---------------------------------------------------------------------------


def collect_checksums(stores, workflows: Sequence[str],
                      attempts: int = 40) -> Dict[str, dict]:
    """Per-workflow `(payload crc, close status)` from the authoritative
    store — run-ids excluded (`payload_row`), so a fault-free and a
    chaotic run of the same seed must agree byte for byte. Reads retry:
    the store may still be redialing right after a relaunch."""
    out: Dict[str, dict] = {}
    domain_id = None
    for attempt in range(attempts):
        try:
            domain_id = stores.domain.by_name(DOMAIN).domain_id
            break
        except (ConnectionError, OSError, TimeoutError):
            time.sleep(0.25)
    for wf in workflows:
        for attempt in range(attempts):
            try:
                run = stores.execution.get_current_run_id(domain_id, wf)
                ms = stores.execution.get_workflow(domain_id, wf, run)
                out[wf] = {
                    "crc": int(crc32_of_row(payload_row(ms))),
                    "close_status": int(ms.execution_info.close_status),
                }
                break
            except (ConnectionError, OSError, TimeoutError):
                time.sleep(0.25)
            except EntityNotExistsError:
                out[wf] = {"crc": None, "close_status": None}
                break
    return out


#: (scope, counter) pairs whose fleet-wide sum must be ZERO at campaign
#: close — the parity oracle over every device-serving tier
PARITY_COUNTERS = (("tpu.serving", "parity-divergence"),
                   ("tpu.migration", "parity-divergence"),
                   ("replication.task-processor",
                    "device-parity-divergence"))

#: (scope, counter) membership/fence witnesses summed for the doc
WITNESS_COUNTERS = (("membership", "ring-drops"),
                    ("membership", "ring-joins"),
                    ("controller", "fenced-evictions"),
                    ("rpc.partition", "blocked-sends"),
                    ("replication.task-processor", "backpressure-shed"))


def sum_fleet_counters(cluster: Cluster) -> dict:
    """Sum the parity + witness counters over every LIVE host's metrics
    registry (the admin_metrics wire op — each host's own registry, the
    one its /metrics scrape serves)."""
    sums: Dict[str, int] = {}
    hosts_seen = 0
    for name in sorted(cluster.hosts):
        if cluster.procs[name].poll() is not None:
            continue
        try:
            snap = wire_call(("127.0.0.1", cluster.hosts[name]),
                             ("admin_metrics",), timeout=10)["snapshot"]
        except Exception:
            continue
        hosts_seen += 1
        for scope, counter in PARITY_COUNTERS + WITNESS_COUNTERS:
            key = f"{scope}/{counter}"
            sums[key] = sums.get(key, 0) + int(
                snap.get(scope, {}).get(counter, 0))
    parity = sum(sums.get(f"{scope}/{counter}", 0)
                 for scope, counter in PARITY_COUNTERS)
    return {"hosts_seen": hosts_seen, "parity_divergence": parity,
            "counters": sums}


def verify_fleet(cluster: Cluster) -> dict:
    """Closing oracle↔device verification over the REMOTE store
    (loadgen/scenarios discipline, including the live-cluster torn-read
    re-verify loop: a REAL divergence survives every re-read)."""
    compile_cache.enable()
    stores = RemoteStores(("127.0.0.1", cluster.store_port))
    engine = TPUReplayEngine(stores, DEFAULT_LAYOUT)
    result = engine.verify_all()
    divergent = list(result.divergent)
    first_pass = len(divergent)
    for _ in range(3):
        if not divergent:
            break
        time.sleep(1.0)
        divergent = list(engine.verify_all(divergent).divergent)
    return {"total": result.total,
            "verified_on_device": result.verified_on_device,
            "divergent": len(divergent),
            "divergent_first_pass": first_pass,
            "ok": not divergent}


def collect_flightrec(cluster: Cluster, last_n: int = 120) -> dict:
    """Every live process's flight-recorder ring (admin_flightrec wire
    op) — the forensic payload a failing campaign dumps beside its doc."""
    rings = {}
    for name in sorted(cluster.hosts):
        if cluster.procs[name].poll() is not None:
            continue
        try:
            rings[name] = cluster.admin(name, "admin_flightrec", last_n,
                                        timeout=10)
        except Exception as exc:
            rings[name] = {"error": f"{type(exc).__name__}: {exc}"}
    return rings


# ---------------------------------------------------------------------------
# Campaign runs and the scenario
# ---------------------------------------------------------------------------


def run_campaign(campaign: Sequence[CampaignOp], *, seed: int,
                 num_hosts: int = 3, num_shards: int = 8,
                 profile: str = "steady", faults: bool = True,
                 regions: int = 1, env_extra=None) -> dict:
    """Execute one campaign op list against a FRESH cluster (or 2-region
    group) and collect every gate. `faults=False` replays the identical
    workload with the fault ops skipped — the baseline the byte-identity
    oracle compares against."""
    tmp = tempfile.mkdtemp(prefix="fleetchaos-")
    env = {"CADENCE_TPU_SERVING": "1"}
    env.update(env_extra or {})
    group = None
    if regions == 2:
        group = launch_group(num_hosts=num_hosts, num_shards=num_shards,
                             wal_dir=tmp, env_extra=env)
        cluster = group.clusters["primary"]
    else:
        cluster = launch(num_hosts=num_hosts, num_shards=num_shards,
                         wal=os.path.join(tmp, "store.wal"),
                         env_extra=env)
    started = time.monotonic()
    doc: dict = {"profile": profile, "faults": faults, "regions": regions}
    try:
        driver = CampaignDriver(cluster, seed, faults=faults)
        if group is not None:
            group.register_global_domain(DOMAIN)
        else:
            driver.register()
        for op in campaign:
            driver.execute(op)
        cluster.heal_all_partitions()
        doc.update(driver.summary())
        doc["checksums"] = collect_checksums(driver.stores, driver.started)
        doc["counters"] = sum_fleet_counters(cluster)
        doc["verify"] = verify_fleet(cluster)
        doc["fsck_on_kill"] = driver.fsck_reports
        if group is not None:
            group.replicate()
            group.replicate_domains()
            standby = group.clusters["standby"]
            doc["standby_checksums"] = collect_checksums(
                RemoteStores(("127.0.0.1", standby.store_port)),
                driver.started)
            doc["verify_standby"] = verify_fleet(standby)
        gates_failed = (
            doc["verify"]["divergent"] > 0
            or doc["counters"]["parity_divergence"] > 0
            or any(not r["ok"] for r in driver.fsck_reports))
        if gates_failed:
            doc["flightrec"] = collect_flightrec(cluster)
    except Exception as exc:
        doc["error"] = f"{type(exc).__name__}: {exc}"
        try:
            doc["flightrec"] = collect_flightrec(cluster)
        except Exception:
            pass
        raise
    finally:
        doc["duration_s"] = round(time.monotonic() - started, 3)
        if group is not None:
            group.stop()
        else:
            cluster.stop()
        # post-mortem fsck of every region's WAL, now that no process
        # is appending — the recovered-WAL-is-clean half of the oracle
        walpaths = ([c.wal for c in group.clusters.values()]
                    if group is not None else [cluster.wal])
        doc["fsck_final"] = []
        for path in walpaths:
            if path and os.path.exists(path):
                report = walcheck.fsck(path)
                doc["fsck_final"].append({
                    "wal": os.path.basename(path), "ok": report.ok,
                    "findings": [f.as_dict() for f in report.findings]})
    return doc


def cluster_campaign_scenario(seed: int = 20260806, num_hosts: int = 3,
                              num_shards: int = 8, num_workflows: int = 6,
                              signals_per_wf: int = 2, kills: int = 1,
                              store_kills: int = 1, partitions: int = 1,
                              flaps: int = 1, profile: str = "steady",
                              regions: int = 1,
                              shrink_on_failure: bool = False,
                              env_extra=None) -> dict:
    """The fleet chaos acceptance scenario: run the seeded campaign
    fault-free (baseline), then with every fault live, and gate on

    - byte-identical per-workflow checksums (steady profile only —
      storm terminal state is timing-dependent by design),
    - fsck-clean recovery of every killed store WAL (and the final
      WALs post-shutdown),
    - zero fleet-wide parity divergence,
    - a clean closing verify_all (both regions when regions=2).

    On failure with `shrink_on_failure`, ddmin reduces the campaign to
    a 1-minimal op list (EXPENSIVE: every predicate call replays a
    baseline+chaos pair) and embeds the reproducible report."""
    campaign = build_campaign(seed, num_workflows=num_workflows,
                              signals_per_wf=signals_per_wf,
                              num_hosts=num_hosts, kills=kills,
                              store_kills=store_kills,
                              partitions=partitions, flaps=flaps,
                              profile=profile)
    started = time.monotonic()
    baseline = None
    if profile == "steady":
        baseline = run_campaign(campaign, seed=seed, num_hosts=num_hosts,
                                num_shards=num_shards, profile=profile,
                                faults=False, regions=regions,
                                env_extra=env_extra)
    chaotic = run_campaign(campaign, seed=seed, num_hosts=num_hosts,
                           num_shards=num_shards, profile=profile,
                           faults=True, regions=regions,
                           env_extra=env_extra)

    identical = True
    if baseline is not None:
        identical = baseline["checksums"] == chaotic["checksums"]
        if regions == 2:
            identical = (identical and chaotic.get("standby_checksums")
                         == chaotic["checksums"])
    fsck_ok = (all(r["ok"] for r in chaotic["fsck_on_kill"])
               and all(r["ok"] for r in chaotic["fsck_final"]))
    parity_ok = chaotic["counters"]["parity_divergence"] == 0
    verify_ok = chaotic["verify"]["ok"] and (
        regions != 2 or chaotic["verify_standby"]["ok"])
    ok = bool(identical and fsck_ok and parity_ok and verify_ok)

    doc = {
        "scenario": "cluster_campaign", "seed": seed, "profile": profile,
        "num_hosts": num_hosts, "num_shards": num_shards,
        "regions": regions, "campaign_ops": len(campaign),
        "workflows": num_workflows, "signals_per_wf": signals_per_wf,
        "planned": {"kills": kills, "store_kills": store_kills,
                    "partitions": partitions, "flaps": flaps},
        "executed": {k: chaotic[k] for k in
                     ("kills", "store_kills", "partitions_cut",
                      "partitions_healed", "flaps", "retries", "skipped")},
        "checksums_identical": identical,
        "fsck_clean": fsck_ok,
        "parity_divergence": chaotic["counters"]["parity_divergence"],
        "witnesses": chaotic["counters"]["counters"],
        "verify": chaotic["verify"],
        "baseline": baseline, "chaotic": chaotic,
        "duration_s": round(time.monotonic() - started, 3),
        "ok": ok,
    }
    if regions == 2:
        doc["verify_standby"] = chaotic["verify_standby"]
    if not ok and shrink_on_failure:
        predicate = live_campaign_predicate(
            seed=seed, num_hosts=num_hosts, num_shards=num_shards,
            profile=profile, regions=regions, env_extra=env_extra)
        try:
            report = shrink_campaign(
                seed, predicate, num_workflows=num_workflows,
                signals_per_wf=signals_per_wf, num_hosts=num_hosts,
                kills=kills, store_kills=store_kills,
                partitions=partitions, flaps=flaps, profile=profile,
                max_calls=24)
            doc["shrink"] = report.summary()
        except Exception as exc:
            doc["shrink"] = {"error": f"{type(exc).__name__}: {exc}"}
    return doc


# ---------------------------------------------------------------------------
# Campaign shrinking (gen/shrink.py's ddmin over the op axis)
# ---------------------------------------------------------------------------


@dataclass
class CampaignShrinkReport:
    """One campaign shrink outcome, reproducible from the generator
    coordinates plus the kept op indices — nothing else."""

    seed: int
    profile: str
    num_workflows: int
    signals_per_wf: int
    num_hosts: int
    kills: int
    store_kills: int
    partitions: int
    flaps: int
    kept_indices: List[int] = field(default_factory=list)
    original_ops: int = 0
    shrunk_ops: int = 0
    predicate_calls: int = 0
    kept_kinds: List[str] = field(default_factory=list)

    def reproduce(self) -> List[CampaignOp]:
        """Regenerate the minimal failing schedule from the seed alone."""
        full = build_campaign(self.seed, num_workflows=self.num_workflows,
                              signals_per_wf=self.signals_per_wf,
                              num_hosts=self.num_hosts, kills=self.kills,
                              store_kills=self.store_kills,
                              partitions=self.partitions, flaps=self.flaps,
                              profile=self.profile)
        return [full[i] for i in self.kept_indices]

    def summary(self) -> dict:
        return {"seed": self.seed, "profile": self.profile,
                "num_workflows": self.num_workflows,
                "signals_per_wf": self.signals_per_wf,
                "num_hosts": self.num_hosts, "kills": self.kills,
                "store_kills": self.store_kills,
                "partitions": self.partitions, "flaps": self.flaps,
                "kept_indices": list(self.kept_indices),
                "ops": f"{self.original_ops} -> {self.shrunk_ops}",
                "predicate_calls": self.predicate_calls,
                "kept_kinds": list(self.kept_kinds)}


def shrink_campaign(seed: int,
                    failing: Callable[[List[CampaignOp]], bool], *,
                    num_workflows: int = 6, signals_per_wf: int = 2,
                    num_hosts: int = 3, kills: int = 1,
                    store_kills: int = 0, partitions: int = 1,
                    flaps: int = 0, profile: str = "steady",
                    max_calls: int = 400) -> CampaignShrinkReport:
    """ddmin over the campaign's combined workload+fault op list —
    `gen/shrink.shrink_batches` is generic over any sequence, and a
    campaign slice is always replayable (the driver treats ops against
    never-started workflows as benign). The report's coordinates alone
    reproduce the 1-minimal schedule."""
    from .shrink import shrink_batches

    campaign = build_campaign(seed, num_workflows=num_workflows,
                              signals_per_wf=signals_per_wf,
                              num_hosts=num_hosts, kills=kills,
                              store_kills=store_kills,
                              partitions=partitions, flaps=flaps,
                              profile=profile)
    kept, calls = shrink_batches(list(campaign), failing,
                                 max_calls=max_calls)
    minimal = [campaign[i] for i in kept]
    return CampaignShrinkReport(
        seed=seed, profile=profile, num_workflows=num_workflows,
        signals_per_wf=signals_per_wf, num_hosts=num_hosts, kills=kills,
        store_kills=store_kills, partitions=partitions, flaps=flaps,
        kept_indices=list(kept), original_ops=len(campaign),
        shrunk_ops=len(minimal), predicate_calls=calls,
        kept_kinds=sorted({op.kind for op in minimal}))


def injected_regression_predicate(
        poison_wf: int) -> Callable[[List[CampaignOp]], bool]:
    """The campaign twin of `shrink.poisoned_parity_predicate`: a
    deterministic stand-in for "a host kill corrupts the next signal to
    workflow `poison_wf`" — failing iff the slice contains a kill_host
    op with a signal to `poison_wf` somewhere AFTER it. The 1-minimal
    witness is exactly {one kill, one later signal}, which is what the
    shrinker tests pin without ever launching a cluster."""

    def failing(ops: Sequence[CampaignOp]) -> bool:
        seen_kill = False
        for op in ops:
            if op.kind == "kill_host":
                seen_kill = True
            elif (seen_kill and op.kind == "signal"
                  and op.wf == poison_wf):
                return True
        return False

    return failing


def pick_poison_wf(campaign: Sequence[CampaignOp]) -> Optional[int]:
    """The first workflow with a signal after the first kill — the
    deterministic poison target `injected_regression_predicate` needs
    (None when the schedule has no such pair)."""
    seen_kill = False
    for op in campaign:
        if op.kind == "kill_host":
            seen_kill = True
        elif seen_kill and op.kind == "signal":
            return op.wf
    return None


def live_campaign_predicate(*, seed: int, num_hosts: int,
                            num_shards: int = 8, profile: str = "steady",
                            regions: int = 1, env_extra=None
                            ) -> Callable[[List[CampaignOp]], bool]:
    """The REAL failure predicate: replay the op slice against a fresh
    baseline+chaos cluster pair and report whether the gates fail.
    Each call costs two cluster launches — budget `max_calls` tightly.
    A slice that ERRORS (rather than diverging) is NOT the failure
    being chased (the shrink.py discipline)."""

    def failing(ops: List[CampaignOp]) -> bool:
        if not ops:
            return False
        try:
            base = run_campaign(ops, seed=seed, num_hosts=num_hosts,
                                num_shards=num_shards, profile=profile,
                                faults=False, regions=regions,
                                env_extra=env_extra)
            chaos = run_campaign(ops, seed=seed, num_hosts=num_hosts,
                                 num_shards=num_shards, profile=profile,
                                 faults=True, regions=regions,
                                 env_extra=env_extra)
        except Exception:
            return False
        identical = base["checksums"] == chaos["checksums"]
        fsck_ok = (all(r["ok"] for r in chaos["fsck_on_kill"])
                   and all(r["ok"] for r in chaos["fsck_final"]))
        return not (identical and fsck_ok
                    and chaos["counters"]["parity_divergence"] == 0
                    and chaos["verify"]["ok"])

    return failing


def write_chaos_trajectory(doc: dict, root: str = ".",
                           path: Optional[str] = None) -> str:
    """Write one campaign's document to `path` or the next free
    CHAOS_r0N.json slot under `root`; returns the path."""
    if path is None:
        n = 1
        while os.path.exists(os.path.join(root, _TRAJ_PATTERN.format(n))):
            n += 1
        path = os.path.join(root, _TRAJ_PATTERN.format(n))
    with open(path, "w", encoding="utf-8") as fh:
        json.dump({"schema": TRAJECTORY_SCHEMA, **doc}, fh, indent=2,
                  sort_keys=True, default=str)
        fh.write("\n")
    return path
