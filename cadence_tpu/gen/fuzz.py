"""Generative history fuzzer: compose the FULL Cadence decision surface.

The five hand-written corpus generators (gen/corpus.py) each walk one
narrow groove of the semantic surface. This module is the compositional
counterpart (ROADMAP item 4): a seeded grammar that walks the workflow
state machine emitting *arbitrary legal histories* —

- every one of the 13 decision types (core/enums.DecisionType), each
  evidenced by its command event(s);
- mixed signal / timer / activity / child / marker / cancel
  interleavings, including buffered-event flush shapes (events landing
  in the decision-completed batch BEHIND the command events, the
  FlushBufferedEvents ordering);
- cron starts, workflow + activity retry policies, continue-as-new
  chains (batches carrying `new_run_events`, the FLAG_RUN_RESET row
  chain);
- transient decisions (DecisionTaskFailed/TimedOut) with NDC failover
  version bumps, bounded by the payload's version-history capacity;
- parent-attributed starts, child workflows with every parent-close
  policy, external signal/cancel legs with success AND failure results;
- external closes (Terminated / TimedOut) next to the decision closes.

Legality is enforced by construction: the walker tracks pending
decision / activity / timer / child / external tables and only emits
moves that are enabled, keeping each table within the device payload
capacities (core/checksum.PayloadLayout) so a generated corpus replays
clean on the base kernel — overflow pressure is the `overflow` suite's
job, not this one's.

Reproducibility contract: the same `(seed, workflow_index)` yields a
byte-identical history (string-seeded `random.Random`, exactly like
gen/corpus.py), across processes and platforms; `history_digest` is the
canonical byte witness the shrinker reports and tests pin.

Promotion: interesting shapes become named `CorpusSpec` JSON files
(fuzz_specs/*.json) that `bench.py` and `generate_corpus("fuzz:...")`
consume — a discovered adversarial structure graduates into a permanent
bench suite and perf-gate input via `fuzz promote` (cli.py).
"""
from __future__ import annotations

import hashlib
import json
import os
import random
from dataclasses import asdict, dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..core.checksum import (
    DEFAULT_LAYOUT,
    STICKY_ROW_INDEX,
    PayloadLayout,
    payload_row,
)
from ..core.enums import DecisionType, EventType, TimeoutType
from ..core.events import HistoryBatch, HistoryEvent, RetryPolicy
from ..oracle.state_builder import StateBuilder
from .corpus import (
    HistoryWriter,
    _begin_decision_completed_batch,
    _run_decision,
    _schedule_decision,
    _start,
)

#: profiles weight the walker's move menu toward a shape family; "mixed"
#: is the uniform default every other profile perturbs
PROFILES = ("mixed", "signal_storm", "timer_churn", "child_tree",
            "ndc_conflict", "cron_retry", "chain")

#: events kept free at the tail for the close sequence
_CLOSE_MARGIN = 14

#: decision type → the event types that evidence it in a history (the
#: coverage counter's ground truth; RequestCancelActivityTask and
#: CancelTimer have success AND failure evidence events)
DECISION_EVIDENCE: Dict[DecisionType, Tuple[EventType, ...]] = {
    DecisionType.ScheduleActivityTask: (EventType.ActivityTaskScheduled,),
    DecisionType.RequestCancelActivityTask: (
        EventType.ActivityTaskCancelRequested,
        EventType.RequestCancelActivityTaskFailed),
    DecisionType.StartTimer: (EventType.TimerStarted,),
    DecisionType.CompleteWorkflowExecution: (
        EventType.WorkflowExecutionCompleted,),
    DecisionType.FailWorkflowExecution: (EventType.WorkflowExecutionFailed,),
    DecisionType.CancelTimer: (EventType.TimerCanceled,
                               EventType.CancelTimerFailed),
    DecisionType.CancelWorkflowExecution: (
        EventType.WorkflowExecutionCanceled,),
    DecisionType.RequestCancelExternalWorkflowExecution: (
        EventType.RequestCancelExternalWorkflowExecutionInitiated,),
    DecisionType.RecordMarker: (EventType.MarkerRecorded,),
    DecisionType.ContinueAsNewWorkflowExecution: (
        EventType.WorkflowExecutionContinuedAsNew,),
    DecisionType.StartChildWorkflowExecution: (
        EventType.StartChildWorkflowExecutionInitiated,),
    DecisionType.SignalExternalWorkflowExecution: (
        EventType.SignalExternalWorkflowExecutionInitiated,),
    DecisionType.UpsertWorkflowSearchAttributes: (
        EventType.UpsertWorkflowSearchAttributes,),
}


def _weights(profile: str) -> Dict[str, float]:
    """Move-menu weights per profile; every move stays reachable in
    every profile (coverage must not depend on profile choice, only the
    MIX does)."""
    w = {
        "signal": 1.0, "signal_dup": 0.3, "cancel_request": 0.15,
        "activity": 1.0, "activity_retry": 0.5, "timer": 1.0,
        "timer_cancel": 0.5, "timer_cancel_failed": 0.15,
        "act_cancel": 0.4, "act_cancel_failed": 0.15,
        "marker": 0.6, "upsert": 0.4, "child": 0.8,
        "ext_signal": 0.5, "ext_cancel": 0.4,
        "transient": 0.35, "buffered_flush": 0.4,
    }
    if profile == "signal_storm":
        w.update(signal=4.0, signal_dup=1.5, buffered_flush=1.2)
    elif profile == "timer_churn":
        w.update(timer=4.0, timer_cancel=2.0, timer_cancel_failed=0.5)
    elif profile == "child_tree":
        w.update(child=4.0, ext_signal=1.2, ext_cancel=1.0)
    elif profile == "ndc_conflict":
        w.update(transient=1.4, signal=1.5)
    elif profile == "cron_retry":
        w.update(activity_retry=2.0, activity=2.0)
    # "chain" and "mixed" use the base weights; chain biases the CLOSE
    return w


class _Walker:
    """One workflow's seeded walk over the enabled-move menu."""

    def __init__(self, rng: random.Random, w: HistoryWriter,
                 profile: str, target_events: int,
                 layout: PayloadLayout, chain: bool) -> None:
        self.rng = rng
        self.w = w
        self.profile = profile
        self.target = target_events
        self.layout = layout
        self.chain = chain
        self.weights = _weights(profile)
        #: pending tables (mirror the oracle's, bounded by the layout
        #: with one slot of headroom kept free)
        self.acts: List[Tuple[int, str, Optional[int], bool]] = []
        self.timers: List[Tuple[int, str]] = []
        self.children: List[Tuple[int, Optional[int]]] = []
        self.ext_signals: List[int] = []
        self.ext_cancels: List[int] = []
        self.sched_id: Optional[int] = None
        self.version_bumps = 0
        self.cancel_requested = False
        self.seq = 0

    def _next(self, kind: str) -> str:
        self.seq += 1
        return f"{kind}-{self.seq}"

    # -- enabled-move menu ---------------------------------------------------

    def _pick(self, moves: List[str]) -> str:
        weights = [self.weights.get(mv, 0.5) for mv in moves]
        return self.rng.choices(moves, weights=weights, k=1)[0]

    def run(self) -> None:
        cron = self.profile == "cron_retry" or self.rng.random() < 0.15
        _start(self.w, self.rng, cron=cron,
               retry=self.rng.random() < (0.6 if self.profile == "cron_retry"
                                          else 0.25),
               parent=self.rng.random() < (0.5 if self.profile == "child_tree"
                                           else 0.2))
        self.sched_id = 2
        if self.profile == "ndc_conflict":
            self.w.version = 1
        while self.w.next_id < self.target - _CLOSE_MARGIN:
            if self.sched_id is not None and self.rng.random() < 0.75:
                self._decision_cycle()
            else:
                self._arrival()
        self._close()
        assert self.w._open is None

    # -- decision cycles -----------------------------------------------------

    def _decision_cycle(self) -> None:
        cyc = _run_decision(self.w, self.sched_id)
        self.sched_id = None
        if (self.rng.random() < self.weights["transient"] * 0.5
                and self.version_bumps
                < self.layout.max_version_history_items - 3):
            # transient decision: fail/timeout, sometimes an NDC
            # failover version bump, then a fresh real schedule
            self.w.begin_batch()
            r = self.rng.random()
            if r < 0.4:
                self.w.add(EventType.DecisionTaskFailed,
                           scheduled_event_id=cyc.sched_id,
                           started_event_id=cyc.started_id)
            else:
                self.w.add(EventType.DecisionTaskTimedOut,
                           scheduled_event_id=cyc.sched_id,
                           started_event_id=cyc.started_id,
                           timeout_type=int(
                               TimeoutType.ScheduleToStart if r < 0.6
                               else TimeoutType.StartToClose))
            self.w.end_batch()
            if self.rng.random() < (0.8 if self.profile == "ndc_conflict"
                                    else 0.4):
                self.w.version += 100
                self.version_bumps += 1
            self.sched_id = _schedule_decision(self.w)
            return
        completed = _begin_decision_completed_batch(self.w, cyc)
        for _ in range(self.rng.randrange(0, 4)):
            self._decision_event(completed)
        # buffered flush: events that raced this decision land BEHIND
        # the command events in the same batch, then a fresh decision is
        # scheduled in-batch (the engine's _flush_buffered ordering)
        if self.rng.random() < self.weights["buffered_flush"] * 0.5:
            for _ in range(self.rng.randrange(1, 3)):
                self.w.add(EventType.WorkflowExecutionSignaled,
                           signal_name=self._next("buf-sig"))
            self.sched_id = _schedule_decision(self.w, in_batch=True)
        self.w.end_batch()

    def _decision_event(self, completed) -> None:
        """One command event inside the decision-completed batch."""
        w, rng = self.w, self.rng
        moves = ["marker", "upsert", "act_cancel_failed",
                 "timer_cancel_failed"]
        if len(self.acts) < self.layout.max_activities - 2:
            moves += ["activity", "activity_retry"]
        if len(self.timers) < self.layout.max_timers - 2:
            moves.append("timer")
        if self.timers:
            moves.append("timer_cancel")
        if self.acts:
            moves.append("act_cancel")
        if len(self.children) < self.layout.max_children - 2:
            moves.append("child")
        if len(self.ext_signals) < self.layout.max_signals - 2:
            moves.append("ext_signal")
        if len(self.ext_cancels) < self.layout.max_request_cancels - 2:
            moves.append("ext_cancel")
        mv = self._pick(moves)
        if mv in ("activity", "activity_retry"):
            attrs = dict(
                activity_id=self._next("act"),
                task_list=f"tl-{rng.randrange(3)}",
                schedule_to_start_timeout_seconds=rng.randrange(5, 60),
                schedule_to_close_timeout_seconds=rng.randrange(60, 180),
                start_to_close_timeout_seconds=rng.randrange(5, 60),
                heartbeat_timeout_seconds=rng.choice([0, 0, 3]),
            )
            if mv == "activity_retry":
                attrs["retry_policy"] = RetryPolicy(
                    initial_interval_seconds=1, backoff_coefficient=2.0,
                    maximum_interval_seconds=rng.choice([8, 16]),
                    maximum_attempts=rng.randrange(2, 5),
                )
            ev = w.add(EventType.ActivityTaskScheduled,
                       decision_task_completed_event_id=completed.id,
                       **attrs)
            self.acts.append((ev.id, attrs["activity_id"], None,
                              attrs["heartbeat_timeout_seconds"] > 0))
        elif mv == "timer":
            tid = self._next("timer")
            ev = w.add(EventType.TimerStarted, timer_id=tid,
                       start_to_fire_timeout_seconds=rng.randrange(1, 300),
                       decision_task_completed_event_id=completed.id)
            self.timers.append((ev.id, tid))
        elif mv == "timer_cancel":
            started_id, tid = self.timers.pop(
                rng.randrange(len(self.timers)))
            w.add(EventType.TimerCanceled, timer_id=tid,
                  started_event_id=started_id,
                  decision_task_completed_event_id=completed.id)
        elif mv == "timer_cancel_failed":
            w.add(EventType.CancelTimerFailed,
                  timer_id=self._next("no-such-timer"),
                  cause="TIMER_ID_UNKNOWN",
                  decision_task_completed_event_id=completed.id)
        elif mv == "act_cancel":
            sched_id, aid, started_id, hb = self.acts[
                rng.randrange(len(self.acts))]
            w.add(EventType.ActivityTaskCancelRequested, activity_id=aid,
                  decision_task_completed_event_id=completed.id)
        elif mv == "act_cancel_failed":
            w.add(EventType.RequestCancelActivityTaskFailed,
                  activity_id=self._next("no-such-act"),
                  cause="ACTIVITY_ID_UNKNOWN",
                  decision_task_completed_event_id=completed.id)
        elif mv == "marker":
            w.add(EventType.MarkerRecorded,
                  marker_name=rng.choice(["version", "side-effect",
                                          "local-activity", "echo"]),
                  decision_task_completed_event_id=completed.id)
        elif mv == "upsert":
            w.add(EventType.UpsertWorkflowSearchAttributes,
                  search_attributes={
                      f"CustomKeywordField{rng.randrange(3)}":
                      f"v{rng.randrange(8)}".encode()},
                  decision_task_completed_event_id=completed.id)
        elif mv == "child":
            ev = w.add(EventType.StartChildWorkflowExecutionInitiated,
                       workflow_id=self._next(f"child-{self.w.workflow_id}"),
                       workflow_type="child-type",
                       parent_close_policy=rng.randrange(3),
                       decision_task_completed_event_id=completed.id)
            self.children.append((ev.id, None))
        elif mv == "ext_signal":
            ev = w.add(EventType.SignalExternalWorkflowExecutionInitiated,
                       workflow_id=f"other-{rng.randrange(4)}", run_id="",
                       signal_name=self._next("poke"),
                       child_workflow_only=rng.random() < 0.3,
                       decision_task_completed_event_id=completed.id)
            self.ext_signals.append(ev.id)
        elif mv == "ext_cancel":
            ev = w.add(
                EventType.RequestCancelExternalWorkflowExecutionInitiated,
                workflow_id=f"other-{rng.randrange(4)}", run_id="",
                child_workflow_only=False,
                decision_task_completed_event_id=completed.id)
            self.ext_cancels.append(ev.id)

    # -- arrivals between decisions ------------------------------------------

    def _arrival(self) -> None:
        w, rng = self.w, self.rng
        moves = ["signal", "signal_dup"]
        if not self.cancel_requested:
            moves.append("cancel_request")
        if any(s is None for _, _, s, _ in self.acts):
            moves.append("act_start")
        if any(s is not None for _, _, s, _ in self.acts):
            moves.append("act_close")
        if self.timers:
            moves.append("timer_fire")
        if any(s is None for _, s in self.children):
            moves.append("child_start")
        if any(s is not None for _, s in self.children):
            moves.append("child_close")
        if self.ext_signals:
            moves.append("ext_signal_result")
        if self.ext_cancels:
            moves.append("ext_cancel_result")
        mv = self._pick(moves)
        if mv == "act_start":
            i = next(i for i, a in enumerate(self.acts) if a[2] is None)
            sched_id, aid, _, hb = self.acts[i]
            ev = w.single(EventType.ActivityTaskStarted,
                          scheduled_event_id=sched_id,
                          request_id=f"actpoll-{sched_id}", attempt=0)
            self.acts[i] = (sched_id, aid, ev.id, hb)
            return
        if mv == "child_start":
            i = next(i for i, c in enumerate(self.children) if c[1] is None)
            init_id, _ = self.children[i]
            if rng.random() < 0.15:
                # start failed: the child slot frees without ever starting
                w.begin_batch()
                w.add(EventType.StartChildWorkflowExecutionFailed,
                      initiated_event_id=init_id,
                      cause="WORKFLOW_ALREADY_RUNNING")
                if self.sched_id is None:
                    self.sched_id = _schedule_decision(w, in_batch=True)
                w.end_batch()
                self.children.pop(i)
                return
            ev = w.single(EventType.ChildWorkflowExecutionStarted,
                          initiated_event_id=init_id,
                          run_id=f"child-run-{init_id}")
            self.children[i] = (init_id, ev.id)
            return
        # remaining arrivals are "wake" batches: they schedule a decision
        # in-batch when none is pending (the signal-transaction shape)
        w.begin_batch()
        if mv == "signal" or mv == "signal_dup":
            attrs = dict(signal_name=self._next("sig"))
            if rng.random() < 0.5:
                # request-id carrying signals repopulate the dedup set on
                # replay; a dup id re-applied is the redelivery shape
                attrs["request_id"] = (f"rid-{self.w.workflow_id}-"
                                       f"{self.seq if mv == 'signal' else 1}")
            w.add(EventType.WorkflowExecutionSignaled, **attrs)
        elif mv == "cancel_request":
            w.add(EventType.WorkflowExecutionCancelRequested,
                  cause="fuzz-cancel")
            self.cancel_requested = True
        elif mv == "act_close":
            i = next(i for i, a in enumerate(self.acts) if a[2] is not None)
            sched_id, aid, started_id, hb = self.acts.pop(i)
            kind = rng.choice([EventType.ActivityTaskCompleted,
                               EventType.ActivityTaskFailed,
                               EventType.ActivityTaskTimedOut,
                               EventType.ActivityTaskCanceled])
            attrs = dict(scheduled_event_id=sched_id,
                         started_event_id=started_id)
            if kind == EventType.ActivityTaskFailed:
                attrs["reason"] = "fuzz-failure"
            elif kind == EventType.ActivityTaskTimedOut:
                attrs["timeout_type"] = int(rng.choice(
                    [TimeoutType.StartToClose, TimeoutType.Heartbeat]
                    if hb else [TimeoutType.StartToClose]))
                attrs["dt_nanos"] = 5_000_000_000
            w.add(kind, **attrs)
        elif mv == "timer_fire":
            started_id, tid = self.timers.pop(
                rng.randrange(len(self.timers)))
            w.add(EventType.TimerFired, timer_id=tid,
                  started_event_id=started_id, dt_nanos=2_000_000_000)
        elif mv == "child_close":
            i = next(i for i, c in enumerate(self.children)
                     if c[1] is not None)
            init_id, started_id = self.children.pop(i)
            w.add(rng.choice([EventType.ChildWorkflowExecutionCompleted,
                              EventType.ChildWorkflowExecutionFailed,
                              EventType.ChildWorkflowExecutionCanceled,
                              EventType.ChildWorkflowExecutionTimedOut,
                              EventType.ChildWorkflowExecutionTerminated]),
                  initiated_event_id=init_id, started_event_id=started_id)
        elif mv == "ext_signal_result":
            init_id = self.ext_signals.pop(
                rng.randrange(len(self.ext_signals)))
            w.add(EventType.ExternalWorkflowExecutionSignaled
                  if rng.random() < 0.7
                  else EventType.SignalExternalWorkflowExecutionFailed,
                  initiated_event_id=init_id)
        elif mv == "ext_cancel_result":
            init_id = self.ext_cancels.pop(
                rng.randrange(len(self.ext_cancels)))
            w.add(EventType.ExternalWorkflowExecutionCancelRequested
                  if rng.random() < 0.7
                  else EventType.RequestCancelExternalWorkflowExecutionFailed,
                  initiated_event_id=init_id)
        if self.sched_id is None:
            self.sched_id = _schedule_decision(w, in_batch=True)
        w.end_batch()

    # -- close ---------------------------------------------------------------

    def _close(self) -> None:
        w, rng = self.w, self.rng
        r = rng.random()
        if r < 0.08:
            # external closes need no decision cycle
            w.single(EventType.WorkflowExecutionTerminated
                     if rng.random() < 0.5
                     else EventType.WorkflowExecutionTimedOut,
                     reason="fuzz-close")
            return
        if self.sched_id is None:
            self.sched_id = _schedule_decision(w)
        cyc = _run_decision(w, self.sched_id)
        completed = _begin_decision_completed_batch(w, cyc)
        if self.cancel_requested:
            w.add(EventType.WorkflowExecutionCanceled,
                  decision_task_completed_event_id=completed.id)
            w.end_batch()
            return
        chain_p = 0.7 if self.profile == "chain" else 0.12
        if self.chain and rng.random() < chain_p:
            new_run_id = f"{w.run_id}-chained"
            w.add(EventType.WorkflowExecutionContinuedAsNew,
                  new_execution_run_id=new_run_id,
                  decision_task_completed_event_id=completed.id)
            # the new run's first transaction rides as new_run_events
            # (state_builder.go applyEvents newRunHistory shape); event
            # ids restart at 1 in the new run
            w2 = HistoryWriter(domain_id=w.domain_id,
                               workflow_id=w.workflow_id,
                               run_id=new_run_id, now=w.now,
                               version=w.version)
            _start(w2, rng)
            w.end_batch(new_run_events=[
                e for b in w2.batches for e in b.events])
            return
        # retry/cron-shaped walks close failing more often (their whole
        # point is the failure path); everything else mostly completes
        fail_p = 0.6 if self.profile == "cron_retry" else 0.3
        w.add(EventType.WorkflowExecutionFailed if rng.random() < fail_p
              else EventType.WorkflowExecutionCompleted,
              decision_task_completed_event_id=completed.id)
        w.end_batch()


# ---------------------------------------------------------------------------
# Public generation surface
# ---------------------------------------------------------------------------


def generate_fuzz_history(seed: int, workflow_index: int = 0,
                          target_events: int = 100,
                          profile: str = "mixed",
                          layout: PayloadLayout = DEFAULT_LAYOUT,
                          chain: bool = True) -> List[HistoryBatch]:
    """One workflow's fuzzed batched history; byte-identical for the same
    `(seed, workflow_index, target_events, profile)`."""
    if profile not in PROFILES:
        raise ValueError(f"unknown fuzz profile {profile!r} "
                         f"(have {PROFILES})")
    rng = random.Random(f"fuzz:{seed}:{profile}:{workflow_index}")
    w = HistoryWriter(workflow_id=f"fuzz-{profile}-wf-{workflow_index}",
                      run_id=f"run-{seed}-{workflow_index}")
    _Walker(rng, w, profile, target_events, layout, chain).run()
    return w.batches


def generate_fuzz_corpus(num_workflows: int, seed: int = 0,
                         target_events: int = 100,
                         profile: str = "mixed",
                         layout: PayloadLayout = DEFAULT_LAYOUT,
                         chain: bool = True) -> List[List[HistoryBatch]]:
    return [generate_fuzz_history(seed, i, target_events, profile,
                                  layout, chain)
            for i in range(num_workflows)]


def strip_new_run_events(histories: Sequence[List[HistoryBatch]]
                         ) -> List[List[HistoryBatch]]:
    """Store-shaped copies: a real HistoryStore persists each run's
    events separately — run 1's stored batches never carry the new run's
    (`as_history_batches` has no new_run_events). The verify_all /
    store-seeding drivers use this form so oracle, store, and device all
    replay the same bytes."""
    out: List[List[HistoryBatch]] = []
    for h in histories:
        out.append([
            HistoryBatch(domain_id=b.domain_id, workflow_id=b.workflow_id,
                         run_id=b.run_id, events=b.events,
                         request_id=b.request_id)
            if b.new_run_events else b
            for b in h])
    return out


def oracle_final_row(batches: List[HistoryBatch],
                     layout: PayloadLayout = DEFAULT_LAYOUT) -> np.ndarray:
    """The oracle's expected device payload row for one history,
    following a continue-as-new chain when the final batch carries
    new_run_events (the device row's final state is the LAST run's —
    encode_history FLAG_RUN_RESET chaining)."""
    sb = StateBuilder()
    sb.replay_history(batches)
    ms = sb.new_run_state if sb.new_run_state is not None else sb.ms
    row = payload_row(ms, layout)
    row[STICKY_ROW_INDEX] = 0
    return row


def history_digest(batches: Sequence[HistoryBatch]) -> str:
    """Canonical SHA256 of a batched history (the reproducibility
    witness: same (seed, index) → same digest, across processes)."""
    h = hashlib.sha256()
    for b in batches:
        for group in (b.events, b.new_run_events or ()):
            for e in group:
                h.update(repr((e.id, int(e.event_type), e.version,
                               e.timestamp, e.task_id,
                               sorted((k, repr(v))
                                      for k, v in e.attrs.items()))
                              ).encode())
        h.update(b"|batch|")
    return h.hexdigest()


# ---------------------------------------------------------------------------
# Coverage counter
# ---------------------------------------------------------------------------


def coverage(histories: Sequence[Sequence[HistoryBatch]]) -> dict:
    """Count generated event kinds and the decision types they evidence.

    Returns {"events": {name: n}, "decisions": {name: n},
    "missing_decisions": [names]} — the acceptance counter for "all 13
    decision types composed"."""
    event_counts: Dict[str, int] = {}
    for h in histories:
        for b in h:
            for group in (b.events, b.new_run_events or ()):
                for e in group:
                    name = EventType(e.event_type).name
                    event_counts[name] = event_counts.get(name, 0) + 1
    decision_counts: Dict[str, int] = {}
    for dt, evidence in DECISION_EVIDENCE.items():
        decision_counts[dt.name] = sum(
            event_counts.get(et.name, 0) for et in evidence)
    missing = [name for name, n in decision_counts.items() if n == 0]
    return {"events": event_counts, "decisions": decision_counts,
            "missing_decisions": missing}


# ---------------------------------------------------------------------------
# Store seeding (the verify_all driver's input shape)
# ---------------------------------------------------------------------------


def seed_stores(stores, histories: Sequence[List[HistoryBatch]],
                domain_id: str = "fuzz-domain") -> List[Tuple[str, str, str]]:
    """Persist store-shaped fuzz histories (new_run_events stripped) into
    a Stores bundle with the oracle's live mutable state, so
    `TPUReplayEngine.verify_all` has both sides of the zero-divergence
    contract. Returns the seeded keys."""
    keys: List[Tuple[str, str, str]] = []
    for h in strip_new_run_events(histories):
        first = h[0]
        key = (domain_id, first.workflow_id, first.run_id)
        for batch in h:
            stores.history.append_batch(*key, events=list(batch.events))
        ms = StateBuilder().replay_history(
            stores.history.as_history_batches(*key))
        ms.execution_info.domain_id = domain_id
        stores.execution.upsert_workflow(ms)
        keys.append(key)
    return keys


def fork_ndc_branch(stores, key: Tuple[str, str, str], seed: int,
                    extra_events: int = 3) -> int:
    """Turn one seeded single-lineage history into an NDC two-branch
    conflict tree: fork at a batch boundary, write a HIGHER-version
    signal suffix to the new branch, and make it current (the
    conflict-resolution winner). Returns the winning branch index.

    The losing branch keeps the original tail beyond the fork — the
    device must retain its items in the loser VH table while arbitrating
    the current pointer to the winner (conflict_resolver.go analog,
    exercised through `TPUReplayEngine.replay_tree_payloads`)."""
    rng = random.Random(f"fuzz-fork:{seed}:{key[1]}")
    events = stores.history.read_events(*key)
    # fork roughly mid-history, at a batch-first boundary the store knows
    fork_at = events[max(2, len(events) // 2)].id
    branch = stores.history.fork_branch(*key, source_branch=0,
                                        fork_event_id=fork_at)
    base = next(e for e in events if e.id == fork_at)
    version = max(e.version for e in events) + 100
    suffix = [
        HistoryEvent(id=fork_at + 1 + i,
                     event_type=EventType.WorkflowExecutionSignaled,
                     version=version,
                     timestamp=base.timestamp + 1_000_000 * (i + 1),
                     task_id=9_000 + i,
                     attrs={"signal_name": f"ndc-fork-{i}"})
        for i in range(rng.randrange(1, extra_events + 1))]
    stores.history.append_batch(*key, events=suffix, branch=branch)
    stores.history.set_current_branch(*key, branch=branch)
    return branch


# ---------------------------------------------------------------------------
# Promotion: named corpus specs consumable by bench.py
# ---------------------------------------------------------------------------

SPEC_SCHEMA = "fuzz-corpus-spec-v1"
SPEC_DIR = "fuzz_specs"


@dataclass(frozen=True)
class CorpusSpec:
    """A promoted fuzz shape: everything needed to regenerate the corpus
    byte-identically, plus the digest that proves it."""

    name: str
    seed: int
    workflows: int
    target_events: int
    profile: str = "mixed"
    chain: bool = True
    #: digest of workflow 0 at promotion time — regeneration is refused
    #: if the grammar drifted (the spec names BYTES, not intent)
    digest: str = ""
    note: str = ""

    def generate(self) -> List[List[HistoryBatch]]:
        histories = generate_fuzz_corpus(
            self.workflows, seed=self.seed,
            target_events=self.target_events, profile=self.profile,
            chain=self.chain)
        if self.digest and history_digest(histories[0]) != self.digest:
            raise ValueError(
                f"spec {self.name!r}: generator drifted — workflow 0 no "
                f"longer reproduces digest {self.digest[:12]}…")
        return histories


def make_spec(name: str, seed: int, workflows: int, target_events: int,
              profile: str = "mixed", chain: bool = True,
              note: str = "") -> CorpusSpec:
    digest = history_digest(generate_fuzz_history(
        seed, 0, target_events, profile, chain=chain))
    return CorpusSpec(name=name, seed=seed, workflows=workflows,
                      target_events=target_events, profile=profile,
                      chain=chain, digest=digest, note=note)


def save_spec(spec: CorpusSpec, root: str = ".") -> str:
    """`fuzz promote`'s writer: fuzz_specs/<name>.json under `root`."""
    directory = os.path.join(root, SPEC_DIR)
    os.makedirs(directory, exist_ok=True)
    path = os.path.join(directory, f"{spec.name}.json")
    with open(path, "w", encoding="utf-8") as fh:
        json.dump({"schema": SPEC_SCHEMA, **asdict(spec)}, fh, indent=2,
                  sort_keys=True)
        fh.write("\n")
    return path


def parity_run(seeds: int = 50, workflows_per_seed: int = 4,
               target_events: int = 100,
               profiles: Sequence[str] = PROFILES,
               layout: PayloadLayout = DEFAULT_LAYOUT,
               ndc_forks: int = 2,
               chunk_workflows: int = 64) -> dict:
    """The history-parity driver: stream seeded fuzz corpora through
    BOTH device paths and the engine's verify tier, gating zero
    oracle↔device divergence.

    Per seed, one workflow-per-profile corpus replays (a) dense
    `replay_corpus` vs `oracle_final_row`, (b) wirec `replay_wirec_to_crc`
    vs the oracle rows' CRC32s, and (c) `TPUReplayEngine.verify_all` over
    store-seeded (chain-stripped) histories — the resident/ladder/
    serving-mesh configuration of record; `ndc_forks` of each seed's
    workflows additionally fork into NDC two-branch conflict trees
    checked through `replay_tree_payloads`. Returns the JSON-able doc
    `fuzz run` records as FUZZ_r0N.json."""
    import jax.numpy as jnp

    from ..core.checksum import crc32_of_row
    from ..engine.persistence import Stores
    from ..engine.tpu_engine import TPUReplayEngine
    from ..ops.encode import encode_corpus
    from ..ops.replay import replay_corpus, replay_wirec_to_crc
    from ..ops.wirec import pack_wirec

    doc = {
        "seeds": seeds, "workflows_per_seed": workflows_per_seed,
        "target_events": target_events, "profiles": list(profiles),
        "workflows": 0, "events": 0,
        "dense_divergent": 0, "wirec_divergent": 0, "device_errors": 0,
        "verify_total": 0, "verify_divergent": 0, "verify_fallback": 0,
        "ndc_forked": 0, "ndc_divergent": 0,
    }
    all_histories: List[List[HistoryBatch]] = []
    for seed in range(seeds):
        histories: List[List[HistoryBatch]] = []
        for i in range(workflows_per_seed):
            profile = profiles[(seed + i) % len(profiles)]
            histories.append(generate_fuzz_history(
                seed, i, target_events, profile, layout))
        all_histories.extend(histories)
        expected = np.stack([oracle_final_row(h, layout)
                             for h in histories])
        rows, _crcs, errors = replay_corpus(histories, layout)
        doc["device_errors"] += int((errors != 0).sum())
        doc["dense_divergent"] += int(
            ((rows != expected).any(axis=1) & (errors == 0)).sum())
        c = pack_wirec(encode_corpus(histories))
        wcrc, werr = replay_wirec_to_crc(
            jnp.asarray(c.slab), jnp.asarray(c.bases),
            jnp.asarray(c.n_events), c.profile, layout)
        wcrc = np.asarray(wcrc).astype(np.uint32)
        exp_crc = np.array([crc32_of_row(r) for r in expected],
                           dtype=np.uint32)
        doc["wirec_divergent"] += int(
            ((wcrc != exp_crc) & (np.asarray(werr) == 0)).sum())
        doc["workflows"] += len(histories)
        doc["events"] += sum(len(b.events) + len(b.new_run_events or ())
                             for h in histories for b in h)

    cov = coverage(all_histories)
    doc["decision_coverage"] = cov["decisions"]
    doc["missing_decisions"] = cov["missing_decisions"]
    doc["event_kinds"] = len(cov["events"])

    # the engine tier: store-seeded verify + NDC conflict forks
    stores = Stores()
    keys = seed_stores(stores, all_histories)
    engine = TPUReplayEngine(stores, layout,
                             chunk_workflows=chunk_workflows)
    verify = engine.verify_all(keys)
    doc["verify_total"] = verify.total
    doc["verify_divergent"] = len(verify.divergent)
    doc["verify_fallback"] = len(verify.fallback)
    doc["verify_resident"] = len(verify.resident)
    doc["verify_escalated"] = len(verify.escalated)

    forked = keys[:ndc_forks * max(1, seeds // 2)]
    for i, key in enumerate(forked):
        fork_ndc_branch(stores, key, seed=i)
    if forked:
        rows, errors, branch = engine.replay_tree_payloads(forked)
        hs = stores.history
        for i, key in enumerate(forked):
            doc["ndc_forked"] += 1
            cur = hs.get_current_branch(*key)
            ms = StateBuilder().replay_history(
                hs.as_history_batches(*key, branch=cur))
            row = payload_row(ms, layout)
            row[STICKY_ROW_INDEX] = 0
            if (errors[i] != 0 or branch[i] != cur
                    or not (rows[i] == row).all()):
                doc["ndc_divergent"] += 1

    doc["ok"] = (doc["dense_divergent"] == 0 and doc["wirec_divergent"] == 0
                 and doc["device_errors"] == 0
                 and doc["verify_divergent"] == 0
                 and doc["ndc_divergent"] == 0
                 and not doc["missing_decisions"])
    return doc


# ---------------------------------------------------------------------------
# FUZZ_r0N.json trajectory files (the loadgen/report.py idiom)
# ---------------------------------------------------------------------------

TRAJECTORY_SCHEMA = "fuzz-trajectory-v1"
_TRAJ_PATTERN = "FUZZ_r{:02d}.json"


def write_fuzz_trajectory(doc: dict, root: str = ".",
                          path: Optional[str] = None) -> str:
    """Write one fuzz run's document to `path` or the next free
    FUZZ_r0N.json slot under `root`; returns the path."""
    if path is None:
        n = 1
        while os.path.exists(os.path.join(root, _TRAJ_PATTERN.format(n))):
            n += 1
        path = os.path.join(root, _TRAJ_PATTERN.format(n))
    with open(path, "w", encoding="utf-8") as fh:
        json.dump({"schema": TRAJECTORY_SCHEMA, **doc}, fh, indent=2,
                  sort_keys=True, default=str)
        fh.write("\n")
    return path


def load_specs(root: str = ".") -> List[CorpusSpec]:
    """Every promoted spec under root/fuzz_specs, name-sorted (bench.py
    consumes these as permanent suites)."""
    directory = os.path.join(root, SPEC_DIR)
    if not os.path.isdir(directory):
        return []
    specs = []
    for fname in sorted(os.listdir(directory)):
        if not fname.endswith(".json"):
            continue
        with open(os.path.join(directory, fname), encoding="utf-8") as fh:
            doc = json.load(fh)
        if doc.pop("schema", SPEC_SCHEMA) != SPEC_SCHEMA:
            continue
        specs.append(CorpusSpec(**doc))
    return specs
