"""Golden event-stream corpus generators.

Produces the five BASELINE workload shapes (BASELINE.md / BASELINE.json
configs) as synthetic-but-valid workflow histories, used for:

- differential testing: oracle replayer vs TPU kernel (checksum parity),
- benchmarking: bench.py replays generated corpora at scale.

Workload shapes mirror the reference load/canary suites:
  basic            /root/reference/bench/load/basic/stressWorkflow.go
                   (chained no-op activities driven by decision tasks)
  echo_signal      /root/reference/canary/echo.go, canary/signal.go
  timer_retry      /root/reference/canary/timeout.go, canary/retry.go
  concurrent_child /root/reference/canary/concurrentExec.go, canary/localactivity.go
                   (wide decision batches, child workflows)
  ndc              cross-cluster replication shapes (version bumps mid-history,
                   transient decisions, continue-as-new), per
                   /root/reference/host/ndc/integration_test.go patterns

Histories are generated as *batches* (one batch per would-be transaction),
because batch boundaries are semantically visible: LastFirstEventID,
ScheduledEventBatchID and transient-decision schedule IDs all depend on them
(state_builder.go:642, mutable_state_builder.go:2163).
"""
from __future__ import annotations

import random
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from ..core.enums import EMPTY_EVENT_ID, EventType, TimeoutType
from ..core.events import HistoryBatch, HistoryEvent, RetryPolicy

SUITES = ("basic", "echo_signal", "timer_retry", "concurrent_child", "ndc")


@dataclass
class HistoryWriter:
    """Builds valid batched histories with monotonically increasing event IDs
    and timestamps."""

    domain_id: str = "default-domain-id"
    workflow_id: str = "wf"
    run_id: str = "run"
    version: int = 0
    next_id: int = 1
    now: int = 1_700_000_000_000_000_000  # deterministic epoch, unix nanos
    batches: List[HistoryBatch] = field(default_factory=list)
    _open: Optional[List[HistoryEvent]] = None
    task_id: int = 1000

    def begin_batch(self) -> None:
        assert self._open is None, "batch already open"
        self._open = []

    def end_batch(self, new_run_events: Optional[List[HistoryEvent]] = None) -> None:
        assert self._open, "no open batch or empty batch"
        self.batches.append(
            HistoryBatch(
                domain_id=self.domain_id,
                workflow_id=self.workflow_id,
                run_id=self.run_id,
                events=self._open,
                request_id=f"req-{self.workflow_id}-{self.run_id}",
                new_run_events=new_run_events,
            )
        )
        self._open = None

    def add(self, event_type: EventType, dt_nanos: int = 1_000_000, **attrs: Any) -> HistoryEvent:
        assert self._open is not None, "no open batch"
        self.now += dt_nanos
        self.task_id += 1
        ev = HistoryEvent(
            id=self.next_id,
            event_type=event_type,
            version=self.version,
            timestamp=self.now,
            task_id=self.task_id,
            attrs=attrs,
        )
        self.next_id += 1
        self._open.append(ev)
        return ev

    def single(self, event_type: EventType, **attrs: Any) -> HistoryEvent:
        self.begin_batch()
        ev = self.add(event_type, **attrs)
        self.end_batch()
        return ev

    def execution_cancel_requested(self) -> bool:
        return any(
            e.event_type == EventType.WorkflowExecutionCancelRequested
            for b in self.batches for e in b.events
        )


def _start(w: HistoryWriter, rng: random.Random, *, cron: bool = False,
           retry: bool = False, parent: bool = False) -> None:
    """Start batch: WorkflowExecutionStarted + DecisionTaskScheduled, matching
    the active side's first transaction (historyEngine.go:583-529)."""
    attrs: Dict[str, Any] = dict(
        task_list="tl-default",
        workflow_type=f"workflow-type-{rng.randrange(4)}",
        execution_start_to_close_timeout_seconds=3600,
        task_start_to_close_timeout_seconds=10,
        first_execution_run_id=w.run_id,
    )
    if cron:
        attrs["cron_schedule"] = "* * * * *"
        attrs["first_decision_task_backoff_seconds"] = 60
        attrs["initiator"] = None
    if retry:
        attrs["retry_policy"] = RetryPolicy(
            initial_interval_seconds=1,
            backoff_coefficient=2.0,
            maximum_interval_seconds=10,
            maximum_attempts=3,
            expiration_interval_seconds=0,
        )
        attrs["attempt"] = 0
    if parent:
        attrs["parent_workflow_domain_id"] = "parent-domain-id"
        attrs["parent_workflow_id"] = f"parent-{w.workflow_id}"
        attrs["parent_run_id"] = "parent-run"
        attrs["parent_initiated_event_id"] = 5
    w.begin_batch()
    w.add(EventType.WorkflowExecutionStarted, **attrs)
    w.add(EventType.DecisionTaskScheduled, task_list="tl-default",
          start_to_close_timeout_seconds=10, attempt=0)
    w.end_batch()


def _decision_started(w: HistoryWriter, sched_id: int) -> HistoryEvent:
    return w.single(EventType.DecisionTaskStarted, scheduled_event_id=sched_id,
                    request_id=f"poll-{sched_id}")


@dataclass
class _DecisionCycle:
    sched_id: int
    started_id: int


def _begin_decision_completed_batch(w: HistoryWriter, cyc: _DecisionCycle) -> HistoryEvent:
    w.begin_batch()
    return w.add(EventType.DecisionTaskCompleted, scheduled_event_id=cyc.sched_id,
                 started_event_id=cyc.started_id)


def _schedule_decision(w: HistoryWriter, in_batch: bool = False) -> int:
    if not in_batch:
        ev = w.single(EventType.DecisionTaskScheduled, task_list="tl-default",
                      start_to_close_timeout_seconds=10, attempt=0)
    else:
        ev = w.add(EventType.DecisionTaskScheduled, task_list="tl-default",
                   start_to_close_timeout_seconds=10, attempt=0)
    return ev.id


def _run_decision(w: HistoryWriter, sched_id: int) -> _DecisionCycle:
    started = _decision_started(w, sched_id)
    return _DecisionCycle(sched_id=sched_id, started_id=started.id)


def _close(w: HistoryWriter, rng: random.Random, cyc: _DecisionCycle,
           close_type: EventType = EventType.WorkflowExecutionCompleted) -> None:
    completed = _begin_decision_completed_batch(w, cyc)
    w.add(close_type, decision_task_completed_event_id=completed.id)
    w.end_batch()


# ---------------------------------------------------------------------------
# Suite: basic (chained activities, no-op decisions)
# ---------------------------------------------------------------------------


def gen_basic(rng: random.Random, w: HistoryWriter, target_events: int = 100) -> None:
    _start(w, rng)
    sched_id = 2
    act_seq = 0
    while w.next_id < target_events - 6:
        cyc = _run_decision(w, sched_id)
        completed = _begin_decision_completed_batch(w, cyc)
        act = w.add(
            EventType.ActivityTaskScheduled,
            activity_id=f"act-{act_seq}",
            task_list="tl-default",
            schedule_to_start_timeout_seconds=60,
            schedule_to_close_timeout_seconds=120,
            start_to_close_timeout_seconds=60,
            heartbeat_timeout_seconds=0,
        )
        act_seq += 1
        w.end_batch()
        started = w.single(EventType.ActivityTaskStarted, scheduled_event_id=act.id,
                           request_id=f"actpoll-{act.id}")
        w.begin_batch()
        w.add(EventType.ActivityTaskCompleted, scheduled_event_id=act.id,
              started_event_id=started.id)
        sched_id = _schedule_decision(w, in_batch=True)
        w.end_batch()
    cyc = _run_decision(w, sched_id)
    _close(w, rng, cyc)


# ---------------------------------------------------------------------------
# Suite: echo_signal (mixed signal/decision events)
# ---------------------------------------------------------------------------


def gen_echo_signal(rng: random.Random, w: HistoryWriter, target_events: int = 100) -> None:
    _start(w, rng)
    sched_id = 2
    sig = 0
    while w.next_id < target_events - 8:
        cyc = _run_decision(w, sched_id)
        completed = _begin_decision_completed_batch(w, cyc)
        if rng.random() < 0.4:
            w.add(EventType.MarkerRecorded, marker_name="echo",
                  decision_task_completed_event_id=completed.id)
        w.end_batch()
        # external signals arrive; each signal transaction also schedules a
        # decision when none is pending (historyEngine.go:2202 signal path)
        n_signals = rng.randrange(1, 4)
        for i in range(n_signals):
            w.begin_batch()
            w.add(EventType.WorkflowExecutionSignaled, signal_name=f"sig-{sig}")
            sig += 1
            if i == 0:
                sched_id = _schedule_decision(w, in_batch=True)
            w.end_batch()
    cyc = _run_decision(w, sched_id)
    _close(w, rng, cyc)


# ---------------------------------------------------------------------------
# Suite: timer_retry (timers firing/canceled, activity retries & timeouts)
# ---------------------------------------------------------------------------


def gen_timer_retry(rng: random.Random, w: HistoryWriter, target_events: int = 100) -> None:
    _start(w, rng, retry=rng.random() < 0.5)
    sched_id = 2
    timer_seq = 0
    act_seq = 0
    while w.next_id < target_events - 10:
        cyc = _run_decision(w, sched_id)
        completed = _begin_decision_completed_batch(w, cyc)
        choice = rng.random()
        if choice < 0.45:
            # start a timer, let it fire
            timer = w.add(EventType.TimerStarted, timer_id=f"timer-{timer_seq}",
                          start_to_fire_timeout_seconds=rng.randrange(1, 30),
                          decision_task_completed_event_id=completed.id)
            timer_seq += 1
            w.end_batch()
            w.begin_batch()
            w.add(EventType.TimerFired, timer_id=timer.get("timer_id"),
                  started_event_id=timer.id, dt_nanos=2_000_000_000)
            sched_id = _schedule_decision(w, in_batch=True)
            w.end_batch()
        elif choice < 0.7:
            # start a timer then cancel it on the next decision
            timer = w.add(EventType.TimerStarted, timer_id=f"timer-{timer_seq}",
                          start_to_fire_timeout_seconds=300,
                          decision_task_completed_event_id=completed.id)
            timer_seq += 1
            sched_id2 = _schedule_decision(w, in_batch=True)
            w.end_batch()
            cyc2 = _run_decision(w, sched_id2)
            completed2 = _begin_decision_completed_batch(w, cyc2)
            w.add(EventType.TimerCanceled, timer_id=timer.get("timer_id"),
                  started_event_id=timer.id,
                  decision_task_completed_event_id=completed2.id)
            sched_id = _schedule_decision(w, in_batch=True)
            w.end_batch()
            continue
        else:
            # activity with retry policy that times out / fails then retries
            act = w.add(
                EventType.ActivityTaskScheduled,
                activity_id=f"act-{act_seq}",
                task_list="tl-default",
                schedule_to_start_timeout_seconds=10,
                schedule_to_close_timeout_seconds=60,
                start_to_close_timeout_seconds=5,
                heartbeat_timeout_seconds=rng.choice([0, 3]),
                retry_policy=RetryPolicy(
                    initial_interval_seconds=1, backoff_coefficient=2.0,
                    maximum_interval_seconds=8, maximum_attempts=4,
                ),
            )
            act_seq += 1
            w.end_batch()
            started = w.single(EventType.ActivityTaskStarted,
                               scheduled_event_id=act.id, request_id=f"actpoll-{act.id}",
                               attempt=0)
            w.begin_batch()
            if rng.random() < 0.5:
                w.add(EventType.ActivityTaskTimedOut, scheduled_event_id=act.id,
                      started_event_id=started.id,
                      timeout_type=int(TimeoutType.StartToClose),
                      dt_nanos=5_000_000_000)
            else:
                w.add(EventType.ActivityTaskFailed, scheduled_event_id=act.id,
                      started_event_id=started.id, reason="synthetic-failure")
            sched_id = _schedule_decision(w, in_batch=True)
            w.end_batch()
            continue
        # loop continues with pending decision sched_id
    cyc = _run_decision(w, sched_id)
    _close(w, rng, cyc, EventType.WorkflowExecutionCompleted
           if rng.random() < 0.8 else EventType.WorkflowExecutionFailed)


# ---------------------------------------------------------------------------
# Suite: concurrent_child (wide decision batches, children, externals)
# ---------------------------------------------------------------------------


def gen_concurrent_child(rng: random.Random, w: HistoryWriter,
                         target_events: int = 120) -> None:
    _start(w, rng, parent=rng.random() < 0.3)
    sched_id = 2
    child_seq = 0
    act_seq = 0
    while w.next_id < target_events - 24:
        cyc = _run_decision(w, sched_id)
        completed = _begin_decision_completed_batch(w, cyc)
        # wide batch: several parallel activities + child workflows + externals
        acts = []
        for _ in range(rng.randrange(2, 5)):
            acts.append(w.add(
                EventType.ActivityTaskScheduled,
                activity_id=f"act-{act_seq}",
                task_list=f"tl-{rng.randrange(3)}",
                schedule_to_start_timeout_seconds=60,
                schedule_to_close_timeout_seconds=120,
                start_to_close_timeout_seconds=60,
                heartbeat_timeout_seconds=0,
            ))
            act_seq += 1
        children = []
        for _ in range(rng.randrange(0, 3)):
            children.append(w.add(
                EventType.StartChildWorkflowExecutionInitiated,
                workflow_id=f"child-{w.workflow_id}-{child_seq}",
                workflow_type="child-type",
                parent_close_policy=rng.randrange(3),
                decision_task_completed_event_id=completed.id,
            ))
            child_seq += 1
        ext_signal = None
        if rng.random() < 0.4:
            ext_signal = w.add(
                EventType.SignalExternalWorkflowExecutionInitiated,
                workflow_id="other-wf", run_id="", signal_name="poke",
                child_workflow_only=False,
                decision_task_completed_event_id=completed.id,
            )
        ext_cancel = None
        if rng.random() < 0.25:
            ext_cancel = w.add(
                EventType.RequestCancelExternalWorkflowExecutionInitiated,
                workflow_id="other-wf", run_id="", child_workflow_only=False,
                decision_task_completed_event_id=completed.id,
            )
        if rng.random() < 0.3:
            w.add(EventType.UpsertWorkflowSearchAttributes,
                  search_attributes={"CustomKeywordField": b"v"},
                  decision_task_completed_event_id=completed.id)
        w.end_batch()

        # activities complete
        for act in acts:
            started = w.single(EventType.ActivityTaskStarted,
                               scheduled_event_id=act.id,
                               request_id=f"actpoll-{act.id}")
            w.begin_batch()
            w.add(EventType.ActivityTaskCompleted, scheduled_event_id=act.id,
                  started_event_id=started.id)
            w.end_batch()
        # children start and complete
        for ci in children:
            started = w.single(EventType.ChildWorkflowExecutionStarted,
                               initiated_event_id=ci.id,
                               run_id=f"child-run-{ci.id}")
            w.begin_batch()
            w.add(rng.choice([
                EventType.ChildWorkflowExecutionCompleted,
                EventType.ChildWorkflowExecutionFailed,
                EventType.ChildWorkflowExecutionCanceled,
            ]), initiated_event_id=ci.id, started_event_id=started.id)
            w.end_batch()
        if ext_signal is not None:
            w.single(EventType.ExternalWorkflowExecutionSignaled,
                     initiated_event_id=ext_signal.id)
        if ext_cancel is not None:
            w.single(
                EventType.ExternalWorkflowExecutionCancelRequested
                if rng.random() < 0.7
                else EventType.RequestCancelExternalWorkflowExecutionFailed,
                initiated_event_id=ext_cancel.id,
            )
        sched_id = _schedule_decision(w)
    cyc = _run_decision(w, sched_id)
    _close(w, rng, cyc)


# ---------------------------------------------------------------------------
# Suite: ndc (multi-version histories, transient decisions, cancel request)
# ---------------------------------------------------------------------------


def gen_ndc(rng: random.Random, w: HistoryWriter, target_events: int = 100) -> None:
    w.version = 1
    _start(w, rng)
    sched_id = 2
    timer_seq = 0
    failovers = 0
    while w.next_id < target_events - 12:
        cyc = _run_decision(w, sched_id)
        r = rng.random()
        if r < 0.25 and failovers < 4:
            # decision fails/times out; version bump simulates failover;
            # exercises the transient-decision path (state_builder.go:237-281)
            w.begin_batch()
            if rng.random() < 0.5:
                w.add(EventType.DecisionTaskTimedOut, scheduled_event_id=cyc.sched_id,
                      started_event_id=cyc.started_id,
                      timeout_type=int(TimeoutType.StartToClose))
            else:
                w.add(EventType.DecisionTaskFailed, scheduled_event_id=cyc.sched_id,
                      started_event_id=cyc.started_id)
            w.end_batch()
            failovers += 1
            w.version += 100  # failover version bump
            sched_id = _schedule_decision(w)
        elif r < 0.5:
            completed = _begin_decision_completed_batch(w, cyc)
            timer = w.add(EventType.TimerStarted, timer_id=f"t-{timer_seq}",
                          start_to_fire_timeout_seconds=5,
                          decision_task_completed_event_id=completed.id)
            timer_seq += 1
            w.end_batch()
            w.begin_batch()
            w.add(EventType.TimerFired, timer_id=timer.get("timer_id"),
                  started_event_id=timer.id, dt_nanos=5_000_000_000)
            sched_id = _schedule_decision(w, in_batch=True)
            w.end_batch()
        elif r < 0.6:
            # cancel requested externally mid-flight
            completed = _begin_decision_completed_batch(w, cyc)
            w.end_batch()
            w.begin_batch()
            w.add(EventType.WorkflowExecutionCancelRequested, cause="ndc-test")
            sched_id = _schedule_decision(w, in_batch=True)
            w.end_batch()
        else:
            completed = _begin_decision_completed_batch(w, cyc)
            w.add(EventType.MarkerRecorded, marker_name="ndc-marker",
                  decision_task_completed_event_id=completed.id)
            w.end_batch()
            w.begin_batch()
            w.add(EventType.WorkflowExecutionSignaled, signal_name="ndc-signal")
            sched_id = _schedule_decision(w, in_batch=True)
            w.end_batch()
    cyc = _run_decision(w, sched_id)
    if w.execution_cancel_requested():
        completed = _begin_decision_completed_batch(w, cyc)
        w.add(EventType.WorkflowExecutionCanceled,
              decision_task_completed_event_id=completed.id)
        w.end_batch()
    else:
        _close(w, rng, cyc)


# ---------------------------------------------------------------------------
# Suite: overflow (adversarial — a controlled fraction of workflows exceed
# the device pending-activity table, forcing the oracle fallback)
# ---------------------------------------------------------------------------

#: fraction of overflow-suite workflows engineered to exceed the device
#: tables (SURVEY §7 hard part 3: the fallback must be MEASURED under
#: pressure, not always zero by construction)
OVERFLOW_FRACTION = 0.025


def gen_overflow(rng: random.Random, w: HistoryWriter,
                 target_events: int = 100,
                 capacity_hint: int = 16) -> None:
    """Mostly gen_basic, but OVERFLOW_FRACTION of workflows pile up
    `capacity_hint + 8` concurrently-pending activities in one decision —
    past the device table, valid for the oracle (which has no capacity),
    so the device flags TABLE_OVERFLOW and the engine falls back."""
    if rng.random() >= OVERFLOW_FRACTION:
        gen_basic(rng, w, target_events)
        return
    _start(w, rng)
    cyc = _run_decision(w, 2)
    completed = _begin_decision_completed_batch(w, cyc)
    acts = [w.add(
        EventType.ActivityTaskScheduled,
        activity_id=f"flood-{i}", task_list="tl-default",
        schedule_to_start_timeout_seconds=60,
        schedule_to_close_timeout_seconds=120,
        start_to_close_timeout_seconds=60, heartbeat_timeout_seconds=0,
    ) for i in range(capacity_hint + 8)]
    w.end_batch()
    # drain them so the workflow still closes cleanly on the oracle
    sched_id = None
    for act in acts:
        started = w.single(EventType.ActivityTaskStarted,
                           scheduled_event_id=act.id,
                           request_id=f"actpoll-{act.id}")
        w.begin_batch()
        w.add(EventType.ActivityTaskCompleted, scheduled_event_id=act.id,
              started_event_id=started.id)
        if act is acts[-1]:
            sched_id = _schedule_decision(w, in_batch=True)
        w.end_batch()
    cyc = _run_decision(w, sched_id)
    _close(w, rng, cyc)


_GENERATORS = {
    "basic": gen_basic,
    "echo_signal": gen_echo_signal,
    "timer_retry": gen_timer_retry,
    "concurrent_child": gen_concurrent_child,
    "ndc": gen_ndc,
    "overflow": gen_overflow,
}


def generate_history(suite: str, seed: int, workflow_index: int = 0,
                     target_events: int = 100) -> List[HistoryBatch]:
    """Generate one workflow's batched history for a suite.

    `"fuzz"` / `"fuzz:<profile>"` route to the compositional fuzzer
    (gen/fuzz.py) — the whole decision surface behind the same
    `(suite, seed, workflow_index)` addressing every consumer
    (bench.py, tests, promoted CorpusSpecs) already speaks."""
    if suite == "fuzz" or suite.startswith("fuzz:"):
        from .fuzz import generate_fuzz_history
        profile = suite.partition(":")[2] or "mixed"
        return generate_fuzz_history(seed, workflow_index,
                                     target_events=target_events,
                                     profile=profile)
    # string seeding is stable across processes (random.seed version 2 hashes
    # the string with sha512), unlike tuple __hash__ under PYTHONHASHSEED
    rng = random.Random(f"{seed}:{suite}:{workflow_index}")
    w = HistoryWriter(workflow_id=f"{suite}-wf-{workflow_index}",
                      run_id=f"run-{workflow_index}")
    _GENERATORS[suite](rng, w, target_events=target_events)
    assert w._open is None
    return w.batches


def generate_corpus(suite: str, num_workflows: int, seed: int = 0,
                    target_events: int = 100) -> List[List[HistoryBatch]]:
    """Generate a corpus: one batched history per workflow."""
    return [
        generate_history(suite, seed, i, target_events) for i in range(num_workflows)
    ]
