"""Operator CLI (tools/cli/app.go analog).

The reference's `cadence` CLI talks gRPC to a running cluster; this
framework's cluster state is a durable WAL directory, so the CLI opens
the WAL (recovering state exactly like a restarted host), runs the
command against an in-process cluster, and appends any mutations back to
the same WAL — the same durability story a server would have.

    python -m cadence_tpu --wal ./cluster.wal domain register --name dev
    python -m cadence_tpu --wal ./cluster.wal workflow start \
        --domain dev --workflow-id wf-1 --type t --task-list tl
    python -m cadence_tpu --wal ./cluster.wal workflow show \
        --domain dev --workflow-id wf-1
    python -m cadence_tpu --wal ./cluster.wal admin verify

Output is JSON per command for scriptability (the reference CLI's
--format json mode).
"""
from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Any


def _ensure_jax_backend() -> None:
    """Operator machines may carry a JAX_PLATFORMS pointing at a plugin
    that isn't loadable here; probe in a subprocess (jax caches backend
    init failures in-process) and fall back to CPU so the CLI always
    works."""
    import subprocess
    if not os.environ.get("JAX_PLATFORMS"):
        return
    probe = subprocess.run(
        [sys.executable, "-c", "import jax; jax.devices()"],
        capture_output=True)
    if probe.returncode != 0:
        print(f"warning: JAX backend '{os.environ['JAX_PLATFORMS']}' "
              "unavailable; falling back to cpu", file=sys.stderr)
        os.environ["JAX_PLATFORMS"] = "cpu"


def _build_cluster(wal: str):
    from .engine.durability import open_durable_stores, recover_stores
    from .engine.onebox import Onebox
    from .utils import compile_cache
    from .utils.clock import RealTimeSource

    # any device verify/rebuild this process runs reuses prior compiles
    compile_cache.enable()

    if os.path.exists(wal):
        # commands verify explicitly (admin verify/scan); recovery itself
        # skips BOTH device passes — verification and the batched device
        # rebuild — so cheap reads (`domain list`) never pay JAX backend
        # init plus a whole-cluster device replay
        stores, report = recover_stores(wal, verify_on_device=False,
                                        rebuild_on_device=False)
    else:
        stores, report = open_durable_stores(wal), None
    # the wall clock, not the test clock: retention, cron, and timeouts
    # must actually elapse in CLI-driven clusters
    box = Onebox(num_hosts=1, num_shards=4, stores=stores,
                 time_source=RealTimeSource())
    # replay persisted operator config (admin config-set WAL records)
    for key, value, domain in getattr(stores, "recovered_config", []):
        box.config.set(key, value, domain=domain)
    if report is not None and report.open_workflows:
        box.refresh_all_tasks()
    return box, report


def _emit(obj: Any) -> None:
    print(json.dumps(obj, indent=2, sort_keys=True, default=str))


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="cadence-tpu", description="cadence_tpu operator CLI")
    parser.add_argument("--wal", default="",
                        help="cluster WAL path (durable state; required "
                             "for every group except `load`, which "
                             "launches its own wire cluster)")
    sub = parser.add_subparsers(dest="group", required=True)

    # domain
    dom = sub.add_parser("domain").add_subparsers(dest="cmd", required=True)
    reg = dom.add_parser("register")
    reg.add_argument("--name", required=True)
    reg.add_argument("--retention", type=int, default=0)
    upd = dom.add_parser("update")
    upd.add_argument("--name", required=True)
    upd.add_argument("--retention", type=int, default=None)
    upd.add_argument("--description", default=None)
    upd.add_argument("--archival-uri", default=None)
    upd.add_argument("--active-cluster", default=None)
    upd.add_argument("--clusters", default=None,
                     help="comma-separated; can only grow")
    dep = dom.add_parser("deprecate")
    dep.add_argument("--name", required=True)
    dom.add_parser("list")

    # workflow
    wf = sub.add_parser("workflow").add_subparsers(dest="cmd", required=True)
    start = wf.add_parser("start")
    start.add_argument("--domain", required=True)
    start.add_argument("--workflow-id", required=True)
    start.add_argument("--type", required=True)
    start.add_argument("--task-list", required=True)
    start.add_argument("--cron", default="")
    for name in ("show", "describe"):
        p = wf.add_parser(name)
        p.add_argument("--domain", required=True)
        p.add_argument("--workflow-id", required=True)
        p.add_argument("--run-id", default=None)
    sig = wf.add_parser("signal")
    sig.add_argument("--domain", required=True)
    sig.add_argument("--workflow-id", required=True)
    sig.add_argument("--name", required=True)
    term = wf.add_parser("terminate")
    term.add_argument("--domain", required=True)
    term.add_argument("--workflow-id", required=True)
    term.add_argument("--reason", default="cli")
    lst = wf.add_parser("list")
    lst.add_argument("--domain", required=True)
    lst.add_argument("--closed", action="store_true")
    lst.add_argument("--query", default=None,
                     help="visibility query, e.g. \"WorkflowType = 'x' AND "
                          "CloseStatus = 'Completed'\"")
    cnt = wf.add_parser("count")
    cnt.add_argument("--domain", required=True)
    cnt.add_argument("--query", default="")
    bat = wf.add_parser("batch")
    bat.add_argument("--domain", required=True)
    bat.add_argument("--query", required=True)
    bat.add_argument("--op", required=True,
                     choices=("terminate", "cancel", "signal"))
    bat.add_argument("--name", default="", help="signal name (op=signal)")
    bat.add_argument("--reason", default="cli batch")
    bat.add_argument("--rps", type=float, default=50.0)
    sws = wf.add_parser("signalwithstart")
    sws.add_argument("--domain", required=True)
    sws.add_argument("--workflow-id", required=True)
    sws.add_argument("--type", required=True)
    sws.add_argument("--task-list", required=True)
    sws.add_argument("--name", required=True, help="signal name")

    # admin
    adm = sub.add_parser("admin").add_subparsers(dest="cmd", required=True)
    adm.add_parser("describe-cluster")
    dq = adm.add_parser("describe-queue")
    dq.add_argument("--shard-id", type=int, required=True)
    adm.add_parser("verify")
    scan = adm.add_parser("scan")
    scan.add_argument("--fix", action="store_true")
    adm.add_parser("scavenge")
    wd = adm.add_parser("watchdog")
    wd.add_argument("--fix", action="store_true")
    cg = adm.add_parser("config-get")
    cg.add_argument("--key", required=True)
    cs = adm.add_parser("config-set")
    cs.add_argument("--key", required=True)
    cs.add_argument("--value", required=True)
    adm.add_parser("schema-version")
    adm.add_parser("schema-migrate")
    # replication DLQ (tools/cli dlq read/purge/merge verbs)
    adm.add_parser("dlq-read")
    adm.add_parser("dlq-purge")
    adm.add_parser("dlq-merge")
    # DLQ observability rollup + redrive through the resender
    # (`admin dlq` / `admin dlq redrive`); --http runs the wire arm
    # against a live service host (admin_dlq op)
    dlqp = adm.add_parser("dlq")
    dlqp.add_argument("action", nargs="?", default="summary",
                      choices=("summary", "redrive"))
    dlqp.add_argument("--http", default="",
                      help="HOST:PORT of a live service host (wire arm)")
    fo = adm.add_parser("failover")
    fo.add_argument("--domain", required=True)
    fo.add_argument("--to", required=True, help="target active cluster")
    pr = adm.add_parser("profile")
    pr.add_argument("--out", default="/tmp/cadence_tpu_profile",
                    help="trace output directory (open with TensorBoard "
                         "or Perfetto)")
    pr.add_argument("--workflows", type=int, default=256)
    pr.add_argument("--events", type=int, default=100)
    res = adm.add_parser("resident")
    res.add_argument("--passes", type=int, default=2,
                     help="verify passes to run first (pass 1 seeds the "
                          "cache, pass 2 measures the warm hit rate; "
                          "0 = dump current stats only)")
    adm.add_parser("serving")
    adm.add_parser("visibility")
    clu = adm.add_parser("cluster")
    clu.add_argument("--host", action="append", default=[],
                     metavar="HOST:PORT",
                     help="live service host to query over the wire "
                          "(repeatable; per-host shard ownership, "
                          "migration counters, resident occupancy — "
                          "skips the WAL when given)")
    clu.add_argument("--detail", action="store_true",
                     help="include each resident row's payload CRC32 "
                          "(the migration byte-parity probe)")
    clu.add_argument("--drain", action="store_true",
                     help="run the planned-rebalance drain on every "
                          "--host first: persist a snapshot record for "
                          "each resident row, so a following kill or "
                          "rebalance is a warm failover")
    top = adm.add_parser("top")
    top.add_argument("--http", action="append", default=[],
                     metavar="[NAME=]HOST:PORT",
                     help="live host /timeseries endpoint to scrape "
                          "(repeatable; fleet utilization, binding "
                          "resource, burn rates — skips the WAL when "
                          "given)")
    hp = adm.add_parser("hostprof")
    hp.add_argument("--host", default="", metavar="HOST:PORT",
                    help="live service host to profile over the wire "
                         "(admin_hostprof op; skips the WAL)")
    hp.add_argument("--duration", type=float, default=0.5,
                    help="burst-sample window in seconds when the "
                         "target's profiler thread is not running")
    fr = adm.add_parser("flightrec")
    fr.add_argument("--host", default="", metavar="HOST:PORT",
                    help="live service host to query over the wire "
                         "(admin_flightrec op; skips the WAL)")
    fr.add_argument("--last", type=int, default=100,
                    help="trailing events to include")
    fr.add_argument("--dump", default="",
                    help="also dump the full ring to this JSONL path "
                         "(on the TARGET host in wire mode)")
    snp = adm.add_parser("snapshot")
    snp.add_argument("--sweep", action="store_true",
                     help="run one verify pass (seeding the resident "
                          "pool) then force-write snapshots for every "
                          "resident workflow before the rollup — the "
                          "warm-the-next-restart verb")

    # WAL tools (adminDBScan/adminDBClean analogs over the one backend)
    wal_grp = sub.add_parser("wal").add_subparsers(dest="cmd", required=True)
    wal_grp.add_parser("scan")
    wal_grp.add_parser("clean")
    # recovery fsck: typed findings over the raw stream + the rebuild
    wal_grp.add_parser("fsck")
    # kill-anywhere cut-point sweep (engine/crashsim.py)
    cs = wal_grp.add_parser("crashsim")
    cs.add_argument("--stride", type=int, default=1,
                    help="recover at every Nth record boundary (1 = all)")
    cs.add_argument("--no-torn", action="store_true",
                    help="skip torn mid-record tails (JSONL only)")
    cs.add_argument("--seed-workload", type=int, default=0, metavar="N",
                    help="record an N-workflow seeded workload into the "
                         "WAL first (refuses to overwrite an existing one)")

    # continuous canary (canary/cron.go)
    can = sub.add_parser("canary").add_subparsers(dest="cmd", required=True)
    crun = can.add_parser("run")
    crun.add_argument("--domain", default="canary")
    crun.add_argument("--cycles", type=int, default=10)
    crun.add_argument("--interval", type=float, default=0.0)

    # generative fuzzer (gen/fuzz.py, gen/shrink.py, gen/interleave.py):
    # seeded corpora over the full 13-decision surface, parity-gated on
    # oracle<->device checksums; shrink failures to minimal batch
    # sequences; promote interesting shapes into named bench specs
    fz = sub.add_parser("fuzz").add_subparsers(dest="cmd", required=True)
    fr = fz.add_parser("run")
    fr.add_argument("--seeds", type=int, default=50)
    fr.add_argument("--workflows", type=int, default=4,
                    help="workflows per seed (profiles rotate per slot)")
    fr.add_argument("--events", type=int, default=100)
    fr.add_argument("--profile", default="",
                    help="restrict to one profile (default: rotate all)")
    fr.add_argument("--interleave", action="store_true",
                    help="also run one seeded interleaving scenario "
                         "(serving tier + wire/store chaos + crashpoint "
                         "kills) and gate zero divergence")
    fr.add_argument("--interleave-seed", type=int, default=20260804)
    fr.add_argument("--replication", action="store_true",
                    help="also fuzz the replication seam (standby apply "
                         "pump + device twin vs live traffic, split-brain "
                         "NDC promotion, poison-task quarantine)")
    fr.add_argument("--replication-seed", type=int, default=20260806)
    fr.add_argument("--record", action="store_true",
                    help="write the next FUZZ_r0N.json in CWD")
    fr.add_argument("--out", default="",
                    help="explicit trajectory path (implies --record)")
    fs = fz.add_parser("shrink")
    fs.add_argument("--seed", type=int, required=True)
    fs.add_argument("--index", type=int, default=0)
    fs.add_argument("--events", type=int, default=100)
    fs.add_argument("--profile", default="mixed")
    fs.add_argument("--poison", default="",
                    help="inject a deterministic device-side defect on "
                         "this signal name (harness validation mode); "
                         "default: shrink a REAL parity divergence")
    # fleet chaos campaign (gen/cluster_chaos.py): seeded fault schedule
    # against a REAL multi-host wire cluster — SIGKILLs, store kill +
    # WAL-fsck + relaunch, asymmetric partitions, membership flaps —
    # gated on fault-free byte-identity, clean fsck, zero parity
    # divergence, closing verify_all (both regions with --regions 2)
    fc = fz.add_parser("cluster")
    fc.add_argument("--seed", type=int, default=20260806)
    fc.add_argument("--hosts", type=int, default=3)
    fc.add_argument("--shards", type=int, default=8)
    fc.add_argument("--workflows", type=int, default=6)
    fc.add_argument("--signals", type=int, default=2)
    fc.add_argument("--kills", type=int, default=1,
                    help="service hosts SIGKILLed mid-traffic")
    fc.add_argument("--store-kills", type=int, default=0,
                    help="store-server SIGKILL + fsck + relaunch cycles")
    fc.add_argument("--partitions", type=int, default=1,
                    help="asymmetric partition cut+heal pairs")
    fc.add_argument("--flaps", type=int, default=0,
                    help="membership flap (SIGSTOP past TTL, SIGCONT) arms")
    fc.add_argument("--profile", default="steady",
                    choices=["steady", "storm"])
    fc.add_argument("--regions", type=int, default=1, choices=[1, 2])
    fc.add_argument("--shrink", action="store_true",
                    help="harness-validation mode: shrink the injected "
                         "kill-then-signal regression to its 1-minimal "
                         "campaign (no cluster launched)")
    fc.add_argument("--shrink-on-failure", action="store_true",
                    help="on a REAL gate failure, ddmin the campaign to "
                         "a 1-minimal reproducer (expensive: each "
                         "predicate call is a baseline+chaos pair)")
    fc.add_argument("--record", action="store_true",
                    help="write the next CHAOS_r0N.json in CWD")
    fc.add_argument("--out", default="",
                    help="explicit trajectory path (implies --record)")
    fp = fz.add_parser("promote")
    fp.add_argument("--name", required=True)
    fp.add_argument("--seed", type=int, required=True)
    fp.add_argument("--workflows", type=int, default=64)
    fp.add_argument("--events", type=int, default=100)
    fp.add_argument("--profile", default="mixed")
    fp.add_argument("--note", default="")
    fp.add_argument("--root", default=".",
                    help="repo root holding fuzz_specs/")

    # open-loop load harness (bench/ + canary/ load tooling,
    # cadence_tpu/loadgen/): launches a REAL wire cluster, drives seeded
    # open-loop traffic, evaluates latency SLOs, optionally records a
    # LOADGEN_r0N.json trajectory next to BENCH_r*.json
    load_grp = sub.add_parser("load").add_subparsers(dest="cmd",
                                                     required=True)
    # the serving-tier comparison (in-process, tier on vs off; records
    # decision-transaction p50/p99, launches/sec, coalescing factor)
    sv = load_grp.add_parser("serving")
    sv.add_argument("--duration", type=float, default=4.0)
    sv.add_argument("--rps", type=float, default=160.0,
                    help="scheduled decision-transaction arrival rate")
    sv.add_argument("--workers", type=int, default=16)
    sv.add_argument("--pool-size", type=int, default=12)
    sv.add_argument("--seed", type=int, default=20260803)
    sv.add_argument("--record", action="store_true",
                    help="write the next LOADGEN_r0N.json in CWD")
    sv.add_argument("--out", default="",
                    help="explicit trajectory path (implies --record)")
    # the device-visibility tier comparison (in-process, tier on vs
    # off on the query-heavy mix; records List/Count p50/p99, the
    # device/fallback path mix, staleness and parity counters)
    vis = load_grp.add_parser("visibility")
    vis.add_argument("--duration", type=float, default=4.0)
    vis.add_argument("--rps", type=float, default=60.0,
                     help="scheduled query-heavy arrival rate")
    vis.add_argument("--workers", type=int, default=16)
    vis.add_argument("--pool-size", type=int, default=8)
    vis.add_argument("--seed", type=int, default=20260804)
    vis.add_argument("--staleness-bound", type=int, default=64,
                     help="max appender backlog a query may observe")
    vis.add_argument("--record", action="store_true",
                     help="write the next LOADGEN_r0N.json in CWD")
    vis.add_argument("--out", default="",
                     help="explicit trajectory path (implies --record)")
    # the multi-host kill-mid-traffic migration scenario (wire cluster,
    # serving tier ON in every host; gates victim p99, zero divergence,
    # snapshot-hydrated steals >= the floor; records events/s/cluster)
    cl = load_grp.add_parser("cluster")
    cl.add_argument("--duration", type=float, default=12.0)
    cl.add_argument("--hosts", type=int, default=3)
    cl.add_argument("--rps", type=float, default=16.0,
                    help="scheduled victim-domain arrival rate")
    cl.add_argument("--pool-size", type=int, default=12)
    cl.add_argument("--kill-at", type=float, default=0.5,
                    help="kill the victim host at this fraction of the "
                         "run window")
    cl.add_argument("--workers", type=int, default=24)
    cl.add_argument("--seed", type=int, default=20260804)
    cl.add_argument("--p99-slo-ms", type=float, default=8000.0)
    cl.add_argument("--hydration-floor", type=float, default=0.8)
    cl.add_argument("--record", action="store_true",
                    help="write the next LOADGEN_r0N.json in CWD")
    cl.add_argument("--out", default="",
                    help="explicit trajectory path (implies --record)")
    # the two-region kill-the-active-region scenario (wire regions with
    # continuous replication + snapshot shipping; gates promoted-region
    # p99, bounded pre-kill lag, warm steals >= the floor, zero
    # divergence, both-region verify; records events/s/fleet)
    rg = load_grp.add_parser("region")
    rg.add_argument("--duration", type=float, default=10.0,
                    help="per traffic phase (active + promoted)")
    rg.add_argument("--hosts", type=int, default=2,
                    help="service hosts per region")
    rg.add_argument("--rps", type=float, default=10.0)
    rg.add_argument("--pool-size", type=int, default=12)
    rg.add_argument("--kill-at", type=float, default=0.6,
                    help="kill the active region at this fraction of "
                         "the phase-1 window")
    rg.add_argument("--workers", type=int, default=16)
    rg.add_argument("--seed", type=int, default=20260806)
    rg.add_argument("--p99-slo-ms", type=float, default=8000.0)
    rg.add_argument("--hydration-floor", type=float, default=0.8)
    rg.add_argument("--max-repl-lag", type=int, default=64,
                    help="max unconsumed replication tasks at the kill")
    rg.add_argument("--no-verify", action="store_true")
    rg.add_argument("--record", action="store_true",
                    help="write the next LOADGEN_r0N.json in CWD")
    rg.add_argument("--out", default="",
                    help="explicit trajectory path (implies --record)")
    for cmd_name in ("run", "overload"):
        lp = load_grp.add_parser(cmd_name)
        lp.add_argument("--duration", type=float, default=10.0)
        lp.add_argument("--hosts", type=int, default=2)
        lp.add_argument("--seed", type=int, default=20260803)
        lp.add_argument("--workers", type=int, default=24)
        lp.add_argument("--chaos", default="",
                        help="wire chaos spec for every process "
                             "(rpc/chaos.py), e.g. "
                             "'drop=0.04,sever=0.02,delay=0.1,seed=17'")
        lp.add_argument("--no-verify", action="store_true",
                        help="skip the post-run oracle<->device checksum "
                             "verification")
        lp.add_argument("--record", action="store_true",
                        help="write the next LOADGEN_r0N.json in CWD")
        lp.add_argument("--out", default="",
                        help="explicit trajectory path (implies --record)")
        if cmd_name == "run":
            lp.add_argument("--domains", default="lg-a,lg-b",
                            help="comma-separated domain names")
            lp.add_argument("--rps", type=float, default=3.0,
                            help="scheduled arrival rate per domain")
            lp.add_argument("--p99-slo-ms", type=float, default=2500.0)
            lp.add_argument("--mix", default="standard",
                            choices=("standard", "query-heavy"),
                            help="traffic blend (loadgen/mixes.MIXES); "
                                 "query-heavy drives List/Scan/Count — "
                                 "set CADENCE_TPU_VISIBILITY=1 and the "
                                 "store server serves them from the "
                                 "columnar device tier")
        else:
            lp.add_argument("--victim-rps", type=float, default=4.0)
            lp.add_argument("--aggressor-quota-rps", type=float,
                            default=4.0)
            lp.add_argument("--overdrive", type=float, default=2.0,
                            help="aggressor drive rate as a multiple of "
                                 "its quota")
            lp.add_argument("--victim-p99-slo-ms", type=float,
                            default=2500.0)
            lp.add_argument("--store-faults", default="",
                            help="store-fault spec injected into the "
                                 "STORE server process only "
                                 "(engine/faults.py), e.g. "
                                 "'rate=0.04,seed=13'")

    args = parser.parse_args(argv)
    if args.group == "fuzz":
        return _fuzz_tool(args)
    if args.group == "load":
        return _load_tool(args)
    if args.group == "admin" and args.cmd == "cluster" and args.host:
        # wire mode: roll up live hosts without opening any WAL
        return _cluster_tool(args)
    if args.group == "admin" and args.cmd == "top" and args.http:
        # fleet telemetry rollup over /timeseries scrapes: no WAL either
        return _top_tool(args)
    if args.group == "admin" and args.cmd in ("hostprof", "flightrec") \
            and args.host:
        return _telemetry_tool(args)
    if not args.wal:
        parser.error(f"--wal is required for the {args.group} group")
    if args.group == "wal":
        return _wal_tool(args)
    # schema tools run BEFORE cluster recovery (the cassandra/sql-tool
    # split: schema commands must work on logs recovery would refuse)
    if args.group == "admin" and args.cmd in ("schema-version",
                                              "schema-migrate"):
        from .engine.durability import (
            WAL_VERSION,
            migrate_wal_file,
            read_log,
            wal_version,
        )
        if args.cmd == "schema-version":
            current = (wal_version(read_log(args.wal))
                       if os.path.exists(args.wal) else None)
            _emit({"wal": args.wal, "version": current,
                   "binary_version": WAL_VERSION})
        else:
            if not os.path.exists(args.wal):
                _emit({"error": f"no WAL at {args.wal}"})
                return 1
            before, after = migrate_wal_file(args.wal)
            _emit({"migrated": args.wal, "from": before, "to": after})
        return 0
    _ensure_jax_backend()
    box, _report = _build_cluster(args.wal)
    from .engine.admin import AdminHandler
    admin = AdminHandler(box)

    if args.group == "domain":
        if args.cmd == "register":
            domain_id = box.frontend.register_domain(
                args.name, retention_days=args.retention)
            _emit({"registered": args.name, "domain_id": domain_id})
        elif args.cmd == "update":
            info = box.frontend.update_domain(
                args.name, retention_days=args.retention,
                description=args.description,
                history_archival_uri=args.archival_uri,
                active_cluster=args.active_cluster,
                clusters=(args.clusters.split(",") if args.clusters
                          else None))
            _emit({"updated": info.name,
                   "retention_days": info.retention_days,
                   "active_cluster": info.active_cluster,
                   "archival_uri": info.history_archival_uri,
                   "notification_version": info.notification_version})
        elif args.cmd == "deprecate":
            info = box.frontend.deprecate_domain(args.name)
            _emit({"deprecated": info.name})
        elif args.cmd == "list":
            _emit([{"name": d.name, "domain_id": d.domain_id,
                    "retention_days": d.retention_days,
                    "status": d.status}
                   for d in box.frontend.list_domains()])

    elif args.group == "workflow":
        if args.cmd == "start":
            run_id = box.frontend.start_workflow_execution(
                args.domain, args.workflow_id, args.type, args.task_list,
                cron_schedule=args.cron)
            box.pump_once()
            _emit({"started": args.workflow_id, "run_id": run_id})
        elif args.cmd == "show":
            events = box.frontend.get_workflow_execution_history(
                args.domain, args.workflow_id, args.run_id)
            _emit([{"id": e.id, "type": e.event_type.name,
                    "version": e.version, "attrs": e.attrs}
                   for e in events])
        elif args.cmd == "describe":
            _emit(admin.describe_workflow_execution(
                args.domain, args.workflow_id, args.run_id))
        elif args.cmd == "signal":
            box.frontend.signal_workflow_execution(
                args.domain, args.workflow_id, args.name)
            box.pump_once()
            _emit({"signaled": args.workflow_id})
        elif args.cmd == "terminate":
            box.frontend.terminate_workflow_execution(
                args.domain, args.workflow_id, reason=args.reason)
            box.pump_once()
            _emit({"terminated": args.workflow_id})
        elif args.cmd == "list":
            if args.query is not None:
                recs = box.frontend.list_workflow_executions(args.domain,
                                                             args.query)
            else:
                recs = (box.frontend.list_closed_workflow_executions(args.domain)
                        if args.closed else
                        box.frontend.list_open_workflow_executions(args.domain))
            _emit([{"workflow_id": r.workflow_id, "run_id": r.run_id,
                    "type": r.workflow_type, "close_status": r.close_status,
                    "search_attrs": {k: (v.decode("utf-8", "replace")
                                         if isinstance(v, bytes) else v)
                                     for k, v in r.search_attrs.items()}}
                   for r in recs])
        elif args.cmd == "count":
            _emit({"count": box.frontend.count_workflow_executions(
                args.domain, args.query)})
        elif args.cmd == "batch":
            from .engine.batcher import Batcher
            report = Batcher(box.frontend, rps=args.rps).run(
                args.domain, args.query, args.op, reason=args.reason,
                signal_name=args.name)
            box.pump_once()
            _emit({"total": report.total, "succeeded": report.succeeded,
                   "failed": report.failed, "failures": report.failures})
        elif args.cmd == "signalwithstart":
            run_id = box.frontend.signal_with_start_workflow_execution(
                args.domain, args.workflow_id, args.name, args.type,
                args.task_list)
            box.pump_once()
            _emit({"workflow_id": args.workflow_id, "run_id": run_id})

    elif args.group == "admin":
        if args.cmd == "describe-cluster":
            _emit(admin.describe_cluster())
        elif args.cmd == "describe-queue":
            _emit(admin.describe_queue(args.shard_id))
        elif args.cmd == "verify":
            result = admin.verify()
            _emit({"total": result.total,
                   "verified_on_device": result.verified_on_device,
                   "escalated": len(result.escalated),
                   "fallback": len(result.fallback),
                   "divergent": result.divergent, "ok": result.ok})
            return 0 if result.ok else 1
        elif args.cmd == "scan":
            report = box.scanner.run_once(fix=args.fix)
            _emit({"executions": report.executions,
                   "orphan_pointers": report.orphan_pointers,
                   "missing_history": report.missing_history,
                   "state_divergent": report.state_divergent,
                   "fixed": report.fixed, "ok": report.ok})
            return 0 if report.ok else 1
        elif args.cmd == "scavenge":
            _emit({"deleted": box.scavenger.run_once()})
        elif args.cmd == "watchdog":
            from .engine.workers import Watchdog
            report = Watchdog(box).run_once(fix=args.fix)
            _emit(report)
            return 0 if report["ok"] else 1
        elif args.cmd == "config-get":
            _emit({args.key: admin.get_dynamic_config(args.key)})
        elif args.cmd == "config-set":
            value: Any = args.value
            try:
                value = json.loads(args.value)
            except json.JSONDecodeError:
                pass
            admin.update_dynamic_config(args.key, value)
            # persist: later CLI invocations replay this record
            from .engine.durability import config_record
            box.stores.wal.append(config_record(args.key, value))
            _emit({args.key: value})
        elif args.cmd == "dlq-read":
            from .engine.replication import REPLICATION_DLQ
            entries = box.stores.queue.read(REPLICATION_DLQ, 0, 10_000)
            _emit([{"index": i, "workflow_id": e.task.workflow_id,
                    "run_id": e.task.run_id,
                    "first_event_id": e.task.first_event_id,
                    "next_event_id": e.task.next_event_id,
                    "error": e.error}
                   for i, e in entries])
        elif args.cmd == "dlq-purge":
            from .engine.replication import REPLICATION_DLQ
            _emit({"purged": box.stores.queue.purge(REPLICATION_DLQ)})
        elif args.cmd == "dlq-merge":
            # re-apply quarantined tasks; only still-failing ones remain
            # (dlq_handler.go merge semantics)
            from .engine.replication import (
                REPLICATION_DLQ,
                HistoryReplicator,
                ReplayError,
                RetryReplicationError,
            )
            replicator = HistoryReplicator(box.stores,
                                           rebuilder=box.rebuilder,
                                           notifier=box.notifier)
            entries = [e for _, e in box.stores.queue.read(
                REPLICATION_DLQ, 0, 10_000)]
            applied, still_failed = 0, []
            for entry in entries:
                try:
                    replicator.apply(entry.task)
                    applied += 1
                except (RetryReplicationError, ReplayError) as exc:
                    still_failed.append((entry, str(exc)))
            box.stores.queue.purge(REPLICATION_DLQ)
            for entry, _err in still_failed:
                box.stores.queue.enqueue(REPLICATION_DLQ, entry)
            _emit({"applied": applied, "still_failed": len(still_failed)})
        elif args.cmd == "dlq":
            if args.http:
                from .rpc.wire import call as wire_call
                h, p = args.http.rsplit(":", 1)
                _emit(wire_call((h, int(p)), ("admin_dlq", args.action),
                                timeout=60))
                return 0
            from .engine.replication import (
                HistoryReplicator,
                ReplicationPublisher,
                ReplicationTaskProcessor,
            )
            proc = ReplicationTaskProcessor(
                HistoryReplicator(box.stores, rebuilder=box.rebuilder,
                                  notifier=box.notifier),
                ReplicationPublisher(box.stores), box.stores, tpu=box.tpu)
            proc.metrics = box.metrics
            _emit(proc.redrive_dlq() if args.action == "redrive"
                  else proc.dlq_summary())
        elif args.cmd == "profile":
            # pprof → JAX profiler (SURVEY §5): capture an XLA trace of a
            # representative replay; the trace opens in TensorBoard's
            # profile plugin or Perfetto
            import time as _time

            import jax
            import numpy as np

            from .gen.corpus import generate_corpus
            from .ops.encode import LANE_EVENT_ID, encode_corpus
            from .native.wirec import pack_wirec_auto
            from .ops.replay import replay_wirec_to_crc

            histories = generate_corpus("basic",
                                        num_workflows=args.workflows,
                                        seed=1, target_events=args.events)
            events = encode_corpus(histories)
            corpus = pack_wirec_auto(events)
            import jax.numpy as jnp
            arrs = (jnp.asarray(corpus.slab), jnp.asarray(corpus.bases),
                    jnp.asarray(corpus.n_events))
            # warm (compile outside the trace: the trace should show the
            # steady-state kernel, not the compiler)
            np.asarray(replay_wirec_to_crc(*arrs, corpus.profile,
                                           box.config.payload_layout())[0])
            jax.profiler.start_trace(args.out)
            t0 = _time.perf_counter()
            crc, _err = replay_wirec_to_crc(*arrs, corpus.profile,
                                            box.config.payload_layout())
            np.asarray(crc)
            wall = _time.perf_counter() - t0
            jax.profiler.stop_trace()
            real = int((events[:, :, LANE_EVENT_ID] > 0).sum())
            # leg breakdown (pack/h2d/kernel/readback): run the same
            # corpus through the instrumented host path so the XLA trace
            # ships with the histogram decomposition of its launch; the
            # first pass pays the compile, then the registry is cleared so
            # `legs` reports only the warm steady-state launch
            from .ops.replay import replay_corpus
            from .utils.metrics import DEFAULT_REGISTRY
            from .utils.profiler import ReplayProfiler
            replay_corpus(histories, box.config.payload_layout())  # warm
            DEFAULT_REGISTRY.reset()
            replay_corpus(histories, box.config.payload_layout())
            _emit({"trace_dir": args.out, "workflows": args.workflows,
                   "events": real, "wall_s": round(wall, 4),
                   "events_per_sec": round(real / wall),
                   "platform": jax.devices()[0].platform,
                   "legs": ReplayProfiler().summary()})
        elif args.cmd == "resident":
            # mirror of `admin profile` for the resident-state cache:
            # optional verify passes drive the cache (cold seed, then
            # warm hits), then the occupancy/hit-rate/budget rollup
            passes = []
            for _ in range(args.passes):
                r = admin.verify()
                passes.append({"total": r.total,
                               "verified_on_device": r.verified_on_device,
                               "resident_served": len(r.resident),
                               "ok": r.ok})
            _emit({"passes": passes, **admin.resident()})
        elif args.cmd == "serving":
            # the device-serving tier rollup (engine/serving.py):
            # coalescing factor, queue, path mix, parity counters
            _emit(admin.serving())
        elif args.cmd == "cluster":
            # in-process arm (no --host): the box's per-host shard
            # ownership + resident/migration rollup; --drain runs the
            # same planned-rebalance snapshot sweep the wire arm's
            # admin_drain op does (one verify pass seeds the pool
            # first, like `admin snapshot --sweep`)
            out = {}
            if args.drain:
                admin.verify()
                sweep = box.tpu.snapshot_sweep(force=True)
                out["drain"] = {"considered": sweep.considered,
                                "snapshotted": sweep.written,
                                "skipped": sweep.considered
                                - sweep.written}
            _emit({**out, **admin.cluster(detail=args.detail)})
        elif args.cmd == "visibility":
            # the device-visibility tier rollup
            # (engine/visibility_device.py): columns, backlog, path
            # mix, parity + compile-cache counters
            _emit(admin.visibility())
        elif args.cmd == "snapshot":
            # snapshot-tier rollup (engine/snapshot.py); --sweep first
            # seeds the resident pool via one verify pass and persists a
            # record per resident workflow (checksum-gated), then the
            # WAL carries a warm start for the next recovery
            out = {}
            if args.sweep:
                r = admin.verify()
                sweep = box.tpu.snapshot_sweep(force=True)
                out["sweep"] = {"verified_on_device":
                                r.verified_on_device,
                                "considered": sweep.considered,
                                "written": sweep.written,
                                "skipped_checksum":
                                sweep.skipped_checksum}
            _emit({**out, **admin.snapshot()})
        elif args.cmd == "top":
            # in-process arm: the box's sampler folds one more window
            # (build → now) and the summary renders from it
            _emit(admin.top())
        elif args.cmd == "hostprof":
            # in-process arm: burst-sample THIS process for --duration
            # and report the subsystem attribution + GIL estimate
            _emit(admin.hostprof(duration_s=args.duration))
        elif args.cmd == "flightrec":
            # in-process arm: whatever the box's workload emitted into
            # the process-global ring (CLI batch ops, fsck, breakers)
            _emit(admin.flightrec(last_n=args.last,
                                  dump=args.dump or None))
        elif args.cmd == "failover":
            # flip the domain active to --to on THIS cluster's metadata
            # and regenerate the promoted side's tasks (the CLI arm of
            # adminFailoverCommands; the managed coordinator is
            # engine/failovermanager.py over a cluster group)
            info = box.frontend.update_domain(args.domain,
                                              active_cluster=args.to)
            from .engine.task_refresher import sweep_refresh
            refreshed = sweep_refresh(box.stores, box.route, info.domain_id)
            _emit({"domain": args.domain, "active_cluster": args.to,
                   "failover_version": info.failover_version,
                   "tasks_refreshed": refreshed})

    elif args.group == "canary":
        from .engine.canary import Canary
        try:
            box.frontend.register_domain(args.domain)
        except Exception:
            pass  # already registered
        canary = Canary(box.frontend, args.domain, pump=box.pump_once)
        report = canary.run(args.cycles, interval_s=args.interval)
        _emit(report.summary())
        return 0 if report.ok else 1
    return 0


def _cluster_tool(args) -> int:
    """`admin cluster --host H:P [--host ...]` — the wire arm: each live
    ServiceHost answers the admin_cluster op with its shard ownership,
    serving/resident occupancy, and migration counters; --drain first
    runs the planned-rebalance snapshot sweep on every host."""
    from .rpc.wire import call as wire_call

    doc = {}
    rc = 0
    for spec in args.host:
        h, p = spec.rsplit(":", 1)
        address = (h, int(p))
        try:
            if args.drain:
                wire_call(address, ("admin_drain",), timeout=60)
            per_host = wire_call(address,
                                 ("admin_cluster", args.detail),
                                 timeout=30)
            if "resident_rows" in per_host:
                per_host["resident_rows"] = {
                    "|".join(k): v
                    for k, v in per_host["resident_rows"].items()}
            doc[spec] = per_host
        except Exception as exc:
            doc[spec] = {"error": f"{type(exc).__name__}: {exc}"}
            rc = 1
    _emit(doc)
    return rc


def _top_tool(args) -> int:
    """`admin top --http [NAME=]H:P [--http ...]` — the fleet arm: scrape
    every named host's /timeseries, summarize (utilization, binding
    resource, burn rates), aggregate cluster-wide. Exit 1 iff any host
    failed to scrape."""
    from .engine.admin import fleet_top

    endpoints = {}
    for spec in args.http:
        name, _, endpoint = spec.rpartition("=")
        endpoints[name or endpoint] = endpoint
    doc = fleet_top(endpoints)
    _emit(doc)
    return 1 if any("error" in s for s in doc["hosts"].values()) else 0


def _telemetry_tool(args) -> int:
    """`admin hostprof --host H:P` / `admin flightrec --host H:P` — the
    wire arms over the admin_hostprof / admin_flightrec ops."""
    from .rpc.wire import call as wire_call

    h, p = args.host.rsplit(":", 1)
    address = (h, int(p))
    try:
        if args.cmd == "hostprof":
            doc = wire_call(address, ("admin_hostprof", args.duration),
                            timeout=30)
        else:
            doc = wire_call(address,
                            ("admin_flightrec", args.last,
                             args.dump or None),
                            timeout=30)
    except Exception as exc:
        _emit({"host": args.host,
               "error": f"{type(exc).__name__}: {exc}"})
        return 1
    _emit(doc)
    return 0


def _fuzz_tool(args) -> int:
    """`fuzz run` / `fuzz shrink` / `fuzz promote` (gen/fuzz.py,
    gen/shrink.py, gen/interleave.py): exit 0 iff the run's gates held
    (zero oracle<->device divergence, all 13 decision types covered,
    clean interleaving when requested)."""
    _ensure_jax_backend()
    from .gen import fuzz as fuzz_mod

    if args.cmd == "run":
        profiles = ((args.profile,) if args.profile
                    else fuzz_mod.PROFILES)
        doc = fuzz_mod.parity_run(
            seeds=args.seeds, workflows_per_seed=args.workflows,
            target_events=args.events, profiles=profiles)
        if args.interleave:
            from .gen.interleave import interleave_scenario
            ilv = interleave_scenario(seed=args.interleave_seed)
            doc["interleave"] = ilv
            doc["ok"] = bool(doc["ok"] and ilv["ok"])
        if args.replication:
            from .gen.interleave import replication_interleave_scenario
            rilv = replication_interleave_scenario(
                seed=args.replication_seed)
            doc["replication_interleave"] = rilv
            doc["ok"] = bool(doc["ok"] and rilv["ok"])
        if args.record or args.out:
            doc["trajectory"] = fuzz_mod.write_fuzz_trajectory(
                doc, path=args.out or None)
        _emit(doc)
        return 0 if doc["ok"] else 1

    if args.cmd == "cluster":
        from .gen import cluster_chaos as cc

        if args.shrink:
            # harness-validation arm: prove the campaign shrinker on
            # the injected kill-then-signal regression, no cluster
            campaign = cc.build_campaign(
                args.seed, num_workflows=args.workflows,
                signals_per_wf=args.signals, num_hosts=args.hosts,
                kills=max(1, args.kills), store_kills=args.store_kills,
                partitions=args.partitions, flaps=args.flaps,
                profile=args.profile)
            poison = cc.pick_poison_wf(campaign)
            if poison is None:
                _emit({"ok": False,
                       "note": "campaign has no signal after a kill — "
                               "pick another seed"})
                return 1
            report = cc.shrink_campaign(
                args.seed, cc.injected_regression_predicate(poison),
                num_workflows=args.workflows,
                signals_per_wf=args.signals, num_hosts=args.hosts,
                kills=max(1, args.kills), store_kills=args.store_kills,
                partitions=args.partitions, flaps=args.flaps,
                profile=args.profile)
            minimal = report.reproduce()
            _emit({"ok": report.shrunk_ops == 2, "poison_wf": poison,
                   "minimal_ops": [op.as_dict() for op in minimal],
                   **report.summary()})
            return 0 if report.shrunk_ops == 2 else 1

        doc = cc.cluster_campaign_scenario(
            seed=args.seed, num_hosts=args.hosts, num_shards=args.shards,
            num_workflows=args.workflows, signals_per_wf=args.signals,
            kills=args.kills, store_kills=args.store_kills,
            partitions=args.partitions, flaps=args.flaps,
            profile=args.profile, regions=args.regions,
            shrink_on_failure=args.shrink_on_failure)
        if args.record or args.out:
            doc["trajectory"] = cc.write_chaos_trajectory(
                doc, path=args.out or None)
        _emit(doc)
        return 0 if doc["ok"] else 1

    if args.cmd == "shrink":
        from .gen import shrink as shrink_mod
        predicate = (shrink_mod.poisoned_parity_predicate(args.poison)
                     if args.poison else shrink_mod.parity_predicate())
        full = fuzz_mod.generate_fuzz_history(args.seed, args.index,
                                              args.events, args.profile)
        if not predicate(full):
            _emit({"seed": args.seed, "workflow_index": args.index,
                   "profile": args.profile, "failing": False,
                   "note": "history does not fail the predicate — "
                           "nothing to shrink"})
            return 0
        report = shrink_mod.shrink_history(
            args.seed, args.index, predicate,
            target_events=args.events, profile=args.profile)
        _emit({"failing": True, **report.summary()})
        return 0

    # promote
    spec = fuzz_mod.make_spec(args.name, args.seed, args.workflows,
                              args.events, profile=args.profile,
                              note=args.note)
    path = fuzz_mod.save_spec(spec, root=args.root)
    _emit({"promoted": spec.name, "path": path, "seed": spec.seed,
           "workflows": spec.workflows, "target_events": spec.target_events,
           "profile": spec.profile, "digest": spec.digest})
    return 0


def _load_tool(args) -> int:
    """`load run` / `load overload` (cadence_tpu/loadgen/scenarios.py):
    exit 0 iff the scenario's gate held (SLOs, shed ratio, zero
    checksum divergence)."""
    _ensure_jax_backend()
    from .loadgen import report as lg_report
    from .loadgen import scenarios

    if args.cmd == "serving":
        doc = scenarios.serving_scenario(
            duration_s=args.duration, rps=args.rps, workers=args.workers,
            pool_size=args.pool_size, seed=args.seed)
    elif args.cmd == "visibility":
        doc = scenarios.visibility_scenario(
            duration_s=args.duration, rps=args.rps, workers=args.workers,
            pool_size=args.pool_size, seed=args.seed,
            staleness_bound=args.staleness_bound)
    elif args.cmd == "cluster":
        doc = scenarios.cluster_serving_scenario(
            duration_s=args.duration, num_hosts=args.hosts, rps=args.rps,
            pool_size=args.pool_size, kill_at_frac=args.kill_at,
            seed=args.seed, p99_slo_ms=args.p99_slo_ms,
            workers=args.workers, hydration_floor=args.hydration_floor)
    elif args.cmd == "region":
        doc = scenarios.region_failover_scenario(
            duration_s=args.duration, num_hosts=args.hosts, rps=args.rps,
            pool_size=args.pool_size, kill_at_frac=args.kill_at,
            seed=args.seed, p99_slo_ms=args.p99_slo_ms,
            workers=args.workers, hydration_floor=args.hydration_floor,
            max_repl_lag=args.max_repl_lag, verify=not args.no_verify)
    elif args.cmd == "overload":
        doc = scenarios.overload_scenario(
            duration_s=args.duration, num_hosts=args.hosts,
            victim_rps=args.victim_rps,
            aggressor_quota_rps=args.aggressor_quota_rps,
            overdrive=args.overdrive, chaos_spec=args.chaos,
            store_fault_spec=args.store_faults,
            seed=args.seed, victim_p99_slo_ms=args.victim_p99_slo_ms,
            workers=args.workers, verify=not args.no_verify)
    else:
        doc = scenarios.mixed_scenario(
            duration_s=args.duration, num_hosts=args.hosts,
            domains=[d for d in args.domains.split(",") if d],
            rps_per_domain=args.rps, chaos_spec=args.chaos,
            seed=args.seed, p99_slo_ms=args.p99_slo_ms,
            workers=args.workers, verify=not args.no_verify,
            mix_name=args.mix)
    if args.record or args.out:
        path = lg_report.write_trajectory(doc, path=args.out or None)
        doc["trajectory"] = path
    _emit(doc)
    return 0 if doc["ok"] else 1


def _wal_tool(args) -> int:
    """WAL scan/clean (adminDBScanCommand/adminDBCleanCommand over the
    one WAL backend): scan reports record-type counts, schema version,
    unparseable lines, and tombstoned runs; clean rewrites the log
    dropping corrupt lines and records superseded by delete tombstones
    (atomic replace, like the schema migrator)."""
    import json as _json

    from .engine.durability import (
        WAL_VERSION,
        SchemaVersionError,
        SqliteLog,
        is_sqlite_path,
        migrate_records,
        version_record,
    )

    if args.cmd == "crashsim":
        from .engine.crashsim import CrashSim, seed_workload
        if args.seed_workload:
            if os.path.exists(args.wal):
                _emit({"error": f"refusing to seed over existing WAL "
                                f"{args.wal}"})
                return 1
            seed_workload(args.wal, num_workflows=args.seed_workload)
        if not os.path.exists(args.wal):
            _emit({"error": f"no WAL at {args.wal}"})
            return 1
        report = CrashSim(args.wal).run(torn=not args.no_torn,
                                        stride=args.stride)
        _emit(report.summary())
        return 0 if report.ok else 1

    if not os.path.exists(args.wal):
        _emit({"error": f"no WAL at {args.wal}"})
        return 1

    if args.cmd == "fsck":
        from .engine.walcheck import fsck
        report = fsck(args.wal)
        out = report.as_dict()
        if report.recovery is not None:
            out["executions_rebuilt"] = report.recovery.executions_rebuilt
            out["open_workflows"] = report.recovery.open_workflows
        _emit(out)
        return 0 if report.ok else 1
    records, bad = [], 0
    if is_sqlite_path(args.wal):
        raw_lines = SqliteLog.read_raw(args.wal)
    else:
        with open(args.wal, "r", encoding="utf-8") as fh:
            raw_lines = [l.strip() for l in fh if l.strip()]
    for line in raw_lines:
        try:
            records.append(_json.loads(line))
        except Exception:
            bad += 1
    by_type: dict = {}
    version = 1
    tombstoned = set()
    for rec in records:
        by_type[rec.get("t", "?")] = by_type.get(rec.get("t", "?"), 0) + 1
        if rec.get("t") == "ver":
            version = rec["v"]
        elif rec.get("t") == "delw":
            tombstoned.add((rec["d"], rec["w"], rec["r"]))

    if args.cmd == "scan":
        _emit({"wal": args.wal, "records": len(records),
               "bad_lines": bad, "schema_version": version,
               "binary_version": WAL_VERSION,
               "by_type": by_type, "tombstoned_runs": len(tombstoned),
               "bytes": os.path.getsize(args.wal)})
        return 0 if bad == 0 else 1

    # clean: drop corrupt lines + every record of a tombstoned run (and
    # the tombstone itself — replay without both is equivalent). Kept
    # records are MIGRATED to WAL_VERSION before the header is written:
    # positional labeling means anything under the header claims the
    # header's version, so rewriting a v1 prefix unmigrated would
    # re-label it current-version — exactly the corruption `wal fsck`
    # flags as stale-migration-label.
    def run_key(rec):
        if rec.get("t") in ("h", "f", "cb", "cur", "delw"):
            return (rec.get("d"), rec.get("w"), rec.get("r"))
        return None

    try:
        migrated, _original = migrate_records(records)
    except SchemaVersionError as exc:
        _emit({"error": str(exc)})
        return 1
    kept = [rec for rec in migrated if run_key(rec) not in tombstoned]
    if is_sqlite_path(args.wal):
        SqliteLog.rewrite(args.wal, [version_record()] + kept)
    else:
        tmp = args.wal + ".clean"
        with open(tmp, "w", encoding="utf-8") as fh:
            fh.write(_json.dumps(version_record(),
                                 separators=(",", ":")) + "\n")
            for rec in kept:
                fh.write(_json.dumps(rec, separators=(",", ":")) + "\n")
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, args.wal)
    _emit({"cleaned": args.wal, "dropped_bad_lines": bad,
           "dropped_records": len(records) - len(kept),
           "schema_version": WAL_VERSION, "kept": len(kept) + 1})
    return 0


if __name__ == "__main__":
    sys.exit(main())
