"""wirec: the compressed host→device wire format (columnar, adaptive width).

The host link is the product bottleneck (a tunneled TPU host moves
~15MB/s), and wire32 spends 80 B/event on lanes whose information content
is a handful of bits: event ids advance by 1, timestamps by a fixed tick,
half the lanes are constant per corpus. wirec exploits that shape the way
the reference's serializers exploit thrift compactness
(common/persistence/serialization/, parquet-style columnar encoding) —
but decodes ON DEVICE with pure vectorized XLA ops, so the dense form
never crosses the link.

Format. A corpus [W, E, NUM_LANES] int64 becomes:
  - slab   [W, E, B] uint8 — per-lane byte-columns, little-endian two's
           complement at each lane's minimal width (1..8 bytes);
  - bases  [W, K] int64 — per-workflow first-row values for delta/ts-rel
           lanes (amortized over E events);
  - n_events [W] int32 — real-row counts (tail padding is reconstructed,
           never shipped);
  - profile — a static per-lane plan, chosen at pack time by measuring
           the corpus:
      * CONST  c        : every real value equals c; 0 bytes on the wire.
      * ABS    v = q*s  : values divided by their GCD s, stored at the
                          minimal width for the quotient.
      * DELTA  v = cumsum(q*s) + base : row-to-row differences (event
                          ids, timestamps, task ids), GCD-scaled — a 1ns
                          tick stream ships 1 byte/event regardless of
                          the 8-byte absolute magnitude.
      * TSREL_NZ        : sparse absolute-nanos lanes (expiration
                          timestamps): zero stays zero, nonzero values
                          are GCD-scaled offsets from the workflow's
                          first timestamp.

Decoding is exact: every transform is integer-reversible, so the decoded
tensor is bit-identical to the int64 lane tensor (tests assert equality
and CRC parity with the wire32 path). Widths are chosen from the actual
data, so pathological corpora degrade gracefully toward raw width-8
columns instead of failing.

The profile is a hashable static jit argument: one compiled executable
per (shape, profile), shared by every chunk of a homogeneous stream (the
feeder refits and recompiles only when a chunk's values fall outside the
profile — measured, never silent).
"""
from __future__ import annotations

import threading
from typing import NamedTuple, Optional, Tuple

import numpy as np

from .encode import LANE_EVENT_ID, LANE_EVENT_TYPE, LANE_TIMESTAMP, NUM_LANES

KIND_CONST = 0
KIND_ABS = 1
KIND_DELTA = 2
KIND_TSREL_NZ = 3

#: reconstructed value of each lane in tail-padding rows
PAD_VALUES = tuple(-1 if lane == LANE_EVENT_TYPE else 0
                   for lane in range(NUM_LANES))


class LaneCode(NamedTuple):
    """One lane's static decode plan."""

    lane: int
    kind: int
    offset: int      # byte offset inside the slab row (unused for CONST)
    width: int       # bytes per event (0 for CONST)
    scale: int       # GCD the stored quotient multiplies back by
    const: int       # CONST value
    base_index: int  # column in `bases` (-1 when no base is needed)


class WirecCorpus(NamedTuple):
    slab: np.ndarray       # [W, E, B] uint8
    bases: np.ndarray      # [W, K] int64
    n_events: np.ndarray   # [W] int32
    profile: Tuple[LaneCode, ...]

    @property
    def wire_bytes(self) -> int:
        return self.slab.nbytes + self.bases.nbytes + self.n_events.nbytes

    def bytes_per_event(self) -> float:
        real = int(self.n_events.sum())
        return self.wire_bytes / real if real else float("inf")


class ProfileMisfit(Exception):
    """A chunk's values exceed the pinned profile's widths/scales; the
    caller refits (recompute + recompile) — measured, never silent."""


def _width_for(lo: int, hi: int) -> int:
    """Minimal little-endian two's-complement byte width holding [lo, hi]."""
    for w in range(1, 8):
        if -(1 << (8 * w - 1)) <= lo and hi < (1 << (8 * w - 1)):
            return w
    return 8


def _gcd_scale(vals: np.ndarray) -> int:
    """GCD of |vals| (1 when empty/all-zero): the exact common tick."""
    if vals.size == 0:
        return 1
    g = int(np.gcd.reduce(np.abs(vals)))
    return g if g > 0 else 1


def _delta_codes(v: np.ndarray, mask: np.ndarray) -> np.ndarray:
    """Row-to-row differences with the real→pad cliff zeroed (pad rows
    carry delta 0 — the decoder's pad mask reconstructs their values, so
    only the width matters and zero always fits). d[:, 0] is 0 by
    construction: the workflow base ships in `bases`."""
    d = v.copy()
    d[:, 1:] -= v[:, :-1]
    d[:, 0] = 0
    return np.where(mask, d, 0)


def _plan_lane(v: np.ndarray, mask: np.ndarray, n: np.ndarray,
               ts_base: np.ndarray) -> Tuple[int, int, int, int]:
    """Choose (kind, width, scale, const) for one lane's [W, E] values.
    Only real rows matter — padding is reconstructed from n_events."""
    real = v[mask]
    if real.size == 0 or (real == real.flat[0]).all():
        return KIND_CONST, 0, 1, (int(real.flat[0]) if real.size else 0)

    g_abs = _gcd_scale(real)
    w_abs = _width_for(int(real.min()) // g_abs, int(real.max()) // g_abs)

    d = _delta_codes(v, mask)
    g_d = _gcd_scale(d[mask])
    dq = d[mask] // g_d
    w_d = _width_for(int(dq.min()), int(dq.max())) if dq.size else 1

    best = (KIND_ABS, w_abs, g_abs, 0)
    if w_d < w_abs:
        best = (KIND_DELTA, w_d, g_d, 0)

    # sparse absolute-nanos lanes: zeros + huge values (expiration stamps)
    if (real == 0).any() and (np.abs(real) > 1 << 31).any():
        rel = (v - ts_base[:, None])[mask & (v != 0)]
        g_ts = _gcd_scale(rel)
        q = rel // g_ts
        code_lo = min(int(q.min()), 0)
        code_hi = max(int(q.max()) + 1, 0)
        w_ts = _width_for(code_lo, code_hi)
        if w_ts < best[1] or (best[0] == KIND_DELTA and w_ts == best[1]):
            best = (KIND_TSREL_NZ, w_ts, g_ts, 0)
    return best


def _emit(slab: np.ndarray, off: int, width: int, code: np.ndarray) -> None:
    """Write [W, E] int64 codes as `width` little-endian bytes."""
    u = code.astype(np.uint64)
    for k in range(width):
        slab[:, :, off + k] = ((u >> np.uint64(8 * k))
                               & np.uint64(0xFF)).astype(np.uint8)


def _lane_codes(v: np.ndarray, mask: np.ndarray, n: np.ndarray,
                ts_base: np.ndarray, kind: int, scale: int
                ) -> Tuple[np.ndarray, Optional[np.ndarray]]:
    """The stored quotient grid for one lane, plus the per-workflow base
    column (or None). Pad-row codes are whatever falls out of the raw
    values (ABS) or zero (DELTA/TSREL) — the decoder's pad mask makes
    their decoded value irrelevant; only the byte width must hold them,
    and pad values are 0/-1."""
    if kind == KIND_ABS:
        return v // scale if scale != 1 else v, None
    if kind == KIND_DELTA:
        d = _delta_codes(v, mask)
        return d // scale if scale != 1 else d, v[:, 0].copy()
    if kind == KIND_TSREL_NZ:
        q = (v - ts_base[:, None]) // scale
        code = np.where(q >= 0, q + 1, q)
        return np.where(mask & (v != 0), code, 0), ts_base.copy()
    raise ValueError(f"kind {kind} emits no codes")


def _check_fit(code: np.ndarray, width: int) -> bool:
    lo, hi = -(1 << (8 * width - 1)), (1 << (8 * width - 1)) - 1
    return bool((code >= lo).all() and (code <= hi).all())


def _pack_rows(ev: np.ndarray, mask: np.ndarray, n: np.ndarray,
               ts_base: np.ndarray, profile: Tuple[LaneCode, ...],
               slab: np.ndarray, bases: np.ndarray) -> None:
    """Emit every lane of a [w, E, L] row block into its slab/bases slice
    (each transform is per-workflow-row, so blocks are independent)."""
    for e in profile:
        v = ev[:, :, e.lane]
        if e.kind == KIND_CONST:
            if mask.any() and not (v[mask] == e.const).all():
                raise ProfileMisfit(f"lane {e.lane}: non-const under CONST")
            continue
        code, base = _lane_codes(v, mask, n, ts_base, e.kind, e.scale)
        # exactness: the quotient must reproduce the value on REAL rows
        # (scale divides evenly) — pad rows are reconstructed by mask
        if e.scale != 1 or e.kind == KIND_TSREL_NZ:
            if e.kind == KIND_ABS:
                bad = (code * e.scale != v) & mask
            elif e.kind == KIND_DELTA:
                bad = (code * e.scale != _delta_codes(v, mask)) & mask
            else:  # KIND_TSREL_NZ: undo the zero-escape bias
                m = code - (code >= 1)
                bad = ((m * e.scale + ts_base[:, None] != v)
                       & mask & (v != 0))
            if bad.any():
                raise ProfileMisfit(f"lane {e.lane}: scale {e.scale} misfit")
        if not _check_fit(code, e.width):
            raise ProfileMisfit(f"lane {e.lane}: width {e.width} overflow")
        _emit(slab, e.offset, e.width, code)
        if base is not None:
            bases[:, e.base_index] = base


#: minimum rows per thread block: below this the pool overhead beats the
#: numpy-releases-the-GIL parallelism win
_MIN_BLOCK_ROWS = 256

#: process-lifetime pack pools by worker count — the wirec feeder calls
#: pack_wirec once per chunk, so per-call pool spawn/join would be pure
#: overhead on the exact path this parallelism is optimizing
_POOLS: dict = {}
_POOLS_LOCK = threading.Lock()


def _pack_pool(threads: int):
    with _POOLS_LOCK:
        pool = _POOLS.get(threads)
        if pool is None:
            from concurrent.futures import ThreadPoolExecutor
            pool = _POOLS[threads] = ThreadPoolExecutor(
                max_workers=threads, thread_name_prefix="wirec-pack")
        return pool


def pack_wirec(events64: np.ndarray,
               profile: Optional[Tuple[LaneCode, ...]] = None,
               num_threads: Optional[int] = None) -> WirecCorpus:
    """[W, E, NUM_LANES] int64 → WirecCorpus.

    With `profile` pinned (streaming chunks sharing one executable), the
    chunk is packed under that plan; values that don't fit its
    widths/scales raise ProfileMisfit so the caller refits explicitly.

    `num_threads` > 1 enables the chunk-parallel path: lane PLANNING fans
    out per lane and EMIT fans out over workflow-row blocks (every
    transform — delta, GCD scaling, ts-rel — is per-workflow, so blocks
    are independent and the packed bytes are identical to the serial
    path). numpy releases the GIL inside the ufunc loops, so host packing
    scales with cores instead of pinning one. `None` resolves through the
    one CADENCE_TPU_PACK_THREADS knob (utils/concurrency.pack_threads);
    small corpora stay serial either way (_MIN_BLOCK_ROWS).
    """
    from ..utils.concurrency import pack_threads

    ev = np.asarray(events64, dtype=np.int64)
    W, E, L = ev.shape
    assert L == NUM_LANES, f"expected {NUM_LANES} lanes, got {L}"
    n = (ev[:, :, LANE_EVENT_ID] > 0).sum(axis=1).astype(np.int32)
    mask = np.arange(E)[None, :] < n[:, None]
    # row 0 is real whenever n > 0, so the first-row value IS the base
    ts_base = ev[:, 0, LANE_TIMESTAMP]

    threads = pack_threads(num_threads)
    if W < 2 * _MIN_BLOCK_ROWS:
        threads = 1
    pool = _pack_pool(threads) if threads > 1 else None

    if profile is None:
        if pool is not None:
            plans = list(pool.map(
                lambda lane: _plan_lane(ev[:, :, lane], mask, n, ts_base),
                range(NUM_LANES)))
        else:
            plans = [_plan_lane(ev[:, :, lane], mask, n, ts_base)
                     for lane in range(NUM_LANES)]
        off = 0
        base_cols = 0
        entries = []
        for lane, (kind, width, scale, const) in enumerate(plans):
            bi = -1
            if kind in (KIND_DELTA, KIND_TSREL_NZ):
                bi = base_cols
                base_cols += 1
            entries.append(LaneCode(lane, kind, off if width else 0,
                                    width, scale, const, bi))
            off += width
        profile = tuple(entries)

    B = sum(e.width for e in profile)
    K = sum(1 for e in profile if e.base_index >= 0)
    slab = np.zeros((W, E, B), dtype=np.uint8)
    bases = np.zeros((W, K), dtype=np.int64)
    if pool is not None:
        block = max(_MIN_BLOCK_ROWS, -(-W // threads))
        bounds = [(lo, min(lo + block, W)) for lo in range(0, W, block)]
        list(pool.map(
            lambda b: _pack_rows(ev[b[0]:b[1]], mask[b[0]:b[1]],
                                 n[b[0]:b[1]], ts_base[b[0]:b[1]],
                                 profile, slab[b[0]:b[1]],
                                 bases[b[0]:b[1]]),
            bounds))
    else:
        _pack_rows(ev, mask, n, ts_base, profile, slab, bases)
    return WirecCorpus(slab, bases, n, profile)


def gather_corpus(corpus: WirecCorpus, indices,
                  pad_workflows: int = 0,
                  pad_events: int = 0) -> WirecCorpus:
    """Gather flagged rows into a compact sub-corpus under the SAME
    profile (engine/ladder.py's wirec leg): the widened-K re-replay
    decodes the identical bytes, so gather+re-replay is byte-equivalent
    to the rows' original decode. The event axis trims to the flagged
    rows' longest real history; padding rows carry n_events = 0 (the
    decoder masks every event past n_events to no-op lanes), letting
    padded shapes pow2-bucket for executable reuse."""
    idx = np.asarray(indices, dtype=np.int64)
    n = corpus.n_events[idx]
    e_real = int(n.max()) if len(idx) else 1
    e_real = max(e_real, 1)
    E = max(e_real, pad_events)
    W = max(len(idx), pad_workflows)
    slab = np.zeros((W, E, corpus.slab.shape[2]), dtype=np.uint8)
    bases = np.zeros((W, corpus.bases.shape[1]), dtype=np.int64)
    n_events = np.zeros((W,), dtype=np.int32)
    slab[:len(idx), :e_real] = corpus.slab[idx][:, :e_real]
    bases[:len(idx)] = corpus.bases[idx]
    n_events[:len(idx)] = n
    return WirecCorpus(slab, bases, n_events, corpus.profile)


# ---------------------------------------------------------------------------
# Device decode (pure jnp; exact inverse of pack_wirec)
# ---------------------------------------------------------------------------


def _read_le(slab, off: int, width: int):
    """[..., B] uint8 → [...] int64: little-endian, top byte sign-extended
    (explicit arithmetic, identical on CPU and TPU backends)."""
    import jax.numpy as jnp

    v = (slab[..., off + width - 1].astype(jnp.int8).astype(jnp.int64)
         << (8 * (width - 1)))
    for k in range(width - 1):
        v = v | (slab[..., off + k].astype(jnp.int64) << (8 * k))
    return v


def decode_wirec(slab, bases, n_events,
                 profile: Tuple[LaneCode, ...]):
    """Full-tensor decode: [W, E, B] uint8 → [W, E, NUM_LANES] int64,
    bit-identical to the packed corpus (tests assert)."""
    import jax.numpy as jnp

    W, E, _ = slab.shape
    in_real = jnp.arange(E)[None, :] < n_events[:, None]
    lanes = []
    for e in profile:
        if e.kind == KIND_CONST:
            v = jnp.full((W, E), e.const, dtype=jnp.int64)
        else:
            code = _read_le(slab, e.offset, e.width)
            if e.kind == KIND_ABS:
                v = code * e.scale
            elif e.kind == KIND_DELTA:
                v = (jnp.cumsum(code * e.scale, axis=1)
                     + bases[:, e.base_index][:, None])
            else:  # KIND_TSREL_NZ
                m = jnp.where(code >= 1, code - 1, code)
                v = jnp.where(code == 0, 0,
                              m * e.scale + bases[:, e.base_index][:, None])
        lanes.append(jnp.where(in_real, v, PAD_VALUES[e.lane]))
    return jnp.stack(lanes, axis=-1)


def decode_step(sl, prev, bases, n_events, e_idx,
                profile: Tuple[LaneCode, ...]):
    """Scan-fused decode of ONE event column: sl [W, B] uint8 → (ev
    [W, NUM_LANES] int64, new prev [W, n_delta] int64). DELTA lanes carry
    their running value in `prev` instead of a materialized cumsum, so
    the dense tensor never exists in HBM."""
    import jax.numpy as jnp

    W = sl.shape[0]
    in_real = e_idx < n_events
    vals = []
    new_prev = prev
    di = 0
    for e in profile:
        if e.kind == KIND_CONST:
            v = jnp.full((W,), e.const, dtype=jnp.int64)
        else:
            code = _read_le(sl, e.offset, e.width)
            if e.kind == KIND_ABS:
                v = code * e.scale
            elif e.kind == KIND_DELTA:
                v = prev[:, di] + code * e.scale
                new_prev = new_prev.at[:, di].set(v)
                di += 1
            else:
                m = jnp.where(code >= 1, code - 1, code)
                v = jnp.where(code == 0, 0,
                              m * e.scale + bases[:, e.base_index])
        vals.append(jnp.where(in_real, v, PAD_VALUES[e.lane]))
    return jnp.stack(vals, axis=-1), new_prev


def delta_base_columns(profile: Tuple[LaneCode, ...]) -> Tuple[int, ...]:
    """`bases` columns of the DELTA lanes, in profile order (the scan
    carry's initial values)."""
    return tuple(e.base_index for e in profile if e.kind == KIND_DELTA)
