"""Dense replay state: the TPU-resident twin of the oracle's MutableState.

The reference keeps per-workflow mutable state as Go maps and structs
(mutable_state_builder.go:83-172). Here every field is a struct-of-arrays
tensor over the workflow axis W, so one transition step updates all W
workflows in lockstep:

- scalars:        [W]       (execution info + decision state + version)
- pending tables: [W, K]    (activities, timers, children, cancels, signals)
- version history:[W, Kv]   (event id / version item pairs + count)

Capacities K are fixed (PayloadLayout); overflow sets the per-workflow
error flag — measured and reported by the caller, never silent (the host
engine falls back to the oracle replayer for flagged workflows, the analog
of the reference's per-workflow Go path).

The error flag is sticky: a workflow whose history is invalid freezes its
state at the first bad event, mirroring the reference's error return from
ApplyEvents (which aborts that workflow's replay transaction).
"""
from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp

from ..core.checksum import DEFAULT_LAYOUT, PAD, PayloadLayout
from ..core.enums import EMPTY_EVENT_ID, EMPTY_VERSION, FIRST_EVENT_ID, WorkflowState

I64 = jnp.int64
I32 = jnp.int32
BOOL = jnp.bool_


class ActivityTable(NamedTuple):
    """Pending activities; fields mirror oracle ActivityInfo
    (persistence ActivityInfo, dataManagerInterfaces.go:752)."""

    occ: jnp.ndarray            # [W, K] bool
    schedule_id: jnp.ndarray    # [W, K] i64
    started_id: jnp.ndarray     # [W, K] i64
    version: jnp.ndarray        # [W, K] i64
    activity_key: jnp.ndarray   # [W, K] i64 (interned ActivityID)
    scheduled_time: jnp.ndarray # [W, K] i64 nanos
    started_time: jnp.ndarray   # [W, K] i64 nanos
    last_heartbeat: jnp.ndarray # [W, K] i64 nanos
    sched_to_start: jnp.ndarray # [W, K] i64 seconds
    sched_to_close: jnp.ndarray # [W, K] i64 seconds
    start_to_close: jnp.ndarray # [W, K] i64 seconds
    heartbeat: jnp.ndarray      # [W, K] i64 seconds
    cancel_requested: jnp.ndarray  # [W, K] bool
    cancel_request_id: jnp.ndarray # [W, K] i64
    attempt: jnp.ndarray        # [W, K] i64
    timer_status: jnp.ndarray   # [W, K] i32 (TIMER_TASK_STATUS_* bitmask)
    has_retry: jnp.ndarray      # [W, K] bool
    batch_id: jnp.ndarray       # [W, K] i64 (ScheduledEventBatchID)


class TimerTable(NamedTuple):
    """Pending user timers (TimerInfo, dataManagerInterfaces.go:792)."""

    occ: jnp.ndarray          # [W, K] bool
    timer_key: jnp.ndarray    # [W, K] i64 (interned TimerID)
    started_id: jnp.ndarray   # [W, K] i64
    expiry_time: jnp.ndarray  # [W, K] i64 nanos
    task_status: jnp.ndarray  # [W, K] i32
    version: jnp.ndarray      # [W, K] i64


class ChildTable(NamedTuple):
    """Pending child workflows (ChildExecutionInfo, dataManagerInterfaces.go:801)."""

    occ: jnp.ndarray          # [W, K] bool
    initiated_id: jnp.ndarray # [W, K] i64
    started_id: jnp.ndarray   # [W, K] i64
    version: jnp.ndarray      # [W, K] i64
    batch_id: jnp.ndarray     # [W, K] i64


class InitiatedTable(NamedTuple):
    """Pending external request-cancels / signals (RequestCancelInfo /
    SignalInfo, dataManagerInterfaces.go:818,:826)."""

    occ: jnp.ndarray          # [W, K] bool
    initiated_id: jnp.ndarray # [W, K] i64
    version: jnp.ndarray      # [W, K] i64
    batch_id: jnp.ndarray     # [W, K] i64


class ReplayState(NamedTuple):
    """All per-workflow state carried through the event scan."""

    # execution info scalars (checksum-relevant first)
    state: jnp.ndarray                 # [W] i32 WorkflowState
    close_status: jnp.ndarray          # [W] i32 CloseStatus
    cancel_requested: jnp.ndarray      # [W] bool
    last_first_event_id: jnp.ndarray   # [W] i64
    next_event_id: jnp.ndarray         # [W] i64
    last_processed_event: jnp.ndarray  # [W] i64
    signal_count: jnp.ndarray          # [W] i64
    # decision state (mutable_state_decision_task_manager.go)
    decision_version: jnp.ndarray      # [W] i64
    decision_schedule_id: jnp.ndarray  # [W] i64
    decision_started_id: jnp.ndarray   # [W] i64
    decision_attempt: jnp.ndarray      # [W] i64
    decision_timeout: jnp.ndarray      # [W] i64 seconds
    decision_scheduled_ts: jnp.ndarray # [W] i64 nanos
    decision_started_ts: jnp.ndarray   # [W] i64 nanos
    decision_original_scheduled_ts: jnp.ndarray  # [W] i64 nanos
    # other execution info
    workflow_timeout: jnp.ndarray      # [W] i64 seconds
    decision_sts_timeout: jnp.ndarray  # [W] i64 seconds (DecisionStartToCloseTimeout)
    start_timestamp: jnp.ndarray       # [W] i64 nanos
    completion_event_batch_id: jnp.ndarray  # [W] i64
    last_event_task_id: jnp.ndarray    # [W] i64
    workflow_attempt: jnp.ndarray      # [W] i64
    expiration_time: jnp.ndarray       # [W] i64 nanos
    has_parent: jnp.ndarray            # [W] bool
    # version bookkeeping: per-branch item tables (versionHistories.go) —
    # branch axis B supports NDC divergent histories on device; linear
    # histories use branch 0 only
    current_version: jnp.ndarray       # [W] i64
    vh_event_ids: jnp.ndarray          # [W, B, Kv] i64 (PAD-filled)
    vh_versions: jnp.ndarray           # [W, B, Kv] i64 (PAD-filled)
    vh_count: jnp.ndarray              # [W, B] i32
    current_branch: jnp.ndarray        # [W] i32 (versionHistories.current_index)
    # pending tables
    activities: ActivityTable
    timers: TimerTable
    children: ChildTable
    cancels: InitiatedTable
    signals: InitiatedTable
    # sticky error flag (0 = healthy; else ErrorCode of first failure)
    error: jnp.ndarray                 # [W] i32


class ErrorCode:
    """First-failure codes recorded in ReplayState.error."""

    NONE = 0
    INVALID_STATE_TRANSITION = 1
    VERSION_HISTORY_ORDER = 2
    VERSION_HISTORY_OVERFLOW = 3
    MISSING_DECISION = 4
    MISSING_ACTIVITY = 5
    MISSING_TIMER = 6
    MISSING_CHILD = 7
    MISSING_REQUEST_CANCEL = 8
    MISSING_SIGNAL = 9
    TABLE_OVERFLOW = 10
    UNKNOWN_EVENT_TYPE = 11
    INVALID_BACKOFF_INITIATOR = 12
    BRANCH_OVERFLOW = 13
    BAD_FORK = 14


#: error codes a widened-K re-replay can clear (engine/ladder.py): the
#: history is valid, the kernel's fixed capacities just weren't enough.
#: Every other code is a genuine history error no capacity would fix —
#: those go straight to oracle arbitration.
CAPACITY_ERRORS = (
    ErrorCode.VERSION_HISTORY_OVERFLOW,
    ErrorCode.TABLE_OVERFLOW,
    ErrorCode.BRANCH_OVERFLOW,
)


def widen_layout(layout: PayloadLayout, factor: int) -> PayloadLayout:
    """The escalation-rung layout: every kernel capacity multiplied by
    `factor` (the reference's pending maps are unbounded Go maps —
    mutable_state_builder.go — so capacity pressure is purely a device
    artifact; doubling K per rung keeps flagged rows on device instead
    of falling off to the per-workflow Python oracle)."""
    return PayloadLayout(
        max_version_history_items=layout.max_version_history_items * factor,
        max_activities=layout.max_activities * factor,
        max_timers=layout.max_timers * factor,
        max_children=layout.max_children * factor,
        max_request_cancels=layout.max_request_cancels * factor,
        max_signals=layout.max_signals * factor,
        max_branches=layout.max_branches * factor,
    )


def init_state(num_workflows: int, layout: PayloadLayout = DEFAULT_LAYOUT) -> ReplayState:
    """Fresh state for W workflows, matching the oracle's ExecutionInfo
    defaults (oracle/mutable_state.py ExecutionInfo / NewMutableStateBuilder)."""
    W = num_workflows

    def full(shape, value, dtype=I64):
        return jnp.full(shape, value, dtype=dtype)

    def zeros(shape, dtype=I64):
        return jnp.zeros(shape, dtype=dtype)

    Ka, Kt = layout.max_activities, layout.max_timers
    Kc, Kr, Ks = layout.max_children, layout.max_request_cancels, layout.max_signals
    Kv = layout.max_version_history_items
    B = layout.max_branches

    activities = ActivityTable(
        occ=zeros((W, Ka), BOOL),
        schedule_id=zeros((W, Ka)), started_id=zeros((W, Ka)),
        version=zeros((W, Ka)), activity_key=zeros((W, Ka)),
        scheduled_time=zeros((W, Ka)), started_time=zeros((W, Ka)),
        last_heartbeat=zeros((W, Ka)),
        sched_to_start=zeros((W, Ka)), sched_to_close=zeros((W, Ka)),
        start_to_close=zeros((W, Ka)), heartbeat=zeros((W, Ka)),
        cancel_requested=zeros((W, Ka), BOOL), cancel_request_id=zeros((W, Ka)),
        attempt=zeros((W, Ka)), timer_status=zeros((W, Ka), I32),
        has_retry=zeros((W, Ka), BOOL), batch_id=zeros((W, Ka)),
    )
    timers = TimerTable(
        occ=zeros((W, Kt), BOOL), timer_key=zeros((W, Kt)),
        started_id=zeros((W, Kt)), expiry_time=zeros((W, Kt)),
        task_status=zeros((W, Kt), I32), version=zeros((W, Kt)),
    )
    children = ChildTable(
        occ=zeros((W, Kc), BOOL), initiated_id=zeros((W, Kc)),
        started_id=zeros((W, Kc)), version=zeros((W, Kc)),
        batch_id=zeros((W, Kc)),
    )
    cancels = InitiatedTable(
        occ=zeros((W, Kr), BOOL), initiated_id=zeros((W, Kr)),
        version=zeros((W, Kr)), batch_id=zeros((W, Kr)),
    )
    signals = InitiatedTable(
        occ=zeros((W, Ks), BOOL), initiated_id=zeros((W, Ks)),
        version=zeros((W, Ks)), batch_id=zeros((W, Ks)),
    )

    return ReplayState(
        state=full((W,), WorkflowState.Created, I32),
        close_status=zeros((W,), I32),
        cancel_requested=zeros((W,), BOOL),
        last_first_event_id=full((W,), FIRST_EVENT_ID),
        next_event_id=full((W,), FIRST_EVENT_ID),
        last_processed_event=full((W,), EMPTY_EVENT_ID),
        signal_count=zeros((W,)),
        decision_version=full((W,), EMPTY_VERSION),
        decision_schedule_id=full((W,), EMPTY_EVENT_ID),
        decision_started_id=full((W,), EMPTY_EVENT_ID),
        decision_attempt=zeros((W,)),
        decision_timeout=zeros((W,)),
        decision_scheduled_ts=zeros((W,)),
        decision_started_ts=zeros((W,)),
        decision_original_scheduled_ts=zeros((W,)),
        workflow_timeout=zeros((W,)),
        decision_sts_timeout=zeros((W,)),
        start_timestamp=zeros((W,)),
        completion_event_batch_id=full((W,), EMPTY_EVENT_ID),
        last_event_task_id=zeros((W,)),
        workflow_attempt=zeros((W,)),
        expiration_time=zeros((W,)),
        has_parent=zeros((W,), BOOL),
        current_version=full((W,), EMPTY_VERSION),
        vh_event_ids=full((W, B, Kv), PAD),
        vh_versions=full((W, B, Kv), PAD),
        vh_count=zeros((W, B), I32),
        current_branch=zeros((W,), I32),
        activities=activities,
        timers=timers,
        children=children,
        cancels=cancels,
        signals=signals,
        error=zeros((W,), I32),
    )


def layout_of(s: ReplayState) -> PayloadLayout:
    """Recover the PayloadLayout a state was built with (from array shapes)."""
    return PayloadLayout(
        max_version_history_items=s.vh_event_ids.shape[2],
        max_activities=s.activities.occ.shape[1],
        max_timers=s.timers.occ.shape[1],
        max_children=s.children.occ.shape[1],
        max_request_cancels=s.cancels.occ.shape[1],
        max_signals=s.signals.occ.shape[1],
        max_branches=s.vh_event_ids.shape[1],
    )


def widen_state(s: ReplayState, out_layout: PayloadLayout) -> ReplayState:
    """Re-home a carried state at a WIDER layout: every table keeps its
    occupied slots at their original indices and gains empty slots past
    the old capacity (occ False, PAD for version-history items) — so
    replaying appended events from the widened state is exactly replaying
    them with more headroom, never a different history. This is how the
    escalation ladder keeps capacity-flagged RESIDENT states on device
    (engine/resident.py): the pre-append state widens, the suffix
    re-replays at 2K/4K, and the row stays in HBM instead of falling
    back to a full-history re-replay."""
    import jax

    fresh = init_state(s.state.shape[0], out_layout)

    def widen(cur, new):
        if cur.shape == new.shape:
            return cur
        return new.at[tuple(slice(0, d) for d in cur.shape)].set(cur)

    return jax.tree_util.tree_map(widen, s, fresh)


def narrow_ok(s: ReplayState, out_layout: PayloadLayout) -> jnp.ndarray:
    """[W] bool: rows whose state fits `out_layout` EXACTLY — no occupied
    table slot, version-history item, or branch beyond the narrow
    capacities — so narrow_state() on them is lossless (the re-narrow
    half of the ladder's widen/re-narrow round trip: an escalated
    resident row whose pending load drained back under base K returns to
    base-width HBM footprint)."""
    Kv = out_layout.max_version_history_items
    B = out_layout.max_branches
    ok = s.current_branch < B
    if s.vh_count.shape[1] > B:
        ok &= (s.vh_count[:, B:] == 0).all(axis=1)
    ok &= (s.vh_count <= Kv).all(axis=1)
    for table, cap in ((s.activities, out_layout.max_activities),
                       (s.timers, out_layout.max_timers),
                       (s.children, out_layout.max_children),
                       (s.cancels, out_layout.max_request_cancels),
                       (s.signals, out_layout.max_signals)):
        if table.occ.shape[1] > cap:
            ok &= ~table.occ[:, cap:].any(axis=1)
    return ok


def narrow_state(s: ReplayState, out_layout: PayloadLayout) -> ReplayState:
    """Slice a widened state down to `out_layout`. Only valid for rows
    where narrow_ok() holds — slots past the narrow capacities are
    dropped, so an occupied one would silently vanish (callers gate on
    the mask; engine/resident.py keeps non-narrowable rows widened)."""
    import jax

    fresh = init_state(s.state.shape[0], out_layout)

    def narrow(cur, new):
        if cur.shape == new.shape:
            return cur
        return cur[tuple(slice(0, d) for d in new.shape)]

    return jax.tree_util.tree_map(narrow, s, fresh)


def reset_rows(s: ReplayState, mask: jnp.ndarray) -> ReplayState:
    """Blend fresh init values into the rows where `mask` holds — the
    continue-as-new run boundary (the reference builds a brand-new
    mutableStateBuilder for the new run). The sticky error flag survives:
    a chain whose earlier run corrupted stays flagged."""
    import jax

    fresh = init_state(s.state.shape[0], layout_of(s))

    def blend(cur, new):
        m = mask.reshape((-1,) + (1,) * (cur.ndim - 1))
        return jnp.where(m, new, cur)

    out = jax.tree_util.tree_map(blend, s, fresh)
    return out._replace(error=s.error)
