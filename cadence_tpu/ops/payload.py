"""Device-side canonical checksum payload assembly.

Produces, from the dense ReplayState, exactly the same [W, width] int64
payload matrix as the oracle's core/checksum.payload_row (field order per
reference checksum.go:56-113). Pending-ID lists are sorted on device with
jnp.sort — the PAD sentinel is positive-huge, so unoccupied slots sort to
the tail, matching the oracle's [sorted reals..., PAD...] layout.
"""
from __future__ import annotations

import jax.numpy as jnp

from ..core.checksum import DEFAULT_LAYOUT, PAD, PayloadLayout
from ..core.checksum import fnv64 as _fnv64  # noqa: F401 (sticky always empty → 0)
from .state import ReplayState


def _sorted_ids(occ: jnp.ndarray, ids: jnp.ndarray) -> jnp.ndarray:
    return jnp.sort(jnp.where(occ, ids, jnp.int64(PAD)), axis=1)


def _count(occ: jnp.ndarray) -> jnp.ndarray:
    return occ.sum(axis=1).astype(jnp.int64)


def payload_rows(s: ReplayState, layout: PayloadLayout = DEFAULT_LAYOUT) -> jnp.ndarray:
    """[W, layout.width] int64 canonical payload, comparable elementwise with
    the oracle's payload_row output. One implementation serves both this
    and the escalation ladder's narrowing (the canonical field order must
    never fork): at the state's own layout the projection slices are
    no-ops and XLA dead-code-eliminates the unused overflow mask."""
    rows, _overflow = payload_rows_narrow(s, layout)
    return rows


def payload_rows_narrow(s: ReplayState, out_layout: PayloadLayout
                        ) -> "tuple[jnp.ndarray, jnp.ndarray]":
    """Project a (possibly widened-K) state's canonical payload down to
    `out_layout`'s width — the escalation ladder's readback (engine/
    ladder.py): a flagged row re-replayed at 2K/4K must still hash to the
    BASE payload the oracle and stored checksums use.

    Returns (rows [W, out_layout.width], overflow [W] bool). Sorted
    pending lists put PAD past the occupied count, and the version-history
    tables are PAD-filled past vh_count, so truncating each block to the
    out capacity is exact whenever the FINAL counts fit. Rows whose final
    counts exceed an out capacity are unrepresentable in the canonical
    payload (the oracle's payload_row raises OverflowError on them too)
    and come back with `overflow` set — widening further never fixes
    those, only oracle arbitration can.

    With out_layout equal to the state's own layout this is elementwise
    identical to payload_rows (tests assert)."""
    W = s.state.shape[0]
    Kv = out_layout.max_version_history_items
    scalars = jnp.stack(
        [
            s.cancel_requested.astype(jnp.int64),
            s.state.astype(jnp.int64),
            s.last_first_event_id,
            s.next_event_id,
            s.last_processed_event,
            s.signal_count,
            s.decision_attempt,
            s.decision_schedule_id,
            s.decision_started_id,
            s.decision_version,
            jnp.zeros((W,), jnp.int64),  # sticky cleared on replay → hash 0
        ],
        axis=1,
    )
    bidx = s.current_branch.astype(jnp.int32)
    vh_event_ids = jnp.take_along_axis(
        s.vh_event_ids, bidx[:, None, None], axis=1).squeeze(1)
    vh_versions = jnp.take_along_axis(
        s.vh_versions, bidx[:, None, None], axis=1).squeeze(1)
    vh_count = jnp.take_along_axis(s.vh_count, bidx[:, None],
                                   axis=1).squeeze(1)
    overflow = vh_count.astype(jnp.int64) > Kv
    vh_pairs = jnp.stack(
        [vh_event_ids[:, :Kv], vh_versions[:, :Kv]], axis=2
    ).reshape(W, 2 * Kv)

    def narrowed(occ, ids, cap):
        nonlocal overflow
        cnt = _count(occ)
        overflow = overflow | (cnt > cap)
        return cnt[:, None], _sorted_ids(occ, ids)[:, :cap]

    t_cnt, t_ids = narrowed(s.timers.occ, s.timers.started_id,
                            out_layout.max_timers)
    a_cnt, a_ids = narrowed(s.activities.occ, s.activities.schedule_id,
                            out_layout.max_activities)
    c_cnt, c_ids = narrowed(s.children.occ, s.children.initiated_id,
                            out_layout.max_children)
    sg_cnt, sg_ids = narrowed(s.signals.occ, s.signals.initiated_id,
                              out_layout.max_signals)
    rc_cnt, rc_ids = narrowed(s.cancels.occ, s.cancels.initiated_id,
                              out_layout.max_request_cancels)
    rows = jnp.concatenate([
        scalars, vh_count.astype(jnp.int64)[:, None], vh_pairs,
        t_cnt, t_ids, a_cnt, a_ids, c_cnt, c_ids, sg_cnt, sg_ids,
        rc_cnt, rc_ids,
    ], axis=1)
    assert rows.shape[1] == out_layout.width, (rows.shape, out_layout.width)
    return rows, overflow
