"""Device-side canonical checksum payload assembly.

Produces, from the dense ReplayState, exactly the same [W, width] int64
payload matrix as the oracle's core/checksum.payload_row (field order per
reference checksum.go:56-113). Pending-ID lists are sorted on device with
jnp.sort — the PAD sentinel is positive-huge, so unoccupied slots sort to
the tail, matching the oracle's [sorted reals..., PAD...] layout.
"""
from __future__ import annotations

import jax.numpy as jnp

from ..core.checksum import DEFAULT_LAYOUT, PAD, PayloadLayout
from ..core.checksum import fnv64 as _fnv64  # noqa: F401 (sticky always empty → 0)
from .state import ReplayState


def _sorted_ids(occ: jnp.ndarray, ids: jnp.ndarray) -> jnp.ndarray:
    return jnp.sort(jnp.where(occ, ids, jnp.int64(PAD)), axis=1)


def _count(occ: jnp.ndarray) -> jnp.ndarray:
    return occ.sum(axis=1).astype(jnp.int64)


def payload_rows(s: ReplayState, layout: PayloadLayout = DEFAULT_LAYOUT) -> jnp.ndarray:
    """[W, layout.width] int64 canonical payload, comparable elementwise with
    the oracle's payload_row output."""
    W = s.state.shape[0]
    Kv = layout.max_version_history_items
    scalars = jnp.stack(
        [
            s.cancel_requested.astype(jnp.int64),
            s.state.astype(jnp.int64),
            s.last_first_event_id,
            s.next_event_id,
            s.last_processed_event,
            s.signal_count,
            s.decision_attempt,
            s.decision_schedule_id,
            s.decision_started_id,
            s.decision_version,
            jnp.zeros((W,), jnp.int64),  # sticky cleared on replay → hash 0
        ],
        axis=1,
    )
    # the canonical payload covers the CURRENT branch only (checksum.go:92);
    # gather it out of the per-branch tables
    bidx = s.current_branch.astype(jnp.int32)
    vh_event_ids = jnp.take_along_axis(
        s.vh_event_ids, bidx[:, None, None], axis=1).squeeze(1)
    vh_versions = jnp.take_along_axis(
        s.vh_versions, bidx[:, None, None], axis=1).squeeze(1)
    vh_count = jnp.take_along_axis(s.vh_count, bidx[:, None], axis=1).squeeze(1)
    # interleave (event_id, version) pairs; slots beyond vh_count are PAD-filled
    vh_pairs = jnp.stack([vh_event_ids, vh_versions], axis=2).reshape(W, 2 * Kv)
    parts = [
        scalars,
        vh_count.astype(jnp.int64)[:, None],
        vh_pairs,
        _count(s.timers.occ)[:, None],
        _sorted_ids(s.timers.occ, s.timers.started_id),
        _count(s.activities.occ)[:, None],
        _sorted_ids(s.activities.occ, s.activities.schedule_id),
        _count(s.children.occ)[:, None],
        _sorted_ids(s.children.occ, s.children.initiated_id),
        _count(s.signals.occ)[:, None],
        _sorted_ids(s.signals.occ, s.signals.initiated_id),
        _count(s.cancels.occ)[:, None],
        _sorted_ids(s.cancels.occ, s.cancels.initiated_id),
    ]
    rows = jnp.concatenate(parts, axis=1)
    assert rows.shape[1] == layout.width, (rows.shape, layout.width)
    return rows
