"""One lockstep transition step: apply event e to all W workflows.

This is the vectorized twin of the reference's per-event switch
(state_builder.go:131-631) plus the Replicate* mutations
(mutable_state_builder.go / mutable_state_decision_task_manager.go). Where
the Go code branches per workflow, here every branch's update is computed
for all workflows and blended by event-type masks — the SIMD formulation
that keeps the TPU VPU busy. Pending-map operations become masked
insert/delete/update on fixed-capacity [W, K] tables.

Error semantics: conditions that make the reference return an error
(missing infos, invalid state transitions, version-history order) set a
sticky per-workflow error code and freeze that workflow's row; healthy rows
are unaffected. See ops/state.py ErrorCode.
"""
from __future__ import annotations

from typing import Tuple

import jax.numpy as jnp

from ..core.checksum import PAD
from ..core.enums import (
    CLOSE_EVENT_STATUS,
    EMPTY_EVENT_ID,
    EMPTY_VERSION,
    NANOS_PER_SECOND,
    CloseStatus,
    EventType,
    TimeoutType,
    WorkflowState,
)
from .encode import (
    FLAG_RUN_RESET,
    FLAG_VH_ONLY,
    LANE_A0,
    LANE_BATCH_FIRST,
    LANE_BATCH_LAST,
    LANE_BRANCH,
    LANE_EVENT_ID,
    LANE_EVENT_TYPE,
    LANE_FLAGS,
    LANE_PARENT,
    LANE_TASK_ID,
    LANE_TIMESTAMP,
    LANE_VERSION,
)
from .state import ErrorCode, ReplayState, reset_rows

_I64 = jnp.int64


def _sel(mask, new, old):
    return jnp.where(mask, new, old)


def _set_err(error, cond, code):
    """Record `code` where cond holds and no earlier error exists (sticky)."""
    return jnp.where((error == 0) & cond, jnp.int32(code), error)


# ---------------------------------------------------------------------------
# Masked table primitives (the Go-map analog on dense [W, K] tables)
# ---------------------------------------------------------------------------


def table_insert_slot(occ: jnp.ndarray, mask: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """First-free-slot selection. Returns (onehot [W,K], new_occ, overflow [W])."""
    full = occ.all(axis=1)
    do = mask & ~full
    slot = jnp.argmin(occ, axis=1)  # first False
    K = occ.shape[1]
    onehot = (jnp.arange(K)[None, :] == slot[:, None]) & do[:, None]
    return onehot, occ | onehot, mask & full


def table_match(occ: jnp.ndarray, key_field: jnp.ndarray, key: jnp.ndarray,
                mask: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Equality lookup. Returns (sel [W,K] matching slots under mask,
    missing [W] = masked rows with no match)."""
    eq = occ & (key_field == key[:, None])
    found = eq.any(axis=1)
    return eq & mask[:, None], mask & ~found


def _scatter(field: jnp.ndarray, onehot: jnp.ndarray, value) -> jnp.ndarray:
    value = jnp.asarray(value)
    if value.ndim == 1:
        value = value[:, None]
    return jnp.where(onehot, value.astype(field.dtype), field)


# ---------------------------------------------------------------------------
# Workflow state/close-status transition guard
# (workflowExecutionInfo.go:44-165, vectorized)
# ---------------------------------------------------------------------------


def state_transition_valid(cur_state, cur_close, new_state, new_close):
    none = CloseStatus.Nothing
    to_created_running_zombie_ok = new_close == none
    from_created = (
        jnp.where(
            (new_state == WorkflowState.Created)
            | (new_state == WorkflowState.Running)
            | (new_state == WorkflowState.Zombie),
            to_created_running_zombie_ok,
            (new_state == WorkflowState.Completed)
            & ((new_close == CloseStatus.Terminated)
               | (new_close == CloseStatus.TimedOut)
               | (new_close == CloseStatus.ContinuedAsNew)),
        )
    )
    from_running = jnp.where(
        new_state == WorkflowState.Created,
        False,
        jnp.where(
            (new_state == WorkflowState.Running) | (new_state == WorkflowState.Zombie),
            to_created_running_zombie_ok,
            (new_state == WorkflowState.Completed) & (new_close != none),
        ),
    )
    from_completed = (new_state == WorkflowState.Completed) & (new_close == cur_close)
    from_zombie = jnp.where(
        (new_state == WorkflowState.Created) | (new_state == WorkflowState.Running),
        new_close == none,
        ((new_state == WorkflowState.Completed) | (new_state == WorkflowState.Zombie))
        & (new_close != none),
    )
    return jnp.where(
        cur_state == WorkflowState.Void,
        True,
        jnp.where(
            cur_state == WorkflowState.Created,
            from_created,
            jnp.where(
                cur_state == WorkflowState.Running,
                from_running,
                jnp.where(
                    cur_state == WorkflowState.Completed,
                    from_completed,
                    jnp.where(cur_state == WorkflowState.Zombie, from_zombie, False),
                ),
            ),
        ),
    )


# ---------------------------------------------------------------------------
# The step
# ---------------------------------------------------------------------------


def step(s: ReplayState, ev: jnp.ndarray,
         enable_reset: bool = True) -> ReplayState:
    """Apply one event (lanes [W, L]) to all workflows. Returns new state.

    `enable_reset` statically compiles the continue-as-new run-boundary
    blend in or out: corpora that never set FLAG_RUN_RESET (e.g. the
    device-side generator) skip it entirely — and lax.cond's
    varying-manual-axes typing doesn't mix with shard_map, which the
    sharded fused kernel uses."""
    ev_id = ev[:, LANE_EVENT_ID]
    etype = ev[:, LANE_EVENT_TYPE]
    ev_version = ev[:, LANE_VERSION]
    ts = ev[:, LANE_TIMESTAMP]
    task_id = ev[:, LANE_TASK_ID]
    batch_first = ev[:, LANE_BATCH_FIRST]
    batch_last = ev[:, LANE_BATCH_LAST]
    branch = ev[:, LANE_BRANCH].astype(jnp.int32)
    parent = ev[:, LANE_PARENT].astype(jnp.int32)
    flags = ev[:, LANE_FLAGS]
    a = [ev[:, LANE_A0 + i] for i in range(8)]

    # --- 0. continue-as-new run boundary: a FLAG_RUN_RESET event starts a
    # fresh run in this row (the reference builds a brand-new
    # mutableStateBuilder for newRunHistory); sticky errors survive the
    # reset. lax.cond keeps the full-state blend off the hot path for the
    # (typical) steps where no workflow crosses a run boundary.
    if enable_reset:
        import jax

        do_reset = (ev_id > 0) & (s.error == 0) & ((flags & FLAG_RUN_RESET) != 0)
        s = jax.lax.cond(do_reset.any(), lambda st: reset_rows(st, do_reset),
                         lambda st: st, s)

    live = (ev_id > 0) & (s.error == 0)
    vh_only = (flags & FLAG_VH_ONLY) != 0
    error = s.error

    # --- 1. per-branch version-history bookkeeping (versionHistories.go).
    # The event targets branch `branch`; a branch receiving its FIRST item
    # with parent != branch fork-inherits the parent's items truncated at
    # this event's predecessor (DuplicateUntilLCAItem, versionHistory.go:136).
    B = s.vh_event_ids.shape[1]
    Kv = s.vh_event_ids.shape[2]
    branch_over = live & (branch >= B)
    error = _set_err(error, branch_over, ErrorCode.BRANCH_OVERFLOW)
    live = live & ~branch_over
    b = jnp.clip(branch, 0, B - 1)
    p = jnp.clip(parent, 0, B - 1)

    def gather_branch(arr, idx):
        # arr [W, B, ...] → rows of branch idx [W, ...]
        return jnp.take_along_axis(
            arr, idx.astype(jnp.int32).reshape((-1, 1) + (1,) * (arr.ndim - 2)),
            axis=1).squeeze(1)

    b_ids = gather_branch(s.vh_event_ids, b)        # [W, Kv]
    b_versions = gather_branch(s.vh_versions, b)    # [W, Kv]
    b_count = gather_branch(s.vh_count[..., None], b).squeeze(-1)  # [W]
    p_ids = gather_branch(s.vh_event_ids, p)
    p_versions = gather_branch(s.vh_versions, p)
    p_count = gather_branch(s.vh_count[..., None], p).squeeze(-1)

    # fork-inherit: copy the parent's item prefix covering events < ev_id,
    # capping the covering item at ev_id - 1 (the LCA event)
    inherit = live & (b_count == 0) & (p != b)
    lca_eid = ev_id - 1
    slot = jnp.arange(Kv)[None, :]
    prev_eid = jnp.concatenate(
        [jnp.zeros((p_ids.shape[0], 1), p_ids.dtype), p_ids[:, :-1]], axis=1)
    keep = (slot < p_count[:, None]) & (prev_eid < lca_eid[:, None])
    # a fork below the parent's first item is host packing corruption
    bad_fork = inherit & ((p_count == 0) | (lca_eid < 1))
    error = _set_err(error, bad_fork, ErrorCode.BAD_FORK)
    inherit = inherit & ~bad_fork
    inh_ids = jnp.where(keep, jnp.minimum(p_ids, lca_eid[:, None]),
                        jnp.int64(PAD))
    inh_versions = jnp.where(keep, p_versions, jnp.int64(PAD))
    inh_count = keep.sum(axis=1).astype(s.vh_count.dtype)
    b_ids = jnp.where(inherit[:, None], inh_ids, b_ids)
    b_versions = jnp.where(inherit[:, None], inh_versions, b_versions)
    b_count = jnp.where(inherit, inh_count, b_count)
    live = live & ~bad_fork

    has_items = b_count > 0
    last_idx = jnp.maximum(b_count - 1, 0)
    vh_last_onehot = jnp.arange(Kv)[None, :] == last_idx[:, None]
    vh_last_version = jnp.where(
        has_items,
        jnp.where(vh_last_onehot, b_versions, 0).sum(axis=1),
        jnp.int64(EMPTY_VERSION),
    )
    vh_last_event = jnp.where(
        has_items,
        jnp.where(vh_last_onehot, b_ids, 0).sum(axis=1),
        jnp.int64(EMPTY_EVENT_ID),
    )

    # current branch's last version (for UpdateCurrentVersion on completed)
    cur_versions = gather_branch(s.vh_versions, s.current_branch)
    cur_count = gather_branch(s.vh_count[..., None], s.current_branch).squeeze(-1)
    cur_last_idx = jnp.maximum(cur_count - 1, 0)
    cur_last_onehot = jnp.arange(Kv)[None, :] == cur_last_idx[:, None]
    cur_last_version = jnp.where(
        cur_count > 0,
        jnp.where(cur_last_onehot, cur_versions, 0).sum(axis=1),
        jnp.int64(EMPTY_VERSION),
    )

    # --- 2. version history AddOrUpdateItem(event.ID, event.Version)
    # (versionHistory.go:193-225; state_builder.go:115-128)
    vh_order_bad = live & has_items & (
        (ev_version < vh_last_version) | (ev_id <= vh_last_event)
    )
    error = _set_err(error, vh_order_bad, ErrorCode.VERSION_HISTORY_ORDER)
    vh_ok = live & ~vh_order_bad
    append = vh_ok & (~has_items | (ev_version > vh_last_version))
    vh_overflow = append & (b_count >= Kv)
    error = _set_err(error, vh_overflow, ErrorCode.VERSION_HISTORY_OVERFLOW)
    append_ok = append & ~vh_overflow
    update_last = vh_ok & has_items & (ev_version == vh_last_version)
    onehot_append = (jnp.arange(Kv)[None, :] == b_count[:, None]) & append_ok[:, None]
    onehot_update = vh_last_onehot & update_last[:, None]
    write = onehot_append | onehot_update
    b_ids = jnp.where(write, ev_id[:, None], b_ids)
    b_versions = jnp.where(onehot_append, ev_version[:, None], b_versions)
    b_count = b_count + append_ok.astype(b_count.dtype)

    # scatter branch b's updated table back into [W, B, Kv]
    touched = live & (inherit | append_ok | update_last)
    bsel = (jnp.arange(B)[None, :] == b[:, None]) & touched[:, None]  # [W, B]
    vh_event_ids = jnp.where(bsel[:, :, None], b_ids[:, None, :], s.vh_event_ids)
    vh_versions = jnp.where(bsel[:, :, None], b_versions[:, None, :], s.vh_versions)
    vh_count = jnp.where(bsel, b_count[:, None], s.vh_count)

    # --- 3. current-branch arbitration (conflict_resolver.go: a non-current
    # branch whose head version overtakes the current branch's becomes
    # current; state application for the winner's events is host-scheduled
    # via FLAG_VH_ONLY, and this pointer is the device-side parity output)
    ok = vh_ok & ~vh_overflow
    switch = ok & (b != s.current_branch) & (ev_version > cur_last_version)
    current_branch = jnp.where(switch, b, s.current_branch)

    # --- 4. UpdateCurrentVersion(version, force=True)
    # (mutable_state_builder.go:495-533; state_builder.go:112)
    completed = s.state == WorkflowState.Completed
    current_version = _sel(live & ~vh_only,
                           jnp.where(completed, cur_last_version, ev_version),
                           s.current_version)

    # state transitions below apply only to non-VH-only events
    ok = ok & ~vh_only

    last_event_task_id = _sel(ok, task_id, s.last_event_task_id)

    def m(t: EventType) -> jnp.ndarray:
        return ok & (etype == int(t))

    # unknown event type (state_builder.go:629-630)
    error = _set_err(error, ok & ((etype < 0) | (etype > int(EventType.UpsertWorkflowSearchAttributes))),
                     ErrorCode.UNKNOWN_EVENT_TYPE)

    # ------------------------------------------------------------------
    # WorkflowExecutionStarted (mutable_state_builder.go:1751-1829)
    # ------------------------------------------------------------------
    m_started = m(EventType.WorkflowExecutionStarted)
    started_bad = m_started & ~state_transition_valid(
        s.state, s.close_status,
        jnp.int32(WorkflowState.Created), jnp.int32(CloseStatus.Nothing))
    error = _set_err(error, started_bad, ErrorCode.INVALID_STATE_TRANSITION)
    m_started = m_started & ~started_bad

    # a first-decision backoff with a Decider or unknown initiator is
    # rejected (task_generator.go:279-287); lane a7: -1 none, 1 retry, 2 cron
    bad_initiator = m_started & (a[2] > 0) & ((a[7] == 0) | (a[7] >= 3))
    error = _set_err(error, bad_initiator, ErrorCode.INVALID_BACKOFF_INITIATOR)
    m_started = m_started & ~bad_initiator

    workflow_timeout = _sel(m_started, a[0], s.workflow_timeout)
    decision_sts_timeout = _sel(m_started, a[1], s.decision_sts_timeout)
    start_timestamp = _sel(m_started, ts, s.start_timestamp)
    workflow_attempt = _sel(m_started, a[3], s.workflow_attempt)
    expiration_time = _sel(m_started & (a[4] != 0), a[4], s.expiration_time)
    has_parent = _sel(m_started, a[5] != 0, s.has_parent)
    state_v = _sel(m_started, jnp.int32(WorkflowState.Created), s.state)
    close_v = _sel(m_started, jnp.int32(CloseStatus.Nothing), s.close_status)
    last_processed = _sel(m_started, jnp.int64(EMPTY_EVENT_ID), s.last_processed_event)
    last_first = _sel(m_started, ev_id, s.last_first_event_id)

    # ------------------------------------------------------------------
    # Decision state machine (mutable_state_decision_task_manager.go)
    # ------------------------------------------------------------------
    d_version = s.decision_version
    d_sched = s.decision_schedule_id
    d_started = s.decision_started_id
    d_attempt = s.decision_attempt
    d_timeout = s.decision_timeout
    d_sched_ts = s.decision_scheduled_ts
    d_started_ts = s.decision_started_ts
    d_orig_ts = s.decision_original_scheduled_ts

    # started event resets decision fields (:1778-1782)
    d_version = _sel(m_started, jnp.int64(EMPTY_VERSION), d_version)
    d_sched = _sel(m_started, jnp.int64(EMPTY_EVENT_ID), d_sched)
    d_started = _sel(m_started, jnp.int64(EMPTY_EVENT_ID), d_started)
    d_timeout = _sel(m_started, jnp.int64(0), d_timeout)

    # DecisionTaskScheduled (:129-166)
    m_dsched = m(EventType.DecisionTaskScheduled)
    not_zombie = state_v != WorkflowState.Zombie
    dsched_trans = m_dsched & not_zombie
    dsched_bad = dsched_trans & ~state_transition_valid(
        state_v, close_v, jnp.int32(WorkflowState.Running), jnp.int32(CloseStatus.Nothing))
    error = _set_err(error, dsched_bad, ErrorCode.INVALID_STATE_TRANSITION)
    m_dsched = m_dsched & ~dsched_bad
    dsched_trans = dsched_trans & ~dsched_bad
    state_v = _sel(dsched_trans, jnp.int32(WorkflowState.Running), state_v)
    close_v = _sel(dsched_trans, jnp.int32(CloseStatus.Nothing), close_v)
    d_version = _sel(m_dsched, ev_version, d_version)
    d_sched = _sel(m_dsched, ev_id, d_sched)
    d_started = _sel(m_dsched, jnp.int64(EMPTY_EVENT_ID), d_started)
    d_attempt = _sel(m_dsched, a[1], d_attempt)
    d_timeout = _sel(m_dsched, a[0], d_timeout)
    d_sched_ts = _sel(m_dsched, ts, d_sched_ts)
    d_started_ts = _sel(m_dsched, jnp.int64(0), d_started_ts)
    d_orig_ts = _sel(m_dsched, ts, d_orig_ts)

    # DecisionTaskStarted (:199-242); attempt reset to 0 on replication
    m_dstart = m(EventType.DecisionTaskStarted)
    dstart_missing = m_dstart & (d_sched != a[0])
    error = _set_err(error, dstart_missing, ErrorCode.MISSING_DECISION)
    m_dstart = m_dstart & ~dstart_missing
    d_version = _sel(m_dstart, ev_version, d_version)
    d_started = _sel(m_dstart, ev_id, d_started)
    d_attempt = _sel(m_dstart, jnp.int64(0), d_attempt)
    d_started_ts = _sel(m_dstart, ts, d_started_ts)

    # DecisionTaskCompleted (:244-249, 679-694, 827-838)
    m_dcomp = m(EventType.DecisionTaskCompleted)
    d_version = _sel(m_dcomp, jnp.int64(EMPTY_VERSION), d_version)
    d_sched = _sel(m_dcomp, jnp.int64(EMPTY_EVENT_ID), d_sched)
    d_started = _sel(m_dcomp, jnp.int64(EMPTY_EVENT_ID), d_started)
    d_attempt = _sel(m_dcomp, jnp.int64(0), d_attempt)
    d_timeout = _sel(m_dcomp, jnp.int64(0), d_timeout)
    d_sched_ts = _sel(m_dcomp, jnp.int64(0), d_sched_ts)
    d_started_ts = _sel(m_dcomp, jnp.int64(0), d_started_ts)
    # original scheduled timestamp deliberately kept (:690-691)
    last_processed = _sel(m_dcomp, a[1], last_processed)

    # DecisionTaskFailed / TimedOut: FailDecision then transient decision
    # (:643-676, :168-197; state_builder.go:237-281). A SCHEDULE-TO-START
    # timeout (the sticky dispatch deadline, :256-271) does NOT increment
    # the attempt — decision state clears fully and no transient is
    # created (attempt 0); every other fail/timeout increments, and with
    # attempt >0 and no pending decision the transient is always created:
    # schedule ID = stale next_event_id (see :173-182).
    m_dtimeout = m(EventType.DecisionTaskTimedOut)
    m_noinc = m_dtimeout & (a[0] == int(TimeoutType.ScheduleToStart))
    m_dfail = (m(EventType.DecisionTaskFailed) | m_dtimeout) & ~m_noinc
    attempt_after_fail = d_attempt + 1
    d_version = _sel(m_dfail, current_version, d_version)
    d_version = _sel(m_noinc, jnp.int64(EMPTY_VERSION), d_version)
    d_sched = _sel(m_dfail, s.next_event_id, d_sched)
    d_sched = _sel(m_noinc, jnp.int64(EMPTY_EVENT_ID), d_sched)
    d_started = _sel(m_dfail | m_noinc, jnp.int64(EMPTY_EVENT_ID), d_started)
    d_attempt = _sel(m_dfail, attempt_after_fail, d_attempt)
    d_attempt = _sel(m_noinc, jnp.int64(0), d_attempt)
    d_timeout = _sel(m_dfail, decision_sts_timeout, d_timeout)
    d_timeout = _sel(m_noinc, jnp.int64(0), d_timeout)
    d_sched_ts = _sel(m_dfail, ts, d_sched_ts)
    d_sched_ts = _sel(m_noinc, jnp.int64(0), d_sched_ts)
    d_started_ts = _sel(m_dfail | m_noinc, jnp.int64(0), d_started_ts)
    d_orig_ts = _sel(m_dfail | m_noinc, jnp.int64(0), d_orig_ts)

    # ------------------------------------------------------------------
    # Activities
    # ------------------------------------------------------------------
    act = s.activities

    # ActivityTaskScheduled → insert (mutable_state_builder.go:2142-2197)
    m_asched = m(EventType.ActivityTaskScheduled)
    onehot, act_occ, act_over = table_insert_slot(act.occ, m_asched)
    error = _set_err(error, act_over, ErrorCode.TABLE_OVERFLOW)
    act = act._replace(
        occ=act_occ,
        schedule_id=_scatter(act.schedule_id, onehot, ev_id),
        started_id=_scatter(act.started_id, onehot, jnp.full_like(ev_id, EMPTY_EVENT_ID)),
        version=_scatter(act.version, onehot, ev_version),
        activity_key=_scatter(act.activity_key, onehot, a[0]),
        scheduled_time=_scatter(act.scheduled_time, onehot, ts),
        started_time=_scatter(act.started_time, onehot, jnp.zeros_like(ts)),
        last_heartbeat=_scatter(act.last_heartbeat, onehot, jnp.zeros_like(ts)),
        sched_to_start=_scatter(act.sched_to_start, onehot, a[1]),
        sched_to_close=_scatter(act.sched_to_close, onehot, a[2]),
        start_to_close=_scatter(act.start_to_close, onehot, a[3]),
        heartbeat=_scatter(act.heartbeat, onehot, a[4]),
        cancel_requested=jnp.where(onehot, False, act.cancel_requested),
        cancel_request_id=_scatter(act.cancel_request_id, onehot,
                                   jnp.full_like(ev_id, EMPTY_EVENT_ID)),
        attempt=_scatter(act.attempt, onehot, jnp.zeros_like(ev_id)),
        timer_status=jnp.where(onehot, jnp.int32(0), act.timer_status),
        has_retry=jnp.where(onehot, (a[5] != 0)[:, None], act.has_retry),
        batch_id=_scatter(act.batch_id, onehot, batch_first),
    )
    # NOTE: retry expiration (a[6]) participates only in active-side retry
    # (execution/retry.go), not in replay state; the active engine recomputes
    # it from scheduled_time + the retry policy when needed.

    # ActivityTaskStarted → update by schedule_id (:2254-2276)
    m_astart = m(EventType.ActivityTaskStarted)
    sel_slots, missing = table_match(act.occ, act.schedule_id, a[0], m_astart)
    error = _set_err(error, missing, ErrorCode.MISSING_ACTIVITY)
    act = act._replace(
        version=_scatter(act.version, sel_slots, ev_version),
        started_id=_scatter(act.started_id, sel_slots, ev_id),
        started_time=_scatter(act.started_time, sel_slots, ts),
        last_heartbeat=_scatter(act.last_heartbeat, sel_slots, ts),
    )

    # ActivityTask{Completed,Failed,TimedOut,Canceled} → delete (:2312-2536)
    m_aclose = (
        m(EventType.ActivityTaskCompleted) | m(EventType.ActivityTaskFailed)
        | m(EventType.ActivityTaskTimedOut) | m(EventType.ActivityTaskCanceled)
    )
    sel_slots, missing = table_match(act.occ, act.schedule_id, a[0], m_aclose)
    error = _set_err(error, missing, ErrorCode.MISSING_ACTIVITY)
    act = act._replace(occ=act.occ & ~sel_slots)

    # ActivityTaskCancelRequested → update by activity key; unknown IDs
    # tolerated on the passive side (:2444-2467)
    m_acreq = m(EventType.ActivityTaskCancelRequested)
    sel_slots, _ = table_match(act.occ, act.activity_key, a[0], m_acreq)
    act = act._replace(
        version=_scatter(act.version, sel_slots, ev_version),
        cancel_requested=jnp.where(sel_slots, True, act.cancel_requested),
        cancel_request_id=_scatter(act.cancel_request_id, sel_slots, ev_id),
    )

    # ------------------------------------------------------------------
    # User timers (:3057-3168)
    # ------------------------------------------------------------------
    tmr = s.timers
    m_tstart = m(EventType.TimerStarted)
    onehot, tmr_occ, tmr_over = table_insert_slot(tmr.occ, m_tstart)
    error = _set_err(error, tmr_over, ErrorCode.TABLE_OVERFLOW)
    tmr = tmr._replace(
        occ=tmr_occ,
        timer_key=_scatter(tmr.timer_key, onehot, a[0]),
        started_id=_scatter(tmr.started_id, onehot, ev_id),
        expiry_time=_scatter(tmr.expiry_time, onehot, ts + a[1] * NANOS_PER_SECOND),
        task_status=jnp.where(onehot, jnp.int32(0), tmr.task_status),
        version=_scatter(tmr.version, onehot, ev_version),
    )
    m_tdel = m(EventType.TimerFired) | m(EventType.TimerCanceled)
    sel_slots, missing = table_match(tmr.occ, tmr.timer_key, a[0], m_tdel)
    error = _set_err(error, missing, ErrorCode.MISSING_TIMER)
    tmr = tmr._replace(occ=tmr.occ & ~sel_slots)

    # ------------------------------------------------------------------
    # Child workflows (:3417-3810)
    # ------------------------------------------------------------------
    ch = s.children
    m_cinit = m(EventType.StartChildWorkflowExecutionInitiated)
    onehot, ch_occ, ch_over = table_insert_slot(ch.occ, m_cinit)
    error = _set_err(error, ch_over, ErrorCode.TABLE_OVERFLOW)
    ch = ch._replace(
        occ=ch_occ,
        initiated_id=_scatter(ch.initiated_id, onehot, ev_id),
        started_id=_scatter(ch.started_id, onehot, jnp.full_like(ev_id, EMPTY_EVENT_ID)),
        version=_scatter(ch.version, onehot, ev_version),
        batch_id=_scatter(ch.batch_id, onehot, batch_first),
    )
    m_cstart = m(EventType.ChildWorkflowExecutionStarted)
    sel_slots, missing = table_match(ch.occ, ch.initiated_id, a[0], m_cstart)
    error = _set_err(error, missing, ErrorCode.MISSING_CHILD)
    ch = ch._replace(started_id=_scatter(ch.started_id, sel_slots, ev_id))
    m_cdel = (
        m(EventType.StartChildWorkflowExecutionFailed)
        | m(EventType.ChildWorkflowExecutionCompleted)
        | m(EventType.ChildWorkflowExecutionFailed)
        | m(EventType.ChildWorkflowExecutionCanceled)
        | m(EventType.ChildWorkflowExecutionTimedOut)
        | m(EventType.ChildWorkflowExecutionTerminated)
    )
    sel_slots, missing = table_match(ch.occ, ch.initiated_id, a[0], m_cdel)
    error = _set_err(error, missing, ErrorCode.MISSING_CHILD)
    ch = ch._replace(occ=ch.occ & ~sel_slots)

    # ------------------------------------------------------------------
    # External request-cancels / signals (:2760-2816, :2883-3027)
    # ------------------------------------------------------------------
    rc = s.cancels
    m_rcinit = m(EventType.RequestCancelExternalWorkflowExecutionInitiated)
    onehot, rc_occ, rc_over = table_insert_slot(rc.occ, m_rcinit)
    error = _set_err(error, rc_over, ErrorCode.TABLE_OVERFLOW)
    rc = rc._replace(
        occ=rc_occ,
        initiated_id=_scatter(rc.initiated_id, onehot, ev_id),
        version=_scatter(rc.version, onehot, ev_version),
        batch_id=_scatter(rc.batch_id, onehot, batch_first),
    )
    m_rcdel = (
        m(EventType.RequestCancelExternalWorkflowExecutionFailed)
        | m(EventType.ExternalWorkflowExecutionCancelRequested)
    )
    sel_slots, missing = table_match(rc.occ, rc.initiated_id, a[0], m_rcdel)
    error = _set_err(error, missing, ErrorCode.MISSING_REQUEST_CANCEL)
    rc = rc._replace(occ=rc.occ & ~sel_slots)

    sg = s.signals
    m_sginit = m(EventType.SignalExternalWorkflowExecutionInitiated)
    onehot, sg_occ, sg_over = table_insert_slot(sg.occ, m_sginit)
    error = _set_err(error, sg_over, ErrorCode.TABLE_OVERFLOW)
    sg = sg._replace(
        occ=sg_occ,
        initiated_id=_scatter(sg.initiated_id, onehot, ev_id),
        version=_scatter(sg.version, onehot, ev_version),
        batch_id=_scatter(sg.batch_id, onehot, batch_first),
    )
    m_sgdel = (
        m(EventType.SignalExternalWorkflowExecutionFailed)
        | m(EventType.ExternalWorkflowExecutionSignaled)
    )
    sel_slots, missing = table_match(sg.occ, sg.initiated_id, a[0], m_sgdel)
    error = _set_err(error, missing, ErrorCode.MISSING_SIGNAL)
    sg = sg._replace(occ=sg.occ & ~sel_slots)

    # ------------------------------------------------------------------
    # Workflow-level scalars
    # ------------------------------------------------------------------
    signal_count = s.signal_count + m(EventType.WorkflowExecutionSignaled).astype(_I64)
    cancel_requested = s.cancel_requested | m(EventType.WorkflowExecutionCancelRequested)

    # Close events (:2561-2655, :2719-2733, :3225-3240, :3366-3382)
    m_close = jnp.zeros_like(live)
    close_val = jnp.zeros_like(s.close_status)
    for et, cs in CLOSE_EVENT_STATUS:
        mm = m(et)
        m_close = m_close | mm
        close_val = jnp.where(mm, jnp.int32(cs), close_val)
    close_bad = m_close & ~state_transition_valid(
        state_v, close_v, jnp.int32(WorkflowState.Completed), close_val)
    error = _set_err(error, close_bad, ErrorCode.INVALID_STATE_TRANSITION)
    m_close = m_close & ~close_bad
    state_v = _sel(m_close, jnp.int32(WorkflowState.Completed), state_v)
    close_v = _sel(m_close, close_val, close_v)
    completion_batch = _sel(m_close, batch_first, s.completion_event_batch_id)

    # ------------------------------------------------------------------
    # Batch-end bookkeeping (state_builder.go:642-643); only when this
    # event applied cleanly
    # ------------------------------------------------------------------
    end_ok = ok & (batch_last == 1) & (error == 0)
    last_first = _sel(end_ok, batch_first, last_first)
    next_event_id = _sel(end_ok, ev_id + 1, s.next_event_id)

    return s._replace(
        state=state_v,
        close_status=close_v,
        cancel_requested=cancel_requested,
        last_first_event_id=last_first,
        next_event_id=next_event_id,
        last_processed_event=last_processed,
        signal_count=signal_count,
        decision_version=d_version,
        decision_schedule_id=d_sched,
        decision_started_id=d_started,
        decision_attempt=d_attempt,
        decision_timeout=d_timeout,
        decision_scheduled_ts=d_sched_ts,
        decision_started_ts=d_started_ts,
        decision_original_scheduled_ts=d_orig_ts,
        workflow_timeout=workflow_timeout,
        decision_sts_timeout=decision_sts_timeout,
        start_timestamp=start_timestamp,
        completion_event_batch_id=completion_batch,
        last_event_task_id=last_event_task_id,
        workflow_attempt=workflow_attempt,
        expiration_time=expiration_time,
        has_parent=has_parent,
        current_version=current_version,
        vh_event_ids=vh_event_ids,
        vh_versions=vh_versions,
        vh_count=vh_count,
        current_branch=current_branch,
        activities=act,
        timers=tmr,
        children=ch,
        cancels=rc,
        signals=sg,
        error=error,
    )
