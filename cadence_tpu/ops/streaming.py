"""Chunked event streaming: replay histories longer than device memory.

The sequence axis of this framework is history length (SURVEY.md §2.6 P6):
replay is inherently sequential per workflow, so the long-context strategy
is not ring attention but event-axis chunking with carried state — the scan
runs chunk by chunk while the host packs and ships the next chunk
(double-buffering, the reference's queue-pipeline analog P7).

The carried ReplayState is donated to each chunk step, so device memory
holds one state + at most two event chunks regardless of total history
length; jax's async dispatch overlaps the host-side packing of chunk N+1
with device replay of chunk N.
"""
from __future__ import annotations

from functools import partial
from typing import Iterator, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..core.checksum import DEFAULT_LAYOUT, PayloadLayout
from .payload import payload_rows
from .state import ReplayState, init_state
from .transitions import step


@partial(jax.jit, donate_argnums=(0,))
def _replay_chunk(s: ReplayState, events: jnp.ndarray) -> ReplayState:
    """Apply one [W, E_chunk, L] chunk to carried state (donated in-place)."""
    def body(carry, ev):
        return step(carry, ev), None

    s, _ = jax.lax.scan(body, s, jnp.swapaxes(events, 0, 1))
    return s


class StreamingReplayer:
    """Feed event chunks for W workflows; state carries across chunks.

    Chunks must split histories only at event boundaries (any boundary is
    legal: batch bookkeeping lanes travel with each event). Padding rows
    (event id 0) are no-ops, so ragged chunking across workflows is fine.
    """

    def __init__(self, num_workflows: int,
                 layout: PayloadLayout = DEFAULT_LAYOUT) -> None:
        self.layout = layout
        self.num_workflows = num_workflows
        self.state: ReplayState = init_state(num_workflows, layout)
        self._pending: Optional[jax.Array] = None

    def feed(self, chunk: np.ndarray) -> None:
        """Ship a [W, E_chunk, L] chunk; dispatch is async, so the caller can
        immediately start packing the next chunk."""
        assert chunk.shape[0] == self.num_workflows
        device_chunk = jax.device_put(chunk)
        self.state = _replay_chunk(self.state, device_chunk)

    def finish(self) -> Tuple[np.ndarray, np.ndarray]:
        """Returns (payload rows, errors) after all fed chunks."""
        rows = payload_rows(self.state, self.layout)
        return np.asarray(rows), np.asarray(self.state.error)


def replay_streamed(events: np.ndarray, chunk_events: int,
                    layout: PayloadLayout = DEFAULT_LAYOUT
                    ) -> Tuple[np.ndarray, np.ndarray]:
    """Convenience: replay a full [W, E, L] tensor in chunks of chunk_events."""
    replayer = StreamingReplayer(events.shape[0], layout)
    for start in range(0, events.shape[1], chunk_events):
        chunk = events[:, start:start + chunk_events]
        if chunk.shape[1] < chunk_events:
            # pad the tail chunk to the steady shape: one compiled executable
            pad = np.zeros((chunk.shape[0], chunk_events - chunk.shape[1],
                            chunk.shape[2]), dtype=chunk.dtype)
            pad[:, :, 1] = -1  # LANE_EVENT_TYPE padding marker
            chunk = np.concatenate([chunk, pad], axis=1)
        replayer.feed(chunk)
    return replayer.finish()
