"""Device-side CRC32: the checksum leg of the parity oracle, on chip.

The reference computes IEEE CRC32 over the canonical mutable-state payload
on the CPU (common/checksum/crc.go:35-57); core/checksum.py mirrors it with
zlib over little-endian int64 rows. Pulling [W, width] payload rows to the
host just to hash them is D2H-bandwidth-bound (and on tunneled TPU hosts
catastrophically so) — so the hash itself runs on device: a table-driven
byte-at-a-time CRC over each row's 8·width little-endian bytes, reduced to
one uint32 per workflow. The host then pulls 4 bytes per workflow instead
of 8·width, and bitwise-identical values to `crc32_of_row` (asserted by
tests/test_device_crc.py).

The classic reflected-polynomial table algorithm maps cleanly onto the
VPU: per scanned word, 8 unrolled steps of (xor, mask, 256-entry gather,
shift) over the [W] lane — no host round-trip anywhere.
"""
from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

_POLY = np.uint32(0xEDB88320)  # reflected IEEE polynomial (crc.go IEEETable)


def _make_tables() -> np.ndarray:
    """Slice-by-8 table set T[0..7]: T[0] is the classic byte table;
    T[k][i] advances T[k-1][i] by one zero byte. Processing one int64 word
    per iteration with 8 independent gathers keeps the sequential
    dependency chain at `width` instead of `8*width` — the chain, not the
    gather count, is what a latency-bound [W]-lane loop pays for."""
    t = np.zeros((8, 256), dtype=np.uint32)
    for i in range(256):
        c = np.uint32(i)
        for _ in range(8):
            c = (c >> np.uint32(1)) ^ (_POLY if c & np.uint32(1) else np.uint32(0))
        t[0, i] = c
    for k in range(1, 8):
        prev = t[k - 1]
        t[k] = (prev >> np.uint32(8)) ^ t[0][prev & np.uint32(0xFF)]
    return t


_TABLES = _make_tables()


@jax.jit
def crc32_rows(rows: jnp.ndarray) -> jnp.ndarray:
    """Per-row IEEE CRC32 of a [W, width] int64 matrix's little-endian
    bytes; bit-identical to core.checksum.crc32_of_rows."""
    tables = jnp.asarray(_TABLES)
    init = jnp.full((rows.shape[0],), 0xFFFFFFFF, dtype=jnp.uint32)

    def word_step(crc, word):
        # word [W] int64, consumed LSB-first (little-endian): xor the low
        # half into the running crc, then 8 parallel table gathers
        lo = word.astype(jnp.uint32)  # bits 0..31 (two's complement wrap)
        hi = jnp.right_shift(word, 32).astype(jnp.uint32)
        x = crc ^ lo
        out = jnp.zeros_like(crc)
        for k in range(4):
            out = out ^ tables[7 - k][(x >> (8 * k)) & 0xFF]
        for k in range(4):
            out = out ^ tables[3 - k][(hi >> (8 * k)) & 0xFF]
        return out, None

    crc, _ = jax.lax.scan(word_step, init, jnp.swapaxes(rows, 0, 1))
    return crc ^ jnp.uint32(0xFFFFFFFF)


@partial(jax.jit, static_argnames=("layout",))
def replay_to_crc(events: jnp.ndarray, layout):
    """Replay packed events and reduce all the way to (crc32 [W] uint32,
    error [W]) — the minimal-D2H form of the north-star pipeline."""
    from .payload import payload_rows
    from .replay import replay_events

    s = replay_events(events, layout)
    return crc32_rows(payload_rows(s, layout)), s.error
