"""Device-side transfer/timer task generation during replay.

Replay in the reference does not just rebuild state — it also derives the
transfer and timer tasks the engine must process
(mutable_state_task_generator.go, called from the state_builder switch and
at the end of each ApplyEvents batch). Here tasks are emitted into
fixed-capacity per-workflow logs ([W, T] lanes + counts) so the host can
drain them in bulk; numeric fields match the oracle's GeneratedTask stream
exactly (string fields like task lists are host-resolvable from event IDs).

Replay is the passive-side path: close events emit exactly one
CloseExecution transfer task + the retention-driven DeleteHistoryEvent
timer (task_generator.go:180-185,:249-255); active-side cross-cluster
fan-out belongs to the host engine.

Task logs for workflows whose error flag is set are undefined beyond the
point of failure (the reference aborts the whole replay transaction there).
"""
from __future__ import annotations

from typing import NamedTuple, Tuple

import jax.numpy as jnp

from ..core.enums import (
    CLOSE_EVENT_STATUS,
    EMPTY_EVENT_ID,
    NANOS_PER_SECOND,
    TIMER_TASK_STATUS_CREATED,
    TIMER_TYPE_TO_STATUS_MASK,
    EventType,
    TimeoutType,
    TimerTaskType,
    TransferTaskType,
    WorkflowBackoffTimeoutType,
)
from .encode import (
    FLAG_VH_ONLY,
    LANE_A0,
    LANE_BATCH_LAST,
    LANE_EVENT_ID,
    LANE_EVENT_TYPE,
    LANE_FLAGS,
    LANE_TIMESTAMP,
    LANE_VERSION,
)
from .state import ReplayState
from .transitions import _scatter as _w  # same masked one-hot write rule

_I64 = jnp.int64
_DAY_NANOS = 24 * 3600 * NANOS_PER_SECOND


class TaskLog(NamedTuple):
    """Per-workflow task emission logs (append-only, capacity-capped)."""

    tr_type: jnp.ndarray      # [W, Tt] i64 TransferTaskType
    tr_version: jnp.ndarray   # [W, Tt] i64
    tr_event_id: jnp.ndarray  # [W, Tt] i64 (schedule/initiated id; 0 if n/a)
    tr_count: jnp.ndarray     # [W] i64
    tm_type: jnp.ndarray      # [W, Tm] i64 TimerTaskType
    tm_version: jnp.ndarray   # [W, Tm] i64
    tm_vis: jnp.ndarray       # [W, Tm] i64 visibility timestamp nanos
    tm_event_id: jnp.ndarray  # [W, Tm] i64
    tm_timeout_type: jnp.ndarray  # [W, Tm] i64
    tm_attempt: jnp.ndarray   # [W, Tm] i64
    tm_count: jnp.ndarray     # [W] i64
    overflow: jnp.ndarray     # [W] bool — a log filled up (reported, not silent)


def init_task_log(num_workflows: int, max_transfer: int, max_timer: int) -> TaskLog:
    W = num_workflows

    def z(*shape):
        return jnp.zeros(shape, _I64)

    return TaskLog(
        tr_type=z(W, max_transfer), tr_version=z(W, max_transfer),
        tr_event_id=z(W, max_transfer), tr_count=z(W),
        tm_type=z(W, max_timer), tm_version=z(W, max_timer),
        tm_vis=z(W, max_timer), tm_event_id=z(W, max_timer),
        tm_timeout_type=z(W, max_timer), tm_attempt=z(W, max_timer),
        tm_count=z(W), overflow=jnp.zeros((W,), jnp.bool_),
    )


def _emit(count, overflow, cap, mask):
    full = count >= cap
    do = mask & ~full
    onehot = (jnp.arange(cap)[None, :] == count[:, None]) & do[:, None]
    return onehot, count + do.astype(_I64), overflow | (mask & full)


def emit_transfer(log: TaskLog, mask, ttype, version, event_id) -> TaskLog:
    onehot, count, overflow = _emit(log.tr_count, log.overflow,
                                    log.tr_type.shape[1], mask)
    return log._replace(
        tr_type=_w(log.tr_type, onehot, ttype),
        tr_version=_w(log.tr_version, onehot, version),
        tr_event_id=_w(log.tr_event_id, onehot, event_id),
        tr_count=count, overflow=overflow,
    )


def emit_timer(log: TaskLog, mask, ttype, version, vis, event_id,
               timeout_type, attempt) -> TaskLog:
    onehot, count, overflow = _emit(log.tm_count, log.overflow,
                                    log.tm_type.shape[1], mask)
    return log._replace(
        tm_type=_w(log.tm_type, onehot, ttype),
        tm_version=_w(log.tm_version, onehot, version),
        tm_vis=_w(log.tm_vis, onehot, vis),
        tm_event_id=_w(log.tm_event_id, onehot, event_id),
        tm_timeout_type=_w(log.tm_timeout_type, onehot, timeout_type),
        tm_attempt=_w(log.tm_attempt, onehot, attempt),
        tm_count=count, overflow=overflow,
    )


def _lex_min3(valid, ts, eid, ttype):
    """Lexicographic argmin over (ts, event_id, timer_type) among valid slots.

    Mirrors TimerSequenceIDs.Less (timer_sequence.go:459-493). Returns
    (found [W], sel [W,K] one-hot of the winning slot)."""
    big = jnp.int64(1 << 62)
    found = valid.any(axis=1)
    t1 = jnp.where(valid, ts, big)
    min_ts = t1.min(axis=1)
    m1 = valid & (t1 == min_ts[:, None])
    e1 = jnp.where(m1, eid, big)
    min_e = e1.min(axis=1)
    m2 = m1 & (e1 == min_e[:, None])
    y1 = jnp.where(m2, ttype, big)
    min_y = y1.min(axis=1)
    m3 = m2 & (y1 == min_y[:, None])
    # ties fully broken by (ts, eid, type); keep first slot for safety
    K = valid.shape[1]
    first = jnp.where(m3, jnp.arange(K)[None, :], K).min(axis=1)
    sel = (jnp.arange(K)[None, :] == first[:, None]) & found[:, None]
    return found, sel


def batch_end_timer_tasks(s: ReplayState, log: TaskLog,
                          mask) -> Tuple[ReplayState, TaskLog]:
    """GenerateActivityTimerTasks + GenerateUserTimerTasks at batch end
    (state_builder.go:634-640; timer_sequence.go CreateNext*Timer)."""
    act = s.activities
    W, K = act.occ.shape
    empty = act.started_id == EMPTY_EVENT_ID

    # four candidate timers per activity (timer_sequence.go:219-254)
    cand_valid = jnp.concatenate([
        act.occ,                                  # schedule-to-close
        act.occ & empty,                          # schedule-to-start
        act.occ & ~empty,                         # start-to-close
        act.occ & ~empty & (act.heartbeat > 0),   # heartbeat
    ], axis=1)
    cand_ts = jnp.concatenate([
        act.scheduled_time + act.sched_to_close * NANOS_PER_SECOND,
        act.scheduled_time + act.sched_to_start * NANOS_PER_SECOND,
        act.started_time + act.start_to_close * NANOS_PER_SECOND,
        jnp.maximum(act.started_time, act.last_heartbeat)
        + act.heartbeat * NANOS_PER_SECOND,
    ], axis=1)
    cand_eid = jnp.tile(act.schedule_id, (1, 4))
    type_codes = [TimeoutType.ScheduleToClose, TimeoutType.ScheduleToStart,
                  TimeoutType.StartToClose, TimeoutType.Heartbeat]
    cand_type = jnp.concatenate([
        jnp.full((W, K), int(t), _I64) for t in type_codes
    ], axis=1)
    cand_bit = jnp.concatenate([
        jnp.full((W, K), TIMER_TYPE_TO_STATUS_MASK[t], jnp.int32)
        for t in type_codes
    ], axis=1)
    cand_created = (jnp.tile(act.timer_status, (1, 4)) & cand_bit) > 0

    found, sel = _lex_min3(cand_valid & mask[:, None], cand_ts, cand_eid, cand_type)
    # only create when the first (minimum) timer is not yet created
    # (CreateNextActivityTimer returns early otherwise, :171-174)
    fresh = found & ~(jnp.where(sel, cand_created, False).any(axis=1))
    sel = sel & fresh[:, None]
    sel_ts = jnp.where(sel, cand_ts, 0).sum(axis=1)
    sel_eid = jnp.where(sel, cand_eid, 0).sum(axis=1)
    sel_type = jnp.where(sel, cand_type, 0).sum(axis=1)
    sel_attempt_src = jnp.tile(act.attempt, (1, 4))
    sel_attempt = jnp.where(sel, sel_attempt_src, 0).sum(axis=1)
    # fold the 4 quadrants back onto table slots to set the created bit
    slot_sel = sel[:, 0:K] | sel[:, K:2 * K] | sel[:, 2 * K:3 * K] | sel[:, 3 * K:]
    bit = jnp.where(sel, cand_bit, 0).sum(axis=1).astype(jnp.int32)
    act = act._replace(
        timer_status=jnp.where(slot_sel, act.timer_status | bit[:, None],
                               act.timer_status)
    )
    log = emit_timer(
        log, fresh, jnp.int64(TimerTaskType.ActivityTimeout),
        s.current_version, sel_ts, sel_eid, sel_type, sel_attempt,
    )

    # user timers (timer_sequence.go:127-160): single candidate per timer
    tmr = s.timers
    created = tmr.task_status == TIMER_TASK_STATUS_CREATED
    found, sel = _lex_min3(tmr.occ & mask[:, None], tmr.expiry_time,
                           tmr.started_id,
                           jnp.zeros_like(tmr.started_id))
    fresh = found & ~(jnp.where(sel, created, False).any(axis=1))
    sel = sel & fresh[:, None]
    sel_ts = jnp.where(sel, tmr.expiry_time, 0).sum(axis=1)
    sel_eid = jnp.where(sel, tmr.started_id, 0).sum(axis=1)
    tmr = tmr._replace(
        task_status=jnp.where(sel, jnp.int32(TIMER_TASK_STATUS_CREATED),
                              tmr.task_status)
    )
    log = emit_timer(
        log, fresh, jnp.int64(TimerTaskType.UserTimer),
        s.current_version, sel_ts, sel_eid,
        jnp.zeros_like(sel_eid), jnp.zeros_like(sel_eid),
    )
    return s._replace(activities=act, timers=tmr), log


def step_tasks(s_new: ReplayState, ev: jnp.ndarray,
               log: TaskLog, retention_days: int
               ) -> Tuple[ReplayState, TaskLog]:
    """Emit the tasks generated by applying `ev` (post-step state s_new)."""
    ev_id = ev[:, LANE_EVENT_ID]
    etype = ev[:, LANE_EVENT_TYPE]
    ev_version = ev[:, LANE_VERSION]
    ts = ev[:, LANE_TIMESTAMP]
    batch_last = ev[:, LANE_BATCH_LAST]
    a = [ev[:, LANE_A0 + i] for i in range(8)]

    # VH-only events (non-current-branch persists) generate no tasks: the
    # reference persists them without running the task generator
    # (ndc/transaction_manager.go passive persists)
    vh_only = (ev[:, LANE_FLAGS] & FLAG_VH_ONLY) != 0
    ok = (ev_id > 0) & (s_new.error == 0) & ~vh_only

    def m(t: EventType):
        return ok & (etype == int(t))

    # --- WorkflowExecutionStarted (state_builder.go:158-177)
    m_started = m(EventType.WorkflowExecutionStarted)
    log = emit_transfer(log, m_started,
                        jnp.int64(TransferTaskType.RecordWorkflowStarted),
                        ev_version, jnp.zeros_like(ev_id))
    backoff = a[2] * NANOS_PER_SECOND
    wf_timeout_ts = ts + s_new.workflow_timeout * NANOS_PER_SECOND + backoff
    cap = (a[3] > 0) & (s_new.expiration_time != 0) & (wf_timeout_ts > s_new.expiration_time)
    wf_timeout_ts = jnp.where(cap, s_new.expiration_time, wf_timeout_ts)
    log = emit_timer(log, m_started, jnp.int64(TimerTaskType.WorkflowTimeout),
                     ev_version, wf_timeout_ts, jnp.zeros_like(ev_id),
                     jnp.zeros_like(ev_id), jnp.zeros_like(ev_id))
    m_backoff = m_started & (a[2] > 0)
    # initiator lane: -1 none → Cron; RetryPolicy → Retry (task_generator.go:271-288)
    backoff_type = jnp.where(
        a[7] == 1,
        jnp.int64(WorkflowBackoffTimeoutType.Retry),
        jnp.int64(WorkflowBackoffTimeoutType.Cron),
    )
    log = emit_timer(log, m_backoff, jnp.int64(TimerTaskType.WorkflowBackoffTimer),
                     ev_version, ts + backoff, jnp.zeros_like(ev_id),
                     backoff_type, jnp.zeros_like(ev_id))

    # --- DecisionTask transfer on schedule + on transient schedule
    # (state_builder.go:204-208,:250-259,:272-281; task_generator.go:315-350;
    # no schedule-to-start timer on the replay path)
    m_dsched = m(EventType.DecisionTaskScheduled)
    # a schedule-to-start timeout creates no transient (attempt stays 0,
    # state_builder.go ReplicateTransientDecisionTaskScheduled), so no
    # dispatch task either — the explicit follow-up scheduled event emits it
    m_dtimeout = m(EventType.DecisionTaskTimedOut)
    m_dfail = (m(EventType.DecisionTaskFailed)
               | (m_dtimeout & (a[0] != int(TimeoutType.ScheduleToStart))))
    log = emit_transfer(log, m_dsched | m_dfail,
                        jnp.int64(TransferTaskType.DecisionTask),
                        s_new.decision_version, s_new.decision_schedule_id)

    # --- DecisionTaskStarted → start-to-close timeout timer
    # (task_generator.go:352-388); attempt escalation does not fire on the
    # replay path because replicated starts reset attempt to 0
    m_dstart = m(EventType.DecisionTaskStarted)
    dstart_timeout = s_new.decision_timeout * NANOS_PER_SECOND
    log = emit_timer(log, m_dstart, jnp.int64(TimerTaskType.DecisionTimeout),
                     s_new.decision_version,
                     s_new.decision_started_ts + dstart_timeout,
                     s_new.decision_schedule_id,
                     jnp.full_like(ev_id, int(TimeoutType.StartToClose)),
                     s_new.decision_attempt)

    # --- ActivityTaskScheduled → ActivityTask transfer (task_generator.go:390-428)
    log = emit_transfer(log, m(EventType.ActivityTaskScheduled),
                        jnp.int64(TransferTaskType.ActivityTask),
                        ev_version, ev_id)

    # --- StartChildWorkflowExecutionInitiated (task_generator.go:451-498)
    log = emit_transfer(log, m(EventType.StartChildWorkflowExecutionInitiated),
                        jnp.int64(TransferTaskType.StartChildExecution),
                        ev_version, ev_id)

    # --- external cancel / signal initiated (task_generator.go:500-600)
    log = emit_transfer(log, m(EventType.RequestCancelExternalWorkflowExecutionInitiated),
                        jnp.int64(TransferTaskType.CancelExecution),
                        ev_version, ev_id)
    log = emit_transfer(log, m(EventType.SignalExternalWorkflowExecutionInitiated),
                        jnp.int64(TransferTaskType.SignalExecution),
                        ev_version, ev_id)

    # --- UpsertWorkflowSearchAttributes (task_generator.go:602-612)
    log = emit_transfer(log, m(EventType.UpsertWorkflowSearchAttributes),
                        jnp.int64(TransferTaskType.UpsertWorkflowSearchAttributes),
                        s_new.current_version, jnp.zeros_like(ev_id))

    # --- close events: CloseExecution transfer + retention deletion timer
    # (task_generator.go:168-258, passive path)
    m_close = jnp.zeros_like(ok)
    for et, _status in CLOSE_EVENT_STATUS:
        m_close = m_close | m(et)
    log = emit_transfer(log, m_close, jnp.int64(TransferTaskType.CloseExecution),
                        ev_version, jnp.zeros_like(ev_id))
    log = emit_timer(log, m_close, jnp.int64(TimerTaskType.DeleteHistoryEvent),
                     ev_version, ts + retention_days * _DAY_NANOS,
                     jnp.zeros_like(ev_id), jnp.zeros_like(ev_id),
                     jnp.zeros_like(ev_id))

    # --- batch end: activity + user timer tasks
    m_end = ok & (batch_last == 1)
    return batch_end_timer_tasks(s_new, log, m_end)
