"""Host-side event packing: histories → dense [W, E, L] int64 lane tensors.

The reference decodes thriftrw/JSON event blobs into Go structs per event
(common/persistence/serialization/serializer.go); here batches are packed
into a fixed lane schema the device kernel can scan. String identifiers
(activity IDs, timer IDs) are interned to dense per-workflow integer keys —
state transitions only ever compare them for equality
(state_builder.go:132-646 uses no payload bytes), so payloads stay host-side.

This pure-Python packer is the reference implementation; the C++ packer in
native/ implements the same schema for production feed rates.
"""
from __future__ import annotations

from typing import Dict, List, Sequence, Tuple

import numpy as np

from ..core.enums import EventType
from ..core.events import HistoryBatch

# Lane indices
LANE_EVENT_ID = 0    # 0 = padding row
LANE_EVENT_TYPE = 1  # EventType value; -1 on padding
LANE_VERSION = 2
LANE_TIMESTAMP = 3
LANE_TASK_ID = 4
LANE_BATCH_FIRST = 5  # first event ID of the enclosing batch
LANE_BATCH_LAST = 6   # 1 if this is the last event of its batch
LANE_A0 = 7
NUM_ATTR_LANES = 8
# tree/chain lanes (after the attribute block so attr indices stay stable)
LANE_BRANCH = LANE_A0 + NUM_ATTR_LANES      # version-history branch index
LANE_PARENT = LANE_BRANCH + 1               # branch to fork-inherit items from
LANE_FLAGS = LANE_PARENT + 1                # FLAG_* bitmask
NUM_LANES = LANE_FLAGS + 1  # 18

# LANE_FLAGS bits
FLAG_RUN_RESET = 1  # first event of a continued-as-new run: reset row state
FLAG_VH_ONLY = 2    # event updates its branch's version history only (the
                    # non-current-branch persist path of NDC conflict
                    # resolution, ndc/branch_manager.go); no state transition


class _Interner:
    """Per-workflow string → dense int key (starting at 1; 0 = absent)."""

    def __init__(self) -> None:
        self._map: Dict[str, int] = {}

    def key(self, s: str) -> int:
        if s not in self._map:
            self._map[s] = len(self._map) + 1
        return self._map[s]


def _encode_attrs(ev, interner: _Interner) -> List[int]:
    """Per-type attribute lanes a0..a7. Must stay in lockstep with
    transitions.py's lane reads."""
    a = [0] * NUM_ATTR_LANES
    et = ev.event_type
    g = ev.get

    if et == EventType.WorkflowExecutionStarted:
        a[0] = g("execution_start_to_close_timeout_seconds", 0) or 0
        a[1] = g("task_start_to_close_timeout_seconds", 0) or 0
        a[2] = g("first_decision_task_backoff_seconds", 0) or 0
        a[3] = g("attempt", 0) or 0
        a[4] = g("expiration_timestamp", 0) or 0
        a[5] = 1 if g("parent_workflow_id") else 0
        a[6] = 1 if g("retry_policy") is not None else 0
        initiator = g("initiator")
        a[7] = -1 if initiator is None else int(initiator)
    elif et == EventType.DecisionTaskScheduled:
        a[0] = g("start_to_close_timeout_seconds", 0) or 0
        a[1] = g("attempt", 0) or 0
    elif et == EventType.DecisionTaskStarted:
        a[0] = g("scheduled_event_id", 0)
    elif et == EventType.DecisionTaskCompleted:
        a[0] = g("scheduled_event_id", 0)
        a[1] = g("started_event_id", 0)
    elif et == EventType.DecisionTaskTimedOut:
        a[0] = int(g("timeout_type", 0))
    elif et == EventType.ActivityTaskScheduled:
        a[0] = interner.key("act:" + g("activity_id", ""))
        a[1] = g("schedule_to_start_timeout_seconds", 0) or 0
        a[2] = g("schedule_to_close_timeout_seconds", 0) or 0
        a[3] = g("start_to_close_timeout_seconds", 0) or 0
        a[4] = g("heartbeat_timeout_seconds", 0) or 0
        retry = g("retry_policy")
        a[5] = 1 if retry is not None else 0
        a[6] = retry.expiration_interval_seconds if retry is not None else 0
    elif et == EventType.ActivityTaskStarted:
        a[0] = g("scheduled_event_id", 0)
    elif et in (
        EventType.ActivityTaskCompleted,
        EventType.ActivityTaskFailed,
        EventType.ActivityTaskTimedOut,
        EventType.ActivityTaskCanceled,
    ):
        a[0] = g("scheduled_event_id", 0)
    elif et == EventType.ActivityTaskCancelRequested:
        a[0] = interner.key("act:" + g("activity_id", ""))
    elif et == EventType.TimerStarted:
        a[0] = interner.key("timer:" + g("timer_id", ""))
        a[1] = g("start_to_fire_timeout_seconds", 0) or 0
    elif et in (EventType.TimerFired, EventType.TimerCanceled):
        a[0] = interner.key("timer:" + g("timer_id", ""))
    elif et == EventType.ChildWorkflowExecutionStarted:
        a[0] = g("initiated_event_id", 0)
    elif et in (
        EventType.StartChildWorkflowExecutionFailed,
        EventType.ChildWorkflowExecutionCompleted,
        EventType.ChildWorkflowExecutionFailed,
        EventType.ChildWorkflowExecutionCanceled,
        EventType.ChildWorkflowExecutionTimedOut,
        EventType.ChildWorkflowExecutionTerminated,
    ):
        a[0] = g("initiated_event_id", 0)
    elif et in (
        EventType.RequestCancelExternalWorkflowExecutionFailed,
        EventType.ExternalWorkflowExecutionCancelRequested,
        EventType.SignalExternalWorkflowExecutionFailed,
        EventType.ExternalWorkflowExecutionSignaled,
    ):
        a[0] = g("initiated_event_id", 0)
    # remaining types carry no state-relevant attributes
    return a


def _emit_events(out: np.ndarray, row: int, events, interner: _Interner,
                 branch: int = 0, parent: int = 0, flags: int = 0,
                 reset_first: bool = False) -> int:
    """Pack one batch's events at `row`; the single lane-writing loop every
    encoder shares. Returns the next free row."""
    max_events = out.shape[0]
    first_id = events[0].id
    for j, ev in enumerate(events):
        if row >= max_events:
            raise OverflowError(f"history has more than {max_events} events")
        out[row, LANE_EVENT_ID] = ev.id
        out[row, LANE_EVENT_TYPE] = int(ev.event_type)
        out[row, LANE_VERSION] = ev.version
        out[row, LANE_TIMESTAMP] = ev.timestamp
        out[row, LANE_TASK_ID] = ev.task_id
        out[row, LANE_BATCH_FIRST] = first_id
        out[row, LANE_BATCH_LAST] = 1 if j == len(events) - 1 else 0
        out[row, LANE_A0:LANE_A0 + NUM_ATTR_LANES] = _encode_attrs(ev, interner)
        out[row, LANE_BRANCH] = branch
        out[row, LANE_PARENT] = parent
        out[row, LANE_FLAGS] = (flags | FLAG_RUN_RESET
                                if reset_first and j == 0 else flags)
        row += 1
    return row


def encode_history(batches: Sequence[HistoryBatch], max_events: int) -> np.ndarray:
    """Pack one workflow's batched history into [E, L] lanes (zero-padded).

    A batch carrying `new_run_events` (continue-as-new: cron, retry, or an
    explicit ContinueAsNew decision) chains the new run into the SAME row:
    its first event is flagged FLAG_RUN_RESET, which makes the kernel reset
    that workflow's carried state at the boundary (the device analog of the
    reference starting a fresh mutableStateBuilder for the new run,
    state_builder.go:446-520 applyEvents newRunHistory). The row's final
    state is therefore the LAST run's state."""
    out = np.zeros((max_events, NUM_LANES), dtype=np.int64)
    out[:, LANE_EVENT_TYPE] = -1
    interner = _Interner()
    row = 0
    for batch in batches:
        row = _emit_events(out, row, batch.events, interner)
        if batch.new_run_events:
            # fresh interner: the new run's string IDs are a new namespace
            interner = _Interner()
            row = _emit_events(out, row, batch.new_run_events, interner,
                               reset_first=True)
    return out


def encode_batches_resumable(batches: Sequence[HistoryBatch],
                             interner_map: "Dict[str, int]" = None
                             ) -> "Tuple[np.ndarray, Dict[str, int]]":
    """Pack batches into UNPADDED [n, L] rows, resuming from a prior
    interner state: feeding appended batches back in (with the returned
    map) extends the lanes byte-identically to encode_history having seen
    the whole history at once. This is the pack cache's suffix-pack
    primitive (engine/cache.py PackCache): histories are append-only, so
    a re-verify after one appended batch only pays for the suffix.

    Returns (rows, interner_map) — the map is a snapshot (the caller may
    cache it; later calls never mutate an earlier snapshot)."""
    total = history_length(batches)
    out = np.zeros((total, NUM_LANES), dtype=np.int64)
    out[:, LANE_EVENT_TYPE] = -1
    interner = _Interner()
    if interner_map:
        interner._map = dict(interner_map)
    row = 0
    for batch in batches:
        row = _emit_events(out, row, batch.events, interner)
        if batch.new_run_events:
            # fresh interner: the new run's string IDs are a new namespace
            interner = _Interner()
            row = _emit_events(out, row, batch.new_run_events, interner,
                               reset_first=True)
    return out[:row], dict(interner._map)


def assemble_corpus(rows_list: Sequence[np.ndarray],
                    max_events: int = 0) -> np.ndarray:
    """Stack per-workflow UNPADDED [n, L] row blocks into a padded
    [W, E, L] corpus, byte-identical to encode_corpus on the same
    histories (pad rows are zero with event_type -1)."""
    if max_events <= 0:
        max_events = max((r.shape[0] for r in rows_list), default=0)
    W = len(rows_list)
    out = np.zeros((W, max_events, NUM_LANES), dtype=np.int64)
    out[:, :, LANE_EVENT_TYPE] = -1
    for i, rows in enumerate(rows_list):
        out[i, :rows.shape[0]] = rows
    return out


def gather_subcorpus(events: np.ndarray, indices,
                     pad_workflows: int = 0,
                     pad_events: int = 0) -> np.ndarray:
    """Gather flagged rows of a packed [W, E, L] corpus into a compact
    [F', E', L] sub-corpus for widened-K re-replay (engine/ladder.py).

    The event axis is trimmed to the FLAGGED rows' longest real history
    (the whole point of the gather: a 2.7% flagged fraction re-replays a
    ~2.7%-sized corpus, not the original), then padded up to `pad_events`;
    the workflow axis pads up to `pad_workflows`. Padding rows/slots are
    no-op lanes (event_type -1, id 0 — the kernel skips them), so padded
    shapes can be pow2-bucketed for executable reuse without changing any
    real row's result."""
    idx = np.asarray(indices, dtype=np.int64)
    sub = events[idx]
    real = sub[:, :, LANE_EVENT_ID] > 0
    e_real = (int(real.any(axis=0).nonzero()[0].max()) + 1
              if real.any() else 1)
    E = max(e_real, pad_events)
    W = max(len(idx), pad_workflows)
    out = np.zeros((W, E, NUM_LANES), dtype=np.int64)
    out[:, :, LANE_EVENT_TYPE] = -1
    out[:len(idx), :e_real] = sub[:, :e_real]
    return out


def encode_chain(runs: Sequence[Sequence[HistoryBatch]],
                 max_events: int) -> np.ndarray:
    """Pack a continue-as-new chain (a list of runs, each a list of batches)
    into one [E, L] row: each later run starts with FLAG_RUN_RESET."""
    out = np.zeros((max_events, NUM_LANES), dtype=np.int64)
    out[:, LANE_EVENT_TYPE] = -1
    row = 0
    for r, run in enumerate(runs):
        part = encode_history(run, max_events - row)
        n = int((part[:, LANE_EVENT_ID] > 0).sum())
        out[row:row + n] = part[:n]
        if r > 0:
            out[row, LANE_FLAGS] = int(out[row, LANE_FLAGS]) | FLAG_RUN_RESET
        row += n
    return out


def encode_segments(segments: Sequence[tuple], max_events: int) -> np.ndarray:
    """Pack one workflow's branched history tree into [E, L] lanes.

    Each segment is (batches, branch, parent, vh_only):
    - `branch`: version-history branch index these events belong to;
    - `parent`: branch whose items the target branch fork-inherits when it
      receives its first item (versionHistory.go DuplicateUntilLCAItem on
      device); pass parent == branch for no inheritance;
    - `vh_only`: True for events persisted to a non-current branch without
      touching mutable state (ndc conflict resolution's passive persist).

    Segments are emitted in order; interning is shared across segments (all
    branches of a run share the workflow's string namespace)."""
    out = np.zeros((max_events, NUM_LANES), dtype=np.int64)
    out[:, LANE_EVENT_TYPE] = -1
    interner = _Interner()
    row = 0
    for batches, branch, parent, vh_only in segments:
        flags = FLAG_VH_ONLY if vh_only else 0
        for batch in batches:
            if batch.new_run_events:
                # segment encoding is per-run (branch trees belong to ONE
                # run); chains must go through encode_history/encode_chain
                raise ValueError(
                    "segment batch carries new_run_events; encode the "
                    "continued-as-new chain via encode_chain instead"
                )
            row = _emit_events(out, row, batch.events, interner,
                               branch=branch, parent=parent, flags=flags)
    return out


def encode_segment_corpus(workflows: Sequence[Sequence[tuple]],
                          max_events: int = 0) -> np.ndarray:
    """Pack a corpus of branched histories (each a segment list) into
    [W, E, L]."""
    if max_events <= 0:
        max_events = max(
            sum(sum(len(b.events) for b in seg[0]) for seg in segs)
            for segs in workflows
        )
    return np.stack([encode_segments(s, max_events) for s in workflows])


def history_length(batches: Sequence[HistoryBatch]) -> int:
    """Total packed rows for one history, counting chained new-run events."""
    return sum(
        len(b.events) + len(b.new_run_events or ()) for b in batches
    )


def encode_corpus(histories: Sequence[Sequence[HistoryBatch]],
                  max_events: int = 0) -> np.ndarray:
    """Pack a corpus into [W, E, L]; E = max history length (or `max_events`)."""
    if max_events <= 0:
        max_events = max(history_length(h) for h in histories)
    return np.stack([encode_history(h, max_events) for h in histories])


# ---------------------------------------------------------------------------
# Lane decoding (the packer's inverse, for oracle spot-parity on natively
# generated corpora — string identifiers are synthesized from their
# interned keys, which is payload-neutral: the canonical checksum payload
# carries only numeric ids)
# ---------------------------------------------------------------------------

_DECODE_ATTRS = {
    EventType.WorkflowExecutionStarted: (
        "execution_start_to_close_timeout_seconds",
        "task_start_to_close_timeout_seconds",
        "first_decision_task_backoff_seconds", "attempt",
        "expiration_timestamp", None, None, "initiator"),
    EventType.DecisionTaskScheduled: (
        "start_to_close_timeout_seconds", "attempt"),
    EventType.DecisionTaskStarted: ("scheduled_event_id",),
    EventType.DecisionTaskCompleted: ("scheduled_event_id",
                                      "started_event_id"),
    EventType.DecisionTaskTimedOut: ("timeout_type",),
    EventType.ActivityTaskStarted: ("scheduled_event_id",),
    EventType.ActivityTaskCompleted: ("scheduled_event_id",),
    EventType.ActivityTaskFailed: ("scheduled_event_id",),
    EventType.ActivityTaskTimedOut: ("scheduled_event_id",),
    EventType.ActivityTaskCanceled: ("scheduled_event_id",),
}
_INITIATED_REF_TYPES = frozenset({
    EventType.ChildWorkflowExecutionStarted,
    EventType.StartChildWorkflowExecutionFailed,
    EventType.ChildWorkflowExecutionCompleted,
    EventType.ChildWorkflowExecutionFailed,
    EventType.ChildWorkflowExecutionCanceled,
    EventType.ChildWorkflowExecutionTimedOut,
    EventType.ChildWorkflowExecutionTerminated,
    EventType.RequestCancelExternalWorkflowExecutionFailed,
    EventType.ExternalWorkflowExecutionCancelRequested,
    EventType.SignalExternalWorkflowExecutionFailed,
    EventType.ExternalWorkflowExecutionSignaled,
})


def decode_lanes(rows: np.ndarray, domain_id: str = "bench-domain",
                 workflow_id: str = "wf", run_id: str = "run"
                 ) -> List[HistoryBatch]:
    """One workflow's [E, L] lanes → oracle-replayable batches."""
    from ..core.events import HistoryEvent

    batches: List[HistoryBatch] = []
    events: List = []
    for row in rows:
        if row[LANE_EVENT_ID] <= 0:
            continue
        et = EventType(int(row[LANE_EVENT_TYPE]))
        a = [int(v) for v in row[LANE_A0:LANE_A0 + NUM_ATTR_LANES]]
        attrs = {}
        if et == EventType.ActivityTaskScheduled:
            attrs = dict(activity_id=f"act-{a[0]}",
                         schedule_to_start_timeout_seconds=a[1],
                         schedule_to_close_timeout_seconds=a[2],
                         start_to_close_timeout_seconds=a[3],
                         heartbeat_timeout_seconds=a[4])
        elif et == EventType.ActivityTaskCancelRequested:
            attrs = dict(activity_id=f"act-{a[0]}")
        elif et == EventType.TimerStarted:
            attrs = dict(timer_id=f"timer-{a[0]}",
                         start_to_fire_timeout_seconds=a[1])
        elif et in (EventType.TimerFired, EventType.TimerCanceled):
            attrs = dict(timer_id=f"timer-{a[0]}")
        elif et in _INITIATED_REF_TYPES:
            attrs = dict(initiated_event_id=a[0])
        else:
            names = _DECODE_ATTRS.get(et, ())
            for i, name in enumerate(names):
                if name is not None:
                    attrs[name] = a[i]
            if et == EventType.WorkflowExecutionStarted:
                if attrs.get("initiator") == -1:
                    attrs.pop("initiator")
        events.append(HistoryEvent(
            id=int(row[LANE_EVENT_ID]), event_type=et,
            version=int(row[LANE_VERSION]),
            timestamp=int(row[LANE_TIMESTAMP]),
            task_id=int(row[LANE_TASK_ID]), attrs=attrs))
        if row[LANE_BATCH_LAST] == 1:
            batches.append(HistoryBatch(
                domain_id=domain_id, workflow_id=workflow_id,
                run_id=run_id, events=events))
            events = []
    if events:
        raise ValueError("lanes end mid-batch (no batch_last marker)")
    return batches


# ---------------------------------------------------------------------------
# wire32: the int32 transfer format
# ---------------------------------------------------------------------------
# Host→device bytes are the scarce resource on tunneled TPU hosts; all but
# two lanes fit int32 (event IDs, versions, timeouts, interned keys —
# state_builder.go:132-646 consumes nothing wider), so the wire format
# ships 20 int32 lanes instead of 18 int64: the two 64-bit values
# (LANE_TIMESTAMP nanos, and the Started event's absolute
# expiration_timestamp in attr lane 4) travel split as lo/hi halves and
# are reconstructed exactly on device (ops/replay.py widen_wire32).

LANE32_TS_HI = NUM_LANES       # hi-32 of LANE_TIMESTAMP
LANE32_A4_HI = NUM_LANES + 1   # hi-32 of attr lane a4 (expiration nanos)
NUM_LANES32 = NUM_LANES + 2    # 20

_WIDE_LANES = (LANE_TIMESTAMP, LANE_A0 + 4)


def to_wire32(events: np.ndarray) -> np.ndarray:
    """[.., NUM_LANES] int64 → [.., NUM_LANES32] int32 (exact: wide lanes
    split lo/hi). Raises OverflowError if any lane that must fit int32
    doesn't — callers then stay on the int64 path rather than corrupt."""
    ev = np.asarray(events, dtype=np.int64)
    narrow = [i for i in range(NUM_LANES) if i not in _WIDE_LANES]
    lo, hi = np.iinfo(np.int32).min, np.iinfo(np.int32).max
    bad = (ev[..., narrow] < lo) | (ev[..., narrow] > hi)
    if bad.any():
        lanes = sorted({narrow[i] for i in np.argwhere(bad)[:, -1]})
        raise OverflowError(f"lanes {lanes} exceed int32; use the int64 path")
    out = np.empty(ev.shape[:-1] + (NUM_LANES32,), dtype=np.int32)
    out[..., :NUM_LANES] = ev.astype(np.int32)  # wraps → lo32 halves
    out[..., LANE32_TS_HI] = (ev[..., LANE_TIMESTAMP] >> 32).astype(np.int32)
    out[..., LANE32_A4_HI] = (ev[..., LANE_A0 + 4] >> 32).astype(np.int32)
    return out
