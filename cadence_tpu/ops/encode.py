"""Host-side event packing: histories → dense [W, E, L] int64 lane tensors.

The reference decodes thriftrw/JSON event blobs into Go structs per event
(common/persistence/serialization/serializer.go); here batches are packed
into a fixed lane schema the device kernel can scan. String identifiers
(activity IDs, timer IDs) are interned to dense per-workflow integer keys —
state transitions only ever compare them for equality
(state_builder.go:132-646 uses no payload bytes), so payloads stay host-side.

This pure-Python packer is the reference implementation; the C++ packer in
native/ implements the same schema for production feed rates.
"""
from __future__ import annotations

from typing import Dict, List, Sequence

import numpy as np

from ..core.enums import EventType
from ..core.events import HistoryBatch

# Lane indices
LANE_EVENT_ID = 0    # 0 = padding row
LANE_EVENT_TYPE = 1  # EventType value; -1 on padding
LANE_VERSION = 2
LANE_TIMESTAMP = 3
LANE_TASK_ID = 4
LANE_BATCH_FIRST = 5  # first event ID of the enclosing batch
LANE_BATCH_LAST = 6   # 1 if this is the last event of its batch
LANE_A0 = 7
NUM_ATTR_LANES = 8
NUM_LANES = LANE_A0 + NUM_ATTR_LANES  # 15


class _Interner:
    """Per-workflow string → dense int key (starting at 1; 0 = absent)."""

    def __init__(self) -> None:
        self._map: Dict[str, int] = {}

    def key(self, s: str) -> int:
        if s not in self._map:
            self._map[s] = len(self._map) + 1
        return self._map[s]


def _encode_attrs(ev, interner: _Interner) -> List[int]:
    """Per-type attribute lanes a0..a7. Must stay in lockstep with
    transitions.py's lane reads."""
    a = [0] * NUM_ATTR_LANES
    et = ev.event_type
    g = ev.get

    if et == EventType.WorkflowExecutionStarted:
        a[0] = g("execution_start_to_close_timeout_seconds", 0) or 0
        a[1] = g("task_start_to_close_timeout_seconds", 0) or 0
        a[2] = g("first_decision_task_backoff_seconds", 0) or 0
        a[3] = g("attempt", 0) or 0
        a[4] = g("expiration_timestamp", 0) or 0
        a[5] = 1 if g("parent_workflow_id") else 0
        a[6] = 1 if g("retry_policy") is not None else 0
        initiator = g("initiator")
        a[7] = -1 if initiator is None else int(initiator)
    elif et == EventType.DecisionTaskScheduled:
        a[0] = g("start_to_close_timeout_seconds", 0) or 0
        a[1] = g("attempt", 0) or 0
    elif et == EventType.DecisionTaskStarted:
        a[0] = g("scheduled_event_id", 0)
    elif et == EventType.DecisionTaskCompleted:
        a[0] = g("scheduled_event_id", 0)
        a[1] = g("started_event_id", 0)
    elif et == EventType.DecisionTaskTimedOut:
        a[0] = int(g("timeout_type", 0))
    elif et == EventType.ActivityTaskScheduled:
        a[0] = interner.key("act:" + g("activity_id", ""))
        a[1] = g("schedule_to_start_timeout_seconds", 0) or 0
        a[2] = g("schedule_to_close_timeout_seconds", 0) or 0
        a[3] = g("start_to_close_timeout_seconds", 0) or 0
        a[4] = g("heartbeat_timeout_seconds", 0) or 0
        retry = g("retry_policy")
        a[5] = 1 if retry is not None else 0
        a[6] = retry.expiration_interval_seconds if retry is not None else 0
    elif et == EventType.ActivityTaskStarted:
        a[0] = g("scheduled_event_id", 0)
    elif et in (
        EventType.ActivityTaskCompleted,
        EventType.ActivityTaskFailed,
        EventType.ActivityTaskTimedOut,
        EventType.ActivityTaskCanceled,
    ):
        a[0] = g("scheduled_event_id", 0)
    elif et == EventType.ActivityTaskCancelRequested:
        a[0] = interner.key("act:" + g("activity_id", ""))
    elif et == EventType.TimerStarted:
        a[0] = interner.key("timer:" + g("timer_id", ""))
        a[1] = g("start_to_fire_timeout_seconds", 0) or 0
    elif et in (EventType.TimerFired, EventType.TimerCanceled):
        a[0] = interner.key("timer:" + g("timer_id", ""))
    elif et == EventType.ChildWorkflowExecutionStarted:
        a[0] = g("initiated_event_id", 0)
    elif et in (
        EventType.StartChildWorkflowExecutionFailed,
        EventType.ChildWorkflowExecutionCompleted,
        EventType.ChildWorkflowExecutionFailed,
        EventType.ChildWorkflowExecutionCanceled,
        EventType.ChildWorkflowExecutionTimedOut,
        EventType.ChildWorkflowExecutionTerminated,
    ):
        a[0] = g("initiated_event_id", 0)
    elif et in (
        EventType.RequestCancelExternalWorkflowExecutionFailed,
        EventType.ExternalWorkflowExecutionCancelRequested,
        EventType.SignalExternalWorkflowExecutionFailed,
        EventType.ExternalWorkflowExecutionSignaled,
    ):
        a[0] = g("initiated_event_id", 0)
    # remaining types carry no state-relevant attributes
    return a


def encode_history(batches: Sequence[HistoryBatch], max_events: int) -> np.ndarray:
    """Pack one workflow's batched history into [E, L] lanes (zero-padded)."""
    out = np.zeros((max_events, NUM_LANES), dtype=np.int64)
    out[:, LANE_EVENT_TYPE] = -1
    interner = _Interner()
    row = 0
    for batch in batches:
        if batch.new_run_events:
            # continued-as-new chains are split host-side: the caller must
            # append the new run as its own workflow row (the device kernel
            # replays runs, not chains). Loud failure beats silent drop.
            raise ValueError(
                "batch carries new_run_events; split the continued-as-new "
                "run into its own workflow row before encoding"
            )
        first_id = batch.events[0].id
        for j, ev in enumerate(batch.events):
            if row >= max_events:
                raise OverflowError(
                    f"history has more than {max_events} events"
                )
            out[row, LANE_EVENT_ID] = ev.id
            out[row, LANE_EVENT_TYPE] = int(ev.event_type)
            out[row, LANE_VERSION] = ev.version
            out[row, LANE_TIMESTAMP] = ev.timestamp
            out[row, LANE_TASK_ID] = ev.task_id
            out[row, LANE_BATCH_FIRST] = first_id
            out[row, LANE_BATCH_LAST] = 1 if j == len(batch.events) - 1 else 0
            out[row, LANE_A0:] = _encode_attrs(ev, interner)
            row += 1
    return out


def encode_corpus(histories: Sequence[Sequence[HistoryBatch]],
                  max_events: int = 0) -> np.ndarray:
    """Pack a corpus into [W, E, L]; E = max history length (or `max_events`)."""
    if max_events <= 0:
        max_events = max(
            sum(len(b.events) for b in h) for h in histories
        )
    return np.stack([encode_history(h, max_events) for h in histories])
