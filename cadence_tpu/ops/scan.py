"""Columnar visibility scan kernels: query AST → vectorized mask.

The ES tier's esql surface (PAPER §2.4 indexer), reframed the way this
repo reframes everything: visibility rows live as device-resident
COLUMNS (interned string ids, int64 times/status, float64 numeric
search attributes), and a parsed query AST (engine/visibility_query.py
Cmp/And/Or) compiles into one jitted boolean-mask kernel evaluated over
every row at HBM bandwidth. Readback is minimized by construction:

- count: the mask's scalar reduction — 8 bytes off device;
- bitmap: the mask packed to 1 bit/row (matching row ids, nothing else);
- topk: a device argsort over the start-time column returns the first K
  matching row ids in StartTime-DESC order — the paginated List/Scan
  readback is K ids + a count, independent of table size.

Compilation is two-phase so warm queries recompile NOTHING:
- `compile_plan` walks the AST once per query, resolving each leaf
  through a store-provided binder into (column slot, op code) plus the
  leaf's VALUE, which rides in traced parameter vectors — so two
  queries with the same shape (fields + ops) share one executable and
  only the parameters change;
- the kernel builders below are keyed by that structural signature (+
  padded capacity) in a KernelVariantCache, making every compile an
  observable miss counter (the zero-warm-recompile acceptance bar).

Host parity is the contract: every op code reproduces the host
evaluator's semantics exactly — missing values never match, IEEE NaN
(the float column's null) never matches, and cross-type comparisons
reduce at PLAN time to constant TRUE/FALSE leaves mirroring Python's
`==`-is-False / `<`-is-TypeError split. Ordering comparisons on interned
string columns cannot be expressed on device (interning does not
preserve lexicographic order) — the binder refuses them and the store
falls back to the host path (counted, never silently divergent).
"""
from __future__ import annotations

from typing import Callable, Tuple

import jax
import jax.numpy as jnp

from ..engine.visibility_query import And, Cmp, Node, Or

#: interned-id null (row has no value in this column)
NULL_ID = -1

#: leaf op codes (structural — part of the kernel variant signature)
OP_FALSE = 0    # never matches (cross-type ordering, unknown column)
OP_TRUE = 1     # always matches (e.g. int column != non-integral float)
OP_EQ = 2
OP_NE = 3       # guarded by presence on nullable columns
OP_LT = 4
OP_LE = 5
OP_GT = 6
OP_GE = 7
OP_PRESENT = 8  # matches iff the row has a value (id/f64 `!=` vs
                # cross-type constant: present values always differ)

#: column kinds (structural)
COL_ID = "id"    # int64 interned ids, NULL_ID = missing; EQ/NE/PRESENT
COL_I64 = "i64"  # int64, always present (times, status); all six ops
COL_F64 = "f64"  # float64 numeric search attrs, NaN = missing

_INT64_MAX = (1 << 63) - 1
_INT64_MIN = -(1 << 63)


class UnsupportedPredicate(Exception):
    """The query needs host evaluation (string ordering, a column past
    the intern budget, a type-poisoned column). Not an error: the store
    counts it (`reason` picks the fallback counter — "predicate" for an
    inexpressible op, "column" for a column the device cannot carry)
    and serves the host path."""

    def __init__(self, msg: str, reason: str = "predicate") -> None:
        super().__init__(msg)
        self.reason = reason


class ScanPlan:
    """One compiled query: the structural signature (hashable — the
    kernel variant key) plus this query's parameter vectors.

    `leaves` is a tuple of (kind, op_code, slot) triples; `tree` is the
    nested ("and"|"or"|int) structure over leaf indices. `slots` names
    the columns the kernel consumes, in the order the store must pass
    them. Parameters are NOT part of the signature: they ride the
    traced int64/float64 vectors, so same-shape queries share one
    executable. The plan never crosses the jit boundary — kernels close
    over the structure."""

    def __init__(self, tree, leaves: Tuple, slots: Tuple[str, ...],
                 iparams, fparams) -> None:
        self.tree = tree
        self.leaves = leaves
        self.slots = slots
        self.iparams = iparams
        self.fparams = fparams

    @property
    def signature(self):
        return (self.tree, self.leaves, self.slots)

    def __hash__(self):
        return hash(self.signature)

    def __eq__(self, other):
        return (isinstance(other, ScanPlan)
                and self.signature == other.signature)


def plan_leaf_int(op: str, value: object):
    """Normalize a numeric comparison against an int64 column into an
    exact int64 (op_code, param) — or a constant leaf when Python-exact
    semantics say so. Python compares int/float EXACTLY (5 < 5.3 and
    5 == 5.0 are value comparisons, not casts); float64 cannot represent
    every int64, so the float is folded into the integer lattice here at
    plan time instead of casting the column on device."""
    if isinstance(value, bool) or not isinstance(value, (int, float)):
        # bool is int in Python but never produced by the parser; any
        # non-numeric value vs an always-present int column: == False,
        # != True, ordering TypeError→False
        return {"!=": (OP_TRUE, 0)}.get(op, (OP_FALSE, 0))
    if isinstance(value, float):
        if value != value or value in (float("inf"), float("-inf")):
            if value == float("inf"):
                return ((OP_TRUE, 0) if op in ("<", "<=", "!=")
                        else (OP_FALSE, 0))
            if value == float("-inf"):
                return ((OP_TRUE, 0) if op in (">", ">=", "!=")
                        else (OP_FALSE, 0))
            return (OP_TRUE, 0) if op == "!=" else (OP_FALSE, 0)  # NaN
        if float(value).is_integer() and _INT64_MIN <= value <= _INT64_MAX:
            value = int(value)
        else:
            # non-integral: no int equals it; order against the floor
            import math
            f = math.floor(value)
            if f >= _INT64_MAX:
                lo_ops = ("<", "<=")
                return ((OP_TRUE, 0) if op in lo_ops or op == "!="
                        else (OP_FALSE, 0))
            if f < _INT64_MIN:
                hi_ops = (">", ">=")
                return ((OP_TRUE, 0) if op in hi_ops or op == "!="
                        else (OP_FALSE, 0))
            return {
                "=": (OP_FALSE, 0), "!=": (OP_TRUE, 0),
                "<": (OP_LE, f), "<=": (OP_LE, f),
                ">": (OP_GE, f + 1), ">=": (OP_GE, f + 1),
            }[op]
    if not _INT64_MIN <= value <= _INT64_MAX:
        # beyond int64: every stored value is on one known side
        if value > _INT64_MAX:
            return ((OP_TRUE, 0) if op in ("<", "<=", "!=")
                    else (OP_FALSE, 0))
        return ((OP_TRUE, 0) if op in (">", ">=", "!=")
                else (OP_FALSE, 0))
    return {"=": (OP_EQ, value), "!=": (OP_NE, value),
            "<": (OP_LT, value), "<=": (OP_LE, value),
            ">": (OP_GT, value), ">=": (OP_GE, value)}[op]


def compile_plan(node: Node, binder) -> ScanPlan:
    """Walk the AST into a ScanPlan. `binder.leaf(field, op, value)`
    resolves one comparison into (kind, op_code, slot_name, iparam,
    fparam) — the store owns column naming, interning and budget — and
    raises UnsupportedPredicate to route the whole query to the host."""
    import numpy as np

    leaves = []
    slots: list = []
    iparams: list = []
    fparams: list = []

    def walk(n):
        if isinstance(n, And):
            return ("and", walk(n.left), walk(n.right))
        if isinstance(n, Or):
            return ("or", walk(n.left), walk(n.right))
        assert isinstance(n, Cmp)
        kind, op_code, slot_name, ip, fp = binder.leaf(n.field, n.op,
                                                       n.value)
        if slot_name is None:
            slot = -1
        else:
            if slot_name not in slots:
                slots.append(slot_name)
            slot = slots.index(slot_name)
        leaves.append((kind, op_code, slot))
        iparams.append(int(ip))
        fparams.append(float(fp))
        return len(leaves) - 1

    tree = walk(node)
    return ScanPlan(tree, tuple(leaves), tuple(slots),
                    np.asarray(iparams, dtype=np.int64),
                    np.asarray(fparams, dtype=np.float64))


def _leaf_mask(spec, col, ip, fp):
    kind, op_code, _slot = spec
    if op_code == OP_FALSE:
        return None  # caller broadcasts False
    if op_code == OP_TRUE:
        return True  # caller broadcasts True
    if kind == COL_ID:
        if op_code == OP_EQ:
            return col == ip
        if op_code == OP_NE:
            return (col != NULL_ID) & (col != ip)
        return col != NULL_ID  # OP_PRESENT
    if kind == COL_I64:
        return {OP_EQ: col == ip, OP_NE: col != ip, OP_LT: col < ip,
                OP_LE: col <= ip, OP_GT: col > ip,
                OP_GE: col >= ip}[op_code]
    present = ~jnp.isnan(col)
    if op_code == OP_NE:
        return present & (col != fp)
    if op_code == OP_PRESENT:
        return present
    # IEEE: every comparison against NaN is already False — presence is
    # free for EQ/LT/LE/GT/GE
    return {OP_EQ: col == fp, OP_LT: col < fp, OP_LE: col <= fp,
            OP_GT: col > fp, OP_GE: col >= fp}[op_code]


def _tree_mask(tree, leaves, cols, valid, iparams, fparams):
    def eval_node(n):
        if isinstance(n, tuple):
            op, l, r = n
            lm, rm = eval_node(l), eval_node(r)
            if op == "and":
                if lm is None or rm is None:
                    return None
                if lm is True:
                    return rm
                if rm is True:
                    return lm
                return lm & rm
            if lm is True or rm is True:
                return True
            if lm is None:
                return rm
            if rm is None:
                return lm
            return lm | rm
        spec = leaves[n]
        col = cols[spec[2]] if spec[2] >= 0 else None
        return _leaf_mask(spec, col, iparams[n], fparams[n])

    m = eval_node(tree)
    if m is None:
        return jnp.zeros_like(valid)
    if m is True:
        return valid
    return m & valid


def build_count(plan: ScanPlan) -> Callable:
    """count(cols, valid, iparams, fparams) → int64 scalar: match count.
    One 8-byte readback regardless of table size."""
    tree, leaves = plan.tree, plan.leaves

    @jax.jit
    def count(cols, valid, iparams, fparams):
        mask = _tree_mask(tree, leaves, cols, valid, iparams, fparams)
        return jnp.sum(mask, dtype=jnp.int64)

    return count


def build_bitmap(plan: ScanPlan) -> Callable:
    """bitmap(cols, valid, iparams, fparams) → (uint8[ceil(N/8)],
    int64): the mask packed 1 bit/row (numpy-default big bitorder; host
    unpacks with np.unpackbits) plus the match count — matching row ids
    at 1/64th the readback of the id column itself."""
    tree, leaves = plan.tree, plan.leaves

    @jax.jit
    def bitmap(cols, valid, iparams, fparams):
        mask = _tree_mask(tree, leaves, cols, valid, iparams, fparams)
        return jnp.packbits(mask), jnp.sum(mask, dtype=jnp.int64)

    return bitmap


def build_topk(plan: ScanPlan, k: int) -> Callable:
    """topk(cols, valid, start, iparams, fparams) → (int64[k], int64):
    the first k MATCHING row ids in (start_time DESC, row ASC) order —
    a device argsort over the start-time column with non-matching rows
    keyed to the end — plus the total match count. The paged List/Scan
    readback: k ids + a count, independent of table size. Row-ASC tie
    order inside one start_time is the DEVICE order; the store
    re-resolves ties against its host (workflow_id, run_id) order and
    escalates to the bitmap path when a tie straddles the k boundary."""
    tree, leaves = plan.tree, plan.leaves

    @jax.jit
    def topk(cols, valid, start, iparams, fparams):
        mask = _tree_mask(tree, leaves, cols, valid, iparams, fparams)
        n = start.shape[0]
        order = jnp.lexsort((jnp.arange(n, dtype=jnp.int64),
                             -start, ~mask))
        return order[:k], jnp.sum(mask, dtype=jnp.int64)

    return topk


def build_apply(dtypes: Tuple[str, ...]) -> Callable:
    """apply(cols, idx, vals) → cols: scatter one drained delta batch
    (full replacement rows at `idx`) into every column in a single
    device launch. `idx` is padded to its pow2 bucket with
    out-of-range indices, dropped by scatter mode='drop' — padding
    never touches row state. dtypes is structural (one executable per
    column-set shape)."""

    @jax.jit
    def apply(cols, idx, vals):
        return tuple(c.at[idx].set(v, mode="drop")
                     for c, v in zip(cols, vals))

    return apply


def pow2_bucket(n: int, floor: int = 64) -> int:
    """Smallest pow2 ≥ max(n, floor) — delta batches and capacities land
    on shared kernel variants instead of minting one per exact size."""
    b = floor
    while b < n:
        b <<= 1
    return b
