"""Device-side replay: dense state layout, event encoding, transition kernels.

64-bit mode is required: event timestamps are unix nanoseconds and the
checksum payload is defined over int64 lanes. This must run before any jax
arrays are created, which importing this package guarantees for all ops users.
"""
import jax

jax.config.update("jax_enable_x64", True)
