"""The batched replay kernel: scan the event axis, one lockstep step per event.

This is the TPU reframing of the reference's replay call stack
(historyEngine.ReplicateEventsV2 → stateBuilder.ApplyEvents →
Replicate*Event; see SURVEY.md §3.5): instead of one Go goroutine replaying
one workflow's events in a loop, a single jitted `lax.scan` applies event i
of every workflow's (padded) history to all W workflows at once. Sequence
axis = scan (state transitions are inherently sequential per workflow);
workflow axis = vectorization + sharding (parallel/mesh.py).
"""
from __future__ import annotations

from functools import partial
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..core.checksum import DEFAULT_LAYOUT, PayloadLayout, crc32_of_rows
from ..core.events import HistoryBatch
from .encode import encode_corpus
from .payload import payload_rows
from .state import ReplayState, init_state
from .transitions import step


def _scan_body(s: ReplayState, ev: jnp.ndarray) -> Tuple[ReplayState, None]:
    return step(s, ev), None


@partial(jax.jit, static_argnames=("layout", "max_transfer", "max_timer",
                                   "retention_days"))
def replay_events_with_tasks(events: jnp.ndarray,
                             layout: PayloadLayout = DEFAULT_LAYOUT,
                             max_transfer: int = 128,
                             max_timer: int = 128,
                             retention_days: int = 1):
    """Replay with task generation: returns (final state, TaskLog).

    The task-emitting variant of replay_events — the full stateBuilder
    analog (state also feeds the transfer/timer queues, SURVEY.md §3.5).
    """
    from .taskgen import init_task_log, step_tasks

    W = events.shape[0]
    s0 = init_state(W, layout)
    log0 = init_task_log(W, max_transfer, max_timer)

    def body(carry, ev):
        s, log = carry
        s_new = step(s, ev)
        s_new, log = step_tasks(s_new, ev, log, retention_days)
        return (s_new, log), None

    (s, log), _ = jax.lax.scan(body, (s0, log0), jnp.swapaxes(events, 0, 1))
    return s, log


@partial(jax.jit, static_argnames=("layout",))
def replay_events(events: jnp.ndarray,
                  layout: PayloadLayout = DEFAULT_LAYOUT) -> ReplayState:
    """Replay packed events [W, E, L] from a fresh state; returns final state."""
    s0 = init_state(events.shape[0], layout)
    # scan over the event axis: xs must be [E, W, L]
    s, _ = jax.lax.scan(_scan_body, s0, jnp.swapaxes(events, 0, 1))
    return s


@partial(jax.jit, static_argnames=("layout",))
def replay_to_payload(events: jnp.ndarray,
                      layout: PayloadLayout = DEFAULT_LAYOUT
                      ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Replay and reduce to (canonical payload rows [W, width], error [W])."""
    s = replay_events(events, layout)
    return payload_rows(s, layout), s.error


def widen_wire32(ev32: jnp.ndarray) -> jnp.ndarray:
    """[.., NUM_LANES32] int32 → [.., NUM_LANES] int64, reconstructing the
    two wide lanes exactly from their lo/hi halves (encode.to_wire32)."""
    from .encode import LANE32_A4_HI, LANE32_TS_HI, LANE_A0, LANE_TIMESTAMP, NUM_LANES

    base = ev32[..., :NUM_LANES].astype(jnp.int64)
    lo_ts = ev32[..., LANE_TIMESTAMP].astype(jnp.uint32).astype(jnp.int64)
    ts = (ev32[..., LANE32_TS_HI].astype(jnp.int64) << 32) | lo_ts
    lo_a4 = ev32[..., LANE_A0 + 4].astype(jnp.uint32).astype(jnp.int64)
    a4 = (ev32[..., LANE32_A4_HI].astype(jnp.int64) << 32) | lo_a4
    return base.at[..., LANE_TIMESTAMP].set(ts).at[..., LANE_A0 + 4].set(a4)


@partial(jax.jit, static_argnames=("layout",))
def replay_events32(events32: jnp.ndarray,
                    layout: PayloadLayout = DEFAULT_LAYOUT) -> ReplayState:
    """Replay wire32-packed events [W, E, L32] int32: the device-resident
    tensor stays int32 (44% of the int64 bytes in HBM and over the host
    link); each scan step widens its [W, L32] slice on the fly."""
    s0 = init_state(events32.shape[0], layout)

    def body(s, ev32):
        return step(s, widen_wire32(ev32)), None

    s, _ = jax.lax.scan(body, s0, jnp.swapaxes(events32, 0, 1))
    return s


@partial(jax.jit, static_argnames=("layout",))
def replay_to_crc32(events32: jnp.ndarray,
                    layout: PayloadLayout = DEFAULT_LAYOUT
                    ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """wire32 replay reduced to (crc32 [W] uint32, error [W]): the
    minimal-transfer configuration — int32 lanes up, 4 bytes/workflow
    down (the D2H leg is the bottleneck on tunneled TPU hosts)."""
    from .crc import crc32_rows

    s = replay_events32(events32, layout)
    return crc32_rows(payload_rows(s, layout)), s.error


@partial(jax.jit, static_argnames=("profile", "layout"))
def replay_wirec(slab: jnp.ndarray, bases: jnp.ndarray,
                 n_events: jnp.ndarray, profile,
                 layout: PayloadLayout = DEFAULT_LAYOUT) -> ReplayState:
    """Replay a wirec-compressed corpus ([W, E, B] uint8 slab +
    per-workflow bases/counts, ops/wirec.py): each scan step decodes ONE
    event column in registers — delta lanes ride the scan carry, so the
    dense int64 tensor never materializes in HBM and only the compressed
    bytes ever cross the host link."""
    from .wirec import decode_step, delta_base_columns

    W, E, _ = slab.shape
    s0 = init_state(W, layout)
    cols = delta_base_columns(profile)
    prev0 = (bases[:, list(cols)] if cols
             else jnp.zeros((W, 0), dtype=jnp.int64))

    def body(carry, xs):
        s, prev = carry
        sl, e_idx = xs
        ev, prev = decode_step(sl, prev, bases, n_events, e_idx, profile)
        return (step(s, ev), prev), None

    (s, _), _ = jax.lax.scan(
        body, (s0, prev0),
        (jnp.swapaxes(slab, 0, 1), jnp.arange(E, dtype=n_events.dtype)))
    return s


@partial(jax.jit, static_argnames=("profile", "layout"))
def replay_wirec_to_crc(slab: jnp.ndarray, bases: jnp.ndarray,
                        n_events: jnp.ndarray, profile,
                        layout: PayloadLayout = DEFAULT_LAYOUT
                        ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """wirec replay reduced to (crc32 [W] uint32, error [W]): the
    minimal-transfer product path — ~10-18 compressed bytes/event up,
    4 bytes/workflow down."""
    from .crc import crc32_rows

    s = replay_wirec(slab, bases, n_events, profile, layout)
    return crc32_rows(payload_rows(s, layout)), s.error


# ---------------------------------------------------------------------------
# Incremental (from-state) replay: the O(new-events) append kernels.
#
# The existing kernels all start from init_state — O(history) per call.
# These take a CARRIED initial state instead (the HBM-resident
# per-workflow states engine/resident.py pins between calls), so an
# append-transaction replays only the new batches: the device analogue
# of the reference applying just the new events to the execution cache's
# warm mutable state (historyEngine + execution/cache.go) instead of
# rebuilding from event 0.
# ---------------------------------------------------------------------------


@jax.jit
def replay_from_state(events: jnp.ndarray, s0: ReplayState) -> ReplayState:
    """Replay packed suffix events [W, E, L] against carried state `s0`
    (whose shapes imply the layout — base or ladder-widened); returns the
    final state. With s0 = init_state this is exactly replay_events."""
    s, _ = jax.lax.scan(_scan_body, s0, jnp.swapaxes(events, 0, 1))
    return s


@partial(jax.jit, static_argnames=("out_layout",))
def replay_from_state_to_payload(events: jnp.ndarray, s0: ReplayState,
                                 out_layout: PayloadLayout = DEFAULT_LAYOUT):
    """From-state replay reduced to the serving shape: (final state,
    payload rows at `out_layout` width, error [W], narrow_overflow [W]).
    The state may be ladder-widened; the payload always projects to the
    BASE width the oracle and stored checksums use — same contract as
    replay_escalated."""
    from .payload import payload_rows_narrow

    s = replay_from_state(events, s0)
    rows, ovf = payload_rows_narrow(s, out_layout)
    return s, rows, s.error, ovf


@partial(jax.jit, static_argnames=("out_layout",))
def replay_from_state_to_crc(events: jnp.ndarray, s0: ReplayState,
                             out_layout: PayloadLayout = DEFAULT_LAYOUT
                             ) -> Tuple[jnp.ndarray, jnp.ndarray,
                                        jnp.ndarray]:
    """From-state replay reduced to (crc32 [W] uint32, error [W],
    narrow_overflow [W]) — the minimal-readback append transaction:
    suffix lanes up, 4 bytes/workflow down."""
    from .crc import crc32_rows
    from .payload import payload_rows_narrow

    s = replay_from_state(events, s0)
    rows, ovf = payload_rows_narrow(s, out_layout)
    return crc32_rows(rows), s.error, ovf


@partial(jax.jit, static_argnames=("profile",))
def replay_wirec_from_state(slab: jnp.ndarray, bases: jnp.ndarray,
                            n_events: jnp.ndarray, profile,
                            s0: ReplayState) -> ReplayState:
    """From-state replay of a wirec-compressed SUFFIX corpus: the suffix
    packs as its own corpus (bases are its first-row values), so decode
    is self-contained and only the appended batches' compressed bytes
    ever cross the link."""
    from .wirec import decode_step, delta_base_columns

    W, E, _ = slab.shape
    cols = delta_base_columns(profile)
    prev0 = (bases[:, list(cols)] if cols
             else jnp.zeros((W, 0), dtype=jnp.int64))

    def body(carry, xs):
        s, prev = carry
        sl, e_idx = xs
        ev, prev = decode_step(sl, prev, bases, n_events, e_idx, profile)
        return (step(s, ev), prev), None

    (s, _), _ = jax.lax.scan(
        body, (s0, prev0),
        (jnp.swapaxes(slab, 0, 1), jnp.arange(E, dtype=n_events.dtype)))
    return s


@partial(jax.jit, static_argnames=("profile", "out_layout"))
def replay_wirec_from_state_to_payload(slab: jnp.ndarray,
                                       bases: jnp.ndarray,
                                       n_events: jnp.ndarray, profile,
                                       s0: ReplayState,
                                       out_layout: PayloadLayout
                                       = DEFAULT_LAYOUT):
    """wirec from-state replay reduced to the serving shape: (final
    state, payload rows at `out_layout` width, error [W],
    narrow_overflow [W]) — the compressed-transfer twin of
    replay_from_state_to_payload, so the resident append path ships
    ~10-18 B/event of suffix instead of 144 dense bytes."""
    from .payload import payload_rows_narrow

    s = replay_wirec_from_state(slab, bases, n_events, profile, s0)
    rows, ovf = payload_rows_narrow(s, out_layout)
    return s, rows, s.error, ovf


@partial(jax.jit, static_argnames=("profile", "out_layout"))
def replay_wirec_from_state_to_crc(slab: jnp.ndarray, bases: jnp.ndarray,
                                   n_events: jnp.ndarray, profile,
                                   s0: ReplayState,
                                   out_layout: PayloadLayout = DEFAULT_LAYOUT
                                   ) -> Tuple[jnp.ndarray, jnp.ndarray,
                                              jnp.ndarray]:
    """wirec from-state replay reduced to (crc32 [W] uint32, error [W],
    narrow_overflow [W])."""
    from .crc import crc32_rows
    from .payload import payload_rows_narrow

    s = replay_wirec_from_state(slab, bases, n_events, profile, s0)
    rows, ovf = payload_rows_narrow(s, out_layout)
    return crc32_rows(rows), s.error, ovf


@partial(jax.jit, static_argnames=("layout", "out_layout"))
def replay_escalated(events: jnp.ndarray, layout: PayloadLayout,
                     out_layout: PayloadLayout = DEFAULT_LAYOUT
                     ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray,
                                jnp.ndarray]:
    """One escalation rung: re-replay a flagged sub-corpus [F, E, L] at a
    WIDENED capacity `layout` (engine/ladder.py doubles K per rung) and
    project the canonical payload back down to `out_layout` — the base
    width the oracle and stored checksums use. Returns (rows
    [F, out_width], error [F], narrow_overflow [F], current_branch [F]);
    a row is resolved when error == 0 and narrow_overflow is unset."""
    from .payload import payload_rows_narrow

    s = replay_events(events, layout)
    rows, ovf = payload_rows_narrow(s, out_layout)
    return rows, s.error, ovf, s.current_branch


@partial(jax.jit, static_argnames=("layout", "out_layout"))
def replay_escalated_state(events: jnp.ndarray, layout: PayloadLayout,
                           out_layout: PayloadLayout = DEFAULT_LAYOUT):
    """Ladder rung variant that also returns the full widened ReplayState:
    the rebuild path (engine/rebuild.py) hydrates pending tables straight
    out of the widened state's occupied slots."""
    from .payload import payload_rows_narrow

    s = replay_events(events, layout)
    rows, ovf = payload_rows_narrow(s, out_layout)
    return s, rows, s.error, ovf


@partial(jax.jit, static_argnames=("profile", "layout", "out_layout"))
def replay_wirec_escalated_crc(slab: jnp.ndarray, bases: jnp.ndarray,
                               n_events: jnp.ndarray, profile,
                               layout: PayloadLayout,
                               out_layout: PayloadLayout = DEFAULT_LAYOUT
                               ) -> Tuple[jnp.ndarray, jnp.ndarray,
                                          jnp.ndarray]:
    """Escalation rung over a wirec-compressed flagged sub-corpus: decode
    + widened replay + base-width payload + CRC32 all on device — the
    bulk-bench fallback leg's configuration (4 bytes/flagged-row back).
    Returns (crc32 [F] uint32, error [F], narrow_overflow [F])."""
    from .crc import crc32_rows
    from .payload import payload_rows_narrow

    s = replay_wirec(slab, bases, n_events, profile, layout)
    rows, ovf = payload_rows_narrow(s, out_layout)
    return crc32_rows(rows), s.error, ovf


@jax.jit
def verify_rows(rows: jnp.ndarray, expected_rows: jnp.ndarray,
                branch: jnp.ndarray, expected_branch: jnp.ndarray
                ) -> jnp.ndarray:
    """Device-side verify_all compare: payload rows and the device-chosen
    current branch against the expected (live mutable-state) values, ON
    DEVICE — the host reads back one mismatch bit per workflow instead of
    the full [W, width] payload tensor. A set bit means row divergence OR
    branch-arbitration disagreement (verify_all treats both as
    divergent, so the OR loses nothing)."""
    row_mismatch = (rows != expected_rows).any(axis=1)
    return row_mismatch | (branch != expected_branch.astype(branch.dtype))


def replay_corpus(histories: Sequence[Sequence[HistoryBatch]],
                  layout: PayloadLayout = DEFAULT_LAYOUT,
                  max_events: int = 0,
                  ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Host helper: encode histories, replay on the default backend, and
    return (payload_rows, crc32s, errors) as numpy arrays. Legs land in
    the default registry's SCOPE_TPU_REPLAY histograms (utils/profiler)."""
    from ..utils import metrics as m
    from ..utils.profiler import ReplayProfiler

    prof = ReplayProfiler()
    with prof.leg(m.M_PROFILE_PACK):
        events = encode_corpus(histories, max_events)
    with prof.leg(m.M_PROFILE_H2D):
        device_events = jax.device_put(jnp.asarray(events))
        prof.h2d(events.nbytes)
    with prof.leg(m.M_PROFILE_KERNEL):
        rows, errors = replay_to_payload(device_events, layout)
        jax.block_until_ready(rows)
    with prof.leg(m.M_PROFILE_READBACK):
        rows_np = np.asarray(rows)
        errors_np = np.asarray(errors)
    return rows_np, crc32_of_rows(rows_np), errors_np
