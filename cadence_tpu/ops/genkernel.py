"""Device-side corpus generator: distinct histories born where they replay.

The north-star bench needs 1M x 1k-event DISTINCT histories. Generating
them on host and shipping 144GB of lanes through the host→device link
makes the link the benchmark; the TPU-first formulation generates each
event ON DEVICE inside the same `lax.scan` that replays it — a stochastic
workflow simulator (per-workflow counter-based splitmix64 stream, fully
reproducible from (seed, workflow_index, step)) emitting one event per
workflow per step, fused with the transition kernel so the corpus never
materializes anywhere.

The emitted sequences follow engine-shaped rules: start → decision cycles
(scheduled → started → completed) interleaved with activity
schedule/start/close chains, user timers, child workflows, and signals;
every pending entity resolves before the close, capacities stay below the
kernel's tables, and every history ends with WorkflowExecutionCompleted.
`generate_lanes` materializes the identical rows (same RNG stream) for
small samples so the ORACLE can replay and cross-check payloads
(ops/encode.decode_lanes) — the spot-parity contract.
"""
from __future__ import annotations

from functools import partial
from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp

from ..core.checksum import DEFAULT_LAYOUT, PayloadLayout
from ..core.enums import EventType
from .encode import (
    LANE_A0,
    LANE_BATCH_FIRST,
    LANE_BATCH_LAST,
    LANE_EVENT_ID,
    LANE_EVENT_TYPE,
    LANE_TASK_ID,
    LANE_TIMESTAMP,
    LANE_VERSION,
    NUM_LANES,
)

I64 = jnp.int64
NANOS_MS = 1_000_000


class GenState(NamedTuple):
    ts: jnp.ndarray           # [W] i64 nanos
    phase: jnp.ndarray        # [W] i32: 0 none, 1 scheduled, 2 started
    dsched: jnp.ndarray       # [W] i64
    dstart: jnp.ndarray       # [W] i64
    act_occ: jnp.ndarray      # [W, 4] bool
    act_sched: jnp.ndarray    # [W, 4] i64
    act_started: jnp.ndarray  # [W, 4] bool
    act_count: jnp.ndarray    # [W] i64 (interned-key counter)
    tmr_occ: jnp.ndarray      # [W, 3] bool
    tmr_key: jnp.ndarray      # [W, 3] i64
    tmr_count: jnp.ndarray    # [W] i64
    ch_occ: jnp.ndarray       # [W, 2] bool
    ch_init: jnp.ndarray      # [W, 2] i64
    ch_started: jnp.ndarray   # [W, 2] bool


# action codes
A_STARTED, A_DSCHED, A_DSTART, A_DCOMPLETE = 0, 1, 2, 3
A_ASCHED, A_ASTART, A_ACLOSE = 4, 5, 6
A_TSTART, A_TFIRE = 7, 8
A_CINIT, A_CSTART, A_CCLOSE = 9, 10, 11
A_SIGNAL, A_WFCLOSE = 12, 13

_CODE_TO_TYPE = jnp.array([
    int(EventType.WorkflowExecutionStarted),
    int(EventType.DecisionTaskScheduled),
    int(EventType.DecisionTaskStarted),
    int(EventType.DecisionTaskCompleted),
    int(EventType.ActivityTaskScheduled),
    int(EventType.ActivityTaskStarted),
    int(EventType.ActivityTaskCompleted),
    int(EventType.TimerStarted),
    int(EventType.TimerFired),
    int(EventType.StartChildWorkflowExecutionInitiated),
    int(EventType.ChildWorkflowExecutionStarted),
    int(EventType.ChildWorkflowExecutionCompleted),
    int(EventType.WorkflowExecutionSignaled),
    int(EventType.WorkflowExecutionCompleted),
], dtype=I64)


def _mix(seed: jnp.ndarray, w: jnp.ndarray, step, salt: int) -> jnp.ndarray:
    """splitmix64-style counter hash; int64 wraparound is the ring."""
    z = (seed + w * jnp.int64(-7046029254386353131)
         + jnp.int64(step) * jnp.int64(6364136223846793005)
         + jnp.int64(salt) * jnp.int64(1442695040888963407))
    z = (z ^ (z >> 30)) * jnp.int64(-4658895280553007687)
    z = (z ^ (z >> 27)) * jnp.int64(-7723592293110705685)
    return z ^ (z >> 31)


def _die(r: jnp.ndarray, n: int) -> jnp.ndarray:
    return jnp.abs(r) % n


def _first(mask: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(onehot of first True per row, any per row)."""
    K = mask.shape[1]
    idx = jnp.argmax(mask, axis=1)
    onehot = (jnp.arange(K)[None, :] == idx[:, None]) & mask.any(
        axis=1)[:, None]
    return onehot, mask.any(axis=1)


def init_gen_state(num_workflows: int, seed: int,
                   first_index: int) -> GenState:
    W = num_workflows
    w = jnp.arange(W, dtype=I64) + jnp.int64(first_index)
    jitter = jnp.abs(_mix(jnp.int64(seed), w, 0, 17)) % 1_000_000
    return GenState(
        ts=jnp.int64(1_700_000_000_000_000_000) + jitter * NANOS_MS,
        phase=jnp.zeros((W,), jnp.int32),
        dsched=jnp.zeros((W,), I64),
        dstart=jnp.zeros((W,), I64),
        act_occ=jnp.zeros((W, 4), bool),
        act_sched=jnp.zeros((W, 4), I64),
        act_started=jnp.zeros((W, 4), bool),
        act_count=jnp.zeros((W,), I64),
        tmr_occ=jnp.zeros((W, 3), bool),
        tmr_key=jnp.zeros((W, 3), I64),
        tmr_count=jnp.zeros((W,), I64),
        ch_occ=jnp.zeros((W, 2), bool),
        ch_init=jnp.zeros((W, 2), I64),
        ch_started=jnp.zeros((W, 2), bool),
    )


def gen_step(g: GenState, seed: int, first_index: int, step: int,
             total_events: int):
    """Emit event lanes [W, NUM_LANES] for scan step `step` and advance the
    generator state. Every workflow emits exactly one REAL event per step;
    ids are therefore step+1 for all workflows."""
    W = g.ts.shape[0]
    w = jnp.arange(W, dtype=I64) + jnp.int64(first_index)
    s = jnp.int64(seed)
    r0 = _mix(s, w, step, 1)
    r1 = _mix(s, w, step, 2)
    r2 = _mix(s, w, step, 3)
    r3 = _mix(s, w, step, 4)

    eid = jnp.full((W,), step + 1, I64)
    ts = g.ts + (_die(r3, 5000) + 1) * NANOS_MS

    pending = (g.act_occ.sum(axis=1) + g.tmr_occ.sum(axis=1)
               + g.ch_occ.sum(axis=1)).astype(I64)
    # unstarted activities/children need TWO drain events (start, close):
    # the engine never produces a Completed event for an unstarted item,
    # so the drain must not either (generator.cc mirrors this)
    n_unstarted = ((g.act_occ & ~g.act_started).sum(axis=1)
                   + (g.ch_occ & ~g.ch_started).sum(axis=1)).astype(I64)
    remaining = jnp.int64(total_events - step)
    # margin 4: one normal step can grow pending+n_unstarted by 2 (a
    # schedule/init event creates an occupied AND unstarted item) while
    # remaining drops 1, overshooting a tighter threshold by 2
    drain = remaining <= pending + n_unstarted + 4

    # -- choose the action code -------------------------------------------
    # normal mode by decision phase
    die = _die(r0, 16)
    die2 = _die(r1, 8)
    act_free = ~g.act_occ.all(axis=1)
    act_unstarted = (g.act_occ & ~g.act_started).any(axis=1)
    act_any = g.act_occ.any(axis=1)
    # closes only ever land on STARTED items: the engine cannot produce
    # ActivityTaskCompleted / ChildWorkflowExecutionCompleted without a
    # preceding Started event (state_builder.go replicate order)
    act_started_any = (g.act_occ & g.act_started).any(axis=1)
    tmr_free = ~g.tmr_occ.all(axis=1)
    tmr_any = g.tmr_occ.any(axis=1)
    ch_free = ~g.ch_occ.all(axis=1)
    ch_unstarted = (g.ch_occ & ~g.ch_started).any(axis=1)
    ch_any = g.ch_occ.any(axis=1)
    ch_started_any = (g.ch_occ & g.ch_started).any(axis=1)

    external = jnp.select(
        [die2 <= 1, die2 == 2, die2 == 3, die2 == 4, die2 == 5,
         die2 == 6, die2 == 7],
        [jnp.where(act_free, A_ASCHED, A_SIGNAL),
         jnp.where(act_unstarted, A_ASTART, A_SIGNAL),
         jnp.where(act_started_any, A_ACLOSE, A_SIGNAL),
         jnp.where(tmr_free, A_TSTART,
                   jnp.where(tmr_any, A_TFIRE, A_SIGNAL)),
         jnp.where(tmr_any, A_TFIRE, A_SIGNAL),
         jnp.where(ch_free, A_CINIT,
                   jnp.where(ch_started_any, A_CCLOSE, A_SIGNAL)),
         jnp.where(ch_unstarted, A_CSTART,
                   jnp.where(ch_started_any, A_CCLOSE, A_SIGNAL))],
        A_SIGNAL)
    normal = jnp.select(
        [g.phase == 1, g.phase == 2],
        [jnp.where(die < 13, A_DSTART, A_SIGNAL),
         jnp.where(die < 6, A_DCOMPLETE, external)],
        jnp.where(die < 8, A_DSCHED, external))

    # start-before-close within each family: closes pick the FIRST occupied
    # slot, and all starts precede all closes, so a close never lands on an
    # unstarted item — the history shape the real engine produces
    drained = jnp.select(
        [act_unstarted, act_any, ch_unstarted, tmr_any, ch_any,
         remaining > 1],
        [A_ASTART, A_ACLOSE, A_CSTART, A_TFIRE, A_CCLOSE, A_SIGNAL],
        A_WFCLOSE)

    code = jnp.where(drain, drained, normal)
    code = jnp.where(eid == 1, A_STARTED, code)
    code = jnp.where(eid == 2, A_DSCHED, code)

    def m(k):
        return code == k

    # -- per-action state updates + attr lanes ----------------------------
    a = [jnp.zeros((W,), I64) for _ in range(8)]

    # Started
    a[0] = jnp.where(m(A_STARTED), 600 + _die(r2, 6600), a[0])
    a[1] = jnp.where(m(A_STARTED), 10, a[1])
    a[7] = jnp.where(m(A_STARTED), -1, a[7])

    # decision machine
    a[0] = jnp.where(m(A_DSCHED), 10, a[0])
    phase = jnp.where(m(A_DSCHED), 1, g.phase)
    dsched = jnp.where(m(A_DSCHED), eid, g.dsched)
    a[0] = jnp.where(m(A_DSTART), dsched, a[0])
    phase = jnp.where(m(A_DSTART), 2, phase)
    dstart = jnp.where(m(A_DSTART), eid, g.dstart)
    a[0] = jnp.where(m(A_DCOMPLETE), dsched, a[0])
    a[1] = jnp.where(m(A_DCOMPLETE), dstart, a[1])
    phase = jnp.where(m(A_DCOMPLETE), 0, phase)

    # activities
    ins, _ = _first(~g.act_occ)
    ins = ins & m(A_ASCHED)[:, None]
    act_occ = g.act_occ | ins
    act_sched = jnp.where(ins, eid[:, None], g.act_sched)
    act_started = g.act_started & ~ins
    act_count = g.act_count + m(A_ASCHED)
    a[0] = jnp.where(m(A_ASCHED), act_count, a[0])       # interned key
    a[1] = jnp.where(m(A_ASCHED), 5 + _die(r2, 115), a[1])
    a[2] = jnp.where(m(A_ASCHED), 30 + _die(r2, 570), a[2])
    a[3] = jnp.where(m(A_ASCHED), 10 + _die(r3, 290), a[3])

    sel, _ = _first(act_occ & ~act_started)
    sel = sel & m(A_ASTART)[:, None]
    a[0] = jnp.where(m(A_ASTART),
                     jnp.where(sel, act_sched, 0).sum(axis=1), a[0])
    act_started = act_started | sel

    sel, _ = _first(act_occ & act_started)
    sel = sel & m(A_ACLOSE)[:, None]
    a[0] = jnp.where(m(A_ACLOSE),
                     jnp.where(sel, act_sched, 0).sum(axis=1), a[0])
    act_occ = act_occ & ~sel
    act_started = act_started & ~sel

    # timers
    ins, _ = _first(~g.tmr_occ)
    ins = ins & m(A_TSTART)[:, None]
    tmr_count = g.tmr_count + m(A_TSTART)
    tmr_occ = g.tmr_occ | ins
    tmr_key = jnp.where(ins, tmr_count[:, None], g.tmr_key)
    a[0] = jnp.where(m(A_TSTART), tmr_count, a[0])
    a[1] = jnp.where(m(A_TSTART), 1 + _die(r2, 600), a[1])

    sel, _ = _first(tmr_occ)
    sel = sel & m(A_TFIRE)[:, None]
    a[0] = jnp.where(m(A_TFIRE),
                     jnp.where(sel, tmr_key, 0).sum(axis=1), a[0])
    tmr_occ = tmr_occ & ~sel

    # children
    ins, _ = _first(~g.ch_occ)
    ins = ins & m(A_CINIT)[:, None]
    ch_occ = g.ch_occ | ins
    ch_init = jnp.where(ins, eid[:, None], g.ch_init)
    ch_started = g.ch_started & ~ins

    sel, _ = _first(ch_occ & ~ch_started)
    sel = sel & m(A_CSTART)[:, None]
    a[0] = jnp.where(m(A_CSTART),
                     jnp.where(sel, ch_init, 0).sum(axis=1), a[0])
    ch_started = ch_started | sel

    sel, _ = _first(ch_occ & ch_started)
    sel = sel & m(A_CCLOSE)[:, None]
    a[0] = jnp.where(m(A_CCLOSE),
                     jnp.where(sel, ch_init, 0).sum(axis=1), a[0])
    ch_occ = ch_occ & ~sel
    ch_started = ch_started & ~sel

    # -- assemble lanes ----------------------------------------------------
    lanes = jnp.zeros((W, NUM_LANES), I64)
    lanes = lanes.at[:, LANE_EVENT_ID].set(eid)
    lanes = lanes.at[:, LANE_EVENT_TYPE].set(_CODE_TO_TYPE[code])
    lanes = lanes.at[:, LANE_VERSION].set(0)
    lanes = lanes.at[:, LANE_TIMESTAMP].set(ts)
    lanes = lanes.at[:, LANE_TASK_ID].set(eid + 1000)
    lanes = lanes.at[:, LANE_BATCH_FIRST].set(eid)  # one event per batch
    lanes = lanes.at[:, LANE_BATCH_LAST].set(1)
    for i in range(8):
        lanes = lanes.at[:, LANE_A0 + i].set(a[i])

    return GenState(ts=ts, phase=phase, dsched=dsched, dstart=dstart,
                    act_occ=act_occ, act_sched=act_sched,
                    act_started=act_started, act_count=act_count,
                    tmr_occ=tmr_occ, tmr_key=tmr_key, tmr_count=tmr_count,
                    ch_occ=ch_occ, ch_init=ch_init,
                    ch_started=ch_started), lanes


@partial(jax.jit, static_argnames=("num_workflows", "total_events"))
def generate_lanes(seed: int, first_index: int, num_workflows: int,
                   total_events: int) -> jnp.ndarray:
    """Materialize [W, E, L] lanes (for samples, tests, and oracle
    cross-checks — identical to what the fused path replays)."""
    g0 = init_gen_state(num_workflows, seed, first_index)

    def body(g, step):
        g, lanes = gen_step(g, seed, first_index, step, total_events)
        return g, lanes

    _, lanes = jax.lax.scan(body, g0, jnp.arange(total_events), unroll=2)
    return jnp.swapaxes(lanes, 0, 1)  # [W, E, L]


def _fused_scan(g0, s0, seed, first_index, total_events: int,
                layout: PayloadLayout, to_crc: bool = False):
    from .payload import payload_rows
    from .transitions import step as replay_step

    def body(carry, step):
        g, s = carry
        g, lanes = gen_step(g, seed, first_index, step, total_events)
        # the generator never emits FLAG_RUN_RESET: compile the
        # run-boundary blend out (also keeps shard_map happy — see step())
        s = replay_step(s, lanes, enable_reset=False)
        return (g, s), None

    (_, s), _ = jax.lax.scan(body, (g0, s0), jnp.arange(total_events),
                             unroll=2)
    rows = payload_rows(s, layout)
    if to_crc:
        # checksum on chip: the host pulls 4 bytes/workflow, not the row —
        # D2H is the scarce resource on tunneled TPU hosts
        from .crc import crc32_rows
        return crc32_rows(rows), s.error
    return rows, s.error


@partial(jax.jit, static_argnames=("num_workflows", "total_events", "layout"))
def generate_and_replay(seed: int, first_index: int, num_workflows: int,
                        total_events: int,
                        layout: PayloadLayout = DEFAULT_LAYOUT):
    """The fused north-star step: generate each event and apply it to the
    replay state in the SAME scan iteration — the corpus never exists as a
    tensor. Returns (payload rows [W, width], errors [W])."""
    from .state import init_state

    g0 = init_gen_state(num_workflows, seed, first_index)
    s0 = init_state(num_workflows, layout)
    return _fused_scan(g0, s0, seed, first_index, total_events, layout)


@partial(jax.jit, static_argnames=("num_workflows", "total_events", "layout"))
def generate_and_replay_crc(seed: int, first_index: int, num_workflows: int,
                            total_events: int,
                            layout: PayloadLayout = DEFAULT_LAYOUT):
    """Fused north-star step reduced to (crc32 [W] uint32, errors [W]):
    generation, replay, canonical payload, and checksum all on device —
    the host pulls 4 bytes per workflow."""
    from .state import init_state

    g0 = init_gen_state(num_workflows, seed, first_index)
    s0 = init_state(num_workflows, layout)
    return _fused_scan(g0, s0, seed, first_index, total_events, layout,
                       to_crc=True)


#: compiled sharded executables keyed by (mesh, local_W, E, layout) —
#: rebuilt closures would defeat the jit cache and recompile every call
_SHARDED_CACHE: dict = {}


def _sharded_fn(mesh, local: int, total_events: int,
                layout: PayloadLayout, to_crc: bool = False):
    # jax.shard_map is the stable home (jax.experimental.shard_map is
    # deprecated since 0.8); keep the fallback for older pins
    shard_map = getattr(jax, "shard_map", None)
    if shard_map is None:  # pragma: no cover - older JAX
        from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    from .state import init_state

    key = (mesh, local, total_events, layout, to_crc)
    fn = _SHARDED_CACHE.get(key)
    if fn is not None:
        return fn

    def local_fn(seed, offset):
        first = offset[0]
        # mark the constant-built initial carries as varying across the
        # mesh (each shard's trajectory differs), or scan/cond typing
        # rejects the mix of replicated carries with shard-varying lanes.
        # Pre-typeof JAX (<0.6) has no varying-manual-axes typing at all:
        # no lifting is needed (or possible — pvary/pcast don't exist),
        # so the tree passes through untouched there.
        def varying(tree):
            if not hasattr(jax, "typeof"):
                return tree

            def pv(x):
                # only lift replicated leaves; some (built from the traced
                # offset) are already shard-varying
                if "shard" in getattr(jax.typeof(x), "vma", ()):
                    return x
                if hasattr(jax.lax, "pcast"):
                    # pvary's replacement (deprecated since 0.9)
                    return jax.lax.pcast(x, ("shard",), to="varying")
                return jax.lax.pvary(x, ("shard",))
            return jax.tree_util.tree_map(pv, tree)

        g0 = varying(init_gen_state(local, seed, first))
        s0 = varying(init_state(local, layout))
        return _fused_scan(g0, s0, seed, first, total_events, layout,
                           to_crc=to_crc)

    fn = jax.jit(shard_map(local_fn, mesh=mesh, in_specs=(None, P("shard")),
                           out_specs=(P("shard"), P("shard"))))
    _SHARDED_CACHE[key] = fn
    return fn


def generate_and_replay_sharded(seed: int, first_index: int,
                                num_workflows: int, total_events: int,
                                mesh,
                                layout: PayloadLayout = DEFAULT_LAYOUT):
    """SPMD north-star step over a device mesh: every device runs the fused
    generator+replay on its own workflow-index range (pure data
    parallelism — per-workflow RNG streams make shards independent), so a
    multi-chip host actually exercises all chips. Workflow count must
    divide by the mesh size. Identical outputs to the single-device path
    for the same (seed, index) range. The compiled executable is cached
    per (mesh, shape): seed and offsets are traced arguments, so repeated
    chunks reuse it."""
    n = mesh.devices.size
    if num_workflows % n:
        raise ValueError(f"workflows {num_workflows} not divisible by "
                         f"mesh size {n}")
    local = num_workflows // n
    offsets = jnp.asarray(first_index + jnp.arange(n) * local, I64)
    fn = _sharded_fn(mesh, local, total_events, layout)
    return fn(jnp.int64(seed), offsets)


def generate_and_replay_sharded_crc(seed: int, first_index: int,
                                    num_workflows: int, total_events: int,
                                    mesh,
                                    layout: PayloadLayout = DEFAULT_LAYOUT):
    """SPMD fused step reduced on device to (crc32 [W], errors [W])."""
    n = mesh.devices.size
    if num_workflows % n:
        raise ValueError(f"workflows {num_workflows} not divisible by "
                         f"mesh size {n}")
    local = num_workflows // n
    offsets = jnp.asarray(first_index + jnp.arange(n) * local, I64)
    fn = _sharded_fn(mesh, local, total_events, layout, to_crc=True)
    return fn(jnp.int64(seed), offsets)
