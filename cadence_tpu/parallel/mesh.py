"""Device mesh + shardings: the TPU analog of the reference's shard fabric.

The reference scales by hashing workflow IDs onto history shards owned by
hosts via a consistent hashring (common/config/config.go:170-173,
membership/resolver.go:169, shard/controller.go). Here the same axis —
"which workflows live where" — is a sharded array dimension: workflows are
partitioned over the mesh's 'shard' axis and the replay kernel runs SPMD
with XLA inserting collectives only where results are aggregated (global
error counts, corpus-level checksums) — those ride ICI within a slice and
DCN across slices, replacing the reference's gRPC fan-out.

There are no weight tensors in a state-machine engine, so tensor/expert
parallelism do not apply; the event axis is inherently sequential per
workflow (scan), handled by host-side event-chunk streaming (the P6/P7
pipeline analog, see SURVEY.md §2.6).
"""
from __future__ import annotations

import os
import zlib
from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..core.checksum import DEFAULT_LAYOUT, PayloadLayout
from ..ops.payload import payload_rows
from ..ops.replay import replay_events

SHARD_AXIS = "shard"

#: serving-mesh width knob: how many devices the SERVING hot path
#: (engine/executor.py replay paths, verify/rebuild/feeder/bench) shards
#: across. Unset/1 = single-chip (byte-identical to the pre-mesh
#: executor); 0 or "all" = every visible device; n = the first n.
MESH_DEVICES_ENV = "CADENCE_TPU_MESH_DEVICES"


def make_mesh(devices: Optional[list] = None) -> Mesh:
    """1D mesh over all (or given) devices; axis 'shard' partitions the
    workflow axis, mirroring numHistoryShards→host assignment (P1)."""
    if devices is None:
        devices = jax.devices()
    return Mesh(np.asarray(devices), (SHARD_AXIS,))


def mesh_devices_requested() -> int:
    """Parse the CADENCE_TPU_MESH_DEVICES knob WITHOUT touching a JAX
    backend (callers like ServiceHost pre-register metrics before any
    device work): 0 means "all visible devices", otherwise a count with
    a floor of 1."""
    raw = os.environ.get(MESH_DEVICES_ENV, "1").strip().lower()
    if raw in ("all", "pod"):
        return 0
    try:
        n = int(raw)
    except ValueError:
        return 1
    return 0 if n == 0 else max(1, n)


def serving_mesh(devices: Optional[list] = None) -> Mesh:
    """The serving executor's mesh, resolved from the env knob: the one
    mesh verify/rebuild/feeder/bench fan their chunks across. Defaults
    to a mesh of 1 so unconfigured deployments stay byte-identical to
    the single-chip executor."""
    if devices is None:
        n = mesh_devices_requested()
        devices = jax.devices()
        if n:
            devices = devices[:min(n, len(devices))]
    return make_mesh(devices)


def workflow_shard(key: Tuple[str, str, str], n_shards: int) -> int:
    """Stable workflow→shard assignment over the mesh — the device-mesh
    analog of the reference's workflowID→historyShard hash
    (common/config numHistoryShards): the same key always lands on the
    same mesh position, so per-device state (the sharded resident pool)
    stays on its owning device across calls."""
    if n_shards <= 1:
        return 0
    return zlib.crc32("|".join(key).encode()) % n_shards


def place_corpus(array: np.ndarray, mesh: Mesh) -> jnp.ndarray:
    """Per-device H2D staging of any leading-workflow-axis array: the
    device_put against a NamedSharding splits the HOST array and copies
    each shard slice to its own device — N parallel transfers instead of
    one chip absorbing the whole corpus."""
    spec = P(SHARD_AXIS, *([None] * (np.ndim(array) - 1)))
    return jax.device_put(array, NamedSharding(mesh, spec))


def shard_events(events: jnp.ndarray, mesh: Mesh) -> jnp.ndarray:
    """Place [W, E, L] events with W partitioned over the 'shard' axis."""
    return jax.device_put(events, NamedSharding(mesh, P(SHARD_AXIS, None, None)))


@partial(jax.jit, static_argnames=("layout",))
def _replay_with_stats(ev: jnp.ndarray, layout: PayloadLayout):
    s = replay_events(ev, layout)
    rows = payload_rows(s, layout)
    # cross-shard aggregation — XLA lowers to all-reduce over the mesh
    stats = jnp.stack([
        (s.error != 0).sum().astype(jnp.int64),
        (s.close_status != 0).sum().astype(jnp.int64),
    ])
    return rows, s.error, stats


def replay_sharded(events: jnp.ndarray, mesh: Mesh,
                   layout: PayloadLayout = DEFAULT_LAYOUT
                   ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """SPMD replay over the mesh.

    Returns (payload_rows [W, width] sharded, errors [W] sharded,
    global_stats [2] replicated = [total_errors, total_closed]); the stats
    reduction is the cross-shard collective (psum over ICI), standing in for
    the reference's shard-level ack aggregation.
    """
    events = shard_events(events, mesh)
    # input NamedShardings propagate through jit; no global mesh needed
    return _replay_with_stats(events, layout)


@partial(jax.jit, static_argnames=("layout",))
def _replay_crc_with_stats(ev32: jnp.ndarray, layout: PayloadLayout):
    from ..ops.crc import crc32_rows
    from ..ops.replay import replay_events32

    s = replay_events32(ev32, layout)
    rows = payload_rows(s, layout)
    stats = jnp.stack([
        (s.error != 0).sum().astype(jnp.int64),
        (s.close_status != 0).sum().astype(jnp.int64),
    ])
    return crc32_rows(rows), s.error, stats


def shard_events32(events32: jnp.ndarray, mesh: Mesh) -> jnp.ndarray:
    """Place wire32 [W, E, L32] int32 events sharded over 'shard'."""
    return jax.device_put(events32,
                          NamedSharding(mesh, P(SHARD_AXIS, None, None)))


def replay_sharded_crc(events32: jnp.ndarray, mesh: Mesh,
                       layout: PayloadLayout = DEFAULT_LAYOUT
                       ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """SPMD wire32 replay reduced on device to (crc32 [W], errors [W],
    global stats [2]) — the production bulk-replay configuration: int32
    lanes in, 4 bytes/workflow out, checksum computed on chip."""
    events32 = shard_events32(events32, mesh)
    return _replay_crc_with_stats(events32, layout)


@partial(jax.jit, static_argnames=("profile", "layout"))
def _replay_wirec_crc_with_stats(slab, bases, n_events, profile,
                                 layout: PayloadLayout):
    from ..ops.crc import crc32_rows
    from ..ops.replay import replay_wirec

    s = replay_wirec(slab, bases, n_events, profile, layout)
    rows = payload_rows(s, layout)
    stats = jnp.stack([
        (s.error != 0).sum().astype(jnp.int64),
        (s.close_status != 0).sum().astype(jnp.int64),
    ])
    return crc32_rows(rows), s.error, stats


def shard_wirec(corpus, mesh: Mesh):
    """Place a WirecCorpus's arrays with W partitioned over 'shard'."""
    w_spec = lambda nd: NamedSharding(mesh, P(SHARD_AXIS, *([None] * (nd - 1))))
    return (jax.device_put(corpus.slab, w_spec(3)),
            jax.device_put(corpus.bases, w_spec(2)),
            jax.device_put(corpus.n_events, w_spec(1)))


def replay_wirec_sharded_crc(corpus, mesh: Mesh,
                             layout: PayloadLayout = DEFAULT_LAYOUT
                             ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """SPMD wirec replay: the compressed slab (~10-18 B/event) is what
    crosses the host link; decode + replay + CRC all on device."""
    slab, bases, n_events = shard_wirec(corpus, mesh)
    return _replay_wirec_crc_with_stats(slab, bases, n_events,
                                        corpus.profile, layout)


# ---------------------------------------------------------------------------
# Capacity-escalation rungs under the shard axis (engine/ladder.py): the
# flagged-row sub-corpus re-replays at widened K partitioned over the SAME
# 'shard' axis as the primary replay — capacity pressure stays SPMD on
# device instead of funnelling flagged rows to a per-workflow host oracle.
# The sub-corpus is padded to a multiple of the mesh size (padding rows
# are no-op lanes), so every shard re-replays its slice of the flagged set.
# ---------------------------------------------------------------------------


def replay_sharded_escalated(events: jnp.ndarray, mesh: Mesh,
                             layout: PayloadLayout,
                             out_layout: PayloadLayout = DEFAULT_LAYOUT
                             ) -> Tuple[jnp.ndarray, jnp.ndarray,
                                        jnp.ndarray, jnp.ndarray]:
    """SPMD widened-K re-replay of a flagged sub-corpus; returns (rows
    [F, out_width] at the BASE payload width, errors [F], narrow-overflow
    [F], current branch [F]), all sharded over 'shard'."""
    from ..ops.replay import replay_escalated

    events = shard_events(events, mesh)
    return replay_escalated(events, layout, out_layout)


def replay_wirec_sharded_escalated_crc(corpus, mesh: Mesh,
                                       layout: PayloadLayout,
                                       out_layout: PayloadLayout = DEFAULT_LAYOUT
                                       ) -> Tuple[jnp.ndarray, jnp.ndarray,
                                                  jnp.ndarray]:
    """SPMD widened-K wirec re-replay reduced to (crc32 [F] uint32 at the
    base payload width, errors [F], narrow-overflow [F])."""
    from ..ops.replay import replay_wirec_escalated_crc

    slab, bases, n_events = shard_wirec(corpus, mesh)
    return replay_wirec_escalated_crc(slab, bases, n_events,
                                      corpus.profile, layout, out_layout)
