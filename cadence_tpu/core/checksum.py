"""Mutable-state checksum: the replay parity oracle.

The reference computes a CRC32 over a thrift-serialized canonical payload of
the mutable state (/root/reference/service/history/execution/checksum.go:36-114,
CRC at /root/reference/common/checksum/crc.go:35-76). This framework keeps the
same payload *content and field order* but serializes it as a fixed-width
little-endian int64 row, so the identical payload can be produced by the
Python oracle (from a `MutableState`) and by the TPU kernel (from the dense
`ReplayState` arrays, sorted with `lax.sort`) and compared elementwise.

Payload field order (mirroring checksum.go:58-113):
  cancel_requested, state, last_first_event_id, next_event_id,
  last_processed_event_id, signal_count, decision_attempt,
  decision_schedule_id, decision_started_id, decision_version,
  sticky_task_list (fnv64 hash; 0 when empty — always empty after replay,
  state_builder.go:108), version histories (count + (event_id, version)
  pairs), then the five sorted pending-ID lists, each count-prefixed:
  timer started IDs, activity schedule IDs, child initiated IDs,
  signal initiated IDs, request-cancel initiated IDs.

Counts are included (reference thrift lists are length-delimited) and lists
are padded to the layout capacities with PAD so rows are fixed-width.
"""
from __future__ import annotations

import zlib
from dataclasses import dataclass
from typing import TYPE_CHECKING, List, Sequence

import numpy as np

if TYPE_CHECKING:  # avoid oracle<->core import cycle at runtime
    from ..oracle.mutable_state import MutableState

# Pad sentinel for unused list slots. Positive-huge so a plain ascending
# lax.sort on the kernel's dense ID arrays yields [real ids..., PAD...] in
# exactly this row layout; never a legal event ID (real ids are small) nor a
# legal version, so padded rows cannot collide with real payloads.
PAD = np.int64(1 << 62)

CHECKSUM_PAYLOAD_VERSION = 1  # mutableStateChecksumPayloadV1, checksum.go:33
CHECKSUM_FLAVOR_IEEE_CRC32_OVER_INT64 = 1


@dataclass(frozen=True)
class PayloadLayout:
    """Fixed capacities of the canonical payload row (must match the kernel's
    table capacities in ops/state.py)."""

    max_version_history_items: int = 8
    max_activities: int = 16
    max_timers: int = 16
    max_children: int = 8
    max_request_cancels: int = 8
    max_signals: int = 8
    #: version-history branches the kernel can carry per workflow (NDC
    #: divergence); does not affect the payload width — the canonical
    #: payload covers the CURRENT branch only (checksum.go:92-100)
    max_branches: int = 2

    NUM_SCALARS = 11  # fields before the version-history block

    @property
    def width(self) -> int:
        return (
            self.NUM_SCALARS
            + 1 + 2 * self.max_version_history_items
            + 1 + self.max_timers
            + 1 + self.max_activities
            + 1 + self.max_children
            + 1 + self.max_signals
            + 1 + self.max_request_cancels
        )


DEFAULT_LAYOUT = PayloadLayout()

#: row index of the sticky-task-list hash. Replay always clears stickyness
#: (state_builder.go:108), so device-replayed rows carry 0 here while a live
#: ACTIVE state may legitimately hold a sticky hash — live-vs-replay
#: comparisons mask this field (the reference never replay-derives it
#: either: its checksum is only compared against the same stored state).
STICKY_ROW_INDEX = 10


def fnv64(s: str) -> int:
    """FNV-1a 64-bit hash, wrapped to signed int64; 0 for the empty string."""
    if not s:
        return 0
    h = 0xCBF29CE484222325
    for b in s.encode("utf-8"):
        h ^= b
        h = (h * 0x100000001B3) & 0xFFFFFFFFFFFFFFFF
    return h - (1 << 64) if h >= (1 << 63) else h


def _put_list(row: np.ndarray, offset: int, ids: Sequence[int], cap: int, what: str) -> int:
    if len(ids) > cap:
        raise OverflowError(f"{what}: {len(ids)} pending items exceed layout capacity {cap}")
    row[offset] = len(ids)
    offset += 1
    row[offset : offset + len(ids)] = sorted(ids)
    offset += cap
    return offset


def payload_row(ms: "MutableState", layout: PayloadLayout = DEFAULT_LAYOUT) -> np.ndarray:
    """Canonical payload row for one mutable state (oracle side)."""
    info = ms.execution_info
    row = np.full(layout.width, PAD, dtype=np.int64)
    row[0] = 1 if info.cancel_requested else 0
    row[1] = int(info.state)
    row[2] = info.last_first_event_id
    row[3] = info.next_event_id
    row[4] = info.last_processed_event
    row[5] = info.signal_count
    row[6] = info.decision_attempt
    row[7] = info.decision_schedule_id
    row[8] = info.decision_started_id
    row[9] = info.decision_version
    row[10] = fnv64(info.sticky_task_list)
    offset = layout.NUM_SCALARS

    items = ms.version_histories.current().items
    if len(items) > layout.max_version_history_items:
        raise OverflowError(
            f"version history items {len(items)} exceed capacity {layout.max_version_history_items}"
        )
    row[offset] = len(items)
    offset += 1
    for i, item in enumerate(items):
        row[offset + 2 * i] = item.event_id
        row[offset + 2 * i + 1] = item.version
    offset += 2 * layout.max_version_history_items

    offset = _put_list(
        row, offset,
        [ti.started_id for ti in ms.pending_timer_info_ids.values()],
        layout.max_timers, "timers",
    )
    offset = _put_list(
        row, offset, list(ms.pending_activity_info_ids.keys()),
        layout.max_activities, "activities",
    )
    offset = _put_list(
        row, offset, list(ms.pending_child_execution_info_ids.keys()),
        layout.max_children, "children",
    )
    offset = _put_list(
        row, offset, list(ms.pending_signal_info_ids.keys()),
        layout.max_signals, "signals",
    )
    offset = _put_list(
        row, offset, list(ms.pending_request_cancel_info_ids.keys()),
        layout.max_request_cancels, "request cancels",
    )
    assert offset == layout.width
    return row


def crc32_of_row(row: np.ndarray) -> int:
    """IEEE CRC32 over the row's little-endian bytes.

    Reference analog: checksum.GenerateCRC32 (common/checksum/crc.go:35-57).
    """
    return zlib.crc32(np.ascontiguousarray(row, dtype="<i8").tobytes())


def crc32_of_rows(rows: np.ndarray) -> np.ndarray:
    """Vectorized (per-row) CRC32 for a [W, width] payload matrix."""
    rows = np.ascontiguousarray(rows, dtype="<i8")
    return np.fromiter(
        (zlib.crc32(r.tobytes()) for r in rows), dtype=np.uint32, count=len(rows)
    )


@dataclass(frozen=True)
class Checksum:
    """Reference analog: checksum.Checksum (common/checksum/checksum.go)."""

    version: int
    flavor: int
    value: int

    @classmethod
    def of(cls, ms: "MutableState", layout: PayloadLayout = DEFAULT_LAYOUT) -> "Checksum":
        return cls(
            version=CHECKSUM_PAYLOAD_VERSION,
            flavor=CHECKSUM_FLAVOR_IEEE_CRC32_OVER_INT64,
            value=crc32_of_row(payload_row(ms, layout)),
        )


def verify(ms: "MutableState", csum: Checksum, layout: PayloadLayout = DEFAULT_LAYOUT) -> None:
    """Reference analog: checksum.Verify (crc.go:59-76)."""
    if csum.version != CHECKSUM_PAYLOAD_VERSION:
        raise ValueError(f"invalid checksum payload version {csum.version}")
    if csum.flavor != CHECKSUM_FLAVOR_IEEE_CRC32_OVER_INT64:
        raise ValueError(f"unknown checksum flavor {csum.flavor}")
    actual = Checksum.of(ms, layout)
    if actual.value != csum.value:
        raise ValueError(f"checksum mismatch: expected {csum.value}, got {actual.value}")
