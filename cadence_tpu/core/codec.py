"""Binary event-batch codec: the framework's wire/storage serialization.

Reference analog: the thriftrw/JSON payload serializer
(common/persistence/serialization/serializer.go:40,:272) that encodes event
batches for the history store. This codec defines a compact little-endian
binary layout that both the Python serializer/deserializer here and the C++
native packer (native/packer.cc) understand; the native packer decodes it
straight into the [W, E, L] lane tensors at host-feed rates (SURVEY.md §7
hard part 6).

Wire layout (version 1), little-endian throughout:

  history  := u32 n_batches, batch*
  batch    := u16 n_events, event*
  event    := i64 id, u8 type, i64 version, i64 timestamp, i64 task_id,
              u8 n_attrs, attr*
  attr     := u8 code, payload
  payload  := i64                      (numeric codes)
            | u16 len, bytes           (string codes: ACTIVITY_ID, TIMER_ID)

Only replay-relevant attributes are carried (state transitions never read
payload blobs; state_builder.go:132-646).
"""
from __future__ import annotations

import struct
from typing import List, Sequence

from .enums import EventType
from .events import HistoryBatch, HistoryEvent, RetryPolicy

CODEC_VERSION = 1

# attribute wire codes (mirrored in native/packer.cc — keep in lockstep)
A_EXEC_TIMEOUT = 1        # execution_start_to_close_timeout_seconds
A_TASK_TIMEOUT = 2        # task_start_to_close_timeout_seconds
A_BACKOFF = 3             # first_decision_task_backoff_seconds
A_ATTEMPT = 4             # attempt
A_EXPIRATION_TS = 5       # expiration_timestamp (nanos)
# code 6 reserved (was a bare has-parent flag; superseded by codes 21-24)
A_HAS_RETRY = 7           # 0/1 (kept alongside codes 25-28 for the lane path)
A_INITIATOR = 8           # ContinueAsNewInitiator; absent → none
A_SCHED_EVENT_ID = 9      # scheduled_event_id
A_STARTED_EVENT_ID = 10   # started_event_id
A_TIMEOUT_TYPE = 11
A_ACTIVITY_ID = 12        # string
A_S2S = 13                # schedule_to_start_timeout_seconds
A_S2C = 14                # schedule_to_close_timeout_seconds
A_STC = 15                # start_to_close_timeout_seconds
A_HEARTBEAT = 16          # heartbeat_timeout_seconds
A_RETRY_EXPIRATION = 17   # retry policy expiration_interval_seconds
A_TIMER_ID = 18           # string
A_START_TO_FIRE = 19      # start_to_fire_timeout_seconds
A_INITIATED_EVENT_ID = 20
# parent linkage + full retry policy (transport fidelity: child workflows
# and retrying activities must round-trip the codec with nothing lost)
A_PARENT_WORKFLOW_ID = 21   # string
A_PARENT_RUN_ID = 22        # string
A_PARENT_DOMAIN_ID = 23     # string
A_PARENT_INITIATED_ID = 24
A_RETRY_INIT_INTERVAL = 25
A_RETRY_COEFF_MILLI = 26    # backoff coefficient * 1000, integer
A_RETRY_MAX_INTERVAL = 27
A_RETRY_MAX_ATTEMPTS = 28
# routing/lineage strings (round 2): a standby rebuilt from replicated blobs
# must be able to DRIVE the workflow after failover — dispatch decisions and
# activities to the real task list, start children, deliver external
# signals/cancels, follow continue-as-new chains. The reference replicates
# full thrift event blobs so these always survive the wire
# (common/persistence/serialization/serializer.go); here they are explicit
# codes. Keep native/packer.cc in lockstep (it refuses unknown codes).
A_TASK_LIST = 29            # string
A_WORKFLOW_TYPE = 30        # string
A_CRON_SCHEDULE = 31        # string
A_FIRST_EXEC_RUN_ID = 32    # string
A_REQUEST_ID = 33           # string
A_TARGET_WORKFLOW_ID = 34   # string ("workflow_id" on initiated/started events)
A_TARGET_RUN_ID = 35        # string ("run_id")
A_TARGET_DOMAIN_ID = 36     # string ("domain_id")
A_SIGNAL_NAME = 37          # string
A_NEW_RUN_ID = 38           # string ("new_execution_run_id", ContinuedAsNew)
A_PARENT_CLOSE_POLICY = 39
A_CHILD_WF_ONLY = 40        # "child_workflow_only" on external cancel/signal
A_LAST_FAILURE_REASON = 41  # string; flushed transient ActivityTaskStarted

STRING_CODES = frozenset({A_ACTIVITY_ID, A_TIMER_ID, A_PARENT_WORKFLOW_ID,
                          A_PARENT_RUN_ID, A_PARENT_DOMAIN_ID,
                          A_TASK_LIST, A_WORKFLOW_TYPE, A_CRON_SCHEDULE,
                          A_FIRST_EXEC_RUN_ID, A_REQUEST_ID,
                          A_TARGET_WORKFLOW_ID, A_TARGET_RUN_ID,
                          A_TARGET_DOMAIN_ID, A_SIGNAL_NAME, A_NEW_RUN_ID,
                          A_LAST_FAILURE_REASON})

_EV_HEAD = struct.Struct("<qBqqqB")  # id, type, version, ts, task_id, n_attrs
_I64 = struct.Struct("<q")
_U16 = struct.Struct("<H")
_U32 = struct.Struct("<I")


def _event_wire_attrs(ev: HistoryEvent) -> List[tuple]:
    """The replay-relevant attributes of one event as (code, value) pairs."""
    et = ev.event_type
    g = ev.get
    out: List[tuple] = []

    def num(code: int, key: str) -> None:
        v = g(key, 0) or 0
        if v:
            out.append((code, int(v)))

    def retry_fields(retry: RetryPolicy) -> None:
        out.append((A_HAS_RETRY, 1))
        if retry.initial_interval_seconds:
            out.append((A_RETRY_INIT_INTERVAL, retry.initial_interval_seconds))
        if retry.backoff_coefficient:
            out.append((A_RETRY_COEFF_MILLI, round(retry.backoff_coefficient * 1000)))
        if retry.maximum_interval_seconds:
            out.append((A_RETRY_MAX_INTERVAL, retry.maximum_interval_seconds))
        if retry.maximum_attempts:
            out.append((A_RETRY_MAX_ATTEMPTS, retry.maximum_attempts))
        if retry.expiration_interval_seconds:
            out.append((A_RETRY_EXPIRATION, retry.expiration_interval_seconds))

    def string(code: int, key: str) -> None:
        v = g(key, "")
        if v:
            out.append((code, v))

    if et == EventType.WorkflowExecutionStarted:
        num(A_EXEC_TIMEOUT, "execution_start_to_close_timeout_seconds")
        num(A_TASK_TIMEOUT, "task_start_to_close_timeout_seconds")
        num(A_BACKOFF, "first_decision_task_backoff_seconds")
        num(A_ATTEMPT, "attempt")
        num(A_EXPIRATION_TS, "expiration_timestamp")
        string(A_TASK_LIST, "task_list")
        string(A_WORKFLOW_TYPE, "workflow_type")
        string(A_CRON_SCHEDULE, "cron_schedule")
        string(A_FIRST_EXEC_RUN_ID, "first_execution_run_id")
        if g("parent_workflow_id"):
            out.append((A_PARENT_WORKFLOW_ID, g("parent_workflow_id")))
            out.append((A_PARENT_RUN_ID, g("parent_run_id", "")))
            out.append((A_PARENT_DOMAIN_ID, g("parent_workflow_domain_id", "")))
            if g("parent_initiated_event_id") is not None:
                out.append((A_PARENT_INITIATED_ID, g("parent_initiated_event_id")))
        if g("retry_policy") is not None:
            retry_fields(g("retry_policy"))
        if g("initiator") is not None:
            out.append((A_INITIATOR, int(g("initiator"))))
    elif et == EventType.DecisionTaskScheduled:
        num(A_STC, "start_to_close_timeout_seconds")
        num(A_ATTEMPT, "attempt")
        string(A_TASK_LIST, "task_list")
    elif et in (EventType.DecisionTaskStarted, EventType.ActivityTaskStarted):
        num(A_SCHED_EVENT_ID, "scheduled_event_id")
        string(A_REQUEST_ID, "request_id")
        num(A_ATTEMPT, "attempt")
        string(A_LAST_FAILURE_REASON, "last_failure_reason")
    elif et == EventType.DecisionTaskCompleted:
        num(A_SCHED_EVENT_ID, "scheduled_event_id")
        num(A_STARTED_EVENT_ID, "started_event_id")
    elif et == EventType.DecisionTaskTimedOut:
        num(A_TIMEOUT_TYPE, "timeout_type")
    elif et == EventType.ActivityTaskScheduled:
        out.append((A_ACTIVITY_ID, g("activity_id", "")))
        num(A_S2S, "schedule_to_start_timeout_seconds")
        num(A_S2C, "schedule_to_close_timeout_seconds")
        num(A_STC, "start_to_close_timeout_seconds")
        num(A_HEARTBEAT, "heartbeat_timeout_seconds")
        string(A_TASK_LIST, "task_list")
        string(A_TARGET_DOMAIN_ID, "domain_id")
        retry: RetryPolicy = g("retry_policy")
        if retry is not None:
            retry_fields(retry)
    elif et in (EventType.ActivityTaskCompleted, EventType.ActivityTaskFailed,
                EventType.ActivityTaskTimedOut, EventType.ActivityTaskCanceled):
        num(A_SCHED_EVENT_ID, "scheduled_event_id")
    elif et == EventType.ActivityTaskCancelRequested:
        out.append((A_ACTIVITY_ID, g("activity_id", "")))
    elif et == EventType.TimerStarted:
        out.append((A_TIMER_ID, g("timer_id", "")))
        num(A_START_TO_FIRE, "start_to_fire_timeout_seconds")
    elif et in (EventType.TimerFired, EventType.TimerCanceled):
        out.append((A_TIMER_ID, g("timer_id", "")))
    elif et == EventType.StartChildWorkflowExecutionInitiated:
        string(A_TARGET_WORKFLOW_ID, "workflow_id")
        string(A_TARGET_DOMAIN_ID, "domain_id")
        string(A_WORKFLOW_TYPE, "workflow_type")
        string(A_TASK_LIST, "task_list")
        num(A_PARENT_CLOSE_POLICY, "parent_close_policy")
    elif et in (EventType.SignalExternalWorkflowExecutionInitiated,
                EventType.RequestCancelExternalWorkflowExecutionInitiated):
        string(A_TARGET_WORKFLOW_ID, "workflow_id")
        string(A_TARGET_RUN_ID, "run_id")
        string(A_TARGET_DOMAIN_ID, "domain_id")
        num(A_CHILD_WF_ONLY, "child_workflow_only")
        if et == EventType.SignalExternalWorkflowExecutionInitiated:
            string(A_SIGNAL_NAME, "signal_name")
    elif et == EventType.WorkflowExecutionSignaled:
        # signal name + request id must survive the WAL/replication
        # round-trip: replay rebuilds the signal dedup set from the event
        # (a redelivered request id after recovery must stay a no-op)
        string(A_SIGNAL_NAME, "signal_name")
        string(A_REQUEST_ID, "request_id")
    elif et == EventType.WorkflowExecutionContinuedAsNew:
        string(A_NEW_RUN_ID, "new_execution_run_id")
    elif et == EventType.ChildWorkflowExecutionStarted:
        num(A_INITIATED_EVENT_ID, "initiated_event_id")
        string(A_TARGET_RUN_ID, "run_id")
    elif et in (
        EventType.StartChildWorkflowExecutionFailed,
        EventType.ChildWorkflowExecutionCompleted,
        EventType.ChildWorkflowExecutionFailed,
        EventType.ChildWorkflowExecutionCanceled,
        EventType.ChildWorkflowExecutionTimedOut,
        EventType.ChildWorkflowExecutionTerminated,
        EventType.RequestCancelExternalWorkflowExecutionFailed,
        EventType.ExternalWorkflowExecutionCancelRequested,
        EventType.SignalExternalWorkflowExecutionFailed,
        EventType.ExternalWorkflowExecutionSignaled,
    ):
        num(A_INITIATED_EVENT_ID, "initiated_event_id")
    return out


def serialize_history(batches: Sequence[HistoryBatch]) -> bytes:
    """One workflow's batched history → wire bytes."""
    parts: List[bytes] = [_U32.pack(len(batches))]
    for batch in batches:
        parts.append(_U16.pack(len(batch.events)))
        for ev in batch.events:
            attrs = _event_wire_attrs(ev)
            parts.append(_EV_HEAD.pack(ev.id, int(ev.event_type), ev.version,
                                       ev.timestamp, ev.task_id, len(attrs)))
            for code, value in attrs:
                parts.append(bytes([code]))
                if code in STRING_CODES:
                    raw = value.encode("utf-8")
                    parts.append(_U16.pack(len(raw)))
                    parts.append(raw)
                else:
                    parts.append(_I64.pack(value))
    return b"".join(parts)


def serialize_corpus(histories: Sequence[Sequence[HistoryBatch]]) -> List[bytes]:
    return [serialize_history(h) for h in histories]


def deserialize_history(data: bytes, domain_id: str = "d", workflow_id: str = "w",
                        run_id: str = "r") -> List[HistoryBatch]:
    """Wire bytes → batches (numeric/string attrs only — the decode side of
    the codec, used by replication transport and tests)."""
    off = 0
    (n_batches,) = _U32.unpack_from(data, off)
    off += 4
    batches: List[HistoryBatch] = []
    for _ in range(n_batches):
        (n_events,) = _U16.unpack_from(data, off)
        off += 2
        events: List[HistoryEvent] = []
        for _ in range(n_events):
            eid, etype, version, ts, task_id, n_attrs = _EV_HEAD.unpack_from(data, off)
            off += _EV_HEAD.size
            attrs = {}
            for _ in range(n_attrs):
                code = data[off]
                off += 1
                if code in STRING_CODES:
                    (slen,) = _U16.unpack_from(data, off)
                    off += 2
                    sval = data[off:off + slen].decode("utf-8")
                    off += slen
                    attrs[_CODE_TO_KEY[code]] = sval
                else:
                    (v,) = _I64.unpack_from(data, off)
                    off += 8
                    attrs[_CODE_TO_KEY[code]] = v
            # reassemble the retry policy object the replayer consumes
            if attrs.pop("has_retry", 0):
                attrs["retry_policy"] = RetryPolicy(
                    initial_interval_seconds=attrs.pop("retry_initial_interval", 0),
                    backoff_coefficient=attrs.pop("retry_coeff_milli", 0) / 1000.0,
                    maximum_interval_seconds=attrs.pop("retry_maximum_interval", 0),
                    maximum_attempts=attrs.pop("retry_maximum_attempts", 0),
                    expiration_interval_seconds=attrs.pop(
                        "retry_expiration_interval_seconds", 0),
                )
            events.append(HistoryEvent(id=eid, event_type=EventType(etype),
                                       version=version, timestamp=ts,
                                       task_id=task_id, attrs=attrs))
        batches.append(HistoryBatch(domain_id=domain_id, workflow_id=workflow_id,
                                    run_id=run_id, events=events))
    return batches


_CODE_TO_KEY = {
    A_EXEC_TIMEOUT: "execution_start_to_close_timeout_seconds",
    A_TASK_TIMEOUT: "task_start_to_close_timeout_seconds",
    A_BACKOFF: "first_decision_task_backoff_seconds",
    A_ATTEMPT: "attempt",
    A_EXPIRATION_TS: "expiration_timestamp",
    A_HAS_RETRY: "has_retry",
    A_INITIATOR: "initiator",
    A_SCHED_EVENT_ID: "scheduled_event_id",
    A_STARTED_EVENT_ID: "started_event_id",
    A_TIMEOUT_TYPE: "timeout_type",
    A_ACTIVITY_ID: "activity_id",
    A_S2S: "schedule_to_start_timeout_seconds",
    A_S2C: "schedule_to_close_timeout_seconds",
    A_STC: "start_to_close_timeout_seconds",
    A_HEARTBEAT: "heartbeat_timeout_seconds",
    A_RETRY_EXPIRATION: "retry_expiration_interval_seconds",
    A_TIMER_ID: "timer_id",
    A_START_TO_FIRE: "start_to_fire_timeout_seconds",
    A_INITIATED_EVENT_ID: "initiated_event_id",
    A_PARENT_WORKFLOW_ID: "parent_workflow_id",
    A_PARENT_RUN_ID: "parent_run_id",
    A_PARENT_DOMAIN_ID: "parent_workflow_domain_id",
    A_PARENT_INITIATED_ID: "parent_initiated_event_id",
    A_RETRY_INIT_INTERVAL: "retry_initial_interval",
    A_RETRY_COEFF_MILLI: "retry_coeff_milli",
    A_RETRY_MAX_INTERVAL: "retry_maximum_interval",
    A_RETRY_MAX_ATTEMPTS: "retry_maximum_attempts",
    A_TASK_LIST: "task_list",
    A_WORKFLOW_TYPE: "workflow_type",
    A_CRON_SCHEDULE: "cron_schedule",
    A_FIRST_EXEC_RUN_ID: "first_execution_run_id",
    A_REQUEST_ID: "request_id",
    A_TARGET_WORKFLOW_ID: "workflow_id",
    A_TARGET_RUN_ID: "run_id",
    A_TARGET_DOMAIN_ID: "domain_id",
    A_SIGNAL_NAME: "signal_name",
    A_NEW_RUN_ID: "new_execution_run_id",
    A_PARENT_CLOSE_POLICY: "parent_close_policy",
    A_CHILD_WF_ONLY: "child_workflow_only",
    A_LAST_FAILURE_REASON: "last_failure_reason",
}
