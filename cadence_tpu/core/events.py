"""History event model.

The reference represents each history event as a large union struct with one
pointer-to-attributes field per event type
(/root/reference/common/types/shared.go `HistoryEvent`). Here an event is a
small record: (id, type, version, timestamp, task_id) plus a flat attribute
mapping. Only attributes that drive mutable-state transitions are modeled —
payload blobs (inputs/results/details) never affect replay state in the
reference (verified against state_builder.go:132-646 attribute usage), so they
stay host-side and out of the device path by design.

String-valued attributes (activity IDs, timer IDs, task lists, run IDs) are
interned to dense integer keys by the batch encoder (`ops/encode.py`); the
oracle operates on the raw strings.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from .enums import EventType


@dataclass(slots=True)
class HistoryEvent:
    """One workflow history event.

    Mirrors the fields of the reference `types.HistoryEvent` that replay
    consumes: ID, type, version, timestamp (unix nanos), task ID, and the
    per-type attributes (flattened into `attrs`).
    """

    id: int
    event_type: EventType
    version: int = 0
    timestamp: int = 0  # unix nanos
    task_id: int = 0
    attrs: Dict[str, Any] = field(default_factory=dict)

    def get(self, name: str, default: Any = None) -> Any:
        return self.attrs.get(name, default)

    def __repr__(self) -> str:  # compact, for test failure messages
        return (
            f"Event(id={self.id}, {self.event_type.name}, v={self.version}, "
            f"ts={self.timestamp}, {self.attrs})"
        )


@dataclass(slots=True)
class RetryPolicy:
    """Mirrors types.RetryPolicy fields used by replay.

    Reference: mutable_state_builder.go:1803-1811 (workflow) and
    :2181-2190 (activity).
    """

    initial_interval_seconds: int = 0
    backoff_coefficient: float = 0.0
    maximum_interval_seconds: int = 0
    maximum_attempts: int = 0
    expiration_interval_seconds: int = 0
    non_retriable_error_reasons: List[str] = field(default_factory=list)


@dataclass(slots=True)
class WorkflowExecution:
    workflow_id: str
    run_id: str


@dataclass(slots=True)
class HistoryBatch:
    """A contiguous batch of events for one run, as fed to ApplyEvents.

    Reference: `ApplyEvents(domainID, requestID, execution, history,
    newRunHistory)` at state_builder.go:90-96. `first_event_id`/`next_event_id`
    are derived from the events.
    """

    domain_id: str
    workflow_id: str
    run_id: str
    events: List[HistoryEvent]
    request_id: str = "replay-request"
    new_run_events: Optional[List[HistoryEvent]] = None

    @property
    def first_event_id(self) -> int:
        return self.events[0].id

    @property
    def last_event_id(self) -> int:
        return self.events[-1].id
