"""Core enums and sentinel constants for the TPU-native Cadence framework.

These mirror the reference engine's wire-visible enumerations so that event
streams and mutable state are semantically comparable with the Go engine:

- event types:      /root/reference/common/types/shared.go:3273-3356 (iota order)
- workflow states:  /root/reference/common/persistence/dataManagerInterfaces.go:117-124
- close statuses:   /root/reference/common/persistence/dataManagerInterfaces.go:127-135
- timeout types:    /root/reference/common/types/shared.go (TimeoutType iota)
- task types:       /root/reference/common/persistence/dataManagerInterfaces.go:149-190
- sentinels:        /root/reference/common/constants.go:30-58

The integer values are load-bearing: they are the lane values in the packed
event tensors consumed by the device replay kernel, and several of them
(state, close status, decision fields) feed the mutable-state checksum.
"""
from __future__ import annotations

import enum


class EventType(enum.IntEnum):
    """History event types, in the reference's iota order.

    Reference: /root/reference/common/types/shared.go:3273-3356.
    """

    WorkflowExecutionStarted = 0
    WorkflowExecutionCompleted = 1
    WorkflowExecutionFailed = 2
    WorkflowExecutionTimedOut = 3
    DecisionTaskScheduled = 4
    DecisionTaskStarted = 5
    DecisionTaskCompleted = 6
    DecisionTaskTimedOut = 7
    DecisionTaskFailed = 8
    ActivityTaskScheduled = 9
    ActivityTaskStarted = 10
    ActivityTaskCompleted = 11
    ActivityTaskFailed = 12
    ActivityTaskTimedOut = 13
    ActivityTaskCancelRequested = 14
    RequestCancelActivityTaskFailed = 15
    ActivityTaskCanceled = 16
    TimerStarted = 17
    TimerFired = 18
    CancelTimerFailed = 19
    TimerCanceled = 20
    WorkflowExecutionCancelRequested = 21
    WorkflowExecutionCanceled = 22
    RequestCancelExternalWorkflowExecutionInitiated = 23
    RequestCancelExternalWorkflowExecutionFailed = 24
    ExternalWorkflowExecutionCancelRequested = 25
    MarkerRecorded = 26
    WorkflowExecutionSignaled = 27
    WorkflowExecutionTerminated = 28
    WorkflowExecutionContinuedAsNew = 29
    StartChildWorkflowExecutionInitiated = 30
    StartChildWorkflowExecutionFailed = 31
    ChildWorkflowExecutionStarted = 32
    ChildWorkflowExecutionCompleted = 33
    ChildWorkflowExecutionFailed = 34
    ChildWorkflowExecutionCanceled = 35
    ChildWorkflowExecutionTimedOut = 36
    ChildWorkflowExecutionTerminated = 37
    SignalExternalWorkflowExecutionInitiated = 38
    SignalExternalWorkflowExecutionFailed = 39
    ExternalWorkflowExecutionSignaled = 40
    UpsertWorkflowSearchAttributes = 41


NUM_EVENT_TYPES = len(EventType)


class WorkflowState(enum.IntEnum):
    """Reference: /root/reference/common/persistence/dataManagerInterfaces.go:117-124."""

    Created = 0
    Running = 1
    Completed = 2
    Zombie = 3
    Void = 4
    Corrupted = 5


class CloseStatus(enum.IntEnum):
    """Reference: /root/reference/common/persistence/dataManagerInterfaces.go:127-135."""

    Nothing = 0  # "None" in Go; renamed to avoid the Python keyword
    Completed = 1
    Failed = 2
    Canceled = 3
    Terminated = 4
    ContinuedAsNew = 5
    TimedOut = 6


class TimeoutType(enum.IntEnum):
    """Activity/decision timeout flavors.

    Reference: /root/reference/common/types/shared.go (TimeoutType iota) and
    /root/reference/service/history/execution/timer_sequence.go:40-49.
    """

    StartToClose = 0
    ScheduleToStart = 1
    ScheduleToClose = 2
    Heartbeat = 3


class TransferTaskType(enum.IntEnum):
    """Reference: /root/reference/common/persistence/dataManagerInterfaces.go:149-162."""

    DecisionTask = 0
    ActivityTask = 1
    CloseExecution = 2
    CancelExecution = 3
    StartChildExecution = 4
    SignalExecution = 5
    RecordWorkflowStarted = 6
    ResetWorkflow = 7
    UpsertWorkflowSearchAttributes = 8
    RecordWorkflowClosed = 9
    RecordChildExecutionCompleted = 10
    ApplyParentClosePolicy = 11


class CrossClusterTaskType(enum.IntEnum):
    """Reference: /root/reference/common/persistence/dataManagerInterfaces.go:165-171."""

    StartChildExecution = 1
    CancelExecution = 2
    SignalExecution = 3
    RecordChildExecutionCompleted = 4
    ApplyParentClosePolicy = 5


class ReplicationTaskType(enum.IntEnum):
    """Reference: /root/reference/common/persistence/dataManagerInterfaces.go:174-178."""

    History = 0
    SyncActivity = 1
    FailoverMarker = 2


class TimerTaskType(enum.IntEnum):
    """Reference: /root/reference/common/persistence/dataManagerInterfaces.go:181-189."""

    DecisionTimeout = 0
    ActivityTimeout = 1
    UserTimer = 2
    WorkflowTimeout = 3
    DeleteHistoryEvent = 4
    ActivityRetryTimer = 5
    WorkflowBackoffTimer = 6


class WorkflowBackoffTimeoutType(enum.IntEnum):
    """Reference: /root/reference/common/persistence/dataManagerInterfaces.go:196-199."""

    Retry = 0
    Cron = 1


class ParentClosePolicy(enum.IntEnum):
    """Reference: /root/reference/common/types/shared.go (ParentClosePolicy iota)."""

    Abandon = 0
    RequestCancel = 1
    Terminate = 2


class ContinueAsNewInitiator(enum.IntEnum):
    """Reference: /root/reference/common/types/shared.go (ContinueAsNewInitiator iota)."""

    Decider = 0
    RetryPolicy = 1
    CronSchedule = 2


class DecisionType(enum.IntEnum):
    """Decisions emitted by workflow workers.

    Reference: /root/reference/common/types/shared.go (DecisionType iota).
    """

    ScheduleActivityTask = 0
    RequestCancelActivityTask = 1
    StartTimer = 2
    CompleteWorkflowExecution = 3
    FailWorkflowExecution = 4
    CancelTimer = 5
    CancelWorkflowExecution = 6
    RequestCancelExternalWorkflowExecution = 7
    RecordMarker = 8
    ContinueAsNewWorkflowExecution = 9
    StartChildWorkflowExecution = 10
    SignalExternalWorkflowExecution = 11
    UpsertWorkflowSearchAttributes = 12


# --- User/activity timer bookkeeping -----------------------------------------
# Reference: /root/reference/service/history/execution/timer_sequence.go:51-67

TIMER_TASK_STATUS_NONE = 0
TIMER_TASK_STATUS_CREATED = 1  # user timers

TIMER_TASK_STATUS_CREATED_START_TO_CLOSE = 1
TIMER_TASK_STATUS_CREATED_SCHEDULE_TO_START = 2
TIMER_TASK_STATUS_CREATED_SCHEDULE_TO_CLOSE = 4
TIMER_TASK_STATUS_CREATED_HEARTBEAT = 8

TIMER_TYPE_TO_STATUS_MASK = {
    TimeoutType.StartToClose: TIMER_TASK_STATUS_CREATED_START_TO_CLOSE,
    TimeoutType.ScheduleToStart: TIMER_TASK_STATUS_CREATED_SCHEDULE_TO_START,
    TimeoutType.ScheduleToClose: TIMER_TASK_STATUS_CREATED_SCHEDULE_TO_CLOSE,
    TimeoutType.Heartbeat: TIMER_TASK_STATUS_CREATED_HEARTBEAT,
}

# Close events and the close status each one sets
# (mutable_state_builder.go:2561-2655,:2719-2733,:3225-3240,:3366-3382) —
# shared by the device transition kernel and task generator so the two can
# never enumerate different close sets.
CLOSE_EVENT_STATUS = (
    (EventType.WorkflowExecutionCompleted, CloseStatus.Completed),
    (EventType.WorkflowExecutionFailed, CloseStatus.Failed),
    (EventType.WorkflowExecutionTimedOut, CloseStatus.TimedOut),
    (EventType.WorkflowExecutionCanceled, CloseStatus.Canceled),
    (EventType.WorkflowExecutionTerminated, CloseStatus.Terminated),
    (EventType.WorkflowExecutionContinuedAsNew, CloseStatus.ContinuedAsNew),
)

# --- Sentinels ----------------------------------------------------------------
# Reference: /root/reference/common/constants.go:30-58

FIRST_EVENT_ID = 1
EMPTY_EVENT_ID = -23
EMPTY_VERSION = -24
END_EVENT_ID = (1 << 63) - 1
BUFFERED_EVENT_ID = -123
#: in-memory-only started marker for retrying activities whose started
#: event is flushed lazily at close (common/constants.go:43)
TRANSIENT_EVENT_ID = -124
EMPTY_UUID = "emptyUuid"

# Nanoseconds per second: event timestamps are unix nanos, timeouts are seconds
# (reference stores timestamps as UnixNano int64 and timeouts as int32 seconds).
NANOS_PER_SECOND = 1_000_000_000

# Failure reasons that are never retried regardless of retry policy.
# Reference: /root/reference/service/history/execution/retry.go:74-80 and
# /root/reference/common/constants.go (FailureReason*).
FAILURE_REASON_CANCEL_DETAILS_EXCEEDS_LIMIT = "CANCEL_DETAILS_EXCEEDS_LIMIT"
FAILURE_REASON_COMPLETE_RESULT_EXCEEDS_LIMIT = "COMPLETE_RESULT_EXCEEDS_LIMIT"
FAILURE_REASON_HEARTBEAT_EXCEEDS_LIMIT = "HEARTBEAT_EXCEEDS_LIMIT"
FAILURE_REASON_DECISION_BLOB_SIZE_EXCEEDS_LIMIT = "DECISION_BLOB_SIZE_EXCEEDS_LIMIT"

NON_RETRIABLE_SIZE_FAILURE_REASONS = frozenset(
    {
        FAILURE_REASON_CANCEL_DETAILS_EXCEEDS_LIMIT,
        FAILURE_REASON_COMPLETE_RESULT_EXCEEDS_LIMIT,
        FAILURE_REASON_HEARTBEAT_EXCEEDS_LIMIT,
        FAILURE_REASON_DECISION_BLOB_SIZE_EXCEEDS_LIMIT,
    }
)
