"""Activity retry: transient re-attempt without history events.

Reference: mutableStateBuilder.RetryActivity
(service/history/execution/mutable_state_builder.go:3812-3866) + the
backoff math in execution/retry.go:31-80. A failing activity with a retry
policy is NOT closed with a failure event; its ActivityInfo is reset for
the next attempt and an ActivityRetryTimer re-dispatches it — history only
records the final outcome (the transient started event is flushed when the
activity finally closes, mutable_state_builder.go:2199).
"""
from __future__ import annotations

from ..core.enums import EMPTY_EVENT_ID, TIMER_TASK_STATUS_NONE
from ..utils.backoff import NO_BACKOFF, get_backoff_interval
from . import task_generator as taskgen
from .mutable_state import ActivityInfo, MutableState


def retry_activity(ms: MutableState, ai: ActivityInfo, now_nanos: int,
                   failure_reason: str, failure_details: bytes = b"") -> bool:
    """Attempt a transient retry; True when the activity will re-run
    (RetryActivity, mutable_state_builder.go:3812)."""
    if not ai.has_retry_policy or ai.cancel_requested:
        return False
    backoff_nanos = get_backoff_interval(
        now_nanos=now_nanos,
        expiration_time_nanos=ai.expiration_time,
        curr_attempt=ai.attempt,
        max_attempts=ai.maximum_attempts,
        init_interval_seconds=ai.initial_interval,
        max_interval_seconds=ai.maximum_interval,
        backoff_coefficient=ai.backoff_coefficient,
        failure_reason=failure_reason,
        non_retriable_errors=ai.non_retriable_errors,
    )
    if backoff_nanos == NO_BACKOFF:
        return False

    ai.version = ms.current_version
    ai.attempt += 1
    ai.scheduled_time = now_nanos + backoff_nanos  # next schedule time
    ai.started_id = EMPTY_EVENT_ID
    ai.request_id = ""
    ai.started_time = 0
    ai.timer_task_status = TIMER_TASK_STATUS_NONE
    ai.last_failure_reason = failure_reason
    ai.last_worker_identity = ai.started_identity
    ai.last_failure_details = failure_details
    taskgen.generate_activity_retry_tasks(ms, ai.schedule_id)
    return True
