"""Oracle state builder: replays history event batches into mutable state.

This is the Python semantic twin of the reference's replay hot loop:

- the per-event switch:  /root/reference/service/history/execution/state_builder.go:90-647
- Replicate* semantics:  /root/reference/service/history/execution/mutable_state_builder.go
- decision transitions:  /root/reference/service/history/execution/mutable_state_decision_task_manager.go

`apply_batch` corresponds to one `ApplyEvents` call (one persisted event
batch / transaction); `replay_history` corresponds to
`stateRebuilder.Rebuild`'s paginated loop
(/root/reference/service/history/execution/state_rebuilder.go:102-148).
"""
from __future__ import annotations

from typing import List, Optional

from ..core.enums import (
    EMPTY_EVENT_ID,
    EMPTY_UUID,
    EMPTY_VERSION,
    TIMER_TASK_STATUS_NONE,
    CloseStatus,
    EventType,
    TimeoutType,
    WorkflowState,
)
from ..core.events import HistoryBatch, HistoryEvent, RetryPolicy
from . import task_generator as taskgen
from .mutable_state import (
    ActivityInfo,
    ChildExecutionInfo,
    DecisionInfo,
    DomainEntry,
    MutableState,
    ReplayError,
    RequestCancelInfo,
    SignalInfo,
    TimerInfo,
    seconds_to_nanos,
)


class StateBuilder:
    """Replays event batches into a MutableState (passive/rebuild path)."""

    def __init__(self, mutable_state: Optional[MutableState] = None,
                 domain_entry: Optional[DomainEntry] = None,
                 clear_sticky: bool = True) -> None:
        self.ms = mutable_state if mutable_state is not None else MutableState(domain_entry)
        #: mutable state of the continued-as-new run, when one was applied
        self.new_run_state: Optional[MutableState] = None
        #: the REPLAY path clears stickyness — the workflow turned passive
        #: (state_builder.go:108); the ACTIVE engine routes its own
        #: transactions through this same builder (active ≡ replayed by
        #: construction) and passes False so sticky execution survives
        #: between decisions
        self.clear_sticky = clear_sticky

    # ------------------------------------------------------------------
    # Entry points
    # ------------------------------------------------------------------

    def replay_history(self, batches: List[HistoryBatch]) -> MutableState:
        """Replay a full history, batch by batch (state_rebuilder.go:114-148)."""
        for batch in batches:
            self.apply_batch(batch)
        return self.ms

    def apply_batch(self, batch: HistoryBatch) -> MutableState:
        """One ApplyEvents call; reference state_builder.go:90-647."""
        if not batch.events:
            raise ReplayError("encounter history size being zero")
        ms = self.ms
        first_event = batch.events[0]
        last_event = batch.events[-1]

        # need to clear the stickiness since workflow turned to passive (:108)
        if self.clear_sticky:
            ms.clear_stickyness()

        for event in batch.events:
            ms.update_current_version(event.version, force_update=True)  # :112
            ms.version_histories.current().add_or_update_item(event.id, event.version)  # :123
            ms.execution_info.last_event_task_id = event.task_id  # :129
            self._apply_event(batch, first_event, event)

        # activity/user timers are generated at the very end (:634-640)
        taskgen.generate_activity_timer_tasks(ms)
        taskgen.generate_user_timer_tasks(ms)

        ms.execution_info.last_first_event_id = first_event.id  # :642
        ms.execution_info.next_event_id = last_event.id + 1  # :643
        return ms

    # ------------------------------------------------------------------
    # The event-type switch (state_builder.go:131-631)
    # ------------------------------------------------------------------

    def _apply_event(self, batch: HistoryBatch, first_event: HistoryEvent,
                     event: HistoryEvent) -> None:
        ms = self.ms
        et = event.event_type

        if et == EventType.WorkflowExecutionStarted:
            self._replicate_workflow_execution_started(batch, event)
            taskgen.generate_record_workflow_started_tasks(ms, event)
            taskgen.generate_workflow_start_tasks(ms, event.timestamp, event)
            if (event.get("first_decision_task_backoff_seconds", 0) or 0) > 0:
                taskgen.generate_delayed_decision_tasks(ms, event)

        elif et == EventType.DecisionTaskScheduled:
            decision = self._replicate_decision_task_scheduled(
                version=event.version,
                schedule_id=event.id,
                task_list=event.get("task_list", ""),
                start_to_close_timeout=event.get("start_to_close_timeout_seconds", 0) or 0,
                attempt=event.get("attempt", 0) or 0,
                scheduled_timestamp=event.timestamp,
                original_scheduled_timestamp=event.timestamp,
            )
            taskgen.generate_decision_schedule_tasks(ms, decision.schedule_id)

        elif et == EventType.DecisionTaskStarted:
            decision = self._replicate_decision_task_started(
                version=event.version,
                schedule_id=event.get("scheduled_event_id"),
                started_id=event.id,
                request_id=event.get("request_id", ""),
                timestamp=event.timestamp,
            )
            taskgen.generate_decision_start_tasks(ms, decision.schedule_id)

        elif et == EventType.DecisionTaskCompleted:
            self._replicate_decision_task_completed(event)

        elif et == EventType.DecisionTaskTimedOut:
            self._replicate_decision_task_timed_out(
                TimeoutType(event.get("timeout_type", TimeoutType.StartToClose))
            )
            decision = self._replicate_transient_decision_task_scheduled(event)
            if decision is not None:
                taskgen.generate_decision_schedule_tasks(ms, decision.schedule_id)

        elif et == EventType.DecisionTaskFailed:
            self._fail_decision(increment_attempt=True, now=event.timestamp)
            decision = self._replicate_transient_decision_task_scheduled(event)
            if decision is not None:
                taskgen.generate_decision_schedule_tasks(ms, decision.schedule_id)

        elif et == EventType.ActivityTaskScheduled:
            self._replicate_activity_task_scheduled(first_event.id, event)
            taskgen.generate_activity_transfer_tasks(ms, event)

        elif et == EventType.ActivityTaskStarted:
            self._replicate_activity_task_started(event)

        elif et in (
            EventType.ActivityTaskCompleted,
            EventType.ActivityTaskFailed,
            EventType.ActivityTaskTimedOut,
            EventType.ActivityTaskCanceled,
        ):
            # mutable_state_builder.go:2312,:2354,:2400,:2528 — all reduce to
            # DeleteActivity(scheduledEventID)
            ms.delete_activity(event.get("scheduled_event_id"))

        elif et == EventType.ActivityTaskCancelRequested:
            self._replicate_activity_task_cancel_requested(event)

        elif et == EventType.RequestCancelActivityTaskFailed:
            pass  # no mutable state action (state_builder.go:339-340)

        elif et == EventType.TimerStarted:
            self._replicate_timer_started(event)

        elif et == EventType.TimerFired:
            ms.delete_user_timer(event.get("timer_id"))  # :3109-3117

        elif et == EventType.TimerCanceled:
            ms.delete_user_timer(event.get("timer_id"))  # :3160-3168

        elif et == EventType.CancelTimerFailed:
            pass  # no mutable state action (state_builder.go:363-364)

        elif et == EventType.StartChildWorkflowExecutionInitiated:
            self._replicate_start_child_initiated(first_event.id, event)
            taskgen.generate_child_workflow_tasks(ms, event)

        elif et == EventType.StartChildWorkflowExecutionFailed:
            ms.delete_pending_child_execution(event.get("initiated_event_id"))

        elif et == EventType.ChildWorkflowExecutionStarted:
            self._replicate_child_started(event)

        elif et in (
            EventType.ChildWorkflowExecutionCompleted,
            EventType.ChildWorkflowExecutionFailed,
            EventType.ChildWorkflowExecutionCanceled,
            EventType.ChildWorkflowExecutionTimedOut,
            EventType.ChildWorkflowExecutionTerminated,
        ):
            # mutable_state_builder.go:3590-3810 — DeletePendingChildExecution
            ms.delete_pending_child_execution(event.get("initiated_event_id"))

        elif et == EventType.RequestCancelExternalWorkflowExecutionInitiated:
            self._replicate_request_cancel_initiated(first_event.id, event)
            taskgen.generate_request_cancel_external_tasks(ms, event)

        elif et in (
            EventType.RequestCancelExternalWorkflowExecutionFailed,
            EventType.ExternalWorkflowExecutionCancelRequested,
        ):
            ms.delete_pending_request_cancel(event.get("initiated_event_id"))

        elif et == EventType.SignalExternalWorkflowExecutionInitiated:
            self._replicate_signal_external_initiated(first_event.id, event)
            taskgen.generate_signal_external_tasks(ms, event)

        elif et in (
            EventType.SignalExternalWorkflowExecutionFailed,
            EventType.ExternalWorkflowExecutionSignaled,
        ):
            ms.delete_pending_signal(event.get("initiated_event_id"))

        elif et == EventType.MarkerRecorded:
            pass  # no mutable state action (state_builder.go:494-495)

        elif et == EventType.WorkflowExecutionSignaled:
            ms.execution_info.signal_count += 1  # :3260-3267
            # repopulate the at-least-once dedup set from the event's
            # request id (mutable_state_builder.go AddSignalRequested on
            # the replicate path): a redelivered cross-cluster signal
            # after recovery/promotion must stay a no-op
            request_id = event.get("request_id", "")
            if request_id:
                ms.signal_requested_ids.add(request_id)

        elif et == EventType.WorkflowExecutionCancelRequested:
            ms.execution_info.cancel_requested = True  # :2688-2694

        elif et == EventType.UpsertWorkflowSearchAttributes:
            self._replicate_upsert_search_attributes(event)
            taskgen.generate_workflow_search_attr_tasks(ms)

        elif et == EventType.WorkflowExecutionCompleted:
            self._complete_workflow(first_event.id, event, CloseStatus.Completed)

        elif et == EventType.WorkflowExecutionFailed:
            self._complete_workflow(first_event.id, event, CloseStatus.Failed)

        elif et == EventType.WorkflowExecutionTimedOut:
            self._complete_workflow(first_event.id, event, CloseStatus.TimedOut)

        elif et == EventType.WorkflowExecutionCanceled:
            self._complete_workflow(first_event.id, event, CloseStatus.Canceled)

        elif et == EventType.WorkflowExecutionTerminated:
            self._complete_workflow(first_event.id, event, CloseStatus.Terminated)

        elif et == EventType.WorkflowExecutionContinuedAsNew:
            self._replicate_continued_as_new(batch, first_event.id, event)

        else:
            raise ReplayError(f"Unknown event type: {et}")

    # ------------------------------------------------------------------
    # Replicate* implementations
    # ------------------------------------------------------------------

    def _replicate_workflow_execution_started(self, batch: HistoryBatch,
                                              event: HistoryEvent) -> None:
        """Reference: mutable_state_builder.go:1751-1829."""
        ms = self.ms
        info = ms.execution_info
        info.create_request_id = batch.request_id
        info.domain_id = batch.domain_id
        info.workflow_id = batch.workflow_id
        info.run_id = batch.run_id
        info.first_execution_run_id = event.get("first_execution_run_id", batch.run_id)
        info.task_list = event.get("task_list", "")
        info.workflow_type_name = event.get("workflow_type", "")
        info.workflow_timeout = event.get("execution_start_to_close_timeout_seconds", 0) or 0
        info.decision_start_to_close_timeout = event.get("task_start_to_close_timeout_seconds", 0) or 0
        info.start_timestamp = event.timestamp

        info.update_workflow_state_close_status(WorkflowState.Created, CloseStatus.Nothing)
        info.last_processed_event = EMPTY_EVENT_ID
        info.last_first_event_id = event.id

        info.decision_version = EMPTY_VERSION
        info.decision_schedule_id = EMPTY_EVENT_ID
        info.decision_started_id = EMPTY_EVENT_ID
        info.decision_request_id = EMPTY_UUID
        info.decision_timeout = 0

        info.cron_schedule = event.get("cron_schedule", "") or ""
        info.first_decision_backoff = event.get(
            "first_decision_task_backoff_seconds", 0) or 0

        parent_domain_id = event.get("parent_workflow_domain_id")
        if parent_domain_id:
            info.parent_domain_id = parent_domain_id
        if event.get("parent_workflow_id"):
            info.parent_workflow_id = event.get("parent_workflow_id")
            info.parent_run_id = event.get("parent_run_id", "")
        if event.get("parent_initiated_event_id") is not None:
            info.initiated_id = event.get("parent_initiated_event_id")
        else:
            info.initiated_id = EMPTY_EVENT_ID

        info.attempt = event.get("attempt", 0) or 0
        expiration_ts = event.get("expiration_timestamp", 0) or 0
        if expiration_ts != 0:
            info.expiration_time = expiration_ts
        retry: Optional[RetryPolicy] = event.get("retry_policy")
        if retry is not None:
            info.has_retry_policy = True
            info.backoff_coefficient = retry.backoff_coefficient
            info.expiration_seconds = retry.expiration_interval_seconds
            info.initial_interval = retry.initial_interval_seconds
            info.maximum_attempts = retry.maximum_attempts
            info.maximum_interval = retry.maximum_interval_seconds
            info.non_retriable_errors = list(retry.non_retriable_error_reasons)

        memo = event.get("memo")
        if memo:
            info.memo = dict(memo)
        search_attributes = event.get("search_attributes")
        if search_attributes:
            info.search_attributes = dict(search_attributes)

    # -- decision state machine (mutable_state_decision_task_manager.go) ----

    def _update_decision(self, d: DecisionInfo) -> None:
        """Reference: mutable_state_decision_task_manager.go:697-721."""
        info = self.ms.execution_info
        info.decision_version = d.version
        info.decision_schedule_id = d.schedule_id
        info.decision_started_id = d.started_id
        info.decision_request_id = d.request_id
        info.decision_timeout = d.decision_timeout
        info.decision_attempt = d.attempt
        info.decision_started_timestamp = d.started_timestamp
        info.decision_scheduled_timestamp = d.scheduled_timestamp
        info.decision_original_scheduled_timestamp = d.original_scheduled_timestamp
        # NOTE: tasklist deliberately not written to execution info (:710)

    def _replicate_decision_task_scheduled(self, version: int, schedule_id: int,
                                           task_list: str, start_to_close_timeout: int,
                                           attempt: int, scheduled_timestamp: int,
                                           original_scheduled_timestamp: int) -> DecisionInfo:
        """Reference: mutable_state_decision_task_manager.go:129-166."""
        ms = self.ms
        if ms.execution_info.state != WorkflowState.Zombie:
            ms.execution_info.update_workflow_state_close_status(
                WorkflowState.Running, CloseStatus.Nothing
            )
        decision = DecisionInfo(
            version=version,
            schedule_id=schedule_id,
            started_id=EMPTY_EVENT_ID,
            request_id=EMPTY_UUID,
            decision_timeout=start_to_close_timeout,
            task_list=task_list,
            attempt=attempt,
            scheduled_timestamp=scheduled_timestamp,
            started_timestamp=0,
            original_scheduled_timestamp=original_scheduled_timestamp,
        )
        self._update_decision(decision)
        return decision

    def _replicate_transient_decision_task_scheduled(
        self, event: HistoryEvent
    ) -> Optional[DecisionInfo]:
        """Reference: mutable_state_decision_task_manager.go:168-197.

        Uses the event timestamp in place of timeSource.Now() (deterministic;
        not checksum-relevant).
        """
        ms = self.ms
        info = ms.execution_info
        has_pending = info.decision_schedule_id != EMPTY_EVENT_ID
        if has_pending or info.decision_attempt == 0:
            return None
        decision = DecisionInfo(
            version=ms.current_version,
            schedule_id=ms.get_next_event_id(),  # deliberately "wrong", see :173-182
            started_id=EMPTY_EVENT_ID,
            request_id=EMPTY_UUID,
            decision_timeout=info.decision_start_to_close_timeout,
            task_list=info.task_list,
            attempt=info.decision_attempt,
            scheduled_timestamp=event.timestamp,
            started_timestamp=0,
        )
        self._update_decision(decision)
        return decision

    def _replicate_decision_task_started(self, version: int, schedule_id: int,
                                         started_id: int, request_id: str,
                                         timestamp: int) -> DecisionInfo:
        """Reference: mutable_state_decision_task_manager.go:199-242."""
        info = self.ms.execution_info
        if info.decision_schedule_id != schedule_id:
            raise ReplayError(f"unable to find decision: {schedule_id}")
        # transient-decision "magic": attempt reset to 0 on replication (:215-223)
        attempt = 0
        decision = DecisionInfo(
            version=version,
            schedule_id=schedule_id,
            started_id=started_id,
            request_id=request_id,
            decision_timeout=info.decision_timeout,
            attempt=attempt,
            started_timestamp=timestamp,
            scheduled_timestamp=info.decision_scheduled_timestamp,
            task_list=info.sticky_task_list if info.sticky_task_list else info.task_list,
            original_scheduled_timestamp=info.decision_original_scheduled_timestamp,
        )
        self._update_decision(decision)
        return decision

    def _delete_decision(self) -> None:
        """Reference: mutable_state_decision_task_manager.go:679-694."""
        reset = DecisionInfo(
            version=EMPTY_VERSION,
            schedule_id=EMPTY_EVENT_ID,
            started_id=EMPTY_EVENT_ID,
            request_id=EMPTY_UUID,
            decision_timeout=0,
            attempt=0,
            started_timestamp=0,
            scheduled_timestamp=0,
            task_list="",
            # keep last original scheduled timestamp (:690-691)
            original_scheduled_timestamp=self.ms.execution_info.decision_original_scheduled_timestamp,
        )
        self._update_decision(reset)

    def _replicate_decision_task_completed(self, event: HistoryEvent) -> None:
        """Reference: mutable_state_decision_task_manager.go:244-249, 827-838."""
        self._delete_decision()
        self.ms.execution_info.last_processed_event = event.get("started_event_id")
        # addBinaryCheckSumIfNotExists is active-side reset-point bookkeeping;
        # binary checksums are absent from replay corpora (not checksum-relevant)

    def _fail_decision(self, increment_attempt: bool, now: int) -> None:
        """Reference: mutable_state_decision_task_manager.go:643-676."""
        ms = self.ms
        ms.clear_stickyness()
        fail_info = DecisionInfo(
            version=EMPTY_VERSION,
            schedule_id=EMPTY_EVENT_ID,
            started_id=EMPTY_EVENT_ID,
            request_id=EMPTY_UUID,
            decision_timeout=0,
            started_timestamp=0,
            task_list="",
            original_scheduled_timestamp=0,
        )
        if increment_attempt:
            fail_info.attempt = ms.execution_info.decision_attempt + 1
            fail_info.scheduled_timestamp = now
        self._update_decision(fail_info)

    def _replicate_decision_task_timed_out(self, timeout_type: TimeoutType) -> None:
        """Reference: mutable_state_decision_task_manager.go:256-271 — a
        schedule-to-start timeout (the sticky-decision dispatch deadline)
        does NOT increment the attempt, so the follow-up decision is a real
        scheduled event on the normal task list, never a transient."""
        increment = timeout_type != TimeoutType.ScheduleToStart
        self._fail_decision(increment, now=0)

    # -- activities ---------------------------------------------------------

    def _replicate_activity_task_scheduled(self, first_event_id: int,
                                           event: HistoryEvent) -> ActivityInfo:
        """Reference: mutable_state_builder.go:2142-2197."""
        ms = self.ms
        retry: Optional[RetryPolicy] = event.get("retry_policy")
        ai = ActivityInfo(
            version=event.version,
            schedule_id=event.id,
            scheduled_event_batch_id=first_event_id,
            scheduled_time=event.timestamp,
            started_id=EMPTY_EVENT_ID,
            started_time=0,
            activity_id=event.get("activity_id", ""),
            domain_id=event.get("domain_id") or ms.execution_info.domain_id,
            task_list=event.get("task_list", ""),
            schedule_to_start_timeout=event.get("schedule_to_start_timeout_seconds", 0) or 0,
            schedule_to_close_timeout=event.get("schedule_to_close_timeout_seconds", 0) or 0,
            start_to_close_timeout=event.get("start_to_close_timeout_seconds", 0) or 0,
            heartbeat_timeout=event.get("heartbeat_timeout_seconds", 0) or 0,
            cancel_requested=False,
            cancel_request_id=EMPTY_EVENT_ID,
            timer_task_status=TIMER_TASK_STATUS_NONE,
            has_retry_policy=retry is not None,
        )
        if retry is not None:
            ai.initial_interval = retry.initial_interval_seconds
            ai.backoff_coefficient = retry.backoff_coefficient
            ai.maximum_interval = retry.maximum_interval_seconds
            ai.maximum_attempts = retry.maximum_attempts
            ai.non_retriable_errors = list(retry.non_retriable_error_reasons)
            if retry.expiration_interval_seconds != 0:
                ai.expiration_time = ai.scheduled_time + seconds_to_nanos(
                    retry.expiration_interval_seconds
                )
        ms.pending_activity_info_ids[ai.schedule_id] = ai
        ms.pending_activity_id_to_event_id[ai.activity_id] = ai.schedule_id
        return ai

    def _replicate_activity_task_started(self, event: HistoryEvent) -> None:
        """Reference: mutable_state_builder.go:2254-2276."""
        ms = self.ms
        schedule_id = event.get("scheduled_event_id")
        ai = ms.pending_activity_info_ids.get(schedule_id)
        if ai is None:
            raise ReplayError(f"missing activity info for schedule id {schedule_id}")
        ai.version = event.version
        ai.started_id = event.id
        ai.request_id = event.get("request_id", "")
        ai.started_time = event.timestamp
        ai.last_heartbeat_updated_time = ai.started_time

    def _replicate_activity_task_cancel_requested(self, event: HistoryEvent) -> None:
        """Reference: mutable_state_builder.go:2444-2467 — silently ignores
        unknown activity IDs on the passive side (:2451-2454)."""
        ms = self.ms
        activity_id = event.get("activity_id", "")
        schedule_id = ms.pending_activity_id_to_event_id.get(activity_id)
        if schedule_id is None:
            return
        ai = ms.pending_activity_info_ids[schedule_id]
        ai.version = event.version
        ai.cancel_requested = True
        ai.cancel_request_id = event.id

    # -- timers -------------------------------------------------------------

    def _replicate_timer_started(self, event: HistoryEvent) -> TimerInfo:
        """Reference: mutable_state_builder.go:3057-3081."""
        ms = self.ms
        timer_id = event.get("timer_id", "")
        start_to_fire = event.get("start_to_fire_timeout_seconds", 0) or 0
        ti = TimerInfo(
            version=event.version,
            timer_id=timer_id,
            expiry_time=event.timestamp + seconds_to_nanos(start_to_fire),
            started_id=event.id,
            task_status=TIMER_TASK_STATUS_NONE,
        )
        ms.pending_timer_info_ids[timer_id] = ti
        ms.pending_timer_event_id_to_id[ti.started_id] = timer_id
        return ti

    # -- children / external cancels / external signals ---------------------

    def _replicate_start_child_initiated(self, first_event_id: int,
                                         event: HistoryEvent) -> ChildExecutionInfo:
        """Reference: mutable_state_builder.go:3417-3453."""
        ms = self.ms
        ci = ChildExecutionInfo(
            version=event.version,
            initiated_id=event.id,
            initiated_event_batch_id=first_event_id,
            started_id=EMPTY_EVENT_ID,
            started_workflow_id=event.get("workflow_id", ""),
            create_request_id=batch_request_id(event),
            domain_id=event.get("domain_id") or ms.execution_info.domain_id,
            workflow_type_name=event.get("workflow_type", ""),
            parent_close_policy=event.get("parent_close_policy", 0) or 0,
            task_list=event.get("task_list", "") or "",
        )
        ms.pending_child_execution_info_ids[ci.initiated_id] = ci
        return ci

    def _replicate_child_started(self, event: HistoryEvent) -> None:
        """Reference: mutable_state_builder.go:3485-3507."""
        ms = self.ms
        initiated_id = event.get("initiated_event_id")
        ci = ms.pending_child_execution_info_ids.get(initiated_id)
        if ci is None:
            raise ReplayError(f"missing child execution info {initiated_id}")
        ci.started_id = event.id
        ci.started_run_id = event.get("run_id", "")

    def _replicate_request_cancel_initiated(self, first_event_id: int,
                                            event: HistoryEvent) -> RequestCancelInfo:
        """Reference: mutable_state_builder.go:2760-2779."""
        ms = self.ms
        rci = RequestCancelInfo(
            version=event.version,
            initiated_event_batch_id=first_event_id,
            initiated_id=event.id,
            cancel_request_id=batch_request_id(event),
        )
        ms.pending_request_cancel_info_ids[rci.initiated_id] = rci
        return rci

    def _replicate_signal_external_initiated(self, first_event_id: int,
                                             event: HistoryEvent) -> SignalInfo:
        """Reference: mutable_state_builder.go:2883-2905."""
        ms = self.ms
        si = SignalInfo(
            version=event.version,
            initiated_event_batch_id=first_event_id,
            initiated_id=event.id,
            signal_request_id=batch_request_id(event),
            signal_name=event.get("signal_name", ""),
        )
        ms.pending_signal_info_ids[si.initiated_id] = si
        return si

    # -- search attributes / close --------------------------------------

    def _replicate_upsert_search_attributes(self, event: HistoryEvent) -> None:
        """Reference: mutable_state_builder.go:2926-2948."""
        upsert = event.get("search_attributes") or {}
        self.ms.execution_info.search_attributes.update(upsert)

    def _complete_workflow(self, first_event_id: int, event: HistoryEvent,
                           close_status: CloseStatus) -> None:
        """Common close-event handling + close tasks.

        Reference: mutable_state_builder.go:2561-2576 (completed), :2601-2616
        (failed), :2640-2655 (timed out), :2719-2733 (canceled), :3225-3240
        (terminated); task generation state_builder.go:517-585.
        """
        ms = self.ms
        ms.execution_info.update_workflow_state_close_status(
            WorkflowState.Completed, close_status
        )
        ms.execution_info.completion_event_batch_id = first_event_id
        ms.clear_stickyness()
        taskgen.generate_workflow_close_tasks(ms, event)

    def _replicate_continued_as_new(self, batch: HistoryBatch, first_event_id: int,
                                    event: HistoryEvent) -> None:
        """Reference: state_builder.go:587-627 + mutable_state_builder.go:3366-3382."""
        ms = self.ms
        if batch.new_run_events:
            new_run_id = event.get("new_execution_run_id", "")
            new_builder = StateBuilder(MutableState(ms.domain_entry))
            new_batch = HistoryBatch(
                domain_id=batch.domain_id,
                workflow_id=batch.workflow_id,
                run_id=new_run_id,
                events=batch.new_run_events,
                request_id=f"{batch.request_id}-new-run",
            )
            new_builder.apply_batch(new_batch)
            self.new_run_state = new_builder.ms
        ms.execution_info.update_workflow_state_close_status(
            WorkflowState.Completed, CloseStatus.ContinuedAsNew
        )
        ms.execution_info.completion_event_batch_id = first_event_id
        ms.clear_stickyness()
        taskgen.generate_workflow_close_tasks(ms, event)


def batch_request_id(event: HistoryEvent) -> str:
    """Replay creates fresh request IDs for initiated externals
    (state_builder.go:370-372,:436-438,:465); a deterministic derivation is
    used instead of uuid.New() so oracle and kernel agree."""
    return f"replay-req-{event.id}"
