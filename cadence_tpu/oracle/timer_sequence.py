"""User/activity timer sequence: picks the next timer task to create.

Reference: /root/reference/service/history/execution/timer_sequence.go.
Only the replay-relevant surface (CreateNextUserTimer / CreateNextActivityTimer
and the load-and-sort logic) is implemented; `IsExpired` belongs to the timer
queue processor in `engine/`.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional

from ..core.enums import (
    EMPTY_EVENT_ID,
    TIMER_TASK_STATUS_CREATED,
    TIMER_TYPE_TO_STATUS_MASK,
    TimeoutType,
    TimerTaskType,
)
from .mutable_state import GeneratedTask, MutableState, ReplayError, seconds_to_nanos


@dataclass(slots=True, frozen=True)
class TimerSequenceID:
    """Reference: timer_sequence.go:71-77; sort order :459-493
    (timestamp, event id, timer type)."""

    event_id: int
    timestamp: int  # unix nanos
    timer_type: int
    timer_created: bool
    attempt: int

    def sort_key(self):
        return (self.timestamp, self.event_id, self.timer_type)


def load_and_sort_user_timers(ms: MutableState) -> List[TimerSequenceID]:
    """Reference: timer_sequence.go:201-217."""
    timers = [
        TimerSequenceID(
            event_id=ti.started_id,
            timestamp=ti.expiry_time,
            timer_type=TimeoutType.StartToClose,
            timer_created=ti.task_status == TIMER_TASK_STATUS_CREATED,
            attempt=0,
        )
        for ti in ms.pending_timer_info_ids.values()
    ]
    timers.sort(key=TimerSequenceID.sort_key)
    return timers


def load_and_sort_activity_timers(ms: MutableState) -> List[TimerSequenceID]:
    """Reference: timer_sequence.go:219-254 (schedule-to-close,
    schedule-to-start, start-to-close, heartbeat per pending activity)."""
    timers: List[TimerSequenceID] = []
    for ai in ms.pending_activity_info_ids.values():
        if ai.schedule_id == EMPTY_EVENT_ID:
            continue  # not scheduled yet (retry backoff), :274,:301,:323

        # schedule-to-close (:296-316): always applicable once scheduled
        timers.append(
            TimerSequenceID(
                event_id=ai.schedule_id,
                timestamp=ai.scheduled_time + seconds_to_nanos(ai.schedule_to_close_timeout),
                timer_type=TimeoutType.ScheduleToClose,
                timer_created=bool(ai.timer_task_status & TIMER_TYPE_TO_STATUS_MASK[TimeoutType.ScheduleToClose]),
                attempt=ai.attempt,
            )
        )
        if ai.started_id == EMPTY_EVENT_ID:
            # schedule-to-start (:269-294): only while not started
            timers.append(
                TimerSequenceID(
                    event_id=ai.schedule_id,
                    timestamp=ai.scheduled_time + seconds_to_nanos(ai.schedule_to_start_timeout),
                    timer_type=TimeoutType.ScheduleToStart,
                    timer_created=bool(ai.timer_task_status & TIMER_TYPE_TO_STATUS_MASK[TimeoutType.ScheduleToStart]),
                    attempt=ai.attempt,
                )
            )
        else:
            # start-to-close (:318-343): only once started
            timers.append(
                TimerSequenceID(
                    event_id=ai.schedule_id,
                    timestamp=ai.started_time + seconds_to_nanos(ai.start_to_close_timeout),
                    timer_type=TimeoutType.StartToClose,
                    timer_created=bool(ai.timer_task_status & TIMER_TYPE_TO_STATUS_MASK[TimeoutType.StartToClose]),
                    attempt=ai.attempt,
                )
            )
            # heartbeat (:346-381): started and heartbeat timeout configured
            if ai.heartbeat_timeout > 0:
                last_heartbeat = max(ai.started_time, ai.last_heartbeat_updated_time)
                timers.append(
                    TimerSequenceID(
                        event_id=ai.schedule_id,
                        timestamp=last_heartbeat + seconds_to_nanos(ai.heartbeat_timeout),
                        timer_type=TimeoutType.Heartbeat,
                        timer_created=bool(ai.timer_task_status & TIMER_TYPE_TO_STATUS_MASK[TimeoutType.Heartbeat]),
                        attempt=ai.attempt,
                    )
                )
    timers.sort(key=TimerSequenceID.sort_key)
    return timers


def create_next_user_timer(ms: MutableState) -> bool:
    """Reference: timer_sequence.go:127-160."""
    timers = load_and_sort_user_timers(ms)
    if not timers:
        return False
    first = timers[0]
    if first.timer_created:
        return False
    timer_id = ms.pending_timer_event_id_to_id.get(first.event_id)
    if timer_id is None:
        raise ReplayError(f"unable to load timer info {first.event_id}")
    ti = ms.pending_timer_info_ids[timer_id]
    ti.task_status = TIMER_TASK_STATUS_CREATED
    ms.add_timer_task(
        GeneratedTask(
            kind="timer",
            task_type=TimerTaskType.UserTimer,
            version=ms.current_version,
            visibility_timestamp=first.timestamp,
            event_id=first.event_id,
        )
    )
    return True


def create_next_activity_timer(ms: MutableState) -> bool:
    """Reference: timer_sequence.go:162-199."""
    timers = load_and_sort_activity_timers(ms)
    if not timers:
        return False
    first = timers[0]
    if first.timer_created:
        return False
    ai = ms.pending_activity_info_ids.get(first.event_id)
    if ai is None:
        raise ReplayError(f"unable to load activity info {first.event_id}")
    ai.timer_task_status |= TIMER_TYPE_TO_STATUS_MASK[TimeoutType(first.timer_type)]
    if first.timer_type == TimeoutType.Heartbeat:
        ai.last_heartbeat_timeout_visibility = first.timestamp // 1_000_000_000
    ms.add_timer_task(
        GeneratedTask(
            kind="timer",
            task_type=TimerTaskType.ActivityTimeout,
            version=ms.current_version,
            visibility_timestamp=first.timestamp,
            event_id=first.event_id,
            timeout_type=first.timer_type,
            attempt=first.attempt,
        )
    )
    return True
