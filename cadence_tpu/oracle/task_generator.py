"""Transfer/timer task generation during replay.

Reference: /root/reference/service/history/execution/mutable_state_task_generator.go.
Replay also emits tasks (decision dispatch, activity dispatch, timeouts,
close/retention), so kernel parity requires generating them too.

Deliberate deviation: `getNextDecisionTimeout` (task_generator.go:1051-1064)
adds random jitter to the decision start-to-close backoff; here the jitter
draw is fixed to 0 so replay is deterministic (visibility timestamps are
scheduling hints and never feed the mutable-state checksum).
"""
from __future__ import annotations

from ..core.enums import (
    CloseStatus,
    ContinueAsNewInitiator,
    TimeoutType,
    TimerTaskType,
    TransferTaskType,
    WorkflowBackoffTimeoutType,
)
from ..core.events import HistoryEvent
from .mutable_state import GeneratedTask, MutableState, ReplayError, seconds_to_nanos
from .timer_sequence import create_next_activity_timer, create_next_user_timer

# Decision retry backoff constants, task_generator.go:119-121
DEFAULT_INIT_INTERVAL_FOR_DECISION_RETRY_NANOS = 60 * 1_000_000_000
DEFAULT_MAX_INTERVAL_FOR_DECISION_RETRY_NANOS = 300 * 1_000_000_000
DEFAULT_JITTER_COEFFICIENT = 0.2

# Dynamic-config default: normal (non-sticky) decisions get no
# schedule-to-start timer (service/history/config NormalDecisionScheduleToStartMaxAttempts
# defaults to 0); stickiness is cleared on the replay path (state_builder.go:108),
# matching the standby-side comment at state_builder.go:201-203.
NORMAL_DECISION_SCHEDULE_TO_START_MAX_ATTEMPTS = 0


def generate_record_workflow_started_tasks(ms: MutableState, start_event: HistoryEvent) -> None:
    """Reference: task_generator.go:301-313."""
    ms.add_transfer_task(
        GeneratedTask(
            kind="transfer",
            task_type=TransferTaskType.RecordWorkflowStarted,
            version=start_event.version,
        )
    )


def generate_workflow_start_tasks(ms: MutableState, start_time: int, start_event: HistoryEvent) -> None:
    """Workflow-timeout timer; reference: task_generator.go:143-166."""
    info = ms.execution_info
    backoff = seconds_to_nanos(start_event.get("first_decision_task_backoff_seconds", 0) or 0)
    timeout_ts = start_time + seconds_to_nanos(info.workflow_timeout) + backoff
    attempt = start_event.get("attempt", 0) or 0
    if attempt > 0 and info.expiration_time != 0 and timeout_ts > info.expiration_time:
        timeout_ts = info.expiration_time
    ms.add_timer_task(
        GeneratedTask(
            kind="timer",
            task_type=TimerTaskType.WorkflowTimeout,
            version=start_event.version,
            visibility_timestamp=timeout_ts,
        )
    )


def generate_delayed_decision_tasks(ms: MutableState, start_event: HistoryEvent) -> None:
    """First-decision backoff timer; reference: task_generator.go:260-299."""
    backoff = seconds_to_nanos(start_event.get("first_decision_task_backoff_seconds", 0) or 0)
    execution_ts = start_event.timestamp + backoff
    initiator = start_event.get("initiator")
    timeout_type = WorkflowBackoffTimeoutType.Cron  # noParentWorkflow default, :271
    if initiator is not None:
        if initiator == ContinueAsNewInitiator.RetryPolicy:
            timeout_type = WorkflowBackoffTimeoutType.Retry
        elif initiator == ContinueAsNewInitiator.CronSchedule:
            timeout_type = WorkflowBackoffTimeoutType.Cron
        elif initiator == ContinueAsNewInitiator.Decider:
            raise ReplayError("continue as new initiator & first decision delay not 0")
        else:
            raise ReplayError(f"unknown initiator retry policy: {initiator}")
    ms.add_timer_task(
        GeneratedTask(
            kind="timer",
            task_type=TimerTaskType.WorkflowBackoffTimer,
            version=start_event.version,
            visibility_timestamp=execution_ts,
            timeout_type=timeout_type,
        )
    )


def _decision_schedule_to_start_timeout(ms: MutableState) -> int:
    """Seconds; reference: mutable_state_decision_task_manager.go:765-782."""
    info = ms.execution_info
    if info.sticky_task_list != "":
        return info.sticky_schedule_to_start_timeout
    if info.decision_attempt < NORMAL_DECISION_SCHEDULE_TO_START_MAX_ATTEMPTS:
        raise ReplayError("normal decision schedule-to-start timers not modeled")
    return 0


def generate_decision_schedule_tasks(ms: MutableState, decision_schedule_id: int) -> None:
    """Reference: task_generator.go:315-350."""
    info = ms.execution_info
    if info.decision_schedule_id != decision_schedule_id:
        raise ReplayError(f"cannot get pending decision {decision_schedule_id}")
    task_list = info.sticky_task_list if info.sticky_task_list else info.task_list
    ms.add_transfer_task(
        GeneratedTask(
            kind="transfer",
            task_type=TransferTaskType.DecisionTask,
            version=info.decision_version,
            event_id=info.decision_schedule_id,
            task_list=task_list,
        )
    )
    timeout_s = _decision_schedule_to_start_timeout(ms)
    if timeout_s != 0:
        ms.add_timer_task(
            GeneratedTask(
                kind="timer",
                task_type=TimerTaskType.DecisionTimeout,
                version=info.decision_version,
                visibility_timestamp=info.decision_scheduled_timestamp + seconds_to_nanos(timeout_s),
                timeout_type=TimeoutType.ScheduleToStart,
                event_id=info.decision_schedule_id,
                attempt=info.decision_attempt,
            )
        )


def get_next_decision_timeout_nanos(attempt: int, default_start_to_close_nanos: int) -> int:
    """Deterministic variant of task_generator.go:1051-1064 (jitter draw = 0)."""
    if attempt <= 1:
        return default_start_to_close_nanos
    interval = float(DEFAULT_INIT_INTERVAL_FOR_DECISION_RETRY_NANOS) * (2.0 ** (attempt - 2))
    interval = min(interval, float(DEFAULT_MAX_INTERVAL_FOR_DECISION_RETRY_NANOS))
    return int(interval * (1 - DEFAULT_JITTER_COEFFICIENT))


def generate_decision_start_tasks(ms: MutableState, decision_schedule_id: int) -> None:
    """Decision start-to-close timeout timer; reference: task_generator.go:352-388."""
    info = ms.execution_info
    if info.decision_schedule_id != decision_schedule_id:
        raise ReplayError(f"cannot get pending decision {decision_schedule_id}")
    start_to_close = seconds_to_nanos(info.decision_timeout)
    if info.decision_attempt > 1:
        start_to_close = get_next_decision_timeout_nanos(
            info.decision_attempt, seconds_to_nanos(info.decision_start_to_close_timeout)
        )
        info.decision_timeout = start_to_close // 1_000_000_000  # override, :374
    ms.add_timer_task(
        GeneratedTask(
            kind="timer",
            task_type=TimerTaskType.DecisionTimeout,
            version=info.decision_version,
            visibility_timestamp=info.decision_started_timestamp + start_to_close,
            timeout_type=TimeoutType.StartToClose,
            event_id=info.decision_schedule_id,
            attempt=info.decision_attempt,
        )
    )


def generate_activity_transfer_tasks(ms: MutableState, event: HistoryEvent) -> None:
    """Reference: task_generator.go:390-428."""
    ai = ms.pending_activity_info_ids.get(event.id)
    if ai is None:
        raise ReplayError(f"cannot get pending activity {event.id}")
    ms.add_transfer_task(
        GeneratedTask(
            kind="transfer",
            task_type=TransferTaskType.ActivityTask,
            version=ai.version,
            event_id=ai.schedule_id,
            task_list=ai.task_list,
            target_domain_id=ai.domain_id,
        )
    )


def generate_activity_retry_tasks(ms: MutableState, activity_schedule_id: int) -> None:
    """Reference: task_generator.go:430-449."""
    ai = ms.pending_activity_info_ids.get(activity_schedule_id)
    if ai is None:
        raise ReplayError(f"cannot get pending activity {activity_schedule_id}")
    ms.add_timer_task(
        GeneratedTask(
            kind="timer",
            task_type=TimerTaskType.ActivityRetryTimer,
            version=ai.version,
            visibility_timestamp=ai.scheduled_time,
            event_id=ai.schedule_id,
            attempt=ai.attempt,
        )
    )


def generate_child_workflow_tasks(ms: MutableState, event: HistoryEvent) -> None:
    """Reference: task_generator.go:451-498 (same-cluster path)."""
    ci = ms.pending_child_execution_info_ids.get(event.id)
    if ci is None:
        raise ReplayError(f"cannot get pending child workflow {event.id}")
    ms.add_transfer_task(
        GeneratedTask(
            kind="transfer",
            task_type=TransferTaskType.StartChildExecution,
            version=ci.version,
            event_id=ci.initiated_id,
            target_domain_id=ci.domain_id or ms.execution_info.domain_id,
            target_workflow_id=ci.started_workflow_id,
        )
    )


def generate_request_cancel_external_tasks(ms: MutableState, event: HistoryEvent) -> None:
    """Reference: task_generator.go:500-549 (same-cluster path)."""
    if event.id not in ms.pending_request_cancel_info_ids:
        raise ReplayError(f"cannot get pending request cancel {event.id}")
    ms.add_transfer_task(
        GeneratedTask(
            kind="transfer",
            task_type=TransferTaskType.CancelExecution,
            version=event.version,
            event_id=event.id,
            target_domain_id=event.get("domain_id") or ms.execution_info.domain_id,
            target_workflow_id=event.get("workflow_id", ""),
            target_run_id=event.get("run_id", ""),
            target_child_workflow_only=bool(event.get("child_workflow_only", False)),
        )
    )


def generate_signal_external_tasks(ms: MutableState, event: HistoryEvent) -> None:
    """Reference: task_generator.go:551-600 (same-cluster path)."""
    if event.id not in ms.pending_signal_info_ids:
        raise ReplayError(f"cannot get pending signal external {event.id}")
    ms.add_transfer_task(
        GeneratedTask(
            kind="transfer",
            task_type=TransferTaskType.SignalExecution,
            version=event.version,
            event_id=event.id,
            target_domain_id=event.get("domain_id") or ms.execution_info.domain_id,
            target_workflow_id=event.get("workflow_id", ""),
            target_run_id=event.get("run_id", ""),
            target_child_workflow_only=bool(event.get("child_workflow_only", False)),
        )
    )


def generate_workflow_search_attr_tasks(ms: MutableState) -> None:
    """Reference: task_generator.go:602-612."""
    ms.add_transfer_task(
        GeneratedTask(
            kind="transfer",
            task_type=TransferTaskType.UpsertWorkflowSearchAttributes,
            version=ms.current_version,
        )
    )


def generate_workflow_close_tasks(ms: MutableState, close_event: HistoryEvent) -> None:
    """Reference: task_generator.go:168-258.

    Replay is the passive-side path (`!isActive`, :180-185): exactly one
    CloseExecution transfer task plus the retention-driven history-deletion
    timer. The active-side cross-cluster fan-out lives in the host engine.
    """
    domain = ms.domain_entry
    if not domain.is_active:
        ms.add_transfer_task(
            GeneratedTask(
                kind="transfer",
                task_type=TransferTaskType.CloseExecution,
                version=close_event.version,
            )
        )
    else:
        # active same-cluster path: record child completion for parent, then
        # a single CloseExecution task (no cross-cluster children modeled here)
        if ms.has_parent_execution() and ms.execution_info.close_status != CloseStatus.ContinuedAsNew:
            ms.add_transfer_task(
                GeneratedTask(
                    kind="transfer",
                    task_type=TransferTaskType.RecordChildExecutionCompleted,
                    version=close_event.version,
                    target_domain_id=ms.execution_info.parent_domain_id,
                    target_workflow_id=ms.execution_info.parent_workflow_id,
                    target_run_id=ms.execution_info.parent_run_id,
                )
            )
        ms.add_transfer_task(
            GeneratedTask(
                kind="transfer",
                task_type=TransferTaskType.CloseExecution,
                version=close_event.version,
            )
        )
    retention_nanos = domain.retention_days * 24 * 3600 * 1_000_000_000
    ms.add_timer_task(
        GeneratedTask(
            kind="timer",
            task_type=TimerTaskType.DeleteHistoryEvent,
            version=close_event.version,
            visibility_timestamp=close_event.timestamp + retention_nanos,
        )
    )


def generate_activity_timer_tasks(ms: MutableState) -> None:
    """Reference: task_generator.go:911-915."""
    create_next_activity_timer(ms)


def generate_user_timer_tasks(ms: MutableState) -> None:
    """Reference: task_generator.go:917-921."""
    create_next_user_timer(ms)
