"""Reference mutable state: the Python semantic oracle for the TPU replay kernel.

This module re-implements, in plain Python, the passive-side (replication /
rebuild) semantics of the reference engine's `mutableStateBuilder`:

- struct fields:      /root/reference/service/history/execution/mutable_state_builder.go:83-172
- Replicate* methods: mutable_state_builder.go:1751-3810
- decision manager:   /root/reference/service/history/execution/mutable_state_decision_task_manager.go
- state transitions:  /root/reference/common/persistence/workflowExecutionInfo.go:44-165
- version histories:  /root/reference/common/persistence/versionHistory.go

It is the oracle against which the batched JAX kernel is differentially
tested (checksum parity), playing the role the Go `stateBuilder` plays in
BASELINE.json's north star. It is deliberately one-workflow-at-a-time and
readable; throughput comes from the device kernel, not from here.

Known deliberate deviation: where the reference reads the wall clock
(`timeSource.Now()`, e.g. transient-decision scheduled timestamps at
mutable_state_decision_task_manager.go:191,662) the oracle uses the current
event's timestamp so replay is deterministic. None of those timestamps feed
the mutable-state checksum (see core/checksum.py).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ..core.enums import (
    EMPTY_EVENT_ID,
    EMPTY_UUID,
    EMPTY_VERSION,
    FIRST_EVENT_ID,
    NANOS_PER_SECOND,
    TIMER_TASK_STATUS_NONE,
    CloseStatus,
    WorkflowState,
)


class ReplayError(Exception):
    """Raised on invalid history/state transitions.

    Mirrors the reference's error returns (ErrMissingActivityInfo,
    ErrMissingChildWorkflowInfo, invalid state transition, ...). The device
    kernel reports the same conditions through a sticky per-workflow error
    flag instead of raising.
    """


# ---------------------------------------------------------------------------
# Version histories (reference: common/persistence/versionHistory.go)
# ---------------------------------------------------------------------------


@dataclass(slots=True)
class VersionHistoryItem:
    event_id: int
    version: int


@dataclass(slots=True)
class VersionHistory:
    branch_token: bytes = b""
    items: List[VersionHistoryItem] = field(default_factory=list)

    def is_empty(self) -> bool:
        return not self.items

    def last_item(self) -> VersionHistoryItem:
        if not self.items:
            raise ReplayError("version history is empty")
        return self.items[-1]

    def add_or_update_item(self, event_id: int, version: int) -> None:
        """Reference: versionHistory.go:193-225."""
        if not self.items:
            self.items.append(VersionHistoryItem(event_id, version))
            return
        last = self.items[-1]
        if version < last.version:
            raise ReplayError(
                f"cannot update version history with a lower version {version} < {last.version}"
            )
        if event_id <= last.event_id:
            raise ReplayError(
                f"cannot add version history with a lower event id {event_id} <= {last.event_id}"
            )
        if version > last.version:
            self.items.append(VersionHistoryItem(event_id, version))
        else:
            last.event_id = event_id

    def find_lca_item(self, remote_items: List[VersionHistoryItem]
                      ) -> VersionHistoryItem:
        """Lowest common ancestor of this branch vs a remote item list
        (versionHistory.go:239-271 FindLCAItem): walk both item lists from
        the tail; the first version match contributes min(event_id)."""
        li = len(self.items) - 1
        ri = len(remote_items) - 1
        while li >= 0 and ri >= 0:
            local = self.items[li]
            remote = remote_items[ri]
            if local.version == remote.version:
                return VersionHistoryItem(
                    min(local.event_id, remote.event_id), local.version)
            if local.version > remote.version:
                li -= 1
            else:
                ri -= 1
        raise ReplayError("version histories have no common ancestor")

    def is_lca_appendable(self, lca: VersionHistoryItem) -> bool:
        """versionHistory.go:227-237: the remote branch extends this one
        iff the LCA is this branch's last item."""
        last = self.last_item()
        return last.event_id == lca.event_id and last.version == lca.version

    def duplicate_until_lca(self, lca: VersionHistoryItem) -> "VersionHistory":
        """versionHistory.go:136-158 DuplicateUntilLCAItem: the fork's item
        list — every item strictly below the LCA version plus the LCA-capped
        item of its version."""
        items: List[VersionHistoryItem] = []
        for item in self.items:
            if item.version < lca.version and item.event_id <= lca.event_id:
                items.append(VersionHistoryItem(item.event_id, item.version))
            elif item.version == lca.version:
                items.append(VersionHistoryItem(
                    min(item.event_id, lca.event_id), item.version))
                return VersionHistory(items=items)
            else:
                break
        raise ReplayError(f"version history cannot be forked at {lca}")


@dataclass(slots=True)
class VersionHistories:
    current_index: int = 0
    histories: List[VersionHistory] = field(default_factory=lambda: [VersionHistory()])

    def current(self) -> VersionHistory:
        return self.histories[self.current_index]

    def find_lca_index_and_item(self, remote_items: List[VersionHistoryItem]
                                ) -> tuple:
        """versionHistories.go FindLCAVersionHistoryIndexAndItem: the local
        branch sharing the deepest common ancestor with the remote items."""
        best_index = -1
        best_item: Optional[VersionHistoryItem] = None
        best_len = 0
        for index, history in enumerate(self.histories):
            if history.is_empty():
                continue
            try:
                item = history.find_lca_item(remote_items)
            except ReplayError:
                continue
            # tie-break on equal LCA event ids: prefer the branch with the
            # shorter item list, so an incoming batch appends to the branch
            # whose head IS the LCA instead of forking a duplicate
            # (versionHistories.go FindLCAVersionHistoryIndexAndItem)
            if (best_item is None or item.event_id > best_item.event_id
                    or (item.event_id == best_item.event_id
                        and len(history.items) < best_len)):
                best_index, best_item = index, item
                best_len = len(history.items)
        if best_item is None:
            raise ReplayError("no local branch shares an ancestor with remote")
        return best_index, best_item


# ---------------------------------------------------------------------------
# Pending-item infos (reference: common/persistence/dataManagerInterfaces.go
# ActivityInfo:752, TimerInfo:792, ChildExecutionInfo:801, RequestCancelInfo:818,
# SignalInfo:826)
# ---------------------------------------------------------------------------


@dataclass(slots=True)
class ActivityInfo:
    version: int
    schedule_id: int
    scheduled_event_batch_id: int
    scheduled_time: int  # unix nanos
    started_id: int
    started_time: int  # unix nanos; 0 == zero time
    activity_id: str
    domain_id: str
    task_list: str
    schedule_to_start_timeout: int
    schedule_to_close_timeout: int
    start_to_close_timeout: int
    heartbeat_timeout: int
    cancel_requested: bool = False
    cancel_request_id: int = EMPTY_EVENT_ID
    request_id: str = ""
    last_heartbeat_updated_time: int = 0
    timer_task_status: int = TIMER_TASK_STATUS_NONE
    attempt: int = 0
    has_retry_policy: bool = False
    initial_interval: int = 0
    backoff_coefficient: float = 0.0
    maximum_interval: int = 0
    maximum_attempts: int = 0
    expiration_time: int = 0  # unix nanos; 0 == zero time
    non_retriable_errors: List[str] = field(default_factory=list)
    last_failure_reason: str = ""
    last_failure_details: bytes = b""
    started_identity: str = ""
    last_worker_identity: str = ""
    last_heartbeat_timeout_visibility: int = 0  # unix seconds


@dataclass(slots=True)
class TimerInfo:
    version: int
    timer_id: str
    started_id: int
    expiry_time: int  # unix nanos
    task_status: int = TIMER_TASK_STATUS_NONE


@dataclass(slots=True)
class ChildExecutionInfo:
    version: int
    initiated_id: int
    initiated_event_batch_id: int
    started_id: int
    started_workflow_id: str
    started_run_id: str = ""
    create_request_id: str = ""
    domain_id: str = ""
    workflow_type_name: str = ""
    parent_close_policy: int = 0
    #: the StartChildWorkflowExecution decision's task list (empty =
    #: inherit the parent's, the pre-attr behavior); host-side only —
    #: never part of the canonical payload
    task_list: str = ""


@dataclass(slots=True)
class RequestCancelInfo:
    version: int
    initiated_event_batch_id: int
    initiated_id: int
    cancel_request_id: str = ""


@dataclass(slots=True)
class SignalInfo:
    version: int
    initiated_event_batch_id: int
    initiated_id: int
    signal_request_id: str = ""
    signal_name: str = ""


@dataclass(slots=True)
class DecisionInfo:
    """Reference: service/history/execution/mutable_state.go DecisionInfo."""

    version: int = EMPTY_VERSION
    schedule_id: int = EMPTY_EVENT_ID
    started_id: int = EMPTY_EVENT_ID
    request_id: str = EMPTY_UUID
    decision_timeout: int = 0
    task_list: str = ""
    attempt: int = 0
    scheduled_timestamp: int = 0
    started_timestamp: int = 0
    original_scheduled_timestamp: int = 0


# ---------------------------------------------------------------------------
# Execution info (reference: dataManagerInterfaces.go WorkflowExecutionInfo:296-353)
# ---------------------------------------------------------------------------


@dataclass(slots=True)
class ExecutionInfo:
    domain_id: str = ""
    workflow_id: str = ""
    run_id: str = ""
    first_execution_run_id: str = ""
    parent_domain_id: str = ""
    parent_workflow_id: str = ""
    parent_run_id: str = ""
    initiated_id: int = EMPTY_EVENT_ID
    completion_event_batch_id: int = EMPTY_EVENT_ID
    task_list: str = ""
    workflow_type_name: str = ""
    workflow_timeout: int = 0  # seconds
    decision_start_to_close_timeout: int = 0  # seconds
    state: int = WorkflowState.Created
    close_status: int = CloseStatus.Nothing
    last_first_event_id: int = FIRST_EVENT_ID
    last_event_task_id: int = 0
    next_event_id: int = FIRST_EVENT_ID
    last_processed_event: int = EMPTY_EVENT_ID
    start_timestamp: int = 0  # unix nanos
    create_request_id: str = ""
    signal_count: int = 0
    cron_schedule: str = ""
    #: start event's FirstDecisionTaskBackoffSeconds, kept here so cron
    #: anchor math (GetCronBackoffDuration) needn't re-read the start event
    first_decision_backoff: int = 0

    sticky_task_list: str = ""
    sticky_schedule_to_start_timeout: int = 0
    client_library_version: str = ""
    client_feature_version: str = ""
    client_impl: str = ""

    decision_version: int = EMPTY_VERSION
    decision_schedule_id: int = EMPTY_EVENT_ID
    decision_started_id: int = EMPTY_EVENT_ID
    decision_request_id: str = EMPTY_UUID
    decision_timeout: int = 0
    decision_attempt: int = 0
    decision_started_timestamp: int = 0
    decision_scheduled_timestamp: int = 0
    decision_original_scheduled_timestamp: int = 0

    cancel_requested: bool = False
    cancel_request_id: str = ""

    attempt: int = 0  # workflow retry attempt
    has_retry_policy: bool = False
    initial_interval: int = 0
    backoff_coefficient: float = 0.0
    maximum_interval: int = 0
    maximum_attempts: int = 0
    expiration_seconds: int = 0
    expiration_time: int = 0  # unix nanos
    non_retriable_errors: List[str] = field(default_factory=list)

    memo: Dict[str, bytes] = field(default_factory=dict)
    search_attributes: Dict[str, bytes] = field(default_factory=dict)

    def update_workflow_state_close_status(self, state: int, close_status: int) -> None:
        """State-machine guard; reference workflowExecutionInfo.go:44-165."""
        cur = self.state
        invalid = False
        if cur == WorkflowState.Void:
            pass  # no validation
        elif cur == WorkflowState.Created:
            if state in (WorkflowState.Created, WorkflowState.Running, WorkflowState.Zombie):
                invalid = close_status != CloseStatus.Nothing
            elif state == WorkflowState.Completed:
                invalid = close_status not in (
                    CloseStatus.Terminated,
                    CloseStatus.TimedOut,
                    CloseStatus.ContinuedAsNew,
                )
            else:
                raise ReplayError(f"unknown workflow state: {state}")
        elif cur == WorkflowState.Running:
            if state == WorkflowState.Created:
                invalid = True
            elif state in (WorkflowState.Running, WorkflowState.Zombie):
                invalid = close_status != CloseStatus.Nothing
            elif state == WorkflowState.Completed:
                invalid = close_status == CloseStatus.Nothing
            else:
                raise ReplayError(f"unknown workflow state: {state}")
        elif cur == WorkflowState.Completed:
            if state == WorkflowState.Completed:
                invalid = close_status != self.close_status
            elif state in (WorkflowState.Created, WorkflowState.Running, WorkflowState.Zombie):
                invalid = True
            else:
                raise ReplayError(f"unknown workflow state: {state}")
        elif cur == WorkflowState.Zombie:
            if state in (WorkflowState.Created, WorkflowState.Running):
                invalid = close_status != CloseStatus.Nothing
            elif state in (WorkflowState.Completed, WorkflowState.Zombie):
                invalid = close_status == CloseStatus.Nothing
            else:
                raise ReplayError(f"unknown workflow state: {state}")
        else:
            raise ReplayError(f"unknown workflow state: {cur}")

        if invalid:
            raise ReplayError(
                f"unable to change workflow state from {cur} to {state}, close status {close_status}"
            )
        self.state = state
        self.close_status = close_status


# ---------------------------------------------------------------------------
# Tasks generated during replay (reference: persistence task structs referenced
# from mutable_state_task_generator.go; only replay-relevant fields kept)
# ---------------------------------------------------------------------------


@dataclass(slots=True)
class GeneratedTask:
    """One transfer/timer/cross-cluster task produced by replay.

    `kind` is "transfer" | "timer" | "cross_cluster"; `task_type` is the
    TransferTaskType / TimerTaskType value.
    """

    kind: str
    task_type: int
    version: int
    visibility_timestamp: int = 0  # unix nanos; transfer tasks: 0 (set by shard)
    event_id: int = 0  # schedule/initiated/started event id, when applicable
    timeout_type: int = 0
    attempt: int = 0
    task_list: str = ""
    target_domain_id: str = ""
    target_workflow_id: str = ""
    target_run_id: str = ""
    target_child_workflow_only: bool = False


class MutableState:
    """Oracle mutable state: pending maps + execution info + generated tasks.

    Mirrors mutableStateBuilder's replication-relevant fields
    (mutable_state_builder.go:83-172).
    """

    __slots__ = (
        "execution_info",
        "pending_activity_info_ids",
        "pending_activity_id_to_event_id",
        "pending_timer_info_ids",
        "pending_timer_event_id_to_id",
        "pending_child_execution_info_ids",
        "pending_request_cancel_info_ids",
        "pending_signal_info_ids",
        "version_histories",
        "current_version",
        "transfer_tasks",
        "timer_tasks",
        "cross_cluster_tasks",
        "domain_entry",
        "history_size",
        "buffered_events",
        "signal_requested_ids",
    )

    def __init__(self, domain_entry: Optional["DomainEntry"] = None) -> None:
        self.execution_info = ExecutionInfo()
        self.pending_activity_info_ids: Dict[int, ActivityInfo] = {}
        self.pending_activity_id_to_event_id: Dict[str, int] = {}
        self.pending_timer_info_ids: Dict[str, TimerInfo] = {}
        self.pending_timer_event_id_to_id: Dict[int, str] = {}
        self.pending_child_execution_info_ids: Dict[int, ChildExecutionInfo] = {}
        self.pending_request_cancel_info_ids: Dict[int, RequestCancelInfo] = {}
        self.pending_signal_info_ids: Dict[int, SignalInfo] = {}
        self.version_histories = VersionHistories()
        self.current_version: int = EMPTY_VERSION
        self.transfer_tasks: List[GeneratedTask] = []
        self.timer_tasks: List[GeneratedTask] = []
        self.cross_cluster_tasks: List[GeneratedTask] = []
        self.domain_entry = domain_entry if domain_entry is not None else DomainEntry()
        self.history_size: int = 0
        #: events received while a decision is in flight, awaiting ID
        #: assignment at decision close (mutable_state_builder.go:112-114
        #: bufferedEvents / updateBufferedEvents); entries carry
        #: BUFFERED_EVENT_ID until FlushBufferedEvents reassigns them
        self.buffered_events: List["HistoryEvent"] = []
        #: applied external-signal request ids (mutable_state_builder.go
        #: signalRequestedIDs / AddSignalRequested): the at-least-once
        #: signal legs dedup against this so a redelivered signal does not
        #: append a duplicate WorkflowExecutionSignaled event
        self.signal_requested_ids: set = set()

    # -- version bookkeeping ------------------------------------------------

    def update_current_version(self, version: int, force_update: bool) -> None:
        """Reference: mutable_state_builder.go:495-533."""
        if self.execution_info.state == WorkflowState.Completed:
            # always pin to last write version once completed
            self.current_version = self.get_last_write_version()
            return
        history = self.version_histories.current()
        if not history.is_empty():
            self.current_version = history.last_item().version
        if version > self.current_version or force_update:
            self.current_version = version

    def get_last_write_version(self) -> int:
        return self.version_histories.current().last_item().version

    # -- misc helpers -------------------------------------------------------

    def clear_stickyness(self) -> None:
        """Reference: mutable_state_builder.go:1504-1511."""
        info = self.execution_info
        info.sticky_task_list = ""
        info.sticky_schedule_to_start_timeout = 0
        info.client_library_version = ""
        info.client_feature_version = ""
        info.client_impl = ""

    def get_next_event_id(self) -> int:
        return self.execution_info.next_event_id

    def has_parent_execution(self) -> bool:
        """Reference: mutableStateBuilder.HasParentExecution (parent ids set)."""
        return (
            self.execution_info.parent_workflow_id != ""
            and self.execution_info.parent_run_id != ""
        )

    # -- pending-map delete helpers ----------------------------------------

    def delete_activity(self, schedule_id: int) -> None:
        """Reference: mutable_state_builder.go:1310 DeleteActivity."""
        ai = self.pending_activity_info_ids.pop(schedule_id, None)
        if ai is None:
            raise ReplayError(f"missing activity info for schedule id {schedule_id}")
        self.pending_activity_id_to_event_id.pop(ai.activity_id, None)

    def delete_user_timer(self, timer_id: str) -> None:
        """Reference: mutable_state_builder.go:1390 DeleteUserTimer."""
        ti = self.pending_timer_info_ids.pop(timer_id, None)
        if ti is None:
            raise ReplayError(f"missing timer info for timer id {timer_id}")
        self.pending_timer_event_id_to_id.pop(ti.started_id, None)

    def delete_pending_child_execution(self, initiated_id: int) -> None:
        if self.pending_child_execution_info_ids.pop(initiated_id, None) is None:
            raise ReplayError(f"missing child execution info {initiated_id}")

    def delete_pending_request_cancel(self, initiated_id: int) -> None:
        if self.pending_request_cancel_info_ids.pop(initiated_id, None) is None:
            raise ReplayError(f"missing request cancel info {initiated_id}")

    def delete_pending_signal(self, initiated_id: int) -> None:
        if self.pending_signal_info_ids.pop(initiated_id, None) is None:
            raise ReplayError(f"missing signal info {initiated_id}")

    # -- task emission ------------------------------------------------------

    def add_transfer_task(self, task: GeneratedTask) -> None:
        self.transfer_tasks.append(task)

    def add_timer_task(self, task: GeneratedTask) -> None:
        self.timer_tasks.append(task)

    def add_cross_cluster_task(self, task: GeneratedTask) -> None:
        self.cross_cluster_tasks.append(task)


@dataclass(slots=True)
class DomainEntry:
    """Minimal domain metadata used by replay task generation.

    Reference analog: cache.DomainCacheEntry (common/cache/domainCache.go).
    Replay in this framework is the passive-side bulk path, so domains default
    to passive; the active engine sets is_active=True.
    """

    domain_id: str = "default-domain-id"
    name: str = "default-domain"
    is_active: bool = False
    retention_days: int = 1  # defaultWorkflowRetentionInDays, task_generator.go:118
    failover_version: int = 0


def seconds_to_nanos(seconds: int) -> int:
    return int(seconds) * NANOS_PER_SECOND
