"""Circuit breakers: per-target fail-fast around cross-process calls.

Reference: the Go server fronts every remote dependency with
hystrix-style breakers (yarpc outbound middleware; persistence clients
get them via the error-injection/retry decorator stack). The observable
contract reduced here:

- CLOSED: calls flow; failures within a sliding window are counted, and
  tripping the threshold (consecutive failures OR failure-rate over a
  minimum throughput) opens the circuit.
- OPEN: calls fail immediately with `CircuitOpenError` (no connect, no
  socket timeout burn) until `reset_timeout_s` elapses.
- HALF-OPEN: one probe call is let through; success closes the circuit,
  failure re-opens it (with the reset clock restarted).

A `BreakerRegistry` keys breakers by target address, so every client
tier (`rpc/client._Pool`, `RemoteCluster`, `RemoteMatching`) sharing the
registry shares breaker state per peer. State transitions emit through a
metrics registry when one is attached (gauge: 0=closed, 1=open,
2=half-open; counter: transitions), so /metrics shows which peers are
being shed.
"""
from __future__ import annotations

import threading
import time
from typing import Dict, Optional, Tuple

from . import flightrecorder

CLOSED = 0
OPEN = 1
HALF_OPEN = 2

_STATE_NAMES = {CLOSED: "closed", OPEN: "open", HALF_OPEN: "half-open"}


class CircuitOpenError(ConnectionError):
    """The breaker for this target is open: the call was shed without
    touching the network. A ConnectionError subclass, so existing
    dead-peer handling (routers trying the next host) degrades
    naturally."""


class ServiceBusy(Exception):
    """Typed server-overload/shed signal surfaced to API callers (the
    reference's ServiceBusyError): the frontend tier translates a
    breaker-open into this, so callers back off instead of queueing
    behind a dead host. Picklable — crosses the wire as-is."""


class CircuitBreaker:
    """One target's breaker (thread-safe; monotonic clock)."""

    def __init__(self, failure_threshold: int = 5,
                 failure_rate: float = 0.5, min_throughput: int = 10,
                 reset_timeout_s: float = 5.0,
                 window_s: float = 30.0) -> None:
        self.failure_threshold = failure_threshold
        self.failure_rate = failure_rate
        self.min_throughput = min_throughput
        self.reset_timeout_s = reset_timeout_s
        self.window_s = window_s
        self._lock = threading.Lock()
        self._state = CLOSED
        self._consecutive_failures = 0
        self._window_start = time.monotonic()
        self._window_successes = 0
        self._window_failures = 0
        self._opened_at = 0.0
        self._probe_inflight = False
        #: transition hook (the registry wires metrics through this)
        self.on_transition = None

    # -- state machine -----------------------------------------------------

    def _transition(self, state: int) -> None:
        """Caller holds the lock."""
        if state == self._state:
            return
        self._state = state
        if state == OPEN:
            self._opened_at = time.monotonic()
        if state in (CLOSED, HALF_OPEN):
            self._probe_inflight = False
        if state == CLOSED:
            self._consecutive_failures = 0
            self._reset_window()
        hook = self.on_transition
        if hook is not None:
            try:
                hook(state)
            except Exception:
                pass  # metrics must never fail the call path

    def _reset_window(self) -> None:
        self._window_start = time.monotonic()
        self._window_successes = 0
        self._window_failures = 0

    def _maybe_roll_window(self) -> None:
        if time.monotonic() - self._window_start > self.window_s:
            self._reset_window()

    def state(self) -> int:
        with self._lock:
            return self._state

    def state_name(self) -> str:
        return _STATE_NAMES[self.state()]

    def allow(self) -> bool:
        """May a call proceed now? OPEN→HALF_OPEN happens here once the
        reset timeout elapses; in HALF_OPEN only ONE probe is admitted at
        a time (a thundering herd against a barely-recovered peer is how
        it goes straight back down)."""
        with self._lock:
            if self._state == CLOSED:
                return True
            if self._state == OPEN:
                if time.monotonic() - self._opened_at < self.reset_timeout_s:
                    return False
                self._transition(HALF_OPEN)
            # HALF_OPEN: admit a single probe
            if self._probe_inflight:
                return False
            self._probe_inflight = True
            return True

    def on_success(self) -> None:
        with self._lock:
            if self._state == HALF_OPEN:
                self._transition(CLOSED)
                return
            self._maybe_roll_window()
            self._window_successes += 1
            self._consecutive_failures = 0

    def on_probe_abandoned(self) -> None:
        """The call admitted as the half-open probe ended with NO evidence
        about the peer (the caller's own deadline budget expired before
        the wire was touched): free the slot so the next caller probes,
        instead of wedging HALF_OPEN with a forever-inflight probe."""
        with self._lock:
            if self._state == HALF_OPEN:
                self._probe_inflight = False

    def on_failure(self) -> None:
        with self._lock:
            if self._state == HALF_OPEN:
                # the probe failed: back to OPEN, reset clock restarted
                self._transition(OPEN)
                return
            if self._state == OPEN:
                return
            self._maybe_roll_window()
            self._window_failures += 1
            self._consecutive_failures += 1
            if self._consecutive_failures >= self.failure_threshold:
                self._transition(OPEN)
                return
            total = self._window_successes + self._window_failures
            if (total >= self.min_throughput
                    and self._window_failures / total >= self.failure_rate):
                self._transition(OPEN)


class BreakerRegistry:
    """Address → breaker, shared by every client pool in a process.

    Metrics: per-target state gauge under scope "rpc.circuitbreaker"
    (metric name = "state:<host>:<port>") plus a cluster-wide transition
    counter — the BENCH-visible record of shed traffic."""

    def __init__(self, metrics=None, **breaker_kwargs) -> None:
        self._lock = threading.Lock()
        self._breakers: Dict[Tuple[str, int], CircuitBreaker] = {}
        self._kwargs = breaker_kwargs
        self.metrics = metrics

    def for_target(self, address: Tuple[str, int]) -> CircuitBreaker:
        key = (str(address[0]), int(address[1]))
        with self._lock:
            breaker = self._breakers.get(key)
            if breaker is None:
                breaker = CircuitBreaker(**self._kwargs)
                breaker.on_transition = self._transition_hook(key)
                self._breakers[key] = breaker
                registry = _resolve(self.metrics)
                if registry is not None:
                    # register the state gauge at creation (CLOSED), so
                    # /metrics shows every target even before a transition
                    registry.gauge(f"rpc.circuitbreaker.{key[0]}:{key[1]}",
                                   "breaker-state", float(CLOSED))
            return breaker

    def _transition_hook(self, key: Tuple[str, int]):
        def hook(state: int) -> None:
            # the black box records every transition even when no
            # registry is wired — breaker flaps around a dead peer are
            # exactly what a post-mortem reconstructs
            flightrecorder.emit(
                "breaker-transition", target=f"{key[0]}:{key[1]}",
                state=_STATE_NAMES.get(state, str(state)))
            registry = _resolve(self.metrics)
            if registry is None:
                return
            # target rides the scope label (prometheus metric names must
            # stay static: cadence_breaker_state{scope="...<host>:<port>"})
            registry.gauge(f"rpc.circuitbreaker.{key[0]}:{key[1]}",
                           "breaker-state", float(state))
            registry.inc("rpc.circuitbreaker", "transitions")
            if state == OPEN:
                registry.inc("rpc.circuitbreaker", "opened")
        return hook

    def snapshot(self) -> Dict[str, str]:
        with self._lock:
            return {f"{h}:{p}": _STATE_NAMES[b.state()]
                    for (h, p), b in self._breakers.items()}

    def reset(self) -> None:
        with self._lock:
            self._breakers.clear()


def _resolve(metrics):
    """None → the process-default registry (mirrors components that fall
    back to metrics.DEFAULT_REGISTRY when unwired)."""
    if metrics is not None:
        return metrics
    from .metrics import DEFAULT_REGISTRY
    return DEFAULT_REGISTRY


#: process-default registry: client pools constructed without explicit
#: wiring (bare RemoteStores in tests/tools) share breaker state per peer
DEFAULT_BREAKERS = BreakerRegistry()
