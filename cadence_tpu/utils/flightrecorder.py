"""Flight recorder: a lock-cheap in-memory ring of wide structured events.

Reference discipline: the "black box" every production service grows once
post-mortems start depending on whatever happened to be scraped last —
the Go server's equivalent surface is the structured log stream tally
cannot replay. Here one process-global ring records WIDE events (one
dict per interesting decision, not one line per log call) from the
subsystems whose interactions chaos/crashsim post-mortems reconstruct:

  txn-commit          history_engine._Txn.commit — one committed batch
  serving-drain       engine/serving._flush — one micro-batch drain cycle
  migration-out/in    engine/migration — shard movement either direction
  breaker-transition  utils/circuitbreaker — a target changed state
  quota-shed          engine/frontend._admit — admission door rejected
  crashpoint-arm/fire engine/crashpoints — durability kill sites
  fsck-finding        engine/walcheck.fsck — a typed WAL audit finding
  host-boot/host-stop rpc/server.ServiceHost lifecycle

Emit cost is one bounded-payload dict build + a deque append under a
short lock — cheap enough for the commit path. The ring dumps to JSONL
on SIGTERM / atexit / unhandled exception (install_dump_handlers, wired
by ServiceHost) and on demand (`admin flightrec`, GET /flightrec), so a
SIGTERM'd host leaves its own black box behind and a SIGKILL'd host's
last interactions survive in its PEERS' rings (their migration/breaker
events name the dead host).

Knobs: CADENCE_TPU_FLIGHTREC=0 disables emits, CADENCE_TPU_FLIGHTREC_CAP
sizes the ring (default 4096 events), CADENCE_TPU_FLIGHTREC_DUMP names
the JSONL the process-exit handlers write (default
/tmp/cadence_flightrec-<pid>.jsonl).
"""
from __future__ import annotations

import atexit
import itertools
import json
import os
import signal
import sys
import threading
import time
from collections import deque
from typing import Dict, List, Optional

ENV_ENABLED = "CADENCE_TPU_FLIGHTREC"
ENV_CAP = "CADENCE_TPU_FLIGHTREC_CAP"
ENV_DUMP = "CADENCE_TPU_FLIGHTREC_DUMP"

#: JSONL header schema tag (bump on incompatible event-shape changes)
SCHEMA = "cadence.flightrec/1"

#: per-string payload clamp: wide events carry identifiers and counts,
#: never histories — a runaway payload must not grow the ring's footprint
MAX_STR = 256
#: per-event field cap, same rationale
MAX_FIELDS = 24


def enabled() -> bool:
    return os.environ.get(ENV_ENABLED, "1") not in ("0", "false", "no")


def _cap() -> int:
    try:
        return max(16, int(os.environ.get(ENV_CAP, "4096")))
    except ValueError:
        return 4096


def default_dump_path() -> str:
    return os.environ.get(
        ENV_DUMP, f"/tmp/cadence_flightrec-{os.getpid()}.jsonl")


def _clamp(value):
    """Bound one payload value into something small and JSON-able."""
    if value is None or isinstance(value, (bool, int, float)):
        return value
    if isinstance(value, str):
        return value if len(value) <= MAX_STR else value[:MAX_STR] + "…"
    if isinstance(value, (list, tuple)):
        return [_clamp(v) for v in list(value)[:32]]
    if isinstance(value, dict):
        return {str(k)[:64]: _clamp(v)
                for k, v in itertools.islice(value.items(), 16)}
    return _clamp(repr(value))


class FlightRecorder:
    """One bounded ring of wide events. `metrics` (optional, a
    MetricsRegistry) receives flightrec/* counters when attached —
    ServiceHost points it at the host registry; the default recorder in
    a bare test process counts internally only."""

    def __init__(self, capacity: Optional[int] = None) -> None:
        self.capacity = capacity if capacity is not None else _cap()
        self._ring: deque = deque(maxlen=self.capacity)
        self._lock = threading.Lock()
        self._seq = 0
        self.metrics = None
        self.events_total = 0
        self.dropped_total = 0
        self.dumps_total = 0
        #: process-exit dump guard: SIGTERM → atexit must not write twice
        self._exit_dumped = False

    # -- emit ---------------------------------------------------------------

    def emit(self, kind: str, **fields) -> None:
        if not enabled():
            return
        if len(fields) > MAX_FIELDS:
            fields = dict(itertools.islice(fields.items(), MAX_FIELDS))
        event = {"kind": kind, "t": time.time(),
                 **{k: _clamp(v) for k, v in fields.items()}}
        with self._lock:
            self._seq += 1
            event["seq"] = self._seq
            if len(self._ring) == self.capacity:
                self.dropped_total += 1
            self._ring.append(event)
            self.events_total += 1
        registry = self.metrics
        if registry is not None:
            try:
                registry.inc("flightrec", "events")
            except Exception:
                pass  # telemetry must never fail the emitting path

    # -- reads --------------------------------------------------------------

    def snapshot(self, last_n: Optional[int] = None) -> List[Dict]:
        with self._lock:
            events = list(self._ring)
        if last_n is not None and last_n >= 0:
            events = events[-last_n:]
        return events

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {"capacity": self.capacity, "ring": len(self._ring),
                    "events": self.events_total,
                    "dropped": self.dropped_total,
                    "dumps": self.dumps_total}

    # -- dump ---------------------------------------------------------------

    def dump(self, path: Optional[str] = None, reason: str = "demand") -> str:
        """Write header + every ring event as JSONL; returns the path."""
        path = path or default_dump_path()
        events = self.snapshot()
        with self._lock:
            self.dumps_total += 1
            header = {"schema": SCHEMA, "pid": os.getpid(),
                      "reason": reason, "dumped_at": time.time(),
                      "events": len(events),
                      "dropped": self.dropped_total,
                      "events_total": self.events_total}
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as fh:
            fh.write(json.dumps(header) + "\n")
            for event in events:
                fh.write(json.dumps(event, default=str) + "\n")
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, path)  # a crashed dump never leaves a torn file
        registry = self.metrics
        if registry is not None:
            try:
                registry.inc("flightrec", "dumps")
            except Exception:
                pass
        return path

    def _dump_on_exit(self, reason: str) -> None:
        """Once-only dump for the process-exit paths (a SIGTERM handler
        that then re-raises also runs atexit)."""
        with self._lock:
            if self._exit_dumped or self.events_total == 0:
                return
            self._exit_dumped = True
        try:
            self.dump(reason=reason)
        except Exception:
            pass  # dying anyway; never mask the real exit

    def reset(self) -> None:
        """Per-test isolation: clear the ring and counters in place
        (emit points reach this recorder through the module global)."""
        with self._lock:
            self._ring.clear()
            self._seq = 0
            self.events_total = 0
            self.dropped_total = 0
            self.dumps_total = 0
            self._exit_dumped = False
        self.metrics = None


#: the process-global recorder every emit point writes through (one ring
#: per process is the point: the post-mortem wants ONE interleaved
#: timeline, not per-component shards)
DEFAULT_RECORDER = FlightRecorder()

_HANDLERS_INSTALLED = False
_INSTALL_LOCK = threading.Lock()


def emit(kind: str, **fields) -> None:
    """Module-level emit through the default recorder (the form the
    engine's emit points use)."""
    DEFAULT_RECORDER.emit(kind, **fields)


def install_dump_handlers() -> bool:
    """Arm the process-exit dumps: SIGTERM (chaining any prior handler),
    atexit, and unhandled-exception hook. Idempotent; returns whether
    the signal handler landed (only the main thread may install one —
    callers off the main thread still get atexit + excepthook)."""
    global _HANDLERS_INSTALLED
    with _INSTALL_LOCK:
        if _HANDLERS_INSTALLED:
            return True
        _HANDLERS_INSTALLED = True

    atexit.register(lambda: DEFAULT_RECORDER._dump_on_exit("atexit"))

    prev_hook = sys.excepthook

    def _excepthook(exc_type, exc, tb):
        DEFAULT_RECORDER.emit("unhandled-exception",
                              type=exc_type.__name__, error=str(exc))
        DEFAULT_RECORDER._dump_on_exit("unhandled-exception")
        prev_hook(exc_type, exc, tb)

    sys.excepthook = _excepthook

    try:
        prev_term = signal.getsignal(signal.SIGTERM)

        def _on_term(signum, frame):
            DEFAULT_RECORDER.emit("sigterm")
            DEFAULT_RECORDER._dump_on_exit("sigterm")
            if callable(prev_term) and prev_term not in (
                    signal.SIG_IGN, signal.SIG_DFL):
                prev_term(signum, frame)
            else:
                # restore + re-raise so the default disposition (die)
                # still applies after the dump
                signal.signal(signal.SIGTERM, signal.SIG_DFL)
                os.kill(os.getpid(), signal.SIGTERM)

        signal.signal(signal.SIGTERM, _on_term)
        return True
    except ValueError:
        return False  # not the main thread; exit hooks still armed


def dump_on_crash() -> None:
    """Best-effort dump for simulated hard deaths (crashpoints firing in
    kill mode SIGKILL the process — no handler will ever run, so the
    black box writes out right before the trigger pulls)."""
    DEFAULT_RECORDER._dump_on_exit("crash")


def reset_all() -> None:
    """conftest seam: clear the default recorder in place (the emit
    points hold it by reference, matching DEFAULT_REGISTRY's contract)."""
    DEFAULT_RECORDER.reset()
