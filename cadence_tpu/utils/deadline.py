"""Deadline propagation: a per-request time budget that rides the wire.

Reference: gRPC/YARPC deadlines — the caller's remaining budget (not an
absolute wall-clock time, which would require synchronized clocks) is
injected into every outbound envelope; each hop converts it back to a
local absolute deadline on receipt. A handler whose budget is already
exhausted rejects the request with a typed `DeadlineExceeded` BEFORE
doing any work (the reference's context.Deadline check at the top of
every handler), and socket timeouts for nested hops derive from what is
LEFT of the budget instead of a fixed per-hop constant.

The active deadline is a thread-local stack (like the tracer's
active-span stack in utils/tracing.py): a server handler `bind()`s the
extracted deadline for the duration of the dispatch, so every outbound
store/engine hop the handler makes inherits the shrinking budget
automatically — frontend→history→store chains share ONE budget.
"""
from __future__ import annotations

import threading
import time
from typing import Any, Dict, Optional


class DeadlineExceeded(Exception):
    """The request's time budget expired (gRPC DEADLINE_EXCEEDED analog).

    Raised client-side when a call would start with no budget left, and
    server-side when an envelope arrives already expired — in both cases
    BEFORE burning work (a kernel launch, a store transaction) that the
    caller has already given up on. Picklable, so it crosses the wire as
    a typed service error."""


class Deadline:
    """An absolute local deadline (monotonic clock) with budget math."""

    __slots__ = ("expires_at",)

    def __init__(self, expires_at: float) -> None:
        self.expires_at = expires_at

    @classmethod
    def after(cls, budget_s: float) -> "Deadline":
        return cls(time.monotonic() + budget_s)

    def remaining(self) -> float:
        """Seconds of budget left (may be <= 0)."""
        return self.expires_at - time.monotonic()

    def expired(self) -> bool:
        return self.remaining() <= 0

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"Deadline(remaining={self.remaining():.3f}s)"


_local = threading.local()


def current() -> Optional[Deadline]:
    """The calling thread's active deadline, or None (no budget bound)."""
    stack = getattr(_local, "stack", None)
    return stack[-1] if stack else None


class bind:
    """Context manager: make `deadline` the thread's active deadline.
    `bind(None)` is a no-op pass-through, so handlers can bind whatever
    extract() returned without branching."""

    def __init__(self, deadline: Optional[Deadline]) -> None:
        self._deadline = deadline

    def __enter__(self) -> Optional[Deadline]:
        if self._deadline is not None:
            stack = getattr(_local, "stack", None)
            if stack is None:
                stack = _local.stack = []
            stack.append(self._deadline)
        return self._deadline

    def __exit__(self, *exc) -> None:
        if self._deadline is not None:
            _local.stack.pop()


# -- wire-envelope propagation ----------------------------------------------
#
# The deadline rides the SAME ("traced", carrier, request) envelope the
# tracer uses (utils/tracing.py inject/extract): the carrier is a plain
# dict, so a "deadline_s" key (remaining budget at send time) coexists
# with trace_id/span_id. tracing.extract() tolerates carriers without
# trace ids, so a deadline-only envelope still unwraps cleanly there.

_KEY = "deadline_s"


def inject(request: Any) -> Any:
    """Attach the thread's remaining budget to an outbound wire request.
    Understands both a bare request and one already wrapped by
    tracing.inject(); pass-through when no deadline is bound."""
    deadline = current()
    if deadline is None:
        return request
    remaining = deadline.remaining()
    if (isinstance(request, tuple) and len(request) == 3
            and request[0] == "traced" and isinstance(request[1], dict)):
        carrier = dict(request[1])
        carrier[_KEY] = remaining
        return ("traced", carrier, request[2])
    return ("traced", {_KEY: remaining}, request)


def peek(request: Any) -> Optional[Deadline]:
    """Read the deadline off a possibly-wrapped wire request WITHOUT
    unwrapping it (tracing.extract() owns the unwrap). Tolerant of
    malformed carriers — the wire is internal, but a bad envelope must
    not take the handler down."""
    if (isinstance(request, tuple) and len(request) == 3
            and request[0] == "traced" and isinstance(request[1], dict)):
        budget = request[1].get(_KEY)
        if isinstance(budget, (int, float)):
            return Deadline.after(float(budget))
    return None
