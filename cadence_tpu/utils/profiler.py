"""Replay profiler: per-kernel-launch leg timing into metric histograms.

BENCH numbers report one end-to-end rate; regressions can't be localized
without decomposing a launch into its host legs. Every instrumented
replay path (engine/tpu_engine.py, engine/rebuild.py, native/feeder.py,
ops/replay.replay_corpus) wraps its phases in a ReplayProfiler:

  pack            — host encode/pack of the event corpus
  pack-queue-wait — device consumer stalled waiting on the pack producer
                    pipeline (engine/executor.py): this leg growing means
                    host packing is starving the device; near-zero means
                    the device side is the bottleneck
  h2d             — host→device transfer dispatch (+ bytes, M_H2D_BYTES)
  kernel          — device replay compute, measured to block_until_ready
  readback        — device→host pull of payload rows / CRCs / errors
  fallback        — capacity-escalation ladder (engine/ladder.py): gather
                    + widened-K re-replay of overflow-flagged rows; the
                    batched replacement for the per-workflow oracle leg
  serving         — micro-batched transaction flush (engine/serving.py):
                    one drain cycle of the device-serving tier — suffix
                    from-state launches plus cold full-replay admits

Legs land as histograms under the component's scope (SCOPE_TPU_REPLAY by
default, SCOPE_REBUILD for the rebuilder), so `/metrics` scrapes, the
admin snapshot, and bench.py can all diff the legs across rounds.
"""
from __future__ import annotations

import time
from contextlib import contextmanager
from typing import Dict, Optional

from . import metrics as m

#: the leg metric names, in pipeline order
LEGS = (m.M_PROFILE_PACK, m.M_PROFILE_PACK_WAIT, m.M_PROFILE_H2D,
        m.M_PROFILE_KERNEL, m.M_PROFILE_READBACK, m.M_PROFILE_FALLBACK,
        m.M_PROFILE_SERVING)


class ReplayProfiler:
    """Cheap handle over a registry: construct per launch site, record
    legs; summary() aggregates whatever the registry has accumulated."""

    def __init__(self, registry: Optional[m.MetricsRegistry] = None,
                 scope: str = m.SCOPE_TPU_REPLAY) -> None:
        self.registry = registry if registry is not None else m.DEFAULT_REGISTRY
        self.scope = scope

    @contextmanager
    def leg(self, name: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.registry.observe(self.scope, name,
                                  time.perf_counter() - t0)

    def observe(self, name: str, seconds: float) -> None:
        self.registry.observe(self.scope, name, seconds)

    def h2d(self, nbytes: int) -> None:
        """One host→device transfer of `nbytes` (count + size histogram)."""
        self.registry.inc(self.scope, m.M_H2D_BYTES, int(nbytes))
        self.registry.observe(self.scope, m.M_H2D_BYTES + "-per-transfer",
                              float(nbytes), buckets=m.BYTE_BUCKETS)

    def summary(self) -> Dict[str, object]:
        """Leg breakdown for reports (the bench JSON / `admin profile`)."""
        out: Dict[str, object] = {
            "scope": self.scope,
            "kernel_launches": self.registry.counter(
                self.scope, m.M_KERNEL_LAUNCHES),
            "h2d_bytes": self.registry.counter(self.scope, m.M_H2D_BYTES),
        }
        for leg in LEGS:
            hist = self.registry.histogram(self.scope, leg)
            if hist.count == 0:
                continue
            out[leg] = {
                "count": hist.count,
                "total_s": round(hist.total, 6),
                "p50_s": round(hist.percentile(0.5), 6),
                "p99_s": round(hist.percentile(0.99), 6),
            }
        return out
