"""HTTP scrape surface: /metrics, /health, /traces, /timeseries,
/hostprof, /flightrec.

Reference: the Go server mounts tally's prometheus reporter plus a
health endpoint on every role's HTTP port. Here one tiny stdlib HTTP
server serves the same probes over any MetricsRegistry/Tracer pair;
rpc/server.ServiceHost mounts it next to the wire port, and
Onebox.scrape_server() exposes the in-process cluster the same way.

  GET /metrics    → text/plain prometheus exposition (registry.to_prometheus)
  GET /health     → application/json from the owner's health_fn
  GET /traces     → application/json finished spans grouped by trace_id
  GET /timeseries → application/json ring-buffer windows (timeseries_fn)
  GET /hostprof   → application/json profiler rollup (hostprof_fn)
  GET /flightrec  → application/json flight-recorder snapshot (flightrec_fn)

The three telemetry endpoints take provider callables rather than the
objects themselves so the owner controls the document shape (ServiceHost
bundles sampler windows + burn doc; Onebox serves the box-wide sampler)
and a host that runs with telemetry disabled can simply not pass them —
the paths then 404 like any other unknown route.
"""
from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, Optional, Tuple


class ObservabilityHTTPServer:
    """A started-on-demand scrape server over one registry (+ optional
    tracer). Bind port 0 for an ephemeral port (tests); `port` carries
    the bound value either way."""

    def __init__(self, registry, health_fn: Optional[Callable[[], Dict]] = None,
                 tracer=None,
                 address: Tuple[str, int] = ("127.0.0.1", 0),
                 timeseries_fn: Optional[Callable[[], Dict]] = None,
                 hostprof_fn: Optional[Callable[[], Dict]] = None,
                 flightrec_fn: Optional[Callable[[], Dict]] = None) -> None:
        self.registry = registry
        self.health_fn = health_fn
        self.tracer = tracer
        self.timeseries_fn = timeseries_fn
        self.hostprof_fn = hostprof_fn
        self.flightrec_fn = flightrec_fn
        owner = self

        class _Handler(BaseHTTPRequestHandler):
            def log_message(self, *args) -> None:
                pass  # scrape traffic must not spam the host's stderr

            def _reply(self, status: int, content_type: str,
                       body: bytes) -> None:
                self.send_response(status)
                self.send_header("Content-Type", content_type)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _reply_json(self, doc) -> None:
                self._reply(200, "application/json",
                            json.dumps(doc, default=str).encode())

            def do_GET(self) -> None:
                # name the handler thread so hostprof attributes scrape
                # service time instead of lumping it under "other"
                threading.current_thread().name = "cadence-scrape"
                path = self.path.split("?", 1)[0]
                try:
                    if path == "/metrics":
                        self._reply(200,
                                    "text/plain; version=0.0.4; charset=utf-8",
                                    owner.registry.to_prometheus().encode())
                    elif path == "/health":
                        health = (owner.health_fn()
                                  if owner.health_fn else {"status": "ok"})
                        self._reply_json(health)
                    elif path == "/traces" and owner.tracer is not None:
                        traces = {
                            tid: [s.to_dict() for s in spans]
                            for tid, spans in owner.tracer.traces().items()}
                        self._reply_json(traces)
                    elif (path == "/timeseries"
                          and owner.timeseries_fn is not None):
                        self._reply_json(owner.timeseries_fn())
                    elif path == "/hostprof" and owner.hostprof_fn is not None:
                        self._reply_json(owner.hostprof_fn())
                    elif (path == "/flightrec"
                          and owner.flightrec_fn is not None):
                        self._reply_json(owner.flightrec_fn())
                    else:
                        self._reply(404, "text/plain", b"not found\n")
                except Exception as exc:
                    try:
                        self._reply(500, "text/plain",
                                    f"{type(exc).__name__}: {exc}\n".encode())
                    except Exception:
                        pass  # peer went away mid-reply

        self._httpd = ThreadingHTTPServer(address, _Handler)
        self._httpd.daemon_threads = True
        self.port: int = self._httpd.server_address[1]
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "ObservabilityHTTPServer":
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True, name="cadence-scrape")
        self._thread.start()
        return self

    def stop(self) -> None:
        # shutdown() waits on an event only serve_forever() sets — calling
        # it on a never-started server would deadlock, so gate on the
        # thread (stop() must be safe from any cleanup path)
        if self._thread is not None:
            self._httpd.shutdown()
            self._thread = None
        self._httpd.server_close()
