"""Rate limiting: token buckets over the engine's TimeSource.

Reference: common/tokenbucket/tb.go + common/quotas/ratelimiter.go:43 and
the per-domain collection (quotas/collection.go) / multi-stage limiter
(quotas/multistageratelimiter.go). Built on the injected clock so tests
with a ManualTimeSource get deterministic refill behavior.
"""
from __future__ import annotations

import threading
from typing import Callable, Dict, Tuple

from .clock import TimeSource

NANOS = 1_000_000_000


class TokenBucket:
    """Classic token bucket: `rps` refill, `burst` capacity."""

    def __init__(self, clock: TimeSource, rps: float, burst: float = 0) -> None:
        self._clock = clock
        self._lock = threading.Lock()
        self._rps = float(rps)
        self._burst = float(burst) if burst > 0 else float(rps)
        self._tokens = self._burst
        self._last = clock.now()

    def allow(self, n: float = 1.0) -> bool:
        """Consume n tokens if available (RateLimiter.Allow analog)."""
        if self._rps <= 0:
            return True  # unlimited
        with self._lock:
            now = self._clock.now()
            elapsed = max(0, now - self._last) / NANOS
            self._last = now
            self._tokens = min(self._burst, self._tokens + elapsed * self._rps)
            if self._tokens >= n:
                self._tokens -= n
                return True
            return False


class MultiStageRateLimiter:
    """Global + per-domain stages: a request passes only if EVERY stage
    admits it (quotas/multistageratelimiter.go). Limits come from live
    config closures so updates apply without restarts."""

    def __init__(self, clock: TimeSource,
                 global_rps: Callable[[], int],
                 domain_rps: Callable[[str], int],
                 burst: Callable[[], int]) -> None:
        self._clock = clock
        self._global_rps = global_rps
        self._domain_rps = domain_rps
        self._burst = burst
        self._lock = threading.Lock()
        #: buckets keyed by "" (global stage) or "domain:<name>"
        self._domains: Dict[str, TokenBucket] = {}
        self._applied: Dict[str, Tuple[float, float]] = {}

    def _bucket(self, key: str, rps: float) -> TokenBucket:
        burst = float(self._burst() or rps)
        with self._lock:
            b = self._domains.get(key)
            # rebuild on live limit OR burst changes (collection.go refresh)
            if b is None or self._applied.get(key) != (rps, burst):
                b = TokenBucket(self._clock, rps, burst)
                self._domains[key] = b
                self._applied[key] = (rps, burst)
            return b

    def allow(self, domain: str) -> bool:
        # domain stage FIRST: a hot domain's rejections must not drain the
        # global bucket for everyone else (multistageratelimiter.go order)
        d = float(self._domain_rps(domain) or 0)
        if d > 0 and not self._bucket(f"domain:{domain}", d).allow():
            return False
        g = float(self._global_rps() or 0)
        if g > 0 and not self._bucket("", g).allow():
            return False
        return True


class ServiceBusyError(Exception):
    """Over-limit rejection (types.ServiceBusyError analog)."""
