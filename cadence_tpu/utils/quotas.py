"""Rate limiting: token buckets over the engine's TimeSource.

Reference: common/tokenbucket/tb.go + common/quotas/ratelimiter.go:43,
the per-domain collection (quotas/collection.go), and the multi-stage
limiter (quotas/multistageratelimiter.go). Built on the injected clock so
tests with a ManualTimeSource get deterministic refill behavior.

Admission control contract (the layer-5 quota seat the frontend sits
behind): `MultiStageRateLimiter.admit(domain)` either returns (the
request was charged against the DOMAIN stage then the GLOBAL stage) or
raises a typed `ServiceBusyError` carrying `retry_after_s` — the
earliest moment a retry could be admitted, derived from the failing
bucket's refill rate — so callers degrade by backing off instead of
hammering. Limits come from live closures (dynamicconfig), so an
operator update to a hot domain's RPS takes effect on the next request
without a restart.
"""
from __future__ import annotations

import math
import threading
import time
import weakref
from typing import Callable, Dict, Optional, Tuple

from .clock import RealTimeSource, TimeSource

NANOS = 1_000_000_000


class ServiceBusyError(Exception):
    """Over-limit rejection (types.ServiceBusyError analog).

    Carries `retry_after_s`, the failing bucket's estimate of when one
    token will next be available — clients should back off at least that
    long. Attributes ride `args`, so the exception round-trips through
    pickle across the wire unchanged."""

    def __init__(self, message: str = "over request limit",
                 retry_after_s: float = 0.0, domain: str = "") -> None:
        super().__init__(message, retry_after_s, domain)
        self.message = message
        self.retry_after_s = retry_after_s
        self.domain = domain

    def __str__(self) -> str:
        if self.retry_after_s > 0:
            return f"{self.message} (retry after {self.retry_after_s:.3f}s)"
        return self.message


class TokenBucket:
    """Classic token bucket: `rps` refill rate, `burst` capacity.

    Burst semantics: `burst <= 0` ALIASES to `rps` — i.e. the default
    capacity is one second's worth of tokens, matching the reference's
    `NewDynamicRateLimiter` posture where an unset burst follows the
    rate. Pass an explicit positive `burst` to decouple them. `rps <= 0`
    means UNLIMITED (every consume succeeds, nothing is tracked).

    Clock discipline: refill is computed from the injected `TimeSource`.
    The bucket is safe against NON-MONOTONIC clocks (NTP step-backs,
    manual clocks driven carelessly): a backwards observation neither
    grants tokens nor rewinds `_last` — otherwise the re-elapsed wall
    time would be credited twice when the clock catches back up."""

    def __init__(self, clock: TimeSource, rps: float, burst: float = 0,
                 sleep: Callable[[float], None] = time.sleep) -> None:
        self._clock = clock
        self._lock = threading.Lock()
        self._rps = float(rps)
        self._burst = float(burst) if burst > 0 else float(rps)
        self._tokens = self._burst
        self._last = clock.now()
        #: `wait` sleeps through this seam so deterministic tests can
        #: advance a ManualTimeSource instead of blocking a real thread
        self._sleep = sleep

    @property
    def rps(self) -> float:
        return self._rps

    @property
    def burst(self) -> float:
        return self._burst

    def _refill_locked(self) -> None:
        now = self._clock.now()
        if now <= self._last:
            return  # non-monotonic guard: never credit re-elapsed time
        elapsed = (now - self._last) / NANOS
        self._last = now
        self._tokens = min(self._burst, self._tokens + elapsed * self._rps)

    def try_consume(self, n: float = 1.0) -> bool:
        """Consume n tokens iff available right now (RateLimiter.Allow
        analog); never blocks."""
        if self._rps <= 0:
            return True  # unlimited
        with self._lock:
            self._refill_locked()
            if self._tokens >= n:
                self._tokens -= n
                return True
            return False

    #: historical name — `allow` predates `try_consume`; same contract
    allow = try_consume

    def time_to(self, n: float = 1.0) -> float:
        """Seconds until n tokens COULD be consumed (0.0 when available
        now; +inf when n exceeds burst capacity — it can never be
        granted in one piece). Non-consuming: a reservation estimate the
        caller can sleep on, and the source of ServiceBusyError's
        retry_after_s."""
        if self._rps <= 0:
            return 0.0
        with self._lock:
            self._refill_locked()
            if self._tokens >= n:
                return 0.0
            if n > self._burst:
                return math.inf
            return (n - self._tokens) / self._rps

    def wait(self, n: float = 1.0, deadline: Optional[int] = None) -> bool:
        """Block until n tokens are consumed or `deadline` (absolute unix
        nanos on the injected clock) would pass first; returns whether
        the tokens were obtained. Built on the TimeSource + the injected
        sleep seam, so ManualTimeSource tests drive it deterministically
        (`sleep=lambda s: clock.advance(int(s * NANOS))`)."""
        while True:
            if self.try_consume(n):
                return True
            need = self.time_to(n)
            if math.isinf(need):
                return False  # n > burst: unsatisfiable, never spin
            if deadline is not None:
                now = self._clock.now()
                if now + need * NANOS > deadline:
                    return False
            # sleep the full deficit: the deficit only shrinks with time,
            # so one sleep per loop is enough (competing consumers may
            # steal the refill — the loop re-checks)
            self._sleep(max(need, 1.0 / NANOS))


#: the shared bucket behind every UNLIMITED (rps <= 0) domain: stateless
#: (every consume short-circuits on rps <= 0), so one instance serves all
_UNLIMITED = TokenBucket(RealTimeSource(), rps=0)


class Collection:
    """Per-domain limiter collection (quotas/collection.go): one bucket
    per domain, built lazily from a LIVE limit closure and rebuilt
    whenever the closure's answer changes — a dynamicconfig update to a
    domain's RPS takes effect on that domain's next request, without a
    restart and without touching other domains' buckets."""

    def __init__(self, clock: TimeSource,
                 rps_for: Callable[[str], float],
                 burst_for: Optional[Callable[[str], float]] = None) -> None:
        self._clock = clock
        self._rps_for = rps_for
        self._burst_for = burst_for or (lambda domain: 0.0)
        self._lock = threading.Lock()
        self._buckets: Dict[str, TokenBucket] = {}
        #: domain → (rps, burst) the live closures answered at build time
        self._applied: Dict[str, Tuple[float, float]] = {}

    def bucket(self, domain: str) -> TokenBucket:
        rps = float(self._rps_for(domain) or 0)
        burst = float(self._burst_for(domain) or 0)
        if rps <= 0:
            # unlimited: share one stateless bucket instead of caching an
            # entry per domain NAME — request-supplied names must never
            # grow server memory (a spray of junk domains would otherwise
            # leak a bucket each)
            return _UNLIMITED
        with self._lock:
            b = self._buckets.get(domain)
            if b is None or self._applied.get(domain) != (rps, burst):
                b = TokenBucket(self._clock, rps, burst)
                self._buckets[domain] = b
                self._applied[domain] = (rps, burst)
            return b

    def limited(self, domain: str) -> bool:
        """Whether this domain has a positive configured limit (i.e. its
        bucket is real, not the shared unlimited one)."""
        return float(self._rps_for(domain) or 0) > 0

    def allow(self, domain: str, n: float = 1.0) -> bool:
        return self.bucket(domain).try_consume(n)

    def time_to(self, domain: str, n: float = 1.0) -> float:
        return self.bucket(domain).time_to(n)

    def reset(self) -> None:
        """Drop every bucket (test isolation seam)."""
        with self._lock:
            self._buckets.clear()
            self._applied.clear()


#: every MultiStageRateLimiter constructed in this process — the test
#: isolation seam (`reset_all`), mirroring DEFAULT_BREAKERS/DEFAULT_REGISTRY
_LIMITERS: "weakref.WeakSet[MultiStageRateLimiter]" = weakref.WeakSet()


def reset_all() -> None:
    """Drop every limiter's bucket state in place (components hold their
    limiter by reference, so clearing in place is the only reset that
    reaches them all — same contract as MetricsRegistry.reset)."""
    for limiter in list(_LIMITERS):
        limiter.reset()


class MultiStageRateLimiter:
    """Global + per-domain stages: a request passes only if EVERY stage
    admits it (quotas/multistageratelimiter.go). Limits come from live
    config closures so updates apply without restarts."""

    def __init__(self, clock: TimeSource,
                 global_rps: Callable[[], int],
                 domain_rps: Callable[[str], int],
                 burst: Callable[[], int]) -> None:
        self._clock = clock
        self._burst = burst
        #: domain stage (quotas/collection.go); the global stage rides the
        #: same collection under the reserved "" key (domains are
        #: non-empty strings, so it can never collide)
        self._domains = Collection(
            clock,
            rps_for=lambda d: (global_rps() if d == ""
                               else domain_rps(d)),
            burst_for=lambda d: burst())
        _LIMITERS.add(self)

    def allow(self, domain: str) -> bool:
        # domain stage FIRST: a hot domain's rejections must not drain the
        # global bucket for everyone else (multistageratelimiter.go order)
        if not self._domains.allow(domain):
            return False
        if not self._domains.allow(""):
            return False
        return True

    def retry_after(self, domain: str) -> float:
        """Seconds until BOTH stages could plausibly admit one request —
        the max of the two deficits (non-consuming estimate)."""
        waits = [self._domains.time_to(domain), self._domains.time_to("")]
        finite = [w for w in waits if not math.isinf(w)]
        return max(finite) if finite else 0.0

    def admit(self, domain: str) -> None:
        """allow() or raise the typed shed: ServiceBusyError carrying the
        retry-after estimate (the frontend's admission-control arm)."""
        if not self.allow(domain):
            raise ServiceBusyError(
                f"domain {domain!r} over request limit",
                retry_after_s=round(self.retry_after(domain), 6),
                domain=domain)

    def reset(self) -> None:
        self._domains.reset()


# -- per-host quota knobs over the environment ------------------------------

#: the cross-process quota spec (subprocess clusters inherit it through
#: rpc/cluster.launch env_per_role; rpc/server.ServiceHost applies it to
#: its DynamicConfig at boot):
#:     CADENCE_TPU_QUOTAS="rps=200,burst=50,domain.hot=20,domain.cold=80"
QUOTAS_ENV = "CADENCE_TPU_QUOTAS"


def parse_quota_spec(spec: str) -> Tuple[float, float, Dict[str, float]]:
    """"rps=200,burst=50,domain.hot=20" → (global_rps, burst, {domain:
    rps}). Unknown keys raise — a typo'd spec silently admitting
    everything is worse than failing loudly at boot (same posture as
    chaos.parse_kv_spec)."""
    global_rps, burst = 0.0, 0.0
    domains: Dict[str, float] = {}
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        key, sep, value = part.partition("=")
        key = key.strip()
        if not sep:
            raise ValueError(f"malformed knob {part!r} in {spec!r}")
        if key == "rps":
            global_rps = float(value)
        elif key == "burst":
            burst = float(value)
        elif key.startswith("domain."):
            domain = key[len("domain."):]
            if not domain:
                raise ValueError(f"empty domain in {part!r}")
            domains[domain] = float(value)
        else:
            raise ValueError(f"unknown knob {key!r} in {spec!r}")
    return global_rps, burst, domains
