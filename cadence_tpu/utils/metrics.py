"""Metrics: counters / timers / gauges / histograms behind named scopes.

Reference: common/metrics (Client/Scope at metrics/interfaces.go:31,:53;
every scope and metric name enumerated in metrics/defs.go). The reference
emits through tally to m3/statsd/prometheus; here the registry keeps the
aggregates in-process and exposes two emitter seams: snapshot() (the
structured dump tests and the bench assert on, now with percentiles) and
to_prometheus() (text exposition format 0.0.4, served by the /metrics
scrape surface in utils/scrape.py and rpc/server.py).

Timers feed fixed-bucket histograms on every record(), so each latency
metric carries a full distribution (bucket counts + interpolated
percentiles), not just count/total/max.

Thread-safe; scopes are cheap handles over the shared registry.
"""
from __future__ import annotations

import bisect
import re
import threading
import time
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple


# -- scope names (metrics/defs.go analog; the subset the engine emits) ------

SCOPE_HISTORY_START_WORKFLOW = "history.start-workflow-execution"
SCOPE_HISTORY_DECISION_COMPLETED = "history.respond-decision-task-completed"
SCOPE_HISTORY_ACTIVITY_RESPOND = "history.respond-activity-task"
SCOPE_HISTORY_SIGNAL = "history.signal-workflow-execution"
SCOPE_HISTORY_RESET = "history.reset-workflow-execution"
SCOPE_FRONTEND_START = "frontend.start-workflow-execution"
SCOPE_FRONTEND_SIGNAL = "frontend.signal-workflow-execution"
SCOPE_QUEUE_TRANSFER = "queue.transfer"
SCOPE_QUEUE_TIMER = "queue.timer"
SCOPE_REPLICATION = "replication.task-processor"
SCOPE_TPU_REPLAY = "tpu.replay-engine"
SCOPE_REBUILD = "tpu.device-rebuilder"
SCOPE_PACK_CACHE = "tpu.pack-cache"
SCOPE_TPU_FALLBACK = "tpu.fallback"
SCOPE_TPU_RESIDENT = "tpu.resident"
#: the mesh-aware bulk executor's own scope (engine/executor.py):
#: chunks-dispatched / pack-queue-wait / device-busy, with PER-DEVICE
#: series (device_metric) when the executor runs over a mesh
SCOPE_TPU_EXECUTOR = "tpu.executor"
#: the native (C++) host-packing seam (native/packing.py + native/
#: wirec.py): the `available` gauge says whether the compiled .so is
#: loadable in THIS process (1) or every pack silently took the pure-
#: Python path (0); native-packs / python-packs count which encoder
#: actually served each wirec pack, so a scrape settles "which path ran"
SCOPE_TPU_NATIVE = "tpu.native"
#: the micro-batching device-serving transaction tier (engine/serving.py
#: ServingScheduler): committed decision transactions coalesce into one
#: from-state launch per owning mesh device; counters below under
#: M_SERVING_*
SCOPE_TPU_SERVING = "tpu.serving"
#: M_SNAP_* (engine/snapshot.py — the persisted mutable-state tier)
SCOPE_TPU_SNAPSHOT = "tpu.snapshot"
#: live HBM state migration across the host cluster (engine/migration.py
#: MigrationManager): shard movement snapshots resident rows out of the
#: losing host and hydrates them on the gaining host; counters below
#: under M_MIG_*
SCOPE_TPU_MIGRATION = "tpu.migration"
#: the columnar device visibility tier (engine/visibility_device.py +
#: ops/scan.py): List/Scan/Count served as vectorized mask kernels over
#: device-resident columns; counters below under M_VIS_*
SCOPE_TPU_VISIBILITY = "tpu.visibility"
SCOPE_WORKER_RETENTION = "worker.retention"
SCOPE_WORKER_SCAVENGER = "worker.scavenger"
SCOPE_WORKER_SCANNER = "worker.scanner"
SCOPE_HISTORY_RECORD_STARTED = "history.record-decision-task-started"
SCOPE_FRONTEND_POLL_DECISION = "frontend.poll-for-decision-task"
SCOPE_FRONTEND_RESET = "frontend.reset-workflow-execution"
SCOPE_FRONTEND_QUERY = "frontend.query-workflow"
SCOPE_FRONTEND_READ = "frontend.read"
SCOPE_MATCHING_POLL_DECISION = "matching.poll-decision-task"
SCOPE_MATCHING_ADD_DECISION = "matching.add-decision-task"
#: the admission-control seat (common/quotas, PAPER §1 layer 5): every
#: frontend API charged against the multi-stage limiter counts here —
#: `admitted`/`shed` totals plus per-domain series (domain_metric), so a
#: scrape shows WHICH domain is being shed while the others hold
SCOPE_QUOTAS = "quotas"
#: the open-loop load generator's own scopes ride "loadgen.<op-kind>"
#: (cadence_tpu/loadgen/generator.py); per-domain latency series use the
#: same domain_metric labeling as the quota counters
SCOPE_LOADGEN_PREFIX = "loadgen"
#: host-runtime attribution (utils/hostprof.py HostProfiler): gauges for
#: per-subsystem wall/CPU shares (wall-share-<subsystem>,
#: cpu-seconds-<subsystem>), the GIL-contention estimate, and the
#: attributed-share acceptance gate — the sampling-profiler mirror of
#: the `admin hostprof` rollup
SCOPE_HOSTPROF = "host.prof"
#: ring-buffer sampler health (utils/timeseries.py TimeSeriesSampler):
#: windows retained, samples taken, last-window utilization — the flat
#: /metrics mirror of the windowed GET /timeseries surface
SCOPE_TIMESERIES = "timeseries"
#: flight-recorder ring (utils/flightrecorder.py): wide events recorded
#: and JSONL dumps written by THIS process's black box
SCOPE_FLIGHTREC = "flightrec"
#: continuous SLO burn rates (loadgen/slo.py BurnRateEvaluator over the
#: ring-buffer windows): burn-rate-<op>-<metric>-<horizon>s gauges — 1.0
#: means the error budget is being consumed exactly at its sustainable
#: rate; multi-window alerting fires when the SHORT and LONG horizons
#: both exceed the threshold
SCOPE_SLO = "slo"
#: hashring membership as observed by THIS host (rpc/server.py
#: refresh_membership): drop/join counters plus the ring-generation
#: gauge — the witnesses chaos campaigns read to prove a membership
#: flap propagated fleet-wide (gen/cluster_chaos.py)
SCOPE_MEMBERSHIP = "membership"
#: shard controller (engine/controller.py): fenced-engine evictions — a
#: deposed context discarded and re-acquired after a flap-back
SCOPE_CONTROLLER = "controller"

# -- metric names -----------------------------------------------------------

M_REQUESTS = "requests"
M_ERRORS = "errors"
M_LATENCY = "latency"
M_TASKS_PROCESSED = "tasks-processed"
M_TASKS_DROPPED_NOT_EXISTS = "tasks-dropped-entity-not-exists"
#: executor dropped a task whose workflow a PEER cluster's promotion
#: already owns (version arbitration rejected the local mutation)
M_TASKS_DROPPED_STALE = "tasks-dropped-stale-version"
M_REPL_APPLIED = "replication-applied"
M_REPL_DEDUPED = "replication-deduped"
M_REPL_RESENT = "replication-resends"
M_REPL_DLQ = "replication-dlq"
#: replication DLQ depth gauge: current quarantined-entry count on the
#: target store (maintained at every enqueue/redrive/purge touch point)
M_REPL_DLQ_DEPTH = "dlq-depth"
#: DLQ redrive: entries re-applied through the resender by the
#: `admin dlq` redrive arm / processor.redrive_dlq
M_REPL_REDRIVEN = "replication-redriven"
#: device standby apply (engine/replication.py _DeviceApplier): applied
#: histories streamed through the resident tier at the bulk-ingest rate,
#: host-parity gated per apply — divergence counted, never served
M_REPL_DEVICE_APPLIED = "device-applied"
M_REPL_DEVICE_SUFFIX_EVENTS = "device-suffix-events"
M_REPL_DEVICE_COLD = "device-skipped-cold"
M_REPL_DEVICE_STALE = "device-skipped-stale"
M_REPL_DEVICE_DIVERGENCE = "device-parity-divergence"
M_REPL_DEVICE_UNSTABLE = "device-parity-skipped-unstable"
#: snapshot-shipping replication: checksum-gated SnapshotRecords riding
#: the wire replication stream so a standby's cold admits and promotion
#: are seed_caches + suffix replay, never full replay
M_REPL_SNAP_SHIPPED = "snapshots-shipped"
M_REPL_SNAP_INSTALLED = "snapshots-installed"
M_REPL_SNAP_IGNORED_TORN = "snapshots-ignored-torn"
M_REPL_SNAP_IGNORED_STALE = "snapshots-ignored-stale"
M_REPL_SNAP_IGNORED_FOREIGN = "snapshots-ignored-foreign"
#: per-domain replication backpressure (engine/replication.py): a drain
#: pass stops (typed ReplicationBackpressureShed) once one domain has
#: consumed its per-pass apply budget, so a partition-heal flood on one
#: domain cannot starve the pump tick for every other domain; -deferred
#: counts the tasks the shed pass left for the next tick
M_REPL_BP_SHED = "backpressure-shed"
M_REPL_BP_DEFERRED = "backpressure-deferred"
#: domain-metadata failover-version arbitration (engine/domainrepl.py):
#: applied mutations vs stale ones rejected (lower failover version than
#: the local record — the split-brain loser's update) vs duplicate
#: notification replays at the same failover version
M_DOMREPL_APPLIED = "domain-applied"
M_DOMREPL_STALE_REJECTED = "domain-stale-rejected"
M_DOMREPL_DUPLICATE = "domain-duplicate"
#: membership-flap witnesses (SCOPE_MEMBERSHIP)
M_RING_DROPS = "ring-drops"
M_RING_JOINS = "ring-joins"
M_RING_GENERATION = "ring-generation"
#: fenced-engine evictions (SCOPE_CONTROLLER)
M_FENCED_EVICTIONS = "fenced-evictions"
M_KERNEL_LAUNCHES = "kernel-launches"
M_EVENTS_REPLAYED = "events-replayed"
M_REPLAY_THROUGHPUT = "replay-events-per-sec"
M_DEVICE_REBUILDS = "device-rebuilds"
M_ORACLE_FALLBACKS = "oracle-fallbacks"
M_FALLBACK_RATE = "fallback-rate"
M_BUFFERED_FLUSHED = "buffered-events-flushed"
M_RATE_LIMITED = "requests-rate-limited"
M_RUNS_DELETED = "runs-deleted"
M_RUNS_ARCHIVED = "runs-archived"
M_EXECUTIONS_SCANNED = "executions-scanned"
M_INVARIANT_VIOLATIONS = "invariant-violations"
#: replay-profiler legs (utils/profiler.py): per-kernel-launch host cost
M_PROFILE_PACK = "pack"
M_PROFILE_H2D = "h2d"
M_PROFILE_KERNEL = "kernel"
M_PROFILE_READBACK = "readback"
#: time the device consumer spends waiting on the pack producer pipeline
#: (engine/executor.py): non-zero p50 here means the host packers are
#: starving the device; a near-zero leg means the device is the bottleneck
M_PROFILE_PACK_WAIT = "pack-queue-wait"
#: capacity-escalation leg (engine/ladder.py): gather + widened-K
#: re-replay of flagged rows; replaces the per-workflow oracle leg on
#: capacity overflow, so this leg growing while oracle fallbacks stay
#: flat is the ladder working as intended
M_PROFILE_FALLBACK = "fallback"
#: device-serving leg (engine/serving.py): the micro-batched flush of
#: committed transactions — suffix from-state launches plus cold admits
#: — per drain cycle; this leg next to pack/kernel says how much of a
#: launch window the serving tier occupies
M_PROFILE_SERVING = "serving"
M_H2D_BYTES = "h2d-bytes"
#: pack-cache counters (engine/cache.py PackCache, SCOPE_PACK_CACHE)
M_CACHE_HITS = "hits"
M_CACHE_MISSES = "misses"
M_CACHE_EVICTIONS = "evictions"
M_CACHE_SUFFIX_PACKS = "suffix-packs"
#: resident-state cache counters (engine/resident.py ResidentStateCache,
#: SCOPE_TPU_RESIDENT): exact hits reuse the cached payload with zero
#: device work, suffix hits replay only appended batches against the
#: HBM-resident state, invalidations count stale entries dropped on tail
#: overwrite / reset / NDC branch switch; the resident-bytes gauge is
#: the cache's HBM footprint against its configured budget
M_CACHE_INVALIDATIONS = "invalidations"
M_RESIDENT_SUFFIX_HITS = "suffix-hits"
M_RESIDENT_BYTES = "resident-bytes"
M_RESIDENT_ENTRIES = "resident-entries"
M_RESIDENT_BUDGET_BYTES = "budget-bytes"
M_RESIDENT_EVENTS_APPENDED = "events-appended"
M_RESIDENT_WIDENED = "widened-rows"
M_RESIDENT_NARROWED = "renarrowed-rows"
#: capacity-escalation ladder counters (engine/ladder.py,
#: SCOPE_TPU_FALLBACK): rows entering the ladder, rows re-replayed at
#: each rung (metric name ladder_rung_rows(r)), rows resolved on device,
#: rows left for oracle arbitration, widened-kernel compiles, and the
#: kernel-variant cache hits/misses that prove a warm run recompiled
#: nothing (utils/compile_cache.KernelVariantCache)
M_LADDER_FLAGGED = "flagged-rows"
M_LADDER_RESOLVED = "resolved-rows"
M_LADDER_RESIDUAL = "residual-oracle-rows"
M_LADDER_COMPILES = "rung-compiles"
M_LADDER_CACHE_HITS = "compile-cache-hits"
M_LADDER_CACHE_MISSES = "compile-cache-misses"
#: mesh-aware executor counters (engine/executor.py, SCOPE_TPU_EXECUTOR):
#: chunks dispatched to the device mesh (plus a device_metric series per
#: mesh position) and the per-device busy gauge — in-flight chunks whose
#: shard slice occupies that device; rows-dispatched counts REAL workflow
#: rows per device slice (padding excluded), so skewed shard population
#: is visible on a scrape
M_EXEC_CHUNKS = "chunks-dispatched"
M_EXEC_ROWS = "rows-dispatched"
M_EXEC_DEVICE_BUSY = "device-busy"
#: admission-control counters (SCOPE_QUOTAS): requests the multi-stage
#: limiter admitted vs shed (typed ServiceBusyError with retry-after)
M_QUOTA_ADMITTED = "admitted"
M_QUOTA_SHED = "shed"
#: native-seam observability (SCOPE_TPU_NATIVE)
M_NATIVE_AVAILABLE = "available"
M_NATIVE_PACKS = "native-packs"
M_NATIVE_PY_PACKS = "python-packs"
#: device-serving transaction tier (engine/serving.py ServingScheduler,
#: SCOPE_TPU_SERVING): committed history-engine transactions enqueue
#: into a per-shard coalescing queue and flush as ONE from-state launch
#: per owning mesh device — `transactions`/`batched-launches` give the
#: coalescing factor, `coalesced-appends` counts same-workflow
#: transactions folded into one pending append, `batch-size` and
#: `queue-wait` are the micro-batching histograms, and
#: `parity-divergence` counts device payloads that disagreed with the
#: oracle's committed state (the entry is invalidated, never served)
M_SERVING_TXNS = "transactions"
M_SERVING_LAUNCHES = "batched-launches"
M_SERVING_COALESCED = "coalesced-appends"
M_SERVING_BATCH_SIZE = "batch-size"
M_SERVING_QUEUE_WAIT = "queue-wait"
M_SERVING_DIVERGENCE = "parity-divergence"
M_SERVING_EXACT = "exact-serves"
M_SERVING_SUFFIX = "suffix-appends"
M_SERVING_COLD = "cold-admits"
M_SERVING_BYPASSED = "bypassed"
M_SERVING_REQUEUED = "requeued"
M_SERVING_REJECTED = "busy-rejections"
M_SERVING_QUEUE_DEPTH = "queue-depth"
#: persisted mutable-state snapshot tier (engine/snapshot.py,
#: SCOPE_TPU_SNAPSHOT): `writes` counts checksum-gated snapshot records
#: appended to the WAL, `checksum-skips` counts writes refused because
#: the resident payload disagreed with the oracle's live state (never
#: persisted), `hydrates` counts snapshot→resident seeds on a cold path
#: (restart, chain break, cold admit), `ignored-stale`/`ignored-torn`
#: count snapshots detected invalid and skipped — fallen back to full
#: replay, never served; the gauges mirror the store's occupancy
M_SNAP_WRITES = "writes"
M_SNAP_CHECKSUM_SKIPS = "checksum-skips"
M_SNAP_HYDRATES = "hydrates"
M_SNAP_IGNORED_STALE = "ignored-stale"
M_SNAP_IGNORED_TORN = "ignored-torn"
M_SNAP_BYTES = "snapshot-bytes"
M_SNAP_ENTRIES = "snapshot-entries"

#: live HBM state migration (engine/migration.py, SCOPE_TPU_MIGRATION):
#: on shard RELEASE the losing host writes checksum-gated snapshot
#: records for its moving resident rows (`migrated-out`; gate-refused
#: writes count `migrate-out-skipped`) and drops the local entries
#: (`evicted-resident`); on shard ACQUIRE the gaining host hydrates the
#: stolen shards' open workflows from the shared snapshot store —
#: `migrated-in` counts snapshot-hydrated admits (suffix catch-up
#: events under `suffix-events`), `cold-steals` keys with no usable
#: record (full replay on first touch), `stale-snapshots` records whose
#: address no longer prefixes the stored bytes. `parity-divergence`
#: counts hydrated rows whose payload disagreed with the oracle's live
#: state over a STABLE store (dropped, never served — gated at 0);
#: `parity-skipped-unstable` counts comparisons skipped because a
#: foreign commit moved the tail mid-hydration (not divergence).
M_MIG_OUT = "migrated-out"
M_MIG_OUT_SKIPPED = "migrate-out-skipped"
M_MIG_EVICTED = "evicted-resident"
M_MIG_IN = "migrated-in"
M_MIG_COLD = "cold-steals"
#: record-less keys at/under the young floor (migration.YOUNG_BATCHES):
#: expected-cold per the snapshot policy's own min_events floor, kept
#: out of the warm-failover ratio
M_MIG_YOUNG = "young-steals"
M_MIG_STALE = "stale-snapshots"
M_MIG_SUFFIX_EVENTS = "suffix-events"
M_MIG_DIVERGENCE = "parity-divergence"
M_MIG_UNSTABLE = "parity-skipped-unstable"

#: columnar device visibility tier (engine/visibility_device.py,
#: SCOPE_TPU_VISIBILITY): `queries` counts every routed List/Scan/Count,
#: split into `device-served` (mask kernel answered) vs `host-fallbacks`
#: (evaluated on the host instead — `fallback-predicate` the query uses
#: an op/column the kernels can't express (e.g. string ordering),
#: `fallback-column` a search-attribute column past the intern budget or
#: type-poisoned). `parity-divergence` counts device answers that
#: disagreed with the host oracle (served the HOST answer, gated at 0);
#: `topk-serves` vs `bitmap-scans` splits paged readback strategies,
#: `topk-escalations` counts pages that re-ran through the bitmap path
#: (boundary tie / truncation). `deltas-applied`/`drains` meter the
#: coalescing appender; `staleness-pending` is the backlog a query
#: observed before its flush (the recorded staleness gauge), and
#: `rows`/`attr-columns`/`interned-strings` mirror column occupancy.
M_VIS_QUERIES = "queries"
M_VIS_DEVICE_SERVED = "device-served"
M_VIS_HOST_FALLBACKS = "host-fallbacks"
M_VIS_FALLBACK_PREDICATE = "fallback-predicate"
M_VIS_FALLBACK_COLUMN = "fallback-column"
M_VIS_PARITY_CHECKS = "parity-checks"
M_VIS_DIVERGENCE = "parity-divergence"
M_VIS_TOPK = "topk-serves"
M_VIS_BITMAP = "bitmap-scans"
M_VIS_TOPK_ESCALATIONS = "topk-escalations"
M_VIS_DELTAS = "deltas-applied"
M_VIS_DRAINS = "drains"
M_VIS_STALENESS = "staleness-pending"
M_VIS_ROWS = "rows"
M_VIS_ATTR_COLUMNS = "attr-columns"
M_VIS_INTERNED = "interned-strings"
M_VIS_SCAN_LATENCY = "scan-latency"
#: LFU attr-column swaps: an over-budget search attribute out-demanded
#: the least-queried resident column and took its slot — queries on it
#: stop permanently falling back (visibility_device._maybe_replace_attr)
M_VIS_ATTR_REPLACEMENTS = "attr-column-replacements"


def ladder_rung_rows(rung: int) -> str:
    """Per-rung row counter name: rows-rung1, rows-rung2, ..."""
    return f"rows-rung{rung}"


def device_metric(name: str, device: int) -> str:
    """Per-device series name: chunks-dispatched-dev0, device-busy-dev3,
    ... — the device label of the mesh-aware executor's metrics (the
    registry keys on flat (scope, name), so the label rides the name the
    same way ladder_rung_rows carries the rung)."""
    return f"{name}-dev{device}"


def domain_metric(name: str, domain: str) -> str:
    """Per-domain series name: shed-domain-hot, latency-domain-payments,
    ... — the domain label of the quota/loadgen metrics, riding the flat
    (scope, name) key exactly like device_metric's device label
    (to_prometheus sanitizes the domain into the metric grammar)."""
    return f"{name}-domain-{domain}"


#: latency buckets (seconds): sub-ms sync paths through multi-second
#: device compiles — tally's default histogram ladder, trimmed
DEFAULT_BUCKETS: Tuple[float, ...] = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
    0.25, 0.5, 1.0, 2.5, 5.0, 10.0)

#: byte-size buckets (h2d transfer sizes: KBs to the 256MB frame cap)
BYTE_BUCKETS: Tuple[float, ...] = (
    1024.0, 16384.0, 262144.0, 1048576.0, 16777216.0, 268435456.0)


class HistogramStat:
    """Fixed-bucket histogram (prometheus `le` semantics: bucket i counts
    values <= bounds[i]; the last slot is +Inf)."""

    __slots__ = ("bounds", "bucket_counts", "count", "total")

    def __init__(self, bounds: Sequence[float] = DEFAULT_BUCKETS) -> None:
        self.bounds: Tuple[float, ...] = tuple(bounds)
        self.bucket_counts: List[int] = [0] * (len(self.bounds) + 1)
        self.count = 0
        self.total = 0.0

    def observe(self, value: float) -> None:
        self.count += 1
        self.total += value
        self.bucket_counts[bisect.bisect_left(self.bounds, value)] += 1

    def cumulative(self) -> List[Tuple[str, int]]:
        """[(le_label, cumulative_count)] ending with ("+Inf", count)."""
        out: List[Tuple[str, int]] = []
        running = 0
        for bound, n in zip(self.bounds, self.bucket_counts):
            running += n
            out.append((str(bound), running))
        out.append(("+Inf", self.count))
        return out

    def percentile(self, q: float) -> float:
        """q in [0, 1]; linear interpolation inside the covering bucket.
        Values in the +Inf bucket clamp to the top finite bound."""
        if self.count == 0:
            return 0.0
        target = q * self.count
        running = 0
        lo = 0.0
        for bound, n in zip(self.bounds, self.bucket_counts):
            if n and running + n >= target:
                return lo + (bound - lo) * ((target - running) / n)
            running += n
            lo = bound
        return self.bounds[-1]


@dataclass
class _TimerStat:
    count: int = 0
    total_s: float = 0.0
    max_s: float = 0.0

    def record(self, seconds: float) -> None:
        self.count += 1
        self.total_s += seconds
        self.max_s = max(self.max_s, seconds)


class MetricsRegistry:
    """The tally-registry analog; one per cluster."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._counters: Dict[Tuple[str, str], int] = {}
        self._timers: Dict[Tuple[str, str], _TimerStat] = {}
        self._gauges: Dict[Tuple[str, str], float] = {}
        self._histograms: Dict[Tuple[str, str], HistogramStat] = {}

    def scope(self, name: str) -> "Scope":
        return Scope(self, name)

    # raw ops (scopes call these)

    def inc(self, scope: str, name: str, delta: int = 1) -> None:
        with self._lock:
            self._counters[(scope, name)] = (
                self._counters.get((scope, name), 0) + delta)

    def record(self, scope: str, name: str, seconds: float) -> None:
        """Timer + latency histogram: every record() feeds both, so each
        latency metric carries a full distribution."""
        with self._lock:
            self._timers.setdefault((scope, name), _TimerStat()).record(seconds)
            hist = self._histograms.get((scope, name))
            if hist is None:
                hist = self._histograms[(scope, name)] = HistogramStat()
            hist.observe(seconds)

    def observe(self, scope: str, name: str, value: float,
                buckets: Optional[Sequence[float]] = None) -> None:
        """Histogram-only observation (sizes, per-leg timings); `buckets`
        applies on first touch of the (scope, name) series."""
        with self._lock:
            hist = self._histograms.get((scope, name))
            if hist is None:
                hist = self._histograms[(scope, name)] = HistogramStat(
                    buckets if buckets is not None else DEFAULT_BUCKETS)
            hist.observe(value)

    def gauge(self, scope: str, name: str, value: float) -> None:
        with self._lock:
            self._gauges[(scope, name)] = value

    # reads

    def counter(self, scope: str, name: str) -> int:
        with self._lock:
            return self._counters.get((scope, name), 0)

    def timer(self, scope: str, name: str) -> _TimerStat:
        with self._lock:
            return self._timers.get((scope, name), _TimerStat())

    def gauge_value(self, scope: str, name: str,
                    default: float = 0.0) -> float:
        with self._lock:
            return self._gauges.get((scope, name), default)

    def histogram(self, scope: str, name: str) -> HistogramStat:
        with self._lock:
            return self._histograms.get((scope, name), HistogramStat())

    def percentiles(self, scope: str, name: str,
                    qs: Sequence[float] = (0.5, 0.95, 0.99)
                    ) -> Dict[str, float]:
        hist = self.histogram(scope, name)
        return {f"p{round(q * 100):d}": hist.percentile(q) for q in qs}

    def reset(self) -> None:
        """Drop every series (the per-test isolation seam: components hold
        the registry by reference, so clearing in place is the only reset
        that reaches them all)."""
        with self._lock:
            self._counters.clear()
            self._timers.clear()
            self._gauges.clear()
            self._histograms.clear()

    def raw_series(self) -> Tuple[Dict, Dict, Dict]:
        """Consistent point-in-time copy of every series, taken under ONE
        lock hold: (counters, gauges, histograms) where each histogram
        value is (count, total, bounds, bucket_counts-tuple). The
        time-series sampler's delta math and the prometheus renderer
        both ride this so a concurrent observe()/reset() can never yield
        a half-updated view."""
        with self._lock:
            counters = dict(self._counters)
            gauges = dict(self._gauges)
            histograms = {
                k: (h.count, h.total, h.bounds, tuple(h.bucket_counts))
                for k, h in self._histograms.items()}
        return counters, gauges, histograms

    def snapshot(self) -> Dict[str, Dict[str, object]]:
        """Full dump, grouped by scope — the structured emitter seam."""
        out: Dict[str, Dict[str, object]] = {}
        with self._lock:
            for (scope, name), v in self._counters.items():
                out.setdefault(scope, {})[name] = v
            for (scope, name), t in self._timers.items():
                out.setdefault(scope, {})[name + ".count"] = t.count
                out.setdefault(scope, {})[name + ".total_s"] = round(t.total_s, 6)
                out.setdefault(scope, {})[name + ".max_s"] = round(t.max_s, 6)
            for (scope, name), h in self._histograms.items():
                for q in (0.5, 0.95, 0.99):
                    out.setdefault(scope, {})[
                        f"{name}.p{round(q * 100):d}"] = round(
                            h.percentile(q), 6)
                if (scope, name) not in self._timers:
                    out.setdefault(scope, {})[name + ".count"] = h.count
                    out.setdefault(scope, {})[name + ".sum"] = round(h.total, 6)
            for (scope, name), v in self._gauges.items():
                out.setdefault(scope, {})[name] = v
        return out

    # -- prometheus exposition (text format 0.0.4) --------------------------

    def to_prometheus(self, prefix: str = "cadence") -> str:
        """Render every series in prometheus text format. Scope stays a
        label (the tally-tagged-scope shape), the metric name is
        sanitized into the prometheus grammar: counters get `_total`,
        histograms emit `_bucket`/`_sum`/`_count` with `le` labels.

        Renders from raw_series()'s deep copy: the old shallow copy kept
        live HistogramStat references, so a concurrent observe() could
        land between the `_bucket` walk and the `_count` line and the
        exposition's +Inf bucket would disagree with its own count."""
        counters, gauges, histograms = self.raw_series()

        def metric_name(name: str) -> str:
            return prefix + "_" + re.sub(r"[^a-zA-Z0-9_:]", "_", name)

        def fmt(value: float) -> str:
            return str(int(value)) if float(value).is_integer() else str(value)

        lines: List[str] = []
        typed: set = set()

        def header(mname: str, kind: str) -> None:
            if mname not in typed:
                typed.add(mname)
                lines.append(f"# TYPE {mname} {kind}")

        def by_family(items):
            # all samples of one metric family must be contiguous
            # (exposition-format requirement), so sort name-first
            return sorted(items, key=lambda kv: (kv[0][1], kv[0][0]))

        for (scope, name), v in by_family(counters.items()):
            mname = metric_name(name) + "_total"
            header(mname, "counter")
            lines.append(f'{mname}{{scope="{scope}"}} {v}')
        for (scope, name), v in by_family(gauges.items()):
            mname = metric_name(name)
            header(mname, "gauge")
            lines.append(f'{mname}{{scope="{scope}"}} {fmt(v)}')
        for (scope, name), (count, total, bounds, buckets) in by_family(
                histograms.items()):
            mname = metric_name(name)
            header(mname, "histogram")
            running = 0
            for bound, n in zip(bounds, buckets):
                running += n
                lines.append(f'{mname}_bucket{{scope="{scope}",'
                             f'le="{bound}"}} {running}')
            lines.append(
                f'{mname}_bucket{{scope="{scope}",le="+Inf"}} {count}')
            lines.append(
                f'{mname}_sum{{scope="{scope}"}} {fmt(round(total, 9))}')
            lines.append(f'{mname}_count{{scope="{scope}"}} {count}')
        return "\n".join(lines) + ("\n" if lines else "")


class Scope:
    """One named scope (metrics.Scope analog)."""

    def __init__(self, registry: MetricsRegistry, name: str) -> None:
        self._r = registry
        self.name = name

    def inc(self, metric: str, delta: int = 1) -> None:
        self._r.inc(self.name, metric, delta)

    def record(self, metric: str, seconds: float) -> None:
        self._r.record(self.name, metric, seconds)

    def gauge(self, metric: str, value: float) -> None:
        self._r.gauge(self.name, metric, value)

    @contextmanager
    def timed(self, metric: str = M_LATENCY):
        start = time.perf_counter()
        try:
            yield
        finally:
            self._r.record(self.name, metric, time.perf_counter() - start)


#: fallback registry for components constructed without explicit wiring
#: (a cluster passes its own; the default keeps standalone use observable)
DEFAULT_REGISTRY = MetricsRegistry()
