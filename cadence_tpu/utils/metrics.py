"""Metrics: counters / timers / gauges behind named scopes.

Reference: common/metrics (Client/Scope at metrics/interfaces.go:31,:53;
every scope and metric name enumerated in metrics/defs.go). The reference
emits through tally to m3/statsd/prometheus; here the registry keeps the
aggregates in-process (snapshot() is the emitter seam — a prometheus
text-format dump or a push client would read the same structure) so tests
and the bench can assert on what the engine actually measured.

Thread-safe; scopes are cheap handles over the shared registry.
"""
from __future__ import annotations

import threading
import time
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Dict, Optional, Tuple


# -- scope names (metrics/defs.go analog; the subset the engine emits) ------

SCOPE_HISTORY_START_WORKFLOW = "history.start-workflow-execution"
SCOPE_HISTORY_DECISION_COMPLETED = "history.respond-decision-task-completed"
SCOPE_HISTORY_ACTIVITY_RESPOND = "history.respond-activity-task"
SCOPE_HISTORY_SIGNAL = "history.signal-workflow-execution"
SCOPE_HISTORY_RESET = "history.reset-workflow-execution"
SCOPE_FRONTEND_START = "frontend.start-workflow-execution"
SCOPE_FRONTEND_SIGNAL = "frontend.signal-workflow-execution"
SCOPE_QUEUE_TRANSFER = "queue.transfer"
SCOPE_QUEUE_TIMER = "queue.timer"
SCOPE_REPLICATION = "replication.task-processor"
SCOPE_TPU_REPLAY = "tpu.replay-engine"
SCOPE_REBUILD = "tpu.device-rebuilder"
SCOPE_WORKER_RETENTION = "worker.retention"
SCOPE_WORKER_SCAVENGER = "worker.scavenger"
SCOPE_WORKER_SCANNER = "worker.scanner"

# -- metric names -----------------------------------------------------------

M_REQUESTS = "requests"
M_ERRORS = "errors"
M_LATENCY = "latency"
M_TASKS_PROCESSED = "tasks-processed"
M_TASKS_DROPPED_NOT_EXISTS = "tasks-dropped-entity-not-exists"
M_REPL_APPLIED = "replication-applied"
M_REPL_DEDUPED = "replication-deduped"
M_REPL_RESENT = "replication-resends"
M_REPL_DLQ = "replication-dlq"
M_KERNEL_LAUNCHES = "kernel-launches"
M_EVENTS_REPLAYED = "events-replayed"
M_REPLAY_THROUGHPUT = "replay-events-per-sec"
M_DEVICE_REBUILDS = "device-rebuilds"
M_ORACLE_FALLBACKS = "oracle-fallbacks"
M_FALLBACK_RATE = "fallback-rate"
M_BUFFERED_FLUSHED = "buffered-events-flushed"
M_RATE_LIMITED = "requests-rate-limited"
M_RUNS_DELETED = "runs-deleted"
M_RUNS_ARCHIVED = "runs-archived"
M_EXECUTIONS_SCANNED = "executions-scanned"
M_INVARIANT_VIOLATIONS = "invariant-violations"


@dataclass
class _TimerStat:
    count: int = 0
    total_s: float = 0.0
    max_s: float = 0.0

    def record(self, seconds: float) -> None:
        self.count += 1
        self.total_s += seconds
        self.max_s = max(self.max_s, seconds)


class MetricsRegistry:
    """The tally-registry analog; one per cluster."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._counters: Dict[Tuple[str, str], int] = {}
        self._timers: Dict[Tuple[str, str], _TimerStat] = {}
        self._gauges: Dict[Tuple[str, str], float] = {}

    def scope(self, name: str) -> "Scope":
        return Scope(self, name)

    # raw ops (scopes call these)

    def inc(self, scope: str, name: str, delta: int = 1) -> None:
        with self._lock:
            self._counters[(scope, name)] = (
                self._counters.get((scope, name), 0) + delta)

    def record(self, scope: str, name: str, seconds: float) -> None:
        with self._lock:
            self._timers.setdefault((scope, name), _TimerStat()).record(seconds)

    def gauge(self, scope: str, name: str, value: float) -> None:
        with self._lock:
            self._gauges[(scope, name)] = value

    # reads

    def counter(self, scope: str, name: str) -> int:
        with self._lock:
            return self._counters.get((scope, name), 0)

    def timer(self, scope: str, name: str) -> _TimerStat:
        with self._lock:
            return self._timers.get((scope, name), _TimerStat())

    def gauge_value(self, scope: str, name: str,
                    default: float = 0.0) -> float:
        with self._lock:
            return self._gauges.get((scope, name), default)

    def snapshot(self) -> Dict[str, Dict[str, object]]:
        """Full dump, grouped by scope — the emitter seam."""
        out: Dict[str, Dict[str, object]] = {}
        with self._lock:
            for (scope, name), v in self._counters.items():
                out.setdefault(scope, {})[name] = v
            for (scope, name), t in self._timers.items():
                out.setdefault(scope, {})[name + ".count"] = t.count
                out.setdefault(scope, {})[name + ".total_s"] = round(t.total_s, 6)
                out.setdefault(scope, {})[name + ".max_s"] = round(t.max_s, 6)
            for (scope, name), v in self._gauges.items():
                out.setdefault(scope, {})[name] = v
        return out


class Scope:
    """One named scope (metrics.Scope analog)."""

    def __init__(self, registry: MetricsRegistry, name: str) -> None:
        self._r = registry
        self.name = name

    def inc(self, metric: str, delta: int = 1) -> None:
        self._r.inc(self.name, metric, delta)

    def record(self, metric: str, seconds: float) -> None:
        self._r.record(self.name, metric, seconds)

    def gauge(self, metric: str, value: float) -> None:
        self._r.gauge(self.name, metric, value)

    @contextmanager
    def timed(self, metric: str = M_LATENCY):
        start = time.perf_counter()
        try:
            yield
        finally:
            self._r.record(self.name, metric, time.perf_counter() - start)


#: fallback registry for components constructed without explicit wiring
#: (a cluster passes its own; the default keeps standalone use observable)
DEFAULT_REGISTRY = MetricsRegistry()
