"""Tracing: spans with cross-RPC context propagation.

Reference: the Go server wires opentracing through every handler
(common/rpc sets up jaeger; service handlers carry per-request tagged
loggers). Here the same observable contract is reduced to its core: a
span records (trace_id, span_id, parent_id, operation, start, duration,
tags); the tracer keeps a thread-local active-span stack so nested calls
parent naturally; finished spans land in an in-process collector with an
export seam (CADENCE_TPU_TRACE_EXPORT=<dir> appends JSONL per process, so
multi-process traces stitch by trace_id).

Wire propagation: `inject(request)` wraps a wire-frame request as
("traced", carrier, request) when a span is active; the serving side
`extract(request)`s the carrier back into a SpanContext and parents its
server span on it — a frontend→history→matching chain therefore yields
ONE trace whether the hops are in-process calls or real sockets.
"""
from __future__ import annotations

import functools
import json
import os
import threading
import time
from collections import deque
from contextlib import contextmanager
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Tuple


def _new_id() -> str:
    return os.urandom(8).hex()


@dataclass(frozen=True)
class SpanContext:
    """The propagated identity of a span (what crosses process edges)."""

    trace_id: str
    span_id: str

    def to_carrier(self) -> Dict[str, str]:
        return {"trace_id": self.trace_id, "span_id": self.span_id}

    @staticmethod
    def from_carrier(carrier: Any) -> Optional["SpanContext"]:
        """Tolerant decode of a wire carrier (untrusted shape: the wire is
        an internal transport, but a malformed envelope must not take the
        handler down)."""
        if not isinstance(carrier, dict):
            return None
        trace_id, span_id = carrier.get("trace_id"), carrier.get("span_id")
        if not trace_id or not span_id:
            return None
        return SpanContext(str(trace_id)[:64], str(span_id)[:64])


@dataclass
class Span:
    operation: str
    context: SpanContext
    parent_id: Optional[str] = None
    start_time: float = 0.0  # wall clock, seconds since epoch
    duration_s: float = 0.0
    tags: Dict[str, Any] = field(default_factory=dict)
    finished: bool = False

    def set_tag(self, key: str, value: Any) -> None:
        self.tags[key] = value

    def to_dict(self) -> Dict[str, Any]:
        return {
            "trace_id": self.context.trace_id,
            "span_id": self.context.span_id,
            "parent_id": self.parent_id,
            "operation": self.operation,
            "start_time": round(self.start_time, 6),
            "duration_s": round(self.duration_s, 6),
            "tags": self.tags,
            "pid": os.getpid(),
        }


def _file_exporter(directory: str) -> Callable[[Dict[str, Any]], None]:
    """JSONL exporter: one spans-<pid>.jsonl per process, append-per-span —
    the multi-process stitching seam (a real deployment would point the
    same seam at an OTLP/jaeger forwarder)."""
    os.makedirs(directory, exist_ok=True)
    path = os.path.join(directory, f"spans-{os.getpid()}.jsonl")
    lock = threading.Lock()

    def export(span_dict: Dict[str, Any]) -> None:
        line = json.dumps(span_dict, default=str)
        with lock:
            with open(path, "a", encoding="utf-8") as fh:
                fh.write(line + "\n")

    return export


class Tracer:
    """Span factory + in-process collector (thread-safe; the active-span
    stack is thread-local, so concurrent requests never cross-parent)."""

    def __init__(self, max_spans: int = 10_000) -> None:
        self._lock = threading.Lock()
        #: ring buffer: a long-running host keeps the NEWEST spans, so
        #: /traces stays useful after the cap fills (oldest evicted)
        self._finished: deque = deque(maxlen=max_spans)
        self._evicted = 0
        self.max_spans = max_spans
        self._local = threading.local()
        #: export seam: called with span.to_dict() on every finish
        self.exporter: Optional[Callable[[Dict[str, Any]], None]] = None
        export_dir = os.environ.get("CADENCE_TPU_TRACE_EXPORT")
        if export_dir:
            self.exporter = _file_exporter(export_dir)

    # -- active-span bookkeeping (per thread) ------------------------------

    def _stack(self) -> List[SpanContext]:
        stack = getattr(self._local, "stack", None)
        if stack is None:
            stack = self._local.stack = []
        return stack

    def active_context(self) -> Optional[SpanContext]:
        stack = self._stack()
        return stack[-1] if stack else None

    # -- span lifecycle ----------------------------------------------------

    @contextmanager
    def start_span(self, operation: str,
                   child_of: Optional[SpanContext] = None,
                   tags: Optional[Dict[str, Any]] = None):
        """Open a span: explicit `child_of` (an extracted remote context)
        wins; otherwise the thread's active span is the parent; otherwise
        this span roots a new trace."""
        parent = child_of if child_of is not None else self.active_context()
        ctx = SpanContext(
            trace_id=parent.trace_id if parent else _new_id(),
            span_id=_new_id())
        span = Span(operation=operation, context=ctx,
                    parent_id=parent.span_id if parent else None,
                    start_time=time.time(), tags=dict(tags or {}))
        stack = self._stack()
        stack.append(ctx)
        t0 = time.perf_counter()
        try:
            yield span
        except BaseException as exc:
            span.set_tag("error", type(exc).__name__)
            raise
        finally:
            stack.pop()
            span.duration_s = time.perf_counter() - t0
            span.finished = True
            self._collect(span)

    def _collect(self, span: Span) -> None:
        with self._lock:
            if len(self._finished) == self.max_spans:
                self._evicted += 1
            self._finished.append(span)
        if self.exporter is not None:
            try:
                self.exporter(span.to_dict())
            except Exception:
                pass  # export failure must never fail the traced operation

    # -- reads -------------------------------------------------------------

    def finished_spans(self) -> List[Span]:
        with self._lock:
            return list(self._finished)

    def traces(self) -> Dict[str, List[Span]]:
        """Finished spans grouped by trace_id, each trace start-ordered."""
        out: Dict[str, List[Span]] = {}
        for span in self.finished_spans():
            out.setdefault(span.context.trace_id, []).append(span)
        for spans in out.values():
            spans.sort(key=lambda s: s.start_time)
        return out

    def reset(self) -> None:
        with self._lock:
            self._finished.clear()
            self._evicted = 0


# -- wire-envelope propagation ----------------------------------------------

def inject(request: Any, tracer: Optional["Tracer"] = None) -> Any:
    """Wrap a wire request with the calling thread's active trace context:
    ("traced", carrier, request). Pass-through when no span is active, so
    untraced traffic keeps the bare envelope."""
    ctx = (tracer or DEFAULT_TRACER).active_context()
    if ctx is None:
        return request
    return ("traced", ctx.to_carrier(), request)


def extract(request: Any) -> Tuple[Optional[SpanContext], Any]:
    """Unwrap a possibly-traced wire request → (context or None, inner)."""
    if (isinstance(request, tuple) and len(request) == 3
            and request[0] == "traced"):
        return SpanContext.from_carrier(request[1]), request[2]
    return None, request


def traced(operation: str):
    """Method decorator: span + latency histogram around a service method.

    The span parents on the thread's active span (or an extracted remote
    context activated by the RPC handler); when the instance carries a
    `metrics` registry, the call's latency is recorded under
    scope=`operation` — one name shared by the trace and the metric, the
    reference's scope-per-API convention (metrics/defs.go)."""
    def decorate(fn):
        @functools.wraps(fn)
        def wrapper(self, *args, **kwargs):
            tracer = getattr(self, "tracer", None) or DEFAULT_TRACER
            registry = getattr(self, "metrics", None)
            t0 = time.perf_counter()
            with tracer.start_span(operation):
                try:
                    return fn(self, *args, **kwargs)
                finally:
                    if registry is not None:
                        registry.record(operation, "latency",
                                        time.perf_counter() - t0)
        return wrapper
    return decorate


#: fallback tracer for components constructed without explicit wiring
#: (mirrors metrics.DEFAULT_REGISTRY; tests reset it per test)
DEFAULT_TRACER = Tracer()
