"""Mockable time source.

Reference analog: common/clock/time_source.go — the engine never reads the
wall clock directly, so tests and deterministic replays can drive time.
Times are unix nanoseconds (int), matching event timestamps.
"""
from __future__ import annotations

import time


class TimeSource:
    def now(self) -> int:
        raise NotImplementedError


class RealTimeSource(TimeSource):
    def now(self) -> int:
        return time.time_ns()


class ManualTimeSource(TimeSource):
    """Test clock advanced explicitly (clock.NewMockedTimeSource analog)."""

    def __init__(self, start: int = 1_700_000_000_000_000_000) -> None:
        self._now = start

    def now(self) -> int:
        return self._now

    def advance(self, nanos: int) -> int:
        self._now += nanos
        return self._now

    def advance_to(self, ts: int) -> None:
        if ts > self._now:
            self._now = ts
