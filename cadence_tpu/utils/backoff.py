"""Retry backoff + cron schedule math.

Reference:
- activity retry interval: service/history/execution/retry.go:31-80
  (getBackoffInterval — exponential with cap, total-attempt limit,
  expiration cut-off, non-retriable reasons);
- client retry policies:   common/backoff/retrypolicy.go
  (ExponentialRetryPolicy — exponential with jitter, expiration
  interval, attempt cap; wrapped around every service client);
- cron continuation:      common/backoff/cron.go:48
  (GetBackoffForNextSchedule — next standard-cron fire time at or after
  the close time, measured from the close time, rounded up to seconds).

The cron parser implements standard 5-field cron (minute hour day-of-month
month day-of-week) with *, */step, ranges, lists — the subset
robfig/cron.ParseStandard accepts minus macros and time zones.
"""
from __future__ import annotations

import math
import random
from datetime import datetime, timedelta, timezone
from typing import List, Optional, Sequence

NANOS_PER_SECOND = 1_000_000_000

#: sentinel: no retry / no next cron run (backoff.NoBackoff)
NO_BACKOFF = -1


def get_backoff_interval(now_nanos: int, expiration_time_nanos: int,
                         curr_attempt: int, max_attempts: int,
                         init_interval_seconds: int,
                         max_interval_seconds: int,
                         backoff_coefficient: float,
                         failure_reason: str,
                         non_retriable_errors: Sequence[str]
                         ) -> int:
    """Next retry interval in NANOS, or NO_BACKOFF (retry.go:31-80)."""
    if max_attempts == 0 and expiration_time_nanos == 0:
        return NO_BACKOFF
    if max_attempts > 0 and curr_attempt >= max_attempts - 1:
        # currAttempt starts from 0; MaximumAttempts counts the initial try
        return NO_BACKOFF

    try:
        next_interval = int(float(init_interval_seconds)
                            * math.pow(backoff_coefficient, float(curr_attempt)))
    except OverflowError:
        next_interval = 0
    if next_interval <= 0:
        # math.Pow() could overflow
        if max_interval_seconds > 0:
            next_interval = max_interval_seconds
        else:
            return NO_BACKOFF
    if max_interval_seconds > 0 and next_interval > max_interval_seconds:
        next_interval = max_interval_seconds

    backoff_nanos = next_interval * NANOS_PER_SECOND
    if expiration_time_nanos != 0 and now_nanos + backoff_nanos > expiration_time_nanos:
        return NO_BACKOFF
    if failure_reason in non_retriable_errors:
        return NO_BACKOFF
    return backoff_nanos


# ---------------------------------------------------------------------------
# Client retry policy (common/backoff/retrypolicy.go semantics)
# ---------------------------------------------------------------------------


class RetryPolicy:
    """Exponential backoff with FULL jitter for cross-process clients.

    `get_backoff_interval` above is the ACTIVITY retry policy (persisted,
    deterministic, second-granularity); this is the in-memory CLIENT
    policy the reference wraps every service/persistence client in
    (common/backoff ExponentialRetryPolicy + ConcurrentRetrier):

    - interval_i = init * coefficient^i, capped at max_interval;
    - full jitter: the actual sleep is uniform in [0, interval_i]
      (de-synchronizes retry storms across callers);
    - stop when attempts exceed max_attempts, or when the NEXT sleep
      would land past expiration_s of total elapsed time — the same
      cut-off shape as get_backoff_interval's expiration check;
    - NO_BACKOFF (-1) signals "stop retrying".

    Seedable for reproducible tests; thread-safe (the RNG is the only
    shared state and random.Random is internally locked).
    """

    def __init__(self, init_interval_s: float = 0.05,
                 max_interval_s: float = 2.0,
                 backoff_coefficient: float = 2.0,
                 max_attempts: int = 5,
                 expiration_s: float = 0.0,
                 seed: Optional[int] = None) -> None:
        if init_interval_s <= 0:
            raise ValueError("init_interval_s must be > 0")
        if backoff_coefficient < 1.0:
            raise ValueError("backoff_coefficient must be >= 1.0")
        self.init_interval_s = init_interval_s
        self.max_interval_s = max_interval_s
        self.backoff_coefficient = backoff_coefficient
        self.max_attempts = max_attempts
        self.expiration_s = expiration_s
        self._rng = random.Random(seed)

    def next_interval(self, attempt: int, elapsed_s: float) -> float:
        """Jittered sleep before retry number `attempt` (0-based count of
        FAILED tries so far), or NO_BACKOFF to stop.

        max_attempts counts the initial try (retry.go:38 semantics): a
        policy with max_attempts=3 sleeps at most twice."""
        if self.max_attempts > 0 and attempt >= self.max_attempts - 1:
            return NO_BACKOFF
        try:
            interval = (self.init_interval_s
                        * math.pow(self.backoff_coefficient, float(attempt)))
        except OverflowError:
            interval = 0.0
        if interval <= 0 or not math.isfinite(interval):
            # pow overflow: fall to the cap, or stop if there is none
            if self.max_interval_s > 0:
                interval = self.max_interval_s
            else:
                return NO_BACKOFF
        if self.max_interval_s > 0:
            interval = min(interval, self.max_interval_s)
        if (self.expiration_s > 0
                and elapsed_s + interval > self.expiration_s):
            return NO_BACKOFF
        return self._rng.uniform(0.0, interval)


# ---------------------------------------------------------------------------
# Standard cron (minute-granularity), cron.go:48 semantics
# ---------------------------------------------------------------------------


class CronField:
    """One parsed cron field: the set of allowed values."""

    __slots__ = ("allowed",)

    def __init__(self, spec: str, lo: int, hi: int) -> None:
        allowed = set()
        for part in spec.split(","):
            step = 1
            has_step = False
            if "/" in part:
                part, step_s = part.split("/", 1)
                step = int(step_s)
                has_step = True
                if step <= 0:
                    raise ValueError(f"bad cron step {step_s}")
            if part == "*" or part == "?":
                lo_p, hi_p = lo, hi
            elif "-" in part:
                a, b = part.split("-", 1)
                lo_p, hi_p = int(a), int(b)
            else:
                lo_p = int(part)
                # "N/step" means from N to the field maximum by step
                hi_p = hi if has_step else lo_p
            if lo_p < lo or hi_p > hi or lo_p > hi_p:
                raise ValueError(f"cron value out of range: {part} not in [{lo},{hi}]")
            allowed.update(range(lo_p, hi_p + 1, step))
        self.allowed = frozenset(allowed)

    def match(self, value: int) -> bool:
        return value in self.allowed


def _has_star_bit(spec: str) -> bool:
    """True when any comma part's range (before an optional '/step') is
    '*'/'?' — robfig/cron's starBit, OR'd across parts."""
    return any(part.split("/", 1)[0] in ("*", "?") for part in spec.split(","))


class CronSchedule:
    """Parsed 5-field standard cron expression."""

    def __init__(self, spec: str) -> None:
        fields = spec.split()
        if len(fields) != 5:
            raise ValueError(f"cron spec needs 5 fields, got {len(fields)}: {spec!r}")
        self.minute = CronField(fields[0], 0, 59)
        self.hour = CronField(fields[1], 0, 23)
        self.dom = CronField(fields[2], 1, 31)
        self.month = CronField(fields[3], 1, 12)
        # cron day-of-week: 0-6, 0 == Sunday (7 accepted as a Sunday alias)
        self.dow = CronField(fields[4], 0, 7)
        #: dom/dow OR-semantics apply when both are restricted (std cron).
        #: robfig/cron sets the star bit for any part whose base range is
        #: '*' or '?' — including '*/n' — so those count as unrestricted too
        self.dom_star = _has_star_bit(fields[2])
        self.dow_star = _has_star_bit(fields[4])

    def _day_match(self, t: datetime) -> bool:
        dom_ok = self.dom.match(t.day)
        cron_dow = (t.weekday() + 1) % 7  # python Mon=0 → cron Sun=0
        dow_ok = self.dow.match(cron_dow) or (cron_dow == 0 and self.dow.match(7))
        # robfig/cron v1.2.0 dayMatches (the version the reference pins):
        # AND the two day fields when either carries the star bit — which
        # v1.2.0 keeps for '*/n' — OR them when both are restricted.
        # (cron v3 clears the bit for step>1; not the pinned behavior.)
        if self.dom_star or self.dow_star:
            return dom_ok and dow_ok
        return dom_ok or dow_ok

    def next_after(self, t: datetime) -> Optional[datetime]:
        """Earliest fire time strictly after t (cron.Schedule.Next)."""
        cur = (t.replace(second=0, microsecond=0) + timedelta(minutes=1))
        limit = t + timedelta(days=4 * 366)  # robfig's ~4-year give-up bound
        while cur <= limit:
            if not self.month.match(cur.month):
                cur = (cur.replace(day=1, hour=0, minute=0)
                       + timedelta(days=32)).replace(day=1)
                continue
            if not self._day_match(cur):
                cur = cur.replace(hour=0, minute=0) + timedelta(days=1)
                continue
            if not self.hour.match(cur.hour):
                cur = cur.replace(minute=0) + timedelta(hours=1)
                continue
            if not self.minute.match(cur.minute):
                cur += timedelta(minutes=1)
                continue
            return cur
        return None


def validate_cron_schedule(spec: str) -> bool:
    """ValidateSchedule analog (cron.go:37): empty means "no cron"."""
    if spec == "":
        return True
    try:
        CronSchedule(spec)
        return True
    except (ValueError, IndexError):
        return False


def get_backoff_for_next_schedule(cron_schedule: str, start_nanos: int,
                                  close_nanos: int) -> int:
    """Seconds until the next cron run measured from close time, or
    NO_BACKOFF (cron.go:48 GetBackoffForNextScheduleInSeconds)."""
    if not cron_schedule:
        return NO_BACKOFF
    try:
        schedule = CronSchedule(cron_schedule)
    except (ValueError, IndexError):
        return NO_BACKOFF
    start = datetime.fromtimestamp(start_nanos / NANOS_PER_SECOND, tz=timezone.utc)
    close = datetime.fromtimestamp(close_nanos / NANOS_PER_SECOND, tz=timezone.utc)
    nxt = schedule.next_after(start)
    while nxt is not None and nxt < close:
        nxt = schedule.next_after(nxt)
    if nxt is None:
        return NO_BACKOFF
    backoff_seconds = (nxt - close).total_seconds()
    return int(math.ceil(backoff_seconds))
