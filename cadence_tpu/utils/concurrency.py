"""Host pack-thread resolution: ONE knob for every host-side packer.

Before this module each pack stage picked its own default thread count —
the C++ blob packer capped at cpu_count, `ops/wirec.pack_wirec` defaulted
to serial, the feeder divided cores by pipeline depth, bench took raw
cpu_count — so tuning host packing meant chasing four call sites. Every
stage now resolves through `pack_threads`: explicit argument first, then
the `CADENCE_TPU_PACK_THREADS` env knob, then cpu_count. Callers that
fan out over a bounded work list pass `cap` so a 4-blob chunk never
spawns 64 threads.
"""
from __future__ import annotations

import os
from typing import Optional

#: the one host-packing thread knob (native packer, wirec encoder,
#: feeder, executor, bench all resolve through it)
PACK_THREADS_ENV = "CADENCE_TPU_PACK_THREADS"


def pack_threads(explicit: Optional[int] = None,
                 cap: Optional[int] = None) -> int:
    """Resolve the pack-thread count: explicit arg > env > cpu_count,
    clamped to [1, cap]."""
    if explicit is not None:
        n = int(explicit)
    else:
        env = os.environ.get(PACK_THREADS_ENV, "")
        n = int(env) if env else (os.cpu_count() or 1)
    if cap is not None:
        n = min(n, int(cap))
    return max(1, n)
