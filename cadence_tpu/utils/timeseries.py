"""Time-series ring buffers over a MetricsRegistry.

/metrics exposes instantaneous cumulative counters: good for a scraper
with its own TSDB, useless for the questions this framework's operators
actually ask ("what was the commit rate the last minute", "which
pipeline leg is binding RIGHT NOW", "is the serving queue saturating").
This sampler closes that gap in-process: every period it snapshots the
registry (counters, gauges, histogram count/total — one consistent
raw_series() read) and folds the deltas into a fixed-width window ring
(default 1s × 600), deriving

  rates            counter + histogram-count deltas / window seconds,
                   tolerant of in-place registry resets (a cumulative
                   value moving BACKWARD reads as a fresh epoch: the new
                   cumulative IS the delta, never a negative rate)
  legs             per-window busy seconds of the replay-profiler legs
                   (pack / pack-queue-wait / h2d / kernel / readback /
                   fallback / serving, summed over the replay + rebuild
                   scopes) — `binding_resource` is the leg with the most
                   busy time, "idle" when none ran
  saturation       serving queue depth vs capacity, executor busy gauge,
                   and the pack-queue-wait share of the window's leg time
  utilization      total leg-busy seconds / window seconds, clipped [0,1]

Windows serve as JSON at GET /timeseries (utils/scrape.py) — the signal
`admin top` aggregates fleet-wide and the autoscaler (ROADMAP item 5)
will consume. Histogram BUCKET deltas are retained only for series
registered via track_histogram() (the SLO burn-rate inputs, loadgen/
slo.py) so the ring's footprint stays bounded.

Knobs: CADENCE_TPU_TIMESERIES=0 disables the ServiceHost sampler thread,
CADENCE_TPU_TS_PERIOD_S / CADENCE_TPU_TS_RETENTION size the ring.
"""
from __future__ import annotations

import bisect
import os
import threading
import time
import weakref
from collections import deque
from typing import Callable, Dict, List, Optional, Tuple

from . import metrics as m

ENV_ENABLED = "CADENCE_TPU_TIMESERIES"
ENV_PERIOD = "CADENCE_TPU_TS_PERIOD_S"
ENV_RETENTION = "CADENCE_TPU_TS_RETENTION"

#: the profiler-leg scopes whose histogram-total deltas decompose a
#: window into busy seconds per pipeline leg (utils/profiler.LEGS order)
LEG_SCOPES = (m.SCOPE_TPU_REPLAY, m.SCOPE_REBUILD)
LEGS = (m.M_PROFILE_PACK, m.M_PROFILE_PACK_WAIT, m.M_PROFILE_H2D,
        m.M_PROFILE_KERNEL, m.M_PROFILE_READBACK, m.M_PROFILE_FALLBACK,
        m.M_PROFILE_SERVING)


def enabled() -> bool:
    return os.environ.get(ENV_ENABLED, "1") not in ("0", "false", "no")


def default_period_s() -> float:
    try:
        return max(0.05, float(os.environ.get(ENV_PERIOD, "1.0")))
    except ValueError:
        return 1.0


def default_retention() -> int:
    try:
        return max(2, int(os.environ.get(ENV_RETENTION, "600")))
    except ValueError:
        return 600


class Window:
    """One fixed-width sample window (all derived values, no cumulative
    state): `t` is the window END timestamp."""

    __slots__ = ("t", "dur_s", "deltas", "rates", "gauges", "hist_deltas",
                 "bucket_deltas", "legs", "binding_resource", "saturation",
                 "utilization")

    def __init__(self, t: float, dur_s: float) -> None:
        self.t = t
        self.dur_s = dur_s
        #: (scope, name) → counter delta (nonzero only)
        self.deltas: Dict[Tuple[str, str], float] = {}
        #: (scope, name) → delta / dur_s
        self.rates: Dict[Tuple[str, str], float] = {}
        #: (scope, name) → instantaneous gauge value at window end
        self.gauges: Dict[Tuple[str, str], float] = {}
        #: (scope, name) → (count delta, total delta) for histograms
        self.hist_deltas: Dict[Tuple[str, str], Tuple[int, float]] = {}
        #: (scope, name) → (bounds, per-bucket count deltas) — tracked
        #: series only (the burn-rate inputs)
        self.bucket_deltas: Dict[Tuple[str, str],
                                 Tuple[Tuple[float, ...], Tuple[int, ...]]] = {}
        self.legs: Dict[str, float] = {}
        self.binding_resource = "idle"
        self.saturation: Dict[str, float] = {}
        self.utilization = 0.0

    def to_doc(self) -> Dict[str, object]:
        return {
            "t": round(self.t, 6),
            "dur_s": round(self.dur_s, 6),
            "rates": {f"{s}/{n}": round(r, 6)
                      for (s, n), r in sorted(self.rates.items())},
            "gauges": {f"{s}/{n}": v
                       for (s, n), v in sorted(self.gauges.items())},
            "legs": {leg: round(sec, 6)
                     for leg, sec in sorted(self.legs.items())},
            "binding_resource": self.binding_resource,
            "saturation": {k: round(v, 6)
                           for k, v in sorted(self.saturation.items())},
            "utilization": round(self.utilization, 6),
        }


class TimeSeriesSampler:
    """Ring-buffer sampler over one registry. Thread-run in production
    (start()/stop()); tests drive sample_once(now=...) with explicit
    timestamps for deterministic window math."""

    def __init__(self, registry: Optional[m.MetricsRegistry] = None,
                 period_s: Optional[float] = None,
                 retention: Optional[int] = None) -> None:
        self.registry = (registry if registry is not None
                         else m.DEFAULT_REGISTRY)
        self.period_s = (period_s if period_s is not None
                         else default_period_s())
        self.retention = (retention if retention is not None
                          else default_retention())
        self._lock = threading.Lock()
        self._windows: deque = deque(maxlen=self.retention)
        #: previous tick's cumulative state: (t, counters, hist
        #: {key: (count, total)}, tracked buckets {key: (bounds, counts)})
        self._prev: Optional[tuple] = None
        self._tracked: set = set()
        #: (scope, name) of a queue-depth gauge → capacity (int or
        #: callable); drives the queue-fill saturation derivation
        self._capacities: Dict[Tuple[str, str], object] = {}
        #: post-sample hook (window) — the burn-rate evaluator's seat
        self.on_sample: Optional[Callable[[Window], None]] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.samples_total = 0
        _LIVE.add(self)

    # -- configuration -----------------------------------------------------

    def track_histogram(self, scope: str, name: str) -> None:
        """Retain per-window BUCKET deltas for (scope, name) — the SLO
        burn-rate inputs. Unregistered histograms keep only count/total
        deltas (the ring must stay bounded)."""
        with self._lock:
            self._tracked.add((scope, name))

    def set_capacity(self, scope: str, name: str, capacity) -> None:
        """Declare a gauge as a queue depth with `capacity` (int or
        zero-arg callable) so windows derive its fill fraction."""
        with self._lock:
            self._capacities[(scope, name)] = capacity

    # -- sampling ----------------------------------------------------------

    def sample_once(self, now: Optional[float] = None) -> Optional[Window]:
        """Take one sample. The FIRST call only anchors the cumulative
        baseline (no window yet — a window is a delta between two
        ticks); every later call appends one window and returns it."""
        now = time.time() if now is None else now
        counters, gauges, hists = self.registry.raw_series()
        with self._lock:
            tracked = set(self._tracked)
            prev = self._prev
        hist_state = {k: (h[0], h[1]) for k, h in hists.items()}
        buckets = {k: (hists[k][2], hists[k][3])
                   for k in tracked if k in hists}
        if prev is None:
            with self._lock:
                self._prev = (now, counters, hist_state, buckets)
                self.samples_total += 1
            self._publish()
            return None
        prev_t, prev_counters, prev_hists, prev_buckets = prev
        dur = max(now - prev_t, 1e-9)
        window = Window(t=now, dur_s=dur)

        for key, cum in counters.items():
            before = prev_counters.get(key, 0)
            # in-place reset tolerance: the registry's reset() clears
            # cumulative state under components that keep counting — a
            # backward move means a fresh epoch, so the new cumulative
            # IS this window's delta (never negative)
            delta = cum - before if cum >= before else cum
            if delta:
                window.deltas[key] = delta
                window.rates[key] = delta / dur
        for key, (count, total) in hist_state.items():
            pc, pt = prev_hists.get(key, (0, 0.0))
            dcount = count - pc if count >= pc else count
            dtotal = total - pt if count >= pc else total
            if dcount:
                window.hist_deltas[key] = (dcount, dtotal)
                window.rates[key] = dcount / dur
        for key, (bounds, bucket_counts) in buckets.items():
            prev_b = prev_buckets.get(key)
            if prev_b is None or prev_b[0] != bounds or any(
                    c < p for c, p in zip(bucket_counts, prev_b[1])):
                deltas = bucket_counts  # fresh epoch / bucket change
            else:
                deltas = tuple(c - p for c, p in
                               zip(bucket_counts, prev_b[1]))
            if any(deltas):
                window.bucket_deltas[key] = (bounds, deltas)
        window.gauges = dict(gauges)

        self._derive(window)
        with self._lock:
            self._prev = (now, counters, hist_state, buckets)
            self._windows.append(window)
            self.samples_total += 1
            capacities = dict(self._capacities)
        self._saturation(window, capacities)
        self._publish(window)
        hook = self.on_sample
        if hook is not None:
            try:
                hook(window)
            except Exception:
                pass  # a broken evaluator must not stop the sampler
        return window

    def _derive(self, window: Window) -> None:
        """Leg decomposition + binding resource + utilization."""
        for leg in LEGS:
            busy = 0.0
            for scope in LEG_SCOPES:
                busy += window.hist_deltas.get((scope, leg), (0, 0.0))[1]
            if busy > 0.0:
                window.legs[leg] = busy
        total_busy = sum(window.legs.values())
        if total_busy > 1e-9:
            window.binding_resource = max(window.legs.items(),
                                          key=lambda kv: kv[1])[0]
        window.utilization = min(1.0, total_busy / window.dur_s)

    def _saturation(self, window: Window, capacities: Dict) -> None:
        depth = window.gauges.get(
            (m.SCOPE_TPU_SERVING, m.M_SERVING_QUEUE_DEPTH), 0.0)
        window.saturation["queue_depth"] = depth
        cap = capacities.get((m.SCOPE_TPU_SERVING, m.M_SERVING_QUEUE_DEPTH))
        if cap is not None:
            cap_v = float(cap() if callable(cap) else cap)
            if cap_v > 0:
                window.saturation["queue_capacity"] = cap_v
                window.saturation["queue_fill"] = min(1.0, depth / cap_v)
        window.saturation["device_busy"] = window.gauges.get(
            (m.SCOPE_TPU_EXECUTOR, m.M_EXEC_DEVICE_BUSY), 0.0)
        total_busy = sum(window.legs.values())
        if total_busy > 1e-9:
            window.saturation["queue_wait_share"] = (
                window.legs.get(m.M_PROFILE_PACK_WAIT, 0.0) / total_busy)

    def _publish(self, window: Optional[Window] = None) -> None:
        """Mirror the sampler's own health onto the registry (scraped as
        timeseries/* so a flat /metrics scrape sees the ring is live)."""
        try:
            self.registry.gauge(m.SCOPE_TIMESERIES, "windows",
                                float(len(self._windows)))
            self.registry.gauge(m.SCOPE_TIMESERIES, "samples",
                                float(self.samples_total))
            if window is not None:
                self.registry.gauge(m.SCOPE_TIMESERIES, "utilization",
                                    window.utilization)
        except Exception:
            pass

    # -- reads -------------------------------------------------------------

    def windows(self, horizon_s: Optional[float] = None,
                now: Optional[float] = None) -> List[Window]:
        with self._lock:
            out = list(self._windows)
        if horizon_s is not None:
            now = (now if now is not None
                   else (out[-1].t if out else time.time()))
            out = [w for w in out if w.t > now - horizon_s + 1e-9]
        return out

    def fraction_over(self, scope: str, name: str, threshold: float,
                      horizon_s: float,
                      now: Optional[float] = None) -> Tuple[int, int]:
        """(observations over `threshold`, total observations) for one
        TRACKED histogram over the trailing horizon — the burn-rate
        numerator/denominator. Bucket-granular: an observation counts as
        over iff its bucket's upper bound exceeds the threshold."""
        over = total = 0
        for window in self.windows(horizon_s, now=now):
            entry = window.bucket_deltas.get((scope, name))
            if entry is None:
                continue
            bounds, deltas = entry
            total += sum(deltas)
            # buckets at index >= cut have upper bound > threshold
            # (bucket i counts values <= bounds[i]; last slot is +Inf)
            cut = bisect.bisect_left(bounds, threshold)
            if cut < len(bounds) and bounds[cut] == threshold:
                cut += 1  # a bucket bounded exactly AT the ceiling is ok
            over += sum(deltas[cut:])
        return over, total

    def doc(self, last_n: Optional[int] = 120) -> Dict[str, object]:
        """The GET /timeseries body: config + the trailing windows."""
        windows = self.windows()
        if last_n is not None:
            windows = windows[-last_n:]
        return {
            "period_s": self.period_s,
            "retention": self.retention,
            "samples": self.samples_total,
            "windows": [w.to_doc() for w in windows],
        }

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "TimeSeriesSampler":
        if self._thread is not None and self._thread.is_alive():
            return self
        self._stop.clear()
        self.sample_once()  # anchor the baseline before the first period
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="cadence-timeseries")
        self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.wait(self.period_s):
            try:
                self.sample_once()
            except Exception:
                continue  # registry contention etc.; next period retries

    def stop(self) -> None:
        self._stop.set()
        thread = self._thread
        if thread is not None:
            thread.join(timeout=2.0)
            self._thread = None

    def reset(self) -> None:
        self.stop()
        with self._lock:
            self._windows.clear()
            self._prev = None
            self.samples_total = 0


#: every live sampler (mirrors serving.reset_all's WeakSet contract) so
#: conftest can stop leaked sampler threads between tests
_LIVE: "weakref.WeakSet[TimeSeriesSampler]" = weakref.WeakSet()


def reset_all() -> None:
    for sampler in list(_LIVE):
        try:
            sampler.reset()
        except Exception:
            pass
