"""Host-runtime attribution: a sampling profiler over the interpreter.

ROADMAP item 1 claims the feeder/serving/cluster tiers are "starved by
one interpreter" — this module is the evidence base. A daemon thread
periodically walks sys._current_frames() and, per live thread,

  - attributes the WALL sample to a named subsystem (feeder pack pool,
    serving drain, visibility appender, migration hydrator, RPC
    dispatch, ... — the prefix table below; every framework thread is
    named for exactly this reason),
  - reads the thread's CPU time (per-thread CPU clock:
    /proc/self/task/<tid>/stat on Linux, pthread_getcpuclockid +
    time.clock_gettime(CLOCK_THREAD_CPUTIME_ID-equivalent) via ctypes
    elsewhere; wall-vs-process-cpu delta as the last resort) so wall
    share and CPU share can disagree — the disagreement IS the GIL story,
  - classifies the top of stack as WAITING (blocking call: lock/socket/
    sleep/queue) or RUNNABLE, and counts runnable-but-not-on-cpu samples:
    their share of runnable samples is the GIL-contention estimate,
  - keeps a top-of-stack table per subsystem (function file:line counts)
    — the `admin hostprof` rollup's "where does the time actually go".

Results land as host.prof/* gauges on the registry (scraped flat) and as
the structured rollup() doc (GET /hostprof, `admin hostprof`).

Knobs: CADENCE_TPU_HOSTPROF=0 disables the ServiceHost profiler thread,
CADENCE_TPU_HOSTPROF_PERIOD_MS sets the sampling period (default 20ms).
"""
from __future__ import annotations

import os
import sys
import threading
import time
import weakref
from collections import Counter
from typing import Dict, List, Optional

from . import metrics as m

ENV_ENABLED = "CADENCE_TPU_HOSTPROF"
ENV_PERIOD_MS = "CADENCE_TPU_HOSTPROF_PERIOD_MS"


def enabled() -> bool:
    return os.environ.get(ENV_ENABLED, "1") not in ("0", "false", "no")


def default_period_s() -> float:
    try:
        return max(0.001,
                   float(os.environ.get(ENV_PERIOD_MS, "20")) / 1000.0)
    except ValueError:
        return 0.02


#: thread-name prefix → subsystem bucket. Order matters (first match
#: wins); anything unmatched lands in "other" and counts AGAINST the
#: attributed share — naming a new framework thread is how it earns a row
SUBSYSTEM_PREFIXES = (
    ("cadence-pack", "feeder-pack"),
    ("wirec-pack", "feeder-pack"),
    ("cadence-serving-drain", "serving-drain"),
    ("cadence-serving-warm", "serving-warm"),
    ("visibility-appender", "visibility-appender"),
    ("cadence-migration", "migration-hydrator"),
    ("cadence-rpc", "rpc-dispatch"),
    ("cadence-store", "rpc-dispatch"),
    ("cadence-scrape", "scrape"),
    ("cadence-membership", "membership"),
    ("cadence-queue-pump", "queue-pump"),
    ("cadence-task-worker", "task-workers"),
    ("cadence-timeseries", "telemetry"),
    ("cadence-hostprof", "telemetry"),
    ("MainThread", "main"),
)


def subsystem_for(thread_name: str) -> str:
    for prefix, subsystem in SUBSYSTEM_PREFIXES:
        if thread_name.startswith(prefix):
            return subsystem
    return "other"


#: top-of-stack function names that mean "parked, not runnable" — a
#: blocked thread is not evidence of GIL contention
_WAIT_FUNCTIONS = frozenset((
    "wait", "wait_for", "_wait_for_tstate_lock", "acquire", "sleep",
    "select", "poll", "epoll", "accept", "recv", "recv_into", "recvfrom",
    "read", "readinto", "readline", "get", "join", "getaddrinfo",
    "settimeout", "flush", "fsync",
))


def _thread_cpu_s(thread: threading.Thread) -> Optional[float]:
    """Per-thread CPU seconds. Linux: /proc/self/task/<tid>/stat (utime +
    stime ticks — the same clock CLOCK_THREAD_CPUTIME_ID reads, without
    the pthread_getcpuclockid dead-thread hazard). Elsewhere: the ctypes
    pthread path. None when neither works (caller falls back to the
    wall-vs-process-cpu estimate)."""
    tid = getattr(thread, "native_id", None)
    if tid is not None:
        try:
            with open(f"/proc/self/task/{tid}/stat", "rb") as fh:
                stat = fh.read().decode("ascii", "replace")
            # field 2 (comm) may contain spaces; parse past the last ')'
            fields = stat[stat.rfind(")") + 2:].split()
            utime, stime = int(fields[11]), int(fields[12])
            return (utime + stime) / _clock_ticks()
        except (OSError, ValueError, IndexError):
            pass
    return _pthread_cpu_s(thread)


_TICKS: Optional[float] = None


def _clock_ticks() -> float:
    global _TICKS
    if _TICKS is None:
        try:
            _TICKS = float(os.sysconf("SC_CLK_TCK"))
        except (ValueError, OSError, AttributeError):
            _TICKS = 100.0
    return _TICKS


_PTHREAD_BROKEN = not hasattr(time, "clock_gettime")


def _pthread_cpu_s(thread: threading.Thread) -> Optional[float]:
    """pthread_getcpuclockid(ident) → clock_gettime(clockid): the POSIX
    per-thread CPU clock. Guarded: only consulted for threads still
    alive, and any libc/ctypes failure disables the path for good."""
    global _PTHREAD_BROKEN
    if _PTHREAD_BROKEN or thread.ident is None or not thread.is_alive():
        return None
    try:
        import ctypes
        libc = ctypes.CDLL(None, use_errno=True)
        clockid = ctypes.c_int()
        rc = libc.pthread_getcpuclockid(
            ctypes.c_ulong(thread.ident), ctypes.byref(clockid))
        if rc != 0:
            return None
        return time.clock_gettime(clockid.value)
    except Exception:
        _PTHREAD_BROKEN = True
        return None


class HostProfiler:
    """Sampling profiler over THIS process's threads. Thread-run in
    production (start()/stop()); tests drive sample_once() directly."""

    #: top-of-stack table rows kept per rollup
    TOP_ROWS = 25

    def __init__(self, registry: Optional[m.MetricsRegistry] = None,
                 period_s: Optional[float] = None) -> None:
        self.registry = (registry if registry is not None
                         else m.DEFAULT_REGISTRY)
        self.period_s = (period_s if period_s is not None
                         else default_period_s())
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.samples = 0
        self.started_at = 0.0
        #: subsystem → wall samples
        self._wall: Counter = Counter()
        #: subsystem → CPU seconds (summed per-thread deltas)
        self._cpu: Counter = Counter()
        #: (subsystem, "func (file:line)") → samples
        self._stacks: Counter = Counter()
        self._runnable = 0
        self._gil_starved = 0
        #: thread ident → (last cpu_s, last wall t) for delta math
        self._cpu_prev: Dict[int, tuple] = {}
        self._proc_cpu_prev: Optional[tuple] = None
        _LIVE.add(self)

    # -- one sample ---------------------------------------------------------

    def sample_once(self, now: Optional[float] = None) -> None:
        now = time.monotonic() if now is None else now
        frames = sys._current_frames()
        threads = {t.ident: t for t in threading.enumerate()}
        me = threading.get_ident()
        proc_cpu = time.process_time()
        fallback_share = self._wall_cpu_fallback(now, proc_cpu,
                                                 len(frames) or 1)
        with self._lock:
            self.samples += 1
            for ident, frame in frames.items():
                if ident == me:
                    continue  # the profiler observing itself is noise
                thread = threads.get(ident)
                name = thread.name if thread is not None else f"tid-{ident}"
                subsystem = subsystem_for(name)
                self._wall[subsystem] += 1

                code = frame.f_code
                self._stacks[(subsystem,
                              f"{code.co_name} "
                              f"({os.path.basename(code.co_filename)}:"
                              f"{frame.f_lineno})")] += 1

                waiting = self._is_waiting(frame)
                cpu_delta = self._cpu_delta(ident, thread, now,
                                            fallback_share)
                if cpu_delta is not None:
                    self._cpu[subsystem] += cpu_delta
                if not waiting:
                    self._runnable += 1
                    # runnable but accumulating (almost) no CPU since the
                    # last sample: it wanted the interpreter and did not
                    # get it — the GIL-contention signal
                    if cpu_delta is not None and \
                            cpu_delta < 0.1 * self.period_s:
                        self._gil_starved += 1
            # forget threads that died (their ident may be reused)
            dead = [i for i in self._cpu_prev if i not in frames]
            for ident in dead:
                del self._cpu_prev[ident]
        self._publish()

    @staticmethod
    def _is_waiting(frame) -> bool:
        """Top two frames: a thread inside Condition.wait's inner
        acquire still reports `wait` one frame up."""
        for _ in range(2):
            if frame is None:
                return False
            if frame.f_code.co_name in _WAIT_FUNCTIONS:
                return True
            frame = frame.f_back
        return False

    def _cpu_delta(self, ident: int, thread, now: float,
                   fallback_share: Optional[float]) -> Optional[float]:
        """CPU seconds this thread burned since its last sample."""
        cpu = _thread_cpu_s(thread) if thread is not None else None
        if cpu is None:
            return fallback_share
        prev = self._cpu_prev.get(ident)
        self._cpu_prev[ident] = (cpu, now)
        if prev is None:
            return 0.0
        return max(0.0, cpu - prev[0])

    def _wall_cpu_fallback(self, now: float, proc_cpu: float,
                           nthreads: int) -> Optional[float]:
        """When no per-thread clock exists: split the PROCESS CPU delta
        evenly across threads (coarse, but keeps cpu-share ordering
        meaningful on exotic platforms)."""
        prev = self._proc_cpu_prev
        self._proc_cpu_prev = (proc_cpu, now)
        if prev is None:
            return None
        return max(0.0, proc_cpu - prev[0]) / nthreads

    # -- rollup -------------------------------------------------------------

    def gil_contention(self) -> float:
        with self._lock:
            return (self._gil_starved / self._runnable
                    if self._runnable else 0.0)

    def attributed_share(self) -> float:
        """Fraction of sampled wall time landing on NAMED subsystem
        threads (everything but "other") — the ≥90% acceptance gate."""
        with self._lock:
            total = sum(self._wall.values())
            if not total:
                return 1.0
            return 1.0 - self._wall.get("other", 0) / total

    def rollup(self) -> Dict[str, object]:
        with self._lock:
            total = sum(self._wall.values()) or 1
            subsystems = {
                name: {
                    "samples": samples,
                    "wall_share": round(samples / total, 4),
                    "cpu_s": round(self._cpu.get(name, 0.0), 4),
                }
                for name, samples in self._wall.most_common()
            }
            top = [
                {"subsystem": subsystem, "frame": frame,
                 "samples": count, "share": round(count / total, 4)}
                for (subsystem, frame), count in
                self._stacks.most_common(self.TOP_ROWS)
            ]
            samples = self.samples
            runnable = self._runnable
            starved = self._gil_starved
        return {
            "samples": samples,
            "period_s": self.period_s,
            "threads": len(threading.enumerate()),
            "gil_contention": round(starved / runnable, 4) if runnable
            else 0.0,
            "runnable_samples": runnable,
            "attributed_share": round(self.attributed_share(), 4),
            "subsystems": subsystems,
            "top": top,
        }

    def _publish(self) -> None:
        """host.prof/* gauges on the registry (flat-scrape mirror)."""
        try:
            reg = self.registry
            reg.gauge(m.SCOPE_HOSTPROF, "samples", float(self.samples))
            reg.gauge(m.SCOPE_HOSTPROF, "gil-contention", self.gil_contention())
            reg.gauge(m.SCOPE_HOSTPROF, "attributed-share",
                      self.attributed_share())
            reg.gauge(m.SCOPE_HOSTPROF, "threads",
                      float(len(threading.enumerate())))
            with self._lock:
                total = sum(self._wall.values()) or 1
                shares = {name: samples / total
                          for name, samples in self._wall.items()}
                cpus = dict(self._cpu)
            for name, share in shares.items():
                reg.gauge(m.SCOPE_HOSTPROF, f"wall-share-{name}", round(share, 4))
            for name, cpu_s in cpus.items():
                reg.gauge(m.SCOPE_HOSTPROF, f"cpu-seconds-{name}",
                          round(cpu_s, 4))
        except Exception:
            pass  # telemetry must never take the host down

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> "HostProfiler":
        if self._thread is not None and self._thread.is_alive():
            return self
        self._stop.clear()
        self.started_at = time.monotonic()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="cadence-hostprof")
        self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.wait(self.period_s):
            try:
                self.sample_once()
            except Exception:
                continue

    def stop(self) -> None:
        self._stop.set()
        thread = self._thread
        if thread is not None:
            thread.join(timeout=2.0)
            self._thread = None

    def reset(self) -> None:
        self.stop()
        with self._lock:
            self.samples = 0
            self._wall.clear()
            self._cpu.clear()
            self._stacks.clear()
            self._runnable = 0
            self._gil_starved = 0
            self._cpu_prev.clear()
            self._proc_cpu_prev = None


_LIVE: "weakref.WeakSet[HostProfiler]" = weakref.WeakSet()


def reset_all() -> None:
    for profiler in list(_LIVE):
        try:
            profiler.reset()
        except Exception:
            pass
