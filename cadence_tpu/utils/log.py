"""Structured logging: stdlib logging + typed tags.

Reference: common/log/loggerimpl/logger.go:29 (zap sugared logger) and
log/tag/ (typed tag constructors — WorkflowID, ShardID, Domain...). The
contract kept: every log line carries machine-parseable key=value tags,
loggers compose tags incrementally (`With`), and the library never
configures handlers (hosts/CLI own the sink — NullHandler by default,
exactly how a library should behave).
"""
from __future__ import annotations

import logging
from typing import Any, Dict

_ROOT = logging.getLogger("cadence_tpu")
_ROOT.addHandler(logging.NullHandler())


class TaggedLogger:
    """A logger carrying a tag set; `with_tags` derives a child logger
    (loggerimpl.WithTags analog). Tags render as sorted key=value pairs
    appended to the message."""

    def __init__(self, logger: logging.Logger = _ROOT,
                 tags: Dict[str, Any] = None) -> None:
        self._logger = logger
        self._tags = dict(tags or {})

    def with_tags(self, **tags: Any) -> "TaggedLogger":
        merged = dict(self._tags)
        merged.update(tags)
        return TaggedLogger(self._logger, merged)

    def _render(self, msg: str, tags: Dict[str, Any]) -> str:
        merged = dict(self._tags)
        merged.update(tags)
        if not merged:
            return msg
        suffix = " ".join(f"{k}={merged[k]}" for k in sorted(merged))
        return f"{msg} {suffix}"

    def isEnabledFor(self, level: int) -> bool:
        return self._logger.isEnabledFor(level)

    def debug(self, msg: str, **tags: Any) -> None:
        if self._logger.isEnabledFor(logging.DEBUG):
            self._logger.debug(self._render(msg, tags))

    def info(self, msg: str, **tags: Any) -> None:
        if self._logger.isEnabledFor(logging.INFO):
            self._logger.info(self._render(msg, tags))

    def warning(self, msg: str, **tags: Any) -> None:
        if self._logger.isEnabledFor(logging.WARNING):
            self._logger.warning(self._render(msg, tags))

    def error(self, msg: str, **tags: Any) -> None:
        if self._logger.isEnabledFor(logging.ERROR):
            self._logger.error(self._render(msg, tags))


#: the default cluster logger; components derive tagged children from it
DEFAULT_LOGGER = TaggedLogger()


def configure_stderr(level: int = logging.INFO) -> None:
    """Host/CLI convenience: send cadence_tpu logs to stderr (the library
    itself never does this)."""
    handler = logging.StreamHandler()
    handler.setFormatter(logging.Formatter(
        "%(asctime)s %(levelname)s %(name)s %(message)s"))
    _ROOT.addHandler(handler)
    _ROOT.setLevel(level)
