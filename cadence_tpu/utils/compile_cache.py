"""Persistent XLA compilation cache wiring.

The first compile of the fused replay kernel costs tens of seconds per
shape; without a persistent cache EVERY process (bench, CLI, service
hosts, dryruns) pays it again. JAX supports a disk cache, but on hosts
whose site bootstrap imports jax before user code (this environment's
sitecustomize does), the JAX_COMPILATION_CACHE_DIR environment variable
is read before it can be set — the config freezes at None and the cache
silently never engages (observed: 123 stale entries, zero hits, 50s
compiles in every process). The fix is the post-import config update
this module applies; call enable() early in every entry point.
"""
from __future__ import annotations

import os

DEFAULT_DIR = "/tmp/jax_cache"


def enable(path: str = "") -> str:
    """Point JAX's persistent compilation cache at `path` (default: the
    JAX_COMPILATION_CACHE_DIR env var, then /tmp/jax_cache). Idempotent;
    returns the directory in use."""
    import jax

    path = (path or os.environ.get("JAX_COMPILATION_CACHE_DIR")
            or DEFAULT_DIR)
    os.makedirs(path, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", path)
    return path
