"""Persistent XLA compilation cache wiring.

The first compile of the fused replay kernel costs tens of seconds per
shape; without a persistent cache EVERY process (bench, CLI, service
hosts, dryruns) pays it again. JAX supports a disk cache, but on hosts
whose site bootstrap imports jax before user code (this environment's
sitecustomize does), the JAX_COMPILATION_CACHE_DIR environment variable
is read before it can be set — the config freezes at None and the cache
silently never engages (observed: 123 stale entries, zero hits, 50s
compiles in every process). The fix is the post-import config update
this module applies; call enable() early in every entry point.
"""
from __future__ import annotations

import os
import threading
from typing import Callable, Dict, Hashable

DEFAULT_DIR = "/tmp/jax_cache"


def enable(path: str = "") -> str:
    """Point JAX's persistent compilation cache at `path` (default: the
    JAX_COMPILATION_CACHE_DIR env var, then /tmp/jax_cache). Idempotent;
    returns the directory in use."""
    import jax

    path = (path or os.environ.get("JAX_COMPILATION_CACHE_DIR")
            or DEFAULT_DIR)
    os.makedirs(path, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", path)
    return path


class KernelVariantCache:
    """Process-level registry of compiled kernel VARIANTS, keyed by the
    caller on (wire format, layout, rung/K, padded shape, shard count).

    The escalation ladder (engine/ladder.py) compiles one extra
    executable per widened-K rung variant; this cache makes that cost
    observable and amortized: get() returns the cached callable (a HIT —
    zero compile work) or builds it once (a MISS — exactly one XLA
    compile, itself served from the persistent disk cache above on warm
    processes). Hit/miss counters land on `tpu.fallback/*` so a warm
    re-run can PROVE it paid zero ladder recompiles — the acceptance bar
    bench.py reports against.

    Shape keys should be pow2-bucketed by the caller: flagged-row counts
    wobble run to run, and bucketing keeps them landing on the same
    variant instead of minting a new executable per count.
    """

    def __init__(self, registry=None) -> None:
        self._lock = threading.Lock()
        self._fns: Dict[Hashable, Callable] = {}
        self.metrics = registry

    def _registry(self):
        if self.metrics is not None:
            return self.metrics
        from . import metrics as m
        return m.DEFAULT_REGISTRY

    def get(self, key: Hashable, build: Callable[[], Callable],
            registry=None, scope: str = "") -> Callable:
        """`registry` routes THIS call's hit/miss counters (a shared
        cache serves ladders bound to different per-cluster registries;
        each caller's counters must land on its own /metrics scrape);
        falls back to the cache-level registry, then the default.
        `scope` routes the counters' metric scope — the ladder's
        tpu.fallback by default; the mesh-aware serving executor passes
        its own so a warm serving run can prove zero recompiles without
        reading fallback series."""
        from . import metrics as m

        reg = registry if registry is not None else self._registry()
        scope = scope or m.SCOPE_TPU_FALLBACK
        with self._lock:
            fn = self._fns.get(key)
        if fn is not None:
            reg.inc(scope, m.M_LADDER_CACHE_HITS)
            return fn
        built = build()
        with self._lock:
            fn = self._fns.setdefault(key, built)
        if fn is built:
            # exactly one winner per key counts the miss/compile, even
            # when two ladder passes race on the same variant
            reg.inc(scope, m.M_LADDER_CACHE_MISSES)
            reg.inc(scope, m.M_LADDER_COMPILES)
        else:
            reg.inc(scope, m.M_LADDER_CACHE_HITS)
        return fn

    def clear(self) -> None:
        with self._lock:
            self._fns.clear()


#: shared variant registry — all ladders in a process reuse one another's
#: compiled rungs (Onebox clusters, bench trials, tests)
DEFAULT_VARIANTS = KernelVariantCache()
