"""Dynamic config: runtime knobs consumed as live closures.

Reference: common/dynamicconfig — ~350 typed constants
(dynamicconfig/constants.go) resolved through a Client
(clientInterface.go:40) with domain/shard/task-list filters, consumed as
closures (service/history/config/config.go) so updates apply without
restarts. This module keeps the same shape: named keys with defaults,
filterable overrides, `set()` for live updates, and `*_property` accessors
returning closures.
"""
from __future__ import annotations

import threading
from typing import Any, Callable, Dict, Optional, Tuple


# -- knob names (dynamicconfig/constants.go analog; the knobs in use) -------

# kernel / payload capacities (PayloadLayout; SURVEY §7 "measured, never
# silent" — these bound the device tables, overflow falls back to oracle)
KEY_MAX_ACTIVITIES = "kernel.maxPendingActivities"
KEY_MAX_TIMERS = "kernel.maxPendingTimers"
KEY_MAX_CHILDREN = "kernel.maxPendingChildren"
KEY_MAX_REQUEST_CANCELS = "kernel.maxPendingRequestCancels"
KEY_MAX_SIGNALS = "kernel.maxPendingSignals"
KEY_MAX_VERSION_HISTORY_ITEMS = "kernel.maxVersionHistoryItems"
KEY_MAX_BRANCHES = "kernel.maxVersionHistoryBranches"
# engine / queues
KEY_QUEUE_BATCH_SIZE = "history.queueBatchSize"
# multi-level processing queues (queue/split_policy.go): a domain whose
# observed transfer backlog in one shard exceeds the threshold splits to
# its own level (own ack, own reads) so it cannot starve siblings
KEY_QUEUE_SPLIT_THRESHOLD = "history.queueSplitThreshold"
KEY_QUEUE_MAX_LEVEL = "history.queueMaxLevel"
# matching scale-out (matchingEngine.getAllPartitions / forwarder.go)
KEY_MATCHING_NUM_PARTITIONS = "matching.numTasklistPartitions"
KEY_RETENTION_DAYS_DEFAULT = "domain.defaultRetentionDays"
# frontend quotas (quotas/ratelimiter.go seat)
KEY_FRONTEND_RPS = "frontend.rps"
KEY_FRONTEND_DOMAIN_RPS = "frontend.domainRPS"
KEY_FRONTEND_BURST = "frontend.burst"
# size/count limits (decision/checker.go blob checks, size_limit_test.go
# history growth enforcement); 0 disables a limit
KEY_BLOB_SIZE_LIMIT_WARN = "limit.blobSizeWarn"
KEY_BLOB_SIZE_LIMIT_ERROR = "limit.blobSizeError"
KEY_HISTORY_COUNT_LIMIT_WARN = "limit.historyCountWarn"
KEY_HISTORY_COUNT_LIMIT_ERROR = "limit.historyCountError"
KEY_HISTORY_SIZE_LIMIT_WARN = "limit.historySizeWarn"
KEY_HISTORY_SIZE_LIMIT_ERROR = "limit.historySizeError"
# pagination: the default/maximum page any list-shaped API returns
KEY_HISTORY_PAGE_SIZE = "limit.historyPageSize"
KEY_VISIBILITY_PAGE_SIZE = "limit.visibilityPageSize"
# rpc resilience tier (common/backoff retry policies + outbound breakers):
# client retry policy for cross-process calls ...
KEY_RPC_RETRY_MAX_ATTEMPTS = "rpc.retryMaxAttempts"
KEY_RPC_RETRY_INIT_INTERVAL_MS = "rpc.retryInitIntervalMs"
KEY_RPC_RETRY_MAX_INTERVAL_MS = "rpc.retryMaxIntervalMs"
KEY_RPC_RETRY_EXPIRATION_S = "rpc.retryExpirationSeconds"
# ... per-target circuit breakers ...
KEY_RPC_BREAKER_FAILURE_THRESHOLD = "rpc.breakerFailureThreshold"
KEY_RPC_BREAKER_RESET_TIMEOUT_S = "rpc.breakerResetSeconds"
# ... and the wire chaos spec ("drop=0.05,sever=0.03,delay=0.1,seed=7";
# empty = no chaos; the CADENCE_TPU_CHAOS env var is the cross-process
# equivalent for subprocess clusters)
KEY_WIRE_CHAOS = "rpc.wireChaos"

#: durability crashpoint spec ("" = disarmed), e.g.
#: "site=wal.append.after-write,hit=3,mode=kill" (engine/crashpoints.py)
KEY_CRASHPOINT = "durability.crashpoint"

_DEFAULTS: Dict[str, Any] = {
    KEY_MAX_ACTIVITIES: 16,
    KEY_MAX_TIMERS: 16,
    KEY_MAX_CHILDREN: 8,
    KEY_MAX_REQUEST_CANCELS: 8,
    KEY_MAX_SIGNALS: 8,
    KEY_MAX_VERSION_HISTORY_ITEMS: 8,
    KEY_MAX_BRANCHES: 2,
    KEY_QUEUE_BATCH_SIZE: 100,
    KEY_QUEUE_SPLIT_THRESHOLD: 500,
    KEY_QUEUE_MAX_LEVEL: 2,
    KEY_MATCHING_NUM_PARTITIONS: 1,
    KEY_RETENTION_DAYS_DEFAULT: 1,
    KEY_FRONTEND_RPS: 0,          # 0 = unlimited
    KEY_FRONTEND_DOMAIN_RPS: 0,
    KEY_FRONTEND_BURST: 0,        # 0 = burst == rps
    KEY_BLOB_SIZE_LIMIT_WARN: 256 * 1024,        # the reference's defaults
    KEY_BLOB_SIZE_LIMIT_ERROR: 2 * 1024 * 1024,
    KEY_HISTORY_COUNT_LIMIT_WARN: 150_000,
    KEY_HISTORY_COUNT_LIMIT_ERROR: 200_000,
    KEY_HISTORY_SIZE_LIMIT_WARN: 50 * 1024 * 1024,
    KEY_HISTORY_SIZE_LIMIT_ERROR: 200 * 1024 * 1024,
    KEY_HISTORY_PAGE_SIZE: 1000,
    KEY_VISIBILITY_PAGE_SIZE: 1000,
    KEY_RPC_RETRY_MAX_ATTEMPTS: 6,
    KEY_RPC_RETRY_INIT_INTERVAL_MS: 50,
    KEY_RPC_RETRY_MAX_INTERVAL_MS: 1000,
    KEY_RPC_RETRY_EXPIRATION_S: 30,
    KEY_RPC_BREAKER_FAILURE_THRESHOLD: 5,
    KEY_RPC_BREAKER_RESET_TIMEOUT_S: 5,
    KEY_WIRE_CHAOS: "",
    KEY_CRASHPOINT: "",
}


class DynamicConfig:
    """Key → value store with filterable overrides and live updates."""

    def __init__(self, overrides: Optional[Dict[str, Any]] = None) -> None:
        self._lock = threading.Lock()
        self._values: Dict[str, Any] = dict(overrides or {})
        #: (key, ("domain", domain_name)) → value etc.
        self._filtered: Dict[Tuple[str, Tuple[str, str]], Any] = {}

    def get(self, key: str, default: Any = None, *,
            domain: Optional[str] = None) -> Any:
        """Most-specific wins: domain filter → global override → built-in
        default → caller default (dynamicconfig filter precedence)."""
        with self._lock:
            if domain is not None:
                v = self._filtered.get((key, ("domain", domain)))
                if v is not None:
                    return v
            if key in self._values:
                return self._values[key]
        if key in _DEFAULTS:
            return _DEFAULTS[key]
        return default

    def set(self, key: str, value: Any, *,
            domain: Optional[str] = None) -> None:
        """Live update (file_based_client poll / configstore write analog)."""
        with self._lock:
            if domain is not None:
                self._filtered[(key, ("domain", domain))] = value
            else:
                self._values[key] = value

    def int_property(self, key: str, default: int = 0
                     ) -> Callable[..., int]:
        """A closure the consumer calls at use time, so updates apply live
        (service/history/config/config.go pattern)."""
        def prop(domain: Optional[str] = None) -> int:
            return int(self.get(key, default, domain=domain))
        return prop

    # -- kernel layout -----------------------------------------------------

    def payload_layout(self):
        """The kernel capacities as a PayloadLayout — tunable without code
        edits (VERDICT r2 weak #8)."""
        from ..core.checksum import PayloadLayout
        return PayloadLayout(
            max_version_history_items=int(self.get(KEY_MAX_VERSION_HISTORY_ITEMS)),
            max_activities=int(self.get(KEY_MAX_ACTIVITIES)),
            max_timers=int(self.get(KEY_MAX_TIMERS)),
            max_children=int(self.get(KEY_MAX_CHILDREN)),
            max_request_cancels=int(self.get(KEY_MAX_REQUEST_CANCELS)),
            max_signals=int(self.get(KEY_MAX_SIGNALS)),
            max_branches=int(self.get(KEY_MAX_BRANCHES)),
        )
