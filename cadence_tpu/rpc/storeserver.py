"""Store server: the persistence role as its own process.

The reference's history hosts share a database (Cassandra/MySQL) that is
the single authority for fenced writes (range-ID CAS) — so shard fencing
works ACROSS hosts because the CAS evaluates at the store, not in any
host's memory. This process plays that role: it owns the authoritative
`Stores` bundle (optionally durable via the WAL) and serves

  ("store", sub, method, args, kwargs)  → getattr(stores.<sub>, method)(...)
  ("hb", name, port, advertised_host)   → membership heartbeat upsert
  ("peers", ttl_seconds)                → [(host, port)] with fresh beats
  ("ping",)                             → "pong"

Membership is the ringpop analog reduced to its observable contract
(SURVEY §2.6): hosts that heartbeat are in the ring; hosts that stop are
dropped after a TTL and their shards get stolen — the steal is safe
because every store write from the deposed owner still fails the range
CAS HERE, whatever that host believes about its liveness.

Run: python -m cadence_tpu.rpc.storeserver --port P [--wal PATH]
"""
from __future__ import annotations

import argparse
import socketserver
import threading
import time
from contextlib import nullcontext
from typing import Dict, Tuple

from ..engine.persistence import Stores
from ..utils import deadline as deadline_mod
from ..utils import tracing
from ..utils.deadline import DeadlineExceeded
from .wire import recv_frame, send_frame, verify_hello


class StoreServer(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True

    def __init__(self, address: Tuple[str, int], stores: Stores) -> None:
        super().__init__(address, _Handler)
        self.stores = stores
        self._beats: Dict[Tuple[str, int], float] = {}
        self._beats_lock = threading.Lock()

    def heartbeat(self, name: str, port: int,
                  address: str = "127.0.0.1") -> None:
        """`address` is the beater's ADVERTISED host — what peers and
        remote clusters must dial (loopback only works single-machine;
        containers advertise their service name)."""
        with self._beats_lock:
            self._beats[(name, port)] = (time.monotonic(), address)

    def peers(self, ttl: float):
        """[(name, port, address)] of live beaters."""
        now = time.monotonic()
        with self._beats_lock:
            return sorted((n, p, addr)
                          for (n, p), (t, addr) in self._beats.items()
                          if now - t <= ttl)


class _Handler(socketserver.BaseRequestHandler):
    def handle(self) -> None:
        """One connection, many frames; op errors report to the caller,
        only THIS socket's failures end the connection (see server.py)."""
        server: StoreServer = self.server  # type: ignore[assignment]
        try:
            verify_hello(self.request)  # before the first pickle load
        except (OSError, ConnectionError):
            return
        while True:
            try:
                req = recv_frame(self.request)
            except (OSError, ConnectionError):
                return
            # engine transactions traced at a service host propagate here
            # too, so store round-trips appear inside the same trace; the
            # caller's deadline budget rides the same carrier
            remote_deadline = deadline_mod.peek(req)
            remote_ctx, req = tracing.extract(req)
            try:
                op = req[0]
                if remote_deadline is not None and remote_deadline.expired():
                    from ..utils.metrics import DEFAULT_REGISTRY
                    DEFAULT_REGISTRY.inc("rpc.server",
                                         "deadline-expired-rejections")
                    raise DeadlineExceeded(
                        f"store rpc.{op} arrived with its deadline expired")
                span_cm = (tracing.DEFAULT_TRACER.start_span(
                               f"rpc.{op}", child_of=remote_ctx)
                           if remote_ctx is not None else nullcontext())
                with span_cm, deadline_mod.bind(remote_deadline):
                    result = self._dispatch(server, req)
                response = ("ok", result)
            except BaseException as exc:  # service errors cross the wire
                response = ("err", exc)
            try:
                send_frame(self.request, response)
            except (OSError, ConnectionError):
                return
            except Exception:
                try:
                    send_frame(self.request,
                               ("err", RuntimeError(repr(response[1]))))
                except Exception:
                    return

    @staticmethod
    def _dispatch(server: "StoreServer", req):
        op = req[0]
        if op == "store":
            _, sub, method, args, kwargs = req
            target = getattr(server.stores, sub)
            return getattr(target, method)(*args, **kwargs)
        if op == "hb":
            server.heartbeat(req[1], req[2],
                             req[3] if len(req) > 3 else "127.0.0.1")
            return None
        if op == "peers":
            return server.peers(req[1])
        if op == "ping":
            return "pong"
        raise ValueError(f"unknown op {op!r}")


#: env spec for seeded store-fault injection in a store-server PROCESS
#: (the subprocess analog of calling engine/faults.inject_faults in-proc):
#:   CADENCE_TPU_STORE_FAULTS="rate=0.05,seed=7"
STORE_FAULTS_ENV = "CADENCE_TPU_STORE_FAULTS"


def _parse_fault_spec(spec: str):
    """"rate=0.05,seed=7[,writes_only=0]" → FaultInjector. Injected
    errors raise BEFORE the store method runs (engine/faults.py), so a
    caller retry is always safe — the property the chaos soak leans on."""
    from ..engine.faults import FaultInjector
    from .chaos import parse_kv_spec

    def to_bool(value: str) -> bool:
        return value.lower() not in ("0", "false", "no", "off", "")

    kwargs = parse_kv_spec(
        spec, {"rate": float, "seed": int, "writes_only": to_bool})
    return FaultInjector(**kwargs)


def serve(port: int, wal: str = "", host: str = "127.0.0.1",
          fault_spec: str = "") -> None:
    import os

    if wal:
        from ..engine.durability import open_durable_stores, recover_stores
        if os.path.exists(wal):
            stores, _report = recover_stores(wal, verify_on_device=False,
                                             rebuild_on_device=False)
        else:
            stores = open_durable_stores(wal)
    else:
        stores = Stores()
    fault_spec = fault_spec or os.environ.get(STORE_FAULTS_ENV, "")
    if fault_spec:
        from ..engine.faults import inject_faults
        inject_faults(stores, _parse_fault_spec(fault_spec))
    server = StoreServer((host, port), stores)
    server.serve_forever()


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="cadence-tpu-store")
    p.add_argument("--port", type=int, required=True)
    p.add_argument("--wal", default="")
    p.add_argument("--host", default="127.0.0.1",
                   help="bind address (0.0.0.0 in containers; the HMAC "
                        "connection preamble still gates every peer)")
    p.add_argument("--fault-spec", default="",
                   help="seeded store-fault injection, e.g. "
                        "'rate=0.05,seed=7' (CADENCE_TPU_STORE_FAULTS "
                        "env equivalent; chaos soak harness)")
    args = p.parse_args(argv)
    serve(args.port, args.wal, host=args.host, fault_spec=args.fault_spec)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
