"""Cluster launcher: real OS processes, one wire, shared fenced store.

Reference: docker/docker-compose*.yml runs the four roles + DB as separate
containers; host/testcluster.go builds the in-process equivalent. This is
the process-level deployment for tests and local clusters:

    cluster = launch(num_hosts=2)      # store server + N service hosts
    fe = cluster.frontend(0)           # any host's frontend, over TCP
    fe.register_domain("d")
    fe.start_workflow_execution(...)
    cluster.kill_host(1)               # SIGKILL; TTL drops it from the
                                       # ring; survivors steal its shards

Every control-plane byte crosses real sockets; fenced writes evaluate in
the store-server process, so range-ID fencing holds across hosts.
"""
from __future__ import annotations

import os
import signal
import socket
import subprocess
import sys
import time
from typing import Dict, List, Tuple

from .client import _Pool
from .wire import call


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


class FrontendClient:
    """Frontend over the wire: any method of engine/frontend.Frontend.

    Retries ShardOwnershipLostError with backoff — the retryable-client
    tier (client/frontend wrappers): shard movement mid-request is a
    ROUTINE transient in a live cluster (steal, flap, re-acquire), and the
    fence guarantees a retry lands on a valid owner or fails honestly."""

    RETRIES = 8
    BACKOFF_S = 0.25

    def __init__(self, address: Tuple[str, int]) -> None:
        self.address = address
        self._pool = _Pool(address)

    def __getattr__(self, method: str):
        if method.startswith("_"):
            raise AttributeError(method)
        pool = self._pool

        def invoke(*args, **kwargs):
            from ..engine.controller import ShardNotOwnedError
            from ..engine.persistence import ShardOwnershipLostError

            # ConnectionRefusedError: an outbound hop inside the serving
            # host hit a dead peer before the ring noticed — nothing was
            # applied (the connect failed), so retrying is safe
            last = None
            for attempt in range(self.RETRIES):
                try:
                    return pool.call(("frontend", method, args, kwargs))
                except (ShardOwnershipLostError, ShardNotOwnedError,
                        ConnectionRefusedError) as exc:
                    last = exc
                    time.sleep(self.BACKOFF_S * (attempt + 1))
            raise last

        return invoke


class Cluster:
    def __init__(self, store_port: int, hosts: Dict[str, int],
                 procs: Dict[str, subprocess.Popen],
                 store_proc: subprocess.Popen) -> None:
        self.store_port = store_port
        self.hosts = hosts          # name → port
        self.procs = procs          # name → process
        self.store_proc = store_proc

    def frontend(self, index_or_name) -> FrontendClient:
        name = (index_or_name if isinstance(index_or_name, str)
                else sorted(self.hosts)[index_or_name])
        return FrontendClient(("127.0.0.1", self.hosts[name]))

    def ping(self, name: str):
        return call(("127.0.0.1", self.hosts[name]), ("ping",), timeout=5)

    def owned_shards(self) -> Dict[str, List[int]]:
        out = {}
        for name in self.hosts:
            if self.procs[name].poll() is None:
                try:
                    out[name] = self.ping(name)[2]
                except Exception:
                    out[name] = []
        return out

    def kill_host(self, name: str, sig: int = signal.SIGKILL) -> None:
        self.procs[name].send_signal(sig)
        if sig == signal.SIGKILL:
            self.procs[name].wait(timeout=10)

    def pause_host(self, name: str) -> None:
        """SIGSTOP: the host stops beating but believes it is alive — the
        classic partitioned-owner scenario the range fence exists for."""
        self.procs[name].send_signal(signal.SIGSTOP)

    def resume_host(self, name: str) -> None:
        self.procs[name].send_signal(signal.SIGCONT)

    def stop(self) -> None:
        for p in self.procs.values():
            if p.poll() is None:
                p.kill()
        if self.store_proc.poll() is None:
            self.store_proc.kill()
        for p in list(self.procs.values()) + [self.store_proc]:
            try:
                p.wait(timeout=10)
            except Exception:
                pass


def _wait_listening(port: int, proc: subprocess.Popen,
                    timeout: float = 30.0) -> None:
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if proc.poll() is not None:
            raise RuntimeError(
                f"process exited rc={proc.returncode} before listening")
        try:
            call(("127.0.0.1", port), ("ping",), timeout=2)
            return
        except Exception:
            time.sleep(0.05)
    raise TimeoutError(f"port {port} not serving after {timeout}s")


def launch(num_hosts: int = 2, num_shards: int = 8, wal: str = "",
           hb_interval: float = 0.15, ttl: float = 3.0) -> Cluster:
    """Spawn the store server + `num_hosts` service hosts as OS processes.
    The TTL must comfortably exceed worst-case heartbeat jitter (a
    GIL-starved beat thread on a loaded host): a too-tight TTL makes the
    failure detector flap, and every flap is a spurious steal — safe
    (fencing holds) but churny. Test-sized here; production stretches both."""
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")  # control-plane processes
    repo = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")

    store_port = free_port()
    store_cmd = [sys.executable, "-m", "cadence_tpu.rpc.storeserver",
                 "--port", str(store_port)]
    if wal:
        store_cmd += ["--wal", wal]
    store_proc = subprocess.Popen(store_cmd, env=env)
    _wait_listening(store_port, store_proc)

    hosts: Dict[str, int] = {}
    procs: Dict[str, subprocess.Popen] = {}
    for i in range(num_hosts):
        name = f"host-{i}"
        port = free_port()
        cmd = [sys.executable, "-m", "cadence_tpu.rpc.server",
               "--name", name, "--port", str(port),
               "--store", f"127.0.0.1:{store_port}",
               "--num-shards", str(num_shards),
               "--hb-interval", str(hb_interval), "--ttl", str(ttl)]
        procs[name] = subprocess.Popen(cmd, env=env)
        hosts[name] = port
    for name, port in hosts.items():
        _wait_listening(port, procs[name])
    # let every host's RING converge on the full peer set before handing
    # the cluster out (a host still on a single-member ring believes it
    # owns every shard → spurious steal churn on first requests)
    deadline = time.monotonic() + 10
    want = set(hosts)
    while time.monotonic() < deadline:
        views = []
        for name, port in hosts.items():
            try:
                ping = call(("127.0.0.1", port), ("ping",), timeout=2)
                views.append(set(ping[3]))
            except Exception:
                views.append(set())
        if all(v >= want for v in views):
            break
        time.sleep(0.05)
    return Cluster(store_port, hosts, procs, store_proc)
