"""Cluster launcher: real OS processes, one wire, shared fenced store.

Reference: docker/docker-compose*.yml runs the four roles + DB as separate
containers; host/testcluster.go builds the in-process equivalent. This is
the process-level deployment for tests and local clusters:

    cluster = launch(num_hosts=2)      # store server + N service hosts
    fe = cluster.frontend(0)           # any host's frontend, over TCP
    fe.register_domain("d")
    fe.start_workflow_execution(...)
    cluster.kill_host(1)               # SIGKILL; TTL drops it from the
                                       # ring; survivors steal its shards

Every control-plane byte crosses real sockets; fenced writes evaluate in
the store-server process, so range-ID fencing holds across hosts.
"""
from __future__ import annotations

import os
import signal
import socket
import subprocess
import sys
import time
from typing import Dict, List, Tuple

from .client import _Pool
from .wire import call


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


class FrontendClient:
    """Frontend over the wire: any method of engine/frontend.Frontend.

    Retries ShardOwnershipLostError with backoff — the retryable-client
    tier (client/frontend wrappers): shard movement mid-request is a
    ROUTINE transient in a live cluster (steal, flap, re-acquire), and the
    fence guarantees a retry lands on a valid owner or fails honestly.
    ServiceBusy (a breaker shedding somewhere downstream) and
    TransientStoreError (injected pre-apply, never partially committed)
    are retried the same way; breaker-open on THIS client's own target
    surfaces as a typed ServiceBusy once retries exhaust, so callers
    degrade instead of queueing behind a dead host.

    Caveat (same as the pre-existing ConnectionRefusedError retry): a
    ServiceBusy can fire AFTER a mutation partially applied on the
    serving host (create committed, then a forward hit an open breaker),
    so a retried start may surface WorkflowAlreadyStartedError — callers
    treat that as success (the run is fully usable with history-first
    ordering; see tests/test_faults.py)."""

    RETRIES = 8
    BACKOFF_S = 0.25

    def __init__(self, address: Tuple[str, int]) -> None:
        self.address = address
        self._pool = _Pool(address)

    def __getattr__(self, method: str):
        if method.startswith("_"):
            raise AttributeError(method)
        pool = self._pool

        def invoke(*args, **kwargs):
            from ..engine.controller import ShardNotOwnedError
            from ..engine.faults import TransientStoreError
            from ..engine.persistence import ShardOwnershipLostError
            from ..utils.circuitbreaker import CircuitOpenError, ServiceBusy

            # ConnectionRefusedError: an outbound hop inside the serving
            # host hit a dead peer before the ring noticed — nothing was
            # applied (the connect failed), so retrying is safe
            last = None
            for attempt in range(self.RETRIES):
                try:
                    return pool.call(("frontend", method, args, kwargs))
                except (ShardOwnershipLostError, ShardNotOwnedError,
                        ConnectionRefusedError, ServiceBusy,
                        TransientStoreError) as exc:
                    last = exc
                    time.sleep(self.BACKOFF_S * (attempt + 1))
                except CircuitOpenError as exc:
                    # this client's own breaker shed the call: back off for
                    # the breaker's reset window, then probe again
                    last = ServiceBusy(str(exc))
                    time.sleep(self.BACKOFF_S * (attempt + 1))
            raise last

        return invoke


class Cluster:
    def __init__(self, store_port: int, hosts: Dict[str, int],
                 procs: Dict[str, subprocess.Popen],
                 store_proc: subprocess.Popen,
                 http_ports: Dict[str, int] = None,
                 spawn_host=None, wal: str = "",
                 store_cmd=None, store_env=None) -> None:
        self.store_port = store_port
        #: WAL path of the store server ("" = in-memory): a killed
        #: region's store can relaunch from it for post-mortem recovery
        self.wal = wal
        self.hosts = hosts          # name → port
        self.procs = procs          # name → process
        self.store_proc = store_proc
        #: name → HTTP scrape port (/metrics, /health, /traces)
        self.http_ports = dict(http_ports or {})
        #: launch()'s host-spawn closure (same store, same knobs) — the
        #: planned-rebalance seam: add_host grows the ring mid-life and
        #: the losing hosts migrate their moving shards' resident state
        self._spawn_host = spawn_host
        #: exact store-server invocation (argv + env) — kill_store /
        #: relaunch_store replay it so a WAL-backed store can SIGKILL and
        #: recover on the SAME port mid-campaign (gen/cluster_chaos.py)
        self._store_cmd = list(store_cmd) if store_cmd else None
        self._store_env = dict(store_env) if store_env else None

    def frontend(self, index_or_name) -> FrontendClient:
        name = (index_or_name if isinstance(index_or_name, str)
                else sorted(self.hosts)[index_or_name])
        return FrontendClient(("127.0.0.1", self.hosts[name]))

    def ping(self, name: str):
        return call(("127.0.0.1", self.hosts[name]), ("ping",), timeout=5)

    def admin(self, name: str, op: str, *args, timeout: float = 30):
        """One admin wire op against a host (admin_metrics,
        admin_cluster, admin_drain, ...)."""
        return call(("127.0.0.1", self.hosts[name]), (op,) + args,
                    timeout=timeout)

    def add_host(self, name: str = "") -> str:
        """Planned rebalance: spawn one more service host against the
        same store server and wait until every live ring converges on
        the grown membership (the losing hosts' shard release — and
        their resident-state out-migration — happens on their own beat
        threads as the ring change lands). Returns the new host name."""
        if self._spawn_host is None:
            raise RuntimeError("this cluster was not built by launch()")
        name = name or f"host-{len(self.hosts)}"
        if name in self.hosts:
            raise ValueError(f"host {name!r} already exists")
        port, http_port, proc = self._spawn_host(name)
        self.hosts[name] = port
        self.http_ports[name] = http_port
        self.procs[name] = proc
        _wait_listening(port, proc)
        want = {n for n in self.hosts if self.procs[n].poll() is None}
        deadline = time.monotonic() + 15
        while time.monotonic() < deadline:
            views = []
            for n in sorted(want):
                try:
                    views.append(set(self.ping(n)[3]))
                except Exception:
                    views.append(set())
            if all(v >= want for v in views):
                return name
            time.sleep(0.05)
        raise TimeoutError(f"ring never converged after adding {name}")

    def owned_shards(self) -> Dict[str, List[int]]:
        out = {}
        for name in self.hosts:
            if self.procs[name].poll() is None:
                try:
                    out[name] = self.ping(name)[2]
                except Exception:
                    out[name] = []
        return out

    def kill_host(self, name: str, sig: int = signal.SIGKILL) -> None:
        self.procs[name].send_signal(sig)
        if sig == signal.SIGKILL:
            self.procs[name].wait(timeout=10)

    def pause_host(self, name: str) -> None:
        """SIGSTOP: the host stops beating but believes it is alive — the
        classic partitioned-owner scenario the range fence exists for."""
        self.procs[name].send_signal(signal.SIGSTOP)

    def resume_host(self, name: str) -> None:
        self.procs[name].send_signal(signal.SIGCONT)

    def kill_store(self) -> None:
        """SIGKILL the store-server process mid-traffic. Every host call
        fails retryably until relaunch_store(); only meaningful with a
        durable WAL (an in-memory store's state dies with it)."""
        if self.store_proc.poll() is None:
            self.store_proc.kill()
            self.store_proc.wait(timeout=10)

    def relaunch_store(self) -> None:
        """Respawn the store server with its original argv/env on the
        SAME port: boot recovery replays the WAL it was killed with
        (rpc/storeserver.serve → engine/durability.recover_stores), so
        hosts' pooled connections redial and the fleet resumes. The
        caller fscks `self.wal` BEFORE calling this when it wants the
        recovery gated clean (the campaign oracle does)."""
        if self._store_cmd is None:
            raise RuntimeError("this cluster was not built by launch()")
        if self.store_proc.poll() is None:
            raise RuntimeError("store server still running")
        self.store_proc = subprocess.Popen(self._store_cmd,
                                           env=self._store_env)
        _wait_listening(self.store_port, self.store_proc)

    # -- asymmetric partitions (rpc/chaos.PartitionTable over the wire) ----

    def _endpoint(self, dst: str) -> Tuple[str, int]:
        """"store" or a host name → the (host, port) its dialers use."""
        if dst == "store":
            return ("127.0.0.1", self.store_port)
        return ("127.0.0.1", self.hosts[dst])

    def sever(self, src: str, dst: str) -> dict:
        """Block src's OUTBOUND leg to `dst` ("store" or a host name).
        Asymmetric by construction: dst → src and every other pair keep
        flowing until severed themselves."""
        host, port = self._endpoint(dst)
        return self.admin(src, "admin_partition", "block", host, port)

    def heal(self, src: str, dst: str) -> dict:
        host, port = self._endpoint(dst)
        return self.admin(src, "admin_partition", "heal", host, port)

    def heal_all_partitions(self) -> None:
        """Campaign teardown: clear every live host's partition table so
        the closing gates (checksums, verify_all) read a healed fleet."""
        for name in self.hosts:
            if self.procs[name].poll() is None:
                try:
                    self.admin(name, "admin_partition", "heal_all",
                               timeout=10)
                except Exception:
                    pass

    def stop(self) -> None:
        for p in self.procs.values():
            if p.poll() is None:
                p.kill()
        if self.store_proc.poll() is None:
            self.store_proc.kill()
        for p in list(self.procs.values()) + [self.store_proc]:
            try:
                p.wait(timeout=10)
            except Exception:
                pass


def _wait_listening(port: int, proc: subprocess.Popen,
                    timeout: float = 30.0) -> None:
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if proc.poll() is not None:
            raise RuntimeError(
                f"process exited rc={proc.returncode} before listening")
        try:
            call(("127.0.0.1", port), ("ping",), timeout=2)
            return
        except Exception:
            time.sleep(0.05)
    raise TimeoutError(f"port {port} not serving after {timeout}s")


class WireBox:
    """One wire cluster seen through the FailoverManager/worker 'box'
    duck type: .cluster_name, .frontend, .stores, .route — all backed by
    sockets (the in-process Onebox surface, served remotely)."""

    def __init__(self, name: str, cluster: Cluster) -> None:
        from .client import RemoteCluster, RemoteStores

        self.cluster_name = name
        self.wire = cluster
        self.frontend = cluster.frontend(0)
        self.stores = RemoteStores(("127.0.0.1", cluster.store_port))
        self._remote = RemoteCluster(("127.0.0.1", cluster.store_port))

    def route(self, workflow_id: str):
        return self._remote.engine(workflow_id)

    # -- Onebox pump-surface shims (TaskPoller.drain compatibility): the
    # -- service hosts run their own pump threads, so a client-side pump
    # -- tick is just a short yield to let them progress
    def pump_once(self) -> int:
        time.sleep(0.05)
        return 0

    class _NoBacklog:
        @staticmethod
        def backlog() -> int:
            return 0

    matching = _NoBacklog()


class ClusterGroup:
    """A multi-cluster group of real wire clusters (two store servers,
    N service hosts each; replication/domain/cross-cluster consumers
    poll peers over sockets — the XDC deployment of
    docker-compose-multiclusters + development_xdc_cluster{0,1}.yaml).

    Exposes the same .active/.standby/.replicate* surface the in-process
    ReplicatedClusters offers, so FailoverManager runs against real
    processes unchanged — except replicate() here WAITS for the hosts'
    own pumps to drain (consumers run in the service hosts, not in this
    client)."""

    DRAIN_TIMEOUT_S = 30.0

    def __init__(self, clusters: Dict[str, Cluster]) -> None:
        from ..engine.cluster import ClusterMetadata

        self.clusters = clusters
        self.meta = ClusterMetadata(cluster_names=tuple(sorted(clusters)))
        self.boxes = {name: WireBox(name, c) for name, c in clusters.items()}

    @property
    def active(self) -> WireBox:
        return self.boxes["primary"]

    @property
    def standby(self) -> WireBox:
        return self.boxes["standby"]

    def register_global_domain(self, name: str,
                               retention_days: int = 1) -> str:
        """Register on the active side only; domain replication carries it
        to every peer (worker/replicator). Blocks until the peers have it."""
        domain_id = self.active.frontend.register_domain(
            name, retention_days=retention_days, is_active=True,
            clusters=self.meta.cluster_names, active_cluster="primary",
            failover_version=self.meta.initial_failover_version("primary"))
        deadline = time.monotonic() + self.DRAIN_TIMEOUT_S
        others = [b for n, b in self.boxes.items() if n != "primary"]
        while time.monotonic() < deadline:
            if all(self._has_domain(b, name) for b in others):
                return domain_id
            time.sleep(0.05)
        raise TimeoutError(f"domain {name} never replicated to peers")

    @staticmethod
    def _has_domain(box: WireBox, name: str) -> bool:
        try:
            box.stores.domain.by_name(name)
            return True
        except Exception:
            return False

    # -- drain waits (the hosts' leader pumps do the actual work) ----------

    def _wait_consumed(self, src: str, dst: str, queue: str,
                      ack_key: str) -> None:
        tail = self.boxes[src].stores.queue.size(queue)
        deadline = time.monotonic() + self.DRAIN_TIMEOUT_S
        while time.monotonic() < deadline:
            ack = self.boxes[dst].stores.queue.get_ack(ack_key, dst)
            if ack >= tail:
                return
            time.sleep(0.05)
        raise TimeoutError(
            f"{dst} consumed {ack}/{tail} of {src}'s {queue}")

    def replicate(self) -> int:
        from ..engine.replication import REPLICATION_QUEUE

        self._wait_consumed("primary", "standby", REPLICATION_QUEUE,
                            "repl-from:primary")
        return 0

    def replicate_reverse(self) -> int:
        from ..engine.replication import REPLICATION_QUEUE

        self._wait_consumed("standby", "primary", REPLICATION_QUEUE,
                            "repl-from:standby")
        return 0

    def replicate_domains(self) -> int:
        from ..engine.domainrepl import DOMAIN_REPLICATION_QUEUE

        self._wait_consumed("primary", "standby", DOMAIN_REPLICATION_QUEUE,
                            "domainrepl-from:primary")
        self._wait_consumed("standby", "primary", DOMAIN_REPLICATION_QUEUE,
                            "domainrepl-from:standby")
        return 0

    def stop(self) -> None:
        for c in self.clusters.values():
            c.stop()


def _role_env(env_extra, env_per_role, role: str, generic: str):
    """Compose one process's environment overlay: `env_extra` (every
    process) + the generic-role overlay ("host"/"store") + the exact
    role-name overlay (e.g. "host-1", "primary-host-0"), later layers
    winning. Loadgen uses the per-role seam to hand EACH host its own
    quota knobs (CADENCE_TPU_QUOTAS — a cluster RPS budget split across
    hosts because every host's token buckets are local)."""
    env = dict(env_extra or {})
    per = env_per_role or {}
    env.update(per.get(generic, {}))
    env.update(per.get(role, {}))
    return env


def launch_group(cluster_names=("primary", "standby"), num_hosts: int = 2,
                 num_shards: int = 8, hb_interval: float = 0.15,
                 ttl: float = 3.0, env_extra=None,
                 env_per_role=None, wal_dir: str = "") -> ClusterGroup:
    """Launch a multi-cluster group: per cluster one store server + N
    service hosts, every host configured with the peer clusters' store
    addresses (the cluster-group config) so its leader runs the inbound
    replication/domain/cross-cluster consumers against real sockets.

    `env_extra` lands in EVERY spawned process; `env_per_role` overlays
    it per role: keys are "store", "host", or an exact process name —
    here host names carry the cluster prefix ("primary-host-0").
    `wal_dir` gives each region's store server a WAL under it (one file
    per cluster name) — the region-failover scenario relaunches a
    kill -9'd region's store from its WAL for post-mortem verification."""
    store_ports = {name: free_port() for name in cluster_names}
    clusters: Dict[str, Cluster] = {}
    try:
        for name in cluster_names:
            peers = [f"{p}=127.0.0.1:{store_ports[p]}"
                     for p in cluster_names if p != name]
            clusters[name] = launch(
                num_hosts=num_hosts, num_shards=num_shards,
                hb_interval=hb_interval, ttl=ttl, cluster_name=name,
                store_port=store_ports[name], peer_specs=peers,
                wal=(os.path.join(wal_dir, f"{name}-store.wal")
                     if wal_dir else ""),
                env_extra=env_extra, env_per_role=env_per_role)
    except Exception:
        for c in clusters.values():
            c.stop()
        raise
    return ClusterGroup(clusters)


def launch(num_hosts: int = 2, num_shards: int = 8, wal: str = "",
           hb_interval: float = 0.15, ttl: float = 3.0,
           cluster_name: str = "primary", store_port: int = 0,
           peer_specs=(), env_extra=None, env_per_role=None) -> Cluster:
    """Spawn the store server + `num_hosts` service hosts as OS processes.
    The TTL must comfortably exceed worst-case heartbeat jitter (a
    GIL-starved beat thread on a loaded host): a too-tight TTL makes the
    failure detector flap, and every flap is a spurious steal — safe
    (fencing holds) but churny. Test-sized here; production stretches both.
    `env_extra` lands in every spawned process — the chaos soak sets
    CADENCE_TPU_CHAOS / CADENCE_TPU_STORE_FAULTS through it.
    `env_per_role` overlays env_extra for individual processes: keys are
    "store", "host" (every service host), or an exact host name
    ("host-0"; with peer_specs, "<cluster>-host-0") — the loadgen hands
    each host its own CADENCE_TPU_QUOTAS knobs through this seam."""
    base_env = dict(os.environ)
    base_env.setdefault("JAX_PLATFORMS", "cpu")  # control-plane processes
    repo = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    base_env["PYTHONPATH"] = repo + os.pathsep + base_env.get(
        "PYTHONPATH", "")

    store_port = store_port or free_port()
    store_cmd = [sys.executable, "-m", "cadence_tpu.rpc.storeserver",
                 "--port", str(store_port)]
    if wal:
        store_cmd += ["--wal", wal]
    store_env = dict(base_env)
    store_env.update(_role_env(env_extra, env_per_role, "store", "store"))
    store_proc = subprocess.Popen(store_cmd, env=store_env)
    _wait_listening(store_port, store_proc)

    hosts: Dict[str, int] = {}
    procs: Dict[str, subprocess.Popen] = {}
    http_ports: Dict[str, int] = {}

    def spawn_host(name: str):
        """One service-host process against this cluster's store (shared
        by launch's initial fleet and Cluster.add_host's rebalance)."""
        port = free_port()
        http_port = free_port()
        cmd = [sys.executable, "-m", "cadence_tpu.rpc.server",
               "--name", name, "--port", str(port),
               "--store", f"127.0.0.1:{store_port}",
               "--num-shards", str(num_shards),
               "--hb-interval", str(hb_interval), "--ttl", str(ttl),
               "--cluster-name", cluster_name,
               "--http-port", str(http_port)]
        for spec in peer_specs:
            cmd += ["--peer", spec]
        host_env = dict(base_env)
        host_env.update(_role_env(env_extra, env_per_role, name, "host"))
        return port, http_port, subprocess.Popen(cmd, env=host_env)

    for i in range(num_hosts):
        name = f"{cluster_name}-host-{i}" if peer_specs else f"host-{i}"
        hosts[name], http_ports[name], procs[name] = spawn_host(name)
    for name, port in hosts.items():
        _wait_listening(port, procs[name])
    # let every host's RING converge on the full peer set before handing
    # the cluster out (a host still on a single-member ring believes it
    # owns every shard → spurious steal churn on first requests)
    deadline = time.monotonic() + 10
    want = set(hosts)
    while time.monotonic() < deadline:
        views = []
        for name, port in hosts.items():
            try:
                ping = call(("127.0.0.1", port), ("ping",), timeout=2)
                views.append(set(ping[3]))
            except Exception:
                views.append(set())
        if all(v >= want for v in views):
            break
        time.sleep(0.05)
    return Cluster(store_port, hosts, procs, store_proc,
                   http_ports=http_ports, spawn_host=spawn_host, wal=wal,
                   store_cmd=store_cmd, store_env=store_env)
