"""Service host: one history/matching/frontend process of a real cluster.

Reference: cmd/server/cadence/server.go:271-278 builds the four roles from
one binary; host/onebox.go runs them in-process for tests. This module is
the PROCESS-boundary deployment: each host runs

- a ShardController over the live-peer hashring (shards it owns get real
  engines; the rest raise ShardNotOwnedError and the router redirects),
- queue processors pumping its shards' transfer/timer queues,
- a matching engine for the task lists the ring assigns to it,
- a frontend serving any client (cross-host work forwards over the wire),

all against the store-server process (fenced writes evaluate THERE, so a
deposed owner's writes fail no matter what it believes about liveness —
the cross-host range-ID fence, shard/context.go:586-700).

Membership: each host heartbeats the store server and rebuilds its ring
from the live-peer set every tick; a host that stops beating (killed,
partitioned, paused) is dropped after the TTL and its shards are stolen.

Run: python -m cadence_tpu.rpc.server --name host-0 --port P \
         --store HOST:PORT [--num-shards 8] [--hb-interval 0.2] [--ttl 1.0]
"""
from __future__ import annotations

import argparse
import os
import socketserver
import threading
import time
from contextlib import nullcontext
from typing import Dict, Optional, Tuple

from ..engine.controller import ShardController, ShardNotOwnedError
from ..engine.crosscluster import CrossClusterProcessor
from ..engine.frontend import Frontend
from ..engine.history_engine import HistoryEngine
from ..engine.matching import MatchingEngine
from ..engine.membership import HashRing
from ..engine.queues import QueueProcessors
from ..loadgen.slo import BurnRateEvaluator, BurnTarget
from ..utils import deadline as deadline_mod
from ..utils import flightrecorder
from ..utils import hostprof as hostprof_mod
from ..utils import timeseries as timeseries_mod
from ..utils import tracing
from ..utils.circuitbreaker import (
    BreakerRegistry,
    CircuitOpenError,
    ServiceBusy,
)
from ..utils.clock import RealTimeSource
from ..utils.deadline import DeadlineExceeded
from . import chaos as chaos_mod
from .client import RemoteEngine, RemoteMatching, RemoteStores
from .wire import recv_frame, send_frame, verify_hello

#: server-side p99 latency ceiling (ms) the burn-rate evaluator watches
#: over the frontend start/signal histograms
ENV_SLO_P99_MS = "CADENCE_TPU_SLO_P99_MS"


def _slo_p99_s() -> float:
    try:
        return max(0.001,
                   float(os.environ.get(ENV_SLO_P99_MS, "500")) / 1000.0)
    except ValueError:
        return 0.5


class RoutedMatching:
    """Task-list-ownership router: calls for lists the ring assigns to
    this host run on the local MatchingEngine; the rest forward to the
    owner (client/matching routing by task list)."""

    #: method name → index of the task-list argument in *args
    _TL_ARG = {
        "add_decision_task": 1, "add_activity_task": 1, "add_query_task": 1,
        "poll_and_wait_decision": 1, "poll_and_wait_activity": 1,
        "poll_for_decision_task": 1, "poll_for_activity_task": 1,
        "describe_task_list": 1,
    }

    def __init__(self, host: "ServiceHost") -> None:
        self._host = host
        self.local = MatchingEngine(host.stores, config=host.config)

    def _forward(self, task_list: str) -> Optional[RemoteMatching]:
        owner, address = self._host.tasklist_owner(task_list)
        if owner == self._host.name:
            return None
        return RemoteMatching(address, metrics=self._host.metrics,
                              breakers=self._host.breakers,
                              retry_policy=self._host.retry_policy)

    def __getattr__(self, method: str):
        if method.startswith("_"):
            raise AttributeError(method)
        local = self.local
        impl = getattr(local, method)
        tl_index = self._TL_ARG.get(method)

        if tl_index is None and method in ("requeue_task", "complete_task"):
            def invoke(task, task_type):
                target = self._forward(task.task_list)
                fn = getattr(target, method) if target else getattr(local, method)
                return fn(task, task_type)
            return invoke
        if tl_index is None:
            return impl

        def invoke(*args, **kwargs):
            target = self._forward(args[tl_index])
            return (getattr(target, method) if target else impl)(*args, **kwargs)

        return invoke


class _XdcConsumer:
    """One peer cluster's inbound machinery: history replication, domain
    metadata, and the two cross-cluster task directions."""

    def __init__(self, name, cluster, repl, domain, xc) -> None:
        self.name = name
        self.cluster = cluster
        self.repl = repl
        self.domain = domain
        self.xc = xc


class _WireCrossClusterProcessor(CrossClusterProcessor):
    """CrossClusterProcessor whose RESULT leg routes by the source
    domain's CURRENT active cluster (looked up in the local, replicated
    domain table): locally-active sources apply through the ring;
    remotely-active ones go back through the peer's engine_routed door.
    The reference's cross_cluster_task_processor responds through the
    source cluster's history client the same way."""

    def __init__(self, source_stores, target_router, local_cluster,
                 target_stores, host: "ServiceHost") -> None:
        super().__init__(source_stores, target_router, None, local_cluster,
                         target_stores=target_stores)
        self._host = host

    def _source_engine(self, task):
        host = self._host
        active = None
        try:
            active = host.stores.domain.by_id(
                task.source_domain_id).active_cluster
        except Exception:
            pass
        if active is None or active == host.cluster_name:
            return host.route(task.source_workflow_id)
        consumer = next((c for c in host._xdc_consumers
                         if c.name == active), None)
        if consumer is None:  # unknown cluster: try any peer
            consumer = host._xdc_consumers[0]
        return consumer.cluster.engine(task.source_workflow_id)


class ServiceHost(socketserver.ThreadingTCPServer):
    allow_reuse_address = True
    daemon_threads = True

    def __init__(self, name: str, address: Tuple[str, int],
                 store_address: Tuple[str, int], num_shards: int,
                 hb_interval: float = 0.15, ttl: float = 3.0,
                 pump_interval: float = 0.05,
                 cluster_name: str = "primary",
                 peers: Optional[Dict[str, Tuple[str, int]]] = None,
                 advertise_host: str = "127.0.0.1",
                 http_port: int = 0) -> None:
        super().__init__(address, _Handler)
        from ..utils import compile_cache
        from ..utils.dynamicconfig import DynamicConfig
        from ..utils.metrics import MetricsRegistry

        # device rebuilds (reset/recovery) jit the replay kernel; without
        # the persistent cache EVERY host process pays that compile the
        # first time a reset routes to it — long enough to blow the
        # caller's socket timeout
        compile_cache.enable()
        self.name = name
        self.port = address[1]
        #: the address peers must DIAL to reach this host (loopback only
        #: works single-machine; containers advertise their service name)
        self.advertise_host = advertise_host
        self.num_shards = num_shards
        self.hb_interval = hb_interval
        self.ttl = ttl
        self.cluster_name = cluster_name
        #: peer cluster name → its STORE server address (the cluster-group
        #: config, development_xdc_cluster0.yaml:71-94 analog)
        self.peers = dict(peers or {})
        self.clock = RealTimeSource()
        self.config = DynamicConfig()
        self.metrics = MetricsRegistry()
        #: per-target circuit breakers shared by EVERY outbound client this
        #: host creates (store, peer engines, matching forwards) — breaker
        #: state gauges land on this host's /metrics
        from ..utils import dynamicconfig as dc
        from .client import retry_policy_from_config
        self.breakers = BreakerRegistry(
            metrics=self.metrics,
            failure_threshold=int(
                self.config.get(dc.KEY_RPC_BREAKER_FAILURE_THRESHOLD)),
            reset_timeout_s=float(
                self.config.get(dc.KEY_RPC_BREAKER_RESET_TIMEOUT_S)))
        self.retry_policy = retry_policy_from_config(self.config)
        self.stores = RemoteStores(store_address, metrics=self.metrics,
                                   breakers=self.breakers,
                                   retry_policy=self.retry_policy)
        # pre-register the resilience counters so /metrics always exposes
        # the names (scraped as zero before the first retry/shed/expiry)
        for scope_name, metric in (("rpc.client", "retries"),
                                   ("rpc.client", "breaker-rejected"),
                                   ("rpc.client", "deadline-expired"),
                                   ("rpc.server",
                                    "deadline-expired-rejections"),
                                   ("rpc.circuitbreaker", "transitions")):
            self.metrics.inc(scope_name, metric, 0)
        # resident-state cache series likewise pre-registered: scrapes
        # show tpu.resident/* as zero before the first verify touches it
        from ..utils import metrics as cm
        for metric in (cm.M_CACHE_HITS, cm.M_RESIDENT_SUFFIX_HITS,
                       cm.M_CACHE_MISSES, cm.M_CACHE_EVICTIONS,
                       cm.M_CACHE_INVALIDATIONS,
                       cm.M_RESIDENT_EVENTS_APPENDED,
                       cm.M_RESIDENT_WIDENED, cm.M_RESIDENT_NARROWED):
            self.metrics.inc(cm.SCOPE_TPU_RESIDENT, metric, 0)
        for gauge in (cm.M_RESIDENT_BYTES, cm.M_RESIDENT_ENTRIES,
                      cm.M_RESIDENT_BUDGET_BYTES):
            self.metrics.gauge(cm.SCOPE_TPU_RESIDENT, gauge, 0.0)
        # native-encoder series: the availability gauge answers "does
        # THIS process have the compiled fast path" on every scrape.
        # Boot publishes from the build-cache PROBE (file-hash check,
        # never a compiler run — a fresh box must not block startup on
        # g++); the first wirec pack through this registry re-publishes
        # the live value. Pack counters start visible at zero.
        from ..native import build as native_build
        self.metrics.gauge(cm.SCOPE_TPU_NATIVE, cm.M_NATIVE_AVAILABLE,
                           1.0 if native_build.wirec_cached() else 0.0)
        self.metrics.inc(cm.SCOPE_TPU_NATIVE, cm.M_NATIVE_PACKS, 0)
        self.metrics.inc(cm.SCOPE_TPU_NATIVE, cm.M_NATIVE_PY_PACKS, 0)
        # mesh-aware executor series likewise pre-registered, with the
        # per-device labels the CADENCE_TPU_MESH_DEVICES knob implies
        # (the knob is parsed WITHOUT touching a JAX backend; "all"
        # resolves at first dispatch, so only dev0 pre-registers then)
        from ..parallel.mesh import mesh_devices_requested
        n_mesh = mesh_devices_requested() or 1
        self.metrics.inc(cm.SCOPE_TPU_EXECUTOR, cm.M_EXEC_CHUNKS, 0)
        self.metrics.gauge(cm.SCOPE_TPU_EXECUTOR, cm.M_EXEC_DEVICE_BUSY,
                           0.0)
        for d in range(n_mesh):
            self.metrics.inc(
                cm.SCOPE_TPU_EXECUTOR,
                cm.device_metric(cm.M_EXEC_CHUNKS, d), 0)
            self.metrics.inc(
                cm.SCOPE_TPU_EXECUTOR,
                cm.device_metric(cm.M_EXEC_ROWS, d), 0)
            self.metrics.gauge(
                cm.SCOPE_TPU_EXECUTOR,
                cm.device_metric(cm.M_EXEC_DEVICE_BUSY, d), 0.0)
        # per-host quota knobs (common/quotas seat): the env var is the
        # subprocess-cluster path (rpc/cluster.launch env_per_role hands
        # each host its own spec — a cluster-wide RPS budget is split
        # across hosts because each host's buckets are local); values
        # land in dynamicconfig, so the frontend's live closures pick
        # them up and later operator config.set updates still win
        from ..utils import quotas as quotas_mod
        quota_spec = os.environ.get(quotas_mod.QUOTAS_ENV, "")
        if quota_spec:
            g_rps, g_burst, domain_rps = quotas_mod.parse_quota_spec(
                quota_spec)
            if g_rps:
                self.config.set(dc.KEY_FRONTEND_RPS, g_rps)
            if g_burst:
                self.config.set(dc.KEY_FRONTEND_BURST, g_burst)
            for domain, rps in domain_rps.items():
                self.config.set(dc.KEY_FRONTEND_DOMAIN_RPS, rps,
                                domain=domain)
        # admission-control series pre-registered: a scrape shows
        # quotas/admitted + quotas/shed as zero before the first request
        # (per-domain series appear as domains take traffic)
        self.metrics.inc(cm.SCOPE_QUOTAS, cm.M_QUOTA_ADMITTED, 0)
        self.metrics.inc(cm.SCOPE_QUOTAS, cm.M_QUOTA_SHED, 0)
        # membership/controller/partition witnesses pre-registered: a
        # chaos campaign must distinguish "no flap observed" and "no
        # partition enforced" from "series missing" on every host
        self.metrics.inc(cm.SCOPE_MEMBERSHIP, cm.M_RING_DROPS, 0)
        self.metrics.inc(cm.SCOPE_MEMBERSHIP, cm.M_RING_JOINS, 0)
        self.metrics.gauge(cm.SCOPE_MEMBERSHIP, cm.M_RING_GENERATION, 0.0)
        self.metrics.inc(cm.SCOPE_CONTROLLER, cm.M_FENCED_EVICTIONS, 0)
        self.metrics.inc(chaos_mod.SCOPE_PARTITION,
                         chaos_mod.M_PART_BLOCKED_SENDS, 0)
        self.metrics.gauge(chaos_mod.SCOPE_PARTITION,
                           chaos_mod.M_PART_ACTIVE, 0.0)
        # the process partition table reports into THIS host's registry
        # (scrapes and admin_metrics see what this host enforces)
        chaos_mod.partitions().registry = self.metrics
        # device-serving tier series pre-registered (tpu.serving/*): the
        # parity-divergence counter in particular must ALWAYS scrape — a
        # missing series and "zero divergences" must be distinguishable
        for metric in (cm.M_SERVING_TXNS, cm.M_SERVING_LAUNCHES,
                       cm.M_SERVING_COALESCED, cm.M_SERVING_DIVERGENCE,
                       cm.M_SERVING_EXACT, cm.M_SERVING_SUFFIX,
                       cm.M_SERVING_COLD, cm.M_SERVING_BYPASSED,
                       cm.M_SERVING_REQUEUED, cm.M_SERVING_REJECTED):
            self.metrics.inc(cm.SCOPE_TPU_SERVING, metric, 0)
        self.metrics.gauge(cm.SCOPE_TPU_SERVING, cm.M_SERVING_QUEUE_DEPTH,
                           0.0)
        # snapshot-tier series pre-registered (tpu.snapshot/*): a scrape
        # must distinguish "no torn snapshots" from "series missing",
        # same contract as the serving divergence counter
        for metric in (cm.M_SNAP_WRITES, cm.M_SNAP_CHECKSUM_SKIPS,
                       cm.M_SNAP_HYDRATES, cm.M_SNAP_IGNORED_STALE,
                       cm.M_SNAP_IGNORED_TORN):
            self.metrics.inc(cm.SCOPE_TPU_SNAPSHOT, metric, 0)
        for gauge in (cm.M_SNAP_ENTRIES, cm.M_SNAP_BYTES):
            self.metrics.gauge(cm.SCOPE_TPU_SNAPSHOT, gauge, 0.0)
        # the tier itself (engine/serving.py): CADENCE_TPU_SERVING=1
        # builds this host's TPUReplayEngine over the REMOTE stores and
        # hands every engine a shared scheduler — committed transactions
        # micro-batch into from-state launches; default off (the tier is
        # a deployment choice, and verify/rebuild work without it)
        from ..engine import serving as serving_mod
        self.serving = None
        self.tpu = None
        self.migration = None
        if serving_mod.enabled():
            from ..engine.tpu_engine import TPUReplayEngine
            tpu = TPUReplayEngine(self.stores, self.config.payload_layout())
            tpu.metrics = self.metrics
            self.tpu = tpu
            self.serving = tpu.serving_scheduler()
            # live HBM state migration (engine/migration.py): shard
            # movement snapshots this host's resident rows out and
            # hydrates acquired shards from the SHARED snapshot store
            # (which lives in the store-server process — records written
            # by any host are immediately visible to every peer); wired
            # to the controller's membership hooks below
            from ..engine.migration import MigrationManager
            self.migration = MigrationManager(name, num_shards, tpu,
                                              registry=self.metrics)
            for metric in (cm.M_MIG_OUT, cm.M_MIG_OUT_SKIPPED,
                           cm.M_MIG_EVICTED, cm.M_MIG_IN, cm.M_MIG_COLD,
                           cm.M_MIG_YOUNG, cm.M_MIG_STALE,
                           cm.M_MIG_SUFFIX_EVENTS,
                           cm.M_MIG_DIVERGENCE, cm.M_MIG_UNSTABLE):
                self.metrics.inc(cm.SCOPE_TPU_MIGRATION, metric, 0)
        # boot warm-up: the first live drain window must never pay an
        # XLA compile (a mid-window compile stalls the drain → folds
        # outgrow the warmed buckets → compile snowball; the exact
        # failure serving_scenario's in-process warm() exists for) —
        # background thread so the host serves immediately, flushes
        # that race the warm just pay the compile they would have
        # anyway; `serving_warmed` is surfaced in the admin_cluster doc
        # so deploys/scenarios can hold traffic until the fleet is hot
        self.serving_warmed = self.serving is None
        if self.serving is not None and serving_mod.warm_on_boot():
            def _warm_serving():
                try:
                    self.serving.warm(
                        e_shapes=serving_mod.warm_event_shapes())
                except Exception:
                    pass
                self.serving_warmed = True
            threading.Thread(target=_warm_serving, daemon=True,
                             name="cadence-serving-warm").start()
        elif self.serving is not None:
            self.serving_warmed = True
        # wire chaos can also arrive via dynamicconfig (the env var is the
        # subprocess path; an operator override here wins)
        chaos_spec = self.config.get(dc.KEY_WIRE_CHAOS)
        if chaos_spec:
            chaos_mod.install(chaos_mod.parse_spec(chaos_spec))
        # durability crashpoints ride the same contract (env var for
        # subprocesses, dynamicconfig for operator overrides)
        crash_spec = self.config.get(dc.KEY_CRASHPOINT)
        if crash_spec:
            from ..engine import crashpoints
            crashpoints.install(crashpoints.parse_spec(crash_spec))
        # -- cluster telemetry plane ----------------------------------------
        # the process-global flight recorder counts onto THIS host's
        # registry (one host per process in production; in-process test
        # hosts share the ring, which is exactly the interleaved timeline
        # a post-mortem wants); sampler + profiler objects always exist
        # (the admin ops and scrape endpoints need them) but their
        # threads only start in start(), each gated on its env knob
        flightrecorder.DEFAULT_RECORDER.metrics = self.metrics
        self.metrics.inc(cm.SCOPE_FLIGHTREC, "events", 0)
        self.metrics.inc(cm.SCOPE_FLIGHTREC, "dumps", 0)
        self.timeseries = timeseries_mod.TimeSeriesSampler(self.metrics)
        if self.serving is not None:
            serving_ref = self.serving
            self.timeseries.set_capacity(
                cm.SCOPE_TPU_SERVING, cm.M_SERVING_QUEUE_DEPTH,
                lambda: serving_ref.max_queue)
        self.hostprof = hostprof_mod.HostProfiler(self.metrics)
        for gauge in ("samples", "gil-contention", "attributed-share",
                      "threads"):
            self.metrics.gauge(cm.SCOPE_HOSTPROF, gauge, 0.0)
        for gauge in ("windows", "samples", "utilization"):
            self.metrics.gauge(cm.SCOPE_TIMESERIES, gauge, 0.0)
        # server-side SLO: frontend start/signal latency p99 under the
        # CADENCE_TPU_SLO_P99_MS ceiling; evaluated on every sampler tick
        # so the burn gauges land inside the NEXT /timeseries window and
        # `admin top` reads them fleet-wide with no extra endpoint
        slo_s = _slo_p99_s()
        self.burn = BurnRateEvaluator(
            self.timeseries,
            [BurnTarget("frontend-start", cm.SCOPE_FRONTEND_START,
                        cm.M_LATENCY, slo_s),
             BurnTarget("frontend-signal", cm.SCOPE_FRONTEND_SIGNAL,
                        cm.M_LATENCY, slo_s)],
            registry=self.metrics)
        self.timeseries.on_sample = lambda window: self.burn.evaluate()
        self.tracer = tracing.DEFAULT_TRACER
        #: HTTP scrape surface (/metrics, /health, /traces, /timeseries,
        #: /hostprof, /flightrec): bound in __init__ so the port is known
        #: before start(); 0 = ephemeral
        from ..utils.scrape import ObservabilityHTTPServer
        self.scrape = ObservabilityHTTPServer(
            self.metrics, health_fn=self._health, tracer=self.tracer,
            address=(address[0], http_port),
            timeseries_fn=self.timeseries_doc,
            hostprof_fn=self.hostprof_doc,
            flightrec_fn=self.flightrec_doc)
        #: shared across every engine this host creates (multi-cluster
        #: replication publish seam)
        self._publisher_holder: Dict[str, object] = {"pub": None}
        #: name → (host, port) of every live peer (incl. self)
        self._peer_addresses: Dict[str, Tuple[str, int]] = {
            name: (advertise_host, address[1])}
        self.ring = HashRing([name])
        self.controller = ShardController(name, num_shards, self.stores,
                                          self.ring, self.clock,
                                          engine_factory=self._make_engine)
        self.controller.metrics = self.metrics
        if self.migration is not None:
            self.controller.on_shards_released = \
                self.migration.shards_released
            self.controller.on_shards_acquired = \
                self.migration.shards_acquired
        self.matching = RoutedMatching(self)
        self.frontend = Frontend(self.stores, self.matching, self.route,
                                 config=self.config, metrics=self.metrics,
                                 time_source=self.clock,
                                 cluster_name=cluster_name)
        self.processors = QueueProcessors(self.controller, self.matching,
                                          self.stores, self.clock,
                                          router=self.route,
                                          metrics=self.metrics,
                                          config=self.config,
                                          cluster_name=cluster_name)
        self._xdc_consumers = []
        if self.peers:
            self._wire_cluster_group()
        # the production pump is the N-worker pool (per-domain fairness,
        # redispatch, contiguous acks — engine/tasks.py); store round-trips
        # are I/O the workers overlap
        from ..engine.tasks import TaskScheduler
        self.scheduler = TaskScheduler(num_workers=4)
        self._stop = threading.Event()
        self._beat_thread = threading.Thread(target=self._beat_loop,
                                             daemon=True,
                                             name="cadence-membership-beat")
        self._pump_interval = pump_interval
        self._pump_thread = threading.Thread(target=self._pump_loop,
                                             daemon=True,
                                             name="cadence-queue-pump")

    # -- engines -----------------------------------------------------------

    def _make_engine(self, shard) -> HistoryEngine:
        engine = HistoryEngine(shard, self.stores, self.clock)
        engine.metrics = self.metrics
        engine.config = self.config
        engine.replication_publisher_holder = self._publisher_holder
        engine.serving = self.serving
        return engine

    # -- cluster group (XDC over the wire) ---------------------------------

    def _wire_cluster_group(self) -> None:
        """Compose this host into its cluster group: outbound — engines
        publish committed batches and domain mutations onto the LOCAL
        store's replication queues; inbound — per-peer consumers poll the
        PEER'S store server over sockets and apply here (the remote-poller
        shape of replication/task_fetcher.go + worker/replicator). Ack
        levels persist in the local store, so the pumps survive host death
        and leadership moves (persistence/queue.go UpdateAckLevel)."""
        from ..engine.crosscluster import CrossClusterPublisher
        from ..engine.domainrepl import (
            DomainReplicationProcessor,
            DomainReplicationPublisher,
        )
        from ..engine.replication import (
            HistoryReplicator,
            ReplicationPublisher,
            ReplicationTaskProcessor,
        )
        from .client import RemoteCluster

        pub = ReplicationPublisher(self.stores)
        self._publisher_holder["pub"] = pub
        self.frontend.domain_replication_publisher = (
            DomainReplicationPublisher(self.stores))
        self.processors.cross_cluster_publisher = (
            CrossClusterPublisher(self.stores))
        # snapshot-shipping replication: every record this host's
        # post-append policy writes also rides the outbound replication
        # stream, so standby regions keep warm hydration sources without
        # ever replaying full histories (tentpole 2, ROADMAP item 2)
        if self.tpu is not None:
            cluster = self.cluster_name
            self.tpu.snapshotter().shipper = (
                lambda rec: pub.publish_snapshot(rec, cluster))
        # replication series pre-registered (replication.task-processor/*):
        # the device-parity divergence counter and the DLQ depth gauge in
        # particular must ALWAYS scrape — "zero divergence" and "series
        # missing" must be distinguishable (same contract as tpu.serving)
        from ..utils import metrics as cm
        for metric in (cm.M_REPL_APPLIED, cm.M_REPL_DEDUPED,
                       cm.M_REPL_RESENT, cm.M_REPL_DLQ, cm.M_REPL_REDRIVEN,
                       cm.M_REPL_DEVICE_APPLIED,
                       cm.M_REPL_DEVICE_SUFFIX_EVENTS,
                       cm.M_REPL_DEVICE_COLD, cm.M_REPL_DEVICE_STALE,
                       cm.M_REPL_DEVICE_DIVERGENCE,
                       cm.M_REPL_DEVICE_UNSTABLE,
                       cm.M_REPL_SNAP_SHIPPED, cm.M_REPL_SNAP_INSTALLED,
                       cm.M_REPL_SNAP_IGNORED_TORN,
                       cm.M_REPL_SNAP_IGNORED_STALE,
                       cm.M_REPL_SNAP_IGNORED_FOREIGN,
                       cm.M_REPL_BP_SHED, cm.M_REPL_BP_DEFERRED,
                       cm.M_DOMREPL_APPLIED, cm.M_DOMREPL_STALE_REJECTED,
                       cm.M_DOMREPL_DUPLICATE):
            self.metrics.inc(cm.SCOPE_REPLICATION, metric, 0)
        self.metrics.gauge(cm.SCOPE_REPLICATION, cm.M_REPL_DLQ_DEPTH, 0.0)

        for peer_name, store_addr in self.peers.items():
            peer = RemoteCluster(store_addr, peer_ttl=self.ttl,
                                 metrics=self.metrics,
                                 breakers=self.breakers,
                                 retry_policy=self.retry_policy)

            def read_peer_history(domain_id, workflow_id, run_id,
                                  from_id, to_id, _peer=peer):
                batches = _peer.stores.history.as_history_batches(
                    domain_id, workflow_id, run_id)
                return [b for b in batches
                        if from_id <= b.events[0].id < to_id]

            repl = ReplicationTaskProcessor(
                HistoryReplicator(self.stores),
                ReplicationPublisher(peer.stores), self.stores,
                source_history_reader=read_peer_history,
                tpu=self.tpu)
            repl.metrics = self.metrics
            domain = DomainReplicationProcessor(peer.stores, self.stores,
                                                self.cluster_name)
            domain.metrics = self.metrics
            domain.on_applied = self._on_domain_replicated
            xc_peer = _WireCrossClusterProcessor(
                peer.stores, self.route, self.cluster_name,
                target_stores=self.stores, host=self)
            xc_self = _WireCrossClusterProcessor(
                self.stores, self.route, self.cluster_name,
                target_stores=self.stores, host=self)
            self._xdc_consumers.append(
                _XdcConsumer(peer_name, peer, repl, domain,
                             (xc_peer, xc_self)))

    def _on_domain_replicated(self, task, became_active: bool) -> None:
        """Standby promotion: a replicated flip that makes a domain active
        HERE regenerates its outstanding tasks from mutable state (the
        failover_watcher → RefreshTasks path; without it, pre-failover
        pending work never runs on the new active side)."""
        if not became_active:
            return
        try:
            from ..engine.task_refresher import sweep_refresh
            sweep_refresh(self.stores, self.route, task.domain_id)
        except Exception:
            from ..utils.log import DEFAULT_LOGGER
            DEFAULT_LOGGER.error("promotion task refresh failed",
                                 component="xdc", domain=task.name)
        # warm promotion: hydrate THIS host's shards from shipped
        # snapshots so the first post-flip transactions land on resident
        # rows (peers hydrate via the admin_prehydrate wire op — only
        # the leader sees the replicated flip)
        if self.migration is not None:
            try:
                self.migration.hydrate_shards(self.controller.owned_shards())
            except Exception:
                from ..utils.log import DEFAULT_LOGGER
                DEFAULT_LOGGER.error("promotion hydration failed",
                                     component="xdc", domain=task.name)

    def _pump_xdc(self) -> None:
        """One inbound-replication tick. Leader-gated: the host owning
        shard 0 runs the cluster's consumers (leadership follows the ring;
        persisted acks make handoff seamless). Ack levels load before and
        persist after each pass, monotonic under leadership flaps."""
        if 0 not in self.controller.owned_shards():
            return
        me = self.cluster_name
        for c in self._xdc_consumers:
            q = self.stores.queue
            try:
                ack_key = f"repl-from:{c.name}"
                c.repl.ack_index = max(c.repl.ack_index,
                                       q.get_ack(ack_key, me))
                if c.repl.process_once():
                    q.set_ack(ack_key, me, c.repl.ack_index - 1)
            except Exception:
                pass  # peer briefly unreachable; next tick retries
            try:
                dkey = f"domainrepl-from:{c.name}"
                c.domain._cursor = max(c.domain._cursor,
                                       q.get_ack(dkey, me))
                c.domain.process_once()
                if c.domain._cursor > 0:
                    q.set_ack(dkey, me, c.domain._cursor - 1)
            except Exception:
                pass
            for tag, xc in (("peer", c.xc[0]), ("self", c.xc[1])):
                try:
                    xkey = f"xc-from:{c.name}:{tag}"
                    xc._cursor = max(xc._cursor, q.get_ack(xkey, me))
                    xc.process_once()
                    if xc._cursor > 0:
                        q.set_ack(xkey, me, xc._cursor - 1)
                except Exception:
                    pass

    def route(self, workflow_id: str):
        """History router: local engine when this host owns the shard,
        RemoteEngine to the owner otherwise (SURVEY §3.1 process boundary)."""
        try:
            return self.controller.engine_for_workflow(workflow_id)
        except ShardNotOwnedError:
            owner = self.ring.lookup(
                f"shard-{self.controller.shard_for(workflow_id)}")
            address = self._peer_addresses.get(owner)
            if address is None:
                raise
            return RemoteEngine(address, workflow_id, metrics=self.metrics,
                                breakers=self.breakers,
                                retry_policy=self.retry_policy)

    def tasklist_owner(self, task_list: str) -> Tuple[str, Tuple[str, int]]:
        owner = self.ring.lookup(f"tasklist-{task_list}")
        return owner, self._peer_addresses.get(
            owner, (self.advertise_host, self.port))

    # -- cluster rollup (the admin_cluster wire op body) --------------------

    def cluster_doc(self, detail: bool = False) -> Dict[str, object]:
        """Per-host shard ownership + device-tier occupancy: what the
        `admin cluster` CLI verb and the multi-host scenarios roll up
        across every live host. `detail` adds each resident row's
        canonical payload CRC32 + branch + content address — the
        byte-parity surface the planned-rebalance gate compares against
        the oracle after a migration."""
        doc: Dict[str, object] = {
            "name": self.name,
            "cluster": self.cluster_name,
            "num_shards": self.num_shards,
            "owned_shards": sorted(self.controller.owned_shards()),
            "assigned_shards": sorted(self.controller.assigned_shards()),
            "ring": sorted(self.ring.members()),
            "serving": (self.serving.stats()
                        if self.serving is not None else None),
            "serving_warmed": bool(self.serving_warmed),
            "resident": (self.tpu.resident.stats()
                         if self.tpu is not None else None),
            "migration": (self.migration.stats()
                          if self.migration is not None else None),
        }
        if detail and self.tpu is not None:
            from ..engine.migration import resident_row_checksums
            doc["resident_rows"] = resident_row_checksums(
                self.tpu.resident)
        return doc

    # -- telemetry docs (scrape endpoints + the admin_* wire ops) ----------

    def timeseries_doc(self, last_n: Optional[int] = 120) -> Dict[str, object]:
        """The GET /timeseries body: the ring windows plus the current
        burn-rate verdict (evaluated fresh, unpublished — the published
        gauges already ride the windows with one-tick lag)."""
        doc = self.timeseries.doc(last_n)
        doc["host"] = self.name
        doc["slo"] = self.burn.evaluate(publish=False)
        return doc

    def hostprof_doc(self, duration_s: float = 0.0) -> Dict[str, object]:
        """The GET /hostprof body. With the profiler thread running the
        rollup is free; a host running with CADENCE_TPU_HOSTPROF=0 can
        still be burst-profiled by passing duration_s (the wire op's
        knob)."""
        prof = self.hostprof
        if duration_s > 0 and (prof._thread is None
                               or not prof._thread.is_alive()):
            deadline = time.monotonic() + duration_s
            while True:
                prof.sample_once()
                if time.monotonic() >= deadline:
                    break
                time.sleep(prof.period_s)
        doc = prof.rollup()
        doc["host"] = self.name
        return doc

    def flightrec_doc(self, last_n: int = 200) -> Dict[str, object]:
        recorder = flightrecorder.DEFAULT_RECORDER
        return {"host": self.name, "stats": recorder.stats(),
                "events": recorder.snapshot(last_n)}

    # -- health (the /health probe body) -----------------------------------

    def _health(self) -> Dict[str, object]:
        return {"status": "ok", "name": self.name,
                "cluster": self.cluster_name,
                "owned_shards": sorted(self.controller.owned_shards()),
                "ring": sorted(self.ring.members())}

    # -- membership --------------------------------------------------------

    def _beat_loop(self) -> None:
        while not self._stop.wait(self.hb_interval):
            try:
                self.refresh_membership()
            except Exception:
                continue  # store server briefly unreachable: keep beating

    def refresh_membership(self) -> None:
        self.stores.heartbeat(self.name, self.port, self.advertise_host)
        peers = self.stores.peers(self.ttl)
        names = {entry[0] for entry in peers}
        # peers carry their ADVERTISED host in the heartbeat table (old
        # 2-tuple servers imply loopback)
        self._peer_addresses = {
            entry[0]: ((entry[2], entry[1]) if len(entry) > 2
                       else ("127.0.0.1", entry[1]))
            for entry in peers}
        self._peer_addresses.setdefault(
            self.name, (self.advertise_host, self.port))
        current = set(self.ring.members())
        if names and names != current:
            # ring changes fire the controller's acquire/release callback
            # (shard/controller.go:381) — the steal path
            joined, dropped = names - current, current - names
            for m in joined:
                self.ring.add_member(m)
            for m in dropped:
                self.ring.remove_member(m)
            # flap witnesses: per-host drop/join counters plus a monotonic
            # ring generation, so a chaos campaign can assert "the fleet
            # OBSERVED the flap" from /metrics instead of inferring it
            # from traffic (gen/cluster_chaos.py membership-flap gate)
            from ..utils import metrics as cm
            self.metrics.inc(cm.SCOPE_MEMBERSHIP, cm.M_RING_JOINS,
                             len(joined))
            self.metrics.inc(cm.SCOPE_MEMBERSHIP, cm.M_RING_DROPS,
                             len(dropped))
            self.metrics.gauge(cm.SCOPE_MEMBERSHIP, cm.M_RING_GENERATION,
                               self.ring.generation)
            flightrecorder.emit("ring-change", host=self.name,
                                joined=sorted(joined),
                                dropped=sorted(dropped),
                                members=sorted(names))
        # idempotent re-acquisition: a transient store error during an
        # earlier eager acquire must not leave assigned shards engineless
        self.controller.ensure_assigned()

    def _pump_loop(self) -> None:
        while not self._stop.wait(self._pump_interval):
            try:
                self.processors.process_transfer_concurrent(self.scheduler)
                self.processors.process_timers_once()
            except Exception:
                continue  # shard moved mid-pump etc.; next tick retries
            if self._xdc_consumers:
                self._pump_xdc()

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        # arm the black box FIRST: a host that dies during boot should
        # still leave its record behind
        flightrecorder.install_dump_handlers()
        flightrecorder.emit("host-boot", host=self.name,
                            cluster=self.cluster_name, port=self.port,
                            shards=self.num_shards)
        self.refresh_membership()
        self._beat_thread.start()
        self._pump_thread.start()
        self.scrape.start()
        if timeseries_mod.enabled():
            self.timeseries.start()
        if hostprof_mod.enabled():
            self.hostprof.start()
        threading.Thread(target=self.serve_forever, daemon=True,
                         name="cadence-rpc-accept").start()

    def stop(self) -> None:
        flightrecorder.emit("host-stop", host=self.name)
        self._stop.set()
        for telemetry in (self.timeseries, self.hostprof):
            try:
                telemetry.stop()
            except Exception:
                pass
        if self.serving is not None:
            try:
                self.serving.stop()
            except Exception:
                pass
        try:
            self.scrape.stop()
        except Exception:
            pass
        self.shutdown()


#: matching poll ops that hand out a matched task in their response — the
#: task type routes the dead-socket requeue
_MATCHING_POLLS = {
    "poll_and_wait_decision": 0, "poll_for_decision_task": 0,
    "poll_and_wait_activity": 1, "poll_for_activity_task": 1,
}


class _Handler(socketserver.BaseRequestHandler):
    def handle(self) -> None:
        """One connection, many frames. Op execution and transport are kept
        strictly apart: an op that raises ConnectionError (e.g. an outbound
        hop to a DEAD PEER was refused) is an op ERROR to report to the
        caller — only failures on THIS socket end the connection."""
        server: ServiceHost = self.server  # type: ignore[assignment]
        # name the per-connection thread so hostprof attributes RPC
        # service time to rpc-dispatch rather than "other"
        threading.current_thread().name = "cadence-rpc-dispatch"
        try:
            verify_hello(self.request)  # before the first pickle load
        except (OSError, ConnectionError):
            return
        while True:
            try:
                req = recv_frame(self.request)
            except (OSError, ConnectionError):
                return
            # a traced envelope parents this request's span on the caller's
            # span; untraced traffic (pump loops, heartbeats) stays span-free;
            # the caller's DEADLINE budget rides the same carrier
            remote_deadline = deadline_mod.peek(req)
            remote_ctx, req = tracing.extract(req)
            matched_poll = None  # (task, task_type) needing dead-socket requeue
            try:
                op = req[0] if isinstance(req, tuple) and req else "?"
                if remote_deadline is not None and remote_deadline.expired():
                    # the caller has already given up: reject BEFORE burning
                    # a dispatch (store transaction, kernel launch)
                    server.metrics.inc("rpc.server",
                                       "deadline-expired-rejections")
                    raise DeadlineExceeded(
                        f"rpc.{op} arrived with its deadline expired")
                span_cm = (server.tracer.start_span(f"rpc.{op}",
                                                    child_of=remote_ctx)
                           if remote_ctx is not None else nullcontext())
                # bind the remaining budget for the dispatch, so every
                # outbound hop this handler makes (store writes, peer
                # engines) inherits the shrinking deadline
                with span_cm, deadline_mod.bind(remote_deadline):
                    result, matched_poll = self._dispatch(server, req)
                response = ("ok", result)
            except CircuitOpenError as exc:
                # an outbound dependency of this host is being shed: the
                # caller sees a typed busy signal, not a mystery
                # ConnectionError (degrade, don't queue behind a dead host)
                response = ("err", ServiceBusy(str(exc)))
            except BaseException as exc:
                response = ("err", exc)
            try:
                send_frame(self.request, response)
            except (OSError, ConnectionError):
                if matched_poll is not None:
                    # a matched task delivered to a dead socket (worker
                    # died mid-long-poll) must requeue, not vanish
                    server.matching.local.requeue_task(*matched_poll)
                return
            except Exception:
                # unpicklable result/exception: degrade to a string error
                # rather than killing the connection
                try:
                    send_frame(self.request,
                               ("err", RuntimeError(repr(response[1]))))
                except Exception:
                    return

    @staticmethod
    def _dispatch(server: "ServiceHost", req) -> Tuple[object, Optional[tuple]]:
        """Execute one op → (result, matched_poll)."""
        matched_poll = None
        op = req[0]
        if op == "frontend":
            _, method, args, kwargs = req
            result = getattr(server.frontend, method)(*args, **kwargs)
        elif op == "engine":
            _, workflow_id, path, args, kwargs = req
            target = server.controller.engine_for_workflow(workflow_id)
            for part in path.split("."):
                target = getattr(target, part)
            result = target(*args, **kwargs)
        elif op == "engine_routed":
            # cross-CLUSTER entry: any host accepts and forwards to
            # its ring's owner (server.route), so a peer cluster
            # needs only one live address, not our ring topology
            _, workflow_id, path, args, kwargs = req
            target = server.route(workflow_id)
            for part in path.split("."):
                target = getattr(target, part)
            result = target(*args, **kwargs)
        elif op == "matching":
            _, method, args, kwargs = req
            result = getattr(server.matching.local, method)(*args, **kwargs)
            if method in _MATCHING_POLLS and result is not None:
                matched_poll = (result, _MATCHING_POLLS[method])
        elif op == "admin_stale_probe":
            # deposed-owner fencing probe: write through the CACHED
            # shard engine, bypassing ring validation — the range
            # fence in the store server must reject it
            _, domain_id, workflow_id = req
            sid = server.controller.shard_for(workflow_id)
            engine = server.controller.cached_engine(sid)
            if engine is None:
                raise RuntimeError(f"no cached engine for shard {sid}")
            engine.signal_workflow(domain_id, workflow_id, "stale-probe")
            result = None
        elif op == "admin_metrics":
            # the scrape surface as an RPC (operator tooling that already
            # speaks the wire need not open the HTTP port)
            result = {"snapshot": server.metrics.snapshot(),
                      "prometheus": server.metrics.to_prometheus()}
        elif op == "admin_cluster":
            # per-host cluster rollup (the `admin cluster` CLI verb's
            # wire leg): shard ownership, serving/resident/migration
            # occupancy — and with detail=True the resident rows' payload
            # CRCs, the byte-parity probe the planned-rebalance test
            # compares losing-host→gaining-host→oracle
            detail = bool(req[1]) if len(req) > 1 else False
            result = server.cluster_doc(detail=detail)
        elif op == "admin_drain":
            # planned-rebalance drain (engine/migration.py): persist a
            # snapshot record for every resident row on this host so a
            # following kill/rebalance is a warm failover by construction
            if server.migration is None:
                raise RuntimeError("serving tier (and migration) not "
                                   "enabled on this host")
            evict = bool(req[1]) if len(req) > 1 else False
            rep = server.migration.drain_host(evict=evict)
            result = {"shards": rep.shards, "considered": rep.considered,
                      "snapshotted": rep.snapshotted,
                      "skipped": rep.skipped, "evicted": rep.evicted}
        elif op == "admin_prehydrate":
            # warm-promotion hydration (the `load region` scenario's
            # per-host leg): only the leader host sees the replicated
            # domain flip, so every standby host exposes hydration as a
            # wire op — seed_caches + suffix replay over its OWN shards
            if server.migration is None:
                raise RuntimeError("serving tier (and migration) not "
                                   "enabled on this host")
            rep = server.migration.hydrate_shards(
                server.controller.owned_shards())
            result = {"shards": rep.shards, "considered": rep.considered,
                      "hydrated": rep.hydrated,
                      "suffix_events": rep.suffix_events,
                      "cold": rep.cold, "young": rep.young,
                      "stale": rep.stale,
                      "already_resident": rep.already_resident,
                      "parity_divergence": rep.parity_divergence}
        elif op == "admin_dlq":
            # DLQ rollup / redrive over the wire (the `admin dlq` and
            # `dlq redrive` CLI verbs' wire legs). Consumers live on the
            # leader host; a non-leader still answers with a read-only
            # processor over its cluster's shared stores
            sub = req[1] if len(req) > 1 else "summary"
            if server._xdc_consumers:
                proc = server._xdc_consumers[0].repl
            else:
                from ..engine.replication import (
                    HistoryReplicator as _HR,
                    ReplicationPublisher as _RP,
                    ReplicationTaskProcessor as _RTP,
                )
                proc = _RTP(_HR(server.stores), _RP(server.stores),
                            server.stores)
                proc.metrics = server.metrics
            if sub == "redrive":
                result = proc.redrive_dlq()
            else:
                result = proc.dlq_summary()
        elif op == "admin_partition":
            # per-peer-pair partition control (rpc/chaos.PartitionTable):
            # ("admin_partition", "block"|"heal", host, port) severs or
            # restores THIS host's outbound leg to one endpoint —
            # asymmetric by construction, since the reverse direction
            # lives in the peer's own table; "heal_all" and "list" manage
            # campaign teardown/inspection. The admin call itself rides
            # campaign-client → this host, so a host partitioned from
            # the store stays controllable.
            sub = req[1] if len(req) > 1 else "list"
            table = chaos_mod.partitions()
            if sub == "block":
                table.block(req[2], int(req[3]))
            elif sub == "heal":
                table.heal(req[2], int(req[3]))
            elif sub == "heal_all":
                table.heal_all()
            elif sub != "list":
                raise ValueError(f"unknown admin_partition arm {sub!r}")
            result = {"host": server.name, "pairs": table.pairs(),
                      **table.counts()}
        elif op == "admin_timeseries":
            # the /timeseries doc over the wire (operator tooling that
            # already speaks the protocol need not open the HTTP port)
            result = server.timeseries_doc(
                req[1] if len(req) > 1 else 120)
        elif op == "admin_hostprof":
            result = server.hostprof_doc(
                float(req[1]) if len(req) > 1 else 0.0)
        elif op == "admin_flightrec":
            result = server.flightrec_doc(
                req[1] if len(req) > 1 else 200)
            dump = req[2] if len(req) > 2 else None
            if dump:
                result["dumped"] = flightrecorder.DEFAULT_RECORDER.dump(
                    dump, reason="admin")
        elif op == "ping":
            result = ("pong", server.name,
                      server.controller.owned_shards(),
                      server.ring.members())
        else:
            raise ValueError(f"unknown op {op!r}")
        return result, matched_poll


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="cadence-tpu-host")
    p.add_argument("--name", required=True)
    p.add_argument("--port", type=int, required=True)
    p.add_argument("--store", required=True, help="HOST:PORT of store server")
    p.add_argument("--num-shards", type=int, default=8)
    p.add_argument("--hb-interval", type=float, default=0.15)
    p.add_argument("--ttl", type=float, default=3.0)
    p.add_argument("--cluster-name", default="primary")
    p.add_argument("--peer", action="append", default=[],
                   help="peer cluster as NAME=STOREHOST:PORT (repeatable)")
    p.add_argument("--host", default="127.0.0.1",
                   help="bind address (0.0.0.0 in containers)")
    p.add_argument("--advertise-host", default="",
                   help="address peers dial to reach this host (defaults "
                        "to --host, or 127.0.0.1 when binding 0.0.0.0; "
                        "containers pass their service name)")
    p.add_argument("--http-port", type=int, default=0,
                   help="HTTP scrape port (/metrics, /health, /traces); "
                        "0 binds an ephemeral port")
    args = p.parse_args(argv)
    shost, sport = args.store.rsplit(":", 1)
    peers = {}
    for spec in args.peer:
        pname, paddr = spec.split("=", 1)
        ph, pp = paddr.rsplit(":", 1)
        peers[pname] = (ph, int(pp))
    advertise = args.advertise_host or (
        args.host if args.host != "0.0.0.0" else "127.0.0.1")
    host = ServiceHost(args.name, (args.host, args.port),
                       (shost, int(sport)), args.num_shards,
                       hb_interval=args.hb_interval, ttl=args.ttl,
                       cluster_name=args.cluster_name, peers=peers,
                       advertise_host=advertise, http_port=args.http_port)
    host.start()
    threading.Event().wait()  # serve until killed
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
