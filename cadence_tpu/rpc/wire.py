"""Wire protocol: length-prefixed frames over TCP.

Reference: common/rpc/factory.go:27-90 builds YARPC gRPC+TChannel
inbounds; the equivalent here is a minimal length-prefixed binary framing
(4-byte big-endian length + pickle body) shared by every service role.

TRUST BOUNDARY: frames carry pickled engine/store objects, so the wire is
an INTERNAL cluster transport (the posture of the reference's TChannel and
Cassandra native protocol: authenticated network, not the public edge).
The public edge would terminate in the frontend role with a schema codec
(core/codec.py carries the history blobs already); pickle here keeps the
whole MutableState/persistence surface transportable without a parallel
serialization tier.
"""
from __future__ import annotations

import io
import pickle
import socket
import struct
from typing import Any, Tuple

_LEN = struct.Struct(">I")
MAX_FRAME = 256 * 1024 * 1024


class WireError(ConnectionError):
    """Framing violation or truncated peer stream."""


def send_frame(sock: socket.socket, obj: Any) -> None:
    body = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    if len(body) > MAX_FRAME:
        raise WireError(f"frame {len(body)}B exceeds {MAX_FRAME}B")
    sock.sendall(_LEN.pack(len(body)) + body)


def _read_exact(sock: socket.socket, n: int) -> bytes:
    buf = io.BytesIO()
    got = 0
    while got < n:
        chunk = sock.recv(min(n - got, 1 << 20))
        if not chunk:
            raise WireError("peer closed mid-frame")
        buf.write(chunk)
        got += len(chunk)
    return buf.getvalue()


def recv_frame(sock: socket.socket) -> Any:
    header = _read_exact(sock, _LEN.size)
    (length,) = _LEN.unpack(header)
    if length > MAX_FRAME:
        raise WireError(f"frame {length}B exceeds {MAX_FRAME}B")
    return pickle.loads(_read_exact(sock, length))


def call(address: Tuple[str, int], request: Any, timeout: float = 30.0) -> Any:
    """One-shot request/response over a fresh connection. The response is
    ("ok", value) or ("err", exception) — errors re-raise at the caller,
    carrying the service-level type (ShardOwnershipLostError & co) across
    the process boundary."""
    with socket.create_connection(address, timeout=timeout) as sock:
        send_frame(sock, request)
        kind, payload = recv_frame(sock)
    if kind == "err":
        raise payload
    return payload


class Connection:
    """A pooled client connection (one in-flight request at a time)."""

    def __init__(self, address: Tuple[str, int], timeout: float = 30.0) -> None:
        self.address = address
        self.timeout = timeout
        self._sock: socket.socket | None = None

    def _ensure(self) -> socket.socket:
        if self._sock is None:
            self._sock = socket.create_connection(self.address,
                                                  timeout=self.timeout)
        return self._sock

    def call(self, request: Any) -> Any:
        for attempt in (0, 1):
            sock = self._ensure()
            try:
                send_frame(sock, request)
            except (OSError, WireError):
                # a SEND failure on a pooled socket is the peer-restarted-
                # between-calls case (stale FIN): nothing of this request
                # was processed, so one reconnect+resend is safe
                self.close()
                if attempt:
                    raise
                continue
            try:
                kind, payload = recv_frame(sock)
            except (OSError, WireError):
                # a RECEIVE failure is NOT retried: the peer may already
                # have applied the request (signal appended, task created)
                # and blind resend would double-apply a non-idempotent op —
                # the caller owns that decision (FrontendClient retries
                # only errors the fence makes safe)
                self.close()
                raise
            if kind == "err":
                raise payload
            return payload

    def close(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            finally:
                self._sock = None
