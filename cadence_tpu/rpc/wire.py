"""Wire protocol: length-prefixed frames over TCP.

Reference: common/rpc/factory.go:27-90 builds YARPC gRPC+TChannel
inbounds; the equivalent here is a minimal length-prefixed binary framing
(4-byte big-endian length + pickle body) shared by every service role.

TRUST BOUNDARY: frames carry pickled engine/store objects, so the wire is
an INTERNAL cluster transport (the posture of the reference's TChannel and
Cassandra native protocol: authenticated network, not the public edge).
The public edge would terminate in the frontend role with a schema codec
(core/codec.py carries the history blobs already); pickle here keeps the
whole MutableState/persistence surface transportable without a parallel
serialization tier.
"""
from __future__ import annotations

import hashlib
import hmac
import io
import os
import pickle
import socket
import struct
from typing import Any, Optional, Tuple

from ..utils import deadline as deadline_mod
from ..utils import tracing
from ..utils.deadline import DeadlineExceeded
from . import chaos as chaos_mod

_LEN = struct.Struct(">I")
MAX_FRAME = 256 * 1024 * 1024

#: per-hop ceiling when no caller deadline is bound (the old fixed value,
#: now only an upper bound — live deadlines shrink it per call)
DEFAULT_TIMEOUT_S = 30.0
#: floor for derived socket timeouts: a nearly-expired budget still gets
#: a sliver of wire time instead of a zero/negative timeout
MIN_TIMEOUT_S = 0.001


class WireError(ConnectionError):
    """Framing violation or truncated peer stream."""


def effective_timeout(base: float = DEFAULT_TIMEOUT_S) -> float:
    """Socket timeout for the next hop: the caller's remaining deadline
    budget when one is bound (clamped to [MIN, base]), else `base`.
    Raises DeadlineExceeded instead of dialing when the budget is gone —
    the cheapest possible rejection."""
    current = deadline_mod.current()
    if current is None:
        return base
    remaining = current.remaining()
    if remaining <= 0:
        raise DeadlineExceeded(
            f"deadline expired {-remaining:.3f}s before the call")
    return max(MIN_TIMEOUT_S, min(base, remaining))


# -- connection authentication ---------------------------------------------
#
# The trust-boundary docstring above is ENFORCED, not just declared: every
# connection opens with a challenge-response handshake keyed by a
# per-cluster shared secret — the server sends a fresh random nonce, the
# client answers HMAC-SHA256(secret, nonce || context) — so a recorded
# preamble is worthless on the next connection (replay-proof); a peer that
# cannot produce the response is disconnected before any frame is
# unpickled. The ORIGINAL static preamble, HMAC(secret, context) with no
# nonce, is kept only as a documented LEGACY fallback: the server still
# accepts it unless CADENCE_TPU_WIRE_ALLOW_STATIC=0, which closes the
# replay window — set it once every peer in the cluster speaks the
# challenge. The fallback is ONE-directional by design: it covers OLD
# clients dialing NEW servers, so a rolling upgrade must roll the server
# side first (a new client dialing an old server would wait for a nonce
# that never comes and burn its socket timeout). The secret comes
# from CADENCE_TPU_WIRE_SECRET (explicit per-cluster deployment), falling
# back to a 0600 per-user secret file — so on a multi-user host, reaching
# the port is not enough: an unrelated local user cannot read the key
# material.

_HELLO_CTX = b"cadence-tpu-wire-v1"
_HELLO_LEN = hashlib.sha256().digest_size
_NONCE_LEN = 32
_LEGACY_ENV = "CADENCE_TPU_WIRE_ALLOW_STATIC"
_SECRET_CACHE: Optional[bytes] = None


def cluster_secret() -> bytes:
    global _SECRET_CACHE
    if _SECRET_CACHE is not None:
        return _SECRET_CACHE
    env = os.environ.get("CADENCE_TPU_WIRE_SECRET")
    if env:
        _SECRET_CACHE = env.encode("utf-8")
        return _SECRET_CACHE
    path = os.path.join(os.path.expanduser("~"), ".cadence_tpu_wire_secret")
    try:
        with open(path, "rb") as fh:
            _SECRET_CACHE = fh.read()
            return _SECRET_CACHE
    except FileNotFoundError:
        pass
    secret = os.urandom(32)
    try:
        fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_EXCL, 0o600)
        with os.fdopen(fd, "wb") as fh:
            fh.write(secret)
    except FileExistsError:
        with open(path, "rb") as fh:  # lost the creation race: theirs wins
            secret = fh.read()
    _SECRET_CACHE = secret
    return secret


def _hello_mac() -> bytes:
    """The LEGACY static preamble (pre-challenge peers)."""
    return hmac.new(cluster_secret(), _HELLO_CTX, hashlib.sha256).digest()


def _challenge_mac(nonce: bytes) -> bytes:
    return hmac.new(cluster_secret(), nonce + _HELLO_CTX,
                    hashlib.sha256).digest()


def _legacy_allowed() -> bool:
    return os.environ.get(_LEGACY_ENV, "1") not in ("0", "false", "no")


def send_hello(sock: socket.socket) -> None:
    """Client side of the handshake: read the server's fresh nonce, answer
    HMAC(secret, nonce || context) — the response only opens THIS
    connection; replaying it elsewhere fails against a different nonce."""
    nonce = _read_exact(sock, _NONCE_LEN)
    sock.sendall(_challenge_mac(nonce))


def verify_hello(sock: socket.socket) -> None:
    """Server side: challenge, then read+check the response BEFORE the
    first pickle load. Raises WireError (and the caller drops the
    connection) on mismatch. The static legacy preamble is accepted only
    while CADENCE_TPU_WIRE_ALLOW_STATIC permits it."""
    nonce = os.urandom(_NONCE_LEN)
    sock.sendall(nonce)
    mac = _read_exact(sock, _HELLO_LEN)
    if hmac.compare_digest(mac, _challenge_mac(nonce)):
        return
    if _legacy_allowed() and hmac.compare_digest(mac, _hello_mac()):
        return
    raise WireError("unauthenticated peer (bad cluster secret)")


def _encode_frame(obj: Any) -> Tuple[bytes, bytes]:
    body = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    if len(body) > MAX_FRAME:
        raise WireError(f"frame {len(body)}B exceeds {MAX_FRAME}B")
    return _LEN.pack(len(body)), body


def send_frame(sock: socket.socket, obj: Any) -> None:
    header, body = _encode_frame(obj)
    sock.sendall(header + body)


def send_request_frame(sock: socket.socket, obj: Any) -> None:
    """The CLIENT request leg of send_frame: the chaos injector (when
    installed) may drop, delay, or sever here — before the server can
    have dispatched anything, so injected faults are always retryable.
    Server RESPONSE sends stay on plain send_frame: a chaos'd response
    would lose applied work and break at-least-once semantics.

    Encode failures (oversized frame, unpicklable argument) are tagged
    `_wire_local`: they happen before any byte reaches the peer, so they
    are neither evidence against the target (breakers must not charge
    them) nor worth a resend of the identical payload."""
    try:
        header, body = _encode_frame(obj)
    except BaseException as exc:
        try:
            exc._wire_local = True
        except Exception:
            pass
        raise
    chaos = chaos_mod.active()
    if chaos is not None:
        chaos.before_send(sock, header, body)
    sock.sendall(header + body)


def _mark_relayed(exc: BaseException) -> BaseException:
    """Tag an exception that arrived as an ("err", exc) RESPONSE: the
    peer ANSWERED — the failure (possibly ConnectionError-shaped, from
    the peer's own outbound hop) is not evidence against this transport,
    and client breakers must not charge it (rpc/client._Pool reads the
    tag). Best-effort: exotic exception types without a __dict__ simply
    go untagged."""
    try:
        exc._wire_relayed = True
    except Exception:
        pass
    return exc


def _read_exact(sock: socket.socket, n: int) -> bytes:
    buf = io.BytesIO()
    got = 0
    while got < n:
        chunk = sock.recv(min(n - got, 1 << 20))
        if not chunk:
            raise WireError("peer closed mid-frame")
        buf.write(chunk)
        got += len(chunk)
    return buf.getvalue()


def recv_frame(sock: socket.socket) -> Any:
    header = _read_exact(sock, _LEN.size)
    (length,) = _LEN.unpack(header)
    if length > MAX_FRAME:
        raise WireError(f"frame {length}B exceeds {MAX_FRAME}B")
    return pickle.loads(_read_exact(sock, length))


def call(address: Tuple[str, int], request: Any, timeout: float = 30.0) -> Any:
    """One-shot request/response over a fresh connection. The response is
    ("ok", value) or ("err", exception) — errors re-raise at the caller,
    carrying the service-level type (ShardOwnershipLostError & co) across
    the process boundary. An active caller deadline rides the envelope
    and shrinks the socket timeout."""
    chaos_mod.check_partition(address)
    timeout = effective_timeout(timeout)
    with socket.create_connection(address, timeout=timeout) as sock:
        send_hello(sock)
        send_request_frame(sock, deadline_mod.inject(tracing.inject(request)))
        kind, payload = recv_frame(sock)
    if kind == "err":
        raise _mark_relayed(payload)
    return payload


class Connection:
    """A pooled client connection (one in-flight request at a time)."""

    def __init__(self, address: Tuple[str, int],
                 timeout: float = DEFAULT_TIMEOUT_S) -> None:
        self.address = address
        self.timeout = timeout
        self._sock: socket.socket | None = None

    def _ensure(self, timeout: float) -> socket.socket:
        if self._sock is None:
            self._sock = socket.create_connection(self.address,
                                                  timeout=timeout)
            send_hello(self._sock)
        else:
            # pooled socket: re-derive the timeout from THIS call's
            # remaining budget, not whatever the opening call had left
            self._sock.settimeout(timeout)
        return self._sock

    def call(self, request: Any) -> Any:
        for attempt in (0, 1):
            # an installed partition cuts pooled connections too: check
            # per call (not per dial), close the idle socket so healing
            # redials fresh, and raise before any byte leaves — the
            # nothing-was-applied contract ChaosError promises
            table = chaos_mod.active_partitions()
            if table is not None and table.is_blocked(self.address):
                self.close()
                table.check(self.address)
            # derived per attempt: send-retry time counts against the budget
            timeout = effective_timeout(self.timeout)
            sock = self._ensure(timeout)
            try:
                send_request_frame(sock, request)
            except (OSError, WireError) as exc:
                # a LOCAL encode failure is deterministic: reconnecting
                # and re-encoding the same payload cannot help
                if getattr(exc, "_wire_local", False):
                    raise
                # a SEND failure on a pooled socket is the peer-restarted-
                # between-calls case (stale FIN): nothing of this request
                # was processed, so one reconnect+resend is safe
                self.close()
                if attempt:
                    raise
                continue
            try:
                kind, payload = recv_frame(sock)
            except (OSError, WireError):
                # a RECEIVE failure is NOT retried: the peer may already
                # have applied the request (signal appended, task created)
                # and blind resend would double-apply a non-idempotent op —
                # the caller owns that decision (FrontendClient retries
                # only errors the fence makes safe)
                self.close()
                raise
            if kind == "err":
                raise _mark_relayed(payload)
            return payload

    def close(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            finally:
                self._sock = None
