"""Wire-level chaos: drop, delay, and sever connections mid-stream.

Reference: the store tier already has `engine/faults.py`
(persistenceErrorInjectionClients.go analog — errors injected BEFORE the
target method runs). This module is the same discipline one layer down,
at the TRANSPORT: the client-side request path of every wire call can be

- DELAYED  (a jittered sleep before the frame is written — latency
  injection; bounded, so deadline budgets absorb it),
- DROPPED  (the connection is closed before ANY request byte is sent —
  the classic connect-then-die peer),
- SEVERED  (a PARTIAL frame is written, then the socket is torn down —
  the peer sees a mid-stream FIN and discards the torn frame).

All three are injected on the REQUEST leg only, before the server can
have dispatched anything: a torn frame never unpickles (rpc/wire.py
`_read_exact` raises "peer closed mid-frame" and the handler drops the
connection), so an injected fault ALWAYS means "nothing was applied".
That is the property that makes `ChaosError` universally retryable and
lets the chaos soak demand byte-identical mutable-state checksums
against a fault-free run — at-least-once delivery with zero divergence.

Configuration (cross-process, so subprocess clusters inherit it):

    CADENCE_TPU_CHAOS="drop=0.05,sever=0.03,delay=0.1,delay_ms=10,seed=7"

or programmatically via `install(WireChaos(...))` / `uninstall()`; the
same spec string can ride dynamicconfig (KEY_WIRE_CHAOS) into a
ServiceHost. Seeded RNG keeps runs reproducible.
"""
from __future__ import annotations

import os
import random
import socket
import threading
import time
from typing import Optional


class ChaosError(ConnectionError):
    """An injected transport fault. Guaranteed nothing-was-applied (the
    request never reached a dispatchable frame), so every client tier may
    retry it regardless of the op's idempotency."""


class WireChaos:
    """Seeded fault decider + injector for the client request path.

    Probabilities are per-call and independent; `delay_ms` is the MAX
    latency injected (actual delay is uniform in [0, delay_ms])."""

    def __init__(self, drop: float = 0.0, sever: float = 0.0,
                 delay: float = 0.0, delay_ms: float = 10.0,
                 seed: int = 0) -> None:
        self.drop = drop
        self.sever = sever
        self.delay = delay
        self.delay_ms = delay_ms
        self._rng = random.Random(seed)
        self._lock = threading.Lock()
        self.injected_drops = 0
        self.injected_severs = 0
        self.injected_delays = 0

    def _roll(self) -> tuple:
        with self._lock:
            return (self._rng.random(), self._rng.random(),
                    self._rng.random(), self._rng.random())

    def before_send(self, sock: socket.socket,
                    header: bytes, body: bytes) -> None:
        """Called by the wire just before a request frame is written.
        Raises ChaosError (after closing `sock`) for drop/sever; sleeps
        for delay; returns normally to let the real send proceed."""
        r_delay, r_jitter, r_drop, r_sever = self._roll()
        if self.delay > 0 and r_delay < self.delay:
            with self._lock:
                self.injected_delays += 1
            time.sleep(r_jitter * self.delay_ms / 1000.0)
        if self.drop > 0 and r_drop < self.drop:
            with self._lock:
                self.injected_drops += 1
            _teardown(sock)
            raise ChaosError("chaos: connection dropped before send")
        if self.sever > 0 and r_sever < self.sever:
            with self._lock:
                self.injected_severs += 1
            # mid-stream sever: leak a partial frame so the peer's
            # _read_exact sees a torn body, then hard-close
            try:
                sock.sendall(header + body[: max(1, len(body) // 2)])
            except OSError:
                pass
            _teardown(sock)
            raise ChaosError("chaos: connection severed mid-frame")

    def counts(self) -> dict:
        with self._lock:
            return {"drops": self.injected_drops,
                    "severs": self.injected_severs,
                    "delays": self.injected_delays}


def _teardown(sock: socket.socket) -> None:
    """RST-ish teardown: no graceful shutdown handshake."""
    try:
        sock.close()
    except OSError:
        pass


# -- process-wide installation ----------------------------------------------

_ACTIVE: Optional[WireChaos] = None
_ENV = "CADENCE_TPU_CHAOS"
_LOADED_ENV = False
_LOAD_LOCK = threading.Lock()


def parse_kv_spec(spec: str, casts: dict) -> dict:
    """Shared "k=v,k=v" spec parser for the fault-injection env vars
    (CADENCE_TPU_CHAOS here, CADENCE_TPU_STORE_FAULTS in storeserver).
    Unknown keys raise — a typo'd spec silently doing nothing is worse
    than failing loudly at boot."""
    out = {}
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        key, _, value = part.partition("=")
        key = key.strip()
        cast = casts.get(key)
        if cast is None:
            raise ValueError(f"unknown knob {key!r} in {spec!r}")
        out[key] = cast(value.strip())
    return out


def parse_spec(spec: str) -> WireChaos:
    """"drop=0.05,sever=0.03,delay=0.1,delay_ms=10,seed=7" → WireChaos."""
    return WireChaos(**parse_kv_spec(
        spec, {"drop": float, "sever": float, "delay": float,
               "delay_ms": float, "seed": int}))


def install(chaos: Optional[WireChaos]) -> None:
    """Programmatic installation (tests); None uninstalls."""
    global _ACTIVE, _LOADED_ENV
    _ACTIVE = chaos
    _LOADED_ENV = True  # explicit choice overrides the env default


def uninstall() -> None:
    install(None)


def active() -> Optional[WireChaos]:
    """The process's chaos injector, lazily loaded from CADENCE_TPU_CHAOS
    on first use (subprocess cluster hosts pick it up with zero plumbing).
    Fast path: one global read when chaos was never configured."""
    global _ACTIVE, _LOADED_ENV
    if not _LOADED_ENV:
        with _LOAD_LOCK:
            if not _LOADED_ENV:
                spec = os.environ.get(_ENV, "")
                if spec:
                    _ACTIVE = parse_spec(spec)
                _LOADED_ENV = True
    return _ACTIVE
