"""Wire-level chaos: drop, delay, sever — and asymmetric partitions.

Reference: the store tier already has `engine/faults.py`
(persistenceErrorInjectionClients.go analog — errors injected BEFORE the
target method runs). This module is the same discipline one layer down,
at the TRANSPORT: the client-side request path of every wire call can be

- DELAYED  (a jittered sleep before the frame is written — latency
  injection; bounded, so deadline budgets absorb it),
- DROPPED  (the connection is closed before ANY request byte is sent —
  the classic connect-then-die peer),
- SEVERED  (a PARTIAL frame is written, then the socket is torn down —
  the peer sees a mid-stream FIN and discards the torn frame).

All three are injected on the REQUEST leg only, before the server can
have dispatched anything: a torn frame never unpickles (rpc/wire.py
`_read_exact` raises "peer closed mid-frame" and the handler drops the
connection), so an injected fault ALWAYS means "nothing was applied".
That is the property that makes `ChaosError` universally retryable and
lets the chaos soak demand byte-identical mutable-state checksums
against a fault-free run — at-least-once delivery with zero divergence.

Configuration (cross-process, so subprocess clusters inherit it):

    CADENCE_TPU_CHAOS="drop=0.05,sever=0.03,delay=0.1,delay_ms=10,seed=7"

or programmatically via `install(WireChaos(...))` / `uninstall()`; the
same spec string can ride dynamicconfig (KEY_WIRE_CHAOS) into a
ServiceHost. Seeded RNG keeps runs reproducible.

The PARTITION table (`PartitionTable`, below) is the deterministic
sibling of the probabilistic injector: a per-peer-pair block list
consulted on every outbound dial/call, so a campaign can sever
host A → store while store → A and B → store stay healthy — a real
ASYMMETRIC partition, because each process owns its own table. Blocked
calls raise ChaosError before any byte leaves the process (the same
nothing-was-applied guarantee), and pairs heal on schedule via the
`admin_partition` wire op (rpc/server.py) or `heal`/`heal_all` here.
Boot-time blocks ride CADENCE_TPU_PARTITION="block=host:port;host:port".
"""
from __future__ import annotations

import os
import random
import socket
import threading
import time
from typing import Dict, List, Optional, Tuple

from ..utils.metrics import DEFAULT_REGISTRY

#: registry scope for partition-table counters (per-process; a host's
#: /metrics therefore shows the partitions IT enforces as dialer)
SCOPE_PARTITION = "rpc.partition"
M_PART_BLOCKED_SENDS = "blocked-sends"
M_PART_BLOCKS = "blocks"
M_PART_HEALS = "heals"
M_PART_ACTIVE = "active-pairs"


class ChaosError(ConnectionError):
    """An injected transport fault. Guaranteed nothing-was-applied (the
    request never reached a dispatchable frame), so every client tier may
    retry it regardless of the op's idempotency."""


class WireChaos:
    """Seeded fault decider + injector for the client request path.

    Probabilities are per-call and independent; `delay_ms` is the MAX
    latency injected (actual delay is uniform in [0, delay_ms])."""

    def __init__(self, drop: float = 0.0, sever: float = 0.0,
                 delay: float = 0.0, delay_ms: float = 10.0,
                 seed: int = 0) -> None:
        self.drop = drop
        self.sever = sever
        self.delay = delay
        self.delay_ms = delay_ms
        self._rng = random.Random(seed)
        self._lock = threading.Lock()
        self.injected_drops = 0
        self.injected_severs = 0
        self.injected_delays = 0

    def _roll(self) -> tuple:
        with self._lock:
            return (self._rng.random(), self._rng.random(),
                    self._rng.random(), self._rng.random())

    def before_send(self, sock: socket.socket,
                    header: bytes, body: bytes) -> None:
        """Called by the wire just before a request frame is written.
        Raises ChaosError (after closing `sock`) for drop/sever; sleeps
        for delay; returns normally to let the real send proceed."""
        r_delay, r_jitter, r_drop, r_sever = self._roll()
        if self.delay > 0 and r_delay < self.delay:
            with self._lock:
                self.injected_delays += 1
            time.sleep(r_jitter * self.delay_ms / 1000.0)
        if self.drop > 0 and r_drop < self.drop:
            with self._lock:
                self.injected_drops += 1
            _teardown(sock)
            raise ChaosError("chaos: connection dropped before send")
        if self.sever > 0 and r_sever < self.sever:
            with self._lock:
                self.injected_severs += 1
            # mid-stream sever: leak a partial frame so the peer's
            # _read_exact sees a torn body, then hard-close
            try:
                sock.sendall(header + body[: max(1, len(body) // 2)])
            except OSError:
                pass
            _teardown(sock)
            raise ChaosError("chaos: connection severed mid-frame")

    def counts(self) -> dict:
        with self._lock:
            return {"drops": self.injected_drops,
                    "severs": self.injected_severs,
                    "delays": self.injected_delays}


def _teardown(sock: socket.socket) -> None:
    """RST-ish teardown: no graceful shutdown handshake."""
    try:
        sock.close()
    except OSError:
        pass


# -- process-wide installation ----------------------------------------------

_ACTIVE: Optional[WireChaos] = None
_ENV = "CADENCE_TPU_CHAOS"
_LOADED_ENV = False
_LOAD_LOCK = threading.Lock()


def parse_kv_spec(spec: str, casts: dict) -> dict:
    """Shared "k=v,k=v" spec parser for the fault-injection env vars
    (CADENCE_TPU_CHAOS here, CADENCE_TPU_STORE_FAULTS in storeserver).
    Unknown keys raise — a typo'd spec silently doing nothing is worse
    than failing loudly at boot."""
    out = {}
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        key, _, value = part.partition("=")
        key = key.strip()
        cast = casts.get(key)
        if cast is None:
            raise ValueError(f"unknown knob {key!r} in {spec!r}")
        out[key] = cast(value.strip())
    return out


def parse_spec(spec: str) -> WireChaos:
    """"drop=0.05,sever=0.03,delay=0.1,delay_ms=10,seed=7" → WireChaos."""
    return WireChaos(**parse_kv_spec(
        spec, {"drop": float, "sever": float, "delay": float,
               "delay_ms": float, "seed": int}))


def install(chaos: Optional[WireChaos]) -> None:
    """Programmatic installation (tests); None uninstalls."""
    global _ACTIVE, _LOADED_ENV
    _ACTIVE = chaos
    _LOADED_ENV = True  # explicit choice overrides the env default


def uninstall() -> None:
    install(None)


def active() -> Optional[WireChaos]:
    """The process's chaos injector, lazily loaded from CADENCE_TPU_CHAOS
    on first use (subprocess cluster hosts pick it up with zero plumbing).
    Fast path: one global read when chaos was never configured."""
    global _ACTIVE, _LOADED_ENV
    if not _LOADED_ENV:
        with _LOAD_LOCK:
            if not _LOADED_ENV:
                spec = os.environ.get(_ENV, "")
                if spec:
                    _ACTIVE = parse_spec(spec)
                _LOADED_ENV = True
    return _ACTIVE


# -- asymmetric partitions --------------------------------------------------

#: endpoint key: (host, port); host "*" matches any host at that port
Endpoint = Tuple[str, int]


class PartitionTable:
    """Per-peer-pair partition state for the CURRENT process as dialer.

    Severing is directional by construction: blocking (host, port) here
    stops THIS process from reaching that endpoint, while the reverse
    direction is governed by the peer's own table — so A↔store and A↔B
    can be cut independently (and independently of B↔store), which is
    exactly the asymmetry real switch/iptables partitions produce.

    `check` raises ChaosError BEFORE any connect/send, preserving the
    nothing-was-applied contract that makes the error retryable; healing
    a pair immediately restores traffic (pooled sockets were torn down
    by the failed calls and redial on the next attempt)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._blocked: set = set()
        self.blocked_sends = 0
        #: counter sink — a ServiceHost rebinds this to ITS registry at
        #: boot so the partitions a host enforces show on its /metrics
        self.registry = DEFAULT_REGISTRY

    @staticmethod
    def _key(host: str, port: int) -> Endpoint:
        return (str(host), int(port))

    def block(self, host: str, port: int) -> None:
        with self._lock:
            self._blocked.add(self._key(host, port))
            n = len(self._blocked)
        self.registry.inc(SCOPE_PARTITION, M_PART_BLOCKS)
        self.registry.gauge(SCOPE_PARTITION, M_PART_ACTIVE, n)

    def heal(self, host: str, port: int) -> None:
        with self._lock:
            self._blocked.discard(self._key(host, port))
            n = len(self._blocked)
        self.registry.inc(SCOPE_PARTITION, M_PART_HEALS)
        self.registry.gauge(SCOPE_PARTITION, M_PART_ACTIVE, n)

    def heal_all(self) -> None:
        with self._lock:
            had = len(self._blocked)
            self._blocked.clear()
        if had:
            self.registry.inc(SCOPE_PARTITION, M_PART_HEALS, had)
        self.registry.gauge(SCOPE_PARTITION, M_PART_ACTIVE, 0)

    def pairs(self) -> List[Endpoint]:
        with self._lock:
            return sorted(self._blocked)

    def is_blocked(self, address: Endpoint) -> bool:
        host, port = address[0], int(address[1])
        with self._lock:
            if not self._blocked:
                return False
            return ((host, port) in self._blocked
                    or ("*", port) in self._blocked)

    def check(self, address: Endpoint) -> None:
        """Raise ChaosError iff `address` is severed from this process.
        Called by the wire before every dial AND every pooled send, so a
        partition installed mid-stream cuts an already-open connection's
        next call too."""
        if self.is_blocked(address):
            with self._lock:
                self.blocked_sends += 1
            self.registry.inc(SCOPE_PARTITION, M_PART_BLOCKED_SENDS)
            raise ChaosError(
                f"partition: {address[0]}:{address[1]} unreachable")

    def counts(self) -> Dict[str, int]:
        with self._lock:
            return {"blocked_sends": self.blocked_sends,
                    "active_pairs": len(self._blocked)}


_PARTITIONS: Optional[PartitionTable] = None
_PARTITION_ENV = "CADENCE_TPU_PARTITION"
_PARTITIONS_LOADED = False


def _parse_endpoint(text: str) -> Endpoint:
    """"host:port" or bare "port" (host wildcard) → endpoint key."""
    text = text.strip()
    host, sep, port = text.rpartition(":")
    if not sep:
        return ("*", int(text))
    return (host or "*", int(port))


def parse_partition_spec(spec: str) -> PartitionTable:
    """"block=127.0.0.1:7001;7002" → PartitionTable (";"-separated
    endpoints inside the value; parse_kv_spec owns the k=v framing so a
    typo'd knob still fails loudly)."""
    kv = parse_kv_spec(spec, {"block": str})
    table = PartitionTable()
    for part in kv.get("block", "").split(";"):
        if part.strip():
            table.block(*_parse_endpoint(part))
    return table


def partitions() -> PartitionTable:
    """The process's partition table, created on first use (admin ops
    need somewhere to install blocks even when the env set none)."""
    global _PARTITIONS, _PARTITIONS_LOADED
    with _LOAD_LOCK:
        _load_partitions_env_locked()
        if _PARTITIONS is None:
            _PARTITIONS = PartitionTable()
        return _PARTITIONS


def active_partitions() -> Optional[PartitionTable]:
    """Fast-path accessor for the wire: None (one global read) when no
    partition was ever configured in this process."""
    global _PARTITIONS
    if not _PARTITIONS_LOADED:
        with _LOAD_LOCK:
            _load_partitions_env_locked()
    return _PARTITIONS


def _load_partitions_env_locked() -> None:
    global _PARTITIONS, _PARTITIONS_LOADED
    if not _PARTITIONS_LOADED:
        spec = os.environ.get(_PARTITION_ENV, "")
        if spec:
            _PARTITIONS = parse_partition_spec(spec)
        _PARTITIONS_LOADED = True


def check_partition(address: Endpoint) -> None:
    """Wire hook: raise ChaosError when this process is partitioned from
    `address`. No-op (single global read) when no table exists."""
    table = active_partitions()
    if table is not None:
        table.check(address)
