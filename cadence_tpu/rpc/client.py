"""Client proxies: stores, engines, and matching across the wire.

Reference: client/ wraps every inter-service call (history peer resolver
by workflowID→shard→host, matching by task list) behind typed clients;
here the same seams are generic method-forwarding proxies over wire.py —
the duck typing that lets the whole engine tier run unmodified against a
remote store server (the persistence managers' interface IS the contract,
dataManagerInterfaces.go analog).

Resilience tier (common/backoff retry policies + outbound middleware):
every `_Pool` call consults a per-target CIRCUIT BREAKER (open targets
shed immediately as CircuitOpenError), carries the caller's DEADLINE
budget on the envelope, and retries SAFE failures under an exponential
full-jitter `RetryPolicy`:

- chaos-injected transport faults (`ChaosError`) — guaranteed
  nothing-was-applied by construction (rpc/chaos.py), always retryable;
- `TransientStoreError` — the store-tier injector raises BEFORE the
  target method runs (engine/faults.py), always retryable;
- connection/timeout failures — retried only for ops classified
  IDEMPOTENT (reads, membership, pings, polls whose matched tasks the
  server requeues on a dead socket); a lost response on a mutation is
  surfaced to the caller, who owns the resend decision.
"""
from __future__ import annotations

import threading
import time
from typing import Optional, Tuple

from ..engine.faults import TransientStoreError
from ..utils import deadline as deadline_mod
from ..utils import tracing
from ..utils.backoff import NO_BACKOFF, RetryPolicy
from ..utils.circuitbreaker import (
    DEFAULT_BREAKERS,
    BreakerRegistry,
    CircuitOpenError,
)
from ..utils.deadline import DeadlineExceeded
from .chaos import ChaosError
from .wire import Connection, WireError

#: every sub-store a Stores bundle exposes (persistence.Stores fields)
SUBSTORES = ("shard", "history", "task", "domain", "visibility", "queue",
             "shard_tasks", "execution", "snapshot")

#: metrics scope for the client resilience tier
SCOPE_RPC_CLIENT = "rpc.client"

#: store-method prefixes that are read-only → safe to retry even after a
#: lost response (nothing to double-apply)
_READ_PREFIXES = ("get", "list", "by_", "as_", "read", "peek", "size",
                  "describe", "count", "scan", "current", "history_host")

#: top-level ops that are idempotent end to end: membership upserts,
#: liveness, and matching polls (a matched task delivered to a dead
#: socket is requeued by the serving side — rpc/server._MATCHING_POLLS)
_IDEMPOTENT_OPS = {"hb", "peers", "ping", "admin_metrics"}
_IDEMPOTENT_MATCHING = {"poll_and_wait_decision", "poll_and_wait_activity",
                        "poll_for_decision_task", "poll_for_activity_task",
                        "describe_task_list"}


def _is_idempotent(request) -> bool:
    """May this request be blindly re-sent after a LOST RESPONSE?"""
    if not isinstance(request, tuple) or not request:
        return False
    op = request[0]
    if op in _IDEMPOTENT_OPS:
        return True
    if op == "store" and len(request) >= 3:
        return str(request[2]).startswith(_READ_PREFIXES)
    if op == "matching" and len(request) >= 2:
        return request[1] in _IDEMPOTENT_MATCHING
    return False


def _default_retry_policy() -> RetryPolicy:
    return RetryPolicy(init_interval_s=0.05, max_interval_s=1.0,
                       backoff_coefficient=2.0, max_attempts=6,
                       expiration_s=30.0)


def retry_policy_from_config(config) -> RetryPolicy:
    """Build the client policy from dynamicconfig knobs (rpc.retry*) —
    ServiceHost wires one shared policy through every outbound proxy."""
    from ..utils import dynamicconfig as dc
    return RetryPolicy(
        init_interval_s=float(config.get(dc.KEY_RPC_RETRY_INIT_INTERVAL_MS))
        / 1000.0,
        max_interval_s=float(config.get(dc.KEY_RPC_RETRY_MAX_INTERVAL_MS))
        / 1000.0,
        max_attempts=int(config.get(dc.KEY_RPC_RETRY_MAX_ATTEMPTS)),
        expiration_s=float(config.get(dc.KEY_RPC_RETRY_EXPIRATION_S)))


class _RemoteSubStore:
    def __init__(self, pool: "_Pool", sub: str) -> None:
        self._pool = pool
        self._sub = sub

    def __getattr__(self, method: str):
        if method.startswith("_"):
            raise AttributeError(method)
        pool, sub = self._pool, self._sub

        def invoke(*args, **kwargs):
            return pool.call(("store", sub, method, args, kwargs))

        invoke.__name__ = f"{sub}.{method}"
        return invoke


class _Pool:
    """Per-thread connections to one address (engine transactions issue
    several store calls in sequence; a per-thread socket keeps them
    pipelined without cross-talk), fronted by the shared per-target
    circuit breaker and the retry policy described in the module doc."""

    def __init__(self, address: Tuple[str, int],
                 metrics=None,
                 breakers: Optional[BreakerRegistry] = None,
                 retry_policy: Optional[RetryPolicy] = None) -> None:
        self.address = address
        self.metrics = metrics
        self.breakers = breakers if breakers is not None else DEFAULT_BREAKERS
        self.retry_policy = (retry_policy if retry_policy is not None
                             else _default_retry_policy())
        #: resolved once — the target never changes, and for_target takes
        #: the registry-wide lock (hot path: several store calls per
        #: engine transaction across every handler thread)
        self._breaker = self.breakers.for_target(address)
        self._local = threading.local()

    # -- connection lifecycle ---------------------------------------------

    def _connection(self) -> Connection:
        conn = getattr(self._local, "conn", None)
        if conn is None:
            conn = Connection(self.address)
            self._local.conn = conn
        return conn

    def _drop_connection(self) -> None:
        """Stale-connection poisoning fix: after ANY transport failure the
        per-thread Connection is discarded, so the next call dials fresh
        instead of reusing an object wedged on a dead peer (peer restart
        between calls must not poison the thread's slot)."""
        conn = getattr(self._local, "conn", None)
        if conn is not None:
            conn.close()
            self._local.conn = None

    def _metrics(self):
        if self.metrics is not None:
            return self.metrics
        from ..utils.metrics import DEFAULT_REGISTRY
        return DEFAULT_REGISTRY

    # -- the resilient call path ------------------------------------------

    def call(self, request):
        breaker = self._breaker
        idempotent = _is_idempotent(request)
        attempt = 0
        started = time.monotonic()
        while True:
            if not breaker.allow():
                self._metrics().inc(SCOPE_RPC_CLIENT, "breaker-rejected")
                raise CircuitOpenError(
                    f"circuit open for {self.address[0]}:{self.address[1]}")
            try:
                result = self._call_once(request)
            except DeadlineExceeded:
                # budget exhaustion is the CALLER's timeout, not evidence
                # against the peer: neither a breaker failure nor retried —
                # but a held half-open probe slot must be released, or the
                # breaker wedges HALF_OPEN forever
                breaker.on_probe_abandoned()
                self._metrics().inc(SCOPE_RPC_CLIENT, "deadline-expired")
                raise
            except BaseException as exc:
                retryable = self._classify(exc, idempotent)
                # a LOCAL failure (encode raised before any byte left this
                # process) says NOTHING about the peer: charge neither way,
                # only release a held half-open probe slot
                if getattr(exc, "_wire_local", False):
                    breaker.on_probe_abandoned()
                    raise
                # a RELAYED error (the peer answered ("err", exc) — its OWN
                # outbound hop may have failed) is a healthy peer talking:
                # it must not open THIS target's breaker or drop a live
                # socket, even when the payload is ConnectionError-shaped
                relayed = getattr(exc, "_wire_relayed", False)
                if (isinstance(exc, (ConnectionError, OSError, WireError))
                        and not relayed):
                    # a transport failure with the caller's deadline budget
                    # EXHAUSTED is the same case as DeadlineExceeded above,
                    # just detected mid-flight: the socket timeout was
                    # clamped to the remaining budget (wire.effective_
                    # timeout), so a healthy peer at normal latency still
                    # times out. Charging the breaker here would let a few
                    # tight-deadline callers open it against a healthy
                    # target for everyone. Drop the socket (its stream
                    # state is unknown) but stay breaker-neutral.
                    current = deadline_mod.current()
                    if current is not None and current.remaining() <= 0:
                        breaker.on_probe_abandoned()
                    else:
                        breaker.on_failure()
                    self._drop_connection()
                else:
                    # a typed SERVICE error is a healthy peer answering
                    breaker.on_success()
                if not retryable:
                    raise
                sleep_s = self.retry_policy.next_interval(
                    attempt, time.monotonic() - started)
                if sleep_s == NO_BACKOFF:
                    raise
                current = deadline_mod.current()
                if current is not None and current.remaining() <= sleep_s:
                    raise  # the budget cannot absorb another attempt
                self._metrics().inc(SCOPE_RPC_CLIENT, "retries")
                attempt += 1
                time.sleep(sleep_s)
                continue
            breaker.on_success()
            return result

    def _call_once(self, request):
        # the calling thread's active span and deadline budget ride the
        # envelope, so the serving side parents its span on ours AND
        # rejects work whose budget is already gone (cross-hop stitching
        # + cross-hop deadlines on the same seam)
        conn = self._connection()
        try:
            return conn.call(
                deadline_mod.inject(tracing.inject(request)))
        except (ConnectionError, OSError, WireError) as exc:
            # a RELAYED ConnectionError-shaped payload arrived on a
            # perfectly live socket (the peer answered): keep it pooled
            if not getattr(exc, "_wire_relayed", False):
                self._drop_connection()
            raise

    @staticmethod
    def _classify(exc: BaseException, idempotent: bool) -> bool:
        """Is this failure safe to retry for THIS request?

        The dangerous case is a LOST RESPONSE: the op may have passed its
        commit point, so blind resend double-applies — hence transport
        faults retry only for idempotent requests. A typed injected fault
        is different even when RELAYED from a deeper hop: the failing op
        RAISED, so its transaction never committed, and re-executing the
        whole mutation heals through the commit-point design (history
        writes are id-stable overwrites, the state update is a fenced
        CAS last — tests/test_faults.py torn-tail semantics; the chaos
        soak's byte-identical checksums are the empirical check)."""
        if isinstance(exc, (ChaosError, TransientStoreError)):
            return True
        if isinstance(exc, CircuitOpenError):
            return False
        if isinstance(exc, (ConnectionError, OSError, WireError)):
            return idempotent
        return False


class RemoteStores:
    """Duck-typed `Stores` whose sub-stores forward over the wire. The
    authoritative locks, CAS conditions, and range-ID fences all evaluate
    in the store-server process — which is what makes fencing hold across
    HOSTS, exactly as the reference's DB-evaluated conditional writes do."""

    def __init__(self, address: Tuple[str, int], metrics=None,
                 breakers: Optional[BreakerRegistry] = None,
                 retry_policy: Optional[RetryPolicy] = None) -> None:
        self.address = address
        self._pool = _Pool(address, metrics=metrics, breakers=breakers,
                           retry_policy=retry_policy)
        for sub in SUBSTORES:
            setattr(self, sub, _RemoteSubStore(self._pool, sub))

    def heartbeat(self, name: str, port: int,
                  address: str = "127.0.0.1") -> None:
        self._pool.call(("hb", name, port, address))

    def peers(self, ttl: float):
        return self._pool.call(("peers", ttl))

    def ping(self) -> str:
        return self._pool.call(("ping",))


class _RemoteMethod:
    """A dotted method path on a remote engine: callable, and further
    attribute access extends the path (`engine.queries.attach(...)` →
    path "queries.attach" resolved by getattr-chain on the owning host)."""

    def __init__(self, pool: "_Pool", workflow_id: str, path: str) -> None:
        self._pool = pool
        self._workflow_id = workflow_id
        self._path = path

    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)
        return _RemoteMethod(self._pool, self._workflow_id,
                             f"{self._path}.{name}")

    def __call__(self, *args, **kwargs):
        return self._pool.call(("engine", self._workflow_id, self._path,
                                args, kwargs))


class RemoteEngine:
    """History-engine proxy: forwards any engine method for workflows the
    local host does not own to the owning host (the client/history
    peer-resolver redirect, SURVEY §3.1 PROCESS BOUNDARY)."""

    def __init__(self, address: Tuple[str, int], workflow_id: str,
                 metrics=None,
                 breakers: Optional[BreakerRegistry] = None,
                 retry_policy: Optional[RetryPolicy] = None) -> None:
        self._pool = _Pool(address, metrics=metrics, breakers=breakers,
                           retry_policy=retry_policy)
        self._workflow_id = workflow_id

    def __getattr__(self, method: str):
        if method.startswith("_"):
            raise AttributeError(method)
        return _RemoteMethod(self._pool, self._workflow_id, method)


class _RoutedMethod:
    """Dotted method path issued as an engine_routed op (any live host of
    the TARGET CLUSTER forwards to its ring's owner)."""

    def __init__(self, cluster: "RemoteCluster", workflow_id: str,
                 path: str) -> None:
        self._cluster = cluster
        self._workflow_id = workflow_id
        self._path = path

    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)
        return _RoutedMethod(self._cluster, self._workflow_id,
                             f"{self._path}.{name}")

    def __call__(self, *args, **kwargs):
        return self._cluster._call_routed(self._workflow_id, self._path,
                                          args, kwargs)


class RemoteCluster:
    """A PEER CLUSTER reached through its store server: live hosts are
    discovered from the peer's heartbeat table (no static host config —
    the cluster-group yaml's rpcAddress plus membership, collapsed), and
    engine calls enter through any live host's engine_routed op.

    Reference: common/rpc/outbounds.go crossDCCaller + cluster-group
    config (config/development_xdc_cluster0.yaml:71-94)."""

    #: rounds of peer-list refresh before giving up on the whole cluster
    MAX_ROUNDS = 4

    def __init__(self, store_address: Tuple[str, int],
                 peer_ttl: float = 3.0, metrics=None,
                 breakers: Optional[BreakerRegistry] = None,
                 retry_policy: Optional[RetryPolicy] = None) -> None:
        self.store_address = store_address
        self.metrics = metrics
        self.breakers = breakers if breakers is not None else DEFAULT_BREAKERS
        self.retry_policy = retry_policy
        self.stores = RemoteStores(store_address, metrics=metrics,
                                   breakers=breakers,
                                   retry_policy=retry_policy)
        self.peer_ttl = peer_ttl
        self._host_pools: dict = {}
        #: jittered backoff BETWEEN peer-list refresh rounds (the old code
        #: hammered a one-shot snapshot with zero delay); max_attempts ==
        #: MAX_ROUNDS so the LAST round raises immediately instead of
        #: sleeping a dead backoff first
        self._round_policy = RetryPolicy(init_interval_s=0.05,
                                         max_interval_s=0.5,
                                         max_attempts=self.MAX_ROUNDS)

    def live_host_pools(self):
        """One _Pool per live peer host, preferring already-open pools.
        Peers dial the ADVERTISED host from the heartbeat table (old
        2-tuple entries imply loopback)."""
        peers = self.stores.peers(self.peer_ttl)
        pools = []
        for entry in peers:
            key = ((entry[2], entry[1]) if len(entry) > 2
                   else ("127.0.0.1", entry[1]))
            if key not in self._host_pools:
                self._host_pools[key] = _Pool(
                    key, metrics=self.metrics, breakers=self.breakers,
                    retry_policy=self.retry_policy)
            pools.append(self._host_pools[key])
        return pools

    def _call_routed(self, workflow_id: str, path: str, args, kwargs):
        """Try every live host; on a whole-round failure RE-FETCH the
        heartbeat peer list (hosts that died since the last snapshot drop
        out, restarts re-appear) and back off with jitter before the next
        round. Breaker-open hosts are skipped — a dead entry host sheds
        instantly instead of eating a connect timeout per call."""
        last: Exception = ConnectionError(
            f"no live hosts behind store {self.store_address}")
        started = time.monotonic()
        for round_no in range(self.MAX_ROUNDS):
            try:
                pools = self.live_host_pools()
            except (ConnectionError, OSError) as exc:
                pools, last = [], exc
            for pool in pools:
                try:
                    return pool.call(("engine_routed", workflow_id, path,
                                      args, kwargs))
                except CircuitOpenError as exc:
                    last = exc  # shed: next host, no wire time burned
                except (ConnectionError, OSError) as exc:
                    # entry host died between heartbeat and call: next one
                    last = exc
            sleep_s = self._round_policy.next_interval(
                round_no, time.monotonic() - started)
            if sleep_s == NO_BACKOFF:
                break
            current = deadline_mod.current()
            if current is not None and current.remaining() <= sleep_s:
                break
            time.sleep(sleep_s)
        raise last

    def engine(self, workflow_id: str) -> "_RoutedMethod":
        """An engine proxy routed via any live host of this cluster."""

        class _Root:
            def __getattr__(_self, method: str):
                if method.startswith("_"):
                    raise AttributeError(method)
                return _RoutedMethod(self, workflow_id, method)

        return _Root()


class RemoteMatching:
    """Matching proxy for task lists owned by another host. Long polls
    travel as a server-side blocking op (the gRPC long-poll analog), so no
    live ParkedPoll object ever crosses the wire. Shares the process's
    breaker registry, so a dead matching owner sheds instantly."""

    def __init__(self, address: Tuple[str, int], metrics=None,
                 breakers: Optional[BreakerRegistry] = None,
                 retry_policy: Optional[RetryPolicy] = None) -> None:
        self._pool = _Pool(address, metrics=metrics, breakers=breakers,
                           retry_policy=retry_policy)

    def __getattr__(self, method: str):
        if method.startswith("_"):
            raise AttributeError(method)
        pool = self._pool

        def invoke(*args, **kwargs):
            return pool.call(("matching", method, args, kwargs))

        return invoke
