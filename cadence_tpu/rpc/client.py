"""Client proxies: stores, engines, and matching across the wire.

Reference: client/ wraps every inter-service call (history peer resolver
by workflowID→shard→host, matching by task list) behind typed clients;
here the same seams are generic method-forwarding proxies over wire.py —
the duck typing that lets the whole engine tier run unmodified against a
remote store server (the persistence managers' interface IS the contract,
dataManagerInterfaces.go analog).
"""
from __future__ import annotations

import threading
from typing import Tuple

from .wire import Connection

#: every sub-store a Stores bundle exposes (persistence.Stores fields)
SUBSTORES = ("shard", "history", "task", "domain", "visibility", "queue",
             "shard_tasks", "execution")


class _RemoteSubStore:
    def __init__(self, pool: "_Pool", sub: str) -> None:
        self._pool = pool
        self._sub = sub

    def __getattr__(self, method: str):
        if method.startswith("_"):
            raise AttributeError(method)
        pool, sub = self._pool, self._sub

        def invoke(*args, **kwargs):
            return pool.call(("store", sub, method, args, kwargs))

        invoke.__name__ = f"{sub}.{method}"
        return invoke


class _Pool:
    """Per-thread connections to one address (engine transactions issue
    several store calls in sequence; a per-thread socket keeps them
    pipelined without cross-talk)."""

    def __init__(self, address: Tuple[str, int]) -> None:
        self.address = address
        self._local = threading.local()

    def call(self, request):
        conn = getattr(self._local, "conn", None)
        if conn is None:
            conn = Connection(self.address)
            self._local.conn = conn
        return conn.call(request)


class RemoteStores:
    """Duck-typed `Stores` whose sub-stores forward over the wire. The
    authoritative locks, CAS conditions, and range-ID fences all evaluate
    in the store-server process — which is what makes fencing hold across
    HOSTS, exactly as the reference's DB-evaluated conditional writes do."""

    def __init__(self, address: Tuple[str, int]) -> None:
        self.address = address
        self._pool = _Pool(address)
        for sub in SUBSTORES:
            setattr(self, sub, _RemoteSubStore(self._pool, sub))

    def heartbeat(self, host: str, port: int) -> None:
        self._pool.call(("hb", host, port))

    def peers(self, ttl: float):
        return self._pool.call(("peers", ttl))

    def ping(self) -> str:
        return self._pool.call(("ping",))


class _RemoteMethod:
    """A dotted method path on a remote engine: callable, and further
    attribute access extends the path (`engine.queries.attach(...)` →
    path "queries.attach" resolved by getattr-chain on the owning host)."""

    def __init__(self, pool: "_Pool", workflow_id: str, path: str) -> None:
        self._pool = pool
        self._workflow_id = workflow_id
        self._path = path

    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)
        return _RemoteMethod(self._pool, self._workflow_id,
                             f"{self._path}.{name}")

    def __call__(self, *args, **kwargs):
        return self._pool.call(("engine", self._workflow_id, self._path,
                                args, kwargs))


class RemoteEngine:
    """History-engine proxy: forwards any engine method for workflows the
    local host does not own to the owning host (the client/history
    peer-resolver redirect, SURVEY §3.1 PROCESS BOUNDARY)."""

    def __init__(self, address: Tuple[str, int], workflow_id: str) -> None:
        self._pool = _Pool(address)
        self._workflow_id = workflow_id

    def __getattr__(self, method: str):
        if method.startswith("_"):
            raise AttributeError(method)
        return _RemoteMethod(self._pool, self._workflow_id, method)


class RemoteMatching:
    """Matching proxy for task lists owned by another host. Long polls
    travel as a server-side blocking op (the gRPC long-poll analog), so no
    live ParkedPoll object ever crosses the wire."""

    def __init__(self, address: Tuple[str, int]) -> None:
        self._pool = _Pool(address)

    def __getattr__(self, method: str):
        if method.startswith("_"):
            raise AttributeError(method)
        pool = self._pool

        def invoke(*args, **kwargs):
            return pool.call(("matching", method, args, kwargs))

        return invoke
