"""Client proxies: stores, engines, and matching across the wire.

Reference: client/ wraps every inter-service call (history peer resolver
by workflowID→shard→host, matching by task list) behind typed clients;
here the same seams are generic method-forwarding proxies over wire.py —
the duck typing that lets the whole engine tier run unmodified against a
remote store server (the persistence managers' interface IS the contract,
dataManagerInterfaces.go analog).
"""
from __future__ import annotations

import threading
from typing import Tuple

from ..utils import tracing
from .wire import Connection

#: every sub-store a Stores bundle exposes (persistence.Stores fields)
SUBSTORES = ("shard", "history", "task", "domain", "visibility", "queue",
             "shard_tasks", "execution")


class _RemoteSubStore:
    def __init__(self, pool: "_Pool", sub: str) -> None:
        self._pool = pool
        self._sub = sub

    def __getattr__(self, method: str):
        if method.startswith("_"):
            raise AttributeError(method)
        pool, sub = self._pool, self._sub

        def invoke(*args, **kwargs):
            return pool.call(("store", sub, method, args, kwargs))

        invoke.__name__ = f"{sub}.{method}"
        return invoke


class _Pool:
    """Per-thread connections to one address (engine transactions issue
    several store calls in sequence; a per-thread socket keeps them
    pipelined without cross-talk)."""

    def __init__(self, address: Tuple[str, int]) -> None:
        self.address = address
        self._local = threading.local()

    def call(self, request):
        conn = getattr(self._local, "conn", None)
        if conn is None:
            conn = Connection(self.address)
            self._local.conn = conn
        # the calling thread's active span rides the envelope, so the
        # serving side parents its span on ours (cross-hop stitching)
        return conn.call(tracing.inject(request))


class RemoteStores:
    """Duck-typed `Stores` whose sub-stores forward over the wire. The
    authoritative locks, CAS conditions, and range-ID fences all evaluate
    in the store-server process — which is what makes fencing hold across
    HOSTS, exactly as the reference's DB-evaluated conditional writes do."""

    def __init__(self, address: Tuple[str, int]) -> None:
        self.address = address
        self._pool = _Pool(address)
        for sub in SUBSTORES:
            setattr(self, sub, _RemoteSubStore(self._pool, sub))

    def heartbeat(self, name: str, port: int,
                  address: str = "127.0.0.1") -> None:
        self._pool.call(("hb", name, port, address))

    def peers(self, ttl: float):
        return self._pool.call(("peers", ttl))

    def ping(self) -> str:
        return self._pool.call(("ping",))


class _RemoteMethod:
    """A dotted method path on a remote engine: callable, and further
    attribute access extends the path (`engine.queries.attach(...)` →
    path "queries.attach" resolved by getattr-chain on the owning host)."""

    def __init__(self, pool: "_Pool", workflow_id: str, path: str) -> None:
        self._pool = pool
        self._workflow_id = workflow_id
        self._path = path

    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)
        return _RemoteMethod(self._pool, self._workflow_id,
                             f"{self._path}.{name}")

    def __call__(self, *args, **kwargs):
        return self._pool.call(("engine", self._workflow_id, self._path,
                                args, kwargs))


class RemoteEngine:
    """History-engine proxy: forwards any engine method for workflows the
    local host does not own to the owning host (the client/history
    peer-resolver redirect, SURVEY §3.1 PROCESS BOUNDARY)."""

    def __init__(self, address: Tuple[str, int], workflow_id: str) -> None:
        self._pool = _Pool(address)
        self._workflow_id = workflow_id

    def __getattr__(self, method: str):
        if method.startswith("_"):
            raise AttributeError(method)
        return _RemoteMethod(self._pool, self._workflow_id, method)


class _RoutedMethod:
    """Dotted method path issued as an engine_routed op (any live host of
    the TARGET CLUSTER forwards to its ring's owner)."""

    def __init__(self, cluster: "RemoteCluster", workflow_id: str,
                 path: str) -> None:
        self._cluster = cluster
        self._workflow_id = workflow_id
        self._path = path

    def __getattr__(self, name: str):
        if name.startswith("_"):
            raise AttributeError(name)
        return _RoutedMethod(self._cluster, self._workflow_id,
                             f"{self._path}.{name}")

    def __call__(self, *args, **kwargs):
        return self._cluster._call_routed(self._workflow_id, self._path,
                                          args, kwargs)


class RemoteCluster:
    """A PEER CLUSTER reached through its store server: live hosts are
    discovered from the peer's heartbeat table (no static host config —
    the cluster-group yaml's rpcAddress plus membership, collapsed), and
    engine calls enter through any live host's engine_routed op.

    Reference: common/rpc/outbounds.go crossDCCaller + cluster-group
    config (config/development_xdc_cluster0.yaml:71-94)."""

    def __init__(self, store_address: Tuple[str, int],
                 peer_ttl: float = 3.0) -> None:
        self.store_address = store_address
        self.stores = RemoteStores(store_address)
        self.peer_ttl = peer_ttl
        self._host_pools: dict = {}

    def live_host_pools(self):
        """One _Pool per live peer host, preferring already-open pools.
        Peers dial the ADVERTISED host from the heartbeat table (old
        2-tuple entries imply loopback)."""
        peers = self.stores.peers(self.peer_ttl)
        pools = []
        for entry in peers:
            key = ((entry[2], entry[1]) if len(entry) > 2
                   else ("127.0.0.1", entry[1]))
            if key not in self._host_pools:
                self._host_pools[key] = _Pool(key)
            pools.append(self._host_pools[key])
        return pools

    def _call_routed(self, workflow_id: str, path: str, args, kwargs):
        last: Exception = ConnectionError(
            f"no live hosts behind store {self.store_address}")
        for pool in self.live_host_pools():
            try:
                return pool.call(("engine_routed", workflow_id, path,
                                  args, kwargs))
            except (ConnectionError, OSError) as exc:
                # entry host died between heartbeat and call: next one
                last = exc
        raise last

    def engine(self, workflow_id: str) -> "_RoutedMethod":
        """An engine proxy routed via any live host of this cluster."""

        class _Root:
            def __getattr__(_self, method: str):
                if method.startswith("_"):
                    raise AttributeError(method)
                return _RoutedMethod(self, workflow_id, method)

        return _Root()


class RemoteMatching:
    """Matching proxy for task lists owned by another host. Long polls
    travel as a server-side blocking op (the gRPC long-poll analog), so no
    live ParkedPoll object ever crosses the wire."""

    def __init__(self, address: Tuple[str, int]) -> None:
        self._pool = _Pool(address)

    def __getattr__(self, method: str):
        if method.startswith("_"):
            raise AttributeError(method)
        pool = self._pool

        def invoke(*args, **kwargs):
            return pool.call(("matching", method, args, kwargs))

        return invoke
