#!/usr/bin/env bash
# Observability smoke: boot a onebox, run one workflow, device-replay it,
# scrape /metrics + /health, and FAIL on missing required metric names
# (the assertions live in tests/test_observability.py::TestScrapeSurface) —
# plus the cluster telemetry plane (tests/test_telemetry.py smoke): the
# /timeseries + /hostprof + /flightrec routes, the fleet `admin top`
# rollup over a live 2-host wire cluster with burn-rate gauges, and the
# SIGTERM'd host dumping its own flight record.
#
# Usage: deploy/smoke_observability.sh [extra pytest args]
set -euo pipefail
cd "$(dirname "$0")/.."
exec env JAX_PLATFORMS=cpu python -m pytest \
    tests/test_observability.py tests/test_telemetry.py \
    -m smoke -q "$@"
