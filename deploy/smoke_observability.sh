#!/usr/bin/env bash
# Observability smoke: boot a onebox, run one workflow, device-replay it,
# scrape /metrics + /health, and FAIL on missing required metric names
# (the assertions live in tests/test_observability.py::TestScrapeSurface).
#
# Usage: deploy/smoke_observability.sh [extra pytest args]
set -euo pipefail
cd "$(dirname "$0")/.."
exec env JAX_PLATFORMS=cpu python -m pytest tests/test_observability.py \
    -m smoke -q "$@"
