#!/usr/bin/env bash
# Chaos smoke: boot a real 2-host wire cluster twice — once clean, once
# under seeded wire chaos (drops, delays, severed connections) + injected
# store errors — and FAIL unless the final mutable-state checksums are
# byte-identical and the retry/breaker/deadline metrics are observable on
# /metrics (the assertions live in tests/test_chaos_soak.py, marked
# `chaos`; wired like deploy/smoke_observability.sh).
#
# Usage: deploy/smoke_chaos.sh [extra pytest args]
set -euo pipefail
cd "$(dirname "$0")/.."
exec env JAX_PLATFORMS=cpu python -m pytest tests/test_chaos_soak.py \
    -m chaos -q "$@"
