#!/usr/bin/env bash
# Multi-host serving smoke: the cluster subsystem's two wire gates
# (tests/test_cluster_serving.py, markers slow+load):
#
#   (1) kill-host-mid-traffic — a 3-host wire cluster with the serving
#       tier ON in every host process is driven by seeded open-loop
#       signal-dominant traffic; one host is SIGKILLed mid-window. The
#       gate: the victim domain's p99 (clocked from intended send time)
#       holds its SLO, zero parity divergence anywhere (serving tier,
#       migration hydration, post-run oracle<->device verify), the
#       survivors' stolen-shard admits are >=80% snapshot-hydrated (a
#       warm failover, not a replay storm), and events/s/cluster is
#       recorded next to events/s/pod;
#   (2) planned rebalance — the cluster grows by one host; the losing
#       hosts snapshot their moving resident rows through the shared
#       store, the gaining host hydrates, and every migrated row's
#       payload CRC is byte-identical to the oracle.
#
# The scenario duration is env-tunable (CLUSTER_DURATION_S). The hosts
# pre-compile their flush kernels at boot (CADENCE_TPU_SERVING_WARM);
# the first run on a fresh machine pays those compiles once into the
# persistent JAX cache.
#
# Usage: deploy/smoke_multihost.sh [extra pytest args]
set -euo pipefail
cd "$(dirname "$0")/.."
exec env JAX_PLATFORMS=cpu \
    CLUSTER_DURATION_S="${CLUSTER_DURATION_S:-12}" \
    python -m pytest tests/test_cluster_serving.py -q "$@"
