#!/usr/bin/env bash
# The compose topology without docker: store server + two service hosts
# as background processes (PID-file managed). `xdc` brings up TWO
# clusters wired as a replication group.
#
#   ./deploy/local_cluster.sh up [xdc]
#   ./deploy/local_cluster.sh status
#   ./deploy/local_cluster.sh down
set -euo pipefail

REPO="$(cd "$(dirname "$0")/.." && pwd)"
RUN_DIR="${CADENCE_TPU_RUN_DIR:-/tmp/cadence_tpu_cluster}"
PIDS="$RUN_DIR/pids"
export JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}"
export PYTHONPATH="$REPO${PYTHONPATH:+:$PYTHONPATH}"

spawn() { # name cmd...
  local name="$1"; shift
  nohup "$@" >"$RUN_DIR/$name.log" 2>&1 &
  echo "$! $name" >> "$PIDS"
  echo "started $name (pid $!)"
}

wait_port() { # port
  for _ in $(seq 1 100); do
    python - "$1" <<'EOF' && return 0 || sleep 0.1
import socket, sys
s = socket.socket(); s.settimeout(0.2)
sys.exit(0 if s.connect_ex(("127.0.0.1", int(sys.argv[1]))) == 0 else 1)
EOF
  done
  echo "port $1 never listened" >&2; return 1
}

up() {
  mkdir -p "$RUN_DIR"
  if [ -f "$PIDS" ]; then
    while read -r pid name; do
      if kill -0 "$pid" 2>/dev/null; then
        echo "refusing: $name (pid $pid) still running — run down first" >&2
        exit 1
      fi
    done < "$PIDS"
  fi
  : > "$PIDS"
  spawn store python -m cadence_tpu.rpc.storeserver --port 7240 \
      --wal "$RUN_DIR/primary.wal"
  wait_port 7240
  local peer_args=()
  if [ "${1:-}" = "xdc" ]; then
    spawn store-standby python -m cadence_tpu.rpc.storeserver --port 7250 \
        --wal "$RUN_DIR/standby.wal"
    wait_port 7250
    peer_args=(--peer standby=127.0.0.1:7250)
    for i in 0 1; do
      spawn "standby-host-$i" python -m cadence_tpu.rpc.server \
          --name "standby-host-$i" --port "725$((i+1))" \
          --store 127.0.0.1:7250 --num-shards 16 \
          --cluster-name standby --peer primary=127.0.0.1:7240 \
          --http-port "825$((i+1))"
    done
  fi
  for i in 0 1; do
    spawn "host-$i" python -m cadence_tpu.rpc.server \
        --name "host-$i" --port "724$((i+1))" \
        --store 127.0.0.1:7240 --num-shards 16 \
        --cluster-name primary ${peer_args[@]+"${peer_args[@]}"} \
        --http-port "824$((i+1))"
  done
  wait_port 7241
  echo "cluster up: store 127.0.0.1:7240, frontends 7241/7242," \
       "scrape http://127.0.0.1:8241/metrics (logs in $RUN_DIR)"
}

down() {
  [ -f "$PIDS" ] || { echo "nothing running"; return 0; }
  while read -r pid name; do
    kill "$pid" 2>/dev/null && echo "stopped $name" || true
  done < "$PIDS"
  rm -f "$PIDS"
}

status() {
  [ -f "$PIDS" ] || { echo "nothing running"; return 0; }
  while read -r pid name; do
    if kill -0 "$pid" 2>/dev/null; then echo "$name: up (pid $pid)"
    else echo "$name: DEAD"; fi
  done < "$PIDS"
}

case "${1:-}" in
  up) up "${2:-}" ;;
  down) down ;;
  status) status ;;
  *) echo "usage: $0 up [xdc] | down | status" >&2; exit 2 ;;
esac
