#!/usr/bin/env bash
# Generative-fuzzer smoke (ISSUE 15 acceptance): a 50-seed fuzzed corpus
# composing ALL 13 decision types (asserted by the coverage counter)
# must replay with zero oracle<->device divergence on the dense and
# wirec paths AND through verify_all (resident/ladder engine tier, NDC
# conflict forks included), and one seeded interleaving run — live
# start/signal/signal-with-start/reset/query/decision traffic against a
# serving-enabled durable Onebox under op chaos + store faults +
# crashpoint kills — must hold tpu.serving/parity-divergence == 0 with
# final checksums byte-identical to a fault-free run and a clean
# recovery fsck at every kill. The run records the next FUZZ_r0N.json
# trajectory next to the BENCH/LOADGEN files.
#
# Usage: deploy/smoke_fuzz.sh [extra `fuzz run` args]
set -euo pipefail
cd "$(dirname "$0")/.."
exec env JAX_PLATFORMS=cpu python -m cadence_tpu fuzz run \
    --seeds "${FUZZ_SEEDS:-50}" --workflows "${FUZZ_WORKFLOWS:-4}" \
    --events "${FUZZ_EVENTS:-100}" --interleave --record "$@"
