#!/usr/bin/env bash
# Load smoke: run the two-domain overload scenario against a REAL 2-host
# wire cluster for 30s — one domain (the aggressor) driven at 2x its
# per-domain quota, the other (the victim) running the standard mixed
# open-loop traffic, seeded wire chaos in every process AND seeded
# store faults in the store-server process (CADENCE_TPU_STORE_FAULTS
# via the env_per_role seam) — and FAIL unless
#   (a) the victim domain's p99 (clocked from intended send time) holds
#       its SLO,
#   (b) the shed counters are NONZERO on the hosts' /metrics and >= 90%
#       of the aggressor's overflow was rejected as typed ServiceBusy,
#   (c) every workflow the traffic produced verifies oracle<->device with
#       zero checksum divergence.
# The assertions live in tests/test_loadgen.py (marker `load`); the
# scenario duration/SLO are env-tunable (LOADGEN_DURATION_S, LOADGEN_*).
#
# Usage: deploy/smoke_load.sh [extra pytest args]
set -euo pipefail
cd "$(dirname "$0")/.."
exec env JAX_PLATFORMS=cpu \
    LOADGEN_DURATION_S="${LOADGEN_DURATION_S:-30}" \
    python -m pytest tests/test_loadgen.py -m load -q "$@"
