#!/usr/bin/env bash
# Crash-consistency smoke: sweep the kill-anywhere WAL cut-point matrix on
# BOTH storage backends — seed a onebox workload, truncate the log at every
# record boundary (plus torn mid-record tails on JSONL), recover at each
# cut, and FAIL unless every recovered state is byte-identical to a
# fault-free prefix state with zero recovery-fsck findings (the assertions
# live in tests/test_crashsim.py, marked `crash`; the same sweep is
# runnable by hand via `python -m cadence_tpu --wal X wal crashsim
# --seed-workload 4`).
#
# Usage: deploy/smoke_crash.sh [extra pytest args]
set -euo pipefail
cd "$(dirname "$0")/.."
exec env JAX_PLATFORMS=cpu python -m pytest tests/test_crashsim.py \
    -m crash -q "$@"
