#!/usr/bin/env bash
# Fleet-chaos smoke (ISSUE 18 acceptance): a seeded 3-host campaign with
# real SIGKILLs of a service host AND the store server (its WAL fsck'd
# clean before relaunch), one asymmetric partition cut+healed
# mid-traffic, and one membership flap (SIGSTOP past the heartbeat TTL,
# then SIGCONT -> ring rejoin -> fenced shard re-acquire) must end with
# per-workflow checksums byte-identical to a fault-free run of the same
# seed, zero tpu.serving/tpu.migration/replication parity divergence
# summed across every live host, and a clean closing verify_all. The
# run records the next CHAOS_r0N.json trajectory (kill/partition/flap
# counts, checksum identity, fsck findings) next to the BENCH/FUZZ
# files. A validation arm (--shrink) proves ddmin reduces an injected
# kill-then-signal regression to its 1-minimal 2-op campaign.
#
# Usage: deploy/smoke_fleetchaos.sh [extra `fuzz cluster` args]
set -euo pipefail
cd "$(dirname "$0")/.."
exec env JAX_PLATFORMS=cpu python -m cadence_tpu fuzz cluster \
    --seed "${FLEETCHAOS_SEED:-20260806}" \
    --hosts "${FLEETCHAOS_HOSTS:-3}" \
    --workflows "${FLEETCHAOS_WORKFLOWS:-6}" \
    --kills "${FLEETCHAOS_KILLS:-1}" \
    --store-kills "${FLEETCHAOS_STORE_KILLS:-1}" \
    --partitions "${FLEETCHAOS_PARTITIONS:-1}" \
    --flaps "${FLEETCHAOS_FLAPS:-1}" \
    --record "$@"
