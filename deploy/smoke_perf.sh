#!/usr/bin/env bash
# Perf smoke: run the SMALL bench suite through the pipelined bulk
# executor, write the JSON next to the recorded BENCH_r*.json trajectory
# (PERF_smoke.json), and FAIL unless:
#   - crc_parity_wire32 (and the pipelined-path parity) hold;
#   - every suite's transfer_included_rate stays within PERF_TOLERANCE
#     (default 0.5x) of the recorded baseline — by default the newest
#     BENCH_r*.json, overridable with the first arg;
#   - the fallback-under-pressure gate holds: the capacity-escalation
#     ladder's arbitration stays CRC-identical to the oracle-only path,
#     warm trials recompile nothing, and fallback_under_pressure
#     .mixed_rate_median stays within PERF_TOLERANCE of the baseline's —
#     CI catches a reintroduced overflow cliff (BENCH_r05's 3x collapse)
#     right here;
#   - the incremental gate holds: append transactions through the
#     resident-state cache cost O(new events) — long-history appends
#     within 1.5x of short-history appends at equal suffix size
#     (detail.incremental in the recorded JSON);
#   - the SNAPSHOT gate holds (TestSnapshotGate, ISSUE 11): restarting
#     with persisted mutable-state snapshots rebuilds warm — hydrate +
#     replay only the since-snapshot suffix — in <= 0.3x the cold
#     full-replay time on a long-history corpus, with zero cold-vs-warm
#     state divergence and every workflow hydrated from its record
#     (detail.snapshot in the recorded JSON);
#   - the MESH gate holds (TestMeshGate): the serving executor on a mesh
#     of 1 stays byte-identical to the unsharded kernel, warm passes
#     recompile nothing across mesh shapes already seen, mesh-of-N
#     checksums equal mesh-of-1 (detail.mesh_serving.checksum_identity),
#     the recorded mesh-of-1 rate holds vs baseline, and per-device
#     efficiency ≥ 0.7 on a REAL multi-device mesh (a virtual CPU mesh
#     time-shares cores, so only the identity half applies there). The
#     gate runs on a virtual-device CPU mesh via the same
#     --xla_force_host_platform_device_count trick dryrun_multichip
#     uses; CADENCE_TPU_MESH_DEVICES (default 8 here, default 1 in
#     production serving — set it to shard the serving hot path across
#     N devices) sizes it.
#   - the SERVING gate holds (TestServingGate, ISSUE 10): at
#     concurrency >= 8 the device-serving transaction tier coalesces
#     multiple committed transactions per from-state launch (factor
#     > 1.5 at saturation), micro-batched p99 stays at or below the
#     one-launch-per-transaction baseline, warm flushes recompile
#     nothing, and per-transaction oracle<->device parity holds with a
#     zero divergence counter (detail.serving in the recorded JSON);
#   - the FEEDER gate holds (TestFeederGate, ISSUE 9): the native-wirec
#     feeder's sustained ingest rate stays within FEEDER_GATE_RATIO
#     (default 0.5, i.e. within 2x) of the recorded device
#     transfer-included rate, holds vs the baseline's feeder rate, the
#     suffix-append leg costs by appended events, and a warm
#     homogeneous stream provably compiles nothing new;
#   - the VISIBILITY gate holds (TestVisibilityGate, ISSUE 12): every
#     device-served List/Scan/Count answers with exactly the host
#     store's result ids (parity divergence pinned at 0), warm repeats
#     of a seen query shape recompile nothing, and the recorded
#     detail.visibility section carries the rows/s-scanned sweep (the
#     device-vs-host rate gate engages on real-device recordings only);
#   - the pure-Python wirec fallback stays byte-identical: the full
#     feeder + wirec test suites run AGAIN with the native encoder
#     disabled (CADENCE_TPU_NATIVE_WIREC=0), so a native-only
#     divergence can never hide behind the fast path.
# The assertions live in tests/test_perf_gate.py, marked `perf`.
#
# Usage: deploy/smoke_perf.sh [baseline.json] [extra pytest args]
set -euo pipefail
cd "$(dirname "$0")/.."

BASELINE="${1:-}"
if [ $# -ge 1 ]; then shift; fi
if [ -z "$BASELINE" ]; then
    BASELINE=$(ls -1 BENCH_r*.json 2>/dev/null | sort | tail -1 || true)
fi
[ -n "$BASELINE" ] || { echo "no baseline BENCH_r*.json found"; exit 1; }

OUT="PERF_smoke.json"
echo "perf smoke: baseline=$BASELINE -> $OUT"
env BENCH_NS_WORKFLOWS="${BENCH_NS_WORKFLOWS:-16384}" \
    BENCH_NS_EVENTS="${BENCH_NS_EVENTS:-128}" \
    BENCH_NS_CHUNK="${BENCH_NS_CHUNK:-4096}" \
    BENCH_SUITE_WORKFLOWS="${BENCH_SUITE_WORKFLOWS:-16384}" \
    BENCH_TRIALS="${BENCH_TRIALS:-3}" \
    BENCH_INCR_WORKFLOWS="${BENCH_INCR_WORKFLOWS:-512}" \
    BENCH_INCR_SHORT="${BENCH_INCR_SHORT:-32}" \
    BENCH_INCR_LONG="${BENCH_INCR_LONG:-256}" \
    BENCH_SNAP_WORKFLOWS="${BENCH_SNAP_WORKFLOWS:-256}" \
    BENCH_SNAP_EVENTS="${BENCH_SNAP_EVENTS:-384}" \
    BENCH_VIS_SIZES="${BENCH_VIS_SIZES:-5000,20000}" \
    BENCH_VIS_TRIALS="${BENCH_VIS_TRIALS:-3}" \
    python bench.py > "$OUT"

# mesh gate, on a virtual-device CPU mesh (the dryrun_multichip
# XLA_FLAGS trick; tests/conftest.py applies the same flag, so the
# in-process mesh tests see CADENCE_TPU_MESH_DEVICES virtual devices).
# When the main bench ran on a SINGLE device its recorded mesh_serving
# section is vacuous (devices=1, identity trivially true) — re-measure
# the serving executor on the virtual mesh and splice that in, so the
# recorded checksum-identity/rate gate always covers N > 1. A
# multi-device bench (real hardware) keeps its genuine section, and the
# ≥0.7 efficiency gate engages on it.
MESH_N="${CADENCE_TPU_MESH_DEVICES:-8}"
env CADENCE_TPU_MESH_DEVICES="$MESH_N" \
    XLA_FLAGS="--xla_force_host_platform_device_count=${MESH_N}" \
    JAX_PLATFORMS=cpu \
    BENCH_MESH_WORKFLOWS="${BENCH_MESH_WORKFLOWS:-1024}" \
    python - "$OUT" <<'PY'
import json, os, sys
import jax
jax.config.update("jax_platforms", "cpu")
out = sys.argv[1]
doc = json.load(open(out))
if doc["detail"].get("mesh_serving", {}).get("devices", 1) <= 1:
    import bench
    from cadence_tpu.core.checksum import DEFAULT_LAYOUT
    from cadence_tpu.utils import compile_cache
    compile_cache.enable()
    doc["detail"]["mesh_serving"] = bench._mesh_serving(
        int(os.environ["BENCH_MESH_WORKFLOWS"]), DEFAULT_LAYOUT)
    json.dump(doc, open(out, "w"))
    print("mesh_serving re-measured on the virtual mesh:",
          doc["detail"]["mesh_serving"]["devices"], "devices")
PY
env PERF_CURRENT="$OUT" PERF_BASELINE="$BASELINE" \
    CADENCE_TPU_MESH_DEVICES="$MESH_N" \
    XLA_FLAGS="--xla_force_host_platform_device_count=${MESH_N}" \
    JAX_PLATFORMS=cpu python -m pytest \
    tests/test_perf_gate.py::TestMeshGate -m perf -q

# python-fallback parity: the whole feeder/wirec suite with the native
# encoder pinned OFF — the byte-identical-fallback contract of ISSUE 9
env CADENCE_TPU_NATIVE_WIREC=0 JAX_PLATFORMS=cpu \
    python -m pytest tests/test_feeder.py tests/test_wirec.py \
    tests/test_native_packer.py -q

exec env PERF_CURRENT="$OUT" PERF_BASELINE="$BASELINE" \
    JAX_PLATFORMS=cpu python -m pytest tests/test_perf_gate.py \
    -m perf -q "$@"
