#!/usr/bin/env bash
# Perf smoke: run the SMALL bench suite through the pipelined bulk
# executor, write the JSON next to the recorded BENCH_r*.json trajectory
# (PERF_smoke.json), and FAIL unless:
#   - crc_parity_wire32 (and the pipelined-path parity) hold;
#   - every suite's transfer_included_rate stays within PERF_TOLERANCE
#     (default 0.5x) of the recorded baseline — by default the newest
#     BENCH_r*.json, overridable with the first arg;
#   - the fallback-under-pressure gate holds: the capacity-escalation
#     ladder's arbitration stays CRC-identical to the oracle-only path,
#     warm trials recompile nothing, and fallback_under_pressure
#     .mixed_rate_median stays within PERF_TOLERANCE of the baseline's —
#     CI catches a reintroduced overflow cliff (BENCH_r05's 3x collapse)
#     right here;
#   - the incremental gate holds: append transactions through the
#     resident-state cache cost O(new events) — long-history appends
#     within 1.5x of short-history appends at equal suffix size
#     (detail.incremental in the recorded JSON).
# The assertions live in tests/test_perf_gate.py, marked `perf`.
#
# Usage: deploy/smoke_perf.sh [baseline.json] [extra pytest args]
set -euo pipefail
cd "$(dirname "$0")/.."

BASELINE="${1:-}"
if [ $# -ge 1 ]; then shift; fi
if [ -z "$BASELINE" ]; then
    BASELINE=$(ls -1 BENCH_r*.json 2>/dev/null | sort | tail -1 || true)
fi
[ -n "$BASELINE" ] || { echo "no baseline BENCH_r*.json found"; exit 1; }

OUT="PERF_smoke.json"
echo "perf smoke: baseline=$BASELINE -> $OUT"
env BENCH_NS_WORKFLOWS="${BENCH_NS_WORKFLOWS:-16384}" \
    BENCH_NS_EVENTS="${BENCH_NS_EVENTS:-128}" \
    BENCH_NS_CHUNK="${BENCH_NS_CHUNK:-4096}" \
    BENCH_SUITE_WORKFLOWS="${BENCH_SUITE_WORKFLOWS:-16384}" \
    BENCH_TRIALS="${BENCH_TRIALS:-3}" \
    BENCH_INCR_WORKFLOWS="${BENCH_INCR_WORKFLOWS:-512}" \
    BENCH_INCR_SHORT="${BENCH_INCR_SHORT:-32}" \
    BENCH_INCR_LONG="${BENCH_INCR_LONG:-256}" \
    python bench.py > "$OUT"

exec env PERF_CURRENT="$OUT" PERF_BASELINE="$BASELINE" \
    JAX_PLATFORMS=cpu python -m pytest tests/test_perf_gate.py \
    -m perf -q "$@"
