#!/usr/bin/env bash
# Multi-region failover smoke: the active-active replication subsystem's
# gates (tests/test_multiregion.py):
#
#   (1) warm managed failover (in-process, tier-1 speed) — two regions
#       with snapshot-shipping replication filling the standby's
#       snapshot store; managed_failover pre-hydrates the promoting
#       serving tier BEFORE the active flip (warm steals, parity gated),
#       the bounded replication drain degrades to NDC instead of
#       blocking, and a prehydration failure never fails the flip;
#   (2) replication-seam fuzz — seeded interleaving of one-page apply
#       drains with live traffic, split-brain NDC promotion, poison
#       tasks, heal: byte-identical cross-region checksums, DLQ-only
#       quarantine, zero device-parity divergence (markers slow+fuzz
#       for the wide profile);
#   (3) region kill (wire, markers slow+load) — two real wire regions,
#       standard-mix traffic, SIGKILL of EVERY active-region process
#       mid-window, warm standby promotion under SLO, bounded pre-kill
#       replication lag, post-run oracle<->device verify on BOTH
#       regions (the killed one after relaunching its store from the
#       WAL it crashed with).
#
# The first run on a fresh machine pays the serving tier's flush-kernel
# compiles once into the persistent JAX cache.
#
# Usage: deploy/smoke_multiregion.sh [extra pytest args]
set -euo pipefail
cd "$(dirname "$0")/.."
exec env JAX_PLATFORMS=cpu \
    python -m pytest tests/test_multiregion.py -q "$@"
