"""Benchmark: batched history replay throughput on the available accelerator.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "events/s/chip", "vs_baseline": N}

The baseline is the derived per-chip north-star rate from BASELINE.md: 1M
workflows x 1k events on a v5e-8 in <60s => >=16.7M events/s aggregate
=> ~2.08M events/s/chip. vs_baseline = measured_rate / 2.08e6 (per chip).

The timed section is the honest end-to-end replay path: device scan over
the event axis + device payload assembly + device->host payload transfer +
host CRC32 — i.e. everything the reference's stateBuilder+checksum pair does
(state_builder.go ApplyEvents + execution/checksum.go), amortized over W
workflows in lockstep.

Env knobs: BENCH_WORKFLOWS (default 16384), BENCH_EVENTS (default 1000 —
the north-star history depth), BENCH_SUITE (default "basic"),
BENCH_REPEATS (default 3).
"""
import json
import os
import sys
import time

import numpy as np


def main() -> None:
    workflows = int(os.environ.get("BENCH_WORKFLOWS", "16384"))
    max_events = int(os.environ.get("BENCH_EVENTS", "1000"))
    suite = os.environ.get("BENCH_SUITE", "basic")
    repeats = int(os.environ.get("BENCH_REPEATS", "3"))

    import jax

    from cadence_tpu.core.checksum import crc32_of_rows
    from cadence_tpu.gen.corpus import generate_corpus
    from cadence_tpu.ops.encode import LANE_EVENT_ID, encode_corpus
    from cadence_tpu.ops.replay import replay_to_payload

    n_devices = jax.device_count()

    # generate a pool of distinct histories and tile to full width — replay
    # cost is shape-driven, identical rows don't change the arithmetic
    unique = min(256, workflows)
    histories = generate_corpus(suite, num_workflows=unique, seed=20260729,
                                target_events=max_events)
    pool = encode_corpus(histories)  # sized to the longest generated history
    reps = (workflows + unique - 1) // unique
    events_np = np.tile(pool, (reps, 1, 1))[:workflows]
    real_events = int((events_np[:, :, LANE_EVENT_ID] > 0).sum())

    events = jax.device_put(events_np)

    def run_once():
        rows, errors = replay_to_payload(events)
        rows_np = np.asarray(rows)  # device->host transfer
        crcs = crc32_of_rows(rows_np)
        return rows_np, crcs, np.asarray(errors)

    # warmup: compile + first run
    _, _, errors = run_once()
    n_errors = int((errors != 0).sum())

    t0 = time.perf_counter()
    for _ in range(repeats):
        run_once()
    elapsed = time.perf_counter() - t0

    rate_per_chip = real_events * repeats / elapsed / n_devices
    baseline_per_chip = 16_700_000 / 8  # BASELINE.md derived kernel rate
    print(json.dumps({
        "metric": "replay_events_per_sec_per_chip",
        "value": round(rate_per_chip),
        "unit": "events/s/chip",
        "vs_baseline": round(rate_per_chip / baseline_per_chip, 4),
        "detail": {
            "suite": suite,
            "workflows": workflows,
            "max_events": max_events,
            "real_events": real_events,
            "repeats": repeats,
            "elapsed_s": round(elapsed, 3),
            "devices": n_devices,
            "platform": jax.devices()[0].platform,
            "error_workflows": n_errors,
        },
    }))


if __name__ == "__main__":
    sys.exit(main())
