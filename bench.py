"""Benchmark: the north-star replay measured for real, plus the suite table.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "events/s/chip", "vs_baseline": N,
   "detail": {...}}

The baseline is the derived per-chip north-star rate from BASELINE.md: 1M
workflows x 1k events on a v5e-8 in <60s => >=16.7M events/s aggregate
=> ~2.08M events/s/chip. vs_baseline = headline_rate / 2.08e6.

What runs (r3 verdict asks #1/#7 — honest, minimal-D2H measurement):

1. NORTH STAR: BENCH_NS_WORKFLOWS (default 1,000,000) workflows x
   BENCH_NS_EVENTS (default 1,000) events, every history DISTINCT: the
   fused device generator+replay+checksum kernel (ops/genkernel.py +
   ops/crc.py) births each event from a per-workflow RNG stream inside
   the same scan that replays it, reduces the canonical payload to a
   per-workflow CRC32 ON DEVICE, and the host pulls 4 bytes/workflow.
   The r3 chunk-rate swing (1.9x) was host-side CRC32 of full payload
   rows interleaved with the dispatch pipeline; with the checksum on
   chip the host leg is a [W] u32 pull and the swing collapses —
   min/median/max are reported to show it. CRC spot-parity: sample
   workflows re-materialized from the same RNG stream, ORACLE-replayed,
   host CRC32 vs the device CRC compared.
2. SUITE TABLE: all five BASELINE corpus suites, BENCH_SUITE_WORKFLOWS
   (default 16,384) DISTINCT host-generated histories each, packed to
   the wire32 int32 lane format, pre-placed on device (the host-fed
   configuration the product replays), BENCH_TRIALS timed trials of
   replay + device checksum + [W] CRC pull -> events/s/chip
   min/median/max. A separate `transfer_included` row times the SAME
   work with the host->device copy of the wire32 tensor INSIDE the
   timed region — on tunneled hosts this is link-bound and reported
   as such, never hidden.
3. FEEDER: sustained wire-bytes -> C++ packer -> device rate on a warm
   executable (native/feeder.py), next to the packer's standalone rate.

HBM high-water: device.memory_stats() where the platform provides it,
else XLA's CompiledMemoryStats for the north-star executable
(argument+output+temp) — never silently null (r3 weak #4).

Scale knobs exist for CI only; the defaults ARE the north star.
"""
import json
import os
import statistics
import sys
import time

import numpy as np

# persistent compilation cache: repeated bench invocations (driver rounds,
# operator reruns) skip recompiles. The env var alone is NOT enough on
# hosts whose site bootstrap imports jax first — utils/compile_cache.py
# applies the post-import config update in main()
os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/jax_cache")

BASELINE_PER_CHIP = 16_700_000 / 8  # BASELINE.md derived kernel rate


def _hbm_peak(compiled) -> dict:
    """HBM high-water: live allocator stats if the platform exposes them,
    else the compiled executable's static memory analysis."""
    import jax

    try:
        stats = jax.local_devices()[0].memory_stats()
        if stats and stats.get("peak_bytes_in_use"):
            return {"hbm_peak_bytes": int(stats["peak_bytes_in_use"]),
                    "hbm_source": "memory_stats"}
    except Exception:
        pass
    try:
        ma = compiled.memory_analysis()
        total = (ma.argument_size_in_bytes + ma.output_size_in_bytes
                 + ma.temp_size_in_bytes + ma.generated_code_size_in_bytes)
        return {"hbm_peak_bytes": int(total),
                "hbm_source": "compiled_memory_analysis"}
    except Exception:
        return {"hbm_peak_bytes": None, "hbm_source": "unavailable"}


def _pipelined_transfer(corpus, mesh, layout, n_chunks: int, depth: int):
    """Stream a pre-packed wirec corpus through the MESH-AWARE serving
    executor (engine/executor.stream_wirec_mesh — the same code path the
    dryrun_multichip diagnostic runs) in W chunks: the per-device H2D
    slice copies of chunk N+1 overlap the sharded replay of chunk N, so
    the transfer-included rate approaches the resident kernel rate
    instead of serializing link + compute. Pack cost is zero by design —
    the chunks come pre-packed, the warm pack-cache configuration of the
    production path (engine/cache.PackCache)."""
    from cadence_tpu.engine.executor import stream_wirec_mesh

    def run_once():
        crc, errors, _report = stream_wirec_mesh(
            corpus, mesh, layout, n_chunks=n_chunks, depth=depth)
        return crc, errors

    return run_once


def _suite_table(trials: int, suite_workflows: int, layout):
    """Host-encoded corpora (the product's replay configuration): distinct
    histories, wirec-compressed lanes (~10-18 B/event, ops/wirec.py)
    decoded on device, replay + checksum on device, 4B/wf pulled. The
    transfer-included rate streams the corpus through the pipelined bulk
    executor (chunked H2D overlapping the kernel); the one-shot rate and
    the wire32 rate are kept as comparison points."""
    import jax

    from cadence_tpu.gen.corpus import SUITES, generate_corpus
    from cadence_tpu.native.wirec import pack_wirec_auto
    from cadence_tpu.ops.encode import LANE_EVENT_ID, encode_corpus, to_wire32
    from cadence_tpu.parallel.mesh import (
        make_mesh,
        replay_sharded_crc,
        replay_wirec_sharded_crc,
        shard_events32,
        shard_wirec,
    )

    from cadence_tpu.utils.concurrency import pack_threads as _pack_threads

    mesh = make_mesh()
    n_devices = jax.device_count()
    pack_threads = _pack_threads()  # the one CADENCE_TPU_PACK_THREADS knob
    pipeline_depth = 3
    table = {}
    for suite in SUITES:
        histories = generate_corpus(suite, num_workflows=suite_workflows,
                                    seed=20260730, target_events=120)
        events_np = encode_corpus(histories)
        real = int((events_np[:, :, LANE_EVENT_ID] > 0).sum())
        t0 = time.perf_counter()
        # chunk-parallel host pack (native C++ encoder when available,
        # byte-identical pure-Python otherwise): scales with cores
        corpus = pack_wirec_auto(events_np, num_threads=pack_threads)
        t_pack = time.perf_counter() - t0
        wire = to_wire32(events_np)

        def run_resident(parts):
            from cadence_tpu.parallel.mesh import _replay_wirec_crc_with_stats
            crc, errors, _ = _replay_wirec_crc_with_stats(
                *parts, corpus.profile, layout)
            return np.asarray(crc), np.asarray(errors)

        parts = shard_wirec(corpus, mesh)
        crcs, errors = run_resident(parts)  # compile + warm
        rates = []
        for _ in range(trials):
            t0 = time.perf_counter()
            run_resident(parts)
            rates.append(real / (time.perf_counter() - t0) / n_devices)
        # transfer-inclusive, PIPELINED: the corpus streams through the
        # bulk executor in chunks, each chunk's H2D overlapping the
        # previous chunk's kernel. On tunneled hosts the link is still
        # the floor — but it now hides behind compute instead of adding
        # to it. The chunk count must divide W and keep shards whole.
        n_chunks = next(nc for nc in (4, 2, 1)
                        if suite_workflows % nc == 0
                        and (suite_workflows // nc) % n_devices == 0)
        run_pipelined = _pipelined_transfer(corpus, mesh, layout, n_chunks,
                                            pipeline_depth)
        crc_p, err_p = run_pipelined()  # compile + warm (same executable)
        xfer_rates = []
        for _ in range(trials):
            t0 = time.perf_counter()
            run_pipelined()
            xfer_rates.append(real / (time.perf_counter() - t0) / n_devices)
        # one-shot comparison: the r05 configuration (single H2D + launch)
        t0 = time.perf_counter()
        crc_x, err_x, _ = replay_wirec_sharded_crc(corpus, mesh, layout)
        np.asarray(crc_x)
        t_xfer = time.perf_counter() - t0
        # uncompressed comparison: the r04 configuration
        t0 = time.perf_counter()
        crc_w, _, _ = replay_sharded_crc(shard_events32(wire, mesh), mesh,
                                         layout)
        crc_w = np.asarray(crc_w)
        t_xfer32 = time.perf_counter() - t0
        table[suite] = {
            "workflows": suite_workflows,
            "distinct_histories": True,
            "events": real,
            "wire_format": "wirec",
            "bytes_per_event": round(corpus.bytes_per_event(), 2),
            "pack_s": round(t_pack, 3),
            "pack_threads": pack_threads,
            "rate_min": round(min(rates)),
            "rate_median": round(statistics.median(rates)),
            "rate_max": round(max(rates)),
            "transfer_included_rate": round(
                statistics.median(xfer_rates)),
            "transfer_included_rate_min": round(min(xfer_rates)),
            "transfer_included_rate_oneshot": round(real / t_xfer / n_devices),
            "transfer_included_rate_wire32": round(
                real / t_xfer32 / n_devices),
            "transfer_chunks": n_chunks,
            "pipeline_depth": pipeline_depth,
            "h2d_bytes": int(corpus.wire_bytes),
            "h2d_bytes_wire32": int(wire.nbytes),
            "error_workflows": int((errors != 0).sum()),
            "crc_xor": int(np.bitwise_xor.reduce(crcs.astype(np.uint32))),
            "crc_parity_wire32": bool(
                (crc_w == crcs.astype(np.uint32)).all()),
            "crc_parity_pipelined": bool(
                (crc_p == crcs.astype(np.uint32)).all()
                and (err_p == errors).all()),
        }
    return table


def _north_star(workflows: int, max_events: int, chunk: int, seed: int,
                parity_samples: int, layout):
    """The measured 1M x 1k run: fused device generator+replay+checksum
    (every history DISTINCT, born on device, hashed on device); the host
    pulls one u32 per workflow. Returns the headline stats dict."""
    import jax

    from cadence_tpu.core.checksum import crc32_of_row, payload_row
    from cadence_tpu.core.checksum import STICKY_ROW_INDEX
    from cadence_tpu.ops.encode import decode_lanes
    from cadence_tpu.ops.genkernel import (
        generate_and_replay_sharded_crc,
        generate_lanes,
    )
    from cadence_tpu.oracle.state_builder import StateBuilder
    from cadence_tpu.parallel.mesh import make_mesh

    n_devices = jax.device_count()
    # CI-scale requests smaller than a chunk shrink the chunk instead of
    # silently inflating the run
    chunk = min(chunk, max(workflows, n_devices))
    # ONE code path at every n: the SPMD shard_map kernel over the device
    # mesh — a single chip routes through a mesh of 1 (identical outputs,
    # same executable shape), so the single-chip north star measures the
    # exact kernel the fleet runs instead of a divergent unsharded twin
    mesh = make_mesh()
    chunk = -(-chunk // n_devices) * n_devices

    def run_chunk(sd, lo):
        return generate_and_replay_sharded_crc(sd, lo, chunk, max_events,
                                               mesh, layout)

    n_chunks = -(-workflows // chunk)

    # warm/compile on the first chunk's shape (cold compile reported, not
    # amortized into the steady rate)
    t0 = time.perf_counter()
    crc, _ = run_chunk(seed + 1, 0)
    np.asarray(crc)
    compile_s = time.perf_counter() - t0

    total_events = 0
    total_errors = 0
    chunk_rates = []
    crc_accum = 0
    first_crcs = None

    # depth-2 software pipeline: dispatch chunk i+1 (JAX async) BEFORE
    # blocking on chunk i's 4B/wf pull, so any host-link stall overlaps
    # the next chunk's on-device compute
    real = chunk * max_events  # the generator fills every slot
    t_start = time.perf_counter()
    in_flight = run_chunk(seed, 0)
    t_prev = t_start
    for ci in range(n_chunks):
        crc, errors = in_flight
        if ci + 1 < n_chunks:
            in_flight = run_chunk(seed, (ci + 1) * chunk)
        crcs_np = np.asarray(crc).astype(np.uint32)
        errors_np = np.asarray(errors)
        now = time.perf_counter()
        chunk_rates.append(real / (now - t_prev))  # completion interval
        t_prev = now
        total_events += real
        total_errors += int((errors_np != 0).sum())
        crc_accum ^= int(np.bitwise_xor.reduce(crcs_np))
        if ci == 0:
            first_crcs = crcs_np[:parity_samples].copy()
    wall_s = time.perf_counter() - t_start

    # CRC spot-parity: materialize the SAME rng stream's lanes for a
    # sample block, oracle-replay them, host-CRC the canonical payload,
    # compare against the device-computed CRC
    sample_n = min(parity_samples, chunk)
    lanes = np.asarray(generate_lanes(seed, 0, sample_n, max_events))
    parity_fail = 0
    for i in range(sample_n):
        ms = StateBuilder().replay_history(decode_lanes(lanes[i]))
        expected = payload_row(ms, layout)
        expected[STICKY_ROW_INDEX] = 0
        if np.uint32(crc32_of_row(expected)) != first_crcs[i]:
            parity_fail += 1

    if n_devices > 1:
        hbm = {"hbm_peak_bytes": None, "hbm_source": "sharded-skip"}
    else:
        # memory analysis of the executable that actually ran: the
        # mesh-of-1 shard_map kernel, not an unsharded twin
        import jax.numpy as jnp

        from cadence_tpu.ops.genkernel import _sharded_fn
        fn = _sharded_fn(mesh, chunk, max_events, layout, to_crc=True)
        compiled = fn.lower(jnp.int64(seed),
                            jnp.zeros((1,), jnp.int64)).compile()
        hbm = _hbm_peak(compiled)

    return {
        "workflows": n_chunks * chunk,
        "max_events": max_events,
        "chunk_workflows": chunk,
        "chunks": n_chunks,
        "real_events": total_events,
        "distinct_histories": True,  # per-workflow RNG stream, no tiling
        "checksum_on_device": True,  # host pulls 4 bytes/workflow
        "wall_s": round(wall_s, 3),
        "rate": total_events / wall_s,
        "chunk_rate_min": round(min(chunk_rates)),
        "chunk_rate_median": round(statistics.median(chunk_rates)),
        "chunk_rate_max": round(max(chunk_rates)),
        "chunk_rate_note": ("host leg is a [W] u32 pull; r3's 1.9x swing "
                            "was host-side row CRC32 contending with the "
                            "dispatch pipeline, now on device"),
        "compile_s": round(compile_s, 3),
        "error_workflows": total_errors,
        "oracle_fallback_rate": total_errors / (n_chunks * chunk),
        "crc_xor": crc_accum,
        "parity_samples": sample_n,
        "parity_failures": parity_fail,
        **hbm,
    }


def _fallback_suite(suite_workflows: int, layout):
    """The adversarial mixed path (SURVEY §7 hard part 3): a corpus where
    ~2.5% of workflows overflow the device pending tables.

    The device flags them (TABLE_OVERFLOW) and the capacity-escalation
    LADDER (engine/ladder.py) re-replays exactly those rows on device at
    widened K — gathered into a compact wirec sub-corpus, K→2K→4K — with
    the Python oracle arbitrating only the ladder's residue. Both legs
    sit inside the timed region, so the mixed rate is measured under
    pressure, never assumed cliff-free. Reported alongside: per-rung row
    counts and seconds, the residual-oracle count, CRC parity against
    the ORACLE-ONLY arbitration path (computed outside the timed region:
    the ladder must change nothing but the speed), and the ladder
    compile counters proving warm trials recompiled nothing."""
    import jax

    from cadence_tpu.core.checksum import (
        STICKY_ROW_INDEX,
        crc32_of_row,
        payload_row,
    )
    from cadence_tpu.engine.ladder import EscalationLadder
    from cadence_tpu.gen.corpus import generate_corpus
    from cadence_tpu.native.wirec import pack_wirec_auto
    from cadence_tpu.ops.encode import LANE_EVENT_ID, encode_corpus
    from cadence_tpu.oracle.state_builder import StateBuilder
    from cadence_tpu.parallel.mesh import (
        _replay_wirec_crc_with_stats,
        make_mesh,
        shard_wirec,
    )
    from cadence_tpu.utils import metrics as cm

    mesh = make_mesh()
    n_devices = jax.device_count()
    histories = generate_corpus("overflow", num_workflows=suite_workflows,
                                seed=20260730, target_events=120)
    events_np = encode_corpus(histories)
    real = int((events_np[:, :, LANE_EVENT_ID] > 0).sum())
    corpus = pack_wirec_auto(events_np)
    parts = shard_wirec(corpus, mesh)
    ladder = EscalationLadder(layout,
                              mesh=mesh if n_devices > 1 else None)

    def device_leg():
        crc, errors, _ = _replay_wirec_crc_with_stats(
            *parts, corpus.profile, layout)
        return np.asarray(crc).astype(np.uint32), np.asarray(errors)

    def ladder_leg(crcs, errors):
        """Batched widened-K re-replay of flagged rows; the per-workflow
        oracle arbitrates only what the top rung could not hold."""
        fixed = crcs.copy()
        flagged = np.nonzero(errors != 0)[0]
        cap = ladder.capacity_flagged(errors)
        cap_set = set(cap.tolist())
        residual = [int(i) for i in flagged if i not in cap_set]
        if len(cap):
            crc_l, resolved, _ = ladder.escalate_wirec(corpus, cap)
            fixed[cap[resolved]] = crc_l[resolved]
            residual += [int(i) for i in cap[~resolved]]
        for i in residual:
            ms = StateBuilder().replay_history(histories[i])
            row = payload_row(ms, layout)
            row[STICKY_ROW_INDEX] = 0
            fixed[i] = np.uint32(crc32_of_row(row))
        return fixed, len(residual)

    crcs, errors = device_leg()        # compile + warm
    flagged = np.nonzero(errors != 0)[0]
    ladder_leg(crcs, errors)           # compile + warm the rung variants

    reg = cm.DEFAULT_REGISTRY
    misses0 = reg.counter(cm.SCOPE_TPU_FALLBACK, cm.M_LADDER_CACHE_MISSES)
    rates, ladder_s = [], []
    final, n_residual = crcs, 0
    for _ in range(3):
        t0 = time.perf_counter()
        crcs, errors = device_leg()
        t1 = time.perf_counter()
        final, n_residual = ladder_leg(crcs, errors)
        t2 = time.perf_counter()
        rates.append(real / (t2 - t0) / n_devices)
        ladder_s.append(t2 - t1)
    warm_recompiles = (reg.counter(cm.SCOPE_TPU_FALLBACK,
                                   cm.M_LADDER_CACHE_MISSES) - misses0)

    # oracle-only arbitration (the pre-ladder path), OUTSIDE the timed
    # region: the ladder is a perf path, so its result must be
    # byte-identical — same crc_xor or the suite fails loudly
    oracle_only = crcs.copy()
    for i in flagged:
        ms = StateBuilder().replay_history(histories[i])
        row = payload_row(ms, layout)
        row[STICKY_ROW_INDEX] = 0
        oracle_only[i] = np.uint32(crc32_of_row(row))

    return {
        "workflows": suite_workflows,
        "events": real,
        "wire_format": "wirec",
        "oracle_fallback_rate": round(len(flagged) / suite_workflows, 4),
        "fallback_workflows": int(len(flagged)),
        "mixed_rate_median": round(statistics.median(rates)),
        "device_only_events": int(real - sum(
            (events_np[i, :, LANE_EVENT_ID] > 0).sum() for i in flagged)),
        "ladder_leg_s_median": round(statistics.median(ladder_s), 3),
        "ladder_rungs": ladder.last_run,
        "ladder_max_rungs": ladder.max_rungs,
        "ladder_recompiles_warm": int(warm_recompiles),
        "residual_oracle_rows": int(n_residual),
        "crc_xor": int(np.bitwise_xor.reduce(final)),
        "crc_xor_oracle_only": int(np.bitwise_xor.reduce(oracle_only)),
        "crc_parity_oracle_only": bool((final == oracle_only).all()),
        "note": ("device replay + widened-K ladder re-replay of flagged "
                 "workflows (residue to the host oracle), all inside "
                 "the timed region"),
    }


def _incremental_suite(layout, workflows: int = 0, short_events: int = 0,
                       long_events: int = 0, txns: int = 0):
    """Append-transaction latency vs history length: the serving-path
    claim of the resident-state cache (engine/resident.py) measured for
    real.

    Two corpora — SHORT and LONG histories — each: full-replay once to
    pin every workflow's state in HBM, then (a) TIMED single-workflow
    append transactions (lookup + suffix pack through the pack cache +
    from-state replay + payload readback, the decision-hot-loop shape)
    and (b) one batched append pass over the rest for throughput. The
    O(new events) contract is that the long corpus's append latency
    tracks the short one's (equal suffix sizes ⇒ equal launched shapes)
    — `long_vs_short_p50_ratio` near 1.0, never near
    long_events/short_events. tests/test_perf_gate.py gates the ratio at
    1.5x; full replay of the same corpora is timed alongside so the
    JSON shows what the cache is buying."""
    import jax.numpy as jnp

    from cadence_tpu.engine.cache import PackCache, content_address
    from cadence_tpu.engine.ladder import EscalationLadder
    from cadence_tpu.engine.resident import ResidentStateCache
    from cadence_tpu.gen.corpus import generate_corpus
    from cadence_tpu.ops.encode import (
        LANE_EVENT_ID,
        assemble_corpus,
        encode_batches_resumable,
    )
    from cadence_tpu.ops.payload import payload_rows
    from cadence_tpu.ops.replay import replay_events

    workflows = workflows or int(os.environ.get("BENCH_INCR_WORKFLOWS",
                                                "512"))
    short_events = short_events or int(os.environ.get("BENCH_INCR_SHORT",
                                                      "32"))
    long_events = long_events or int(os.environ.get("BENCH_INCR_LONG",
                                                    "256"))
    txns = txns or int(os.environ.get("BENCH_INCR_TXNS", "32"))
    txns = min(txns, max(1, workflows // 4))
    warm = min(8, workflows - txns) if workflows > txns else 0

    out = {}
    for label, target in (("short", short_events), ("long", long_events)):
        hists = generate_corpus("basic", num_workflows=workflows,
                                seed=20260803, target_events=target)
        keys = [("bench", f"wf-{label}-{i}", "r")
                for i in range(workflows)]
        pack_cache = PackCache(max_size=workflows + 8)
        cache = ResidentStateCache(
            layout, ladder=EscalationLadder(layout),
            budget_bytes=1 << 34)

        # seed: ONE full replay of every prefix (the cold path), states
        # pinned row by row — also timed, as the baseline the cache beats
        prefix_rows = [pack_cache.encode(k, h[:-1])
                       for k, h in zip(keys, hists)]
        corpus = assemble_corpus(prefix_rows,
                                 max(r.shape[0] for r in prefix_rows))
        t0 = time.perf_counter()
        s = replay_events(jnp.asarray(corpus), layout)
        rows = np.asarray(payload_rows(s, layout))
        full_replay_s = time.perf_counter() - t0
        branch = np.asarray(s.current_branch)
        for i, k in enumerate(keys):
            cache.admit(k, content_address(hists[i][:-1]),
                        cache.extract_row(s, i), rows[i], int(branch[i]))

        def one_txn(i):
            """One append transaction: the decision-hot-loop shape."""
            k, h = keys[i], hists[i]
            hit = cache.lookup(k, h)
            assert hit is not None and hit[0] == "suffix", hit
            res = cache.replay_append([(k, hit[1], h)],
                                      encode_suffix=pack_cache.encode_suffix)
            assert res[0].ok
            return res[0]

        for i in range(warm):  # compile + warm the append shapes
            one_txn(i)
        lat = []
        for i in range(warm, warm + txns):
            t0 = time.perf_counter()
            one_txn(i)
            lat.append(time.perf_counter() - t0)
        # batched appends: the bulk re-verify configuration
        rest = list(range(warm + txns, workflows))
        batched_rate = 0.0
        if rest:
            items = [(keys[i], cache.lookup(keys[i], hists[i])[1],
                      hists[i]) for i in rest]
            t0 = time.perf_counter()
            results = cache.replay_append(
                items, encode_suffix=pack_cache.encode_suffix)
            dt = time.perf_counter() - t0
            assert all(r.ok for r in results)
            batched_rate = cache.last_append.events_appended / dt

        real = int((corpus[:, :, LANE_EVENT_ID] > 0).sum())
        suffix_events = [len(h[-1].events) for h in hists[warm:warm + txns]]
        lat.sort()
        out[label] = {
            "workflows": workflows,
            "history_events_mean": round(real / workflows, 1),
            "suffix_events_mean": round(
                sum(suffix_events) / len(suffix_events), 2),
            "append_p50_ms": round(1e3 * lat[len(lat) // 2], 3),
            "append_p95_ms": round(1e3 * lat[int(len(lat) * 0.95)], 3),
            "append_min_ms": round(1e3 * lat[0], 3),
            "batched_append_events_per_sec": round(batched_rate),
            "full_replay_s": round(full_replay_s, 3),
            "txns": txns,
            "chunk_shape": (cache.last_append.chunk_shapes[:1] or
                            [(0, 0)])[0],
        }
    ratio = (out["long"]["append_p50_ms"] / out["short"]["append_p50_ms"]
             if out["short"]["append_p50_ms"] else 0.0)
    return {
        **out,
        "long_vs_short_p50_ratio": round(ratio, 3),
        "shapes_equal": out["short"]["chunk_shape"]
        == out["long"]["chunk_shape"],
        "note": ("append transactions replay ONLY appended batches "
                 "against HBM-resident states; the ratio near 1.0 (not "
                 "near long/short history length) is the O(new events) "
                 "claim. The first corpus's batched/full-replay numbers "
                 "include one-time XLA compiles; the p50s are warmed."),
    }


def _snapshot_suite(layout, workflows: int = 0, target_events: int = 0,
                    trials: int = 0):
    """Warm vs cold restart through the persisted-snapshot tier
    (engine/snapshot.py): the same long-history corpus is verified from
    a fresh resident pool twice — COLD (no snapshots: every workflow
    full-replays its history) and WARM (snapshots persisted, caches
    cleared as a restart would: hydrate + replay only the
    since-snapshot suffix). Both paths run once untimed to compile, and
    the timed trials take the median, so the ratio compares steady
    states. tests/test_perf_gate.py TestSnapshotGate pins warm <= 0.3x
    cold with zero divergence."""
    from cadence_tpu.engine.persistence import Stores
    from cadence_tpu.engine.tpu_engine import TPUReplayEngine
    from cadence_tpu.gen.corpus import generate_corpus
    from cadence_tpu.oracle.state_builder import StateBuilder
    from cadence_tpu.utils import metrics as cm

    workflows = workflows or int(os.environ.get("BENCH_SNAP_WORKFLOWS",
                                                "256"))
    target_events = target_events or int(
        os.environ.get("BENCH_SNAP_EVENTS", "384"))
    trials = trials or int(os.environ.get("BENCH_SNAP_TRIALS", "3"))

    stores = Stores()
    hists = generate_corpus("basic", num_workflows=workflows,
                            seed=20260803, target_events=target_events)
    keys = []
    for h in hists:
        b0 = h[0]
        key = (b0.domain_id, b0.workflow_id, b0.run_id)
        # snapshot point: all but the final batch; the tail commits
        # after the sweep so the warm path genuinely replays a suffix
        for b in h[:-1]:
            stores.history.append_batch(*key, list(b.events))
        ms = StateBuilder().replay_history(
            stores.history.as_history_batches(*key))
        info = ms.execution_info
        info.domain_id, info.workflow_id, info.run_id = key
        stores.execution.upsert_workflow(ms)
        keys.append(key)

    tpu = TPUReplayEngine(stores)
    assert tpu.verify_all().ok
    sweep = tpu.snapshot_sweep(force=True)
    assert sweep.written == workflows, sweep
    # the post-snapshot suffix commits
    for h, key in zip(hists, keys):
        stores.history.append_batch(*key, list(h[-1].events))
        ms = StateBuilder().replay_history(
            stores.history.as_history_batches(*key))
        info = ms.execution_info
        info.domain_id, info.workflow_id, info.run_id = key
        stores.execution.upsert_workflow(ms)

    from cadence_tpu.core.checksum import Checksum
    from cadence_tpu.engine.rebuild import DeviceRebuilder

    reg = cm.DEFAULT_REGISTRY
    total_events = sum(sum(len(b.events) for b in h) for h in hists)
    # the rebuild jobs a restart would hand the rebuilder — read ONCE,
    # outside the timed region (recovery reads the WAL regardless of
    # how states are rebuilt; the snapshot tier's claim is about the
    # REBUILD work, not the log read)
    jobs = [(stores.history.as_history_batches(*key), None)
            for key in keys]

    def run_mode(warm: bool):
        def make():
            rb = DeviceRebuilder(layout)
            if warm:
                rb.snapshots = stores.snapshot
            return rb
        make().rebuild(jobs)  # compile/warm pass for this mode's shapes
        times, states, seeded, suffix_events = [], None, 0, 0
        for _ in range(trials):
            rb = make()  # fresh caches: every trial is a real restart
            pre = reg.counter(cm.SCOPE_TPU_RESIDENT,
                              cm.M_RESIDENT_EVENTS_APPENDED)
            t0 = time.perf_counter()
            states = rb.rebuild(jobs)
            times.append(time.perf_counter() - t0)
            seeded = rb.stats.snapshot_seeded
            suffix_events = reg.counter(
                cm.SCOPE_TPU_RESIDENT,
                cm.M_RESIDENT_EVENTS_APPENDED) - pre
            assert rb.stats.oracle_fallback == 0, rb.stats
        times.sort()
        return times[len(times) // 2], states, seeded, suffix_events

    cold_s, cold_states, _, _ = run_mode(warm=False)
    warm_s, warm_states, hydrated, suffix_events = run_mode(warm=True)
    divergent = sum(
        1 for a, b in zip(cold_states, warm_states)
        if Checksum.of(a).value != Checksum.of(b).value)
    store_stats = stores.snapshot.stats()
    return {
        "workflows": workflows,
        "history_events_mean": round(total_events / workflows, 1),
        "snapshot_records": store_stats["entries"],
        "snapshot_bytes": store_stats["bytes"],
        "cold_restart_s": round(cold_s, 4),
        "warm_restart_s": round(warm_s, 4),
        "warm_vs_cold": round(warm_s / cold_s, 4) if cold_s else 0.0,
        "cold_hydrate_events_per_sec": round(total_events / cold_s)
        if cold_s else 0,
        "warm_hydrate_events_per_sec": round(total_events / warm_s)
        if warm_s else 0,
        "suffix_events_replayed": int(suffix_events),
        "hydrated": hydrated,
        "divergent": divergent,
        "note": ("cold = every workflow's mutable state rebuilt by "
                 "full-history device replay; warm = the persisted "
                 "ReplayState rows hydrate and only the since-snapshot "
                 "suffix replays (fresh rebuilder + caches per trial — "
                 "a genuine restart). Medians over warmed trials; "
                 "hydrate rate counts TOTAL history events made live "
                 "per second of rebuild; divergent counts cold-vs-warm "
                 "state checksum mismatches (must be 0)."),
    }


def _visibility_suite(sizes=None, trials: int = 0):
    """Device-visibility scan rates (ISSUE 12): a synthetic visibility
    population at each BENCH_VIS_SIZES row count, the same selectivity-
    sweep query corpus timed through the HOST store (dict/set indexes +
    per-record predicate) and through the COLUMNAR DEVICE tier
    (ops/scan.py mask kernels, parity off inside the timed region so
    the measurement is the pure device path). Count queries carry the
    rows/s-scanned headline (scalar readback — the HBM-bandwidth
    claim); a selective List is timed separately since it pays host
    materialization of matches. Warm recompiles across the timed
    repeats must be ZERO (the kernel-variant cache counters prove it —
    the acceptance bar TestVisibilityGate pins)."""
    from cadence_tpu.engine.persistence import (
        VisibilityRecord,
        VisibilityStore,
    )
    from cadence_tpu.utils import metrics as cm

    sizes = sizes or [int(s) for s in os.environ.get(
        "BENCH_VIS_SIZES", "10000,100000").split(",") if s]
    trials = trials or int(os.environ.get("BENCH_VIS_TRIALS", "5"))
    reg = cm.DEFAULT_REGISTRY
    sc = cm.SCOPE_TPU_VISIBILITY
    saved = {k: os.environ.get(k) for k in
             ("CADENCE_TPU_VISIBILITY", "CADENCE_TPU_VISIBILITY_PARITY",
              "CADENCE_TPU_VISIBILITY_CAPACITY")}
    out_sizes = []
    try:
        for n in sizes:
            os.environ["CADENCE_TPU_VISIBILITY"] = "0"
            os.environ["CADENCE_TPU_VISIBILITY_CAPACITY"] = str(n)
            import random
            rng = random.Random(20260804)
            store = VisibilityStore()
            base = 1_700_000_000_000_000_000
            for i in range(n):
                attrs = {}
                r = rng.random()
                if r < 0.5:
                    attrs["Priority"] = rng.randrange(0, 10)
                elif r < 0.8:
                    attrs["Tag"] = f"tag-{rng.randrange(4)}"
                rec = VisibilityRecord(
                    domain_id="bench", workflow_id=f"wf-{i}",
                    run_id=f"r-{i}", workflow_type=f"wt-{i % 8}",
                    start_time=base + i * 1000, search_attrs=attrs)
                store.record_started(rec)
                if rng.random() < 0.5:
                    store.record_closed("bench", f"wf-{i}", f"r-{i}",
                                        close_time=base + i * 1000 + 7,
                                        close_status=rng.randrange(0, 3))
            # the selectivity sweep: match fractions from ~0.01% to 100%
            cut99 = base + int(n * 0.999) * 1000
            queries = [
                ("all", ""),
                ("half_open", "CloseStatus = -1"),
                ("type_eighth", "WorkflowType = 'wt-3'"),
                ("attr_tenth", "Priority >= 9"),
                ("narrow_and", "WorkflowType = 'wt-1' AND "
                               "CloseStatus = 0 AND Priority < 2"),
                ("time_tail", f"StartTime > {cut99}"),
            ]

            def run_counts(label):
                t0 = time.perf_counter()
                for _ in range(trials):
                    for _name, q in queries:
                        store.count("bench", q)
                return time.perf_counter() - t0

            host_s = run_counts("host")
            sel = {name: store.count("bench", q) for name, q in queries}

            os.environ["CADENCE_TPU_VISIBILITY"] = "1"
            os.environ["CADENCE_TPU_VISIBILITY_PARITY"] = "0"
            # warm pass: bootstrap flush + one compile per query shape
            for _name, q in queries:
                store.count("bench", q)
                store.query("bench", q)
            pre_miss = reg.counter(sc, cm.M_LADDER_CACHE_MISSES)
            dev_s = run_counts("device")
            warm_recompiles = (reg.counter(sc, cm.M_LADDER_CACHE_MISSES)
                               - pre_miss)
            # a selective list (materializes matches on the host);
            # warm its shape first — the timed repeats must measure the
            # steady state, not the one-off compile
            list_q = "WorkflowType = 'wt-3' AND CloseStatus = -1"
            store.query("bench", list_q)
            t0 = time.perf_counter()
            for _ in range(trials):
                store.query("bench", list_q)
            list_dev_s = (time.perf_counter() - t0) / trials
            # parity pass (outside the timed region): every query's
            # device ids re-checked against the host evaluator
            os.environ["CADENCE_TPU_VISIBILITY_PARITY"] = "1"
            pre_div = reg.counter(sc, cm.M_VIS_DIVERGENCE)
            for _name, q in queries:
                store.count("bench", q)
                store.query("bench", q)
            divergence = reg.counter(sc, cm.M_VIS_DIVERGENCE) - pre_div
            view = store._device
            if view is not None:
                view.stop()
            scans = trials * len(queries)
            out_sizes.append({
                "rows": n,
                "queries_per_trial": len(queries),
                "selectivity": {k: round(v / n, 5)
                                for k, v in sel.items()},
                "host_rows_per_sec": round(n * scans / host_s)
                if host_s else 0,
                "device_rows_per_sec": round(n * scans / dev_s)
                if dev_s else 0,
                "speedup": round(host_s / dev_s, 3) if dev_s else 0.0,
                "device_count_ms": round(dev_s / scans * 1000, 4),
                "host_count_ms": round(host_s / scans * 1000, 4),
                "device_selective_list_ms": round(list_dev_s * 1000, 4),
                "warm_recompiles": int(warm_recompiles),
                "parity_divergence": int(divergence),
            })
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    return {
        "sizes": out_sizes,
        "parity": all(s["parity_divergence"] == 0 for s in out_sizes),
        "warm_recompiles": sum(s["warm_recompiles"] for s in out_sizes),
        "note": ("rows/s = table rows logically scanned per second of "
                 "Count traffic (device: one mask kernel + 8-byte "
                 "readback per query; host: index-planned per-record "
                 "predicate). Warm recompiles across timed repeats "
                 "must be 0; parity pass re-checks every query's ids "
                 "against the host evaluator."),
    }


def _mesh_serving(workflows: int, layout):
    """The pod-scale north-star section (ISSUE 7): events/s/POD and
    per-device efficiency measured THROUGH THE SERVING EXECUTOR
    (engine/executor.replay_corpus_mesh — the exact chunked, pipelined,
    per-device-staged path the engine's verify/rebuild hot path runs,
    and the same code dryrun_multichip diagnoses). A mesh of 1 is timed
    first (the single-chip serving baseline the perf gate pins), then
    the full mesh; mesh-of-N payload rows must be byte-identical to
    mesh-of-1 — sharding is a speed axis, never a result axis. On a
    virtual CPU mesh the devices share physical cores, so
    per_device_efficiency reports scaling OVERHEAD there (virtual_mesh
    flags it); on real hardware the perf gate holds it ≥ 0.7."""
    import jax

    from cadence_tpu.engine.executor import replay_corpus_mesh
    from cadence_tpu.gen.corpus import generate_corpus
    from cadence_tpu.ops.encode import LANE_EVENT_ID, encode_corpus
    from cadence_tpu.parallel.mesh import make_mesh

    devices = jax.devices()
    n = len(devices)
    workflows = -(-workflows // n) * n
    hists = generate_corpus("basic", num_workflows=workflows,
                            seed=20260730, target_events=60)
    events = encode_corpus(hists)
    real = int((events[:, :, LANE_EVENT_ID] > 0).sum())
    chunk = max(n, workflows // 4)

    def rate_on(mesh):
        replay_corpus_mesh(events, mesh, layout,
                           chunk_workflows=chunk)  # compile + warm
        best, rows, errors = 0.0, None, None
        for _ in range(3):
            t0 = time.perf_counter()
            rows, errors, _branch, _rep = replay_corpus_mesh(
                events, mesh, layout, chunk_workflows=chunk)
            best = max(best, real / (time.perf_counter() - t0))
        return best, rows, errors

    rate_1, rows_1, err_1 = rate_on(make_mesh(devices[:1]))
    out = {
        "workflows": workflows,
        "events": real,
        "devices": n,
        "chunk_workflows": chunk,
        "serving_executor": True,
        "virtual_mesh": devices[0].platform == "cpu",
        "rate_n1": round(rate_1),
        "events_per_sec_pod": round(rate_1),
        "error_workflows": int((err_1 != 0).sum()),
        "per_device_efficiency": 1.0,
        "checksum_identity": True,
    }
    if n > 1:
        rate_n, rows_n, err_n = rate_on(make_mesh(devices))
        out.update({
            f"rate_n{n}": round(rate_n),
            "events_per_sec_pod": round(rate_n),
            "speedup": round(rate_n / rate_1, 4),
            "per_device_efficiency": round(rate_n / (rate_1 * n), 4),
            # the PR-5 invariant, extended to the serving path: mesh-of-N
            # must produce the SAME bytes as mesh-of-1 on the same corpus
            "checksum_identity": bool((rows_n == rows_1).all()
                                      and (err_n == err_1).all()),
        })
    return out


def _cluster_serving(layout, hosts_n: int = 0, workflows: int = 0,
                     target_events: int = 0):
    """Multi-host device serving (ISSUE 13): the cluster scale-out of
    the serving tier measured in-process. Workflows partition across H
    simulated hosts by the SAME ring the wire cluster routes with
    (membership.HashRing + shard_id_for_workflow), each host running its
    OWN TPUReplayEngine + ServingScheduler — independent resident pools,
    independent drains — and every host's append round drives
    concurrently. `events_per_sec_cluster` is the summed appended-event
    rate over the whole fleet's wall window, recorded next to the
    single-host `events_per_sec_pod` baseline. The migration leg then
    proves the subsystem's state story: host A's resident rows snapshot
    out through the shared store (engine/migration.MigrationManager),
    host B hydrates + suffix-replays, and every migrated payload must be
    byte-identical to the oracle. On the virtual CPU mesh all "hosts"
    share physical cores, so cluster scaling reports coordination
    overhead there (virtual flag), exactly like detail.mesh_serving."""
    import threading

    from cadence_tpu.core.checksum import STICKY_ROW_INDEX, payload_row
    from cadence_tpu.engine.cache import batch_crc
    from cadence_tpu.engine.membership import (
        HashRing,
        shard_id_for_workflow,
    )
    from cadence_tpu.engine.migration import MigrationManager
    from cadence_tpu.engine.persistence import Stores
    from cadence_tpu.engine.serving import ServingScheduler
    from cadence_tpu.engine.tpu_engine import TPUReplayEngine
    from cadence_tpu.gen.corpus import generate_corpus
    from cadence_tpu.oracle.state_builder import StateBuilder

    hosts_n = hosts_n or int(os.environ.get("BENCH_CLUSTER_HOSTS", "2"))
    workflows = workflows or int(os.environ.get("BENCH_CLUSTER_WORKFLOWS",
                                                "64"))
    target_events = target_events or int(
        os.environ.get("BENCH_CLUSTER_EVENTS", "96"))
    num_shards = 8
    hists = generate_corpus("basic", num_workflows=workflows,
                            seed=20260804, target_events=target_events)
    appends = 4  # warm round + timed round, two batches each
    prefix = min(len(h) for h in hists) - appends
    assert prefix > 1, (prefix, appends)
    keys = [("bench", f"cs-{i}", "r") for i in range(workflows)]
    counts = {k: prefix for k in keys}
    by_key = {k: h for k, h in zip(keys, hists)}

    def read_batches(key):
        return by_key[key][:counts[key]]

    def expected_for(key):
        ms = StateBuilder().replay_history(read_batches(key))
        row = payload_row(ms, layout)
        row[STICKY_ROW_INDEX] = 0
        return row, int(ms.version_histories.current_index)

    def build_fleet(n):
        """n hosts, each owning its ring slice of the keys."""
        ring = HashRing([f"host-{i}" for i in range(n)])
        fleet = {}
        for i in range(n):
            name = f"host-{i}"
            tpu = TPUReplayEngine(Stores(), layout)
            sched = ServingScheduler(tpu, max_batch=8, max_wait_us=2000,
                                     read_batches=read_batches)
            sched.warm(e_shapes=(16, 32))
            fleet[name] = sched
        owned = {name: [] for name in fleet}
        for k in keys:
            sid = shard_id_for_workflow(k[1], num_shards)
            owned[ring.lookup(f"shard-{sid}")].append(k)
        return fleet, owned

    def drive_fleet(fleet, owned, conc_per_host=4):
        """One append per owned workflow on every host, all hosts
        concurrent; returns (wall seconds, total appended events)."""
        errs = []
        total_events = [0]
        lock = threading.Lock()
        threads = []

        def worker(sched, share):
            # a raising submit/result must surface in errs, not die
            # silently with the thread — a dropped share would publish
            # an under-counted (but plausible) cluster rate
            try:
                for k in share:
                    counts[k] += 1
                    batch = read_batches(k)[-1]
                    row, br = expected_for(k)
                    ticket = sched.submit(k, row, br, batch_crc(batch))
                    res = ticket.result(timeout=300.0)
                    with lock:
                        total_events[0] += len(batch.events)
                        if not (res.ok and res.parity_ok):
                            errs.append(res)
            except Exception as exc:
                with lock:
                    errs.append(exc)

        for name, sched in fleet.items():
            share = owned[name]
            for i in range(conc_per_host):
                sl = share[i::conc_per_host]
                if sl:
                    threads.append(threading.Thread(
                        target=worker, args=(sched, sl)))
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = time.perf_counter() - t0
        assert not errs, errs[:3]
        return wall, total_events[0]

    def measure(n):
        fleet, owned = build_fleet(n)
        # seed + warm: one cold round pins every prefix state, one
        # append round traces the from-state shapes (untimed)
        for name, sched in fleet.items():
            for k in owned[name]:
                row, br = expected_for(k)
                sched.submit(k, row, br, batch_crc(read_batches(k)[-1]))
            assert sched.drain(timeout=300.0)
        drive_fleet(fleet, owned)
        wall, events = drive_fleet(fleet, owned)
        for sched in fleet.values():
            sched.stop()
        return events / wall

    rate_pod = measure(1)
    rate_cluster = measure(hosts_n)

    # -- the migration leg: losing host -> shared store -> gaining host --
    stores = Stores()
    mig_keys = []
    for h in hists[:16]:
        b0 = h[0]
        key = (b0.domain_id, b0.workflow_id, b0.run_id)
        for b in h[:prefix]:
            stores.history.append_batch(*key, list(b.events))
        ms = StateBuilder().replay_history(
            stores.history.as_history_batches(*key))
        info = ms.execution_info
        info.domain_id, info.workflow_id, info.run_id = key
        stores.execution.upsert_workflow(ms)
        mig_keys.append(key)
    loser = TPUReplayEngine(stores, layout)
    assert loser.verify_all().ok
    out = MigrationManager("bench-loser", num_shards,
                           loser).migrate_out(range(num_shards))
    # one committed batch lands between snapshot and steal (the live
    # suffix the gaining host must catch up)
    for key, h in zip(mig_keys, hists):
        stores.history.append_batch(*key, list(h[prefix].events))
        ms = StateBuilder().replay_history(
            stores.history.as_history_batches(*key))
        info = ms.execution_info
        info.domain_id, info.workflow_id, info.run_id = key
        stores.execution.upsert_workflow(ms)
    gainer = TPUReplayEngine(stores, layout)
    t0 = time.perf_counter()
    rep = MigrationManager("bench-gainer", num_shards,
                           gainer).hydrate_shards(range(num_shards))
    hydrate_s = time.perf_counter() - t0
    identical = all(
        (np.asarray(gainer.resident.entry_for(k).payload) ==
         _expected_row_of(stores, k, layout)).all()
        for k in mig_keys if gainer.resident.entry_for(k) is not None)

    return {
        "hosts": hosts_n,
        "workflows": workflows,
        "num_shards": num_shards,
        "virtual": True,  # simulated hosts share this process's cores
        "events_per_sec_pod": round(rate_pod),
        "events_per_sec_cluster": round(rate_cluster),
        "cluster_speedup": round(rate_cluster / rate_pod, 4),
        "migration": {
            "snapshotted_out": out.snapshotted,
            "hydrated": rep.hydrated,
            "cold": rep.cold,
            "stale": rep.stale,
            "suffix_events": rep.suffix_events,
            "hydrate_s": round(hydrate_s, 4),
            "parity_divergence": rep.parity_divergence,
            "payload_identity": bool(identical and rep.hydrated > 0),
        },
    }


def _expected_row_of(stores, key, layout):
    from cadence_tpu.core.checksum import STICKY_ROW_INDEX, payload_row

    ms = stores.execution.get_workflow(*key)
    row = payload_row(ms, layout)
    row[STICKY_ROW_INDEX] = 0
    return row


def _feeder_rate(layout):
    """The ingest pipeline: wire bytes → wirec encoder (native C++ fused
    pass when the .so loads — the ISSUE 9 path — byte-identical
    pure-Python otherwise) → pinned staging buffers → H2D → device
    decode+replay+checksum → 4B/wf back; the wire32 (uncompressed)
    sustained rate is kept as the comparison point, and the
    suffix-append leg measures the warm re-verify configuration
    (PackCache suffix repack + resident from-state replay)."""
    from cadence_tpu.gen.corpus import generate_corpus
    from cadence_tpu.native import packing
    from cadence_tpu.native.feeder import feed_corpus32, feed_corpus_wirec

    if not packing.native_available():
        return None
    histories = generate_corpus("basic", num_workflows=16384, seed=7,
                                target_events=100)
    chunk = 8192
    feed_corpus_wirec(histories[:chunk], chunk_workflows=chunk,
                      layout=layout)  # warm
    _, errors, report = feed_corpus_wirec(histories, chunk_workflows=chunk,
                                          layout=layout)
    feed_corpus32(histories[:chunk], chunk_workflows=chunk,
                  layout=layout)  # warm
    _, errors32, report32 = feed_corpus32(histories, chunk_workflows=chunk,
                                          layout=layout)
    return {
        "wire_format": "wirec",
        "native_wirec": report.native_wirec,
        "events": report.events,
        "sustained_events_per_sec": round(report.events_per_sec),
        "pack_only_events_per_sec": round(report.pack_events_per_sec),
        "compress_s": round(report.compress_s, 3),
        "h2d_s": round(report.h2d_s, 3),
        "bytes_per_event": round(report.bytes_per_event, 2),
        "profile_refits": report.profile_refits,
        "pipeline_depth": report.depth,
        "pack_queue_wait_s": round(report.pack_queue_wait_s, 3),
        "error_workflows": int((errors != 0).sum()),
        "wire32_sustained_events_per_sec": round(report32.events_per_sec),
        "wire32_error_workflows": int((errors32 != 0).sum()),
        "suffix_append": _feeder_append_rate(layout),
    }


def _feeder_append_rate(layout, workflows: int = 0):
    """The suffix-append feeder leg: every workflow gets one appended
    batch and the stream re-verifies through feed_appends — PackCache
    suffix repack (O(new events) host cost) + from-state replay against
    HBM-resident states. The rate counts APPENDED events (the honest
    denominator for an append stream); history_events_per_sec is the
    full-history rate an O(history) path would have had to sustain for
    the same wall time, i.e. what residency buys."""
    import jax.numpy as jnp

    from cadence_tpu.engine.cache import PackCache, content_address
    from cadence_tpu.engine.ladder import EscalationLadder
    from cadence_tpu.engine.resident import ResidentStateCache
    from cadence_tpu.gen.corpus import generate_corpus
    from cadence_tpu.native.feeder import feed_appends
    from cadence_tpu.ops.encode import LANE_EVENT_ID, assemble_corpus
    from cadence_tpu.ops.payload import payload_rows
    from cadence_tpu.ops.replay import replay_events

    workflows = workflows or int(os.environ.get("BENCH_FEED_APPEND_WF",
                                                "2048"))
    hists = generate_corpus("basic", num_workflows=workflows,
                            seed=20260803, target_events=80)
    keys = [("bench", f"feed-append-{i}", "r") for i in range(workflows)]
    pack_cache = PackCache(max_size=workflows + 8)
    cache = ResidentStateCache(layout, ladder=EscalationLadder(layout),
                               budget_bytes=1 << 34)
    prefix_rows = [pack_cache.encode(k, h[:-1])
                   for k, h in zip(keys, hists)]
    corpus = assemble_corpus(prefix_rows,
                             max(r.shape[0] for r in prefix_rows))
    s = replay_events(jnp.asarray(corpus), layout)
    rows = np.asarray(payload_rows(s, layout))
    branch = np.asarray(s.current_branch)
    for i, k in enumerate(keys):
        cache.admit(k, content_address(hists[i][:-1]),
                    cache.extract_row(s, i), rows[i], int(branch[i]))
    items = [(k, h) for k, h in zip(keys, hists)]
    # warm the append shapes on a disjoint HALF (compile outside the
    # timed pass; warmed items would re-verify as exact hits and skew
    # it, and both halves pow2-bucket to the same launch shape so the
    # timed pass provably reuses the warmed executable)
    warm_n = workflows // 2
    feed_appends(items[:warm_n], cache, pack_cache)
    items = items[warm_n:]
    results, report = feed_appends(items, cache, pack_cache)
    history_events = int((corpus[warm_n:, :, LANE_EVENT_ID] > 0).sum()) \
        + report.events
    return {
        "workflows": len(items),
        "appended_events": report.events,
        "appended_events_per_sec": round(report.events_per_sec),
        "history_events_per_sec": round(history_events / report.wall_s
                                        if report.wall_s else 0.0),
        "chunks": report.chunks,
        "ok": int(sum(1 for r in results if r.ok)),
        "wall_s": round(report.wall_s, 3),
    }


def _serving_suite(layout, workflows: int = 0, target_events: int = 0,
                   levels=(1, 2, 4, 8)):
    """The device-serving transaction tier (engine/serving.py) measured
    at the scheduler seam: N submitter threads drive committed append
    transactions (each waits for its device parity result — offered
    concurrency == N), the scheduler coalesces them into shared
    from-state launches, and the suite records coalescing factor and
    latency percentiles per concurrency level. An UNBATCHED baseline
    (max_batch=1, zero window — one launch per transaction) runs at the
    top level so the micro-batching claim is a measured ratio, not a
    design note; tests/test_perf_gate.py TestServingGate pins
    batched p99 <= unbatched p99, factor > 1.5 at saturation, zero
    warm recompiles, zero parity divergence."""
    import threading

    from cadence_tpu.core.checksum import STICKY_ROW_INDEX, payload_row
    from cadence_tpu.engine.cache import batch_crc
    from cadence_tpu.engine.persistence import Stores
    from cadence_tpu.engine.serving import ServingScheduler
    from cadence_tpu.engine.tpu_engine import TPUReplayEngine
    from cadence_tpu.gen.corpus import generate_corpus
    from cadence_tpu.oracle.state_builder import StateBuilder
    from cadence_tpu.ops.replay import replay_from_state_to_payload
    from cadence_tpu.utils import metrics as cm

    workflows = workflows or int(os.environ.get("BENCH_SERVING_WORKFLOWS",
                                                "64"))
    target_events = target_events or int(
        os.environ.get("BENCH_SERVING_EVENTS", "96"))
    hists = generate_corpus("basic", num_workflows=workflows,
                            seed=20260803, target_events=target_events)
    # every level appends TWO batches per workflow (an untimed warm
    # round traces this level's stack/flush shapes, then the timed
    # round); the prefix leaves enough tail for all levels plus the
    # unbatched baseline
    appends_needed = 2 * len(levels) + 2
    min_batches = min(len(h) for h in hists)
    assert min_batches > appends_needed + 1, (min_batches, appends_needed)
    prefix = min_batches - appends_needed
    keys = [("bench", f"sv-{i}", "r") for i in range(workflows)]
    counts = {k: prefix for k in keys}
    by_key = {k: h for k, h in zip(keys, hists)}

    def read_batches(key):
        return by_key[key][:counts[key]]

    def expected_for(key):
        ms = StateBuilder().replay_history(read_batches(key))
        row = payload_row(ms, layout)
        row[STICKY_ROW_INDEX] = 0
        return row, int(ms.version_histories.current_index)

    registry = cm.DEFAULT_REGISTRY

    def make_scheduler(max_batch, max_wait_us):
        tpu = TPUReplayEngine(Stores(), layout)
        sched = ServingScheduler(tpu, max_batch=max_batch,
                                 max_wait_us=max_wait_us,
                                 read_batches=read_batches)
        sched.warm(e_shapes=(16, 32))
        # seed: one cold submit per workflow pins every prefix state
        for k in keys:
            row, br = expected_for(k)
            sched.submit(k, row, br, batch_crc(read_batches(k)[-1]))
        assert sched.drain(timeout=300.0)
        return sched

    def drive(sched, conc, wf_slice):
        """conc threads, each appending one batch per owned workflow and
        blocking on its parity ticket; returns sorted latencies."""
        lats, errs = [], []
        lock = threading.Lock()
        barrier = threading.Barrier(conc)
        shares = [wf_slice[i::conc] for i in range(conc)]

        def worker(share):
            barrier.wait()
            for k in share:
                counts[k] += 1
                row, br = expected_for(k)
                t0 = time.perf_counter()
                ticket = sched.submit(k, row, br,
                                      batch_crc(read_batches(k)[-1]))
                res = ticket.result(timeout=300.0)
                dt = time.perf_counter() - t0
                with lock:
                    lats.append(dt)
                    if not (res.ok and res.parity_ok):
                        errs.append(res)

        threads = [threading.Thread(target=worker, args=(s,))
                   for s in shares if s]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errs, errs[:3]
        lats.sort()
        return lats

    suite = {"workflows": workflows, "levels": [], "parity_divergence": 0}
    # max_batch pinned to the top concurrency level: warm() derives its
    # widths from it, so the suite pre-compiles exactly the flush shapes
    # the drive can produce (a wider batch would just warm more shapes)
    sched = make_scheduler(max_batch=max(levels), max_wait_us=4000)
    size0 = None
    for conc in levels:
        drive(sched, conc, keys)  # warm round: trace this level's shapes
        if size0 is None:
            # everything after the first level's warm round must reuse
            # the compiled from-state executables — zero warm recompiles
            size0 = replay_from_state_to_payload._cache_size()
        pre_txn = registry.counter(cm.SCOPE_TPU_SERVING, cm.M_SERVING_TXNS)
        pre_launch = registry.counter(cm.SCOPE_TPU_SERVING,
                                      cm.M_SERVING_LAUNCHES)
        lats = drive(sched, conc, keys)
        txns = registry.counter(cm.SCOPE_TPU_SERVING,
                                cm.M_SERVING_TXNS) - pre_txn
        launches = registry.counter(cm.SCOPE_TPU_SERVING,
                                    cm.M_SERVING_LAUNCHES) - pre_launch
        suite["levels"].append({
            "concurrency": conc,
            "txns": txns,
            "launches": launches,
            "coalescing_factor": round(txns / launches, 3) if launches
            else 0.0,
            "p50_ms": round(1e3 * lats[len(lats) // 2], 3),
            "p99_ms": round(1e3 * lats[min(len(lats) - 1,
                                           int(len(lats) * 0.99))], 3),
        })
    suite["warm_recompiles"] = (replay_from_state_to_payload._cache_size()
                                - size0)
    sched.stop()

    # unbatched baseline: one launch per transaction (max_batch=1, no
    # window) at the top concurrency — what the tier costs WITHOUT
    # micro-batching (warm round first, same as the batched levels)
    top = max(levels)
    unbatched = make_scheduler(max_batch=1, max_wait_us=0)
    drive(unbatched, top, keys)
    lats = drive(unbatched, top, keys)
    unbatched.stop()
    suite["unbatched"] = {
        "concurrency": top,
        "p50_ms": round(1e3 * lats[len(lats) // 2], 3),
        "p99_ms": round(1e3 * lats[min(len(lats) - 1,
                                       int(len(lats) * 0.99))], 3),
    }
    batched_top = next(lv for lv in suite["levels"]
                       if lv["concurrency"] == top)
    suite["batched_p99_ms"] = batched_top["p99_ms"]
    suite["unbatched_p99_ms"] = suite["unbatched"]["p99_ms"]
    suite["coalescing_factor_at_top"] = batched_top["coalescing_factor"]
    suite["parity_divergence"] = registry.counter(
        cm.SCOPE_TPU_SERVING, cm.M_SERVING_DIVERGENCE)
    suite["note"] = (
        "submitters block on per-transaction parity tickets, so offered "
        "concurrency == thread count; batched levels share one "
        "from-state launch per flush window, the unbatched baseline "
        "pays one launch per transaction")
    return suite


def _fuzz_suite(layout, trials: int = 0):
    """Promoted fuzz corpora as permanent bench suites (ROADMAP item 4):
    every fuzz_specs/*.json (written by `fuzz promote`, gen/fuzz.py
    CorpusSpec) regenerates byte-identically from its seed, replays on
    the wirec path for a timed rate, and parity-gates the CRCs against
    the oracle — a discovered adversarial structure stays both a perf
    input and a correctness gate. Empty when nothing is promoted."""
    import jax.numpy as jnp

    from cadence_tpu.core.checksum import crc32_of_row
    from cadence_tpu.gen import fuzz as fuzz_mod
    from cadence_tpu.native.wirec import pack_wirec_auto
    from cadence_tpu.ops.encode import LANE_EVENT_ID, encode_corpus
    from cadence_tpu.ops.replay import replay_wirec_to_crc

    trials = trials or int(os.environ.get("BENCH_TRIALS", "5"))
    table = {}
    for spec in fuzz_mod.load_specs(os.path.dirname(
            os.path.abspath(__file__))):
        histories = spec.generate()
        events_np = encode_corpus(histories)
        real = int((events_np[:, :, LANE_EVENT_ID] > 0).sum())
        corpus = pack_wirec_auto(events_np)
        arrs = (jnp.asarray(corpus.slab), jnp.asarray(corpus.bases),
                jnp.asarray(corpus.n_events))
        crc, errors = replay_wirec_to_crc(*arrs, corpus.profile, layout)
        crc = np.asarray(crc).astype(np.uint32)
        errors = np.asarray(errors)
        rates = []
        for _ in range(trials):
            t0 = time.perf_counter()
            c, e = replay_wirec_to_crc(*arrs, corpus.profile, layout)
            np.asarray(c)
            rates.append(real / (time.perf_counter() - t0))
        expected = np.array([
            crc32_of_row(fuzz_mod.oracle_final_row(h, layout))
            for h in histories], dtype=np.uint32)
        clean = errors == 0
        table[spec.name] = {
            "seed": spec.seed, "profile": spec.profile,
            "workflows": len(histories), "events": real,
            "digest": spec.digest[:12],
            "rate_median": round(statistics.median(rates)),
            "rate_min": round(min(rates)),
            "error_workflows": int((~clean).sum()),
            "crc_parity": bool((crc[clean] == expected[clean]).all()),
            "note": spec.note,
        }
    return table


def _replication_suite(layout):
    """Standby bulk apply (the multi-region standby's steady state): one
    seeded active-region corpus — serving tier on, mid-corpus forced
    sweep shipping snapshot records down the stream — published ONCE,
    then drained by two independent standby consumers off the same
    replication queue: the device twin ON (snapshot-seeded bulk apply,
    per-apply parity gate) and the CADENCE_TPU_REPL_DEVICE=0 kill-switch
    host-only path. Times each apply drain and byte-compares every
    replicated row across the two paths — the kill switch must restore
    the host-only result exactly."""
    from cadence_tpu.core.checksum import payload_row
    from cadence_tpu.engine.domainrepl import DomainReplicationProcessor
    from cadence_tpu.engine.multicluster import ReplicatedClusters
    from cadence_tpu.engine.onebox import Onebox
    from cadence_tpu.engine.replication import (
        HistoryReplicator,
        ReplicationTaskProcessor,
    )
    from cadence_tpu.models.deciders import SignalDecider
    from cadence_tpu.utils import metrics as cm

    domain, tl = "bench-repl", "bench-repl-tl"
    workflows = int(os.environ.get("BENCH_REPL_WORKFLOWS", "32"))
    signals = int(os.environ.get("BENCH_REPL_SIGNALS", "6"))

    # aggressive snapshot policy for the corpus (read at Snapshotter
    # construction, which happens inside ReplicatedClusters.__init__)
    knobs = {"CADENCE_TPU_SNAPSHOT_MIN_EVENTS": "1",
             "CADENCE_TPU_SNAPSHOT_EVERY_EVENTS": "4"}
    saved = {k: os.environ.get(k) for k in knobs}
    os.environ.update(knobs)
    try:
        clusters = ReplicatedClusters(num_hosts=1, num_shards=4)
        host_only = Onebox(num_hosts=1, num_shards=4,
                           cluster_name="standby")
    finally:
        for k, v in saved.items():
            os.environ.pop(k, None) if v is None else \
                os.environ.__setitem__(k, v)
    clusters.active.enable_serving()
    clusters.register_global_domain(domain)
    wfs = [f"br-wf-{i}" for i in range(workflows)]
    deciders = {wf: SignalDecider(expected_signals=999) for wf in wfs}

    def drive(box):
        for _ in range(500):
            progressed = box.pump_once() > 0
            while True:
                resp = box.frontend.poll_for_decision_task(domain, tl)
                if resp is None:
                    break
                progressed = True
                box.frontend.respond_decision_task_completed(
                    resp.token,
                    deciders[resp.token.workflow_id].decide(resp.history))
            if not progressed and box.matching.backlog() == 0:
                return

    for wf in wfs:
        clusters.active.frontend.start_workflow_execution(
            domain, wf, "signal", tl)
    drive(clusters.active)
    for s in range(signals):
        for wf in wfs:
            clusters.active.frontend.signal_workflow_execution(
                domain, wf, f"{wf}-s{s}")
        drive(clusters.active)
        if s == signals // 2 - 1:
            # mid-corpus snapshot shipment: everything after this is the
            # suffix the standby's device twin applies on seeded keys
            clusters.active.serving.drain(timeout=60)
            clusters.active.tpu.snapshotter().sweep(force=True)
    clusters.active.serving.drain(timeout=60)
    clusters.active.serving.stop()

    clusters.domain_processor.process_once()
    DomainReplicationProcessor(clusters.active.stores, host_only.stores,
                               "standby").process_once()

    def timed_drain(proc):
        t0 = time.perf_counter()
        total = 0
        while True:
            n = proc.process_once(batch_size=100)
            total += n
            if n == 0:
                return total, time.perf_counter() - t0

    def events_applied(box):
        return sum(
            box.stores.execution.get_workflow(*key)
            .execution_info.next_event_id - 1
            for key in box.stores.history.list_runs())

    device_tasks, device_s = timed_drain(clusters.processor)
    host_proc = ReplicationTaskProcessor(
        HistoryReplicator(host_only.stores, rebuilder=host_only.rebuilder,
                          notifier=host_only.notifier),
        clusters.publisher, host_only.stores,
        source_history_reader=clusters._read_source_history,
        tpu=host_only.tpu)
    host_proc.metrics = host_only.metrics
    prev = os.environ.get("CADENCE_TPU_REPL_DEVICE")
    os.environ["CADENCE_TPU_REPL_DEVICE"] = "0"
    try:
        host_tasks, host_s = timed_drain(host_proc)
    finally:
        os.environ.pop("CADENCE_TPU_REPL_DEVICE", None) if prev is None \
            else os.environ.__setitem__("CADENCE_TPU_REPL_DEVICE", prev)

    rows, identical = 0, True
    for key in clusters.standby.stores.history.list_runs():
        a = payload_row(clusters.standby.stores.execution.get_workflow(*key))
        b = payload_row(host_only.stores.execution.get_workflow(*key))
        rows += 1
        if not (a == b).all():
            identical = False
    events = events_applied(clusters.standby)

    def repl_counter(reg, name):
        return reg.counter(cm.SCOPE_REPLICATION, name)

    dreg, hreg = clusters.standby.metrics, host_only.metrics
    return {
        "workflows": workflows, "signals_per_workflow": signals,
        "events_replicated": events, "rows_compared": rows,
        "device": {
            "tasks": device_tasks,
            "drain_s": round(device_s, 4),
            "events_per_sec": round(events / device_s) if device_s else 0,
            "applied": repl_counter(dreg, cm.M_REPL_DEVICE_APPLIED),
            "suffix_events": repl_counter(dreg,
                                          cm.M_REPL_DEVICE_SUFFIX_EVENTS),
            "cold": repl_counter(dreg, cm.M_REPL_DEVICE_COLD),
            "divergence": repl_counter(dreg, cm.M_REPL_DEVICE_DIVERGENCE),
            "snapshots_installed": repl_counter(dreg,
                                                cm.M_REPL_SNAP_INSTALLED),
        },
        "host_only": {
            "kill_switch": "CADENCE_TPU_REPL_DEVICE=0",
            "tasks": host_tasks,
            "drain_s": round(host_s, 4),
            "events_per_sec": round(events / host_s) if host_s else 0,
            "device_applied": repl_counter(hreg, cm.M_REPL_DEVICE_APPLIED),
            "snapshots_installed": repl_counter(hreg,
                                                cm.M_REPL_SNAP_INSTALLED),
        },
        "paths_byte_identical": identical,
    }


def main() -> None:
    ns_workflows = int(os.environ.get("BENCH_NS_WORKFLOWS", "1000000"))
    ns_events = int(os.environ.get("BENCH_NS_EVENTS", "1000"))
    ns_chunk = int(os.environ.get("BENCH_NS_CHUNK", "16384"))
    suite_workflows = int(os.environ.get("BENCH_SUITE_WORKFLOWS", "16384"))
    trials = int(os.environ.get("BENCH_TRIALS", "5"))
    parity_samples = int(os.environ.get("BENCH_PARITY_SAMPLES", "64"))
    seed = int(os.environ.get("BENCH_SEED", "20260730"))

    import jax

    from cadence_tpu.core.checksum import DEFAULT_LAYOUT
    from cadence_tpu.utils import compile_cache

    compile_cache.enable()
    layout = DEFAULT_LAYOUT
    n_devices = jax.device_count()

    north = _north_star(ns_workflows, ns_events, ns_chunk, seed,
                        parity_samples, layout)
    suites = _suite_table(trials, suite_workflows, layout)
    fallback = _fallback_suite(suite_workflows, layout)
    incremental = _incremental_suite(layout)
    snapshot = _snapshot_suite(layout)
    mesh_serving = _mesh_serving(
        int(os.environ.get("BENCH_MESH_WORKFLOWS", "4096")), layout)
    serving = _serving_suite(layout)
    cluster_serving = _cluster_serving(layout)
    visibility = _visibility_suite()
    feeder = _feeder_rate(layout)
    fuzz = _fuzz_suite(layout)
    replication = _replication_suite(layout)

    # observability snapshot: the profiler's pack/h2d/kernel/readback leg
    # decomposition (fed by the instrumented feeder path) plus every tpu.*
    # metric scope — so BENCH_r*.json trajectories diff leg-by-leg
    from cadence_tpu.utils import metrics as cm
    from cadence_tpu.utils.profiler import ReplayProfiler
    observability = {
        "profiler": ReplayProfiler().summary(),
        "metrics": {scope: values
                    for scope, values in cm.DEFAULT_REGISTRY.snapshot().items()
                    if scope.startswith("tpu.")},
    }

    rate_per_chip = north["rate"] / n_devices
    # the pod-scale north star: aggregate events/s across the whole mesh
    # (per-device efficiency rides detail.mesh_serving, measured through
    # the serving executor)
    north["events_per_sec_pod"] = round(north["rate"])
    # the cluster-scale north star: summed serving-tier append rate over
    # every simulated host's wall window (detail.cluster_serving)
    north["events_per_sec_cluster"] = \
        cluster_serving["events_per_sec_cluster"]
    north["rate"] = round(north["rate"])
    print(json.dumps({
        "metric": "replay_events_per_sec_per_chip",
        "value": round(rate_per_chip),
        "unit": "events/s/chip",
        "vs_baseline": round(rate_per_chip / BASELINE_PER_CHIP, 4),
        "detail": {
            "devices": n_devices,
            "platform": jax.devices()[0].platform,
            "north_star": north,
            "suites": suites,
            "fallback_under_pressure": fallback,
            "incremental": incremental,
            "snapshot": snapshot,
            "mesh_serving": mesh_serving,
            "serving": serving,
            "cluster_serving": cluster_serving,
            "visibility": visibility,
            "feeder": feeder,
            "fuzz": fuzz,
            "replication": replication,
            "observability": observability,
        },
    }))


if __name__ == "__main__":
    sys.exit(main())
