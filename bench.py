"""Benchmark: the north-star replay measured for real, plus the suite table.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "events/s/chip", "vs_baseline": N,
   "detail": {...}}

The baseline is the derived per-chip north-star rate from BASELINE.md: 1M
workflows x 1k events on a v5e-8 in <60s => >=16.7M events/s aggregate
=> ~2.08M events/s/chip. vs_baseline = headline_rate / 2.08e6.

What runs (VERDICT r2 ask #1 — no tiling, no extrapolation):

1. NORTH STAR: BENCH_NS_WORKFLOWS (default 1,000,000) workflows x
   BENCH_NS_EVENTS (default 1,000) events, every history DISTINCT: the
   fused device generator+replay kernel (ops/genkernel.py) births each
   event from a per-workflow RNG stream inside the same scan that
   replays it — the corpus never materializes and the host link never
   gates the kernel. The measured wall covers generation + scan +
   payload assembly + device->host payload transfer + host CRC32 — the
   full stateBuilder+checksum pipeline. Reported with per-chunk rate
   min/median/max (the variance the r1/r2 bench could not explain),
   oracle-fallback rate (kernel error rows), HBM high-water, and CRC
   spot-parity: BENCH_PARITY_SAMPLES workflows re-materialized from the
   same RNG stream, decoded, ORACLE-replayed, payloads compared.
2. SUITE TABLE: all five corpus suites, BENCH_SUITE_WORKFLOWS (default
   4096) DISTINCT Python-generated histories each, BENCH_TRIALS (default
   5) timed trials -> per-suite events/s/chip min/median/max.
3. FEEDER: sustained wire-bytes -> C++ packer -> device rate on a warm
   executable (native/feeder.py), next to the packer's standalone rate.

Scale knobs exist for CI only; the defaults ARE the north star.
"""
import json
import os
import statistics
import sys
import time

import numpy as np


def _suite_table(trials: int, suite_workflows: int, layout):
    import jax

    from cadence_tpu.core.checksum import crc32_of_rows
    from cadence_tpu.gen.corpus import SUITES, generate_corpus
    from cadence_tpu.ops.encode import LANE_EVENT_ID, encode_corpus
    from cadence_tpu.parallel.mesh import make_mesh, replay_sharded, shard_events

    mesh = make_mesh()
    table = {}
    for suite in SUITES:
        histories = generate_corpus(suite, num_workflows=suite_workflows,
                                    seed=20260730, target_events=120)
        events_np = encode_corpus(histories)
        real = int((events_np[:, :, LANE_EVENT_ID] > 0).sum())
        events = shard_events(jax.device_put(events_np), mesh)

        def run_once():
            rows, errors, _stats = replay_sharded(events, mesh, layout)
            rows_np = np.asarray(rows)
            crc32_of_rows(rows_np)
            return np.asarray(errors)

        errors = run_once()  # compile + warm
        n_devices = jax.device_count()
        rates = []
        for _ in range(trials):
            t0 = time.perf_counter()
            run_once()
            rates.append(real / (time.perf_counter() - t0) / n_devices)
        table[suite] = {
            "workflows": suite_workflows,
            "events": real,
            "rate_min": round(min(rates)),
            "rate_median": round(statistics.median(rates)),
            "rate_max": round(max(rates)),
            "error_workflows": int((errors != 0).sum()),
        }
    return table


def _north_star(workflows: int, max_events: int, chunk: int, seed: int,
                parity_samples: int, layout):
    """The measured 1M x 1k run: the fused device generator+replay kernel
    (ops/genkernel.py) — every history DISTINCT, born on device inside the
    same scan that replays it, so the host link never gates the kernel.
    Returns the headline stats dict."""
    import jax

    from cadence_tpu.core.checksum import STICKY_ROW_INDEX, crc32_of_rows, payload_row
    from cadence_tpu.ops.encode import decode_lanes
    from cadence_tpu.ops.genkernel import (
        generate_and_replay,
        generate_and_replay_sharded,
        generate_lanes,
    )
    from cadence_tpu.oracle.state_builder import StateBuilder
    from cadence_tpu.parallel.mesh import make_mesh

    n_devices = jax.device_count()
    # CI-scale requests smaller than a chunk shrink the chunk instead of
    # silently inflating the run
    chunk = min(chunk, max(workflows, n_devices))
    if n_devices > 1:
        # multi-chip: SPMD over the mesh — every chip generates+replays its
        # own workflow-index range (chunk must divide by the mesh)
        mesh = make_mesh()
        chunk = -(-chunk // n_devices) * n_devices

        def run_chunk(sd, lo):
            return generate_and_replay_sharded(sd, lo, chunk, max_events,
                                               mesh, layout)
    else:
        def run_chunk(sd, lo):
            return generate_and_replay(sd, lo, chunk, max_events, layout)

    n_chunks = -(-workflows // chunk)

    # warm/compile on the first chunk's shape (cold compile reported, not
    # amortized into the steady rate)
    t0 = time.perf_counter()
    rows, _ = run_chunk(seed + 1, 0)
    np.asarray(rows)
    compile_s = time.perf_counter() - t0

    total_events = 0
    total_errors = 0
    chunk_rates = []
    crc_accum = 0

    # depth-2 software pipeline: dispatch chunk i+1 (JAX async) BEFORE
    # blocking on chunk i's payload transfer + CRC, so a host-link stall
    # overlaps the next chunk's on-device compute instead of serializing
    real = chunk * max_events  # the generator fills every slot
    t_start = time.perf_counter()
    in_flight = run_chunk(seed, 0)
    t_prev = t_start
    for ci in range(n_chunks):
        rows, errors = in_flight
        if ci + 1 < n_chunks:
            in_flight = run_chunk(seed, (ci + 1) * chunk)
        rows_np = np.asarray(rows)
        errors_np = np.asarray(errors)
        crcs = crc32_of_rows(rows_np)
        now = time.perf_counter()
        chunk_rates.append(real / (now - t_prev))  # completion interval
        t_prev = now
        total_events += real
        total_errors += int((errors_np != 0).sum())
        crc_accum ^= int(np.bitwise_xor.reduce(crcs.astype(np.uint32)))
        if ci == 0:
            first_rows = rows_np[:parity_samples].copy()
    wall_s = time.perf_counter() - t_start

    # CRC spot-parity: materialize the SAME rng stream's lanes for a
    # sample block, oracle-replay them, compare canonical payloads
    sample_n = min(parity_samples, chunk)
    lanes = np.asarray(generate_lanes(seed, 0, sample_n, max_events))
    parity_fail = 0
    for i in range(sample_n):
        ms = StateBuilder().replay_history(decode_lanes(lanes[i]))
        expected = payload_row(ms, layout)
        expected[STICKY_ROW_INDEX] = 0
        if not (first_rows[i] == expected).all():
            parity_fail += 1

    hbm_peak = None
    try:
        stats = jax.local_devices()[0].memory_stats()
        if stats:
            hbm_peak = int(stats.get("peak_bytes_in_use", 0))
    except Exception:
        pass

    return {
        "workflows": n_chunks * chunk,
        "max_events": max_events,
        "chunk_workflows": chunk,
        "chunks": n_chunks,
        "real_events": total_events,
        "distinct_histories": True,  # per-workflow RNG stream, no tiling
        "wall_s": round(wall_s, 3),
        "rate": total_events / wall_s,
        "chunk_rate_min": round(min(chunk_rates)),
        "chunk_rate_median": round(statistics.median(chunk_rates)),
        "chunk_rate_max": round(max(chunk_rates)),
        "compile_s": round(compile_s, 3),
        "error_workflows": total_errors,
        "oracle_fallback_rate": total_errors / (n_chunks * chunk),
        "crc_xor": crc_accum,
        "parity_samples": sample_n,
        "parity_failures": parity_fail,
        "hbm_peak_bytes": hbm_peak,
    }


def _feeder_rate(layout):
    from cadence_tpu.gen.corpus import generate_corpus
    from cadence_tpu.native import packing
    from cadence_tpu.native.feeder import feed_corpus

    if not packing.native_available():
        return None
    histories = generate_corpus("basic", num_workflows=4096, seed=7,
                                target_events=100)
    feed_corpus(histories[:1024], chunk_workflows=1024, layout=layout)  # warm
    _, errors, report = feed_corpus(histories, chunk_workflows=1024,
                                    layout=layout)
    return {
        "events": report.events,
        "sustained_events_per_sec": round(report.events_per_sec),
        "pack_only_events_per_sec": round(report.pack_events_per_sec),
        "error_workflows": int((errors != 0).sum()),
    }


def main() -> None:
    ns_workflows = int(os.environ.get("BENCH_NS_WORKFLOWS", "1000000"))
    ns_events = int(os.environ.get("BENCH_NS_EVENTS", "1000"))
    ns_chunk = int(os.environ.get("BENCH_NS_CHUNK", "16384"))
    suite_workflows = int(os.environ.get("BENCH_SUITE_WORKFLOWS", "4096"))
    trials = int(os.environ.get("BENCH_TRIALS", "5"))
    parity_samples = int(os.environ.get("BENCH_PARITY_SAMPLES", "64"))
    seed = int(os.environ.get("BENCH_SEED", "20260730"))

    import jax

    from cadence_tpu.core.checksum import DEFAULT_LAYOUT

    layout = DEFAULT_LAYOUT
    n_devices = jax.device_count()

    north = _north_star(ns_workflows, ns_events, ns_chunk, seed,
                        parity_samples, layout)
    suites = _suite_table(trials, suite_workflows, layout)
    feeder = _feeder_rate(layout)

    rate_per_chip = north["rate"] / n_devices
    baseline_per_chip = 16_700_000 / 8  # BASELINE.md derived kernel rate
    north["rate"] = round(north["rate"])
    print(json.dumps({
        "metric": "replay_events_per_sec_per_chip",
        "value": round(rate_per_chip),
        "unit": "events/s/chip",
        "vs_baseline": round(rate_per_chip / baseline_per_chip, 4),
        "detail": {
            "devices": n_devices,
            "platform": jax.devices()[0].platform,
            "north_star": north,
            "suites": suites,
            "feeder": feeder,
        },
    }))


if __name__ == "__main__":
    sys.exit(main())
